"""Benchmark: GRPO episodes/sec/chip on the flagship-shaped policy.

Measures one full GRPO update — rollout (N samples/prompt, jitted KV-cache
decode), reward, group advantage + keep-1-of-N, chunked policy+ref logprob
pass, and the jitted minibatch update — end to end, and reports
episodes/sec/chip against the reference baseline of ~1 s/episode on one
A100 40G (`BASELINE.md`; reference runtime print
`/root/reference/GRPO/grpo_trainer.py:726`).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "episodes/s/chip", "vs_baseline": N}

Env overrides: BENCH_PROMPTS (default 32), BENCH_SAMPLE_N (4),
BENCH_RESPONSE (256), BENCH_MODEL (1_5b | tiny), BENCH_UPDATES (2),
BENCH_ATTENTION (xla | pallas), BENCH_LORA (1 | 0).
"""

import json
import os
import sys
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from nanorlhf_tpu.core import ModelConfig, init_params
    from nanorlhf_tpu.data import ToyTokenizer, load_prompt_dataset
    from nanorlhf_tpu.parallel import MeshConfig
    from nanorlhf_tpu.trainer import AlgoName, RLConfig, RLTrainer

    n_prompts = int(os.environ.get("BENCH_PROMPTS", 32))
    sample_n = int(os.environ.get("BENCH_SAMPLE_N", 4))
    response_len = int(os.environ.get("BENCH_RESPONSE", 256))
    model_name = os.environ.get("BENCH_MODEL", "1_5b")
    n_updates = int(os.environ.get("BENCH_UPDATES", 2))
    attention_impl = os.environ.get("BENCH_ATTENTION", "xla")
    use_lora = os.environ.get("BENCH_LORA", "1") == "1"

    import dataclasses

    n_dev = len(jax.devices())
    mcfg = (
        ModelConfig.qwen2_1_5b() if model_name == "1_5b"
        else ModelConfig.qwen2_tiny(vocab_size=4096)
    )
    mcfg = dataclasses.replace(mcfg, attention_impl=attention_impl)
    dtype = jnp.bfloat16
    tok = ToyTokenizer(vocab_size=min(4096, mcfg.vocab_size))
    params = init_params(mcfg, jax.random.PRNGKey(0), dtype)

    # batch hierarchy: one update consumes n_prompts episodes
    grad_accum = 2 if n_prompts % (2 * 2 * n_dev) == 0 else 1
    num_mini = 2 if n_prompts % (2 * grad_accum * n_dev) == 0 else 1
    per_dev = n_prompts // (grad_accum * num_mini * n_dev)
    assert per_dev >= 1, "BENCH_PROMPTS too small for device count"

    cfg = RLConfig(
        algo=AlgoName.GRPO,
        output_dir="/tmp/nanorlhf_tpu_bench",
        response_length=response_len,
        temperature=0.9,
        sample_n=sample_n,
        per_device_train_batch_size=per_dev,
        gradient_accumulation_steps=grad_accum,
        num_mini_batches=num_mini,
        num_ppo_epochs=1,
        kl_coef=0.01,
        use_lora=use_lora,
        gradient_checkpointing=True,
        mesh=MeshConfig(n_dev, 1, 1),
        save_steps=0,
        report_to="none",
        logging_steps=10**9,
    )
    cfg.total_episodes = n_prompts * (n_updates + 1)  # +1 warmup/compile update

    def reward(pmt_and_responses, eos_token):
        # cheap rule-based reward: keeps the bench focused on the TPU path
        return np.asarray(
            [(1.0 if eos_token in s else 0.0) - 0.001 * len(s.split())
             for s in pmt_and_responses],
            np.float32,
        )

    dataset = load_prompt_dataset(f"synthetic:{max(64, n_prompts * 2)}", tok,
                                  max_prompt_len=64)
    trainer = RLTrainer(cfg, mcfg, tok, params, dataset, reward)

    # run update-by-update so compile time (first update) is excluded
    times = []
    for _ in range(n_updates + 1):
        t0 = time.time()
        trainer.train(num_updates=1)
        times.append(time.time() - t0)

    steady = times[1:] if len(times) > 1 else times
    sec_per_update = float(np.mean(steady))
    # cfg.batch_size (set by finalize inside RLTrainer) is the TRUE episode
    # count per update; n_prompts may round down on non-divisible device counts
    episodes_per_update = cfg.batch_size
    eps_per_sec_per_chip = episodes_per_update / sec_per_update / n_dev

    baseline_eps_per_sec = 1.0  # reference: ~1 s/episode on one A100 40G
    print(json.dumps({
        "metric": "grpo_episodes_per_sec_per_chip",
        "value": round(eps_per_sec_per_chip, 4),
        "unit": "episodes/s/chip",
        "vs_baseline": round(eps_per_sec_per_chip / baseline_eps_per_sec, 4),
        "detail": {
            "model": model_name,
            "attention": attention_impl,
            "lora": use_lora,
            "prompts_per_update": episodes_per_update,
            "sample_n": sample_n,
            "response_length": response_len,
            "devices": n_dev,
            "sec_per_update_steady": round(sec_per_update, 3),
            "compile_update_sec": round(times[0], 3),
        },
    }))


if __name__ == "__main__":
    sys.exit(main())
