"""Benchmark: GRPO episodes/sec/chip on the flagship-shaped policy.

Measures one full GRPO update — rollout (N samples/prompt, jitted KV-cache
decode), reward, group advantage + keep-1-of-N, chunked policy+ref logprob
pass, and the jitted minibatch update — end to end, and reports
episodes/sec/chip against the reference baseline of ~1 s/episode on one
A100 40G (`BASELINE.md`; reference runtime print
`/root/reference/GRPO/grpo_trainer.py:726`).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "episodes/s/chip", "vs_baseline": N,
   "detail": {..., "mfu": ..., "tokens_per_sec": ..., "phase_split_s": ...}}

On failure it STILL prints one parseable JSON line with an "error" field —
round 1 died to a flaky TPU-backend init (hung >280 s) with a bare stack
trace. Architecture: the PARENT process never imports jax. It spawns the
whole measurement as a child subprocess with a hard timeout, retries with
backoff, and finally (optional) falls back to a reduced CPU run. Only one
jax process ever exists at a time — required by the axon TPU tunnel, which
allows a single claim holder and can wedge if probed concurrently.

Env overrides: BENCH_PROMPTS (default 32), BENCH_SAMPLE_N (4),
BENCH_RESPONSE (1500 — the reference's operating point, so `value` and
`vs_baseline` compare like with like; a resp-256 secondary point is
measured into detail.short_response when the budget allows),
BENCH_MODEL (1_5b | tiny), BENCH_UPDATES (2),
BENCH_ATTENTION (xla | pallas | auto), BENCH_LORA (1 | 0),
BENCH_QUANT (0 | 1: int8 rollout weights), BENCH_AHEAD (0 | 1: overlap),
BENCH_ORCH (0 | 1: async rollout orchestrator, docs/ORCHESTRATOR.md),
BENCH_STALENESS (2: orchestrator max_staleness),
BENCH_KV_QUANT (0 | 1: int8 KV cache),
BENCH_SPEC_K (0: speculative rollout decode draft length, cfg.rollout_spec_k
— the n-gram draft + batched-verify lever, sampler/speculative.py; the
always-run detail.spec_decode A/B additionally reports its acceptance /
dispatch-count win on a repetitive synthetic corpus, TPU or CPU alike),
BENCH_SENTINEL (1: also measure the training sentinel disabled and report
detail.sentinel.sentinel_overhead_frac — the resilience guard's cost on
the step wall, docs/RESILIENCE.md),
BENCH_TELEMETRY (1: also measure with the span tracer enabled and report
detail.telemetry.telemetry_overhead_frac — the observability acceptance
gate is < 1% of step wall, docs/OBSERVABILITY.md),
BENCH_HEALTH (1: also measure with the run-health plane disabled and report
detail.health.health_overhead_frac — the streaming-aggregator + rule-eval
cost of the default-on health monitor; acceptance < 1% of step wall,
docs/OBSERVABILITY.md §5),
BENCH_LINEAGE (1: also measure with the sample-lineage ledger enabled and
report detail.lineage.lineage_overhead_frac — the per-rollout provenance
JSONL appends' cost on the step wall; acceptance < 1%,
docs/OBSERVABILITY.md §6),
BENCH_FLEET_WORKERS (0: >1 also measures the elastic rollout fleet at that
worker count against the single-producer pipeline at the SAME staleness
and reports detail.fleet.coordinator_overhead_frac — the lease/reorder
machinery's cost on the step wall; acceptance < 2%, docs/FLEET.md — plus,
budget permitting, the same fleet over the loopback RpcTransport and
detail.fleet.rpc_transport_overhead_frac, the socket framing/codec cost;
acceptance < 5% at 2 workers, docs/FLEET.md §multi-host),
BENCH_PAGED (1: also run the continuous-batching A/B and report
detail.paged — queued-paged vs contiguous fixed-batch at equal resident
batch on a long-tail corpus, docs/PAGED_CACHE.md),
BENCH_SERVING (1: also run the radix prefix-cache A/B and report
detail.serving — radix on vs off at equal resident batch on a >= 50%
prompt-overlap corpus; acceptance prefix_hit_frac > 0.4 with strictly
fewer dispatched prefill tokens, greedy bit-identical, docs/SERVING.md),
BENCH_SESSION (1: also run the decode-session composition A/B and
report detail.session — spec+radix combined vs each feature alone at
equal resident batch on an 87.5%-overlap corpus, acceptance combined
dispatch EVENTS strictly below min(each alone) with greedy output
bit-identical 4-way and combined prefill tokens below spec-alone's,
plus the chunked-prefill p95 inter-token-gap gate at <= 1.2x the
no-long-prompt baseline on a live engine stream,
docs/PAGED_CACHE.md §session),
BENCH_SWAP (1: also run the in-flight weight-swap A/B and report
detail.swap — in-flight mid-sequence swaps vs drain-and-wait at the SAME
mid-decode publish offset (one staleness bound, met two ways), reporting
generator idle fraction, swap installs, and episodes/s; acceptance
in-flight idle strictly below drain-and-wait's with >= 1 install and
segments stamped on the live rows, plus swap_overhead_frac < 1% for an
armed-but-silent refresh vs weight_refresh=None, greedy bit-identical
throughout, docs/ORCHESTRATOR.md §in-flight swaps),
BENCH_ENV (1: also run the multi-turn environment A/B and report
detail.env — 2-turn python-tool episodes vs the single-turn degenerate
case at EQUAL resident batch, reporting turns/episode and the tool-stall
overlap fraction; acceptance turns_per_episode >= 2 with observation
tokens loss-masked and pages recycled mid-episode while single-turn
stays at exactly 1 turn with zero continuation admissions,
docs/ENVIRONMENTS.md),
BENCH_TRAFFIC (1: also run the open-loop offered-load sweep and report
detail.traffic — the SAME deterministic workload spec replayed against a
fresh in-process ServingEngine at each rate on the BENCH_TRAFFIC_RATES
grid ("4,16,64" rps); acceptance >= 3 points with goodput, shed-rate,
and p95-TTFT columns, requests conserved at every point and the top rate
shedding at least as much as the bottom, docs/TRAFFIC.md),
BENCH_ATTEMPTS (2), BENCH_ATTEMPT_TIMEOUT (2100 s per attempt — sized for
a baseline + int8-lever sweep; the sweep auto-skips when the baseline ate
>40% of the budget), BENCH_SWEEP (1 on TPU: also measure the int8 levers,
report the faster config),
BENCH_ALLOW_CPU_FALLBACK (1: after all TPU attempts fail, run a reduced
bench on CPU and mark backend=cpu in the payload rather than emitting
nothing).
"""

import dataclasses
import json
import os
import subprocess
import sys
import time

import numpy as np

BASELINE_EPS_PER_SEC = 1.0  # reference: ~1 s/episode on one A100 40G

_T0 = time.time()  # child-process start (budget accounting for secondaries)

# Peak-FLOPs table and the napkin model-FLOPs/MFU formula live in
# nanorlhf_tpu/telemetry/mfu.py — ONE accounting shared with the trainer's
# per-update `perf/mfu` series, imported in the measurement child
# (mfu.py is jax-free at module level, so the import is safe there).


def _emit(payload: dict) -> None:
    print(json.dumps(payload))


def _error_payload(msg: str, **detail) -> dict:
    return {
        "metric": "grpo_episodes_per_sec_per_chip",
        "value": 0.0,
        "unit": "episodes/s/chip",
        "vs_baseline": 0.0,
        "error": msg[-2000:],
        "detail": detail,
    }


def _load_by_path(mod_name: str, *relpath: str):
    """Load a repo module by FILE PATH — no package import (nanorlhf_tpu's
    __init__ pulls jax, which the bench parent must never do) and no
    sys.path mutation (which would let repo files shadow stdlib names)."""
    import importlib.util

    p = os.path.join(os.path.dirname(os.path.abspath(__file__)), *relpath)
    spec = importlib.util.spec_from_file_location(mod_name, p)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _load_compile_cache_mod():
    return _load_by_path("_bench_compile_cache",
                         "nanorlhf_tpu", "utils", "compile_cache.py")


def _remove_child_sentinel(pid: int) -> None:
    """A SIGKILLed measurement child can't clean its compile-cache claim
    (no atexit, no signal handler runs) — if the parent didn't remove it,
    the next cache writer would read the dead sentinel as a crash and wipe
    the shared cache, costing a full recompile per bench timeout."""
    try:
        mod = _load_compile_cache_mod()
        d = mod.default_cache_dir()
        if d:
            os.remove(mod.sentinel_path(d, pid))
    except Exception:
        pass  # no cache dir / no sentinel — nothing to clean


def _run_child(extra_env: dict, timeout_s: float) -> tuple[dict | None, str]:
    """Run the measurement child; return (payload_or_None, error_tail).

    The child is this same script with BENCH_CHILD=1. Its last stdout line
    that parses as JSON with a "metric" key is the payload. On timeout the
    child is killed — the parent interpreter stays clean for a retry.
    """
    env = {**os.environ, "BENCH_CHILD": "1", **extra_env}
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
    )
    try:
        stdout, stderr = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.kill()
        try:
            _, stderr = proc.communicate(timeout=10)
        except Exception:
            stderr = ""
        # only reap the sentinel once the child is CONFIRMED dead: a child
        # stuck in uninterruptible I/O on a dead relay pends the SIGKILL,
        # and removing a live child's claim would let a concurrent writer's
        # heal wipe the cache under it (the pid-liveness check in
        # heal_and_claim handles an unremoved sentinel correctly either way)
        if proc.poll() is not None:
            _remove_child_sentinel(proc.pid)
        tail = (stderr or "")[-500:]
        return None, f"child timed out after {timeout_s:.0f}s; stderr: {tail}"
    for line in reversed(stdout.strip().splitlines()):
        try:
            payload = json.loads(line)
            if isinstance(payload, dict) and "metric" in payload:
                if payload.get("error") and payload.get("value", 0) == 0:
                    # the child emitted an error payload (e.g. fast-raising
                    # TPU init failure): that is a FAILED attempt — retries
                    # and the CPU fallback must still run. Hand the payload
                    # up so the final failure can emit the most informative
                    # one.
                    return None, json.dumps(payload)
                return payload, ""
        except json.JSONDecodeError:
            continue
    return None, (stderr or stdout).strip()[-800:]


def _relay_ports() -> tuple:
    """Port set lives in tools/tunnel_alive.py (shared with the session/
    watch scripts); falls back to the historical set if the load fails
    (bench.py must stay runnable standalone)."""
    try:
        return _load_by_path("_bench_tunnel_alive",
                             "tools", "tunnel_alive.py").RELAY_PORTS
    except Exception:
        return (8082, 8092, 8102, 8112)


_RELAY_PORTS = _relay_ports()


def _tunnel_alive() -> bool | None:
    """Preflight for the axon TPU tunnel. None = not an axon env (no
    preflight possible); True = a relay port accepts connections; False =
    every port refuses — the relay process is dead and the axon client
    would retry-dial it FOREVER (observed: a dead relay turned each bench
    attempt into a full attempt-timeout burn; a 5 s socket check answers
    the same question)."""
    if os.environ.get("JAX_PLATFORMS") != "axon":
        return None
    import socket

    for port in _RELAY_PORTS:
        try:
            s = socket.create_connection(("127.0.0.1", port), timeout=5)
            s.close()
            return True
        except OSError:
            continue
    return False


def orchestrate() -> int:
    """Parent entry: spawn children with retry/backoff, emit ONE JSON line."""
    attempts = int(os.environ.get("BENCH_ATTEMPTS", 2))
    # generous: the child may measure TWO configs (baseline + int8 sweep)
    timeout_s = float(os.environ.get("BENCH_ATTEMPT_TIMEOUT", 2100))
    allow_cpu = os.environ.get("BENCH_ALLOW_CPU_FALLBACK", "1") == "1"

    errors = []
    for attempt in range(attempts):
        if _tunnel_alive() is False:
            errors.append(
                f"attempt {attempt + 1}: axon relay not listening on "
                f"{_RELAY_PORTS} — TPU tunnel down, skipping TPU attempt"
            )
            print(f"[bench] {errors[-1]}", file=sys.stderr)
            time.sleep(min(20 * (attempt + 1), 60))
            continue
        payload, err = _run_child({}, timeout_s)
        if payload is not None:
            _emit(payload)
            return 0
        errors.append(f"attempt {attempt + 1}: {err}")
        print(f"[bench] attempt {attempt + 1}/{attempts} failed: {err[:300]}",
              file=sys.stderr)
        if attempt < attempts - 1:
            time.sleep(min(20 * (attempt + 1), 60))

    if allow_cpu:
        # strip only the axon site dir (its sitecustomize eagerly claims the
        # TPU at interpreter startup and can hang the CPU child); keep any
        # other PYTHONPATH entries the environment relies on
        pythonpath = ":".join(
            p for p in os.environ.get("PYTHONPATH", "").split(":")
            if p and ".axon_site" not in p
        )
        payload, err = _run_child(
            {"JAX_PLATFORMS": "cpu", "PYTHONPATH": pythonpath,
             "BENCH_CPU_FALLBACK": "1",
             "BENCH_TPU_ERROR": " | ".join(errors)[-500:]},
            timeout_s,
        )
        if payload is not None:
            _emit(payload)
            return 0
        errors.append(f"cpu fallback: {err}")
    # prefer the last structured child error payload over a generic one
    for err in reversed(errors):
        tail = err.split(": ", 1)[-1]
        try:
            payload = json.loads(tail)
            if isinstance(payload, dict) and "metric" in payload:
                _emit(payload)
                return 0
        except json.JSONDecodeError:
            continue
    _emit(_error_payload(" | ".join(errors)))
    return 0


def count_params(tree) -> int:
    import jax

    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(tree))


def pallas_on_chip_check(jax) -> dict:
    """Run the Pallas flash + decode kernels NON-interpreted and assert vs
    the XLA reference — the first real-silicon validation (round 1 only ever
    ran them in interpret mode on CPU). NEVER raises: a kernel lowering
    failure is reported in the payload instead of destroying the measured
    throughput numbers (this exact failure mode ate the first r2 attempt)."""
    try:
        result = _flash_on_chip_check(jax)
    except Exception as e:
        result = {
            "pallas_check": "ERROR",
            "pallas_error": f"{type(e).__name__}: {e}"[:600],
        }
    try:  # independent of the flash check: one failing must not hide the other
        result.update(_decode_on_chip_check(jax))
    except Exception as e:
        result.update({
            "decode_check": "ERROR",
            "decode_error": f"{type(e).__name__}: {e}"[:600],
        })
    try:  # fused hidden→logprob op (ops/fused_logprob.py)
        result.update(_fused_logprob_check(jax))
    except Exception as e:
        result.update({
            "fused_check": "ERROR",
            "fused_error": f"{type(e).__name__}: {e}"[:600],
        })
    return result


def _fused_logprob_check(jax) -> dict:
    """Chunked linear-cross-entropy vs the full-logits oracle: forward
    logprobs + entropy for BOTH impls (lax chunk scan, Pallas online-lse
    kernel — non-interpreted on real silicon) and the custom-VJP grads wrt
    hidden + unembedding for the lax path. Chunk 40 deliberately does not
    divide the row count; V=2050 does not divide the kernel's vocab block."""
    import jax.numpy as jnp

    from nanorlhf_tpu.ops.fused_logprob import (
        fused_logprob, fused_logprob_reference)

    B, T, D, V = 2, 48, 64, 2050
    ks = jax.random.split(jax.random.PRNGKey(13), 3)
    h = jax.random.normal(ks[0], (B, T, D), jnp.bfloat16)
    w = (jax.random.normal(ks[1], (D, V), jnp.float32) * 0.05).astype(jnp.bfloat16)
    labels = jax.random.randint(ks[2], (B, T), 0, V)
    temp = 0.9

    ref_lp, ref_ent = fused_logprob_reference(
        h, w, labels, temp, with_entropy=True)
    errs = {}
    for impl in ("lax", "pallas"):
        lp, ent = fused_logprob(
            h, w, labels, temp, chunk=40, impl=impl, with_entropy=True)
        errs[f"fused_{impl}_max_err"] = float(
            jnp.max(jnp.abs(lp - ref_lp)) + jnp.max(jnp.abs(ent - ref_ent))
        )
    # vocab-major orientation ([V, D] + transposed=True) — how tied
    # embeddings (the Qwen2 default) reach the kernel in production
    lp_t, ent_t = fused_logprob(
        h, w.T, labels, temp, chunk=40, impl="pallas", with_entropy=True,
        transposed=True)
    errs["fused_transposed_max_err"] = float(
        jnp.max(jnp.abs(lp_t - ref_lp)) + jnp.max(jnp.abs(ent_t - ref_ent))
    )

    def g(fn):
        return jax.jit(jax.grad(
            lambda h_, w_: (fn(h_, w_) ** 2).sum(), argnums=(0, 1)
        ))(h, w)

    gf = g(lambda h_, w_: fused_logprob(h_, w_, labels, temp, chunk=40,
                                        impl="lax"))
    gr = g(lambda h_, w_: fused_logprob_reference(h_, w_, labels, temp))
    errs["fused_bwd_max_err"] = max(
        _rel_err(jnp, a, b) for a, b in zip(gf, gr))
    tol = 0.02  # bf16 inputs; the kernel's f32 matmul differs by bf16 rounding
    ok = all(v < tol for v in errs.values())  # compare UNROUNDED errors
    return {"fused_check": "ok" if ok else "MISMATCH",
            **{k: round(v, 5) for k, v in errs.items()}}


def _rel_err(jnp, a, b):
    a = a.astype(jnp.float32)
    b = b.astype(jnp.float32)
    return float(jnp.max(jnp.abs(a - b)) / (jnp.max(jnp.abs(b)) + 1e-6))


def _decode_on_chip_check(jax) -> dict:
    """Prefix-bounded decode kernel vs the XLA oracle, with varied per-row
    left-pad starts (incl. non-block-aligned) and fill levels — the clamp
    logic in the kv index_map is the kernel's distinguishing feature."""
    import jax.numpy as jnp

    from nanorlhf_tpu.ops.decode_attention import (
        decode_attention, reference_decode_attention)

    B, Hq, KV, T, d = 4, 8, 2, 512, 64
    ks = jax.random.split(jax.random.PRNGKey(11), 3)
    qd = jax.random.normal(ks[0], (B, Hq, d), jnp.bfloat16)
    kc = jax.random.normal(ks[1], (B, KV, T, d), jnp.bfloat16)
    vc = jax.random.normal(ks[2], (B, KV, T, d), jnp.bfloat16)
    # starts: 0, mid-block, block-aligned, just-under-block boundary
    st = jnp.asarray([0, 37, 256, 255][:B], jnp.int32)
    fl = jnp.asarray([T - (17 * i) % 64 for i in range(B)], jnp.int32)
    # explicit block 128 → 4 kv blocks at T=512: the scalar-prefetch clamp
    # and block-revisit logic must actually step across blocks (the tuned
    # 512 default would collapse the grid to one trivial block)
    o_p = decode_attention(qd, kc, vc, st, fl, block_k=128)
    o_r = reference_decode_attention(qd, kc, vc, st, fl)
    derr = _rel_err(jnp, o_p, o_r)
    result = {
        "decode_check": "ok" if derr < 0.02 else "MISMATCH",
        "decode_max_err": round(derr, 5),
    }
    # int8-cache variant vs its dequantize-then-exact oracle (same quantized
    # inputs, so the tolerance is kernel numerics, not quantization error)
    from nanorlhf_tpu.core.model import _quantize_kv
    from nanorlhf_tpu.ops.decode_attention import (
        decode_attention_q8, reference_decode_attention_q8)

    kq, ksc = _quantize_kv(kc.astype(jnp.float32))
    vq, vsc = _quantize_kv(vc.astype(jnp.float32))
    o_q = decode_attention_q8(qd, kq, ksc, vq, vsc, st, fl, block_k=128)
    o_qr = reference_decode_attention_q8(qd, kq, ksc, vq, vsc, st, fl)
    qerr = _rel_err(jnp, o_q, o_qr)
    result["decode_q8_check"] = "ok" if qerr < 0.02 else "MISMATCH"
    result["decode_q8_max_err"] = round(qerr, 5)
    return result


def _spec_decode_check(jax) -> dict:
    """Speculative-decode lever A/B on a REPETITIVE synthetic corpus — the
    deterministic Markov "cycle model" (layers zeroed, untied one-hot head:
    token t always yields sigma(t)) emits a period-4 stream, the n-gram
    drafter's best case. Reports acceptance rate, tokens emitted per verify
    dispatch, and the dispatch-count ratio vs the monolithic loop (which
    pays one dispatch per token) — the ISSUE-5 acceptance gate is >= 2x
    fewer dispatches at spec_k=4. Runs on every backend (tiny model), so
    the CPU-fallback bench carries the row while the TPU tunnel is down.
    spec_k=0 routes through the untouched monolithic jit (zero cost when
    the lever is off); its wall is reported for reference."""
    import dataclasses

    import jax.numpy as jnp

    from nanorlhf_tpu.core import ModelConfig, init_params
    from nanorlhf_tpu.sampler import SamplingParams, generate

    V, rows, resp, spec_k = 32, 8, 128, 4
    mcfg = dataclasses.replace(
        ModelConfig.qwen2_tiny(vocab_size=V), tie_word_embeddings=False
    )
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    D = mcfg.hidden_size
    layers = jax.tree.map(jnp.zeros_like, params["layers"])
    for ln in ("input_layernorm", "post_attention_layernorm"):
        layers[ln] = jnp.ones_like(layers[ln])
    params["layers"] = layers
    params["embed_tokens"] = jnp.zeros((V, D), jnp.float32).at[
        jnp.arange(V), jnp.arange(V)
    ].set(1.0)
    sigma = np.arange(V)
    sigma[[5, 6, 7, 8]] = [6, 7, 8, 5]                  # 4-cycle, no EOS
    params["lm_head"] = jnp.zeros((D, V), jnp.float32).at[
        jnp.arange(V), jnp.asarray(sigma)
    ].set(12.0 / np.sqrt(D))

    ids = jnp.asarray(np.tile([5, 6, 7, 8, 5], (rows, 1)), jnp.int32)
    mask = jnp.ones_like(ids, bool)
    kw = dict(eos_token_id=3, pad_token_id=0)

    def wall(sp, stats_out=None):
        ts = []
        for rep in range(2):                            # compile + 1 timed
            t0 = time.time()
            out = generate(params, mcfg, ids, mask, jax.random.PRNGKey(rep),
                           sp, spec_stats_out=stats_out, **kw)
            np.asarray(out)
            ts.append(time.time() - t0)
        return out, ts[-1]

    out0, sec0 = wall(SamplingParams(greedy=True, max_tokens=resp))
    stats: list = []
    out1, sec1 = wall(
        SamplingParams(greedy=True, max_tokens=resp, spec_k=spec_k),
        stats_out=stats,
    )
    st = {k: int(np.asarray(v)) for k, v in stats[-1].items()
          if np.asarray(v).ndim == 0}  # scalars only (accepted_rows is [B])
    mono_steps = resp - 1                               # one dispatch/token after prefill
    identical = bool(np.array_equal(np.asarray(out0), np.asarray(out1)))
    return {
        "spec_k": spec_k,
        "response_length": resp,
        "acceptance_rate": round(st["accepted"] / max(st["drafted"], 1), 4),
        "accepted_per_step": round(st["emitted"] / max(st["row_steps"], 1), 3),
        "dispatch_steps_spec": st["verify_steps"],
        "dispatch_steps_monolithic": mono_steps,
        "dispatch_ratio": round(mono_steps / max(st["verify_steps"], 1), 2),
        "greedy_bit_identical": identical,
        "sec_spec": round(sec1, 3),
        "sec_spec_off": round(sec0, 3),
        "spec_check": "ok" if (
            identical and st["verify_steps"] * 2 <= mono_steps
        ) else "MISMATCH",
    }


def _paged_check(jax) -> dict:
    """Paged-KV continuous-batching A/B on a LONG-TAIL synthetic corpus
    (ISSUE 10, docs/PAGED_CACHE.md). Same deterministic Markov machine as
    the spec check, extended with CHAIN states (v -> v+1 -> ... -> EOS) so
    each prompt's greedy length is chosen by hand: a queue of mostly-short
    chain rows plus a few max-length 4-cycle stragglers (the n-gram
    drafter's best case, so spec_k pays on both sides). The queued paged
    scheduler (decode_rows=R, pages recycled to waiting prompts mid-loop)
    races the contiguous FIXED-BATCH schedule (waves of R, each wave
    paying its longest row) at the same resident batch and spec_k=4 on
    both sides. The ISSUE-10 acceptance gate: bit-identical greedy rows,
    strictly fewer verify dispatches, higher tokens/s. Runs on every
    backend (tiny model); gate with BENCH_PAGED=0."""
    import dataclasses

    import jax.numpy as jnp

    from nanorlhf_tpu.core import ModelConfig, init_params
    from nanorlhf_tpu.sampler import SamplingParams, generate

    V, R, resp, spec_k, P = 64, 4, 40, 4, 4
    EOS, PAD = 3, 0
    # wider than qwen2_tiny ON PURPOSE: the queued scheduler trades host
    # syncs for fewer device dispatches, so the A/B only measures the
    # mechanism when per-step compute dominates dispatch overhead (on a
    # 64-wide model the CPU jit-call floor would swamp the win)
    mcfg = dataclasses.replace(
        ModelConfig.qwen2_tiny(vocab_size=V), tie_word_embeddings=False,
        hidden_size=256, intermediate_size=512, num_hidden_layers=4,
    )
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    D = mcfg.hidden_size
    layers = jax.tree.map(jnp.zeros_like, params["layers"])
    for ln in ("input_layernorm", "post_attention_layernorm"):
        layers[ln] = jnp.ones_like(layers[ln])
    params["layers"] = layers
    params["embed_tokens"] = jnp.zeros((V, D), jnp.float32).at[
        jnp.arange(V), jnp.arange(V)
    ].set(1.0)
    sigma = np.arange(V)
    sigma[[5, 6, 7, 8]] = [6, 7, 8, 5]                  # 4-cycle, no EOS
    for t in range(10, 50):                             # chains -> EOS
        sigma[t] = t + 1
    sigma[50] = EOS
    params["lm_head"] = jnp.zeros((D, V), jnp.float32).at[
        jnp.arange(V), jnp.asarray(sigma)
    ].set(12.0 / np.sqrt(D))

    # start v emits min((50 - v) + 1, resp) tokens; 5/6 start the cycle
    # (resp tokens, but HIGH spec acceptance). Queue order: the four
    # length-40 chain stragglers first (they decode concurrently in the
    # R=4 resident rows — non-repetitive, so spec can't compress them),
    # then the short-chain/cycle tail backfills recycled rows. The fixed
    # schedule is dealt ONE straggler per wave — each wave pays ~39
    # dispatches for rows that mostly finished after 3.
    starts = ([11, 11, 11, 11]
              + [47, 48, 5, 47, 48, 46, 48, 6, 47, 48, 46, 47, 48, 46, 48, 47])
    fixed_waves = [[0, 4, 5, 6], [1, 7, 8, 9], [2, 10, 11, 12],
                   [3, 13, 14, 15], [16, 17, 18, 19]]
    prompts = np.full((len(starts), 5), PAD, np.int32)
    prompts[:, 3] = 9                                   # inert filler state
    prompts[:, 4] = starts
    ids, mask = jnp.asarray(prompts), jnp.asarray(prompts != PAD)
    kw = dict(eos_token_id=EOS, pad_token_id=PAD)

    def run_fixed():
        out, stats = np.zeros((len(starts), resp), np.int32), []
        for wave in fixed_waves:
            st: list = []
            idx = jnp.asarray(wave)
            out[wave] = np.asarray(generate(
                params, mcfg, ids[idx], mask[idx], jax.random.PRNGKey(0),
                SamplingParams(greedy=True, max_tokens=resp, spec_k=spec_k),
                spec_stats_out=st, **kw))
            stats.append(st[-1])
        return out, stats

    def run_queued(latency=None):
        pst: list = []
        out = np.asarray(generate(
            params, mcfg, ids, mask, jax.random.PRNGKey(0),
            SamplingParams(greedy=True, max_tokens=resp, spec_k=spec_k,
                           page_size=P, decode_rows=R),
            paged_stats_out=pst, latency=latency, **kw))
        return out, pst[-1]

    walls = {}
    for name, fn in (("fixed", run_fixed), ("queued", run_queued)):
        for rep in range(2):                            # compile + 1 timed
            t0 = time.time()
            out, stats = fn()
            walls[name] = (out, stats, time.time() - t0)

    # per-request TTFT + inter-token percentiles (telemetry/hist.py): one
    # extra queued run with a hub attached — its admission-prefill syncs
    # would perturb the timed A/B above, so it is deliberately untimed
    from nanorlhf_tpu.telemetry.hist import LatencyHub

    hub = LatencyHub()
    run_queued(latency=hub)
    lat_cols = {}
    for col, key in (("ttft", "latency/ttft_s"),
                     ("intertoken", "latency/intertoken_s")):
        if hub.count(key):
            lat_cols[f"{col}_p50_s"] = round(hub.quantile(key, 0.50), 5)
            lat_cols[f"{col}_p95_s"] = round(hub.quantile(key, 0.95), 5)
            lat_cols[f"{col}_count"] = hub.count(key)

    out_f, stats_f, sec_f = walls["fixed"]
    out_q, stats_q, sec_q = walls["queued"]
    tokens = int((out_f != PAD).sum())
    fixed_dispatches = sum(int(np.asarray(s["verify_steps"]))
                           for s in stats_f)
    queued_dispatches = int(np.asarray(stats_q["decode_iterations"]))
    identical = bool(np.array_equal(out_f, out_q))
    return {
        "queue_length": len(starts),
        "decode_rows": R,
        "page_size": P,
        "spec_k": spec_k,
        "response_length": resp,
        "tokens_emitted": tokens,
        "page_utilization": round(
            float(np.asarray(stats_q["page_utilization"])), 4),
        "pages_recycled": int(np.asarray(stats_q["pages_recycled"])),
        "admitted_midloop": int(np.asarray(stats_q["admitted_midloop"])),
        "dispatch_steps_fixed": fixed_dispatches,
        "dispatch_steps_queued": queued_dispatches,
        "tokens_per_sec_fixed": round(tokens / sec_f, 1),
        "tokens_per_sec_queued": round(tokens / sec_q, 1),
        "sec_fixed": round(sec_f, 3),
        "sec_queued": round(sec_q, 3),
        **lat_cols,
        "greedy_bit_identical": identical,
        "paged_check": "ok" if (
            identical and queued_dispatches < fixed_dispatches
            and sec_q < sec_f
        ) else "MISMATCH",
    }


def _swap_check(jax) -> dict:
    """In-flight mid-sequence weight swaps vs drain-and-wait A/B
    (ISSUE 20, docs/ORCHESTRATOR.md §in-flight swaps) on a deterministic
    chain machine, queued paged scheduler on both sides. A publisher
    thread publishes a fresh (numerically identical, so outputs stay
    comparable) weight version at the SAME wall-clock offset in both
    modes — one staleness bound, met two ways: drain-and-wait finishes
    its in-flight half, sits IDLE until the publish lands, then runs the
    second half on the new version; in-flight queues everything at once
    and installs the publish at a host-sync chunk boundary mid-stream.
    Reports generator idle fraction (drain: measured publish wait;
    in-flight: the cumulative install stall `swap_wait_s`), swap
    installs, and episodes/s — the ISSUE-20 gate is strictly lower idle
    in-flight. Plus the no-publish overhead gate: an armed-but-silent
    refresh callback (store never republishes) must cost < 1% wall vs
    `weight_refresh=None` (`swap_overhead_frac`). Runs on every backend
    (tiny model); gate with BENCH_SWAP=0."""
    import dataclasses
    import threading

    import jax.numpy as jnp

    from nanorlhf_tpu.core import ModelConfig, init_params
    from nanorlhf_tpu.orchestrator.weight_store import (
        VersionedWeightStore, make_swap_refresh, store_poll)
    from nanorlhf_tpu.sampler import SamplingParams, generate

    V, R, resp, P = 64, 4, 40, 4
    EOS, PAD = 3, 0
    # same compute-dominant sizing rationale as _paged_check: the swap
    # poll trades a lock+compare per chunk, measurable only when chunk
    # compute dominates the jit-call floor
    mcfg = dataclasses.replace(
        ModelConfig.qwen2_tiny(vocab_size=V), tie_word_embeddings=False,
        hidden_size=256, intermediate_size=512, num_hidden_layers=4,
    )
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    D = mcfg.hidden_size
    layers = jax.tree.map(jnp.zeros_like, params["layers"])
    for ln in ("input_layernorm", "post_attention_layernorm"):
        layers[ln] = jnp.ones_like(layers[ln])
    params["layers"] = layers
    params["embed_tokens"] = jnp.zeros((V, D), jnp.float32).at[
        jnp.arange(V), jnp.arange(V)
    ].set(1.0)
    sigma = np.arange(V)
    for t in range(10, 50):                             # chains -> EOS
        sigma[t] = t + 1
    sigma[50] = EOS
    params["lm_head"] = jnp.zeros((D, V), jnp.float32).at[
        jnp.arange(V), jnp.asarray(sigma)
    ].set(12.0 / np.sqrt(D))

    # start v emits min(50 - v + 1, resp) tokens; two interleaved halves
    # with matched length mixes, so drain's first half costs ~half the
    # full-queue wall
    starts = [11, 16, 21, 26, 31, 36, 41, 46,
              13, 18, 23, 28, 33, 38, 43, 48]
    Q = len(starts) // 2
    prompts = np.full((len(starts), 5), PAD, np.int32)
    prompts[:, 3] = 9                                   # inert filler state
    prompts[:, 4] = starts
    ids, mask = jnp.asarray(prompts), jnp.asarray(prompts != PAD)
    sp = SamplingParams(greedy=True, max_tokens=resp, page_size=P,
                        decode_rows=R)
    kw = dict(eos_token_id=EOS, pad_token_id=PAD)

    def run(ids_, mask_, refresh=None, stats=None):
        return np.asarray(generate(
            params, mcfg, ids_, mask_, jax.random.PRNGKey(0), sp,
            paged_stats_out=stats, weight_refresh=refresh, **kw))

    run(ids, mask)                                      # compile: full queue
    run(ids[:Q], mask[:Q])                              # compile: half queue
    sec_plain = float("inf")
    for _ in range(2):
        t0 = time.time()
        ref_out = run(ids, mask)
        sec_plain = min(sec_plain, time.time() - t0)

    # ---- no-publish overhead: armed-but-silent refresh vs None --------
    store = VersionedWeightStore()
    store.publish(params)                               # v0, never again
    sec_armed = float("inf")
    for _ in range(2):
        st: list = []
        t0 = time.time()
        out_silent = run(ids, mask, stats=st,
                         refresh=make_swap_refresh(store_poll(store),
                                                   have_version=0))
        sec_armed = min(sec_armed, time.time() - t0)
    silent_identical = bool(np.array_equal(out_silent, ref_out))
    silent_installs = int(st[-1]["swap_installs"])
    overhead = max(0.0, (sec_armed - sec_plain) / sec_plain)

    t_pub = 0.75 * sec_plain                            # mid-decode publish

    # ---- in-flight: one queue, install at a chunk boundary ------------
    store = VersionedWeightStore()
    store.publish(params)
    timer = threading.Timer(t_pub, lambda: store.publish(params))
    st = []
    t0 = time.time()
    timer.start()
    out_if = run(ids, mask, stats=st,
                 refresh=make_swap_refresh(store_poll(store),
                                           have_version=0))
    wall_if = time.time() - t0
    timer.cancel()
    installs = int(st[-1]["swap_installs"])
    idle_if = float(st[-1]["swap_wait_s"])
    segments = st[-1]["segments"]

    # ---- drain-and-wait: half, idle until the publish, half -----------
    store = VersionedWeightStore()
    store.publish(params)
    poll = store_poll(store)
    timer = threading.Timer(t_pub, lambda: store.publish(params))
    t0 = time.time()
    timer.start()
    out_a = run(ids[:Q], mask[:Q])
    t_idle0 = time.time()
    while poll(0)[1] is None:                           # the drained idle
        time.sleep(0.001)
    idle_dw = time.time() - t_idle0
    out_b = run(ids[Q:], mask[Q:])
    wall_dw = time.time() - t0
    timer.cancel()
    out_dw = np.concatenate([out_a, out_b])

    identical = bool(np.array_equal(out_if, ref_out)
                     and np.array_equal(out_dw, ref_out))
    return {
        "queue_length": len(starts),
        "decode_rows": R,
        "response_length": resp,
        "publish_at_s": round(t_pub, 3),
        "swap_installs": installs,
        "rows_multi_segment": sum(1 for s in segments if len(s) > 1),
        "idle_frac_inflight": round(idle_if / wall_if, 4),
        "idle_frac_drain": round(idle_dw / wall_dw, 4),
        "episodes_per_sec_inflight": round(len(starts) / wall_if, 2),
        "episodes_per_sec_drain": round(len(starts) / wall_dw, 2),
        "sec_inflight": round(wall_if, 3),
        "sec_drain": round(wall_dw, 3),
        "swap_overhead_frac": round(overhead, 4),
        "silent_poll_installs": silent_installs,
        "greedy_bit_identical": identical,
        "swap_check": "ok" if (
            identical and silent_identical and silent_installs == 0
            and installs >= 1 and idle_if < idle_dw
            and overhead < 0.01
        ) else "MISMATCH",
    }


def _serving_check(jax) -> dict:
    """Cross-request radix prefix-cache A/B (ISSUE 14, docs/SERVING.md):
    the SAME queued paged scheduler at the SAME resident batch, radix
    cache on vs off, over a corpus where >= 50% of prompts share an
    8-real-token prefix with an earlier prompt (two prefix families x 8
    prompts, distinct 2-token tails). With the cache on, every repeat
    admission installs the matched prefix's pages by refcount and
    prefills only its suffix, so `prefill_token_dispatch` (tokens
    actually pushed through prefill/suffix forwards — the FLOPs proxy)
    must be STRICTLY lower and `prefix_hit_frac` must clear 0.4; greedy
    output must stay bit-identical (the rollout-parity pin from
    tests/test_serving.py, re-checked here at bench scale). TTFT
    percentiles come from untimed hub-attached re-runs — admission
    syncs would perturb the timed A/B. Gate with BENCH_SERVING=0."""
    import jax.numpy as jnp

    from nanorlhf_tpu.core import ModelConfig, init_params
    from nanorlhf_tpu.sampler import SamplingParams, generate
    from nanorlhf_tpu.serving.radix import RadixCache
    from nanorlhf_tpu.telemetry.hist import LatencyHub

    V, R, P, Tp, resp = 64, 2, 4, 12, 24
    EOS, PAD = 3, 0
    mcfg = ModelConfig.qwen2_tiny(vocab_size=V)
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    D = mcfg.hidden_size
    # same deterministic machine as the paged check: zeroed layers +
    # identity embedding make greedy generation a pure token permutation,
    # so each prompt's length is chosen by its last real token
    layers = jax.tree.map(jnp.zeros_like, params["layers"])
    for ln in ("input_layernorm", "post_attention_layernorm"):
        layers[ln] = jnp.ones_like(layers[ln])
    params["layers"] = layers
    params["embed_tokens"] = jnp.zeros((V, D), jnp.float32).at[
        jnp.arange(V), jnp.arange(V)
    ].set(1.0)
    sigma = np.arange(V)
    for t in range(10, 50):                             # chains -> EOS
        sigma[t] = t + 1
    sigma[50] = EOS
    params["lm_head"] = jnp.zeros((D, V), jnp.float32).at[
        jnp.arange(V), jnp.asarray(sigma)
    ].set(12.0 / np.sqrt(D))

    # two 8-token prefix families, 8 prompts each, distinct 2-token
    # tails (tail state sets the greedy length): after each family's
    # first (cold) admission the other 7 are 8-real-token prefix hits —
    # 14/16 prompts overlap an earlier one
    fam_a, fam_b = [9] * 8, list(range(21, 29))
    tails = [(51 + i % 4, s) for i, s in enumerate(
        [44, 46, 40, 47, 42, 45, 41, 48])]
    reals = ([fam_a + list(t) for t in tails]
             + [fam_b + list(t) for t in tails])
    prompts = np.full((len(reals), Tp), PAD, np.int32)
    for i, rtoks in enumerate(reals):
        prompts[i, Tp - len(rtoks):] = rtoks
    ids, mask = jnp.asarray(prompts), jnp.asarray(prompts != PAD)
    sp = SamplingParams(greedy=True, max_tokens=resp,
                        page_size=P, decode_rows=R)
    kw = dict(eos_token_id=EOS, pad_token_id=PAD)

    def run(cache, latency=None):
        pst: list = []
        out = np.asarray(generate(
            params, mcfg, ids, mask, jax.random.PRNGKey(0), sp,
            paged_stats_out=pst, latency=latency, prefix_cache=cache,
            **kw))
        return out, pst[-1]

    walls = {}
    for name, cache in (("off", None), ("on", RadixCache())):
        for rep in range(2):                            # compile + 1 timed
            t0 = time.time()
            out, stats = run(cache)
            walls[name] = (out, stats, time.time() - t0)

    lat_cols = {}
    for name, cache in (("off", None), ("on", RadixCache())):
        hub = LatencyHub()
        run(cache, latency=hub)
        if hub.count("latency/ttft_s"):
            lat_cols[f"ttft_p50_s_{name}"] = round(
                hub.quantile("latency/ttft_s", 0.50), 5)
            lat_cols[f"ttft_p95_s_{name}"] = round(
                hub.quantile("latency/ttft_s", 0.95), 5)

    out_off, st_off, sec_off = walls["off"]
    out_on, st_on, sec_on = walls["on"]
    tokens = int((out_off != PAD).sum())
    disp_off = int(st_off["prefill_token_dispatch"])
    disp_on = int(st_on["prefill_token_dispatch"])
    hit_frac = float(st_on["prefix_hit_frac"])
    identical = bool(np.array_equal(out_off, out_on))
    return {
        "queue_length": len(reals),
        "decode_rows": R,
        "page_size": P,
        "prompt_len": Tp,
        "overlap_frac": round(14 / 16, 3),
        "tokens_emitted": tokens,
        "prefix_hit_frac": round(hit_frac, 4),
        "prefix_hit_tokens": int(st_on["prefix_hit_tokens"]),
        "cow_splits": int(st_on["cow_splits"]),
        "evicted_pages": int(st_on["evicted_pages"]),
        "shared_pages_peak": int(st_on["shared_pages"]),
        "prefill_token_dispatch_off": disp_off,
        "prefill_token_dispatch_on": disp_on,
        "tokens_per_sec_off": round(tokens / sec_off, 1),
        "tokens_per_sec_on": round(tokens / sec_on, 1),
        "sec_off": round(sec_off, 3),
        "sec_on": round(sec_on, 3),
        **lat_cols,
        "greedy_bit_identical": identical,
        "serving_check": "ok" if (
            identical and disp_on < disp_off and hit_frac > 0.4
        ) else "MISMATCH",
    }


def _session_check(jax) -> dict:
    """Decode-session composition A/B (ISSUE 18, docs/PAGED_CACHE.md
    §session): two gates.

    SPEC-UNDER-RADIX — the SAME queued scheduler at the SAME resident
    batch on an 87.5%-overlap corpus (one σ-chain prompt repeated 8
    times: the deterministic permutation machine makes every repeat's
    greedy continuation identical, so after the first row finishes the
    radix tree holds the exact text later admissions will generate and
    the drafter seed covers it). Combined spec+radix must issue STRICTLY
    fewer dispatch EVENTS (admission launches + decode/verify chunk
    iterations) than either feature alone — events, not tokens, because
    a verify dispatch carries k+1 tokens where plain decode carries one
    (docs/DECODE_ANALYSIS.md §dispatch accounting); the token-
    denominated half of the win (combined prefill tokens < spec-alone's)
    is gated separately. Greedy output must be bit-identical across all
    four corners.

    CHUNKED PREFILL — client-observed p95 inter-token gap on a live
    ServingEngine stream while long cold prompts admit mid-decode, with
    `prefill_chunk` on, must stay within 1.2x the no-long-prompt
    baseline (same engine, no interfering traffic). The unchunked column
    is reported for contrast but not gated — it pays each long prompt's
    whole suffix forward inside one gap. Client-side arrival timestamps,
    not the hub's chunk-wall metric, because the admission stall happens
    BETWEEN decode chunks and only the stream sees it. Gate with
    BENCH_SESSION=0."""
    import dataclasses

    import jax.numpy as jnp

    from nanorlhf_tpu.core import ModelConfig, init_params
    from nanorlhf_tpu.sampler import SamplingParams, generate
    from nanorlhf_tpu.serving.radix import RadixCache

    V, R, P, Tp, resp = 64, 2, 4, 12, 12
    EOS, PAD = 3, 0
    # the σ-chain needs an UNTIED lm_head: with tie_word_embeddings the
    # unembedding is embed_tokensᵀ, logits collapse to token similarity
    # and greedy re-emits the input token forever — a constant stream the
    # unseeded drafter matches trivially, which voids the A/B
    mcfg = dataclasses.replace(
        ModelConfig.qwen2_tiny(vocab_size=V), tie_word_embeddings=False
    )
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    D = mcfg.hidden_size
    # the deterministic permutation machine (as in _serving_check):
    # zeroed layers + identity embedding + σ-chain lm_head make greedy
    # generation follow σ from the last real token
    layers = jax.tree.map(jnp.zeros_like, params["layers"])
    for ln in ("input_layernorm", "post_attention_layernorm"):
        layers[ln] = jnp.ones_like(layers[ln])
    params["layers"] = layers
    params["embed_tokens"] = jnp.zeros((V, D), jnp.float32).at[
        jnp.arange(V), jnp.arange(V)
    ].set(1.0)
    sigma = np.arange(V)
    for t in range(10, 50):
        sigma[t] = t + 1
    params["lm_head"] = jnp.zeros((D, V), jnp.float32).at[
        jnp.arange(V), jnp.asarray(sigma)
    ].set(12.0 / np.sqrt(D))

    # 8 identical prompts: 7/8 = 87.5% overlap an earlier admission;
    # chain start 30 → every row greedily emits 31..42
    real = [9] * 6 + [30]
    Q = 8
    prompts = np.full((Q, Tp), PAD, np.int32)
    prompts[:, Tp - len(real):] = real
    ids, mask = jnp.asarray(prompts), jnp.asarray(prompts != PAD)
    kw = dict(eos_token_id=EOS, pad_token_id=PAD)

    def run(spec_k, cache):
        sp = SamplingParams(greedy=True, max_tokens=resp, page_size=P,
                            decode_rows=R, spec_k=spec_k)
        pst: list = []
        out = np.asarray(generate(
            params, mcfg, ids, mask, jax.random.PRNGKey(0), sp,
            paged_stats_out=pst, prefix_cache=cache, **kw))
        return out, pst[-1]

    out_plain, _ = run(0, None)
    out_radix, st_radix = run(0, RadixCache())
    out_spec, st_spec = run(3, None)
    out_both, st_both = run(3, RadixCache())

    identical = (np.array_equal(out_plain, out_radix)
                 and np.array_equal(out_plain, out_spec)
                 and np.array_equal(out_plain, out_both))
    ev = {k: int(s["dispatch_events"]) for k, s in
          (("radix", st_radix), ("spec", st_spec), ("both", st_both))}
    pf = {k: int(s["prefill_token_dispatch"]) for k, s in
          (("radix", st_radix), ("spec", st_spec), ("both", st_both))}
    spec_radix = {
        "queue_length": Q,
        "decode_rows": R,
        "overlap_frac": round((Q - 1) / Q, 3),
        "dispatch_events_radix": ev["radix"],
        "dispatch_events_spec": ev["spec"],
        "dispatch_events_both": ev["both"],
        "prefill_tokens_radix": pf["radix"],
        "prefill_tokens_spec": pf["spec"],
        "prefill_tokens_both": pf["both"],
        "prefix_hit_tokens": int(st_both["prefix_hit_tokens"]),
        "drafter_seed_window": st_both["session"]["features"][
            "drafter_seed_window"],
        "greedy_bit_identical": bool(identical),
        "gate": "ok" if (
            identical and ev["both"] < min(ev["radix"], ev["spec"])
            and pf["both"] < pf["spec"]
        ) else "MISMATCH",
    }

    # ---- chunked prefill: client-observed p95 inter-token gap -------- #
    from nanorlhf_tpu.serving.engine import ServingEngine

    Tp_l, MN, CH = 48, 24, 8
    long_real = list(range(4, 52))                      # 48-token cold
    victim_real = [9] * 3 + [10]

    def gaps(prefill_chunk, n_long):
        eng = ServingEngine(params, mcfg, eos_token_id=EOS,
                            pad_token_id=PAD, page_size=P,
                            prompt_len=Tp_l, max_new_tokens=MN, rows=R,
                            sync_every=4, seed=0,
                            prefill_chunk=prefill_chunk)
        try:
            # warm every compile path (victim admission, long-prompt
            # suffix bucket / chunk forward, decode chunk) before timing
            for warm in (victim_real, long_real):
                wreq, _ = eng.submit(warm, greedy=True)
                list(eng.stream(wreq))
            req, _ = eng.submit(victim_real, greedy=True)
            it = eng.stream(req)
            next(it)
            stamps = [time.perf_counter()]
            submitted = 0
            for _ in it:
                stamps.append(time.perf_counter())
                if submitted < n_long:                  # interfere mid-decode
                    submitted += 1
                    lreq, _ = eng.submit(long_real, greedy=True)
            deltas = np.diff(stamps)
            return float(np.quantile(deltas, 0.95)) if deltas.size else 0.0
        finally:
            eng.close()

    p95_base = gaps(CH, 0)
    p95_chunked = gaps(CH, 3)
    p95_unchunked = gaps(0, 3)
    ratio = p95_chunked / max(p95_base, 1e-9)
    chunked = {
        "prompt_len": Tp_l,
        "prefill_chunk": CH,
        "long_prompts": 3,
        "p95_intertoken_s_baseline": round(p95_base, 5),
        "p95_intertoken_s_chunked": round(p95_chunked, 5),
        "p95_intertoken_s_unchunked": round(p95_unchunked, 5),
        "p95_ratio_vs_baseline": round(ratio, 3),
        "gate": "ok" if ratio <= 1.2 else "MISMATCH",
    }
    return {
        "spec_under_radix": spec_radix,
        "chunked_prefill": chunked,
        "session_check": "ok" if (
            spec_radix["gate"] == "ok" and chunked["gate"] == "ok"
        ) else "MISMATCH",
    }


def _traffic_check(jax) -> dict:
    """Goodput-vs-offered-load curve (ISSUE 16, docs/TRAFFIC.md): replay
    the SAME deterministic workload spec (seed-folded prompts, greedy
    sampling, prefix-family overlap) against a FRESH in-process
    ServingEngine at each rate on a >= 3-point offered-load grid
    (BENCH_TRAFFIC_RATES, rps), via the open-loop TrafficDriver — offered
    load is the spec's, not the engine's, so past the knee the curve
    shows shedding and TTFT degradation instead of silently slowing the
    client. Checks: every point conserves requests (completed + shed +
    errors == offered, errors == 0), and the highest rate sheds at least
    as much as the lowest. Gate with BENCH_TRAFFIC=0."""
    import jax.numpy as jnp

    from nanorlhf_tpu.core import ModelConfig, init_params
    from nanorlhf_tpu.loadgen import (
        TrafficDriver, WorkloadSpec, format_table, points_as_detail,
        run_sweep, spec_digest,
    )
    from nanorlhf_tpu.serving.engine import ServingEngine

    V, R, P, Tp, mx = 64, 2, 4, 12, 8
    EOS, PAD = 3, 0
    mcfg = ModelConfig.qwen2_tiny(vocab_size=V)
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    D = mcfg.hidden_size
    # the serving check's deterministic machine: zeroed layers + identity
    # embedding make greedy generation a pure token permutation
    layers = jax.tree.map(jnp.zeros_like, params["layers"])
    for ln in ("input_layernorm", "post_attention_layernorm"):
        layers[ln] = jnp.ones_like(layers[ln])
    params["layers"] = layers
    params["embed_tokens"] = jnp.zeros((V, D), jnp.float32).at[
        jnp.arange(V), jnp.arange(V)
    ].set(1.0)
    sigma = np.arange(V)
    for t in range(10, 50):
        sigma[t] = t + 1
    sigma[50] = EOS
    params["lm_head"] = jnp.zeros((D, V), jnp.float32).at[
        jnp.arange(V), jnp.asarray(sigma)
    ].set(12.0 / np.sqrt(D))

    spec = WorkloadSpec(
        seed=0, n_requests=24, arrival="poisson",
        prompt_len_min=4, prompt_len_max=Tp,
        token_lo=10, token_hi=50, prefix_groups=3, prefix_frac=0.5,
        prefix_len=4, greedy_frac=1.0,
        max_tokens_min=mx, max_tokens_max=mx,
    )
    rates = [float(r) for r in os.environ.get(
        "BENCH_TRAFFIC_RATES", "4,64,1024").split(",")]

    def make_engine():
        return ServingEngine(
            params, mcfg, eos_token_id=EOS, pad_token_id=PAD,
            page_size=P, prompt_len=Tp, max_new_tokens=mx, rows=R,
            max_queue=4, slo_warn_ttft_s=1e9)

    def run_point(point_spec):
        # fresh engine per point: shed state and radix contents must not
        # bleed across rates. slo_warn disabled so the only shed cause is
        # the queue bound — the deterministic knee. max_queue=4 on 2 rows
        # puts the knee inside the default grid.
        engine = make_engine()
        try:
            driver = TrafficDriver(engine=engine, stream_timeout_s=60.0)
            return driver.run(point_spec)
        finally:
            engine.close()

    # warm the jit cache OUTSIDE the measured sweep: one discarded run of
    # the same workload compiles every suffix-bucket/cow path the points
    # will touch — otherwise compile lands on the first point's arrivals,
    # backs up its queue, and inverts the curve (the LOWEST rate would
    # shed the most)
    run_point(dataclasses.replace(spec, rate_rps=16.0))

    points = run_sweep(run_point, spec, rates)
    print("offered-load sweep (in-process engine):", file=sys.stderr)
    print(format_table(points), file=sys.stderr)
    conserved = all(
        p.completed + p.shed + p.errors == spec.n_requests
        and p.errors == 0
        for p in points)
    monotone_knee = points[-1].shed >= points[0].shed
    return {
        "spec_digest": spec_digest(spec),
        "n_requests": spec.n_requests,
        "decode_rows": R,
        "max_queue": 4,
        "grid": points_as_detail(points),
        "traffic_check": "ok" if (
            len(points) >= 3 and conserved and monotone_knee
        ) else "MISMATCH",
    }


def _env_check(jax) -> dict:
    """Multi-turn environment A/B (ISSUE 15, docs/ENVIRONMENTS.md): the
    SAME episode driver at the SAME resident batch (decode_rows), a 2-turn
    python-tool corpus vs the single-turn degenerate case. The 2-turn side
    must average >= 2 turns/episode, loss-mask its observation tokens
    False, and recycle pages through the continuation admissions (a
    stalled tool holds zero KV capacity); tool_stall_overlap is the
    fraction of continuation decode chunks that ran while at least one
    tool call was still in flight — the latency-hiding signal. The
    single-turn side never enters the continuation loop: exactly 1
    turn/episode, mask all True, zero admissions. Tiny model + toy
    tokenizer, runs on every backend; gate with BENCH_ENV=0."""
    import jax.numpy as jnp

    from nanorlhf_tpu.core import ModelConfig, init_params
    from nanorlhf_tpu.data import ToyTokenizer
    from nanorlhf_tpu.envs import (
        PythonToolEnv,
        SingleTurnEnv,
        run_env_episodes,
    )
    from nanorlhf_tpu.sampler import SamplingParams

    tok = ToyTokenizer(vocab_size=256)
    mcfg = ModelConfig.qwen2_tiny(vocab_size=tok.vocab_size)
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    B, n_samp, Tp = 4, 2, 8
    turn_tokens, obs_budget, resp, P = 16, 8, 48, 4
    rows = B * n_samp
    texts = [f"bench prompt {i} compute the answer" for i in range(B)]
    ids = np.full((B, Tp), tok.pad_token_id, np.int32)
    pmask = np.zeros((B, Tp), bool)
    for i, t in enumerate(texts):
        e = tok.encode(t)[:Tp]
        ids[i, Tp - len(e):] = e
        pmask[i, Tp - len(e):] = True
    sampling = SamplingParams(max_tokens=turn_tokens, temperature=1.0,
                              n=n_samp)
    kw = dict(eos_token_id=tok.eos_token_id, pad_token_id=tok.pad_token_id,
              tokenizer=tok, turn_tokens=turn_tokens, obs_budget=obs_budget,
              response_length=resp, page_size=P, decode_rows=rows // 2)

    def reward(pairs, eos):
        return [1.0] * len(pairs)

    env2 = PythonToolEnv(reward_func=reward, max_turns=2)
    # the toy tokenizer collapses whitespace, so fenced ```python blocks
    # don't survive a decode round-trip — pin the extracted program (same
    # move as tests/test_envs.py); the observation is still a REAL pooled
    # subprocess execution, so tool walls and stalls are genuine
    env2.extractor = lambda text: "print(6 * 7)"
    env1 = SingleTurnEnv(reward_func=reward)

    sides = {}
    try:
        for name, env, mt in (("multi", env2, 2), ("single", env1, 1)):
            t0 = time.time()
            out = run_env_episodes(
                params, mcfg, jnp.asarray(ids), jnp.asarray(pmask),
                jax.random.PRNGKey(7), sampling, env, max_turns=mt, **kw)
            sec = time.time() - t0
            st = out["stats"]
            sides[name] = {
                "turns_per_episode": round(st["env/turns_per_episode"], 3),
                "obs_tokens_masked": int((~out["loss_mask"]).sum()),
                "tool_wall_s": st["env/tool_wall_s"],
                "tool_stall_overlap": round(st["env/tool_stall_overlap"], 3),
                "stalled_rows": int(st["env/stalled_rows"]),
                "admissions": int(out["admissions"]),
                "pages_recycled": int(out["pages_recycled"]),
                "sec": round(sec, 3),
            }
    finally:
        env2.close()
    multi, single = sides["multi"], sides["single"]
    return {
        "episodes": rows,
        "decode_rows": rows // 2,
        "page_size": P,
        "turn_tokens": turn_tokens,
        "obs_budget": obs_budget,
        "response_length": resp,
        "multi_turn": multi,
        "single_turn": single,
        "env_check": "ok" if (
            multi["turns_per_episode"] >= 2.0
            and multi["obs_tokens_masked"] > 0
            and multi["admissions"] >= rows
            and multi["pages_recycled"] > 0
            and single["turns_per_episode"] == 1.0
            and single["obs_tokens_masked"] == 0
            and single["admissions"] == 0
        ) else "MISMATCH",
    }


def _flash_on_chip_check(jax) -> dict:
    import jax.numpy as jnp

    from nanorlhf_tpu.ops.attention import flash_attention, reference_attention

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        B, H, KV, T, d = 2, 8, 2, 512, 64
    else:  # interpret mode runs the grid in Python — keep the shape tiny
        B, H, KV, T, d = 1, 4, 2, 128, 64
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(ks[0], (B, H, T, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (B, KV, T, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (B, KV, T, d), jnp.bfloat16)
    lens = [T] * B
    if B > 1:
        lens[1] = T - 100
    key_valid = jnp.arange(T)[None, :] < jnp.asarray(lens)[:, None]

    def loss(fn):
        def f(q, k, v):
            return (fn(q, k, v) ** 2).sum()

        return jax.jit(jax.grad(f, argnums=(0, 1, 2)))

    # explicit block 128 so the T=512 grid has 4x4 kv/q blocks: the check
    # must exercise cross-block online-softmax carry and the causal skip,
    # not collapse to one block under the (tuned) 512 default
    blk = dict(block_q=128, block_k=128)
    out_p = flash_attention(q, k, v, key_valid, causal=True, **blk)
    out_r = reference_attention(q, k, v, key_valid, causal=True)
    fwd_err = _rel_err(jnp, out_p, out_r)
    gp = loss(lambda q, k, v: flash_attention(q, k, v, key_valid, True, **blk))(q, k, v)
    gr = loss(lambda q, k, v: reference_attention(q, k, v, key_valid, True))(q, k, v)
    bwd_err = max(_rel_err(jnp, a, b) for a, b in zip(gp, gr))
    tol = 0.02  # relative; bf16 inputs, f32 accumulation
    status = "ok" if (fwd_err < tol and bwd_err < tol) else "MISMATCH"
    return {
        "pallas_check": status,
        "pallas_interpret": not on_tpu,
        "pallas_fwd_max_err": round(fwd_err, 5),
        "pallas_bwd_max_err": round(bwd_err, 5),
    }


def main():
    if os.environ.get("BENCH_CHILD") != "1":
        return orchestrate()
    # ---- measurement child: the only process that imports jax ----
    try:
        import jax

        from nanorlhf_tpu.utils.compile_cache import enable_compilation_cache

        # persistent compile cache: warm-started sessions spend tunnel time
        # measuring, not recompiling the bucket menu (VERDICT r4 #2)
        enable_compilation_cache()
        jax.devices()  # force backend init inside the bounded child
        return run_bench(jax, os.environ.get("BENCH_TPU_ERROR") or None)
    except Exception as e:  # one parseable line, never a bare stack trace
        import traceback

        _emit(_error_payload(
            f"{type(e).__name__}: {e}",
            traceback=traceback.format_exc()[-1500:],
        ))
        return 0


def run_bench(jax, init_error):
    import dataclasses

    import jax.numpy as jnp

    from nanorlhf_tpu.core import ModelConfig, init_params
    from nanorlhf_tpu.data import ToyTokenizer, load_prompt_dataset
    from nanorlhf_tpu.parallel import MeshConfig
    from nanorlhf_tpu.trainer import AlgoName, RLConfig, RLTrainer

    backend = jax.default_backend()
    on_cpu_fallback = os.environ.get("BENCH_CPU_FALLBACK") == "1"

    n_prompts = int(os.environ.get("BENCH_PROMPTS", 32))
    sample_n = int(os.environ.get("BENCH_SAMPLE_N", 4))
    # default = the reference's operating point (response_length 1500,
    # `/root/reference/README.md:36`): `value`/`vs_baseline` must compare
    # like with like (VERDICT r3 #8) — a resp-256 headline overstates parity
    # against a resp-1500 A100 baseline
    response_len = int(os.environ.get("BENCH_RESPONSE", 1500))
    model_name = os.environ.get(
        "BENCH_MODEL", "tiny" if on_cpu_fallback else "1_5b"
    )
    n_updates = int(os.environ.get("BENCH_UPDATES", 2))
    attention_impl = os.environ.get("BENCH_ATTENTION", "auto")
    use_lora = os.environ.get("BENCH_LORA", "1") == "1"
    rollout_quant = "int8" if os.environ.get("BENCH_QUANT", "0") == "1" else "none"
    rollout_ahead = os.environ.get("BENCH_AHEAD", "0") == "1"
    orchestrator = os.environ.get("BENCH_ORCH", "0") == "1"
    orch_staleness = int(os.environ.get("BENCH_STALENESS", "2"))
    kv_cache_quant = "int8" if os.environ.get("BENCH_KV_QUANT", "0") == "1" else "none"
    spec_k_env = int(os.environ.get("BENCH_SPEC_K", "0"))
    fleet_workers_env = int(os.environ.get("BENCH_FLEET_WORKERS", "0"))
    # BENCH_SWEEP=1 (default on real TPU): after the baseline, ALSO measure
    # the int8 rollout levers and report the faster config as the headline.
    # A lever failure (lowering, numerics) falls back to the already-measured
    # baseline instead of eating the round's only bench run.
    sweep = os.environ.get(
        "BENCH_SWEEP", "1" if backend == "tpu" else "0"
    ) == "1" and rollout_quant == "none" and kv_cache_quant == "none"
    if on_cpu_fallback:
        # reduced shapes so the fallback terminates; payload marks backend=cpu
        n_prompts = min(n_prompts, 8)
        response_len = min(response_len, 64)

    from nanorlhf_tpu.telemetry.mfu import peak_flops_per_chip, update_flops

    n_dev = len(jax.devices())
    device_kind = jax.devices()[0].device_kind
    peak, peak_known = peak_flops_per_chip(device_kind, backend)

    mcfg = (
        ModelConfig.qwen2_1_5b() if model_name == "1_5b"
        else ModelConfig.qwen2_tiny(vocab_size=4096)
    )
    mcfg = dataclasses.replace(mcfg, attention_impl=attention_impl)
    tok = ToyTokenizer(vocab_size=min(4096, mcfg.vocab_size))
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.bfloat16)
    n_params = count_params({k: v for k, v in params.items() if k != "lora"})

    # batch hierarchy: one update consumes n_prompts episodes
    grad_accum = 2 if n_prompts % (2 * 2 * n_dev) == 0 else 1
    num_mini = 2 if n_prompts % (2 * grad_accum * n_dev) == 0 else 1
    per_dev = n_prompts // (grad_accum * num_mini * n_dev)
    assert per_dev >= 1, "BENCH_PROMPTS too small for device count"

    def reward(pmt_and_responses, eos_token):
        # cheap rule-based reward: keeps the bench focused on the TPU path
        return np.asarray(
            [(1.0 if eos_token in s else 0.0) - 0.001 * len(s.split())
             for s in pmt_and_responses],
            np.float32,
        )

    dataset = load_prompt_dataset(f"synthetic:{max(64, n_prompts * 2)}", tok,
                                  max_prompt_len=64)

    def measure(r_quant, kv_quant, ahead, resp=None, capture=False,
                orchestrator=False, staleness=2, sentinel=True,
                telemetry=False, spec_k=None, workers=1, health=True,
                lineage=False, transport="inprocess", latency=True):
        """One full config measurement: fresh trainer, warmup update
        (compile) + n_updates timed. Returns the timing dict.

        `orchestrator=True` runs the async rollout pipeline
        (docs/ORCHESTRATOR.md) at `max_staleness=staleness` with
        truncated-IS correction (capture forced on — it supplies the
        behavior logprobs). Note the bench's repeated train(num_updates=1)
        calls are exactly where the orchestrator's cross-call pipelining
        beats rollout_ahead, whose prefetch never fires inside a
        single-update train() call — the payload's
        rollout_train_overlap_frac rows make that visible.
        """
        resp = response_len if resp is None else resp
        spec_k = spec_k_env if spec_k is None else spec_k
        cfg = RLConfig(
            algo=AlgoName.GRPO,
            output_dir="/tmp/nanorlhf_tpu_bench",
            sampler_logprob_capture=capture or orchestrator,
            response_length=resp,
            temperature=0.9,
            sample_n=sample_n,
            per_device_train_batch_size=per_dev,
            gradient_accumulation_steps=grad_accum,
            num_mini_batches=num_mini,
            num_ppo_epochs=1,
            kl_coef=0.01,
            use_lora=use_lora,
            rollout_quant=r_quant,
            rollout_ahead=ahead and not orchestrator,
            rollout_orchestrator=orchestrator,
            rollout_workers=workers if orchestrator else 1,
            rollout_transport=transport,
            max_staleness=staleness,
            sentinel=sentinel,
            telemetry=telemetry,
            health=health,
            lineage=lineage,
            latency=latency,
            kv_cache_quant=kv_quant,
            rollout_spec_k=spec_k,
            gradient_checkpointing=True,
            mesh=MeshConfig(n_dev, 1, 1),
            save_steps=0,
            report_to="none",
            logging_steps=10**9,
        )
        cfg.total_episodes = n_prompts * (n_updates + 1)  # +1 warmup/compile
        trainer = RLTrainer(cfg, mcfg, tok, params, dataset, reward)
        times = []
        phase_snapshot = {}
        try:
            for i in range(n_updates + 1):
                t0 = time.time()
                trainer.train(num_updates=1)
                times.append(time.time() - t0)
                if i == 0:  # snapshot after warmup: phase split = steady-state
                    phase_snapshot = dict(trainer.timer.cumulative)
            overlap = trainer.rollout_overlap_frac()
        finally:
            trainer.close()  # join the orchestrator's producer thread
        steady = times[1:] if len(times) > 1 else times
        sec = float(np.mean(steady))
        return {
            "rollout_quant": r_quant,
            "kv_cache_quant": kv_quant,
            "fused_logprob": cfg.fused_logprob,
            "rollout_ahead": cfg.rollout_ahead,
            "rollout_orchestrator": orchestrator,
            "rollout_workers": workers if orchestrator else None,
            "max_staleness": staleness if orchestrator else None,
            "rollout_shared_prefill": cfg.rollout_shared_prefill,
            "rollout_spec_k": spec_k,
            "sampler_logprob_capture": cfg.sampler_logprob_capture,
            "response_length": resp,
            "sec_per_update_steady": round(sec, 3),
            "compile_update_sec": round(times[0], 3),
            # rollout/train overlap: fraction of generation wall-clock that
            # ran concurrently with trainer work (orchestrator.OverlapMeter)
            "rollout_train_overlap_frac": round(overlap, 4),
            # cfg.batch_size (set by finalize inside RLTrainer) is the TRUE
            # episode count per update
            "episodes_per_update": cfg.batch_size,
            "phase_split_s_per_update": {
                k: round((v - phase_snapshot.get(k, 0.0)) / max(len(steady), 1), 3)
                for k, v in sorted(trainer.timer.cumulative.items())
            },
            # latency surface (telemetry/hist.py): per-key count/mean/
            # p50/p95/p99 from this run's streaming histograms — the
            # fleet detail's TTFT/queue-wait percentile columns read it
            "latency_summary": trainer.latency.snapshot(),
        }

    t_baseline = time.time()
    chosen = measure(rollout_quant, kv_cache_quant, rollout_ahead,
                     orchestrator=orchestrator, staleness=orch_staleness)
    t_baseline = time.time() - t_baseline
    # peak HBM across the baseline config's updates (fused hidden→logprob
    # memory trajectory, BENCH_r06 onward; process-cumulative, so captured
    # BEFORE any sweep configs run). 0.0 on backends without memory stats.
    from nanorlhf_tpu.trainer.trainer import device_peak_bytes

    peak_bytes_in_use = device_peak_bytes()
    sweep_detail = None
    # the lever config recompiles everything (≈ another baseline's worth of
    # wall time) — skip when that would risk the parent's attempt timeout
    # eating the numbers we already have
    budget = float(os.environ.get("BENCH_ATTEMPT_TIMEOUT", 2100))
    if sweep and t_baseline > 0.4 * budget:
        sweep = False
        sweep_detail = {
            "skipped": f"baseline took {t_baseline:.0f}s of {budget:.0f}s budget"
        }
    if sweep:
        try:
            lever = measure("int8", "int8", rollout_ahead)
            sweep_detail = {
                "baseline_sec_per_update": chosen["sec_per_update_steady"],
                "int8_sec_per_update": lever["sec_per_update_steady"],
            }
            if lever["sec_per_update_steady"] < chosen["sec_per_update_steady"]:
                chosen = lever
        except Exception as e:  # lever failed: keep the measured baseline
            sweep_detail = {"int8_error": f"{type(e).__name__}: {e}"[:300]}
        # full stack: int8 + rollout-ahead overlap + sampler logprob capture
        # (capture halves the scoring forwards; its decode-vs-scoring drift
        # is logged by the trainer, and the ratio-clip tolerates it) — only
        # when the remaining budget can absorb another compile, and never
        # after an int8 failure (the stack reuses int8 and would just burn
        # ~a baseline's budget reproducing the same error)
        if ("int8_error" not in sweep_detail
                and budget - (time.time() - _T0) > 1.2 * t_baseline):
            try:
                stack = measure("int8", "int8", True, capture=True)
                sweep_detail["all_levers_sec_per_update"] = (
                    stack["sec_per_update_steady"]
                )
                if (stack["sec_per_update_steady"]
                        < chosen["sec_per_update_steady"]):
                    chosen = stack
            except Exception as e:
                sweep_detail["all_levers_error"] = (
                    f"{type(e).__name__}: {e}"[:300]
                )
        # async rollout orchestrator lever (docs/ORCHESTRATOR.md): depth-2
        # pipelined rollouts with truncated-IS correction. Its
        # rollout_train_overlap_frac row vs the baseline's (and vs a
        # BENCH_AHEAD run's) is the pipelining acceptance signal — the
        # bench's repeated train(num_updates=1) calls are exactly where
        # rollout_ahead's in-call prefetch never fires but the
        # orchestrator's producer thread keeps the pipeline warm.
        if (not orchestrator and isinstance(sweep_detail, dict)
                and budget - (time.time() - _T0) > 1.2 * t_baseline):
            try:
                orch = measure(
                    chosen["rollout_quant"], chosen["kv_cache_quant"], False,
                    orchestrator=True, staleness=orch_staleness,
                )
                sweep_detail["orchestrator_sec_per_update"] = (
                    orch["sec_per_update_steady"]
                )
                sweep_detail["orchestrator_overlap_frac"] = (
                    orch["rollout_train_overlap_frac"]
                )
                sweep_detail["baseline_overlap_frac"] = (
                    chosen["rollout_train_overlap_frac"]
                )
                if (orch["sec_per_update_steady"]
                        < chosen["sec_per_update_steady"]):
                    chosen = orch
            except Exception as e:
                sweep_detail["orchestrator_error"] = (
                    f"{type(e).__name__}: {e}"[:300]
                )
        # speculative-decode lever (sampler/speculative.py): draft-free
        # n-gram drafting + batched k-token verify at spec_k=4. Its win is
        # corpus-dependent (acceptance on the toy-tokenizer corpus is the
        # pessimistic floor; R1 math rollouts are the target) — the
        # detail.spec_decode synthetic A/B carries the mechanism's ceiling,
        # this sweep point carries the end-to-end wall on the bench corpus.
        if (spec_k_env == 0 and isinstance(sweep_detail, dict)
                and budget - (time.time() - _T0) > 1.2 * t_baseline):
            try:
                spec = measure(
                    chosen["rollout_quant"], chosen["kv_cache_quant"],
                    chosen["rollout_ahead"],
                    capture=chosen["sampler_logprob_capture"],
                    orchestrator=chosen["rollout_orchestrator"],
                    staleness=chosen["max_staleness"] or orch_staleness,
                    spec_k=4,
                )
                sweep_detail["spec_k4_sec_per_update"] = (
                    spec["sec_per_update_steady"]
                )
                if (spec["sec_per_update_steady"]
                        < chosen["sec_per_update_steady"]):
                    chosen = spec
            except Exception as e:
                sweep_detail["spec_k4_error"] = (
                    f"{type(e).__name__}: {e}"[:300]
                )

    # sentinel-overhead point (docs/RESILIENCE.md acceptance: the guard
    # costs <2% of the step wall): re-measure the chosen config with the
    # training sentinel disabled and report the relative delta. The
    # sentinel-off run reuses the chosen config's compiled executables
    # EXCEPT the update fn (whose grad-norm stat is emitted regardless of
    # the flag, so even that recompile is shape-identical) — cheap relative
    # to a full lever sweep, still gated on remaining budget.
    sentinel_detail = None
    if (os.environ.get("BENCH_SENTINEL", "1") == "1"
            and budget - (time.time() - _T0) > 0.9 * t_baseline):
        try:
            guard_off = measure(
                chosen["rollout_quant"], chosen["kv_cache_quant"],
                chosen["rollout_ahead"],
                capture=chosen["sampler_logprob_capture"],
                orchestrator=chosen["rollout_orchestrator"],
                staleness=chosen["max_staleness"] or orch_staleness,
                spec_k=chosen.get("rollout_spec_k", 0),
                sentinel=False,
            )
            off_sec = guard_off["sec_per_update_steady"]
            sentinel_detail = {
                "on_sec_per_update": chosen["sec_per_update_steady"],
                "off_sec_per_update": off_sec,
                "sentinel_overhead_frac": round(
                    (chosen["sec_per_update_steady"] - off_sec)
                    / max(off_sec, 1e-9), 4,
                ),
            }
        except Exception as e:
            sentinel_detail = {"error": f"{type(e).__name__}: {e}"[:300]}

    # telemetry-overhead A/B (docs/OBSERVABILITY.md acceptance: the span
    # tracer + flight recorder + perf accounting cost < 1% of step wall
    # when enabled): re-measure the chosen config with cfg.telemetry on.
    # Compiled executables are config-identical, so the re-run is cheap
    # relative to a lever sweep; still gated on remaining budget.
    telemetry_detail = None
    if (os.environ.get("BENCH_TELEMETRY", "1") == "1"
            and budget - (time.time() - _T0) > 0.9 * t_baseline):
        try:
            tele_on = measure(
                chosen["rollout_quant"], chosen["kv_cache_quant"],
                chosen["rollout_ahead"],
                capture=chosen["sampler_logprob_capture"],
                orchestrator=chosen["rollout_orchestrator"],
                staleness=chosen["max_staleness"] or orch_staleness,
                spec_k=chosen.get("rollout_spec_k", 0),
                telemetry=True,
            )
            on_sec = tele_on["sec_per_update_steady"]
            telemetry_detail = {
                "off_sec_per_update": chosen["sec_per_update_steady"],
                "on_sec_per_update": on_sec,
                "telemetry_overhead_frac": round(
                    (on_sec - chosen["sec_per_update_steady"])
                    / max(chosen["sec_per_update_steady"], 1e-9), 4,
                ),
            }
        except Exception as e:
            telemetry_detail = {"error": f"{type(e).__name__}: {e}"[:300]}

    # health-plane overhead A/B (docs/OBSERVABILITY.md §5 acceptance: the
    # default-ON streaming aggregators + rule evaluation cost < 1% of step
    # wall): the chosen config already ran with health on, so re-measure it
    # with the monitor disabled and report on-vs-off. Same budget gate as
    # the telemetry A/B.
    health_detail = None
    if (os.environ.get("BENCH_HEALTH", "1") == "1"
            and budget - (time.time() - _T0) > 0.9 * t_baseline):
        try:
            health_off = measure(
                chosen["rollout_quant"], chosen["kv_cache_quant"],
                chosen["rollout_ahead"],
                capture=chosen["sampler_logprob_capture"],
                orchestrator=chosen["rollout_orchestrator"],
                staleness=chosen["max_staleness"] or orch_staleness,
                spec_k=chosen.get("rollout_spec_k", 0),
                health=False,
            )
            off_sec = health_off["sec_per_update_steady"]
            health_detail = {
                "off_sec_per_update": off_sec,
                "on_sec_per_update": chosen["sec_per_update_steady"],
                "health_overhead_frac": round(
                    (chosen["sec_per_update_steady"] - off_sec)
                    / max(off_sec, 1e-9), 4,
                ),
            }
        except Exception as e:
            health_detail = {"error": f"{type(e).__name__}: {e}"[:300]}

    # lineage-ledger overhead A/B (docs/OBSERVABILITY.md §6 acceptance: the
    # per-rollout provenance writes — lease/generation/queue/reward/outcome
    # JSONL appends — cost < 1% of step wall when cfg.lineage is on): the
    # chosen config ran with lineage OFF (the default), so re-measure with
    # the ledger enabled and report on-vs-off. Same budget gate as the
    # other observability A/Bs.
    lineage_detail = None
    if (os.environ.get("BENCH_LINEAGE", "1") == "1"
            and budget - (time.time() - _T0) > 0.9 * t_baseline):
        try:
            lineage_on = measure(
                chosen["rollout_quant"], chosen["kv_cache_quant"],
                chosen["rollout_ahead"],
                capture=chosen["sampler_logprob_capture"],
                orchestrator=chosen["rollout_orchestrator"],
                staleness=chosen["max_staleness"] or orch_staleness,
                spec_k=chosen.get("rollout_spec_k", 0),
                lineage=True,
            )
            on_sec = lineage_on["sec_per_update_steady"]
            lineage_detail = {
                "off_sec_per_update": chosen["sec_per_update_steady"],
                "on_sec_per_update": on_sec,
                "lineage_overhead_frac": round(
                    (on_sec - chosen["sec_per_update_steady"])
                    / max(chosen["sec_per_update_steady"], 1e-9), 4,
                ),
            }
        except Exception as e:
            lineage_detail = {"error": f"{type(e).__name__}: {e}"[:300]}

    # latency-surface overhead A/B (docs/OBSERVABILITY.md §7 acceptance:
    # the default-ON streaming histograms — TTFT/queue-wait/reward/phase
    # recording plus SLO-rule quantile reads — cost < 1% of step wall):
    # the chosen config already ran with the hub on, so re-measure with
    # cfg.latency off and report on-vs-off. Same budget gate as the other
    # observability A/Bs.
    latency_detail = None
    if (os.environ.get("BENCH_LATENCY", "1") == "1"
            and budget - (time.time() - _T0) > 0.9 * t_baseline):
        try:
            latency_off = measure(
                chosen["rollout_quant"], chosen["kv_cache_quant"],
                chosen["rollout_ahead"],
                capture=chosen["sampler_logprob_capture"],
                orchestrator=chosen["rollout_orchestrator"],
                staleness=chosen["max_staleness"] or orch_staleness,
                spec_k=chosen.get("rollout_spec_k", 0),
                latency=False,
            )
            off_sec = latency_off["sec_per_update_steady"]
            latency_detail = {
                "off_sec_per_update": off_sec,
                "on_sec_per_update": chosen["sec_per_update_steady"],
                "latency_overhead_frac": round(
                    (chosen["sec_per_update_steady"] - off_sec)
                    / max(off_sec, 1e-9), 4,
                ),
            }
        except Exception as e:
            latency_detail = {"error": f"{type(e).__name__}: {e}"[:300]}

    # fleet-coordinator overhead A/B (docs/FLEET.md acceptance: the lease /
    # reorder-buffer / liveness machinery costs < 2% of step wall): measure
    # the single-producer pipeline and the N-worker fleet at the SAME
    # staleness (>= N so every worker can hold a lease) and report the
    # relative delta. Generation work is identical — the delta isolates
    # coordination cost. Opt-in via BENCH_FLEET_WORKERS >= 2; two extra
    # measured configs, so gated on a wider budget margin.
    fleet_detail = None
    if (fleet_workers_env >= 2
            and budget - (time.time() - _T0) > 1.8 * t_baseline):
        fleet_staleness = max(orch_staleness, fleet_workers_env)
        try:
            single = measure(
                chosen["rollout_quant"], chosen["kv_cache_quant"], False,
                orchestrator=True, staleness=fleet_staleness,
                spec_k=chosen.get("rollout_spec_k", 0),
            )
            fleet = measure(
                chosen["rollout_quant"], chosen["kv_cache_quant"], False,
                orchestrator=True, staleness=fleet_staleness,
                spec_k=chosen.get("rollout_spec_k", 0),
                workers=fleet_workers_env,
            )
            single_sec = single["sec_per_update_steady"]
            fleet_sec = fleet["sec_per_update_steady"]
            fleet_detail = {
                "workers": fleet_workers_env,
                "max_staleness": fleet_staleness,
                "single_producer_sec_per_update": single_sec,
                "fleet_sec_per_update": fleet_sec,
                "single_producer_overlap_frac": single[
                    "rollout_train_overlap_frac"
                ],
                "fleet_overlap_frac": fleet["rollout_train_overlap_frac"],
                "coordinator_overhead_frac": round(
                    (fleet_sec - single_sec) / max(single_sec, 1e-9), 4,
                ),
            }
            # TTFT / queue-wait percentile columns (telemetry/hist.py):
            # the fleet run's own histograms — dispatch→device-ready TTFT
            # upper bound per generation, dequeue−ready queue wait per
            # consumed sample
            for col, key in (("ttft", "latency/ttft_s"),
                             ("queue_wait", "latency/queue_wait_s")):
                summ = fleet.get("latency_summary", {}).get(key)
                if summ and summ.get("count"):
                    fleet_detail[f"{col}_p50_s"] = round(summ["p50_s"], 4)
                    fleet_detail[f"{col}_p95_s"] = round(summ["p95_s"], 4)
                    fleet_detail[f"{col}_count"] = summ["count"]
            # loopback-RPC transport A/B (docs/FLEET.md §multi-host
            # acceptance: framing + codec + retry machinery costs < 5% of
            # step wall at 2 workers): same fleet config, the 3-call seam
            # now crosses a length-prefixed socket round trip per lease /
            # completion / weight fetch instead of direct method calls.
            if budget - (time.time() - _T0) > 1.3 * t_baseline:
                fleet_rpc = measure(
                    chosen["rollout_quant"], chosen["kv_cache_quant"], False,
                    orchestrator=True, staleness=fleet_staleness,
                    spec_k=chosen.get("rollout_spec_k", 0),
                    workers=fleet_workers_env, transport="rpc",
                )
                rpc_sec = fleet_rpc["sec_per_update_steady"]
                fleet_detail["rpc_sec_per_update"] = rpc_sec
                fleet_detail["rpc_overlap_frac"] = fleet_rpc[
                    "rollout_train_overlap_frac"
                ]
                fleet_detail["rpc_transport_overhead_frac"] = round(
                    (rpc_sec - fleet_sec) / max(fleet_sec, 1e-9), 4,
                )
        except Exception as e:
            fleet_detail = {"error": f"{type(e).__name__}: {e}"[:300]}

    # secondary short-response point (the r1/r2 rounds' resp-256 shape) so
    # the payload carries BOTH operating points — the resp-1500 headline
    # stays baseline-comparable and the short point tracks decode-lever
    # progress round over round. Skipped when the remaining budget can't
    # absorb another full compile, or when the caller pinned BENCH_RESPONSE
    # at/below the short width already.
    # reserve ~a baseline's worth of time for the short point itself (its
    # compile cost matches the baseline's even though its decode is shorter)
    # — launching it into insufficient budget would let the parent timeout
    # kill the child and lose the already-measured headline numbers
    short_detail = None
    if (
        backend == "tpu"
        and response_len > 256
        and budget - (time.time() - _T0) > 0.9 * t_baseline
    ):
        try:
            short = measure(
                chosen["rollout_quant"], chosen["kv_cache_quant"],
                chosen["rollout_ahead"], resp=256,
                capture=chosen["sampler_logprob_capture"],
                orchestrator=chosen["rollout_orchestrator"],
                staleness=chosen["max_staleness"] or orch_staleness,
                spec_k=chosen.get("rollout_spec_k", 0),
            )
            short_detail = {
                "response_length": 256,
                "sampler_logprob_capture": short["sampler_logprob_capture"],
                "sec_per_update_steady": short["sec_per_update_steady"],
                "episodes_per_sec_per_chip": round(
                    short["episodes_per_update"]
                    / short["sec_per_update_steady"] / n_dev, 4,
                ),
            }
        except Exception as e:
            short_detail = {"error": f"{type(e).__name__}: {e}"[:300]}

    sec_per_update = chosen["sec_per_update_steady"]
    episodes_per_update = chosen["episodes_per_update"]
    rollout_quant = chosen["rollout_quant"]
    kv_cache_quant = chosen["kv_cache_quant"]
    eps_per_sec_per_chip = episodes_per_update / sec_per_update / n_dev

    # ---- tokens/s + MFU (napkin model-FLOPs accounting) -------------------
    # decode runs until every row hits EOS; with a toy-tokenizer reward the
    # loop nearly always runs the full response_length — use it as the step
    # count. Rollout processes B·n rows per decode step.
    rollout_rows = episodes_per_update * sample_n
    ctx = min(64, dataset.input_ids.shape[1])
    seq_len = ctx + response_len
    decode_tokens = rollout_rows * response_len
    prefill_tokens = rollout_rows * ctx
    # GRPO keeps 1-of-N BEFORE the logprob pass, so only `episodes` rows are
    # scored (policy + ref) — counting all B·n rows would inflate MFU; with
    # sampler capture the policy half never runs, so only the ref forward
    # counts
    score_forwards = 1 if chosen["sampler_logprob_capture"] else 2
    score_tokens = score_forwards * episodes_per_update * seq_len
    train_tokens = 1 * episodes_per_update * seq_len    # num_ppo_epochs = 1
    # telemetry/mfu.py: forward-only tokens at 2N, trained at 3·2N — the
    # same formula behind the trainer's per-update perf/mfu metric
    flops_per_update = update_flops(
        n_params, decode_tokens=decode_tokens, prefill_tokens=prefill_tokens,
        score_tokens=score_tokens, train_tokens=train_tokens,
    )
    mfu = flops_per_update / sec_per_update / (peak * n_dev)
    tokens_per_sec = (
        (decode_tokens + prefill_tokens + score_tokens + train_tokens)
        / sec_per_update
    )

    pallas = pallas_on_chip_check(jax)
    try:
        # always-run A/B (tiny model, any backend): the lever's acceptance/
        # dispatch mechanics stay measurable on the CPU-fallback bench
        spec_decode_detail = _spec_decode_check(jax)
    except Exception as e:
        spec_decode_detail = {"error": f"{type(e).__name__}: {e}"[:300]}
    paged_detail = None
    if os.environ.get("BENCH_PAGED", "1") == "1":
        try:
            # continuous-batching A/B (tiny model, any backend) — the
            # ISSUE-10 gate: queued-paged beats fixed-batch tokens/s on a
            # long-tail corpus with spec_k=4 on both sides, bit-identical
            paged_detail = _paged_check(jax)
        except Exception as e:
            paged_detail = {"error": f"{type(e).__name__}: {e}"[:300]}
    serving_detail = None
    if os.environ.get("BENCH_SERVING", "1") == "1":
        try:
            # radix prefix-cache A/B (tiny model, any backend) — the
            # ISSUE-14 gate: >= 50% prompt overlap must clear
            # prefix_hit_frac 0.4 with strictly fewer dispatched prefill
            # tokens at equal resident batch, greedy bit-identical
            serving_detail = _serving_check(jax)
        except Exception as e:
            serving_detail = {"error": f"{type(e).__name__}: {e}"[:300]}
    session_detail = None
    if os.environ.get("BENCH_SESSION", "1") == "1":
        try:
            # decode-session composition A/B (tiny model, any backend) —
            # the ISSUE-18 gates: spec+radix combined < min(each alone)
            # in dispatch events at equal resident batch on an
            # 87.5%-overlap corpus, greedy bit-identical 4-way, and the
            # chunked-prefill p95 inter-token gap within 1.2x the
            # no-long-prompt baseline
            session_detail = _session_check(jax)
        except Exception as e:
            session_detail = {"error": f"{type(e).__name__}: {e}"[:300]}
    traffic_detail = None
    if os.environ.get("BENCH_TRAFFIC", "1") == "1":
        try:
            # goodput-vs-offered-load sweep (tiny model, any backend) —
            # the ISSUE-16 gate: >= 3 deterministic offered-load points
            # with goodput, shed-rate, and p95-TTFT columns
            traffic_detail = _traffic_check(jax)
        except Exception as e:
            traffic_detail = {"error": f"{type(e).__name__}: {e}"[:300]}
    swap_detail = None
    if os.environ.get("BENCH_SWAP", "1") == "1":
        try:
            # in-flight weight-swap A/B (tiny model, any backend) — the
            # ISSUE-20 gates: in-flight installs a mid-decode publish at a
            # chunk boundary with strictly lower generator idle than
            # drain-and-wait at the same publish offset, and an armed-but-
            # silent refresh costs < 1% wall vs weight_refresh=None
            swap_detail = _swap_check(jax)
        except Exception as e:
            swap_detail = {"error": f"{type(e).__name__}: {e}"[:300]}
    env_detail = None
    if os.environ.get("BENCH_ENV", "1") == "1":
        try:
            # multi-turn environment A/B (tiny model, any backend) — the
            # ISSUE-15 gate: 2-turn python-tool episodes average >= 2
            # turns/episode at the same resident batch as single-turn,
            # observation tokens loss-masked, pages recycled mid-episode
            env_detail = _env_check(jax)
        except Exception as e:
            env_detail = {"error": f"{type(e).__name__}: {e}"[:300]}

    detail = {
        "backend": backend,
        "device_kind": device_kind,
        "model": model_name,
        "n_params": n_params,
        "attention": attention_impl,
        "lora": use_lora,
        "rollout_quant": rollout_quant,
        "fused_logprob": chosen["fused_logprob"],
        "peak_bytes_in_use": peak_bytes_in_use,
        "rollout_ahead": chosen["rollout_ahead"],
        "rollout_orchestrator": chosen["rollout_orchestrator"],
        "max_staleness": chosen["max_staleness"],
        "rollout_train_overlap_frac": chosen["rollout_train_overlap_frac"],
        "rollout_shared_prefill": chosen["rollout_shared_prefill"],
        "rollout_spec_k": chosen.get("rollout_spec_k", 0),
        "sampler_logprob_capture": chosen["sampler_logprob_capture"],
        "kv_cache_quant": kv_cache_quant,
        "spec_decode": spec_decode_detail,
        **({"paged": paged_detail} if paged_detail is not None else {}),
        **({"serving": serving_detail} if serving_detail is not None else {}),
        **({"session": session_detail} if session_detail is not None else {}),
        **({"traffic": traffic_detail} if traffic_detail is not None else {}),
        **({"swap": swap_detail} if swap_detail is not None else {}),
        **({"env": env_detail} if env_detail is not None else {}),
        "prompts_per_update": episodes_per_update,
        "sample_n": sample_n,
        "response_length": response_len,
        "devices": n_dev,
        "sec_per_update_steady": round(sec_per_update, 3),
        "compile_update_sec": chosen["compile_update_sec"],
        "tokens_per_sec": round(tokens_per_sec, 1),
        "decode_tokens_per_sec": round(decode_tokens / sec_per_update, 1),
        "mfu": round(mfu, 4),
        "peak_flops_per_chip": peak,
        "peak_flops_known": peak_known,
        # the peak-FLOPs table fell back to a nominal constant for this
        # chip: the mfu number above is a placeholder ratio, not a real
        # utilization figure — don't read it bare
        **({} if peak_known else
           {"mfu_note": "untrusted: peak FLOPs unknown for this chip "
                        "(nominal constant used)"}),
        "phase_split_s_per_update": chosen["phase_split_s_per_update"],
        **pallas,
    }
    if sweep_detail is not None:
        detail["sweep"] = sweep_detail
    if sentinel_detail is not None:
        detail["sentinel"] = sentinel_detail
    if telemetry_detail is not None:
        detail["telemetry"] = telemetry_detail
    if health_detail is not None:
        detail["health"] = health_detail
    if lineage_detail is not None:
        detail["lineage"] = lineage_detail
    if latency_detail is not None:
        detail["latency"] = latency_detail
    if fleet_detail is not None:
        detail["fleet"] = fleet_detail
    if short_detail is not None:
        detail["short_response"] = short_detail
    if init_error is not None:
        detail["tpu_init_error"] = init_error[-500:]

    # vs_baseline only means something for the flagship model on real TPU
    # silicon AT the baseline's operating point (response_length 1500) — a
    # tiny-model CPU fallback or a short-response run must not claim a beat
    comparable = (
        backend == "tpu" and model_name == "1_5b" and response_len >= 1500
    )
    payload = {
        "metric": "grpo_episodes_per_sec_per_chip",
        "value": round(eps_per_sec_per_chip, 4),
        "unit": "episodes/s/chip",
        "vs_baseline": (
            round(eps_per_sec_per_chip / BASELINE_EPS_PER_SEC, 4)
            if comparable else 0.0
        ),
        "detail": detail,
    }
    if not comparable:
        detail["vs_baseline_note"] = (
            "0.0: run not comparable to the A100 baseline "
            f"(backend={backend}, model={model_name}, "
            f"response_length={response_len})"
        )
    elif chosen.get("sampler_logprob_capture"):
        # the sweep may promote a capture-mode run (approximate
        # old-logprobs, one fewer scoring forward) to the headline; keep
        # the comparability shift visible next to vs_baseline
        detail["vs_baseline_note"] = (
            "chosen config uses sampler_logprob_capture=True (decode-time "
            "old-logprobs, scoring forwards halved) — the A100 baseline "
            "rescores rollouts; see detail.sweep for the full-scoring time"
        )
    if init_error is not None:
        payload["error"] = f"TPU unavailable, CPU fallback: {init_error[-300:]}"
    _emit(payload)
    return 0


if __name__ == "__main__":
    sys.exit(main())
