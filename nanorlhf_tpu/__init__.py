"""nanorlhf_tpu — a TPU-native RLHF framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of
jackfsuia/nanoRLHF (PPO, GRPO, RLOO, ReMax, REINFORCE, RAFT online RL
post-training), built TPU-first:

- one HBM-resident sharded param tree serves generation, logprob scoring and
  training (no vLLM disk round-trip, no CPU offload choreography);
- rollouts via a jitted autoregressive sampler with KV cache;
- the six near-identical reference trainers collapse to one runtime plus
  per-algorithm (sampling_spec, advantage_fn, loss_fn) triples;
- scaling via jax.sharding.Mesh + pjit/shard_map over ICI, not NCCL.

Reference behavior map: see SURVEY.md at the repo root.
"""

__version__ = "0.1.0"
