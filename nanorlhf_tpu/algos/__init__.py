from nanorlhf_tpu.algos.advantages import (
    grpo_group_advantage,
    rloo_advantage,
    remax_advantage,
    best_of_k_indices,
    keep_one_of_n_indices,
    sparse_terminal_rewards,
    grpo_turn_advantage,
    per_turn_terminal_rewards,
    discounted_returns,
    gae,
)
from nanorlhf_tpu.algos.losses import (
    ppo_clip_loss_token,
    ppo_clip_loss_sequence,
    grpo_loss,
    value_loss_clipped,
    sft_loss,
    k3_kl,
)

__all__ = [
    "grpo_group_advantage",
    "rloo_advantage",
    "remax_advantage",
    "best_of_k_indices",
    "keep_one_of_n_indices",
    "sparse_terminal_rewards",
    "grpo_turn_advantage",
    "per_turn_terminal_rewards",
    "discounted_returns",
    "gae",
    "ppo_clip_loss_token",
    "ppo_clip_loss_sequence",
    "grpo_loss",
    "value_loss_clipped",
    "sft_loss",
    "k3_kl",
]
