"""Advantage estimators for all six algorithms — pure jnp, jit-safe.

Each function re-states, as a standalone pure function, advantage math the
reference inlines inside a 700-line `train()` body (SURVEY.md §2.4):

- GRPO group z-score       `/root/reference/GRPO/grpo_trainer.py:502-519`
- RLOO leave-one-out       `/root/reference/RLOO/rloo_trainer.py:595-599`
- ReMax greedy baseline    `/root/reference/ReMax/remax_trainer.py:506-513`
- PPO GAE(γ, λ)            `/root/reference/PPO/ppo_trainer.py:687-697`
- REINFORCE γ-discounting  `/root/reference/REINFORCE/reinforce_trainer.py:583-588`
- RAFT best-of-K           `/root/reference/RAFT/raft_trainer.py:585-588`
- sparse terminal reward   `/root/reference/GRPO/grpo_trainer.py:596-603`
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def grpo_group_advantage(scores: jnp.ndarray, sample_n: int) -> jnp.ndarray:
    """Per-prompt-group z-score: (r - mean_group) / std_group.

    `scores` is flat [B*N] ordered group-major (N consecutive samples per
    prompt — the order the sampler emits). Uses the unbiased (ddof=1) std to
    match `torch.Tensor.std` in the reference (`GRPO/grpo_trainer.py:508`).
    A zero-variance group divides 0/0; the reference maps the resulting NaN
    to 0 (`:513`), and so do we.
    """
    groups = scores.reshape(-1, sample_n).astype(jnp.float32)
    mean = groups.mean(axis=1, keepdims=True)
    std = jnp.sqrt(
        jnp.sum((groups - mean) ** 2, axis=1, keepdims=True) / (sample_n - 1)
    )
    adv = (groups - mean) / std
    adv = jnp.where(jnp.isnan(adv), 0.0, adv)
    return adv.reshape(-1)


def rloo_advantage(rlhf_reward: jnp.ndarray, sample_n: int) -> jnp.ndarray:
    """Leave-one-out baseline: r_i - mean(r_{j != i}).

    `rlhf_reward` is the flat [B*N] *sequence-level* reward (score + KL
    penalty summed over tokens), group-major. (`RLOO/rloo_trainer.py:595-599`.)
    """
    groups = rlhf_reward.reshape(-1, sample_n).astype(jnp.float32)
    baseline = (groups.sum(axis=1, keepdims=True) - groups) / (sample_n - 1)
    return (groups - baseline).reshape(-1)


def remax_advantage(scores: jnp.ndarray, greedy_scores: jnp.ndarray) -> jnp.ndarray:
    """Sampled-rollout reward minus greedy-rollout reward for the same prompt.

    (`ReMax/remax_trainer.py:513`.)
    """
    return scores - greedy_scores


def best_of_k_indices(
    rlhf_reward: jnp.ndarray, sample_k: int, key: jax.Array | None = None
) -> jnp.ndarray:
    """RAFT selection: index of the best of K samples per prompt.

    The reference computes argmax then immediately overwrites it with a random
    index (`RAFT/raft_trainer.py:585-588`) — the argmax is the documented
    intent ("keep those max reward RAFT samples"). We implement the intent:
    argmax by default; pass `key` to reproduce the as-shipped random-of-K.
    """
    groups = rlhf_reward.reshape(-1, sample_k)
    if key is not None:
        return jax.random.randint(key, (groups.shape[0],), 0, sample_k)
    return jnp.argmax(groups, axis=1)


def keep_one_of_n_indices(key: jax.Array, batch_size: int, sample_n: int) -> jnp.ndarray:
    """GRPO/RLOO keep-1-of-N: a uniformly random sample index per prompt.

    Used to drop N-1 of the N rollouts after the group baseline is computed,
    to save forward/backward time (`GRPO/grpo_trainer.py:505,510`).
    """
    return jax.random.randint(key, (batch_size,), 0, sample_n)


def sparse_terminal_rewards(
    scores: jnp.ndarray,
    sequence_lengths: jnp.ndarray,
    response_length: int,
    kl_penalty: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Build the per-token reward tensor: sparse score at EOS (+ optional KL).

    The score lands at `min(seq_len + 1, seq_len_if_out_of_range)` — i.e. one
    past the last real token when that position exists, else on the last token
    (`GRPO/grpo_trainer.py:596-603`). `kl_penalty`, when given, is the dense
    `-kl_coef * (logprobs - ref_logprobs)` term added at every position
    (KL-in-reward algorithms, e.g. `RLOO/rloo_trainer.py:570-578`).
    """
    batch = scores.shape[0]
    rewards = (
        jnp.zeros((batch, response_length), dtype=jnp.float32)
        if kl_penalty is None
        else kl_penalty.astype(jnp.float32)
    )
    seq_p1 = sequence_lengths + 1
    actual_end = jnp.where(seq_p1 < response_length, seq_p1, sequence_lengths)
    return rewards.at[jnp.arange(batch), actual_end].add(scores.astype(jnp.float32))


def grpo_turn_advantage(turn_rewards: jnp.ndarray, sample_n: int) -> jnp.ndarray:
    """Per-turn GRPO advantage: z-score each turn column within its group.

    `turn_rewards` is [B*N, K] group-major (K = max turns; absent turns
    hold 0 and a whole-group-absent column z-scores to 0 via the NaN→0
    rule). Normalizing per (group, turn-column) instead of on episode
    totals keeps the GRPO baseline semantics while crediting each turn
    against the SAME turn of its siblings — a strong turn 2 after a weak
    turn 1 is rewarded as such, not averaged away. Degenerate K=1 is
    exactly `grpo_group_advantage`.
    """
    rows, k = turn_rewards.shape
    groups = turn_rewards.reshape(-1, sample_n, k).astype(jnp.float32)
    mean = groups.mean(axis=1, keepdims=True)
    std = jnp.sqrt(
        jnp.sum((groups - mean) ** 2, axis=1, keepdims=True) / (sample_n - 1)
    )
    adv = (groups - mean) / std
    adv = jnp.where(jnp.isnan(adv), 0.0, adv)
    return adv.reshape(rows, k)


def per_turn_terminal_rewards(
    turn_rewards: jnp.ndarray,
    turn_ends: jnp.ndarray,
    response_length: int,
) -> jnp.ndarray:
    """Sparse per-token rewards with one spike at EACH turn's final token.

    Multi-turn generalization of `sparse_terminal_rewards`: `turn_ends`
    [B, K] holds the response-coordinate index of each turn's last model
    token (−1 for absent turns — dropped via out-of-range scatter). Running
    `discounted_returns(γ=1)` over the result broadcasts each turn's
    credit over the tokens that produced it AND every earlier turn —
    reward-to-go per turn, the per-turn attribution the multi-turn GRPO
    path scores with.
    """
    batch = turn_rewards.shape[0]
    rewards = jnp.zeros((batch, response_length), jnp.float32)
    ends = jnp.where(turn_ends < 0, response_length, turn_ends)
    return rewards.at[
        jnp.arange(batch)[:, None], ends
    ].add(turn_rewards.astype(jnp.float32), mode="drop")


def discounted_returns(rewards: jnp.ndarray, gamma: float) -> jnp.ndarray:
    """Reversed cumulative sum with discount: A_t = r_t + γ A_{t+1}.

    γ=1 is the GRPO token-advantage broadcast (`GRPO/grpo_trainer.py:610-620`);
    γ<1 is REINFORCE (`REINFORCE/reinforce_trainer.py:583-588`).
    """

    def step(carry, r_t):
        a_t = r_t + gamma * carry
        return a_t, a_t

    _, out = jax.lax.scan(step, jnp.zeros_like(rewards[:, 0]), rewards.T, reverse=True)
    return out.T


def gae(
    rewards: jnp.ndarray, values: jnp.ndarray, gamma: float, lam: float
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Generalized advantage estimation.

    `values[:, t]` is the value of the state *before* emitting token t; there
    are T positions (value at one-past-EOS already zeroed by padding_mask_p1).
    delta_t = r_t + γ V_{t+1} - V_t ; A_t = delta_t + γλ A_{t+1} ;
    returns = A + V. (`PPO/ppo_trainer.py:687-697`.)
    """
    next_values = jnp.concatenate(
        [values[:, 1:], jnp.zeros_like(values[:, :1])], axis=1
    )

    def step(carry, inp):
        r_t, v_t, nv_t = inp
        delta = r_t + gamma * nv_t - v_t
        a_t = delta + gamma * lam * carry
        return a_t, a_t

    _, out = jax.lax.scan(
        step,
        jnp.zeros_like(rewards[:, 0]),
        (rewards.T, values.T, next_values.T),
        reverse=True,
    )
    advantages = out.T
    returns = advantages + values
    return advantages, returns
