"""Loss functions for all six algorithms — pure jnp, jit/grad-safe.

Pinned to the per-trainer inlined losses (SURVEY.md §2.4):

- token-level PPO-clip       `/root/reference/GRPO/grpo_trainer.py:655-661`
- GRPO k3-KL term            `/root/reference/GRPO/grpo_trainer.py:662-672`
- sequence-level PPO-clip    `/root/reference/RLOO/rloo_trainer.py:660-669`
- clipped value loss         `/root/reference/PPO/ppo_trainer.py:742-756`
- RAFT SFT loss              `/root/reference/RAFT/raft_trainer.py:636`

Every function returns `(loss, aux)` where `aux` holds the detached stats the
reference accumulates per microbatch (`GRPO/grpo_trainer.py:674-689`).
"""

from __future__ import annotations

import jax.numpy as jnp

from nanorlhf_tpu.ops.masking import masked_mean


def _ratio_and_stats(new_logprobs, old_logprobs):
    """logprob diff / importance ratio shared by all PPO-style losses.

    Padded positions carry INVALID_LOGPROB in both tensors, so their diff is 0
    and the ratio is exactly 1 there — harmless under the mask, and it keeps
    the unmasked `approxkl = 0.5 * mean(diff²)` identical to the reference's
    (`GRPO/grpo_trainer.py:684`).
    """
    logprobs_diff = new_logprobs - old_logprobs
    ratio = jnp.exp(logprobs_diff)
    approxkl = 0.5 * jnp.mean(logprobs_diff**2)
    return logprobs_diff, ratio, approxkl


def ppo_clip_loss_token(
    new_logprobs: jnp.ndarray,
    old_logprobs: jnp.ndarray,
    advantages: jnp.ndarray,
    mask: jnp.ndarray,
    cliprange: float,
):
    """Token-level clipped policy-gradient loss (PPO/ReMax/REINFORCE/GRPO core).

    `mask` is True on *real* tokens (the reference passes `~padding_mask`).
    """
    _, ratio, approxkl = _ratio_and_stats(new_logprobs, old_logprobs)
    pg_losses = -advantages * ratio
    pg_losses2 = -advantages * jnp.clip(ratio, 1.0 - cliprange, 1.0 + cliprange)
    pg_loss_max = jnp.maximum(pg_losses, pg_losses2)
    loss = masked_mean(pg_loss_max, mask)
    aux = {
        "pg_clipfrac": masked_mean((pg_losses2 > pg_losses).astype(jnp.float32), mask),
        "approxkl": approxkl,
        "ratio_mean": jnp.mean(ratio),
        "pg_loss": loss,
    }
    return loss, aux


def k3_kl(new_logprobs: jnp.ndarray, ref_logprobs: jnp.ndarray) -> jnp.ndarray:
    """k3 KL estimator: e^{-kl} + kl - 1 where kl = logπ - logπ_ref.

    Always ≥ 0; the GRPO in-loss KL penalty (`GRPO/grpo_trainer.py:667-670`).
    """
    kl = new_logprobs - ref_logprobs
    return jnp.exp(-kl) + kl - 1.0


def grpo_loss(
    new_logprobs: jnp.ndarray,
    old_logprobs: jnp.ndarray,
    ref_logprobs: jnp.ndarray,
    advantages: jnp.ndarray,
    mask: jnp.ndarray,
    cliprange: float,
    kl_coef: float,
):
    """GRPO = token-level PPO-clip + kl_coef · k3-KL, jointly masked-meaned.

    (`GRPO/grpo_trainer.py:662-672` — note the KL term sits *inside* the
    masked mean with the clipped PG term.)
    """
    _, ratio, approxkl = _ratio_and_stats(new_logprobs, old_logprobs)
    pg_losses = -advantages * ratio
    pg_losses2 = -advantages * jnp.clip(ratio, 1.0 - cliprange, 1.0 + cliprange)
    kl = new_logprobs - ref_logprobs
    kl_term = kl_coef * k3_kl(new_logprobs, ref_logprobs)
    pg_loss_max = jnp.maximum(pg_losses, pg_losses2) + kl_term
    loss = masked_mean(pg_loss_max, mask)
    aux = {
        "pg_clipfrac": masked_mean((pg_losses2 > pg_losses).astype(jnp.float32), mask),
        "approxkl": approxkl,
        "ratio_mean": jnp.mean(ratio),
        "refkl_mean": jnp.mean(kl),
        "pg_loss": loss,
    }
    return loss, aux


def ppo_clip_loss_sequence(
    new_logprobs: jnp.ndarray,
    old_logprobs: jnp.ndarray,
    advantages: jnp.ndarray,
    mask: jnp.ndarray,
    cliprange: float,
):
    """Sequence-level PPO-clip (RLOO): ratio of summed logprobs, plain mean.

    The reference sums the INVALID_LOGPROB-filled tensors directly
    (`RLOO/rloo_trainer.py:660-662`); the pad contributions cancel in the
    diff, so masking before the sum is exactly equivalent.
    `advantages` is sequence-level, shape [B].
    """
    mask_f = mask.astype(new_logprobs.dtype)
    new_sum = jnp.sum(new_logprobs * mask_f, axis=1)
    old_sum = jnp.sum(old_logprobs * mask_f, axis=1)
    logprobs_diff = new_sum - old_sum
    ratio = jnp.exp(logprobs_diff)
    pg_losses = -advantages * ratio
    pg_losses2 = -advantages * jnp.clip(ratio, 1.0 - cliprange, 1.0 + cliprange)
    pg_loss_max = jnp.maximum(pg_losses, pg_losses2)
    loss = jnp.mean(pg_loss_max)
    aux = {
        "pg_clipfrac": jnp.mean((pg_losses2 > pg_losses).astype(jnp.float32)),
        "approxkl": 0.5 * jnp.mean(logprobs_diff**2),
        "ratio_mean": jnp.mean(ratio),
        "pg_loss": loss,
    }
    return loss, aux


def value_loss_clipped(
    vpred: jnp.ndarray,
    values: jnp.ndarray,
    returns: jnp.ndarray,
    mask_p1: jnp.ndarray,
    cliprange_value: float,
):
    """PPO clipped value loss: 0.5 · masked_mean(max((v-R)², (v_clip-R)²)).

    `mask_p1` is True on valid value positions (~padding_mask_p1).
    (`PPO/ppo_trainer.py:742-748`.)
    """
    vpredclipped = jnp.clip(vpred, values - cliprange_value, values + cliprange_value)
    vf_losses1 = jnp.square(vpred - returns)
    vf_losses2 = jnp.square(vpredclipped - returns)
    vf_loss_max = jnp.maximum(vf_losses1, vf_losses2)
    loss = 0.5 * masked_mean(vf_loss_max, mask_p1)
    aux = {
        "vf_loss": loss,
        "vf_clipfrac": masked_mean((vf_losses2 > vf_losses1).astype(jnp.float32), mask_p1),
    }
    return loss, aux


def sft_loss(new_logprobs: jnp.ndarray, mask: jnp.ndarray):
    """RAFT: negative summed logprob of the kept sample, mean over batch.

    The reference sums the INVALID_LOGPROB-filled tensor
    (`RAFT/raft_trainer.py:636`), adding a gradient-free -1·n_pad constant per
    row; we mask before summing — identical gradients, cleaner loss value.
    """
    mask_f = mask.astype(new_logprobs.dtype)
    loss = -jnp.mean(jnp.sum(new_logprobs * mask_f, axis=1))
    return loss, {"pg_loss": loss}
