"""nanolint — project-invariant static analysis + lock-order audit.

This package is deliberately jax-free and stdlib-only so the CLI
(`tools/nanolint.py`) and the runtime lock-order sanitizer
(`lockorder.make_lock`, enabled via ``NANORLHF_LOCK_CHECK=1``) can be
imported from any module — including the jax-free telemetry layer —
without dragging in heavy dependencies.

Modules:
  engine      Finding model, allowlist annotations, baseline workflow.
  determinism Rule family 1: wall-clock / unseeded-RNG / PRNG key reuse.
  jitpurity   Rule family 2: host syncs + traced-value branches under jit.
  registry    Rule family 3: fault-site / metric / health-rule cross-checks.
  lockgraph   Rule family 4: static lock-acquisition graph extraction.
  lockorder   Declared partial order + instrumented OrderedLock runtime.

See docs/STATIC_ANALYSIS.md for the rule catalog.
"""
