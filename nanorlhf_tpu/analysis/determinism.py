"""Rule family 1 — determinism.

Replayability (lineage ledger, bit-parity fleet tests) and the
straggler-deadline EWMA both require that nothing nondeterministic or
NTP-steppable leaks into rollout/trainer/orchestrator control flow:

  determinism.wall-clock      time.time() in scoped paths. Wall clock is
                              legal only for provenance stamps (lineage
                              record times, metrics rows) via an
                              allowlist annotation; anything feeding
                              durations, EWMAs, deadlines, or intervals
                              must use time.perf_counter()/monotonic()
                              (the PhaseTimer NTP-step fix from PR 4).
  determinism.unseeded-random random.* / np.random.* module-state draws.
                              All sampling randomness flows through
                              fold_in-derived jax.random keys; the only
                              sanctioned stdlib-RNG use is a locally
                              constructed random.Random(seed).
  determinism.key-reuse       the same jax.random key variable consumed
                              by two draws with no intervening
                              split/fold_in/reassignment.

Scope for the clock/RNG rules: orchestrator/, trainer/, sampler/ (the
paths that feed PRNG, latency EWMAs, and lease deadlines). The
telemetry layer is out of scope by design — its timestamps are
provenance by definition and its rows carry both time and t_mono.
"""

from __future__ import annotations

import ast

from .engine import Finding, Project, dotted_name

SCOPE_PREFIXES = (
    "nanorlhf_tpu/orchestrator/",
    "nanorlhf_tpu/trainer/",
    "nanorlhf_tpu/sampler/",
)

# jax.random callables that *derive* new keys rather than consuming
# entropy for a draw; using the source key again after these is the
# documented idiom (split) or a no-op on the key (fold_in returns new).
_DERIVERS = {"split", "fold_in", "PRNGKey", "key", "clone", "wrap_key_data"}


class _SiteCounter:
    """Stable per-function ordinals so details survive line churn."""

    def __init__(self):
        self._counts: dict[tuple[str, str], int] = {}

    def detail(self, what: str, func: str) -> str:
        n = self._counts.get((what, func), 0)
        self._counts[(what, func)] = n + 1
        suffix = f"#{n}" if n else ""
        return f"{what} in {func}{suffix}"


class _DetVisitor(ast.NodeVisitor):
    def __init__(self, relpath: str, in_scope: bool):
        self.relpath = relpath
        self.in_scope = in_scope
        self.findings: list[Finding] = []
        self._func_stack: list[str] = ["<module>"]
        self._sites = _SiteCounter()

    @property
    def _func(self) -> str:
        return self._func_stack[-1]

    def _visit_def(self, node):
        name = (self._func_stack[-1] + "." + node.name
                if self._func_stack[-1] != "<module>" else node.name)
        self._func_stack.append(name)
        if self.in_scope:
            self._scan_key_reuse(node)
        self.generic_visit(node)
        self._func_stack.pop()

    visit_FunctionDef = _visit_def
    visit_AsyncFunctionDef = _visit_def

    def visit_ClassDef(self, node):
        self._func_stack.append(node.name)
        self.generic_visit(node)
        self._func_stack.pop()

    def visit_Call(self, node: ast.Call):
        if self.in_scope:
            name = dotted_name(node.func)
            if name == "time.time":
                self.findings.append(Finding(
                    rule="determinism.wall-clock", path=self.relpath,
                    line=node.lineno,
                    detail=self._sites.detail("time.time", self._func),
                    message="time.time() in a rollout/orchestrator path; "
                            "use time.perf_counter() for anything feeding "
                            "durations/EWMAs/deadlines, or annotate "
                            "`# nanolint: allow[determinism.wall-clock] "
                            "<why this is provenance>`",
                ))
            elif name and (name.startswith("random.")
                           or name.startswith("np.random.")
                           or name.startswith("numpy.random.")):
                # locally *seeded* generators are the sanctioned stdlib/numpy
                # form: random.Random(seed), np.random.default_rng(seed)
                ctor = name.split(".")[-1]
                if ctor in ("Random", "default_rng", "RandomState") \
                        and (node.args or node.keywords):
                    self.generic_visit(node)
                    return
                self.findings.append(Finding(
                    rule="determinism.unseeded-random", path=self.relpath,
                    line=node.lineno,
                    detail=self._sites.detail(name, self._func),
                    message=f"{name}() draws from module-level RNG state; "
                            "route randomness through fold_in-derived "
                            "jax.random keys or a locally seeded "
                            "random.Random(seed)",
                ))
        self.generic_visit(node)

    # -- PRNG key reuse -------------------------------------------------
    def _scan_key_reuse(self, func_node):
        """Branch-aware source-order scan of one function body.

        Dirty state (key var -> first-draw line) threads through
        straight-line code; If branches are analyzed independently and
        merged as the union of the fall-through branches (a branch
        ending in return/raise/break/continue can't flow past the If,
        so exclusive-branch draws never alias). Loop bodies are scanned
        once — cross-iteration reuse with a rebound key is the normal
        fold_in idiom and is not flagged.
        """
        self._reuse_block(func_node.body, {})

    def _stmt_events(self, stmt) -> list[tuple[str, str, int]]:
        """(kind, var, line) events of one statement, nested blocks excluded."""
        events: list[tuple[str, str, int]] = []

        def walk_expr(n):
            for child in ast.walk(n):
                if isinstance(child, ast.Call):
                    name = dotted_name(child.func)
                    if not name:
                        continue
                    parts = name.split(".")
                    is_jr = ((len(parts) == 3 and parts[:2] == ["jax", "random"])
                             or (len(parts) == 2
                                 and parts[0] in ("jrandom", "jrnd", "jr")))
                    if is_jr and child.args and \
                            isinstance(child.args[0], ast.Name):
                        kind = ("derive" if parts[-1] in _DERIVERS else "draw")
                        events.append((kind, child.args[0].id, child.lineno))
                elif isinstance(child, ast.NamedExpr):
                    events.append(("bind", child.target.id, child.lineno))

        if isinstance(stmt, ast.Assign):
            walk_expr(stmt.value)
            for t in stmt.targets:
                for leaf in ast.walk(t):
                    if isinstance(leaf, ast.Name):
                        events.append(("bind", leaf.id, stmt.lineno))
        elif isinstance(stmt, ast.AugAssign):
            walk_expr(stmt.value)
            if isinstance(stmt.target, ast.Name):
                events.append(("bind", stmt.target.id, stmt.lineno))
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                walk_expr(stmt.value)
            if isinstance(stmt.target, ast.Name):
                events.append(("bind", stmt.target.id, stmt.lineno))
        elif isinstance(stmt, (ast.Expr, ast.Return)):
            if getattr(stmt, "value", None) is not None:
                walk_expr(stmt.value)
        return events

    def _apply_events(self, events, dirty):
        for kind, var, lineno in events:
            if kind == "draw":
                if var in dirty:
                    self.findings.append(Finding(
                        rule="determinism.key-reuse", path=self.relpath,
                        line=lineno,
                        detail=self._sites.detail(f"key-reuse:{var}",
                                                  self._func),
                        message=f"jax.random key {var!r} consumed again "
                                f"(first draw at line {dirty[var]}) with no "
                                f"intervening split/fold_in/reassignment — "
                                f"reused keys produce correlated samples",
                    ))
                else:
                    dirty[var] = lineno
            else:  # bind or derive clears the reuse hazard
                dirty.pop(var, None)

    def _reuse_block(self, body, dirty) -> tuple[dict, bool]:
        """Returns (dirty-out, terminated) for one statement list."""
        for stmt in body:
            if isinstance(stmt, (ast.Return, ast.Raise, ast.Break,
                                 ast.Continue)):
                self._apply_events(self._stmt_events(stmt), dirty)
                return dirty, True
            if isinstance(stmt, ast.If):
                self._apply_events(self._stmt_events_expr(stmt.test), dirty)
                d1, t1 = self._reuse_block(stmt.body, dict(dirty))
                d2, t2 = self._reuse_block(stmt.orelse, dict(dirty))
                merged: dict[str, int] = {}
                for d, t in ((d1, t1), (d2, t2)):
                    if not t:
                        merged.update(d)
                dirty = merged
                if t1 and t2:
                    return dirty, True
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                for leaf in ast.walk(stmt.target):
                    if isinstance(leaf, ast.Name):
                        dirty.pop(leaf.id, None)
                d1, _ = self._reuse_block(stmt.body, dict(dirty))
                d2, _ = self._reuse_block(stmt.orelse, dict(dirty))
                dirty = {**dirty, **d1, **d2}
            elif isinstance(stmt, ast.While):
                d1, _ = self._reuse_block(stmt.body, dict(dirty))
                d2, _ = self._reuse_block(stmt.orelse, dict(dirty))
                dirty = {**dirty, **d1, **d2}
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                dirty, term = self._reuse_block(stmt.body, dirty)
                if term:
                    return dirty, True
            elif isinstance(stmt, ast.Try):
                d1, t1 = self._reuse_block(stmt.body, dict(dirty))
                merged = dict(dirty) if not t1 else {}
                if not t1:
                    merged.update(d1)
                for h in stmt.handlers:
                    dh, th = self._reuse_block(h.body, dict(dirty))
                    if not th:
                        merged.update(dh)
                dirty = merged
                dirty, term = self._reuse_block(stmt.finalbody, dirty)
                if term:
                    return dirty, True
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                pass  # nested scopes are scanned separately
            else:
                self._apply_events(self._stmt_events(stmt), dirty)
        return dirty, False

    def _stmt_events_expr(self, expr):
        fake = ast.Expr(value=expr)
        fake.lineno = getattr(expr, "lineno", 1)
        return self._stmt_events(fake)


def run(proj: Project) -> list[Finding]:
    findings: list[Finding] = []
    for src in proj.iter_trees():
        in_scope = src.relpath.startswith(SCOPE_PREFIXES)
        # key-reuse applies everywhere jax.random is used; clock/RNG
        # rules only inside the scoped paths.
        v = _DetVisitor(src.relpath, in_scope)
        if in_scope:
            v.visit(src.tree)
        else:
            # still scan for key reuse outside the scoped paths
            v.in_scope = True
            only_keys = _DetVisitor(src.relpath, True)
            for qual, fn in _iter_funcs(src.tree):
                only_keys._func_stack = [qual]
                only_keys._scan_key_reuse(fn)
            v = only_keys
        findings.extend(v.findings)
    return findings


def _iter_funcs(tree: ast.AST):
    stack: list[tuple[ast.AST, str]] = [(tree, "")]
    while stack:
        node, prefix = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}.{child.name}" if prefix else child.name
                yield qual, child
                stack.append((child, qual))
            elif isinstance(child, ast.ClassDef):
                qual = f"{prefix}.{child.name}" if prefix else child.name
                stack.append((child, qual))
