"""nanolint engine: findings, allowlist annotations, baseline workflow.

A *finding* is one violation of a project invariant at one site. Its
identity (``key``) is ``rule::path::detail`` — deliberately free of
line numbers so unrelated edits don't churn the baseline.

Two suppression mechanisms, both requiring a written reason:

- **allowlist annotation** — a comment on the finding's line (or the
  line above): ``# nanolint: allow[<rule>] <reason>``. ``<rule>`` may
  be the full rule id (``determinism.wall-clock``) or its family
  prefix (``determinism``). An annotation with no reason is itself a
  finding (``meta.allow-missing-reason``).

- **baseline file** — JSON checked in at
  ``nanorlhf_tpu/analysis/baseline.json`` listing known findings with
  reasons. CI fails on findings not in the baseline ("fix or suppress
  with a reason") AND on stale baseline entries that no longer fire
  (so the baseline only ever shrinks or is consciously edited).
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path

ALLOW_RE = re.compile(
    r"#\s*nanolint:\s*allow\[(?P<rule>[a-z0-9_.-]+)\]\s*(?P<reason>.*)$"
)


@dataclass
class Finding:
    rule: str      # e.g. "determinism.wall-clock"
    path: str      # repo-relative posix path
    line: int      # 1-based, for humans; not part of identity
    detail: str    # stable site identity, e.g. "time.time in FleetWorker._run#2"
    message: str   # full human-readable explanation

    @property
    def key(self) -> str:
        return f"{self.rule}::{self.path}::{self.detail}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class SourceFile:
    path: Path           # absolute
    relpath: str         # repo-relative posix
    text: str
    lines: list[str]
    tree: ast.AST | None
    parse_error: str | None = None


@dataclass
class Project:
    """Parsed view of the files under analysis plus repo-root context."""

    root: Path
    files: list[SourceFile] = field(default_factory=list)

    def by_rel(self, relpath: str) -> SourceFile | None:
        for f in self.files:
            if f.relpath == relpath:
                return f
        return None

    def iter_trees(self):
        for f in self.files:
            if f.tree is not None:
                yield f


def load_project(root: Path, targets: list[Path]) -> Project:
    proj = Project(root=root)
    seen: set[Path] = set()
    for target in targets:
        paths = sorted(target.rglob("*.py")) if target.is_dir() else [target]
        for p in paths:
            p = p.resolve()
            if p in seen or p.suffix != ".py":
                continue
            seen.add(p)
            text = p.read_text(encoding="utf-8")
            rel = p.relative_to(root).as_posix() if p.is_relative_to(root) else p.as_posix()
            try:
                tree = ast.parse(text, filename=rel)
                err = None
            except SyntaxError as e:  # report, don't crash the lint run
                tree, err = None, f"{e.msg} (line {e.lineno})"
            proj.files.append(SourceFile(p, rel, text, text.splitlines(), tree))
            proj.files[-1].parse_error = err
    return proj


def _annotation_at(src: SourceFile, line: int):
    """The allow-annotation covering 1-based `line`, if any.

    Checked on the finding's own line (trailing comment) and the line
    directly above (a dedicated comment line).
    """
    for lno in (line, line - 1):
        if 1 <= lno <= len(src.lines):
            m = ALLOW_RE.search(src.lines[lno - 1])
            if m:
                return m.group("rule"), m.group("reason").strip(), lno
    return None


def apply_allowlist(proj: Project, findings: list[Finding]) -> list[Finding]:
    """Drop findings covered by a matching annotation with a reason.

    Annotations with an empty reason never suppress and instead add a
    meta.allow-missing-reason finding at the annotation site.
    """
    out: list[Finding] = []
    for f in findings:
        src = proj.by_rel(f.path)
        ann = _annotation_at(src, f.line) if src else None
        if ann is not None:
            rule, reason, lno = ann
            matches = f.rule == rule or f.rule.split(".")[0] == rule
            if matches and reason:
                continue  # suppressed with a written reason
            if matches and not reason:
                out.append(Finding(
                    rule="meta.allow-missing-reason", path=f.path, line=lno,
                    detail=f"allow[{rule}]@{f.detail}",
                    message=f"allow[{rule}] annotation has no reason; "
                            f"every suppression must say why",
                ))
        out.append(f)
    return out


def load_baseline(path: Path) -> tuple[list[dict], list[str]]:
    """Baseline entries + validation errors (missing/empty reasons)."""
    if not path.exists():
        return [], []
    data = json.loads(path.read_text(encoding="utf-8"))
    entries = data.get("entries", [])
    errors = []
    for e in entries:
        if not str(e.get("reason", "")).strip():
            errors.append(
                f"baseline entry {e.get('rule')}::{e.get('path')}::"
                f"{e.get('detail')} has no written reason"
            )
    return entries, errors


def diff_baseline(findings: list[Finding], entries: list[dict]):
    """(new_findings, stale_entries) vs the baseline."""
    baselined = {f"{e['rule']}::{e['path']}::{e['detail']}" for e in entries}
    current = {f.key for f in findings}
    new = [f for f in findings if f.key not in baselined]
    stale = [e for e in entries
             if f"{e['rule']}::{e['path']}::{e['detail']}" not in current]
    return new, stale


def write_baseline(path: Path, findings: list[Finding], reason: str) -> None:
    entries = [
        {"rule": f.rule, "path": f.path, "detail": f.detail,
         "line": f.line, "reason": reason, "message": f.message}
        for f in sorted(findings, key=lambda f: f.key)
    ]
    path.write_text(
        json.dumps({"entries": entries}, indent=2) + "\n", encoding="utf-8")


# ---------------------------------------------------------------------------
# shared AST helpers used by the rule modules
# ---------------------------------------------------------------------------

def dotted_name(node: ast.AST) -> str | None:
    """'a.b.c' for nested Attribute/Name chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class FuncIndex(ast.NodeVisitor):
    """Maps every function/method node to a qualname like Class.method."""

    def __init__(self):
        self.funcs: dict[str, ast.AST] = {}   # qualname -> def node
        self._stack: list[str] = []

    def _visit_def(self, node):
        self._stack.append(node.name)
        self.funcs[".".join(self._stack)] = node
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_def
    visit_AsyncFunctionDef = _visit_def

    def visit_ClassDef(self, node):
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()


def index_functions(tree: ast.AST) -> dict[str, ast.AST]:
    idx = FuncIndex()
    idx.visit(tree)
    return idx.funcs


def parse_errors(proj: Project) -> list[Finding]:
    return [
        Finding(rule="meta.parse-error", path=f.relpath, line=1,
                detail="parse", message=f"file does not parse: {f.parse_error}")
        for f in proj.files if f.parse_error
    ]
