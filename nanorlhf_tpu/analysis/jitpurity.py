"""Rule family 2 — jit purity / recompile hazards.

Finds host syncs and trace-breaking Python control flow inside
functions reachable from ``jax.jit`` / ``pjit`` call sites:

  jit.host-sync      ``.item()``, ``float(...)`` / ``int(...)`` of a
                     traced parameter, ``np.asarray`` / ``np.array`` of
                     a traced parameter inside jit-reachable code. Each
                     forces a device→host transfer (or a trace-time
                     concretization error) in the hot path.
  jit.traced-branch  Python ``if``/``while`` whose test references a
                     traced (non-static) parameter of the jitted
                     function. Branching on traced values either fails
                     at trace time or — on values that happen to be
                     concrete — silently forks the compile cache.

"Traced" is approximated conservatively: the parameters of the jitted
entry function minus ``static_argnums`` / ``static_argnames``. The
reachability closure follows same-module calls (module-level functions
and ``self.``-methods of the same class); traced-ness does not
propagate through calls — callees are only checked for unconditional
hazards (``.item()``) to keep the false-positive rate near zero.
"""

from __future__ import annotations

import ast

from .engine import Finding, Project, dotted_name, index_functions

_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit",
              "jax.experimental.pjit.pjit"}


def _jit_call_info(call: ast.Call):
    """If `call` is jax.jit(...)/partial(jax.jit, ...), return
    (wrapped_name_or_None, static_argnums, static_argnames)."""
    name = dotted_name(call.func)
    args = list(call.args)
    if name in ("partial", "functools.partial") and args:
        inner_name = dotted_name(args[0])
        if inner_name in _JIT_NAMES:
            return _extract(call, args[1:])
        return None
    if name in _JIT_NAMES:
        return _extract(call, args)
    return None


def _extract(call: ast.Call, fn_args: list[ast.expr]):
    wrapped = None
    if fn_args:
        a = fn_args[0]
        if isinstance(a, ast.Name):
            wrapped = a.id
        elif isinstance(a, ast.Attribute):
            wrapped = dotted_name(a)
    nums: list[int] = []
    names: list[str] = []
    for kw in call.keywords:
        if kw.arg in ("static_argnums", "static_argnames", "donate_argnums"):
            vals = []
            if isinstance(kw.value, (ast.Tuple, ast.List)):
                vals = list(kw.value.elts)
            elif isinstance(kw.value, ast.Constant):
                vals = [kw.value]
            for v in vals:
                if isinstance(v, ast.Constant):
                    if kw.arg == "static_argnums" and isinstance(v.value, int):
                        nums.append(v.value)
                    elif kw.arg == "static_argnames" and isinstance(v.value, str):
                        names.append(v.value)
    return wrapped, nums, names


class _JitSites(ast.NodeVisitor):
    """Collects (function qualname, static nums/names) for every jitted fn."""

    def __init__(self):
        self.sites: dict[str, tuple[list[int], list[str]]] = {}
        self._class_stack: list[str] = []

    def visit_ClassDef(self, node):
        self._class_stack.append(node.name)
        self.generic_visit(node)
        self._class_stack.pop()

    def _qual(self, name: str) -> str:
        return ".".join(self._class_stack + [name])

    def _visit_def(self, node):
        for dec in node.decorator_list:
            info = None
            if isinstance(dec, ast.Call):
                info = _jit_call_info(dec)
            elif dotted_name(dec) in _JIT_NAMES:
                info = (None, [], [])
            if info is not None:
                self._class_stack.append(node.name)
                self._class_stack.pop()
                self.sites[self._qual(node.name)] = (info[1], info[2])
        self._class_stack.append(node.name)
        self.generic_visit(node)
        self._class_stack.pop()

    visit_FunctionDef = _visit_def
    visit_AsyncFunctionDef = _visit_def

    def visit_Call(self, node):
        info = _jit_call_info(node)
        if info is not None and info[0]:
            # jax.jit(fn, ...) call form: fn may be bare or dotted; keep
            # the last component to match module-level defs and methods.
            self.sites.setdefault(info[0].split(".")[-1], (info[1], info[2]))
        self.generic_visit(node)


def _params(fn: ast.AST) -> list[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    return names


def _traced_params(fn: ast.AST, nums: list[int], statics: list[str]) -> set[str]:
    names = _params(fn)
    if names and names[0] in ("self", "cls"):
        offset_names = names[1:]
    else:
        offset_names = names
    static = set(statics)
    for i in nums:
        if 0 <= i < len(offset_names):
            static.add(offset_names[i])
    return {n for n in offset_names if n not in static}


def _refs(expr: ast.AST, traced: set[str]) -> bool:
    return any(isinstance(n, ast.Name) and n.id in traced
               for n in ast.walk(expr))


class _BodyChecker(ast.NodeVisitor):
    def __init__(self, relpath: str, qual: str, traced: set[str],
                 entry: bool):
        self.relpath = relpath
        self.qual = qual
        self.traced = traced
        self.entry = entry  # direct jit target (vs transitively reachable)
        self.findings: list[Finding] = []
        self._ord = 0

    def _finding(self, rule: str, node: ast.AST, what: str, msg: str):
        self._ord += 1
        self.findings.append(Finding(
            rule=rule, path=self.relpath, line=node.lineno,
            detail=f"{what} in {self.qual}#{self._ord}", message=msg))

    def visit_Call(self, node: ast.Call):
        name = dotted_name(node.func)
        if isinstance(node.func, ast.Attribute) and node.func.attr == "item" \
                and not node.args:
            self._finding(
                "jit.host-sync", node, "item",
                ".item() inside jit-reachable code forces a device->host "
                "sync (or a tracer concretization error); compute on-device "
                "or hoist to the caller")
        elif name in ("float", "int", "bool") and node.args and \
                _refs(node.args[0], self.traced):
            self._finding(
                "jit.host-sync", node, f"{name}()",
                f"{name}() applied to traced value inside a jitted "
                f"function concretizes the tracer; keep it as a jax array")
        elif name in ("np.asarray", "np.array", "numpy.asarray",
                      "numpy.array") and node.args and \
                _refs(node.args[0], self.traced):
            self._finding(
                "jit.host-sync", node, name,
                f"{name}() of a traced value forces host materialization "
                f"inside jit; use jnp instead")
        self.generic_visit(node)

    def _check_test(self, node, kind: str):
        if self.traced and _refs(node.test, self.traced):
            self._finding(
                "jit.traced-branch", node, kind,
                f"Python {kind} on a traced parameter inside a jitted "
                f"function; use lax.cond/select or mark the argument "
                f"static_argnames")

    def visit_If(self, node):
        self._check_test(node, "if")
        self.generic_visit(node)

    def visit_While(self, node):
        self._check_test(node, "while")
        self.generic_visit(node)


def _callees(fn: ast.AST) -> set[str]:
    out: set[str] = set()
    for n in ast.walk(fn):
        if isinstance(n, ast.Call):
            name = dotted_name(n.func)
            if not name:
                continue
            if name.startswith("self."):
                out.add(name.split(".", 1)[1])
            elif "." not in name:
                out.add(name)
    return out


def run(proj: Project) -> list[Finding]:
    findings: list[Finding] = []
    for src in proj.iter_trees():
        if not src.relpath.startswith("nanorlhf_tpu/"):
            continue
        sites = _JitSites()
        sites.visit(src.tree)
        if not sites.sites:
            continue
        funcs = index_functions(src.tree)
        # resolve jit sites to def nodes (qualname or last-component match)
        resolved: dict[str, tuple[ast.AST, list[int], list[str]]] = {}
        for qual, (nums, statics) in sites.sites.items():
            node = funcs.get(qual)
            if node is None:
                cands = [q for q in funcs if q.split(".")[-1] == qual]
                node = funcs[cands[0]] if len(cands) == 1 else None
            if node is not None:
                resolved[qual] = (node, nums, statics)

        # reachability closure over same-module simple calls
        reachable: dict[str, bool] = {}   # qualname -> is_entry
        work = list(resolved.keys())
        seen = set(work)
        while work:
            qual = work.pop()
            node = (resolved[qual][0] if qual in resolved
                    else funcs.get(qual))
            if node is None:
                for q2 in funcs:
                    if q2.split(".")[-1] == qual:
                        node = funcs[q2]
                        break
            if node is None:
                continue
            reachable[qual] = qual in resolved
            for callee in _callees(node):
                # match by last component within this module
                for q2 in funcs:
                    if q2.split(".")[-1] == callee and q2 not in seen:
                        seen.add(q2)
                        work.append(q2)

        for qual, is_entry in reachable.items():
            if qual in resolved:
                node, nums, statics = resolved[qual]
                traced = _traced_params(node, nums, statics)
            else:
                node = funcs.get(qual)
                if node is None:
                    cands = [q for q in funcs if q.split(".")[-1] == qual]
                    node = funcs[cands[0]] if cands else None
                traced = set()   # traced-ness doesn't propagate to callees
            if node is None:
                continue
            checker = _BodyChecker(src.relpath, qual, traced, is_entry)
            for stmt in node.body:
                checker.visit(stmt)
            findings.extend(checker.findings)
    return findings
