"""Rule family 4 — static lock-acquisition graph.

Extracts, from the AST alone, the "may acquire B while holding A" graph
across the threaded modules (orchestrator/, telemetry/,
trainer/metrics.py, resilience/faults.py, serving/) and checks it
against the
declared partial order in `lockorder.LOCK_ORDER`:

  lockorder.undeclared  a raw threading.Lock/RLock/Condition() in a
                        scoped module — every lock must be created via
                        the named make_lock/make_rlock/make_condition
                        factories so it has a declared rank (and so the
                        runtime sanitizer can see it)
  lockorder.inversion   an extracted edge A->B where rank(A) >= rank(B)
  lockorder.cycle       a cycle in the extracted graph — a potential
                        deadlock even if each edge looks locally benign

Extraction model: each (class, method) gets a summary of (a) locks
acquired directly (``with self._lock:`` blocks and ``.acquire()``
calls on declared lock attributes) and (b) calls made while holding
locks. Receivers are resolved through RECEIVER_TYPES — a
project-specific attr->class table (this is a project lint, not a type
checker) — plus same-module function names. A fixpoint pass closes
"may acquire" over the call graph, then every (held, acquired) pair
becomes an edge. Conservative in both directions by design: dynamic
dispatch it can't see is missed (the runtime sanitizer covers that),
and calls it can't prove lock-free are assumed lock-free.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .engine import Finding, Project, dotted_name
from .lockorder import LOCK_ORDER, _RANK

SCOPE = (
    "nanorlhf_tpu/orchestrator/",
    "nanorlhf_tpu/telemetry/",
    "nanorlhf_tpu/trainer/metrics.py",
    "nanorlhf_tpu/resilience/faults.py",
    "nanorlhf_tpu/serving/",
    "nanorlhf_tpu/loadgen/",
)

# attr-name -> class-name receiver table for resolving self._attr.m() calls.
RECEIVER_TYPES: dict[str, str] = {
    "_queue": "BoundedStalenessQueue",
    "_lineage": "LineageLedger",
    "lineage": "LineageLedger",
    "_tracer": "SpanTracer",
    "tracer": "SpanTracer",
    "_meter": "OverlapMeter",
    "meter": "OverlapMeter",
    "_faults": "FaultInjector",
    "faults": "FaultInjector",
    "_store": "VersionedWeightStore",
    "_coord": "FleetCoordinator",
    "_health": "HealthMonitor",
    "_logger": "MetricsLogger",
    "_metrics": "MetricsLogger",
    "_client": "RpcClient",
    "_server": "FleetRpcServer",
    "_latency": "LatencyHub",
    "_hub": "LatencyHub",
    "_radix": "RadixCache",
    "_engine": "ServingEngine",
}

# attrs that hold a bound method of another class (callable attributes).
BOUND_METHODS: dict[str, tuple[str, str]] = {
    "_transport_info": ("FleetRpcServer", "transport_info"),
}

_FACTORIES = {"make_lock": False, "make_rlock": True, "make_condition": False}
_RAW = {"threading.Lock", "threading.RLock", "threading.Condition",
        "Lock", "RLock", "Condition"}


@dataclass
class Edge:
    src: str
    dst: str
    path: str
    line: int
    via: str  # "direct" or the callee that transitively acquires dst


@dataclass
class LockGraph:
    locks: dict[tuple[str, str], str] = field(default_factory=dict)
    # (owner, attr) -> lock name; owner is a class name or "<module>:relpath"
    reentrant: set[str] = field(default_factory=set)
    edges: list[Edge] = field(default_factory=list)
    undeclared: list[Finding] = field(default_factory=list)

    def edge_pairs(self) -> set[tuple[str, str]]:
        return {(e.src, e.dst) for e in self.edges}


@dataclass
class _MethodSummary:
    qual: str                 # Class.method or module fn name
    path: str = ""
    direct: list[tuple[str, int, list[str]]] = field(default_factory=list)
    # (lockname, line, held-at-acquire)
    calls: list[tuple[str, int, list[str]]] = field(default_factory=list)
    # (callee qual, line, held-at-call)


class _Collector(ast.NodeVisitor):
    """Builds per-method summaries + lock declarations for one file."""

    def __init__(self, relpath: str, graph: LockGraph,
                 summaries: dict[str, _MethodSummary]):
        self.relpath = relpath
        self.graph = graph
        self.summaries = summaries
        self._class: list[str] = []
        self._method: list[_MethodSummary | None] = [None]
        self._held: list[str] = []

    # -- lock declarations ----------------------------------------------
    def _lock_from_value(self, value: ast.expr) -> tuple[str | None, bool, bool]:
        """(lockname, is_reentrant, is_raw_threading_primitive)."""
        if not isinstance(value, ast.Call):
            return None, False, False
        name = dotted_name(value.func)
        if name in _FACTORIES or (name and name.split(".")[-1] in _FACTORIES):
            fn = (name if name in _FACTORIES else name.split(".")[-1])
            if value.args and isinstance(value.args[0], ast.Constant):
                return value.args[0].value, _FACTORIES[fn], False
            return None, False, False
        if name in _RAW:
            return None, name.endswith("RLock"), True
        return None, False, False

    def visit_Assign(self, node: ast.Assign):
        lockname, reentrant, raw = self._lock_from_value(node.value)
        for t in node.targets:
            owner = attr = None
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and t.value.id == "self" \
                    and self._class:
                owner, attr = self._class[-1], t.attr
            elif isinstance(t, ast.Name) and not self._class \
                    and self._method[-1] is None:
                owner, attr = f"<module>:{self.relpath}", t.id
            if owner is None:
                continue
            if raw:
                self.graph.undeclared.append(Finding(
                    rule="lockorder.undeclared", path=self.relpath,
                    line=node.lineno, detail=f"{owner}.{attr}",
                    message=f"raw threading primitive at {owner}.{attr}; "
                            f"create it via analysis.lockorder.make_lock/"
                            f"make_rlock/make_condition with a name ranked "
                            f"in LOCK_ORDER"))
            elif lockname is not None:
                self.graph.locks[(owner, attr)] = lockname
                if reentrant:
                    self.graph.reentrant.add(lockname)
        self.generic_visit(node)

    # -- structure -------------------------------------------------------
    def visit_ClassDef(self, node):
        self._class.append(node.name)
        self.generic_visit(node)
        self._class.pop()

    def _visit_def(self, node):
        if self._class and self._method[-1] is None:
            qual = f"{self._class[-1]}.{node.name}"
        elif not self._class and self._method[-1] is None:
            qual = node.name
        else:
            qual = None  # nested defs fold into the enclosing summary
        if qual is not None:
            summary = _MethodSummary(qual=qual, path=self.relpath)
            self.summaries[qual] = summary
            self._method.append(summary)
            saved_held, self._held = self._held, []
            self.generic_visit(node)
            self._held = saved_held
            self._method.pop()
        else:
            self.generic_visit(node)

    visit_FunctionDef = _visit_def
    visit_AsyncFunctionDef = _visit_def

    # -- lock use --------------------------------------------------------
    def _resolve_lock_expr(self, expr: ast.expr) -> str | None:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self" \
                and self._class:
            return self.graph.locks.get((self._class[-1], expr.attr))
        if isinstance(expr, ast.Name):
            return self.graph.locks.get((f"<module>:{self.relpath}", expr.id))
        return None

    def visit_With(self, node: ast.With):
        acquired: list[str] = []
        summary = self._method[-1]
        for item in node.items:
            lock = self._resolve_lock_expr(item.context_expr)
            if lock is not None:
                if summary is not None:
                    summary.direct.append(
                        (lock, node.lineno, list(self._held)))
                self._held.append(lock)
                acquired.append(lock)
        for stmt in node.body:
            self.visit(stmt)
        for lock in acquired:
            self._held.remove(lock)
        # also visit the context expressions themselves (call args etc.)
        for item in node.items:
            self.visit(item.context_expr)

    def visit_Call(self, node: ast.Call):
        summary = self._method[-1]
        if summary is not None:
            if isinstance(node.func, ast.Attribute):
                recv, meth = node.func.value, node.func.attr
                if meth in ("acquire", "wait", "wait_for") and \
                        self._resolve_lock_expr(recv):
                    lock = self._resolve_lock_expr(recv)
                    if meth == "acquire":
                        summary.direct.append(
                            (lock, node.lineno, list(self._held)))
                elif isinstance(recv, ast.Name) and recv.id == "self" \
                        and self._class:
                    if meth in BOUND_METHODS and not node.args:
                        pass  # handled below as attr access
                    summary.calls.append((f"{self._class[-1]}.{meth}",
                                          node.lineno, list(self._held)))
                elif isinstance(recv, ast.Attribute) and \
                        isinstance(recv.value, ast.Name) and \
                        recv.value.id == "self":
                    cls = RECEIVER_TYPES.get(recv.attr)
                    if cls is not None:
                        summary.calls.append((f"{cls}.{meth}", node.lineno,
                                              list(self._held)))
            elif isinstance(node.func, ast.Name):
                summary.calls.append((node.func.id, node.lineno,
                                      list(self._held)))
        # bound-method attributes called directly: self._transport_info()
        if summary is not None and isinstance(node.func, ast.Attribute) and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id == "self" and \
                node.func.attr in BOUND_METHODS:
            cls, meth = BOUND_METHODS[node.func.attr]
            summary.calls.append((f"{cls}.{meth}", node.lineno,
                                  list(self._held)))
        self.generic_visit(node)


def extract(proj: Project) -> LockGraph:
    graph = LockGraph()
    summaries: dict[str, _MethodSummary] = {}
    for src in proj.iter_trees():
        if not src.relpath.startswith(SCOPE):
            continue
        _Collector(src.relpath, graph, summaries).visit(src.tree)

    # fixpoint: ACQ[qual] = locks possibly acquired inside qual
    acq: dict[str, set[str]] = {
        q: {lock for lock, _, _ in s.direct} for q, s in summaries.items()}
    changed = True
    while changed:
        changed = False
        for q, s in summaries.items():
            for callee, _, _ in s.calls:
                extra = acq.get(callee)
                if extra and not extra <= acq[q]:
                    acq[q] |= extra
                    changed = True

    # edges
    seen: set[tuple[str, str]] = set()
    for q, s in summaries.items():
        for lock, line, held in s.direct:
            for h in held:
                if (h, lock) not in seen:
                    seen.add((h, lock))
                    graph.edges.append(Edge(h, lock, s.path, line, "direct"))
        for callee, line, held in s.calls:
            if not held:
                continue
            for a in acq.get(callee, ()):
                for h in held:
                    if (h, a) not in seen:
                        seen.add((h, a))
                        graph.edges.append(Edge(h, a, s.path, line, callee))
    return graph


def _find_cycle(pairs: set[tuple[str, str]]) -> list[str] | None:
    adj: dict[str, list[str]] = {}
    for a, b in pairs:
        adj.setdefault(a, []).append(b)
    WHITE, GREY, BLACK = 0, 1, 2
    color: dict[str, int] = {}
    stack_path: list[str] = []

    def dfs(n: str) -> list[str] | None:
        color[n] = GREY
        stack_path.append(n)
        for m in adj.get(n, ()):
            if color.get(m, WHITE) == GREY:
                return stack_path[stack_path.index(m):] + [m]
            if color.get(m, WHITE) == WHITE:
                cyc = dfs(m)
                if cyc:
                    return cyc
        stack_path.pop()
        color[n] = BLACK
        return None

    for n in list(adj):
        if color.get(n, WHITE) == WHITE:
            cyc = dfs(n)
            if cyc:
                return cyc
    return None


def check(graph: LockGraph) -> list[Finding]:
    findings: list[Finding] = list(graph.undeclared)
    for e in graph.edges:
        if e.src == e.dst and e.src in graph.reentrant:
            continue  # reentrant re-acquire is the point of an RLock
        ra, rb = _RANK.get(e.src), _RANK.get(e.dst)
        if ra is None or rb is None:
            continue  # undeclared lock already reported above
        if ra >= rb:
            findings.append(Finding(
                rule="lockorder.inversion", path=e.path, line=e.line,
                detail=f"{e.src}->{e.dst}",
                message=f"acquires {e.dst!r} (rank {rb}) while holding "
                        f"{e.src!r} (rank {ra}) via {e.via}; LOCK_ORDER "
                        f"requires strictly ascending ranks"))
    pairs = {(e.src, e.dst) for e in graph.edges
             if not (e.src == e.dst and e.src in graph.reentrant)}
    cyc = _find_cycle(pairs)
    if cyc:
        findings.append(Finding(
            rule="lockorder.cycle", path="nanorlhf_tpu/analysis/lockorder.py",
            line=1, detail="cycle:" + ">".join(cyc),
            message=f"extracted lock graph has a cycle (potential "
                    f"deadlock): {' -> '.join(cyc)}"))
    return findings


def run(proj: Project) -> list[Finding]:
    return check(extract(proj))


def render(graph: LockGraph) -> str:
    lines = ["declared order (ascending):"]
    for i, name in enumerate(LOCK_ORDER):
        lines.append(f"  {i:2d}  {name}")
    lines.append("extracted edges (held -> acquired):")
    for e in sorted(graph.edges, key=lambda e: (e.src, e.dst)):
        lines.append(f"  {e.src} -> {e.dst}   [{e.path}:{e.line} via {e.via}]")
    if not graph.edges:
        lines.append("  (none)")
    return "\n".join(lines)
