"""Declared lock partial order + runtime lock-order sanitizer.

Five threaded subsystems (fleet coordinator, rpc server/client, sample
queue, health monitor, exporter/metrics) share locks whose nesting was
previously a set of per-module docstring conventions ("the coordinator
lock may be held while taking the queue's lock, never the reverse").
This module makes the convention executable in both directions:

- ``LOCK_ORDER`` is the single declared partial order, as a tuple of
  lock names in ascending rank. A thread may acquire a lock only if its
  rank is strictly greater than every lock it already holds. The static
  analyzer (`nanorlhf_tpu.analysis.lockgraph`) checks every extracted
  acquisition edge against this same table; the two views cannot drift
  because they read the same tuple.

- ``make_lock`` / ``make_rlock`` / ``make_condition`` are drop-in
  factories for ``threading.Lock/RLock/Condition``. With
  ``NANORLHF_LOCK_CHECK=1`` in the environment they return instrumented
  ``OrderedLock`` wrappers that maintain a thread-local stack of held
  locks and raise ``LockOrderViolation`` on any out-of-order
  acquisition; otherwise they return the plain ``threading`` primitive
  with zero overhead.

Lock names not in ``LOCK_ORDER`` are a hard error at construction time
(when checking is enabled) and a `lockorder.undeclared` finding
statically — new locks must be ranked before they ship.
"""

from __future__ import annotations

import os
import threading

# Ascending rank: a thread holding a lock may only acquire locks that
# appear LATER in this tuple. Derived from the audited acquisition
# edges (see docs/STATIC_ANALYSIS.md §lock-order for the edge list):
#   fleet.coordinator -> {orchestrator.queue, rpc.server,
#                         orchestrator.meter, telemetry.{lineage,tracer},
#                         resilience.faults}
#   orchestrator.queue -> telemetry.lineage
#   rpc.client -> resilience.faults
#   {orchestrator.queue, rpc.client, telemetry.health} -> telemetry.hist
#     (queue-wait / RPC-RTT recording under the holder's lock; SLO rule
#      evaluation reads hub quantiles under the health monitor's lock)
#   serving.engine -> telemetry.hist
#     (the shed check reads hub TTFT quantiles under the engine's
#      condition; serving.radix is ranked just below serving.engine so
#      an admission that ever plans under the condition stays ascending)
#   loadgen.autoscaler -> {fleet.coordinator, telemetry.{lineage,tracer}}
#     (evaluate() actuates add/remove_worker and records the decision
#      while holding the controller lock — ranked above everything)
#   loadgen.driver ranks above serving.engine/telemetry.{hist,lineage}
#     for the same reason, though the driver only guards bookkeeping
LOCK_ORDER: tuple[str, ...] = (
    "loadgen.autoscaler",     # Autoscaler._lock            (loadgen/autoscaler.py)
    "loadgen.driver",         # TrafficDriver._lock         (loadgen/driver.py)
    "fleet.coordinator",      # FleetCoordinator._cond      (fleet.py)
    "orchestrator.queue",     # BoundedStalenessQueue._cond (sample_queue.py)
    "orchestrator.weights",   # VersionedWeightStore._cond  (weight_store.py)
    "rpc.server",             # FleetRpcServer._lock        (rpc.py)
    "rpc.client",             # RpcClient._lock             (rpc.py)
    "serving.engine",         # ServingEngine._cond         (serving/engine.py)
    "serving.radix",          # RadixCache._lock            (serving/radix.py)
    "trainer.metrics",        # MetricsLogger._lock         (metrics.py)
    "telemetry.health",       # HealthMonitor._lock         (health.py)
    "telemetry.hist",         # LatencyHub._lock            (hist.py)
    "telemetry.tracer",       # SpanTracer._lock            (tracer.py)
    "telemetry.lineage",      # LineageLedger._lock         (lineage.py)
    "orchestrator.meter",     # OverlapMeter._lock          (orchestrator.py)
    "telemetry.mfu.counter",  # RecompileCounter._lock      (mfu.py)
    "telemetry.mfu.registry", # _COUNTER_LOCK               (mfu.py)
    "rewards.executor",       # PooledPythonExecutor._lock  (python_executor.py)
    "resilience.faults",      # FaultInjector._lock         (faults.py)
)

_RANK: dict[str, int] = {name: i for i, name in enumerate(LOCK_ORDER)}


def lock_rank(name: str) -> int:
    """Rank of a declared lock name; raises KeyError for undeclared names."""
    return _RANK[name]


class LockOrderViolation(RuntimeError):
    """A thread acquired a lock out of the declared LOCK_ORDER."""


class _HeldStack(threading.local):
    def __init__(self):
        self.stack: list[tuple[str, int]] = []  # (name, rank), outermost first


_held = _HeldStack()


def held_locks() -> list[str]:
    """Names of OrderedLocks held by the calling thread, outermost first."""
    return [name for name, _ in _held.stack]


class OrderedLock:
    """A Lock/RLock wrapper that asserts the declared acquisition order.

    Works as the underlying lock of a ``threading.Condition``: it
    implements ``acquire``/``release``/``_is_owned``/``locked`` and
    context-manager protocol. Reentrant acquires (RLock mode) skip the
    order check and the held-stack push — only the first acquisition of
    a lock establishes ordering constraints.
    """

    def __init__(self, name: str, *, reentrant: bool = False):
        if name not in _RANK:
            raise LockOrderViolation(
                f"lock name {name!r} is not declared in LOCK_ORDER; "
                f"rank every lock before shipping it "
                f"(see docs/STATIC_ANALYSIS.md)"
            )
        self.name = name
        self.rank = _RANK[name]
        self._reentrant = reentrant
        self._inner = threading.RLock() if reentrant else threading.Lock()
        self._owner: int | None = None
        self._count = 0

    def _check(self) -> None:
        if _held.stack:
            top_name, top_rank = _held.stack[-1]
            if top_rank >= self.rank:
                chain = " -> ".join(held_locks() + [self.name])
                raise LockOrderViolation(
                    f"lock order violation: acquiring {self.name!r} "
                    f"(rank {self.rank}) while holding {top_name!r} "
                    f"(rank {top_rank}); held chain: {chain}. Declared "
                    f"order requires strictly ascending ranks — see "
                    f"LOCK_ORDER in nanorlhf_tpu/analysis/lockorder.py"
                )

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        me = threading.get_ident()
        if self._reentrant and self._owner == me:
            self._inner.acquire()
            self._count += 1
            return True
        self._check()
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._owner = me
            self._count = 1
            _held.stack.append((self.name, self.rank))
        return got

    def release(self) -> None:
        me = threading.get_ident()
        if self._owner != me:
            raise RuntimeError(f"release of {self.name!r} by non-owner thread")
        self._count -= 1
        if self._count == 0:
            self._owner = None
            # Pop our entry; locks are normally released LIFO but tolerate
            # out-of-order release (it is legal for plain Locks).
            for i in range(len(_held.stack) - 1, -1, -1):
                if _held.stack[i][0] == self.name:
                    del _held.stack[i]
                    break
        self._inner.release()

    # Condition() integration -------------------------------------------
    def _is_owned(self) -> bool:
        return self._owner == threading.get_ident()

    def locked(self) -> bool:
        return self._owner is not None

    def __enter__(self) -> "OrderedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<OrderedLock {self.name!r} rank={self.rank}>"


def _enabled() -> bool:
    return os.environ.get("NANORLHF_LOCK_CHECK", "") not in ("", "0")


def make_lock(name: str):
    """A named mutex: plain ``threading.Lock`` unless NANORLHF_LOCK_CHECK=1."""
    if _enabled():
        return OrderedLock(name)
    return threading.Lock()


def make_rlock(name: str):
    """A named reentrant mutex, order-checked on first acquisition only."""
    if _enabled():
        return OrderedLock(name, reentrant=True)
    return threading.RLock()


def make_condition(name: str):
    """A named ``threading.Condition`` whose underlying lock is ordered."""
    if _enabled():
        return threading.Condition(OrderedLock(name))
    return threading.Condition()
