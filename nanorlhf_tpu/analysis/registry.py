"""Rule family 3 — registry cross-checks.

The fault-injection points, the metric surface, and the health rules
are each declared twice: once in code, once in a registry the humans
read (RESILIENCE.md tables, METRICS.md table, `health.DEFAULT_RULES`).
These rules diff the two views so they cannot drift:

  registry.fault-site-undocumented  a `fire("x.y")` call site whose
                                    point is missing from RESILIENCE.md
  registry.fault-site-unwired       a RESILIENCE.md table row no code
                                    fires
  registry.invariant-undocumented   a `chaos.*` invariant name checked
                                    by an auditor but missing from the
                                    RESILIENCE.md invariant table
  registry.invariant-unchecked      a RESILIENCE.md invariant-table row
                                    no auditor checks
  registry.metric-undocumented      a metric key referenced in code
                                    (emitted OR read) missing from
                                    METRICS.md
  registry.metric-unemitted         a METRICS.md row nothing in code
                                    references
  registry.health-rule-metric       a HealthRule.metric naming a row no
                                    code emits
  registry.prometheus               a code metric key that renders into
                                    an invalid Prometheus exposition
                                    line (shared validate_prometheus_text)

Metric-key extraction is deliberately syntactic: any string literal of
shape ``family/name`` in the trainer/orchestrator/telemetry/sampler
modules counts, plus f-strings whose constant segments look like metric
keys (``f"fleet/{k}"``, ``f"health/rule_{name}"``, ``f"{p}/staleness_
hist_{k}"``) which are matched as patterns. Doc-side wildcards
(``health/rule_<name>``, trailing ``_K``, ``{reason="..."}`` labels,
``{a,b}`` brace lists) are expanded/normalized symmetrically. Bare keys
without a slash (``lr``, ``episode``) are out of scope — indistinguishable
from ordinary strings.

Histogram families (keys under ``hist.HISTOGRAM_KEY_PREFIX``) get shape-
aware treatment: the Prometheus surface derives three sample names per
family (``_bucket{le="..."}``/``_sum``/``_count``), so both cross-check
directions fold such suffixes back to the family before diffing, and the
prometheus rule renders these keys through the real histogram exposition
path instead of the gauge renderer.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from .engine import Finding, Project, dotted_name

METRIC_SCOPES = (
    "nanorlhf_tpu/trainer/",
    "nanorlhf_tpu/orchestrator/",
    "nanorlhf_tpu/telemetry/",
    "nanorlhf_tpu/sampler/",
    "nanorlhf_tpu/serving/",             # gateway/engine emit serving/*
    "nanorlhf_tpu/loadgen/",             # traffic harness emits loadgen/*
    "nanorlhf_tpu/envs/",                # episode driver emits env/*
    "nanorlhf_tpu/utils/profiling.py",   # PhaseTimer emits time/{k}_s
)

# slash-shaped literals that are not metric keys (HTTP content types)
_NOT_METRICS = {"text/plain", "text/html", "application/json",
                "application/octet-stream"}

_KEY_RE = re.compile(r'^[a-z][a-z0-9_]*/[a-z0-9_]+(\{[a-z_]+="[^"]*"\})?$')
_FSTR_SEG_RE = re.compile(r'^[a-z0-9_/{}="]*$')
_FAULT_RE = re.compile(r"^[a-z_]+\.[a-z_]+$")
# chaos run-invariant names (chaos/auditors.py INVARIANTS) — collected
# only from nanorlhf_tpu/chaos/ modules, diffed against the RESILIENCE.md
# `| invariant |` table in both directions
_INVARIANT_RE = re.compile(r"^chaos\.[a-z_]+$")
_INVARIANT_SCOPE = "nanorlhf_tpu/chaos/"

# histogram metric families (telemetry/hist.py): a key under this prefix
# is exported as Prometheus HISTOGRAM exposition — three derived sample
# names per family (`<f>_bucket{le="..."}`, `<f>_sum`, `<f>_count`)
# instead of one gauge line. METRICS.md documents the FAMILY name once;
# the cross-check below normalizes both directions (a doc row carrying an
# explicit suffix/label, or a code literal building one, folds back to
# its family before the diff).
try:
    from nanorlhf_tpu.telemetry.hist import HISTOGRAM_KEY_PREFIX
except Exception:  # pragma: no cover - hist.py is jax-free
    HISTOGRAM_KEY_PREFIX = "latency/"

_HIST_SUFFIX_RE = re.compile(r'(_bucket(\{le="[^"]*"\})?|_sum|_count)$')


def hist_family(name: str) -> str:
    """Fold a histogram sample name back to its family key: strip one
    `_bucket{le="..."}`/`_bucket`/`_sum`/`_count` suffix from keys under
    the histogram prefix; every other name passes through unchanged."""
    if not name.startswith(HISTOGRAM_KEY_PREFIX):
        return name
    return _HIST_SUFFIX_RE.sub("", name)


# ---------------------------------------------------------------------------
# doc parsing
# ---------------------------------------------------------------------------

def parse_fault_tables(text: str) -> set[str]:
    """Backticked first-cell names from RESILIENCE.md `| point |` tables."""
    sites: set[str] = set()
    in_table = False
    for line in text.splitlines():
        s = line.strip()
        if s.startswith("|") and "point" in s.split("|")[1]:
            in_table = True
            continue
        if not s.startswith("|"):
            in_table = False
            continue
        if in_table:
            first = s.split("|")[1]
            for tok in re.findall(r"`([^`]+)`", first):
                if _FAULT_RE.match(tok):
                    sites.add(tok)
    return sites


def parse_invariant_tables(text: str) -> set[str]:
    """Backticked first-cell names from RESILIENCE.md `| invariant |`
    tables — same grammar as the fault-site tables, different header."""
    names: set[str] = set()
    in_table = False
    for line in text.splitlines():
        s = line.strip()
        if s.startswith("|") and "invariant" in s.split("|")[1].lower():
            in_table = True
            continue
        if not s.startswith("|"):
            in_table = False
            continue
        if in_table:
            first = s.split("|")[1]
            for tok in re.findall(r"`([^`]+)`", first):
                if _INVARIANT_RE.match(tok):
                    names.add(tok)
    return names


def parse_metric_doc(text: str) -> tuple[set[str], list[str]]:
    """(exact names, wildcard names-with-'*') from METRICS.md first cells."""
    exact: set[str] = set()
    wild: list[str] = []
    for line in text.splitlines():
        s = line.strip()
        if not s.startswith("|") or s.startswith("|---") or "Metric" in s[:10]:
            continue
        first = s.split("|")[1]
        for tok in re.findall(r"`([^`]+)`", first):
            for name in _expand_doc_name(tok):
                if "*" in name:
                    wild.append(name)
                elif "/" in name:
                    exact.add(name)
                # bare names (lr, episode) are out of scope
    return exact, wild


def _expand_doc_name(tok: str) -> list[str]:
    # brace list: time/{rollout,reward}_s -> time/rollout_s, time/reward_s
    m = re.match(r"^([^{]*)\{([a-z0-9_,]+)\}(.*)$", tok)
    if m and "," in m.group(2):
        return [x for part in m.group(2).split(",")
                for x in _expand_doc_name(m.group(1) + part + m.group(3))]
    name = tok
    name = re.sub(r"<[^>]+>", "*", name)           # health/rule_<name>
    name = name.replace('"..."', '"*"')            # {reason="..."} label
    if re.search(r"_K$", name):                    # staleness_hist_K
        name = name[:-1] + "*"
    return [name]


# ---------------------------------------------------------------------------
# code extraction
# ---------------------------------------------------------------------------

class _CodeInventory(ast.NodeVisitor):
    def __init__(self, relpath: str, collect_metrics: bool,
                 collect_invariants: bool = False):
        self.relpath = relpath
        self.collect_metrics = collect_metrics
        self.collect_invariants = collect_invariants
        self.fires: list[tuple[str, int]] = []          # (point, line)
        self.keys: list[tuple[str, int]] = []           # (literal key, line)
        self.patterns: list[tuple[str, int]] = []       # (regex source, line)
        self.health_metrics: list[tuple[str, int]] = []
        self.invariants: list[tuple[str, int]] = []     # (chaos.* name, line)
        self._not_keys: set[int] = set()   # Constant node ids to skip

    def visit_Call(self, node: ast.Call):
        name = dotted_name(node.func)
        if isinstance(node.func, ast.Attribute) and node.func.attr == "fire" \
                and node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            self.fires.append((node.args[0].value, node.lineno))
        if name and name.split(".")[-1] == "HealthRule":
            for kw in node.keywords:
                if kw.arg == "metric" and isinstance(kw.value, ast.Constant):
                    self.health_metrics.append((kw.value.value, node.lineno))
                    # a HealthRule WATCHING a row is not an emission of it —
                    # counting it as a key would make health-rule-metric
                    # vacuously satisfied by its own argument
                    self._not_keys.add(id(kw.value))
        self.generic_visit(node)

    def visit_Constant(self, node: ast.Constant):
        if self.collect_metrics and isinstance(node.value, str) \
                and node.value not in _NOT_METRICS \
                and id(node) not in self._not_keys \
                and _KEY_RE.match(node.value):
            self.keys.append((node.value, node.lineno))
        if self.collect_invariants and isinstance(node.value, str) \
                and _INVARIANT_RE.match(node.value):
            self.invariants.append((node.value, node.lineno))

    def visit_JoinedStr(self, node: ast.JoinedStr):
        if not self.collect_metrics:
            return
        segs: list[str] = []
        ok = True
        has_slash = False
        for part in node.values:
            if isinstance(part, ast.Constant) and isinstance(part.value, str):
                if not _FSTR_SEG_RE.match(part.value):
                    ok = False
                    break
                has_slash = has_slash or "/" in part.value
                segs.append(re.escape(part.value))
            else:
                segs.append(".*")
        if ok and has_slash and any(s != ".*" for s in segs):
            self.patterns.append(("^" + "".join(segs) + "$", node.lineno))
        # do not recurse: inner constants of an f-string aren't standalone keys


def run(proj: Project) -> list[Finding]:
    findings: list[Finding] = []
    root = proj.root

    res_md = root / "docs" / "RESILIENCE.md"
    met_md = root / "docs" / "METRICS.md"
    res_text = res_md.read_text() if res_md.exists() else ""
    doc_sites = parse_fault_tables(res_text)
    doc_invariants = parse_invariant_tables(res_text)
    doc_exact, doc_wild = (parse_metric_doc(met_md.read_text())
                           if met_md.exists() else (set(), []))

    fires: dict[str, tuple[str, int]] = {}
    keys: dict[str, tuple[str, int]] = {}
    patterns: list[tuple[str, str, int]] = []   # (regex, path, line)
    health: list[tuple[str, str, int]] = []
    invariants: dict[str, tuple[str, int]] = {}
    for src in proj.iter_trees():
        in_scope = src.relpath.startswith(METRIC_SCOPES)
        inv = _CodeInventory(src.relpath, in_scope,
                             src.relpath.startswith(_INVARIANT_SCOPE))
        inv.visit(src.tree)
        for point, line in inv.fires:
            fires.setdefault(point, (src.relpath, line))
        for k, line in inv.keys:
            keys.setdefault(k, (src.relpath, line))
        patterns.extend((rx, src.relpath, line) for rx, line in inv.patterns)
        health.extend((m, src.relpath, line) for m, line in inv.health_metrics)
        for name, line in inv.invariants:
            invariants.setdefault(name, (src.relpath, line))

    # --- fault sites <-> RESILIENCE.md -------------------------------------
    for point, (path, line) in sorted(fires.items()):
        if point not in doc_sites:
            findings.append(Finding(
                rule="registry.fault-site-undocumented", path=path, line=line,
                detail=f"fire:{point}",
                message=f'fire("{point}") has no row in the RESILIENCE.md '
                        f"fault-site tables"))
    for point in sorted(doc_sites - set(fires)):
        findings.append(Finding(
            rule="registry.fault-site-unwired", path="docs/RESILIENCE.md",
            line=1, detail=f"doc:{point}",
            message=f"RESILIENCE.md documents fault point `{point}` but no "
                    f'code calls fire("{point}")'))

    # --- chaos invariants <-> RESILIENCE.md --------------------------------
    for name, (path, line) in sorted(invariants.items()):
        if name not in doc_invariants:
            findings.append(Finding(
                rule="registry.invariant-undocumented", path=path, line=line,
                detail=f"invariant:{name}",
                message=f"chaos invariant '{name}' has no row in the "
                        f"RESILIENCE.md invariant table"))
    for name in sorted(doc_invariants - set(invariants)):
        findings.append(Finding(
            rule="registry.invariant-unchecked", path="docs/RESILIENCE.md",
            line=1, detail=f"doc:{name}",
            message=f"RESILIENCE.md documents invariant `{name}` but no "
                    f"chaos auditor checks it"))

    # --- metric keys <-> METRICS.md ----------------------------------------
    wild_prefixes = [w.split("*")[0] for w in doc_wild]

    def documented(key: str) -> bool:
        if key in doc_exact or any(
                key.startswith(p) and p for p in wild_prefixes):
            return True
        # histogram shape: a code literal naming an exposition sample
        # (`latency/x_s_count`) is covered by its documented family row
        fam = hist_family(key)
        return fam != key and documented(fam)

    for key, (path, line) in sorted(keys.items()):
        if not documented(key):
            findings.append(Finding(
                rule="registry.metric-undocumented", path=path, line=line,
                detail=f"key:{key}",
                message=f"metric key '{key}' referenced in code but absent "
                        f"from docs/METRICS.md (add a row, or fix the key "
                        f"if it is a typo for an existing row)"))

    pattern_res = [(re.compile(rx), path, line) for rx, path, line in patterns]
    for rx, path, line in pattern_res:
        probe_ok = any(rx.match(d) for d in doc_exact) or any(
            rx.match(w.replace("*", "x")) for w in doc_wild)
        if not probe_ok:
            findings.append(Finding(
                rule="registry.metric-undocumented", path=path, line=line,
                detail=f"pattern:{rx.pattern}",
                message=f"metric f-string pattern {rx.pattern} matches no "
                        f"documented METRICS.md row"))

    def emitted(doc_name: str) -> bool:
        probe = doc_name.replace("*", "x")
        if doc_name.rstrip("*") and "*" in doc_name:
            # wildcard doc rows: emitted if a code pattern or literal shares
            # the prefix
            pre = doc_name.split("*")[0]
            if any(k.startswith(pre) for k in keys):
                return True
            if any(rx.match(probe) for rx, _, _ in pattern_res):
                return True
        elif doc_name in keys or any(rx.match(doc_name)
                                     for rx, _, _ in pattern_res):
            return True
        # histogram shape, doc→code direction: a doc row spelling an
        # exposition suffix (`latency/x_s_bucket{le="..."}` — the `...`
        # label arrives here as `*`) is emitted when code references the
        # family it derives from
        fam = hist_family(probe)
        return fam != probe and emitted(fam)

    for doc_name in sorted(doc_exact) + sorted(doc_wild):
        if not emitted(doc_name):
            findings.append(Finding(
                rule="registry.metric-unemitted", path="docs/METRICS.md",
                line=1, detail=f"doc:{doc_name}",
                message=f"METRICS.md documents '{doc_name}' but no scoped "
                        f"module references it"))

    # --- HealthRule.metric must be an emitted row --------------------------
    for metric, path, line in health:
        if not (metric in keys or documented(metric)
                or any(rx.match(metric) for rx, _, _ in pattern_res)):
            findings.append(Finding(
                rule="registry.health-rule-metric", path=path, line=line,
                detail=f"health:{metric}",
                message=f"HealthRule watches metric '{metric}' but nothing "
                        f"emits that row — the rule can never fire"))

    # --- Prometheus name validity via the shared validator -----------------
    findings.extend(_prometheus_check(keys))
    return findings


def _prometheus_check(keys: dict[str, tuple[str, int]]) -> list[Finding]:
    try:
        from nanorlhf_tpu.telemetry.exporter import (
            render_prometheus, render_prometheus_histograms,
            validate_prometheus_text)
        from nanorlhf_tpu.telemetry.hist import StreamingHistogram
    except Exception as e:  # pragma: no cover - exporter is jax-free
        return [Finding(
            rule="registry.prometheus", path="nanorlhf_tpu/telemetry/exporter.py",
            line=1, detail="import",
            message=f"could not import the shared Prometheus validator: {e}")]
    probe_hist = StreamingHistogram()
    probe_hist.record(0.05)
    out: list[Finding] = []
    for key, (path, line) in sorted(keys.items()):
        if key.startswith(HISTOGRAM_KEY_PREFIX):
            # histogram families render through the histogram exposition
            # path — the derived _bucket/_sum/_count sample names and the
            # le label are what must survive the validator
            text = render_prometheus_histograms(
                {hist_family(key): probe_hist.state()})
        else:
            text = render_prometheus({key: 1.0})
        errors = validate_prometheus_text(text)
        for err in errors:
            out.append(Finding(
                rule="registry.prometheus", path=path, line=line,
                detail=f"prom:{key}",
                message=f"metric key '{key}' renders to invalid Prometheus "
                        f"exposition text: {err}"))
    return out
