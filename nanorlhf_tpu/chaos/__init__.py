"""Chaos soak harness (docs/RESILIENCE.md §chaos).

Composes multi-site fault schedules from the wired injection-point
registry under the splitmix64 lineage-PRNG discipline (composer.py),
drives a full end-to-end run under them (runner.py), audits the run's
GLOBAL invariants from the lineage ledger + component snapshots
(auditors.py), and — on any auditor failure — delta-debugs the spec
down to a minimal failing clause set with a one-line repro command
(shrink.py). `python -m nanorlhf_tpu.chaos` is the CLI entry point.
"""

from nanorlhf_tpu.chaos.auditors import (  # noqa: F401
    AuditResult, AUDITORS, INVARIANTS, run_audits,
)
from nanorlhf_tpu.chaos.composer import (  # noqa: F401
    ChaosPlan, KEY_PATH, SERVING_SITES, TRAINER_SITES, compose,
    plan_digest, uncovered_sites,
)
from nanorlhf_tpu.chaos.runner import (  # noqa: F401
    SOAKS, SoakReport, soak_serving, soak_trainer,
)
from nanorlhf_tpu.chaos.shrink import repro_command, shrink  # noqa: F401
