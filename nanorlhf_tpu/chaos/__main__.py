"""Chaos soak CLI (docs/RESILIENCE.md §chaos).

    python -m nanorlhf_tpu.chaos --path trainer --seed 3
    python -m nanorlhf_tpu.chaos --path serving --seed 3 --shrink
    python -m nanorlhf_tpu.chaos --path serving --seed 3 \
        --spec "gw.disconnect:every=2,count=2" --run-dir /tmp/repro

Composes a seeded schedule (or takes an explicit --spec, as printed by
a failed soak's repro line), drives the soak, prints every auditor
verdict, and exits nonzero when any invariant fails. With --shrink a
failing spec is ddmin-minimized first — each probe re-runs the soak in
its own subdirectory — and the minimal repro command is printed last.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys

# match tests/conftest.py BEFORE anything imports jax: the trainer soak
# wants the same 8-way forced host topology the tier-1 suite runs under
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

from nanorlhf_tpu.chaos.composer import PATHS, ChaosPlan, compose
from nanorlhf_tpu.chaos.runner import SOAKS
from nanorlhf_tpu.chaos.shrink import repro_command, shrink


def _print_report(report) -> None:
    print(f"chaos: path={report.plan.path} seed={report.plan.seed} "
          f"digest={report.plan.digest}")
    print(f"chaos: spec: {report.plan.spec or '(empty)'}")
    for point, s in sorted(report.fault_stats.items()):
        print(f"chaos: site {point}: {s['fires']}/{s['calls']} "
              f"fires/calls")
    for a in report.audits:
        mark = "ok " if a.ok else "FAIL"
        extra = f" — {a.detail}" if a.detail else ""
        print(f"chaos: [{mark}] {a.name} (checked={a.checked}){extra}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m nanorlhf_tpu.chaos",
        description="composed-fault soak + run-invariant audit")
    ap.add_argument("--path", choices=sorted(PATHS), required=True)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--sites", type=int, default=3,
                    help="clauses to compose (ignored with --spec)")
    ap.add_argument("--spec", default=None,
                    help="explicit NANORLHF_FAULT spec instead of "
                         "composing one (repro replay)")
    ap.add_argument("--run-dir", default="/tmp/chaos_soak")
    ap.add_argument("--shrink", action="store_true",
                    help="on audit failure, ddmin the spec to a minimal "
                         "failing clause set (re-runs the soak per probe)")
    ap.add_argument("--max-tests", type=int, default=16,
                    help="shrink probe budget")
    args = ap.parse_args(argv)

    if args.spec is not None:
        plan = ChaosPlan(seed=args.seed, path=args.path,
                         clauses=tuple(args.spec.split()))
    else:
        plan = compose(args.seed, args.path, n_sites=args.sites)
    soak = SOAKS[args.path]
    report = soak(args.run_dir, plan)
    _print_report(report)
    if report.ok:
        print("chaos: PASS")
        return 0

    print("chaos: FAIL — "
          + ", ".join(a.name for a in report.failed))
    if args.shrink and len(plan.clauses) > 1:
        probe = [0]

        def failing(clauses) -> bool:
            probe[0] += 1
            sub = dataclasses.replace(plan, clauses=tuple(clauses))
            rep = soak(f"{args.run_dir}/shrink_{probe[0]:02d}", sub)
            return not rep.ok

        minimal = shrink(plan.clauses, failing, max_tests=args.max_tests)
        print(f"chaos: minimal failing spec ({len(minimal)} of "
              f"{len(plan.clauses)} clauses): {' '.join(minimal)}")
        print("chaos: repro: "
              + repro_command(minimal, path=plan.path, seed=plan.seed))
    else:
        print("chaos: repro: "
              + repro_command(plan.clauses, path=plan.path,
                              seed=plan.seed))
    return 1


if __name__ == "__main__":
    sys.exit(main())
