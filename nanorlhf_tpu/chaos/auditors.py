"""Run-invariant auditors (docs/RESILIENCE.md §chaos).

Each auditor checks one GLOBAL invariant of a finished run — properties
that must hold no matter which faults fired or how recovery interleaved
— from the lineage ledger plus end-of-run component snapshots the soak
runner collects into `ctx`. Auditors are tolerant by construction: an
invariant whose evidence is absent from this run (no fleet, no serving
engine, lineage disabled) passes with a "not exercised" detail rather
than failing on missing data, so one auditor set serves both paths.

Invariant names are the `chaos.*` strings in INVARIANTS; nanolint
cross-checks them against the docs/RESILIENCE.md invariant table in
both directions (analysis/registry.py), like the metric and fault-site
registries.

Jax-free — audits replay offline from a ledger directory alone
(`tools/inspect_run.py --chaos` re-prints journaled verdicts).
"""

from __future__ import annotations

import dataclasses

INVARIANTS = (
    "chaos.sample_conservation",
    "chaos.lease_epoch_monotonic",
    "chaos.counter_conservation",
    "chaos.kv_page_leak",
    "chaos.worker_leak",
    "chaos.degraded_honestly",
)


@dataclasses.dataclass
class AuditResult:
    """One auditor's verdict. `checked` counts the pieces of evidence
    examined — a pass with checked=0 means "not exercised", which the
    smoke test treats differently from a real pass."""

    name: str
    ok: bool
    detail: str = ""
    checked: int = 0


def _by_type(events, etype: str) -> list:
    return [e for e in events if e.get("type") == etype]


def audit_sample_conservation(events, ctx) -> AuditResult:
    """No consumed rollout index is lost or duplicated: every leased
    index reaches a generation or an attributed drop, consumed indices
    are duplicate-free (unless a sentinel rollback legitimately replays
    them), and the consumed range has no unattributed gaps."""
    name = "chaos.sample_conservation"
    outcomes = _by_type(events, "outcome")
    drops = _by_type(events, "drop")
    leases = _by_type(events, "lease")
    gens = _by_type(events, "generation")
    if not outcomes and not leases:
        return AuditResult(name, True, "no outcome/lease events", 0)
    consumed = [e["rollout_index"] for e in outcomes
                if e.get("rollout_index") is not None]
    dropped = {e["rollout_index"] for e in drops
               if e.get("rollout_index") is not None}
    problems = []
    rollbacks = int(ctx.get("rollbacks", 0) or 0)
    dup = sorted({i for i in consumed if consumed.count(i) > 1})
    if dup and not rollbacks:
        problems.append(f"duplicated consumed indices {dup[:8]}")
    leased = {e["rollout_index"] for e in leases
              if e.get("rollout_index") is not None}
    generated = {e["rollout_index"] for e in gens
                 if e.get("rollout_index") is not None}
    lost = sorted(leased - generated - dropped - set(consumed))
    if lost:
        problems.append(f"leased but never generated/dropped {lost[:8]}")
    if consumed:
        lo, hi = min(consumed), max(consumed)
        gaps = sorted(set(range(lo, hi + 1)) - set(consumed) - dropped)
        if gaps:
            problems.append(f"unattributed gaps {gaps[:8]}")
    checked = len(outcomes) + len(leases)
    return AuditResult(name, not problems, "; ".join(problems), checked)


def audit_lease_epoch_monotonic(events, ctx) -> AuditResult:
    """Lease epochs never move backward in ledger order (grants are
    serialized under the fleet lock), equal epochs belong to one lease,
    and every fenced late-duplicate drop carries an epoch BELOW some
    later grant — the fencing story the ledger tells must be coherent."""
    name = "chaos.lease_epoch_monotonic"
    leases = [e for e in _by_type(events, "lease")
              if e.get("epoch") is not None]
    if not leases:
        return AuditResult(name, True, "no lease events", 0)
    problems = []
    prev_epoch, prev_lease = None, None
    max_epoch = 0
    for e in leases:
        epoch, lease_id = int(e["epoch"]), e.get("lease_id")
        if prev_epoch is not None:
            if epoch < prev_epoch:
                problems.append(
                    f"epoch regressed {prev_epoch}->{epoch} "
                    f"(lease {lease_id})")
            elif epoch == prev_epoch and lease_id != prev_lease:
                problems.append(
                    f"epoch {epoch} reused across leases "
                    f"{prev_lease}/{lease_id}")
        prev_epoch, prev_lease = epoch, lease_id
        max_epoch = max(max_epoch, epoch)
    for e in _by_type(events, "drop"):
        if e.get("reason") != "fleet_late_duplicate":
            continue
        if not e.get("fenced"):
            problems.append(
                f"late-duplicate drop without fencing evidence "
                f"(index {e.get('rollout_index')})")
        elif e.get("epoch") is not None and int(e["epoch"]) >= max_epoch:
            problems.append(
                f"fenced drop epoch {e['epoch']} not below any later "
                f"grant (max {max_epoch})")
    return AuditResult(name, not problems, "; ".join(problems[:6]),
                       len(leases))


def audit_counter_conservation(events, ctx) -> AuditResult:
    """Every request/sample is accounted exactly once at quiescence:
    serving requests == admitted + shed with admitted == completed +
    cancelled (and nothing pending/active), loadgen offered ==
    completed + shed + errors, and the client/server tallies of the
    same run agree."""
    name = "chaos.counter_conservation"
    problems = []
    checked = 0
    eng = ctx.get("engine")
    if eng:
        checked += 1
        c = eng.get("counters", {})
        if c.get("requests", 0) != c.get("admitted", 0) + c.get("shed", 0):
            problems.append(
                f"requests {c.get('requests')} != admitted "
                f"{c.get('admitted')} + shed {c.get('shed')}")
        if c.get("admitted", 0) != (c.get("completed", 0)
                                    + c.get("cancelled", 0)):
            problems.append(
                f"admitted {c.get('admitted')} != completed "
                f"{c.get('completed')} + cancelled {c.get('cancelled')}")
        if eng.get("pending", 0) or eng.get("active", 0):
            problems.append(
                f"not quiescent: pending={eng.get('pending')} "
                f"active={eng.get('active')}")
    gen = ctx.get("loadgen")
    if gen:
        checked += 1
        offered = gen.get("loadgen/offered", 0)
        parts = (gen.get("loadgen/completed", 0) + gen.get("loadgen/shed", 0)
                 + gen.get("loadgen/errors", 0))
        if offered != parts:
            problems.append(f"offered {offered} != completed+shed+errors "
                            f"{parts}")
        if eng:
            c = eng.get("counters", {})
            if offered != c.get("requests", 0):
                problems.append(
                    f"client offered {offered} != server requests "
                    f"{c.get('requests')}")
    traffic = _by_type(events, "traffic")
    if traffic and gen:
        checked += 1
        if len(traffic) != gen.get("loadgen/offered", 0):
            problems.append(
                f"{len(traffic)} traffic events != offered "
                f"{gen.get('loadgen/offered')}")
    if not checked:
        return AuditResult(name, True, "no counter surfaces in ctx", 0)
    return AuditResult(name, not problems, "; ".join(problems), checked)


def audit_kv_page_leak(events, ctx) -> AuditResult:
    """At quiescence every KV page is either free or owned by the radix
    tree alone: free + cached == num_pages, no page multi-referenced,
    and no row's block table still holds page ids — a vanished client
    or crashed worker must not strand a page."""
    name = "chaos.kv_page_leak"
    snap = ctx.get("radix")
    if not snap:
        return AuditResult(name, True, "no radix snapshot", 0)
    problems = []
    total = snap.get("num_pages", 0)
    free, cached = snap.get("free_pages", 0), snap.get("cached_pages", 0)
    if free + cached != total:
        problems.append(
            f"{total - free - cached} pages stranded "
            f"(free {free} + cached {cached} != {total})")
    if snap.get("shared_pages", 0):
        problems.append(f"{snap['shared_pages']} pages still shared")
    live_rows = ctx.get("live_table_rows")
    if live_rows:
        problems.append(f"rows still holding pages: {live_rows}")
    return AuditResult(name, not problems, "; ".join(problems), 1)


def audit_worker_leak(events, ctx) -> AuditResult:
    """Component teardown leaves no threads or child processes behind:
    the runner diffs thread names / child-process counts across the run
    (after close), filtered to this project's thread-name prefixes."""
    name = "chaos.worker_leak"
    leaked = ctx.get("leaked_threads")
    procs = int(ctx.get("leaked_procs", 0) or 0)
    if leaked is None and not procs:
        return AuditResult(name, True, "no leak snapshot", 0)
    problems = []
    if leaked:
        problems.append(f"threads still alive: {sorted(leaked)[:8]}")
    if procs > 0:
        problems.append(f"{procs} child processes still alive")
    return AuditResult(name, not problems, "; ".join(problems), 1)


def audit_degraded_honestly(events, ctx) -> AuditResult:
    """Any non-bit-exact recovery must be journaled, never silent: for
    every (signal, journaled) pair the runner collects — watchdog
    degraded mode, checkpoint fallbacks, cancelled streams, sentinel
    rollbacks — a truthy signal requires truthy journal evidence (a
    metric row, counter, or ledger event recording the transition)."""
    name = "chaos.degraded_honestly"
    pairs = ctx.get("honesty") or []
    if not pairs:
        return AuditResult(name, True, "no degradation signals", 0)
    problems = []
    for label, signal, journaled in pairs:
        if signal and not journaled:
            problems.append(f"{label}: degraded ({signal!r}) but not "
                            f"journaled")
    return AuditResult(name, not problems, "; ".join(problems), len(pairs))


AUDITORS = {
    "chaos.sample_conservation": audit_sample_conservation,
    "chaos.lease_epoch_monotonic": audit_lease_epoch_monotonic,
    "chaos.counter_conservation": audit_counter_conservation,
    "chaos.kv_page_leak": audit_kv_page_leak,
    "chaos.worker_leak": audit_worker_leak,
    "chaos.degraded_honestly": audit_degraded_honestly,
}


def run_audits(events, ctx) -> list:
    """Run every auditor over one finished run; never raises — an
    auditor crash is itself a failed verdict (the harness must report,
    not mask)."""
    results = []
    for invariant in INVARIANTS:
        fn = AUDITORS[invariant]
        try:
            results.append(fn(events, ctx))
        except Exception as e:
            results.append(AuditResult(
                invariant, False, f"auditor crashed: "
                f"{type(e).__name__}: {e}", 0))
    return results
