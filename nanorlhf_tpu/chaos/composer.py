"""Seeded chaos schedule composer (docs/RESILIENCE.md §chaos).

Samples multi-site, multi-clause `NANORLHF_FAULT` specs from the wired
fault-site registry (`resilience.faults.INJECTION_POINTS`) under the
same splitmix64 key-derivation discipline the loadgen workload sampler
uses: every clause and every field draw consumes its own `fold_in`-
derived key, so the same seed composes the same chaos byte-for-byte in
any process — the ledger's `chaos_run` header (seed + spec + KEY_PATH)
is a complete replay recipe.

Per-path site pools. A composed soak must PASS its auditors, so each
pool admits only bounded, recoverable perturbations on that path;
every other registered site is listed in EXCLUDED with the reason —
`uncovered_sites()` returns the registry diff and a test pins it empty,
so adding a fault site forces a composer decision.

Jax-free: the composer (like the auditors and shrinker) must run
anywhere the ledger can be read.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json

from nanorlhf_tpu.loadgen.workload import fold_in, randint, uniform
from nanorlhf_tpu.resilience.faults import INJECTION_POINTS, parse_fault_spec

# root stream id: clause keys are fold_in(fold_in(seed, _ROOT), slot),
# field draws fold one more level (slot is the clause's position in the
# composed spec, not the site name — two clauses on one site diverge)
_ROOT = 0xC4A0

KEY_PATH = "fold_in(fold_in(seed, 0xC4A0), clause_slot)"

# field ids folded under a clause key — one per sampled parameter, so
# adding a parameter never shifts its siblings' draws
_F_SITE, _F_AT, _F_EVERY, _F_COUNT, _F_WORKER, _F_DELAY = range(6)

# trainer+fleet path: orchestrated run with rollout workers. Each entry
# is a bounded perturbation the resilience stack recovers from without
# exhausting a budget (crash→lease reassignment, slow→straggler
# redispatch, save/produce/reward→retry paths).
#
# ORDER MATTERS: site selection is a keyed shuffle over this tuple, so
# reordering or inserting entries reshuffles every composed plan. The
# current order keeps the pinned seed-3 plan (test_seed3_plans_are_
# pinned) drawing {worker.crash, worker.slow, ckpt.save} — the
# deterministic crash-recovery soak — while swap.stale stays reachable
# under other seeds. swap.stale only fires on a run with
# rollout_inflight_swaps enabled (a mid-rollout install stalls briefly,
# then installs anyway), so a soak that composes it without swaps
# enabled records zero fires for that clause.
TRAINER_SITES = (
    "ckpt.save",
    "rollout.produce",
    "reward.exec",
    "worker.slow",
    "swap.stale",
    "worker.fetch_weights",
    "worker.crash",
)

# loadgen→engine serving path: the only wired serving-side site today
# (clients vanishing mid-stream); multi-clause specs still compose —
# several disconnect waves with distinct phases/counts.
SERVING_SITES = ("gw.disconnect",)

# registry entries deliberately absent from both pools, with the reason
# — uncovered_sites() keeps this enumeration honest against the
# registry, so a new INJECTION_POINTS entry fails tests until it is
# pooled or excluded here
EXCLUDED = {
    "ckpt.restore": "restore-path only — a fresh soak never resumes",
    "ckpt.corrupt": "restore-path only — exercised by its own tier-1 test",
    "update.step": "nan rollback needs a committed checkpoint and replays "
                   "the step — doubles soak runtime; own tier-1 tests",
    "worker.hang": "stalls until the lease deadline — too slow for a "
                   "smoke soak",
    "net.drop": "rpc transport mode only",
    "net.delay": "rpc transport mode only",
    "net.partition": "rpc transport mode only",
    "net.duplicate": "rpc transport mode only",
    "net.tear": "rpc transport mode only",
    "env.hang": "multi-turn env episodes only",
    "env.crash": "multi-turn env episodes only",
}

PATHS = {"trainer": TRAINER_SITES, "serving": SERVING_SITES}


def uncovered_sites() -> set:
    """Registry entries neither pooled nor excluded (should be empty)."""
    covered = set(TRAINER_SITES) | set(SERVING_SITES) | set(EXCLUDED)
    return set(INJECTION_POINTS) - covered


@dataclasses.dataclass(frozen=True)
class ChaosPlan:
    """One composed chaos schedule — value-typed so two compositions of
    the same (seed, path) compare == field for field (replay contract,
    like loadgen's GenRequest)."""

    seed: int
    path: str                 # "trainer" | "serving"
    clauses: tuple            # NANORLHF_FAULT entries, one per slot
    key_path: str = KEY_PATH

    @property
    def spec(self) -> str:
        return " ".join(self.clauses)

    @property
    def sites(self) -> tuple:
        return tuple(c.partition(":")[0] for c in self.clauses)

    @property
    def digest(self) -> str:
        return plan_digest(self)


def plan_digest(plan: ChaosPlan) -> str:
    """sha256[:16] pin over the plan's replay-relevant fields."""
    blob = json.dumps([plan.seed, plan.path, list(plan.clauses)],
                      separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def _clause(site: str, key: int, n_workers: int) -> str:
    """Sample one spec entry for `site` from the clause key. Parameter
    ranges keep every fire bounded (small delays, capped counts) so a
    composed soak stays a smoke test, not a stress test.

    Worker targeting is partitioned, not sampled: worker.crash is FATAL
    to its thread (the fleet reassigns the lease, it never respawns the
    worker), so crash always takes the LAST worker while the surviving
    sites stay untargeted or pinned to worker 0 — composed clauses must
    not mask each other by all landing on the corpse."""
    if site == "ckpt.save":
        return f"ckpt.save:at={randint(fold_in(key, _F_AT), 1, 3)}"
    if site == "rollout.produce":
        return f"rollout.produce:at={randint(fold_in(key, _F_AT), 1, 4)}"
    if site == "reward.exec":
        return f"reward.exec:at={randint(fold_in(key, _F_AT), 1, 3)}"
    if site == "worker.slow":
        every = randint(fold_in(key, _F_EVERY), 2, 5)
        delay = round(0.02 + 0.06 * uniform(fold_in(key, _F_DELAY)), 3)
        count = randint(fold_in(key, _F_COUNT), 1, 4)
        return f"worker.slow:every={every},delay={delay},count={count}"
    if site == "worker.crash":
        return f"worker.crash:at=1,worker={max(0, n_workers - 1)}"
    if site == "worker.fetch_weights":
        return (f"worker.fetch_weights:at="
                f"{randint(fold_in(key, _F_AT), 1, 3)},worker=0")
    if site == "swap.stale":
        # small stall before a mid-rollout install (the default delay
        # action installs the tree anyway — recoverable by construction)
        every = randint(fold_in(key, _F_EVERY), 1, 4)
        delay = round(0.02 + 0.06 * uniform(fold_in(key, _F_DELAY)), 3)
        count = randint(fold_in(key, _F_COUNT), 1, 3)
        return f"swap.stale:every={every},delay={delay},count={count}"
    if site == "gw.disconnect":
        every = randint(fold_in(key, _F_EVERY), 2, 6)
        count = randint(fold_in(key, _F_COUNT), 1, 4)
        return f"gw.disconnect:every={every},count={count}"
    raise ValueError(f"no clause template for site {site!r}")


def compose(seed: int, path: str, *, n_sites: int = 3,
            n_workers: int = 2) -> ChaosPlan:
    """Compose an `n_sites`-clause schedule for `path` from `seed`.

    Site selection is a keyed Fisher-Yates over the path's pool (every
    site reachable, no duplicates until the pool is exhausted — pools
    smaller than n_sites wrap with fresh clause keys, so a 3-clause
    serving plan is three distinct disconnect waves). The result
    round-trips through `parse_fault_spec`, so it is a valid
    NANORLHF_FAULT value by construction."""
    if path not in PATHS:
        raise ValueError(f"path {path!r}: expected one of {sorted(PATHS)}")
    if n_sites < 1:
        raise ValueError(f"n_sites={n_sites} must be >= 1")
    pool = list(PATHS[path])
    root = fold_in(seed, _ROOT)
    # keyed shuffle: deterministic site order for this seed
    for i in range(len(pool) - 1, 0, -1):
        j = randint(fold_in(fold_in(root, _F_SITE), i), 0, i + 1)
        pool[i], pool[j] = pool[j], pool[i]
    clauses = []
    for slot in range(n_sites):
        site = pool[slot % len(pool)]
        clauses.append(_clause(site, fold_in(root, slot), n_workers))
    plan = ChaosPlan(seed=int(seed), path=path, clauses=tuple(clauses))
    parse_fault_spec(plan.spec)  # valid by construction — or fail loudly
    return plan
