"""Chaos soak runner (docs/RESILIENCE.md §chaos).

Drives one full end-to-end run — trainer+fleet or loadgen→engine —
under a composed fault schedule, journaling the chaos provenance into
the run's lineage ledger as it goes (`chaos_run` header, one `fault`
event per fire, one `chaos_audit` verdict per invariant), then audits
the finished run's global invariants from the ledger plus end-of-run
component snapshots.

Verdicts are journaled AFTER component teardown (worker-leak evidence
only exists post-close), by reopening the ledger — the ledger resumes
by appending to its newest rotation file, so the audit tail lands in
the same replayable stream `tools/inspect_run.py --chaos` reads.

This module imports jax lazily inside the soak functions: the package
surface (composer/auditors/shrinker) stays importable anywhere the
ledger can be read.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time

from nanorlhf_tpu.chaos.auditors import run_audits
from nanorlhf_tpu.chaos.composer import ChaosPlan, compose
from nanorlhf_tpu.chaos.shrink import repro_command

# thread-name prefixes this project owns — the worker-leak auditor only
# flags names matching these, so unrelated test-runner threads (pytest
# timers, jax pools) never produce false leaks
_THREAD_PREFIXES = (
    "fleet-", "rollout-", "serving-", "loadgen-", "status-exporter",
)


@dataclasses.dataclass
class SoakReport:
    """One soak's outcome: the plan that ran, every auditor verdict,
    and the injector's per-site fire counts."""

    plan: ChaosPlan
    audits: list
    fault_stats: dict
    summary: dict

    @property
    def ok(self) -> bool:
        return all(a.ok for a in self.audits)

    @property
    def failed(self) -> list:
        return [a for a in self.audits if not a.ok]

    def fired_sites(self) -> set:
        return {p for p, s in self.fault_stats.items()
                if s.get("fires", 0) > 0}

    def repro(self, run_dir: str = "/tmp/chaos_repro") -> str:
        return repro_command(self.plan.clauses, path=self.plan.path,
                             seed=self.plan.seed, run_dir=run_dir)


def _thread_names() -> set:
    return {t.name for t in threading.enumerate() if t.is_alive()}


def _leaked_threads(before: set) -> list:
    """Project-owned thread names alive now that were not alive before
    the soak. Teardown joins are synchronous, so no grace loop."""
    return sorted(n for n in _thread_names() - before
                  if n.startswith(_THREAD_PREFIXES))


def _child_procs() -> int:
    import multiprocessing

    return len(multiprocessing.active_children())


def _fault_hook(ledger, t0: float):
    """on_fire observer: journal every fire as an index-less `fault`
    event with its offset from soak start (perf_counter — durations
    never come from the wall clock)."""

    def on_fire(point, worker, action):
        ledger.fault(point=point, worker=worker, action=action,
                     t_offset=round(time.perf_counter() - t0, 6))

    return on_fire


def _journal_audits(run_dir: str, plan: ChaosPlan, audits) -> None:
    """Append the verdicts to the run's ledger post-teardown (reopening
    resumes the newest rotation file — no clobber)."""
    from nanorlhf_tpu.telemetry.lineage import LineageLedger

    ledger = LineageLedger(run_dir, enabled=True)
    for a in audits:
        ledger.chaos_audit(name=a.name, ok=a.ok, detail=a.detail or None,
                           checked=a.checked, spec_digest=plan.digest)
    ledger.close()


def _metric_rows(output_dir: str) -> list:
    import json

    rows = []
    path = os.path.join(output_dir, "metrics.jsonl")
    if os.path.exists(path):
        with open(path) as fh:
            for line in fh:
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if "episode" in row:
                    rows.append(row)
    return rows


def soak_serving(run_dir: str, plan: ChaosPlan = None, *, seed: int = 0,
                 n_sites: int = 3, n_requests: int = 24,
                 time_scale: float = 0.02) -> SoakReport:
    """Serving-path soak: an open-loop workload through a tiny in-
    process ServingEngine with gw.disconnect armed on the client side
    (the driver IS the client for the in-process target). Quiescence
    plus teardown, then the auditor sweep over ledger + snapshots."""
    import jax
    import jax.numpy as jnp

    from nanorlhf_tpu.core import ModelConfig, init_params
    from nanorlhf_tpu.loadgen.driver import TrafficDriver
    from nanorlhf_tpu.loadgen.workload import WorkloadSpec
    from nanorlhf_tpu.resilience.faults import FaultInjector
    from nanorlhf_tpu.serving.engine import ServingEngine
    from nanorlhf_tpu.telemetry.hist import LatencyHub
    from nanorlhf_tpu.telemetry.lineage import LineageLedger, read_ledger

    if plan is None:
        plan = compose(seed, "serving", n_sites=n_sites)
    os.makedirs(run_dir, exist_ok=True)
    before = _thread_names()
    t0 = time.perf_counter()

    ledger = LineageLedger(run_dir, enabled=True)
    ledger.chaos_run(seed=plan.seed, spec=plan.spec,
                     spec_digest=plan.digest, path=plan.path,
                     key_path=plan.key_path)
    injector = FaultInjector.from_spec(plan.spec or None)
    injector.on_fire = _fault_hook(ledger, t0)

    config = ModelConfig.qwen2_tiny(vocab_size=128)
    params = init_params(config, jax.random.PRNGKey(7), jnp.float32)
    hub = LatencyHub(enabled=True)
    engine = ServingEngine(params, config, eos_token_id=3, pad_token_id=0,
                           page_size=4, prompt_len=12, max_new_tokens=8,
                           rows=2, latency=hub, seed=plan.seed)
    driver = TrafficDriver(engine=engine, latency=hub, lineage=ledger,
                           faults=injector, time_scale=time_scale)
    spec = WorkloadSpec(seed=plan.seed, n_requests=n_requests,
                        rate_rps=40.0, prompt_len_max=12, token_hi=120,
                        max_tokens_max=8)
    try:
        run_summary = driver.run(spec)
    finally:
        engine.close()
        ledger.close()

    snap = engine.snapshot()
    counters = snap["counters"]
    metrics = engine.metrics()
    ctx = {
        "engine": snap,
        "radix": snap["prefix_cache"],
        "loadgen": driver.metrics(),
        "live_table_rows": [
            r for r in range(engine.rows)
            if any(int(p) < engine.num_pages
                   for p in engine.session.table_np[r])
        ],
        "leaked_threads": _leaked_threads(before),
        "leaked_procs": 0,
        "honesty": [
            # internal degradation counters must reach the exported
            # metric surface — a silent cancel is a dishonest recovery
            ("serving_cancelled", counters.get("cancelled", 0),
             metrics.get("serving/cancelled", 0)),
            ("disconnect_shed", snap["shed_reasons"].get("disconnect", 0),
             metrics.get('serving/shed_total{reason="disconnect"}', 0)),
            # every injector fire must have a journaled fault event
            ("faults_journaled",
             sum(s.get("fires", 0) for s in injector.stats().values()),
             sum(1 for e in read_ledger(run_dir)
                 if e.get("type") == "fault")),
        ],
    }
    events = list(read_ledger(run_dir))
    audits = run_audits(events, ctx)
    _journal_audits(run_dir, plan, audits)
    return SoakReport(plan=plan, audits=audits,
                      fault_stats=injector.stats(),
                      summary={"offered": run_summary.offered,
                               "completed": run_summary.completed,
                               "errors": run_summary.errors,
                               "shed": run_summary.shed})


def soak_trainer(run_dir: str, plan: ChaosPlan = None, *, seed: int = 0,
                 n_sites: int = 3, total_episodes: int = 48) -> SoakReport:
    """Trainer-path soak: a tiny GRPO run with the rollout fleet
    (2 workers, strict staleness) under the composed schedule. The
    trainer wires the injector itself from `fault_spec`; the soak only
    attaches the on_fire observer and audits afterwards."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nanorlhf_tpu.core import ModelConfig, init_params
    from nanorlhf_tpu.data import ToyTokenizer, load_prompt_dataset
    from nanorlhf_tpu.parallel import MeshConfig
    from nanorlhf_tpu.telemetry.lineage import read_ledger
    from nanorlhf_tpu.trainer import AlgoName, RLConfig, RLTrainer

    if plan is None:
        plan = compose(seed, "trainer", n_sites=n_sites)
    os.makedirs(run_dir, exist_ok=True)
    before = _thread_names()
    t0 = time.perf_counter()

    tok = ToyTokenizer(vocab_size=256)
    mcfg = ModelConfig.qwen2_tiny(vocab_size=256)
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    cfg = RLConfig(
        algo=AlgoName.GRPO,
        output_dir=run_dir,
        response_length=8,
        sample_n=2,
        total_episodes=total_episodes,
        per_device_train_batch_size=1,
        gradient_accumulation_steps=2,
        num_mini_batches=2,
        num_ppo_epochs=1,
        learning_rate=1e-4,
        kl_coef=0.05,
        use_lora=True,
        lora_r=4,
        lora_alpha=8,
        gradient_checkpointing=False,
        # the tier-1 topology when 8 forced host devices are available
        # (tests/conftest.py, the CLI); single-device otherwise
        mesh=(MeshConfig(2, 2, 2) if jax.device_count() >= 8
              else MeshConfig(1, 1, 1)),
        save_steps=1,
        report_to="jsonl",
        lineage=True,
        rollout_orchestrator=True,
        rollout_workers=2,
        max_staleness=0,
        producer_backoff_base=0.01,
        producer_backoff_max=0.05,
        fault_spec=plan.spec or None,
    )
    dataset = load_prompt_dataset("synthetic:64", tok, max_prompt_len=12)

    def rule_reward(pmt_and_responses, eos_token):
        out = [(1.0 if eos_token in s else 0.0) - 0.01 * len(s.split())
               for s in pmt_and_responses]
        return np.asarray(out, dtype=np.float32)

    trainer = RLTrainer(cfg, mcfg, tok, params, dataset, rule_reward)
    trainer.lineage.chaos_run(seed=plan.seed, spec=plan.spec,
                              spec_digest=plan.digest, path=plan.path,
                              key_path=plan.key_path)
    trainer.faults.on_fire = _fault_hook(trainer.lineage, t0)
    try:
        trainer.train()
    finally:
        rollbacks = trainer.sentinel.rollbacks
        restarts = trainer.watchdog.restarts_total
        degraded = trainer.watchdog.degraded
        fallbacks = trainer.ckpt.fallback_count
        fault_stats = trainer.faults.stats()
        trainer.close()

    rows = _metric_rows(run_dir)
    last = rows[-1] if rows else {}
    fault_events = sum(1 for e in read_ledger(run_dir)
                       if e.get("type") == "fault")
    ctx = {
        "rollbacks": rollbacks,
        "leaked_threads": _leaked_threads(before),
        "leaked_procs": max(0, _child_procs()),
        "honesty": [
            # in-memory recovery state must be journaled in the final
            # metrics row — degrading silently fails the audit
            ("watchdog_degraded", degraded,
             last.get("resilience/degraded_mode", 0.0)),
            ("producer_restarts", restarts,
             last.get("resilience/producer_restarts", 0.0)),
            ("sentinel_rollbacks", rollbacks,
             last.get("resilience/rollbacks", 0.0)),
            ("ckpt_fallbacks", fallbacks,
             last.get("resilience/ckpt_fallbacks", 0.0)),
            ("faults_journaled",
             sum(s.get("fires", 0) for s in fault_stats.values()),
             fault_events),
        ],
    }
    events = list(read_ledger(run_dir))
    audits = run_audits(events, ctx)
    _journal_audits(run_dir, plan, audits)
    return SoakReport(plan=plan, audits=audits, fault_stats=fault_stats,
                      summary={"updates": int(last.get("step", 0) or 0),
                               "rows": len(rows)})


SOAKS = {"trainer": soak_trainer, "serving": soak_serving}
