"""Delta-debugging shrinker (docs/RESILIENCE.md §chaos).

When a composed chaos spec breaks an auditor, most of its clauses are
usually bystanders. `shrink` runs classic ddmin (Zeller & Hildebrandt,
"Simplifying and Isolating Failure-Inducing Input") over the CLAUSE
list: partition the failing set into n chunks, try each chunk and each
complement, recurse on whichever still fails with finer granularity,
and stop at a 1-minimal set — removing any single remaining clause
makes the failure disappear. `repro_command` turns the survivor into
the one-liner a bug report needs.

The predicate re-runs the soak, so shrinking is expensive by nature;
`max_tests` bounds the spend and the best-so-far subset is returned
even when the budget runs out. Deterministic composition (composer.py)
is what makes the re-runs meaningful at all: the subset replays the
exact surviving schedules, not a fresh sampling.
"""

from __future__ import annotations

from typing import Callable, Sequence


def shrink(clauses: Sequence[str], failing: Callable[[list], bool],
           *, max_tests: int = 64) -> list:
    """Minimize `clauses` to a 1-minimal subset for which `failing`
    (clause list -> True when the failure REPRODUCES) still holds.
    `failing(list(clauses))` must be True on entry — shrinking a
    passing spec is a caller bug worth failing loudly on."""
    current = list(clauses)
    if not failing(current):
        raise ValueError("failing() is False on the full clause list — "
                         "nothing to shrink")
    tests = 1
    n = 2
    while len(current) >= 2 and tests < max_tests:
        chunk = max(1, -(-len(current) // n))  # ceil division
        subsets = [current[i:i + chunk]
                   for i in range(0, len(current), chunk)]
        reduced = False
        # a failing chunk becomes the new set at coarsest granularity;
        # a failing complement keeps granularity (one chunk proved
        # irrelevant) — the standard ddmin schedule
        for s in subsets:
            if len(s) == len(current):
                continue
            tests += 1
            if failing(list(s)):
                current, n, reduced = list(s), 2, True
                break
            if tests >= max_tests:
                return current
        if not reduced and len(subsets) > 1:
            for s in subsets:
                comp = [c for c in current if c not in s]
                if not comp or len(comp) == len(current):
                    continue
                tests += 1
                if failing(list(comp)):
                    current, n, reduced = comp, max(n - 1, 2), True
                    break
                if tests >= max_tests:
                    return current
        if not reduced:
            if n >= len(current):
                break  # 1-minimal: no chunk or complement still fails
            n = min(len(current), n * 2)
    return current


def repro_command(clauses: Sequence[str], *, path: str, seed: int,
                  run_dir: str = "/tmp/chaos_repro") -> str:
    """The one-line repro a failed soak prints: re-runs the minimal
    clause set through the same soak path via the chaos CLI."""
    spec = " ".join(clauses)
    return (f'python -m nanorlhf_tpu.chaos --path {path} --seed {seed} '
            f'--spec "{spec}" --run-dir {run_dir}')
