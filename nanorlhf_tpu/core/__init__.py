from nanorlhf_tpu.core.config import ModelConfig
from nanorlhf_tpu.core.model import (
    init_params,
    model_forward,
    padded_forward_logits,
    prefill,
    decode_step,
    init_kv_cache,
    init_score_head,
    score_forward,
)

__all__ = [
    "ModelConfig",
    "init_params",
    "model_forward",
    "padded_forward_logits",
    "prefill",
    "decode_step",
    "init_kv_cache",
    "init_score_head",
    "score_forward",
]
