from nanorlhf_tpu.core.config import ModelConfig
from nanorlhf_tpu.core.params import (
    export_hf_checkpoint,
    load_hf_checkpoint,
    params_from_hf_state_dict,
)
from nanorlhf_tpu.core.model import (
    init_params,
    model_forward,
    padded_forward_logits,
    padded_forward_hidden,
    unembedding,
    unembedding_weight,
    prefill,
    decode_step,
    init_kv_cache,
    init_score_head,
    score_forward,
)

__all__ = [
    "export_hf_checkpoint",
    "load_hf_checkpoint",
    "params_from_hf_state_dict",
    "ModelConfig",
    "init_params",
    "model_forward",
    "padded_forward_logits",
    "padded_forward_hidden",
    "unembedding",
    "unembedding_weight",
    "prefill",
    "decode_step",
    "init_kv_cache",
    "init_score_head",
    "score_forward",
]
