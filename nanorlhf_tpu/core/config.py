"""Model architecture config (Qwen2-family decoder).

The reference loads policies with `AutoModelForCausalLM` (Qwen2.5 models,
`/root/reference/GRPO/grpo.py:218-224`); this dataclass captures the Qwen2
architecture hyperparameters our JAX decoder needs. Presets mirror the HF
configs of the model sizes the reference trains (0.5B/1.5B/7B).
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    vocab_size: int = 151936
    hidden_size: int = 1536
    intermediate_size: int = 8960
    num_hidden_layers: int = 28
    num_attention_heads: int = 12
    num_key_value_heads: int = 2
    head_dim: Optional[int] = None  # defaults to hidden_size // num_attention_heads
    rope_theta: float = 1_000_000.0
    rms_norm_eps: float = 1e-6
    tie_word_embeddings: bool = True
    max_position_embeddings: int = 32768
    # Qwen2-family attention projections carry biases; Llama-family do not.
    # The decoder treats biases as optional, so this only steers random init
    # (HF loading is data-driven off the state dict).
    attention_bias: bool = True
    # HF family slug ("qwen2" | "llama"); carried through load → export so a
    # round-trip re-emits the source architecture instead of inferring it
    # from attention_bias (a Llama with attention_bias=True is valid, ADVICE
    # r3). None (random-init configs) falls back to the bias heuristic.
    model_type: Optional[str] = None
    # "int8": the sampler's KV cache stores int8 values + per-token-per-head
    # bf16 scales (absmax over head_dim). At long responses the cache read is
    # the dominant decode HBM stream (≈7.5 GB/step at 8k tokens, batch 32);
    # int8 + the 8x-sublane-replicated bf16 scale stream reads 144 B per
    # token/kv-head/side vs 256 B exact at hd=128 — a 1.78x reduction. The
    # Pallas decode kernel consumes int8 natively (scales fold into the
    # score row and the probability row, ops/decode_attention.py) and is
    # gated by the same attention_impl resolution as the exact kernel; the
    # XLA path dequantizes per step (correct, no bandwidth win).
    # Training/scoring paths never use a cache, so they are unaffected.
    kv_cache_quant: str = "none"  # none | int8
    # "xla": einsum attention fused by XLA everywhere.
    # "pallas": blockwise flash kernel (ops/attention.py) on self-attention
    #   paths + prefix-bounded decode kernel (ops/decode_attention.py).
    # "auto" (default): picks per call site from real-TPU v5e sweeps — flash
    #   at padded T >= _FLASH_AUTO_MIN_T (pallas-512 beats XLA 1.4x at T=512
    #   and 21x at T=8192; ties below), decode kernel at cache
    #   T_max >= _DECODE_AUTO_MIN_T (XLA's single fused matmul wins on short
    #   caches; prefix-skip bandwidth wins on long ones). Off-TPU backends
    #   always resolve to XLA (interpret-mode Pallas is a test vehicle, not
    #   an execution path).
    attention_impl: str = "auto"
    # Rematerialization policy for the training forward when gradient
    # checkpointing is on ("full" = jax.checkpoint default, save nothing and
    # recompute the whole layer in the backward; "dots" = save MXU matmul
    # outputs without batch dims — the projections' results survive to the
    # backward, trading HBM for roughly a third less recompute FLOPs). A
    # tuning knob, not a numerics one: gradients are identical either way.
    remat_policy: str = "full"  # full | dots
    # SPMD hints for the Pallas kernels. GSPMD has no partitioning rule for
    # a custom call: without these, a batch-sharded training/rollout step
    # ALL-GATHERS the kernel operands (q/k/v, the whole KV cache) onto every
    # device and replicates the output — silently, observed in compiled HLO.
    # When `spmd_mesh` is set, the kernel call sites wrap themselves in
    # shard_map over the batch dim (axes in `spmd_batch_axes` that are >1 in
    # the mesh) and, where head counts divide, the head dim over
    # `spmd_head_axis` — each device then runs the kernel on its own shard,
    # which is the whole point of the kernels. The trainer sets these from
    # its mesh automatically; None = single-device behavior (no wrap).
    # (Mesh is hashable, so this stays a valid static jit argument.)
    spmd_mesh: object = None            # jax.sharding.Mesh | None
    spmd_batch_axes: tuple = ()         # e.g. ("data", "fsdp")
    spmd_head_axis: Optional[str] = None  # e.g. "tensor"

    @property
    def actual_head_dim(self) -> int:
        return self.head_dim or self.hidden_size // self.num_attention_heads

    @property
    def num_kv_groups(self) -> int:
        return self.num_attention_heads // self.num_key_value_heads

    @classmethod
    def qwen2_tiny(cls, vocab_size: int = 512) -> "ModelConfig":
        """Test-size model: runs fast on the CPU test mesh."""
        return cls(
            vocab_size=vocab_size,
            hidden_size=64,
            intermediate_size=128,
            num_hidden_layers=2,
            num_attention_heads=4,
            num_key_value_heads=2,
            rope_theta=10_000.0,
            max_position_embeddings=1024,
        )

    @classmethod
    def qwen2_0_5b(cls) -> "ModelConfig":
        return cls(
            hidden_size=896,
            intermediate_size=4864,
            num_hidden_layers=24,
            num_attention_heads=14,
            num_key_value_heads=2,
            tie_word_embeddings=True,
        )

    @classmethod
    def qwen2_1_5b(cls) -> "ModelConfig":
        return cls()  # defaults are Qwen2.5-1.5B

    @classmethod
    def qwen2_7b(cls) -> "ModelConfig":
        return cls(
            hidden_size=3584,
            intermediate_size=18944,
            num_hidden_layers=28,
            num_attention_heads=28,
            num_key_value_heads=4,
            tie_word_embeddings=False,
        )

    @classmethod
    def llama3_2_1b(cls) -> "ModelConfig":
        """Llama-3.2-1B geometry — the Llama side of the same decoder
        (no attention biases, untied-by-default in larger family members)."""
        return cls(
            vocab_size=128256,
            hidden_size=2048,
            intermediate_size=8192,
            num_hidden_layers=16,
            num_attention_heads=32,
            num_key_value_heads=8,
            head_dim=64,
            rope_theta=500_000.0,
            rms_norm_eps=1e-5,
            tie_word_embeddings=True,
            max_position_embeddings=131072,
            attention_bias=False,
            model_type="llama",
        )

    @classmethod
    def llama3_8b(cls) -> "ModelConfig":
        return cls(
            vocab_size=128256,
            hidden_size=4096,
            intermediate_size=14336,
            num_hidden_layers=32,
            num_attention_heads=32,
            num_key_value_heads=8,
            rope_theta=500_000.0,
            rms_norm_eps=1e-5,
            tie_word_embeddings=False,
            max_position_embeddings=131072,
            attention_bias=False,
            model_type="llama",
        )

    @classmethod
    def from_hf_config(cls, hf_config) -> "ModelConfig":
        """Build from a `transformers` Qwen2Config / LlamaConfig (or dict)."""
        get = (lambda k, d=None: getattr(hf_config, k, d)) if not isinstance(
            hf_config, dict
        ) else (lambda k, d=None: hf_config.get(k, d))
        # Qwen2 has no attention_bias knob (its q/k/v always carry biases);
        # Llama-family configs expose it (default False)
        model_type = str(get("model_type", "qwen2")).lower()
        attn_bias = get("attention_bias", "qwen" in model_type)
        return cls(
            vocab_size=get("vocab_size"),
            hidden_size=get("hidden_size"),
            intermediate_size=get("intermediate_size"),
            num_hidden_layers=get("num_hidden_layers"),
            num_attention_heads=get("num_attention_heads"),
            num_key_value_heads=get("num_key_value_heads"),
            head_dim=get("head_dim", None),
            rope_theta=get("rope_theta", 1_000_000.0),
            rms_norm_eps=get("rms_norm_eps", 1e-6),
            tie_word_embeddings=get("tie_word_embeddings", False),
            max_position_embeddings=get("max_position_embeddings", 32768),
            attention_bias=bool(attn_bias),
            model_type=model_type,
        )
