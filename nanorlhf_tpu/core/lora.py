"""LoRA adapters applied in-graph — no merge/disk round-trip, ever.

The reference wraps the policy with PEFT (r=64, alpha=16, all seven
projections; embed/lm_head fully trained via `modules_to_save`)
(`/root/reference/GRPO/grpo.py:86-99,226-243`) and must merge the adapter
into a full checkpoint on disk every update so vLLM can load it
(`/root/reference/GRPO/grpo_trainer.py:131-141`). Here the adapter is just an
extra `params["lora"]` subtree that the decoder applies inline during
training, scoring *and* sampling — weight freshness is automatic because
there is only one tree.

Layout mirrors the stacked layer tree: `lora["layers"][proj] = {"a": [L, in, r],
"b": [L, r, out]}`; contribution `(x @ A) @ B * (alpha / r)`, B zero-init so
step 0 is exactly the base model.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from nanorlhf_tpu.core.config import ModelConfig

ALL_TARGETS = ("q_proj", "k_proj", "v_proj", "o_proj", "gate_proj", "up_proj", "down_proj")


@dataclasses.dataclass(frozen=True)
class LoraConfig:
    r: int = 64
    alpha: int = 16
    # default matches `lora_target_modules` (`GRPO/grpo.py:94`)
    targets: tuple[str, ...] = ALL_TARGETS
    # fully-trained extras, as `modules_to_save` (`GRPO/grpo.py:95`)
    train_embed: bool = True
    train_lm_head: bool = True

    @property
    def scale(self) -> float:
        return self.alpha / self.r


def _proj_dims(config: ModelConfig, name: str) -> tuple[int, int]:
    hd = config.actual_head_dim
    D, F = config.hidden_size, config.intermediate_size
    H, KV = config.num_attention_heads, config.num_key_value_heads
    return {
        "q_proj": (D, H * hd),
        "k_proj": (D, KV * hd),
        "v_proj": (D, KV * hd),
        "o_proj": (H * hd, D),
        "gate_proj": (D, F),
        "up_proj": (D, F),
        "down_proj": (F, D),
    }[name]


def init_lora_params(
    config: ModelConfig, lora: LoraConfig, key: jax.Array, dtype=jnp.bfloat16
) -> dict:
    """A ~ N(0, 1/r) (kaiming-ish), B = 0 → adapter starts as identity."""
    L = config.num_hidden_layers
    keys = jax.random.split(key, len(lora.targets))
    layers = {}
    for k, name in zip(keys, lora.targets):
        d_in, d_out = _proj_dims(config, name)
        layers[name] = {
            "a": (jax.random.normal(k, (L, d_in, lora.r), jnp.float32) / jnp.sqrt(lora.r)).astype(dtype),
            "b": jnp.zeros((L, lora.r, d_out), dtype),
        }
    return {"layers": layers}


def merge_lora(params: dict, lora_scale: float) -> dict:
    """Fold the adapter into the base kernels (checkpoint export only —
    runtime never needs this)."""
    if "lora" not in params:
        return params
    merged = dict(params)
    lora_layers = params["lora"]["layers"]
    new_layers = dict(params["layers"])
    for name, ab in lora_layers.items():
        delta = jnp.einsum("lir,lro->lio", ab["a"].astype(jnp.float32), ab["b"].astype(jnp.float32))
        entry = dict(new_layers[name])
        entry["kernel"] = (
            entry["kernel"].astype(jnp.float32) + lora_scale * delta
        ).astype(entry["kernel"].dtype)
        new_layers[name] = entry
    merged["layers"] = new_layers
    del merged["lora"]
    return merged


def trainable_mask(params: dict, lora: LoraConfig | None) -> dict:
    """Boolean pytree: which leaves the optimizer updates.

    Full fine-tuning (lora=None): everything True. LoRA: adapter leaves plus
    (optionally) embed_tokens / lm_head — PEFT `modules_to_save` parity.
    """
    if lora is None:
        return jax.tree.map(lambda _: True, params)

    def mask(path, leaf):
        keys = tuple(p.key if hasattr(p, "key") else str(p) for p in path)
        if keys and keys[0] == "lora":
            return True
        if keys and keys[0] == "embed_tokens":
            return lora.train_embed
        if keys and keys[0] == "lm_head":
            return lora.train_lm_head
        return False

    return jax.tree_util.tree_map_with_path(mask, params)
