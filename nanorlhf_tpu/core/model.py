"""Qwen2-family decoder as pure functions over a stacked-layer pytree.

TPU-first design choices (vs the reference's HF `AutoModelForCausalLM`,
`/root/reference/GRPO/grpo.py:218-224`):

- **Stacked layers + `lax.scan`**: all per-layer weights are stacked along a
  leading [L, ...] axis and the decoder runs one traced layer body L times.
  One compilation regardless of depth; XLA pipelines the scan body.
- **Pure pytrees**: params are a nested dict of jnp arrays — the same tree is
  sharded once over the mesh and shared by rollout, logprob scoring and the
  train step (this kills the reference's CPU↔GPU offload + disk→vLLM handoff,
  `GRPO/grpo_trainer.py:122-166,475-476`).
- **bf16 params, f32 softmax/norms**: matmuls hit the MXU in bf16; softmax,
  RMSNorm statistics and rotary tables run in f32 for stability.
- **GQA without materializing repeated KV**: queries are reshaped to
  [B, KV, G, T, hd] and contracted against unrepeated KV heads.

The padding-robust entrypoint `padded_forward_logits` reproduces the contract
of the reference's shared `forward()` helper (`GRPO/grpo_trainer.py:90-120`):
mask = (ids != pad), positions = cumsum(mask)-mask, padded ids zeroed.

Weight layout: all projection matrices are stored [in, out] (x @ W), i.e. the
transpose of torch `nn.Linear.weight`; the HF loader transposes on load.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from nanorlhf_tpu.core.config import ModelConfig

NEG_INF = -2.0**30  # large-but-finite mask value; -inf breaks softmax rows that are fully masked


# ---------------------------------------------------------------------------
# Parameter construction
# ---------------------------------------------------------------------------

def init_params(config: ModelConfig, key: jax.Array, dtype=jnp.bfloat16) -> dict:
    """Random-init a full parameter tree (tests / from-scratch training)."""
    hd = config.actual_head_dim
    D, F, V = config.hidden_size, config.intermediate_size, config.vocab_size
    H, KV, L = config.num_attention_heads, config.num_key_value_heads, config.num_hidden_layers

    keys = iter(jax.random.split(key, 16))

    def dense(k, shape, scale=None):
        scale = scale if scale is not None else (1.0 / jnp.sqrt(shape[0]))
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    def stacked(k, shape, scale=None):
        return dense(k, (L,) + shape, scale)

    def qkv(k, shape):
        entry = {"kernel": stacked(k, shape)}
        if config.attention_bias:  # Qwen2 yes, Llama no (core/config.py)
            entry["bias"] = jnp.zeros((L, shape[-1]), dtype)
        return entry

    params = {
        "embed_tokens": dense(next(keys), (V, D), scale=0.02),
        "layers": {
            "input_layernorm": jnp.ones((L, D), dtype),
            "q_proj": qkv(next(keys), (D, H * hd)),
            "k_proj": qkv(next(keys), (D, KV * hd)),
            "v_proj": qkv(next(keys), (D, KV * hd)),
            "o_proj": {"kernel": stacked(next(keys), (H * hd, D))},
            "post_attention_layernorm": jnp.ones((L, D), dtype),
            "gate_proj": {"kernel": stacked(next(keys), (D, F))},
            "up_proj": {"kernel": stacked(next(keys), (D, F))},
            "down_proj": {"kernel": stacked(next(keys), (F, D))},
        },
        "norm": jnp.ones((D,), dtype),
    }
    if not config.tie_word_embeddings:
        params["lm_head"] = dense(next(keys), (D, V), scale=0.02)
    return params


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------

def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float) -> jnp.ndarray:
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(x.dtype)


def rope_tables(positions: jnp.ndarray, head_dim: int, theta: float):
    """cos/sin tables [B, T, hd] for the given absolute positions (f32)."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [B, T, hd/2]
    angles = jnp.concatenate([angles, angles], axis=-1)  # HF rotate_half layout
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """x: [B, H, T, hd]; cos/sin: [B, T, hd] (HF rotate-half convention)."""
    cos = cos[:, None, :, :]
    sin = sin[:, None, :, :]
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    xf = x.astype(jnp.float32)
    rf = rotated.astype(jnp.float32)
    return (xf * cos + rf * sin).astype(x.dtype)


# "auto" thresholds, from real TPU v5e sweeps (fwd+bwd, Qwen2-1.5B head
# geometry): pallas-512 flash ties XLA at T=256 and wins from T=512 up
# (11.9→7.4ms at T=512; 371→17ms at T=8192). The decode kernel's
# prefix-bounded reads only pay off once the cache is large enough that
# skipped HBM traffic beats its finer-grained grid (XLA decode is one fused
# masked matmul and wins on short caches).
_FLASH_AUTO_MIN_T = 512
_DECODE_AUTO_MIN_T = 2048


def use_flash(impl: str, seq_len: int) -> bool:
    """Resolve the train/prefill self-attention impl for a padded length."""
    if impl == "pallas":
        return True
    return (impl == "auto" and seq_len >= _FLASH_AUTO_MIN_T
            and jax.default_backend() == "tpu")


def use_decode_kernel(impl: str, cache_len: int) -> bool:
    """Resolve the single-token decode-attention impl for a cache size."""
    if impl == "pallas":
        return True
    return (impl == "auto" and cache_len >= _DECODE_AUTO_MIN_T
            and jax.default_backend() == "tpu")


def use_q8_decode_kernel(impl: str) -> bool:
    """int8-cache decode routing. Unlike the exact case there is no length
    threshold: the only alternative is the dequantize-everything fallback,
    which re-materializes the full cache per layer per step and is strictly
    worse than both the q8 kernel and the unquantized path — so on TPU every
    non-"xla" impl takes the kernel at any cache length ("xla" stays the
    operator escape hatch; "pallas" also exercises it in interpret mode)."""
    return impl == "pallas" or (impl != "xla" and jax.default_backend() == "tpu")


def _kernel_spmd(config: ModelConfig, H: int, KV: int):
    """(mesh, batch_axes, head_axis|None) for wrapping a Pallas kernel in
    shard_map, or None when no multi-device hint applies (single device, or
    nothing in the config's axes actually spans >1 device)."""
    mesh = config.spmd_mesh
    if mesh is None:
        return None
    batch = tuple(
        a for a in config.spmd_batch_axes if mesh.shape.get(a, 1) > 1
    )
    head = config.spmd_head_axis
    hsz = mesh.shape.get(head, 1) if head else 1
    if hsz <= 1 or H % hsz or KV % hsz:
        head = None  # uneven heads: replicate them (still fixes the batch)
    if not batch and head is None:
        return None
    return mesh, (batch or None), head


def _spmd_call(spmd, fn, args, head_dims):
    """Run `fn(*args)` under shard_map: batch dim 0 sharded over the batch
    axes, the head dim (per-arg index in `head_dims`, None = no head dim)
    over the head axis. Output shards like the first argument. Without this
    GSPMD must treat the inner pallas_call as an opaque custom call and
    all-gathers every operand (see ModelConfig.spmd_mesh)."""
    from jax.sharding import PartitionSpec as P

    from nanorlhf_tpu.utils.shardmap_compat import shard_map

    mesh, batch, head = spmd

    def spec(x, hdim):
        s = [None] * x.ndim
        s[0] = batch
        if head is not None and hdim is not None:
            s[hdim] = head
        return P(*s)

    in_specs = tuple(spec(x, h) for x, h in zip(args, head_dims))
    out_specs = spec(args[0], head_dims[0])
    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     check_vma=False)(*args)


def gqa_attention(
    q: jnp.ndarray,       # [B, H, Tq, hd]
    k: jnp.ndarray,       # [B, KV, Tk, hd]
    v: jnp.ndarray,       # [B, KV, Tk, hd]
    mask: jnp.ndarray,    # [B, 1, Tq, Tk] bool, True = attend
    impl: str = "xla",
    mask_is_causal_x_keyvalid: bool = False,
    spmd=None,
) -> jnp.ndarray:
    """`mask_is_causal_x_keyvalid` asserts the mask factors as
    causal(Tq,Tk) & key_valid[B,Tk] — required for the flash path, which
    rebuilds the causal part in-kernel and keeps only the key-validity row.
    Callers with arbitrary masks (prefix-LM etc.) must leave it False and get
    the general XLA path. `spmd` (from `_kernel_spmd`) shard_maps the flash
    kernel so a sharded batch stays sharded."""
    B, H, Tq, hd = q.shape
    Tk = k.shape[2]
    if use_flash(impl, Tq) and mask_is_causal_x_keyvalid and Tq == Tk and Tq > 1:
        # key-validity = the mask's last query row (causal there is all-True)
        from nanorlhf_tpu.ops.attention import flash_attention

        key_valid = mask[:, 0, -1, :]
        if spmd is not None:
            return _spmd_call(
                spmd, lambda q, k, v, kv: flash_attention(q, k, v, kv, causal=True),
                (q, k, v, key_valid), (1, 1, 1, None),
            )
        return flash_attention(q, k, v, key_valid, causal=True)
    KV = k.shape[1]
    G = H // KV
    qg = q.reshape(B, KV, G, Tq, hd)
    scores = jnp.einsum("bkgqh,bkth->bkgqt", qg, k).astype(jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(hd))
    scores = jnp.where(mask[:, :, None, :, :], scores, NEG_INF)  # [B,1,1,Tq,Tk] broadcast
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgqt,bkth->bkgqh", probs, v)
    return out.reshape(B, H, Tq, hd)


# ---------------------------------------------------------------------------
# Layer body (scanned)
# ---------------------------------------------------------------------------

def _proj(h, layer_params, lora_layer, name, lora_scale):
    """x @ W (+ bias) (+ LoRA (x@A)@B · scale) — LoRA applied in-graph so
    sampling/scoring/training all see fresh adapter weights (core/lora.py).

    Weight-only int8 form (`kernel_q` + per-output-channel `kernel_scale`,
    core/quant.py): the upcast feeds the matmul directly (int8 stays the HBM
    resident form) and the scale folds into the epilogue."""
    p = layer_params[name]
    if "kernel_q" in p:
        y = h @ p["kernel_q"].astype(h.dtype)
        y = (y.astype(jnp.float32) * p["kernel_scale"]).astype(h.dtype)
    else:
        y = h @ p["kernel"]
    if "bias" in p:
        y = y + p["bias"]
    if lora_layer is not None and name in lora_layer:
        ab = lora_layer[name]
        y = y + ((h @ ab["a"]) @ ab["b"]) * lora_scale
    return y


def _cache_update(cache, new, idx):
    """Write `new` [B, KV, T, hd] into `cache` [B, KV, T_max, hd] at slot
    `idx` along the sequence axis. A scalar `idx` is the shared-slot decode/
    prefill path; a per-row [B] `idx` (speculative verify — accepted rows
    advance at different rates) vmaps the update over the batch."""
    if getattr(idx, "ndim", 0) == 1:
        return jax.vmap(
            lambda c, n, i: jax.lax.dynamic_update_slice(c, n, (0, i, 0))
        )(cache, new, idx)
    return jax.lax.dynamic_update_slice(cache, new, (0, 0, idx, 0))


def _scale_update(cache, new, idx):
    """Same for the int8 cache's sublane-expanded scales [B, KV, 8, T_max]
    (sequence on the LAST axis)."""
    if getattr(idx, "ndim", 0) == 1:
        return jax.vmap(
            lambda c, n, i: jax.lax.dynamic_update_slice(c, n, (0, 0, i))
        )(cache, new, idx)
    return jax.lax.dynamic_update_slice(cache, new, (0, 0, 0, idx))


# --------------------------------------------------------------------------- #
# paged KV cache (ISSUE 10): a global page pool + per-row block table replaces
# the per-row [T_max] slab — see docs/PAGED_CACHE.md and sampler/paged/
# --------------------------------------------------------------------------- #

def _paged_slots(cache_index, B, T):
    """Logical cache slots [B, T] for a write of T tokens starting at
    `cache_index` (scalar shared slot, or per-row [B] — speculative verify
    and the continuous-batching scheduler advance rows at different rates)."""
    idx = jnp.asarray(cache_index, jnp.int32)
    if idx.ndim == 0:
        idx = jnp.broadcast_to(idx, (B,))
    return idx[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]


def _paged_pages(pool, table, slots, page_size):
    """Resolve logical slots [B, T] to (physical page, offset) pairs.
    Out-of-table slots and sentinel table entries both map to page
    `num_pages`, which `mode="drop"` scatters discard — a row past its page
    budget (or with released pages) can never corrupt a live page."""
    num_pages, nb = pool.shape[0], table.shape[1]
    lb = slots // page_size
    page = jnp.where(
        lb < nb,
        jnp.take_along_axis(table, jnp.clip(lb, 0, nb - 1), axis=1),
        num_pages,
    )
    return page, slots % page_size


def _paged_cache_update(pool, new, table, cache_index, page_size):
    """Write `new` [B, KV, T, hd] through the block table into the page pool
    [num_pages, KV, page_size, hd]."""
    B, KV, T, hd = new.shape
    page, off = _paged_pages(pool, table, _paged_slots(cache_index, B, T),
                             page_size)
    return pool.at[page, :, off, :].set(
        new.transpose(0, 2, 1, 3), mode="drop")


def _paged_scale_update(pool, new, table, cache_index, page_size):
    """Same for the int8 scale pool [num_pages, KV, 8, page_size]
    (offset on the LAST axis); `new` is [B, KV, 8, T]."""
    B, KV, e, T = new.shape
    page, off = _paged_pages(pool, table, _paged_slots(cache_index, B, T),
                             page_size)
    return pool.at[page, :, :, off].set(
        new.transpose(0, 3, 1, 2), mode="drop")


def _paged_view(pool, table, width):
    """Gather a row-contiguous [B, KV, width, hd] cache view from the pool —
    the off-TPU read path. Sentinel entries clamp to page num_pages-1; the
    garbage they surface sits in slots the attention mask already excludes,
    and NEG_INF masking zeroes its contribution exactly, so this view is
    bit-identical to the contiguous cache under the same mask."""
    num_pages = pool.shape[0]
    g = pool[jnp.minimum(table, num_pages - 1)]      # [B, nb, KV, P, hd]
    B, nb, KV, P, hd = g.shape
    return g.transpose(0, 2, 1, 3, 4).reshape(B, KV, nb * P, hd)[:, :, :width, :]


def _paged_scale_view(pool, table, width):
    """[num_pages, KV, 8, P] scale pool → [B, KV, 8, width] view."""
    num_pages = pool.shape[0]
    g = pool[jnp.minimum(table, num_pages - 1)]      # [B, nb, KV, 8, P]
    B, nb, KV, e, P = g.shape
    return g.transpose(0, 2, 3, 1, 4).reshape(B, KV, e, nb * P)[..., :width]


def _layer_body(config: ModelConfig, x, layer_params, cos, sin, mask, kv_cache,
                cache_index, lora_layer=None, lora_scale=1.0, attn_fn=None,
                decode_bounds=None, verify_bounds=None, paged=None):
    """One decoder layer. If kv_cache is not None, operate incrementally.

    Returns (x_out, new_kv_pair_or_None).
    kv_cache: (k_cache, v_cache) each [B, KV, T_max, hd] or None.
    `attn_fn(q, k, v)`, when given, replaces the attention contraction (used
    by the sequence-parallel path to route through ring attention) — every
    other op stays this single implementation.
    `verify_bounds=(start, fill)` ([B] each) marks the speculative-verify
    path: T = k+1 candidate tokens per row, cache_index is per-row, and
    attention runs the k-query prefix-bounded contraction over the cache
    (general masked XLA attention off-TPU / for the int8 cache, which
    dequantizes — correct, no bandwidth win; the single-token q8 kernel is
    unaffected).
    `paged=(block_table [B, nb] int32, page_size)` switches the cache to the
    paged layout (init_paged_kv_cache): writes scatter through the table
    with `mode="drop"` (sentinel/over-budget slots discard), reads go to the
    paged Pallas kernels on TPU or a gathered row-contiguous view sliced to
    the mask width elsewhere — the view path reuses the exact same masked
    gqa_attention math as the contiguous cache, which is what makes paged
    generation bit-identical to contiguous on the CPU mesh (test-pinned).
    The paged kernels skip the shard_map wrap (`_spmd_call` shards arg dim 0,
    which for pools is pages, not batch); GSPMD partitions them instead.
    """
    hd = config.actual_head_dim
    H, KV = config.num_attention_heads, config.num_key_value_heads
    B, T, D = x.shape
    spmd = _kernel_spmd(config, H, KV)

    h = rms_norm(x, layer_params["input_layernorm"], config.rms_norm_eps)
    q = _proj(h, layer_params, lora_layer, "q_proj", lora_scale)
    k = _proj(h, layer_params, lora_layer, "k_proj", lora_scale)
    v = _proj(h, layer_params, lora_layer, "v_proj", lora_scale)
    q = q.reshape(B, T, H, hd).transpose(0, 2, 1, 3)
    k = k.reshape(B, T, KV, hd).transpose(0, 2, 1, 3)
    v = v.reshape(B, T, KV, hd).transpose(0, 2, 1, 3)

    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    if attn_fn is not None:
        new_cache = None
        out = attn_fn(q, k, v)
    elif kv_cache is not None and len(kv_cache) == 4:
        # int8 KV cache: (k_q, k_scales, v_q, v_scales) — see init_kv_cache
        kq_c, ks_c, vq_c, vs_c = kv_cache
        k_q, k_s = _quantize_kv(k)
        v_q, v_s = _quantize_kv(v)
        if paged is not None:
            table, psize = paged
            kq_c = _paged_cache_update(kq_c, k_q, table, cache_index, psize)
            vq_c = _paged_cache_update(vq_c, v_q, table, cache_index, psize)
            ks_c = _paged_scale_update(ks_c, k_s, table, cache_index, psize)
            vs_c = _paged_scale_update(vs_c, v_s, table, cache_index, psize)
        else:
            kq_c = _cache_update(kq_c, k_q, cache_index)
            vq_c = _cache_update(vq_c, v_q, cache_index)
            ks_c = _scale_update(ks_c, k_s, cache_index)
            vs_c = _scale_update(vs_c, v_s, cache_index)
        new_cache = (kq_c, ks_c, vq_c, vs_c)

        def _q8_views(width):
            """Row-contiguous dequantized cache views (paged gathers through
            the table; contiguous passes the slabs through)."""
            if paged is not None:
                return (
                    _dequantize_kv(_paged_view(kq_c, paged[0], width),
                                   _paged_scale_view(ks_c, paged[0], width),
                                   q.dtype),
                    _dequantize_kv(_paged_view(vq_c, paged[0], width),
                                   _paged_scale_view(vs_c, paged[0], width),
                                   q.dtype),
                )
            return (_dequantize_kv(kq_c, ks_c, q.dtype),
                    _dequantize_kv(vq_c, vs_c, q.dtype))

        if verify_bounds is not None:
            # speculative verify over the int8 cache: dequantize and run the
            # general masked path — correct everywhere, no bandwidth win
            # (the q8 k-query kernel is future work; single-token decode
            # keeps the q8 kernel either way)
            kd, vd = _q8_views(mask.shape[-1])
            out = gqa_attention(q, kd, vd, mask)
        elif T > 1 and use_flash(config.attention_impl, T):
            out = gqa_attention(q, k, v, mask[..., :T], impl="pallas",
                                mask_is_causal_x_keyvalid=True, spmd=spmd)
        elif T > 1:
            out = gqa_attention(q, k, v, mask[..., :T])
        elif (decode_bounds is not None
              and use_q8_decode_kernel(config.attention_impl)):
            # decode reads the cache: the q8 kernel consumes int8 + scales
            # natively — the whole point of the quantized cache.
            # attention_impl="xla" stays a working escape hatch (dequant
            # fallback below: correct, no bandwidth win)
            start, filled = decode_bounds
            if paged is not None:
                from nanorlhf_tpu.ops.decode_attention import (
                    paged_decode_attention_q8,
                )

                out = paged_decode_attention_q8(
                    q[:, :, 0, :], kq_c, ks_c, vq_c, vs_c, paged[0],
                    start, filled,
                )[:, :, None, :]
            else:
                from nanorlhf_tpu.ops.decode_attention import (
                    decode_attention_q8,
                )

                q8_args = (q[:, :, 0, :], kq_c, ks_c, vq_c, vs_c, start,
                           filled)
                if spmd is not None:
                    out = _spmd_call(spmd, decode_attention_q8, q8_args,
                                     (1, 1, 1, 1, 1, None, None))[:, :, None, :]
                else:
                    out = decode_attention_q8(*q8_args)[:, :, None, :]
        else:
            # correctness fallback (CPU tests): dequantize and reuse the
            # exact path — no bandwidth win off-TPU, none needed
            kd, vd = _q8_views(mask.shape[-1])
            out = gqa_attention(q, kd, vd, mask)
    elif kv_cache is not None:
        k_cache, v_cache = kv_cache
        if paged is not None:
            table, psize = paged
            k_cache = _paged_cache_update(k_cache, k, table, cache_index, psize)
            v_cache = _paged_cache_update(v_cache, v, table, cache_index, psize)
            # logical cache length (for the kernel-eligibility threshold and
            # the gathered view) is the mask width, not the pool shape
            cache_len = mask.shape[-1]
        else:
            k_cache = _cache_update(k_cache, k, cache_index)
            v_cache = _cache_update(v_cache, v, cache_index)
            cache_len = k_cache.shape[2]
        new_cache = (k_cache, v_cache)

        def _kv_views(width):
            if paged is not None:
                return (_paged_view(k_cache, paged[0], width),
                        _paged_view(v_cache, paged[0], width))
            return k_cache, v_cache

        if verify_bounds is not None:
            # speculative verify: T = k+1 candidate queries read the cache
            # (their KV just landed at per-row slots [fill, fill+T)). The
            # k-query prefix-bounded kernel on TPU; the general masked XLA
            # contraction elsewhere (mask carries prefix + causal-within-
            # candidates, built by decode_verify).
            if use_decode_kernel(config.attention_impl, cache_len):
                start, vfill = verify_bounds
                if paged is not None:
                    from nanorlhf_tpu.ops.decode_attention import (
                        paged_decode_verify_attention,
                    )

                    out = paged_decode_verify_attention(
                        q, k_cache, v_cache, paged[0], start, vfill)
                else:
                    from nanorlhf_tpu.ops.decode_attention import (
                        decode_verify_attention,
                    )

                    ver_args = (q, k_cache, v_cache, start, vfill)
                    if spmd is not None:
                        out = _spmd_call(spmd, decode_verify_attention,
                                         ver_args, (1, 1, 1, None, None))
                    else:
                        out = decode_verify_attention(*ver_args)
            else:
                kd, vd = _kv_views(mask.shape[-1])
                out = gqa_attention(q, kd, vd, mask)
        elif T > 1 and use_flash(config.attention_impl, T):
            # prefill: cache slots beyond T are masked anyway, so attend over
            # the local-length K/V through the flash kernel instead of the
            # T_max-padded cache
            out = gqa_attention(q, k, v, mask[..., :T], impl="pallas",
                                mask_is_causal_x_keyvalid=True, spmd=spmd)
        elif (T == 1 and decode_bounds is not None
              and use_decode_kernel(config.attention_impl, cache_len)):
            # decode: prefix-bounded Pallas kernel reads only the filled
            # cache range instead of the masked T_max square
            if paged is not None:
                from nanorlhf_tpu.ops.decode_attention import (
                    paged_decode_attention,
                )

                out = paged_decode_attention(
                    q[:, :, 0, :], k_cache, v_cache, paged[0],
                    *decode_bounds)[:, :, None, :]
            else:
                from nanorlhf_tpu.ops.decode_attention import decode_attention

                dec_args = (q[:, :, 0, :], k_cache, v_cache) + tuple(decode_bounds)
                if spmd is not None:
                    out = _spmd_call(spmd, decode_attention, dec_args,
                                     (1, 1, 1, None, None))[:, :, None, :]
                else:
                    out = decode_attention(*dec_args)[:, :, None, :]
        else:
            kd, vd = _kv_views(mask.shape[-1])
            out = gqa_attention(q, kd, vd, mask)
    else:
        new_cache = None
        out = gqa_attention(q, k, v, mask, impl=config.attention_impl,
                            mask_is_causal_x_keyvalid=True, spmd=spmd)
    out = out.transpose(0, 2, 1, 3).reshape(B, T, H * hd)
    out = _proj(out, layer_params, lora_layer, "o_proj", lora_scale)
    x = x + out

    h = rms_norm(x, layer_params["post_attention_layernorm"], config.rms_norm_eps)
    gate = _proj(h, layer_params, lora_layer, "gate_proj", lora_scale)
    up = _proj(h, layer_params, lora_layer, "up_proj", lora_scale)
    ff = _proj(
        jax.nn.silu(gate.astype(jnp.float32)).astype(h.dtype) * up,
        layer_params, lora_layer, "down_proj", lora_scale,
    )
    x = x + ff
    return x, new_cache


def _run_layers(config, params, x, cos, sin, mask, kv_caches=None, cache_index=0,
                lora_scale=1.0, remat=False, attn_fn=None, layer_transform=None,
                decode_bounds=None, verify_bounds=None, paged=None):
    """Scan the stacked layer params over the layer body.

    `remat=True` wraps the body in jax.checkpoint — the training path's
    activation rematerialization (capability parity with the reference's
    `gradient_checkpointing=True`, `/root/reference/GRPO/grpo.py:134`, but
    trading FLOPs for HBM the XLA way).

    `layer_transform(layer_params, lora_layer) -> (layer_params, lora_layer)`
    runs inside the scan body before the layer math — the FSDP hook: scanned
    param slices enter as shards and are all-gathered one layer at a time.
    """
    lora_layers = params.get("lora", {}).get("layers") if isinstance(params, dict) else None

    if kv_caches is None:
        def body(carry, inp):
            layer_params, lora_layer = inp
            if layer_transform is not None:
                layer_params, lora_layer = layer_transform(layer_params, lora_layer)
            y, _ = _layer_body(config, carry, layer_params, cos, sin, mask, None, 0,
                               lora_layer, lora_scale, attn_fn=attn_fn)
            return y, None

        if remat:
            if config.remat_policy == "dots":
                # keep MXU matmul outputs (no batch dims = the weight
                # projections, not attention scores) for the backward
                body = jax.checkpoint(
                    body,
                    policy=jax.checkpoint_policies
                    .dots_with_no_batch_dims_saveable,
                )
            elif config.remat_policy == "full":
                body = jax.checkpoint(body)
            else:
                raise ValueError(
                    f"remat_policy={config.remat_policy!r}: must be "
                    "'full' or 'dots'"
                )
        x, _ = jax.lax.scan(body, x, (params["layers"], lora_layers))
        return x, None
    else:
        # cache is a tuple of stacked arrays: (k, v) exact, or
        # (k_q, k_s, v_q, v_s) int8 — threaded generically through the scan.
        # `paged` (block table + page size) is closure-captured, not scanned:
        # one table serves every layer
        def body(carry, inp):
            layer_params, lora_layer = inp[0], inp[1]
            y, new_cache = _layer_body(
                config, carry, layer_params, cos, sin, mask, tuple(inp[2:]),
                cache_index, lora_layer, lora_scale,
                decode_bounds=decode_bounds, verify_bounds=verify_bounds,
                paged=paged,
            )
            return y, new_cache

        x, new_caches = jax.lax.scan(
            body, x, (params["layers"], lora_layers, *kv_caches)
        )
        return x, new_caches


def unembedding(config: ModelConfig, params: dict):
    """`(weight, transposed)` for the fused hidden→logprob op
    (ops/fused_logprob.py): `(lm_head [D, V], False)`, or
    `(embed_tokens [V, D], True)` when tied. The tied leaf is handed over
    UNtransposed on purpose — the op contracts on the shared D axis either
    way, dW accumulates straight into `embed_tokens`, and its Pallas kernel
    reads vocab-row blocks; an `embed.T` view feeding a Pallas custom call
    would make XLA stage the full [D, V] transposed copy (custom-call
    operands are physical buffers; only XLA dots fold transposes)."""
    if config.tie_word_embeddings:
        return params["embed_tokens"], True
    return params["lm_head"], False


def unembedding_weight(config: ModelConfig, params: dict) -> jnp.ndarray:
    """The [D, V] unembedding matrix: `lm_head`, or `embed_tokens`ᵀ when
    tied. Under jit the transpose fuses into the consuming XLA matmul (dot
    dimension numbers), so no transposed copy materializes — and gradients
    flow back through the transpose to `embed_tokens` unchanged. That
    folding does NOT hold for Pallas custom calls: anything feeding
    ops/fused_logprob.py should use `unembedding()` + `transposed=` and
    skip the view entirely."""
    if config.tie_word_embeddings:
        return params["embed_tokens"].T
    return params["lm_head"]


def _logits(config: ModelConfig, params: dict, x: jnp.ndarray) -> jnp.ndarray:
    x = rms_norm(x, params["norm"], config.rms_norm_eps)
    return x @ unembedding_weight(config, params)


# ---------------------------------------------------------------------------
# Public entrypoints
# ---------------------------------------------------------------------------

def model_forward(
    params: dict,
    config: ModelConfig,
    input_ids: jnp.ndarray,       # [B, T]
    attention_mask: jnp.ndarray,  # [B, T] bool/int, True = real token
    position_ids: jnp.ndarray,    # [B, T]
    lora_scale: float = 1.0,
    remat: bool = False,
) -> jnp.ndarray:
    """Full-sequence forward (training / logprob pass). Returns logits [B, T, V]."""
    x = _hidden_from_inputs(params, config, input_ids, attention_mask,
                            position_ids, lora_scale, remat)
    return _logits(config, params, x)


def _hidden_from_inputs(params, config, input_ids, attention_mask, position_ids,
                        lora_scale, remat, attn_fn=None, layer_transform=None):
    """embed → rope → causal+padding mask → scanned layers. The one copy of
    this recipe; every forward entrypoint goes through it.

    `attn_fn` overrides the attention contraction (sequence-parallel ring
    path); the local causal mask is then unused — the override builds its own
    mask from global positions.
    """
    attention_mask = attention_mask.astype(bool)
    x = params["embed_tokens"][input_ids].astype(params["embed_tokens"].dtype)
    T = input_ids.shape[1]
    cos, sin = rope_tables(position_ids, config.actual_head_dim, config.rope_theta)
    causal = jnp.tril(jnp.ones((T, T), bool))
    mask = causal[None, None, :, :] & attention_mask[:, None, None, :]
    x, _ = _run_layers(config, params, x, cos, sin, mask,
                       lora_scale=lora_scale, remat=remat, attn_fn=attn_fn,
                       layer_transform=layer_transform)
    return x


def _padded_hidden(
    params: dict,
    config: ModelConfig,
    query_responses: jnp.ndarray,
    pad_token_id: int,
    lora_scale: float = 1.0,
    remat: bool = False,
) -> jnp.ndarray:
    """Shared padding recipe → pre-final-norm hidden states [B, T, D].

    attention_mask = (ids != pad); position_ids = cumsum(mask) - mask; padded
    ids replaced with 0 (`/root/reference/GRPO/grpo_trainer.py:90-120`). The
    single source of truth for both the policy logit pass and the value/RM
    score pass — their padding numerics must never drift apart.
    """
    input_ids, attention_mask, position_ids = padding_inputs(
        query_responses, pad_token_id
    )
    return _hidden_from_inputs(params, config, input_ids, attention_mask,
                               position_ids, lora_scale, remat)


def padding_inputs(query_responses: jnp.ndarray, pad_token_id: int):
    """(input_ids, attention_mask, position_ids) from padded token ids — the
    single copy of the reference's padding recipe, shared by every scorer
    (incl. the sequence-parallel paths in parallel/sp.py)."""
    attention_mask = query_responses != pad_token_id
    position_ids = jnp.cumsum(attention_mask, axis=1) - attention_mask.astype(jnp.int32)
    input_ids = jnp.where(attention_mask, query_responses, 0)
    return input_ids, attention_mask, position_ids


def padded_forward_logits(
    params: dict,
    config: ModelConfig,
    query_responses: jnp.ndarray,
    pad_token_id: int,
    lora_scale: float = 1.0,
    remat: bool = False,
    response_context_length: int | None = None,
) -> jnp.ndarray:
    """Padding-robust forward: the reference's shared `forward()` contract.

    `response_context_length=ctx` returns next-token logits for the response
    positions only — hidden states are sliced `[ctx-1:-1]` BEFORE the vocab
    projection, so the lm_head never runs over prompt positions (the
    reference slices logits after computing all of them,
    `GRPO/grpo_trainer.py:546`; at 152k vocab the discarded prompt logits
    are the single largest wasted tensor in the update pass). The shift-by-
    one next-token convention lives here, in one place.
    """
    x = _padded_hidden(params, config, query_responses, pad_token_id, lora_scale, remat)
    if response_context_length is not None:
        x = x[:, response_context_length - 1 : -1]
    return _logits(config, params, x)


def padded_forward_hidden(
    params: dict,
    config: ModelConfig,
    query_responses: jnp.ndarray,
    pad_token_id: int,
    lora_scale: float = 1.0,
    remat: bool = False,
    response_context_length: int | None = None,
) -> jnp.ndarray:
    """`padded_forward_logits` minus the vocab projection: FINAL-NORMED
    hidden states [B, T', D] — the input the fused hidden→logprob op
    (ops/fused_logprob.py) consumes together with `unembedding_weight`.

    `padded_forward_logits(p, c, qr, ...) ==
    padded_forward_hidden(p, c, qr, ...) @ unembedding_weight(c, p)` exactly:
    the response slice happens at the same point (before the head; the final
    RMSNorm is positionwise, so slicing before or after it is equivalent),
    and the shift-by-one next-token convention stays in one place.
    """
    x = _padded_hidden(params, config, query_responses, pad_token_id, lora_scale, remat)
    if response_context_length is not None:
        x = x[:, response_context_length - 1 : -1]
    return rms_norm(x, params["norm"], config.rms_norm_eps)


def init_score_head(config: ModelConfig, key: jax.Array, num_labels: int = 1,
                    dtype=jnp.bfloat16) -> jnp.ndarray:
    """Score head [D, num_labels] — a value/reward model is the decoder with
    this head instead of lm_head (HF `AutoModelForSequenceClassification(
    num_labels=1)`, `/root/reference/PPO/ppo.py:280-287`)."""
    scale = 1.0 / jnp.sqrt(jnp.float32(config.hidden_size))
    return (jax.random.normal(key, (config.hidden_size, num_labels), jnp.float32) * scale).astype(dtype)


def score_forward(
    params: dict,
    config: ModelConfig,
    query_responses: jnp.ndarray,
    pad_token_id: int,
    lora_scale: float = 1.0,
    remat: bool = False,
) -> jnp.ndarray:
    """Per-position scores [B, T, num_labels] from a tree carrying "score".

    Same padding recipe as padded_forward_logits (shared `_padded_hidden`);
    hidden states are final-normed before the head (matching
    Qwen2ForSequenceClassification). Used for the PPO value pass
    (`PPO/ppo_trainer.py:630-634,732`) and RM-based rewards.
    """
    x = _padded_hidden(params, config, query_responses, pad_token_id, lora_scale, remat)
    x = rms_norm(x, params["norm"], config.rms_norm_eps)
    return (x.astype(jnp.float32) @ params["score"].astype(jnp.float32))


def init_kv_cache(
    config: ModelConfig, batch: int, max_len: int, dtype=jnp.bfloat16
) -> tuple[jnp.ndarray, ...]:
    """Stacked KV cache.

    Exact: (k, v), each [L, B, KV, max_len, hd].
    kv_cache_quant="int8": (k_q, k_s, v_q, v_s) — int8 values plus bf16
    per-token-per-head scales carried SUBLANE-EXPANDED as [L, B, KV, 8,
    max_len]: the decode kernel's (1, 1, 8, block_k) scale blocks are
    Mosaic-legal because the 8 SPANS its array dimension (the
    equal-to-the-dim clause; bf16's native sublane tile is 16, so the
    divisibility clause alone would not cover it), with the sequence on
    the lane axis — same recipe as the flash kernel's mask
    (ops/attention.py).
    """
    shape = (
        config.num_hidden_layers,
        batch,
        config.num_key_value_heads,
        max_len,
        config.actual_head_dim,
    )
    if config.kv_cache_quant == "int8":
        sshape = shape[:3] + (8, max_len)
        return (
            jnp.zeros(shape, jnp.int8), jnp.ones(sshape, jnp.bfloat16),
            jnp.zeros(shape, jnp.int8), jnp.ones(sshape, jnp.bfloat16),
        )
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def init_paged_kv_cache(
    config: ModelConfig, num_pages: int, page_size: int, dtype=jnp.bfloat16
) -> tuple[jnp.ndarray, ...]:
    """Paged KV cache: a global page pool shared by every row, addressed
    through a per-row block table (sampler/paged/pages.py).

    Exact: (k, v), each [L, num_pages, KV, page_size, hd].
    kv_cache_quant="int8": (k_q, k_s, v_q, v_s) with scale pools
    [L, num_pages, KV, 8, page_size] — the sublane-expanded layout of
    `init_kv_cache`, per page instead of per row.

    Same tuple arity as the contiguous cache, so `_run_layers` threads it
    through the layer scan unchanged; the block table is NOT part of the
    cache tuple (it is shared across layers and rides as a separate
    argument).
    """
    shape = (
        config.num_hidden_layers,
        num_pages,
        config.num_key_value_heads,
        page_size,
        config.actual_head_dim,
    )
    if config.kv_cache_quant == "int8":
        sshape = shape[:3] + (8, page_size)
        return (
            jnp.zeros(shape, jnp.int8), jnp.ones(sshape, jnp.bfloat16),
            jnp.zeros(shape, jnp.int8), jnp.ones(sshape, jnp.bfloat16),
        )
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def _quantize_kv(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """[B, KV, T, hd] -> (int8 [B, KV, T, hd], bf16 scales [B, KV, 8, T]).

    Scales are STORED bf16 (the sublane-replicated layout already costs 8x,
    so dtype is where the scale stream's bandwidth goes) and quantization
    divides by the bf16-ROUNDED scale, keeping dequantization exact with
    respect to what the cache actually holds.
    """
    B, KV, T, hd = x.shape
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1)                 # [B, KV, T]
    scale = jnp.maximum(absmax / 127.0, 1e-8)
    scale = scale.astype(jnp.bfloat16)
    q = jnp.clip(
        jnp.round(xf / scale[..., None].astype(jnp.float32)), -127, 127
    ).astype(jnp.int8)
    scale8 = jnp.broadcast_to(scale[:, :, None, :], (B, KV, 8, T))
    return q, scale8


def _dequantize_kv(q: jnp.ndarray, scale8: jnp.ndarray, dtype) -> jnp.ndarray:
    """Inverse of _quantize_kv (XLA fallback path)."""
    return (
        q.astype(jnp.float32)
        * scale8[:, :, 0, :, None].astype(jnp.float32)
    ).astype(dtype)


def prefill(
    params: dict,
    config: ModelConfig,
    input_ids: jnp.ndarray,       # [B, T_prompt]
    attention_mask: jnp.ndarray,  # [B, T_prompt]
    kv_caches: tuple[jnp.ndarray, jnp.ndarray],  # from init_kv_cache, T_max >= T_prompt
    lora_scale: float = 1.0,
    page_table=None,              # [B, nb] int32 (paged layout; see init_paged_kv_cache)
    page_size: int = 0,
    logical_len: int = 0,         # paged: the logical cache width T_max (mask
                                  # width must match the contiguous run
                                  # bit-for-bit, so it cannot be inferred
                                  # from the pool shape)
):
    """Prompt ingestion: fills the KV cache, returns (last-position logits, caches).

    Prompts are assumed *left-padded* to a common length (sampler contract), so
    the last position is the last prompt token for every row.
    """
    B, T = input_ids.shape
    paged = None
    if page_table is not None:
        T_max = logical_len if logical_len else page_table.shape[1] * page_size
        paged = (page_table, page_size)
    else:
        T_max = kv_caches[0].shape[3]
    attention_mask = attention_mask.astype(bool)
    position_ids = jnp.cumsum(attention_mask, axis=1) - attention_mask.astype(jnp.int32)
    x = params["embed_tokens"][jnp.where(attention_mask, input_ids, 0)].astype(
        params["embed_tokens"].dtype
    )
    cos, sin = rope_tables(position_ids, config.actual_head_dim, config.rope_theta)
    causal = jnp.tril(jnp.ones((T, T), bool))
    # queries attend over cache positions [0, T); the rest of T_max is masked
    mask = (causal[None, None, :, :] & attention_mask[:, None, None, :])
    mask_full = jnp.zeros((B, 1, T, T_max), bool).at[:, :, :, :T].set(mask)
    x, new_caches = _run_layers(
        config, params, x, cos, sin, mask_full, kv_caches=kv_caches, cache_index=0,
        lora_scale=lora_scale, paged=paged,
    )
    logits = _logits(config, params, x[:, -1:, :])[:, 0, :]
    return logits, new_caches


def decode_step(
    params: dict,
    config: ModelConfig,
    token: jnp.ndarray,           # [B] current token
    position: jnp.ndarray,        # [B] its absolute position id
    cache_index,                  # slot to write KV into: scalar, or per-row
                                  # [B] (continuous-batching rows advance at
                                  # different rates)
    key_mask: jnp.ndarray,        # [B, T_max] bool: which cache slots are valid (incl. this one)
    kv_caches: tuple[jnp.ndarray, jnp.ndarray],
    lora_scale: float = 1.0,
    page_table=None,              # [B, nb] int32 (paged layout)
    page_size: int = 0,
):
    """One autoregressive decode step. Returns (logits [B, V], new caches)."""
    B = token.shape[0]
    paged = (page_table, page_size) if page_table is not None else None
    x = params["embed_tokens"][token][:, None, :].astype(params["embed_tokens"].dtype)
    cos, sin = rope_tables(position[:, None], config.actual_head_dim, config.rope_theta)
    mask = key_mask[:, None, None, :]  # [B, 1, 1, T_max]
    # valid cache slots form the contiguous range [start, cache_index+1):
    # left-pad offset up to the slot just written (sampler sets it True before
    # the call) — the bounds the prefix-reading Pallas decode kernel needs
    start = jnp.argmax(key_mask, axis=1).astype(jnp.int32)
    filled = jnp.broadcast_to(
        jnp.asarray(cache_index, jnp.int32) + 1, (B,))
    x, new_caches = _run_layers(
        config, params, x, cos, sin, mask, kv_caches=kv_caches, cache_index=cache_index,
        lora_scale=lora_scale, decode_bounds=(start, filled), paged=paged,
    )
    logits = _logits(config, params, x)[:, 0, :]
    return logits, new_caches


def decode_verify(
    params: dict,
    config: ModelConfig,
    tokens: jnp.ndarray,          # [B, Tq] candidates: last accepted + k drafts
    positions: jnp.ndarray,       # [B, Tq] their absolute position ids
    fill: jnp.ndarray,            # [B] cache slot of tokens[:, 0] (per-row!)
    key_mask: jnp.ndarray,        # [B, T_max] valid slots BEFORE this call
                                  # (excludes the candidate slots)
    kv_caches: tuple[jnp.ndarray, ...],
    lora_scale: float = 1.0,
    page_table=None,              # [B, nb] int32 (paged layout)
    page_size: int = 0,
    want_logits: bool = True,
):
    """Batched k-token verification for speculative decode
    (sampler/speculative.py): one small-T causal forward over Tq = k+1
    candidate tokens against the cache — the prefill attention recipe at
    decode granularity, so the dominant per-step weight stream is amortized
    over every candidate. Candidate KV is written at per-row slots
    [fill, fill+Tq) (accepted rows advance at different rates, hence the
    [B]-shaped slot index); query i attends to `key_mask` plus candidates
    0..i. Rejected candidates leave garbage KV in slots the caller never
    marks valid — the next verify overwrites them. On the paged layout a
    candidate write may straddle two pages; the generic table-routed scatter
    handles that, and writes past the row's page budget drop (those
    candidates are beyond `max_tokens` and are truncated before emission —
    docs/PAGED_CACHE.md walks the bound). Returns
    (logits [B, Tq, V], new caches): logits[:, i] is the next-token
    distribution after consuming candidates 0..i, bit-matching a chain of
    `decode_step` calls over the same tokens on the CPU mesh (test-pinned).

    `want_logits=False` skips the lm_head matmul and returns
    (None, new caches) — the chunked-prefill path (sampler/paged/session.py)
    runs every non-final prompt chunk purely for its KV writes, and at LLM
    vocabularies the unread [B, Tq, V] projection would dominate the chunk.
    """
    B, Tq = tokens.shape
    # the logical width is the key_mask width — equal to the slab's T_max on
    # the contiguous layout, and the only meaningful width on the paged one
    T_max = key_mask.shape[1]
    paged = (page_table, page_size) if page_table is not None else None
    key_mask = key_mask.astype(bool)
    x = params["embed_tokens"][tokens].astype(params["embed_tokens"].dtype)
    cos, sin = rope_tables(positions, config.actual_head_dim, config.rope_theta)
    slot = jnp.arange(T_max)[None, None, :]                  # [1, 1, T_max]
    qi = jnp.arange(Tq)[None, :, None]                       # [1, Tq, 1]
    cand = (slot >= fill[:, None, None]) & (slot <= fill[:, None, None] + qi)
    mask = (key_mask[:, None, :] | cand)[:, None, :, :]      # [B, 1, Tq, T_max]
    start = jnp.argmax(key_mask, axis=1).astype(jnp.int32)
    x, new_caches = _run_layers(
        config, params, x, cos, sin, mask, kv_caches=kv_caches,
        cache_index=fill.astype(jnp.int32), lora_scale=lora_scale,
        verify_bounds=(start, fill.astype(jnp.int32)), paged=paged,
    )
    if not want_logits:
        return None, new_caches
    return _logits(config, params, x), new_caches
