"""HF checkpoint → stacked JAX param tree.

The reference gets weights via `AutoModelForCausalLM.from_pretrained`
(`/root/reference/GRPO/grpo.py:218-224`). Here we map the HF Qwen2/Llama
state-dict layout (both families share it — Llama just drops the q/k/v
biases) onto our scan-friendly stacked tree (core/model.py): per-layer
tensors are stacked along a leading [L, ...] axis and torch `nn.Linear`
weights ([out, in]) are transposed to the x @ W layout ([in, out]).

Weight fidelity (GQA head layout, tied embeddings, RoPE) is pinned by
tests/test_model_parity.py against the torch Qwen2 AND Llama
implementations.
"""

from __future__ import annotations

import json
import os

import jax.numpy as jnp
import numpy as np

from nanorlhf_tpu.core.config import ModelConfig

# bias presence is read off the state dict itself (Qwen2 q/k/v carry
# biases, Llama-family none — both map onto the same optional-bias tree)
_LINEAR_KEYS = (
    ("q_proj", "self_attn.q_proj"),
    ("k_proj", "self_attn.k_proj"),
    ("v_proj", "self_attn.v_proj"),
    ("o_proj", "self_attn.o_proj"),
    ("gate_proj", "mlp.gate_proj"),
    ("up_proj", "mlp.up_proj"),
    ("down_proj", "mlp.down_proj"),
)


def _to_np(t) -> np.ndarray:
    """torch tensor / np array → np array (bf16-safe via float32 round-trip)."""
    if hasattr(t, "detach"):
        t = t.detach()
        if t.dtype.is_floating_point:
            t = t.float()
        t = t.cpu().numpy()
    return np.asarray(t)


def params_from_hf_state_dict(
    config: ModelConfig, state_dict: dict, dtype=jnp.bfloat16
) -> dict:
    """Convert an HF Qwen2ForCausalLM state dict (name → tensor) to our tree."""
    sd = {k: _to_np(v) for k, v in state_dict.items()}
    L = config.num_hidden_layers

    def cast(x):
        return jnp.asarray(x, dtype)

    layers: dict = {
        "input_layernorm": cast(
            np.stack([sd[f"model.layers.{i}.input_layernorm.weight"] for i in range(L)])
        ),
        "post_attention_layernorm": cast(
            np.stack(
                [sd[f"model.layers.{i}.post_attention_layernorm.weight"] for i in range(L)]
            )
        ),
    }
    for ours, theirs in _LINEAR_KEYS:
        kernel = np.stack(
            [sd[f"model.layers.{i}.{theirs}.weight"].T for i in range(L)]
        )
        entry = {"kernel": cast(kernel)}
        if f"model.layers.0.{theirs}.bias" in sd:
            entry["bias"] = cast(
                np.stack([sd[f"model.layers.{i}.{theirs}.bias"] for i in range(L)])
            )
        layers[ours] = entry

    params = {
        "embed_tokens": cast(sd["model.embed_tokens.weight"]),
        "layers": layers,
        "norm": cast(sd["model.norm.weight"]),
    }
    if not config.tie_word_embeddings:
        # some HF checkpoints omit lm_head when tied; require it when untied
        params["lm_head"] = cast(sd["lm_head.weight"].T)
    return params


def load_hf_checkpoint(model_dir: str, dtype=jnp.bfloat16):
    """Load (ModelConfig, params) from an HF model directory on disk.

    Reads config.json + *.safetensors (or pytorch_model.bin fallback).
    Host-side, outside the compiled graph — like the reference's tokenizer/
    checkpoint IO.
    """
    with open(os.path.join(model_dir, "config.json")) as f:
        config = ModelConfig.from_hf_config(json.load(f))

    state_dict: dict = {}
    st_files = sorted(
        f for f in os.listdir(model_dir) if f.endswith(".safetensors")
    )
    if st_files:
        from safetensors import safe_open

        for fname in st_files:
            with safe_open(os.path.join(model_dir, fname), framework="np") as f:
                for k in f.keys():
                    state_dict[k] = f.get_tensor(k)
    else:
        import torch

        state_dict = torch.load(
            os.path.join(model_dir, "pytorch_model.bin"), map_location="cpu"
        )
    return config, params_from_hf_state_dict(config, state_dict, dtype)
