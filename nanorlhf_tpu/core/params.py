"""HF checkpoint → stacked JAX param tree.

The reference gets weights via `AutoModelForCausalLM.from_pretrained`
(`/root/reference/GRPO/grpo.py:218-224`). Here we map the HF Qwen2/Llama
state-dict layout (both families share it — Llama just drops the q/k/v
biases) onto our scan-friendly stacked tree (core/model.py): per-layer
tensors are stacked along a leading [L, ...] axis and torch `nn.Linear`
weights ([out, in]) are transposed to the x @ W layout ([in, out]).

Weight fidelity (GQA head layout, tied embeddings, RoPE) is pinned by
tests/test_model_parity.py against the torch Qwen2 AND Llama
implementations.
"""

from __future__ import annotations

import json
import os

import jax.numpy as jnp
import numpy as np

from nanorlhf_tpu.core.config import ModelConfig

# bias presence is read off the state dict itself (Qwen2 q/k/v carry
# biases, Llama-family none — both map onto the same optional-bias tree)
_LINEAR_KEYS = (
    ("q_proj", "self_attn.q_proj"),
    ("k_proj", "self_attn.k_proj"),
    ("v_proj", "self_attn.v_proj"),
    ("o_proj", "self_attn.o_proj"),
    ("gate_proj", "mlp.gate_proj"),
    ("up_proj", "mlp.up_proj"),
    ("down_proj", "mlp.down_proj"),
)


def _to_np(t) -> np.ndarray:
    """torch tensor / np array → np array (bf16-safe via float32 round-trip)."""
    if hasattr(t, "detach"):
        t = t.detach()
        if t.dtype.is_floating_point:
            t = t.float()
        t = t.cpu().numpy()
    return np.asarray(t)


def params_from_hf_state_dict(
    config: ModelConfig, state_dict: dict, dtype=jnp.bfloat16
) -> dict:
    """Convert an HF Qwen2ForCausalLM state dict (name → tensor) to our tree."""
    sd = {k: _to_np(v) for k, v in state_dict.items()}
    L = config.num_hidden_layers

    def cast(x):
        return jnp.asarray(x, dtype)

    layers: dict = {
        "input_layernorm": cast(
            np.stack([sd[f"model.layers.{i}.input_layernorm.weight"] for i in range(L)])
        ),
        "post_attention_layernorm": cast(
            np.stack(
                [sd[f"model.layers.{i}.post_attention_layernorm.weight"] for i in range(L)]
            )
        ),
    }
    for ours, theirs in _LINEAR_KEYS:
        kernel = np.stack(
            [sd[f"model.layers.{i}.{theirs}.weight"].T for i in range(L)]
        )
        entry = {"kernel": cast(kernel)}
        if f"model.layers.0.{theirs}.bias" in sd:
            entry["bias"] = cast(
                np.stack([sd[f"model.layers.{i}.{theirs}.bias"] for i in range(L)])
            )
        layers[ours] = entry

    params = {
        "embed_tokens": cast(sd["model.embed_tokens.weight"]),
        "layers": layers,
        "norm": cast(sd["model.norm.weight"]),
    }
    if not config.tie_word_embeddings:
        # some HF checkpoints omit lm_head when tied; require it when untied
        params["lm_head"] = cast(sd["lm_head.weight"].T)
    return params


def hf_state_dict_from_params(config: ModelConfig, params: dict,
                              dtype=jnp.float32) -> dict:
    """Inverse of `params_from_hf_state_dict`: stacked JAX tree → flat HF
    Qwen2/Llama state dict (torch [out, in] linear layout), cast per-tensor
    to `dtype` so a 7B export never holds a second full-precision copy.
    LoRA subtrees are NOT folded here — pass a `merge_lora`'d tree to export
    adapters into the base weights (`save_model` parity: the reference's
    trained output is a plain HF checkpoint, `GRPO/grpo_trainer.py:321-341`)."""
    L = config.num_hidden_layers
    sd: dict = {}

    def put(name, arr):
        sd[name] = jnp.asarray(arr, dtype)

    layers = params["layers"]
    for i in range(L):
        put(f"model.layers.{i}.input_layernorm.weight",
            layers["input_layernorm"][i])
        put(f"model.layers.{i}.post_attention_layernorm.weight",
            layers["post_attention_layernorm"][i])
        for ours, theirs in _LINEAR_KEYS:
            put(f"model.layers.{i}.{theirs}.weight", layers[ours]["kernel"][i].T)
            if "bias" in layers[ours]:
                put(f"model.layers.{i}.{theirs}.bias", layers[ours]["bias"][i])
    put("model.embed_tokens.weight", params["embed_tokens"])
    put("model.norm.weight", params["norm"])
    if not config.tie_word_embeddings:
        put("lm_head.weight", params["lm_head"].T)
    return sd


def export_hf_checkpoint(
    config: ModelConfig,
    params: dict,
    out_dir: str,
    lora_scale: float | None = None,
    dtype: str = "bfloat16",
    tokenizer=None,
    eos_token_id: int | None = None,
    bos_token_id: int | None = None,
    pad_token_id: int | None = None,
) -> str:
    """Write an HF-format checkpoint dir (config.json + model.safetensors)
    that `AutoModelForCausalLM.from_pretrained` (and this module's
    `load_hf_checkpoint`) accepts — the reference's `save_model` output
    contract. `lora_scale` folds a `params["lora"]` subtree into the base
    weights first (the reference merges adapters before saving/handoff,
    `GRPO/grpo_trainer.py:131-141,321-341`).

    The handoff is only usable if generation knows how to stop and tokenize:
    a `tokenizer` with `save_pretrained` is saved alongside the weights
    (the reference's save_model does the same), and eos/bos/pad ids — taken
    from the tokenizer when not given — go into config.json and
    generation_config.json so transformers/vLLM terminate correctly."""
    from safetensors.flax import save_file

    if lora_scale is not None and "lora" in params:
        from nanorlhf_tpu.core.lora import merge_lora

        params = merge_lora(params, lora_scale)
    params = {k: v for k, v in params.items() if k != "lora"}

    os.makedirs(out_dir, exist_ok=True)
    jdtype = {"bfloat16": jnp.bfloat16, "float32": jnp.float32}[dtype]
    sd = hf_state_dict_from_params(config, params, dtype=jdtype)
    save_file(sd, os.path.join(out_dir, "model.safetensors"))

    if tokenizer is not None:
        if eos_token_id is None:
            eos_token_id = getattr(tokenizer, "eos_token_id", None)
        if bos_token_id is None:
            bos_token_id = getattr(tokenizer, "bos_token_id", None)
        if pad_token_id is None:
            pad_token_id = getattr(tokenizer, "pad_token_id", None)
        if hasattr(tokenizer, "save_pretrained"):
            tokenizer.save_pretrained(out_dir)

    # echo the source family when the config carries one (from_hf_config /
    # load_hf_checkpoint set it; a Llama with attention_bias=True must not
    # round-trip to Qwen2), but only for the two families this exporter can
    # faithfully emit — an unknown slug (e.g. "mistral") echoed verbatim
    # would make transformers' AutoConfig apply that family's defaults
    # (sliding_window, ...) to keys we never write. Anything else falls
    # back to the attention_bias heuristic, as do random-init configs.
    family = config.model_type if config.model_type in ("qwen2", "llama") \
        else ("qwen2" if config.attention_bias else "llama")
    arch = {"qwen2": "Qwen2ForCausalLM", "llama": "LlamaForCausalLM"}[family]
    hf_config = {
        "architectures": [arch],
        "model_type": family,
        "vocab_size": config.vocab_size,
        "hidden_size": config.hidden_size,
        "intermediate_size": config.intermediate_size,
        "num_hidden_layers": config.num_hidden_layers,
        "num_attention_heads": config.num_attention_heads,
        "num_key_value_heads": config.num_key_value_heads,
        "head_dim": config.actual_head_dim,
        "rope_theta": config.rope_theta,
        "rms_norm_eps": config.rms_norm_eps,
        "tie_word_embeddings": config.tie_word_embeddings,
        "max_position_embeddings": config.max_position_embeddings,
        "attention_bias": config.attention_bias,
        "hidden_act": "silu",
        "torch_dtype": dtype,
    }
    gen_config = {"_from_model_config": True}
    for key, val in (("eos_token_id", eos_token_id),
                     ("bos_token_id", bos_token_id),
                     ("pad_token_id", pad_token_id)):
        if val is not None:
            hf_config[key] = int(val)
            gen_config[key] = int(val)
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump(hf_config, f, indent=2)
    with open(os.path.join(out_dir, "generation_config.json"), "w") as f:
        json.dump(gen_config, f, indent=2)
    return out_dir


def load_hf_checkpoint(model_dir: str, dtype=jnp.bfloat16):
    """Load (ModelConfig, params) from an HF model directory on disk.

    Reads config.json + *.safetensors (or pytorch_model.bin fallback).
    Host-side, outside the compiled graph — like the reference's tokenizer/
    checkpoint IO.
    """
    with open(os.path.join(model_dir, "config.json")) as f:
        config = ModelConfig.from_hf_config(json.load(f))

    state_dict: dict = {}
    st_files = sorted(
        f for f in os.listdir(model_dir) if f.endswith(".safetensors")
    )
    if st_files:
        from safetensors import safe_open

        for fname in st_files:
            with safe_open(os.path.join(model_dir, fname), framework="np") as f:
                for k in f.keys():
                    state_dict[k] = f.get_tensor(k)
    else:
        import torch

        state_dict = torch.load(
            os.path.join(model_dir, "pytorch_model.bin"), map_location="cpu"
        )
    return config, params_from_hf_state_dict(config, state_dict, dtype)
