"""Weight-only int8 quantization for the rollout path.

Decode is HBM-bandwidth-bound: every step re-reads the full weight set, and
at LLM sizes the seven layer projections are ~85% of those bytes. Storing
them as int8 with per-output-channel scales halves that traffic (the MXU
consumes the int8 blocks straight from VMEM; XLA fuses the upcast into the
matmul operand pipeline, so no bf16 copy lands in HBM).

Placement in the RL loop (`RLConfig.rollout_quant="int8"`):
- generation samples from the quantized base + EXACT bf16 LoRA/embed/norm
  (adapters ride on top in-graph, so policy updates reach the sampler
  immediately — same freshness story as the bf16 path);
- the scoring pass and the update always run the exact bf16 weights. With
  the default recomputed-old-logprobs scoring, the quantization mismatch
  enters the gradient as a small unmeasured off-policy bias that the
  PPO-clip TOLERATES (the same way it tolerates `rollout_ahead`'s
  one-update staleness — the reference leans on the same tolerance,
  `REINFORCE/reinforce_trainer.py:637`). To have the ratio MEASURE and
  importance-correct the quantized behavior distribution, enable
  `sampler_logprob_capture=True`: the captured logprobs then come from the
  quantized policy that actually sampled, which is the correct π_behavior.

Under LoRA the base projections are FROZEN, so quantization happens once at
trainer construction; under full fine-tuning the trainer re-quantizes after
each update (a jitted elementwise pass, negligible next to the update).

Per-output-channel symmetric scheme: y[o] = Σ_i x[i]·w[i,o] with
w[i,o] ≈ q[i,o]·s[o] gives y ≈ (x @ q)·s — one multiply per output element,
fused into the matmul epilogue.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# the stacked-kernel projections of core/model.py's layer tree
QUANT_PROJS = (
    "q_proj", "k_proj", "v_proj", "o_proj",
    "gate_proj", "up_proj", "down_proj",
)


def quantize_kernel(w: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """[..., in, out] -> (int8 [..., in, out], f32 scale [..., 1, out])."""
    wf = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(wf), axis=-2, keepdims=True)
    scale = jnp.maximum(absmax / 127.0, 1e-12)
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_kernel(q: jnp.ndarray, scale: jnp.ndarray,
                      dtype=jnp.bfloat16) -> jnp.ndarray:
    return (q.astype(jnp.float32) * scale).astype(dtype)


@jax.jit
def quantize_layers(layers: dict) -> dict:
    """Replace each projection's `kernel` with (`kernel_q`, `kernel_scale`).

    Non-kernel leaves (biases, layernorms) pass through by reference.
    """
    out = {}
    for name, entry in layers.items():
        if isinstance(entry, dict) and name in QUANT_PROJS:
            e = dict(entry)
            q, scale = quantize_kernel(e.pop("kernel"))
            e["kernel_q"] = q
            e["kernel_scale"] = scale
            out[name] = e
        else:
            out[name] = entry
    return out


def rollout_view(params: dict, quant_layers: dict) -> dict:
    """Splice the quantized layer tree into the LIVE param tree: embeddings,
    norms and LoRA adapters stay the caller's (fresh, trainable) arrays —
    only the frozen projection kernels are swapped for int8."""
    return {**params, "layers": quant_layers}
