from nanorlhf_tpu.data.tokenizer import ToyTokenizer, load_tokenizer
from nanorlhf_tpu.data.datasets import PromptDataset, load_prompt_dataset, synthetic_prompts

__all__ = [
    "ToyTokenizer",
    "load_tokenizer",
    "PromptDataset",
    "load_prompt_dataset",
    "synthetic_prompts",
]
