"""Prompt dataset pipeline.

The reference extracts the first "Human:" turn from Anthropic/hh-rlhf, wraps
it in the Qwen chat template, pre-tokenizes with dataset.map, and feeds a
shuffling, drop-last dataloader of *left-padded* prompt id tensors
(`/root/reference/GRPO/grpo.py:247-270`, `GRPO/grpo_trainer.py:302-310`).

This module reproduces that shape: `PromptDataset` holds pre-tokenized,
left-padded prompts; `load_prompt_dataset` sources them from HF datasets when
available locally (zero-egress builds fall back to synthetic prompts).
"""

from __future__ import annotations

import dataclasses
import multiprocessing
import os

import numpy as np


@dataclasses.dataclass
class PromptDataset:
    input_ids: np.ndarray   # [N, T] left-padded
    pad_token_id: int

    def __len__(self) -> int:
        return self.input_ids.shape[0]

    def loader(self, batch_size: int, seed: int):
        """Infinite shuffling iterator, drop-last — dataloader parity with
        `DataLoader(shuffle=True, drop_last=True)` (`grpo_trainer.py:302-310`)."""
        rng = np.random.default_rng(seed)
        n = len(self)
        assert n >= batch_size, f"dataset ({n}) smaller than batch ({batch_size})"
        while True:
            perm = rng.permutation(n)
            for i in range(0, n - batch_size + 1, batch_size):
                yield self.input_ids[perm[i : i + batch_size]]


def _left_pad(seqs: list[list[int]], pad_id: int, max_len: int | None = None) -> np.ndarray:
    # numpy is already optimal here (per-row assignment); the native pack
    # kernels exist for callers that hold pre-flattened token buffers
    max_len = max_len or max(len(s) for s in seqs)
    out = np.full((len(seqs), max_len), pad_id, np.int32)
    for i, s in enumerate(seqs):
        s = s[-max_len:]
        out[i, max_len - len(s):] = s
    return out


def extract_hh_question(chosen: str) -> str:
    """First human turn of an hh-rlhf transcript — mirrors the launcher's
    string surgery (`GRPO/grpo.py:249-258`)."""
    text = chosen.split("Human:", 1)[-1]
    return text.split("Assistant:", 1)[0].strip()


def synthetic_prompts(n: int, tokenizer, seed: int = 0, min_words: int = 4,
                      max_words: int = 24) -> list[str]:
    """Deterministic offline prompt corpus for smoke runs and tests."""
    rng = np.random.default_rng(seed)
    topics = [
        "how do I learn to cook pasta properly",
        "explain why the sky appears blue at noon",
        "what is a good plan for saving money",
        "describe the history of the printing press",
        "how can I improve my running endurance",
        "what makes a good friendship last long",
        "explain how photosynthesis works in plants",
        "what should I consider when adopting a dog",
    ]
    prompts = []
    for i in range(n):
        base = topics[int(rng.integers(len(topics)))]
        words = base.split()
        k = int(rng.integers(min_words, min(max_words, len(words)) + 1))
        prompts.append(" ".join(words[:k]))
    return prompts


_WORKER_TOK = None


def _pool_init(tok):
    global _WORKER_TOK
    _WORKER_TOK = tok


def _pool_encode_chunk(args):
    texts, max_len = args
    return [_WORKER_TOK.encode(t)[:max_len] for t in texts]


def encode_texts(tokenizer, texts, max_prompt_len: int,
                 num_proc: int | None = None) -> list[list[int]]:
    """Tokenize a text list — `dataset.map(num_proc=6)` parity
    (`/root/reference/GRPO/grpo.py:266-268`); round-1 tokenized serially,
    which bites at the reference's 250k-episode scale.

    Three tiers, all byte-identical to `[tokenizer.encode(t)[:max] for t in
    texts]`:
    - HF fast tokenizers: ONE batched call — the Rust backend parallelizes
      internally, no process fan-out or pickling needed;
    - picklable slow tokenizers: fork pool over chunks (opt out with
      `parallel_safe = False` — e.g. ToyTokenizer, whose decode cache must
      populate in the parent);
    - fallback: serial.
    """
    num_proc = num_proc if num_proc is not None else min(6, os.cpu_count() or 1)
    if getattr(tokenizer, "is_fast", False):
        ids = tokenizer(list(texts))["input_ids"]
        return [row[:max_prompt_len] for row in ids]
    if (
        num_proc > 1
        and len(texts) >= 16 * num_proc
        and getattr(tokenizer, "parallel_safe", True)
    ):
        ctx = multiprocessing.get_context("fork")
        chunk = -(-len(texts) // (num_proc * 4))
        chunks = [
            (texts[i : i + chunk], max_prompt_len)
            for i in range(0, len(texts), chunk)
        ]
        try:
            with ctx.Pool(num_proc, initializer=_pool_init,
                          initargs=(tokenizer,)) as pool:
                # bounded wait: forking a threaded (JAX) parent can wedge a
                # child on an inherited lock, and a deadlock is not an
                # Exception — map_async + timeout converts it into one so
                # the serial fallback actually runs (same hazard the grader
                # bounds with join+terminate)
                timeout_s = max(60.0, 0.05 * len(texts))
                parts = pool.map_async(_pool_encode_chunk, chunks).get(timeout_s)
            return [row for part in parts for row in part]
        except Exception:
            pass  # unpicklable tokenizer / wedged pool — serial fallback below
    return [tokenizer.encode(t)[:max_prompt_len] for t in texts]


def _load_hf_dataset(name: str, split: str):
    """Local HF cache first (fast, no network retries); fall back to a normal
    online load when the cache misses.

    The offline switch must flip the already-imported module constants —
    `huggingface_hub`/`datasets` read HF_HUB_OFFLINE from the environment at
    *import* time, so env vars alone do nothing once they're loaded. Scoped
    and restored: it must not leak into later hub/transformers calls.
    """
    import datasets
    import datasets.config as dcfg
    import huggingface_hub.constants as hub_c
    from huggingface_hub.utils import reset_sessions

    # datasets < 2.19 has no HF_HUB_OFFLINE attribute; fall back to the older
    # HF_DATASETS_OFFLINE name so the attribute write targets what exists
    dcfg_attr = "HF_HUB_OFFLINE" if hasattr(dcfg, "HF_HUB_OFFLINE") else "HF_DATASETS_OFFLINE"
    saved = (hub_c.HF_HUB_OFFLINE, getattr(dcfg, dcfg_attr, False))
    try:
        hub_c.HF_HUB_OFFLINE = True
        setattr(dcfg, dcfg_attr, True)
        reset_sessions()  # drop cached sessions so they re-read the flag
        return datasets.load_dataset(name, split=split)
    except Exception:
        pass
    finally:
        hub_c.HF_HUB_OFFLINE = saved[0]
        setattr(dcfg, dcfg_attr, saved[1])
        # sessions created during the offline window baked in OfflineAdapter;
        # reset again so post-restore hub calls get fresh online sessions
        reset_sessions()
    return datasets.load_dataset(name, split=split)  # online attempt


def load_prompt_dataset(
    name: str,
    tokenizer,
    split: str = "train",
    max_prompt_len: int = 256,
    limit: int | None = None,
    seed: int = 0,
    num_proc: int | None = None,
    cache_dir: str | None = None,
) -> PromptDataset:
    """hh-rlhf-style prompt dataset; `synthetic:<n>` for the offline corpus.

    Applies the chat template (`GRPO/grpo.py:259-263`) then tokenizes
    (multiprocess/batched, `num_proc` as `dataset.map(num_proc=6)`) and
    left-pads to the batch max — matching the reference's pre-tokenized
    dataloader contract.

    `cache_dir` enables the native token cache (`data/token_cache.py`) —
    the Arrow-cache role `dataset.map` plays for the reference: re-launches
    with identical (source, split, limit, seed, max len, tokenizer) mmap
    the encoded corpus instead of re-tokenizing it.
    """
    # HF sources load their texts BEFORE the cache check so the fingerprint
    # can cover the corpus CONTENT, not just its name: an upstream revision
    # change (or a cache dir shared across hosts with different local
    # snapshots) must miss and re-tokenize, the same way
    # grpo_r1.build_prompt_dataset hashes its corpus (ADVICE r3). The cache
    # still skips the expensive half (templating + tokenization, ~50× the
    # raw-text scan); `_load_hf_dataset` is offline-first, so a warm HF
    # cache keeps working without network. `synthetic:` corpora are fully
    # determined by (name, seed, tokenizer identity), so they keep the
    # load-free params-only fast path.
    texts = None
    if not name.startswith("synthetic"):
        ds = _load_hf_dataset(name, split)
        texts = [extract_hh_question(row["chosen"]) for row in ds]
        if limit:
            texts = texts[:limit]

    cache_path = fp = None
    if cache_dir is not None:
        import hashlib

        from nanorlhf_tpu.data.token_cache import (
            corpus_fingerprint, load_token_cache, save_token_cache,
            tokenizer_identity)

        fp_kw = dict(
            name=name, split=split, limit=limit, seed=seed,
            max_prompt_len=max_prompt_len, tok=tokenizer_identity(tokenizer),
        )
        if texts is not None:
            h = hashlib.blake2b(digest_size=8)
            for t in texts:
                h.update(t.encode())
                h.update(b"\x1f")
            fp_kw["content"] = h.hexdigest()
        fp = corpus_fingerprint(**fp_kw)
        cache_path = os.path.join(cache_dir, f"prompts-{fp:016x}.tok")
        cached = load_token_cache(cache_path, fp)
        if cached is not None:
            return PromptDataset(
                _left_pad(cached, tokenizer.pad_token_id), tokenizer.pad_token_id
            )

    if texts is None:
        _, _, count = name.partition(":")
        texts = synthetic_prompts(int(count) if count else 512, tokenizer, seed)
        if limit:
            texts = texts[:limit]

    templated = [
        tokenizer.apply_chat_template(
            [{"role": "user", "content": t}], tokenize=False, add_generation_prompt=True
        )
        for t in texts
    ]
    ids = encode_texts(tokenizer, templated, max_prompt_len, num_proc=num_proc)
    if cache_path is not None:
        save_token_cache(cache_path, ids, fp)
    return PromptDataset(_left_pad(ids, tokenizer.pad_token_id), tokenizer.pad_token_id)
