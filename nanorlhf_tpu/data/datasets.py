"""Prompt dataset pipeline.

The reference extracts the first "Human:" turn from Anthropic/hh-rlhf, wraps
it in the Qwen chat template, pre-tokenizes with dataset.map, and feeds a
shuffling, drop-last dataloader of *left-padded* prompt id tensors
(`/root/reference/GRPO/grpo.py:247-270`, `GRPO/grpo_trainer.py:302-310`).

This module reproduces that shape: `PromptDataset` holds pre-tokenized,
left-padded prompts; `load_prompt_dataset` sources them from HF datasets when
available locally (zero-egress builds fall back to synthetic prompts).
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class PromptDataset:
    input_ids: np.ndarray   # [N, T] left-padded
    pad_token_id: int

    def __len__(self) -> int:
        return self.input_ids.shape[0]

    def loader(self, batch_size: int, seed: int):
        """Infinite shuffling iterator, drop-last — dataloader parity with
        `DataLoader(shuffle=True, drop_last=True)` (`grpo_trainer.py:302-310`)."""
        rng = np.random.default_rng(seed)
        n = len(self)
        assert n >= batch_size, f"dataset ({n}) smaller than batch ({batch_size})"
        while True:
            perm = rng.permutation(n)
            for i in range(0, n - batch_size + 1, batch_size):
                yield self.input_ids[perm[i : i + batch_size]]


def _left_pad(seqs: list[list[int]], pad_id: int, max_len: int | None = None) -> np.ndarray:
    # numpy is already optimal here (per-row assignment); the native pack
    # kernels exist for callers that hold pre-flattened token buffers
    max_len = max_len or max(len(s) for s in seqs)
    out = np.full((len(seqs), max_len), pad_id, np.int32)
    for i, s in enumerate(seqs):
        s = s[-max_len:]
        out[i, max_len - len(s):] = s
    return out


def extract_hh_question(chosen: str) -> str:
    """First human turn of an hh-rlhf transcript — mirrors the launcher's
    string surgery (`GRPO/grpo.py:249-258`)."""
    text = chosen.split("Human:", 1)[-1]
    return text.split("Assistant:", 1)[0].strip()


def synthetic_prompts(n: int, tokenizer, seed: int = 0, min_words: int = 4,
                      max_words: int = 24) -> list[str]:
    """Deterministic offline prompt corpus for smoke runs and tests."""
    rng = np.random.default_rng(seed)
    topics = [
        "how do I learn to cook pasta properly",
        "explain why the sky appears blue at noon",
        "what is a good plan for saving money",
        "describe the history of the printing press",
        "how can I improve my running endurance",
        "what makes a good friendship last long",
        "explain how photosynthesis works in plants",
        "what should I consider when adopting a dog",
    ]
    prompts = []
    for i in range(n):
        base = topics[int(rng.integers(len(topics)))]
        words = base.split()
        k = int(rng.integers(min_words, min(max_words, len(words)) + 1))
        prompts.append(" ".join(words[:k]))
    return prompts


def _load_hf_dataset(name: str, split: str):
    """Local HF cache first (fast, no network retries); fall back to a normal
    online load when the cache misses.

    The offline switch must flip the already-imported module constants —
    `huggingface_hub`/`datasets` read HF_HUB_OFFLINE from the environment at
    *import* time, so env vars alone do nothing once they're loaded. Scoped
    and restored: it must not leak into later hub/transformers calls.
    """
    import datasets
    import datasets.config as dcfg
    import huggingface_hub.constants as hub_c
    from huggingface_hub.utils import reset_sessions

    # datasets < 2.19 has no HF_HUB_OFFLINE attribute; fall back to the older
    # HF_DATASETS_OFFLINE name so the attribute write targets what exists
    dcfg_attr = "HF_HUB_OFFLINE" if hasattr(dcfg, "HF_HUB_OFFLINE") else "HF_DATASETS_OFFLINE"
    saved = (hub_c.HF_HUB_OFFLINE, getattr(dcfg, dcfg_attr, False))
    try:
        hub_c.HF_HUB_OFFLINE = True
        setattr(dcfg, dcfg_attr, True)
        reset_sessions()  # drop cached sessions so they re-read the flag
        return datasets.load_dataset(name, split=split)
    except Exception:
        pass
    finally:
        hub_c.HF_HUB_OFFLINE = saved[0]
        setattr(dcfg, dcfg_attr, saved[1])
        # sessions created during the offline window baked in OfflineAdapter;
        # reset again so post-restore hub calls get fresh online sessions
        reset_sessions()
    return datasets.load_dataset(name, split=split)  # online attempt


def load_prompt_dataset(
    name: str,
    tokenizer,
    split: str = "train",
    max_prompt_len: int = 256,
    limit: int | None = None,
    seed: int = 0,
) -> PromptDataset:
    """hh-rlhf-style prompt dataset; `synthetic:<n>` for the offline corpus.

    Applies the chat template (`GRPO/grpo.py:259-263`) then tokenizes and
    left-pads to the batch max — matching the reference's pre-tokenized
    dataloader contract.
    """
    if name.startswith("synthetic"):
        _, _, count = name.partition(":")
        texts = synthetic_prompts(int(count) if count else 512, tokenizer, seed)
    else:
        ds = _load_hf_dataset(name, split)
        texts = [extract_hh_question(row["chosen"]) for row in ds]

    if limit:
        texts = texts[:limit]

    templated = [
        tokenizer.apply_chat_template(
            [{"role": "user", "content": t}], tokenize=False, add_generation_prompt=True
        )
        for t in texts
    ]
    ids = [tokenizer.encode(t)[:max_prompt_len] for t in templated]
    return PromptDataset(_left_pad(ids, tokenizer.pad_token_id), tokenizer.pad_token_id)
