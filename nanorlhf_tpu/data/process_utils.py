"""Per-benchmark dataset item processors.

Capability parity with the reference's data-processing toolkit
(`/root/reference/examples/r1-v0/utils/data_processing/process_utils.py:5-158`):
each processor takes one raw benchmark item (a dict in that benchmark's
native schema) and yields zero or more normalized samples:

    {"dataset": <name>, "id": ..., "messages": [{"role","content"}, ...],
     "answer": <str | list[str]>, ...extra benchmark fields}

Processors are host-side, pure-Python generators (an item may be skipped by
yielding nothing — e.g. MATH items whose gold answer fails extraction).
A registry maps benchmark names to processors, mirroring how the reference's
eval scripts pick a processor per `dataset` field.
"""

from __future__ import annotations

import re
from typing import Callable, Iterator

from nanorlhf_tpu.rewards.answer_extraction import extract_math_answer
from nanorlhf_tpu.rewards.math_grader import normalize_math_answer

Sample = dict
Processor = Callable[[dict], Iterator[Sample]]


def process_gsm8k_test(item: dict) -> Iterator[Sample]:
    """GSM8K: strip calculator annotations `<<...>>`, append the boxed
    answer sentence, de-comma the gold answer (ref `process_utils.py:5-15`)."""
    cot = re.sub(r"<<[^<>]*>>", "", item["cot"])
    yield {
        "dataset": "gsm8k-cot",
        "id": item["id"],
        "messages": [
            {"role": "user", "content": item["question"]},
            {
                "role": "assistant",
                "content": cot
                + "\nSo the answer is $\\boxed{"
                + item["answer"].strip()
                + "}$.",
            },
        ],
        "answer": item["answer"].replace(",", ""),
    }


def process_math_test(item: dict) -> Iterator[Sample]:
    """MATH: gold answer extracted from the official solution; items whose
    solution yields no answer are dropped (ref `process_utils.py:17-35`).
    The solution text is re-wrapped one sentence per line."""
    question = item["problem"]
    try:
        answer = extract_math_answer(question, item["solution"], task="cot")
    except Exception:
        return
    if not answer:
        return
    yield {
        "dataset": "math-cot",
        "id": item["id"],
        "level": item.get("level"),
        "type": item.get("type"),
        "category": item.get("category"),
        "messages": [
            {"role": "user", "content": question},
            {
                "role": "assistant",
                "content": "\n".join(
                    re.split(r"(?<=\.) (?=[A-Z])", item["solution"])
                ),
            },
        ],
        "answer": answer,
    }


def process_math_sat(item: dict) -> Iterator[Sample]:
    """SAT-math: reflow 'A) ... B) ...' options into '(A) ... (B) ...' and
    append the choice prompt (ref `process_utils.py:37-55`)."""
    options = item["options"].strip()
    if not options.startswith("A"):
        raise ValueError(f"SAT options must start with 'A': {options[:20]!r}")
    options = "(" + options
    for ch in "BCDEFG":
        options = re.sub(rf" {ch}\) ", f" ({ch}) ", options)
    question = (
        f"{item['question'].strip()}\n"
        "What of the following is the right choice? Explain your answer.\n"
        f"{options.strip()}"
    )
    yield {
        "dataset": "math_sat",
        "id": item["id"],
        "language": "en",
        "messages": [
            {"role": "user", "content": question},
            {"role": "assistant", "content": item["Answer"]},
        ],
        "answer": item["Answer"],
    }


def process_ocwcourses(item: dict) -> Iterator[Sample]:
    """OCW Courses (ref `process_utils.py:57-69`)."""
    yield {
        "dataset": "OCWCourses",
        "id": item["id"],
        "language": "en",
        "messages": [
            {"role": "user", "content": item["problem"].strip()},
            {"role": "assistant", "content": item["solution"].strip()},
        ],
        "answer": item["answer"],
    }


def process_mmlu_stem(item: dict) -> Iterator[Sample]:
    """MMLU-STEM: label the four options (A)-(D) and append the choice
    prompt (ref `process_utils.py:71-89`)."""
    options = [
        f"({label}) {str(option).strip()}"
        for label, option in zip("ABCD", item["options"])
    ]
    question = (
        f"{item['question'].strip()}\n"
        "What of the following is the right choice? Explain your answer.\n"
        f"{', '.join(options)}"
    )
    yield {
        "dataset": "MMLU-STEM",
        "id": item["id"],
        "language": "en",
        "messages": [
            {"role": "user", "content": question},
            {"role": "assistant", "content": item["answer"]},
        ],
        "answer": item["answer"],
    }


def process_mgsm_zh(item: dict) -> Iterator[Sample]:
    """MGSM-zh: de-comma the numeric answer in place (ref
    `process_utils.py:91-93`)."""
    out = dict(item)
    out["answer"] = out["answer"].replace(",", "")
    yield out


def process_cmath(item: dict) -> Iterator[Sample]:
    """CMATH (ref `process_utils.py:95-107`)."""
    yield {
        "dataset": "cmath",
        "id": item["id"],
        "grade": item.get("grade"),
        "reasoning_step": item.get("reasoning_step"),
        "messages": [
            {"role": "user", "content": item["question"].strip()},
            {"role": "assistant", "content": ""},
        ],
        "answer": item["golden"].strip().replace(",", ""),
    }


def process_agieval_gaokao_math_cloze(item: dict) -> Iterator[Sample]:
    """Gaokao math cloze: multi-answer gold split on ';' and normalized
    (ref `process_utils.py:109-119`)."""
    yield {
        "dataset": "agieval-gaokao-math-cloze",
        "id": item["id"],
        "messages": [
            {"role": "user", "content": item["question"].strip()},
            {"role": "assistant", "content": ""},
        ],
        "answer": [
            normalize_math_answer(ans)
            for ans in item["answer"].strip().split(";")
        ],
    }


def process_agieval_gaokao_mathqa(item: dict) -> Iterator[Sample]:
    """Gaokao mathqa: options arrive as '(A)...'; reflow to 'A: ...'
    (ref `process_utils.py:121-141`)."""
    question = item["question"].strip()
    options = []
    for option in item["options"]:
        option = option.strip()
        if len(option) < 4 or not (
            option[0] == "(" and option[2] == ")" and option[1] in "ABCD"
        ):
            raise ValueError(f"malformed gaokao option: {option[:10]!r}")
        options.append(f"{option[1]}: {option[3:].strip()}")
    # the reference interpolates the Python list (its prompt literally shows
    # "['A: 1', ...]", `process_utils.py:133`) — joined cleanly here
    yield {
        "dataset": "agieval-gaokao-mathqa",
        "id": item["id"],
        "messages": [
            {"role": "user", "content": f"{question}\n{' '.join(options)}"},
            {"role": "assistant", "content": ""},
        ],
        "answer": item["label"],
    }


def process_agieval_gaokao_mathqa_few_shot_cot_test(
    item: dict,
) -> Iterator[Sample]:
    """Gaokao mathqa few-shot variant: Chinese choice prompt, options joined
    inline (ref `process_utils.py:143-156`)."""
    question = item["question"].strip().rstrip("\\")
    options = " ".join(opt.strip() for opt in item["options"])
    yield {
        "dataset": "agieval-gaokao-mathqa",
        "id": item["id"],
        "messages": [
            {
                "role": "user",
                "content": f"{question}\n从以下选项中选择:    {options}",
            },
            {"role": "assistant", "content": ""},
        ],
        "answer": item["label"],
    }


def process_minif2f_isabelle(item: dict) -> Iterator[Sample]:
    """miniF2F (Isabelle): wrap the informal statement+proof as a comment
    above the formal statement (ref `process_utils.py:158-169`)."""
    question = (
        f"(*### Problem\n\n{item['informal_statement'].strip()}\n\n"
        f"### Solution\n\n{item['informal_proof'].strip()} *)\n\n"
        f"Formal:\n{item['formal_statement'].strip()}"
    )
    yield {
        "dataset": "minif2f-isabelle",
        "id": item["id"],
        "messages": [
            {"role": "user", "content": question},
            {"role": "assistant", "content": ""},
        ],
        "answer": "placeholder",
    }


PROCESSORS: dict[str, Processor] = {
    "gsm8k": process_gsm8k_test,
    "gsm8k-cot": process_gsm8k_test,
    "math": process_math_test,
    "math-cot": process_math_test,
    "math_sat": process_math_sat,
    "sat": process_math_sat,
    "ocwcourses": process_ocwcourses,
    "ocw": process_ocwcourses,
    "mmlu_stem": process_mmlu_stem,
    "mmlu-stem": process_mmlu_stem,
    "mgsm-zh": process_mgsm_zh,
    "mgsm_zh": process_mgsm_zh,
    "cmath": process_cmath,
    "agieval-gaokao-math-cloze": process_agieval_gaokao_math_cloze,
    "agieval-gaokao-mathqa": process_agieval_gaokao_mathqa,
    "agieval-gaokao-mathqa-few-shot": (
        process_agieval_gaokao_mathqa_few_shot_cot_test
    ),
    "minif2f-isabelle": process_minif2f_isabelle,
}


def get_processor(name: str) -> Processor:
    """Look up a benchmark item processor by (normalized) dataset name."""
    key = name.strip().lower()
    if key in PROCESSORS:
        return PROCESSORS[key]
    raise KeyError(
        f"no dataset processor for {name!r}; known: {sorted(set(PROCESSORS))}"
    )


def process_items(name: str, items: list[dict]) -> list[Sample]:
    """Run a benchmark's processor over raw items, flattening the yields."""
    proc = get_processor(name)
    out: list[Sample] = []
    for item in items:
        out.extend(proc(item))
    return out
