"""Tokenized-corpus cache — `dataset.map`'s Arrow-cache role, TPU-host native.

The reference leans on HF datasets' native Arrow cache so repeated launches
skip tokenization (`/root/reference/GRPO/grpo.py:266-268`). This module is
that capability for the prompt pipeline: one binary file (format defined by
`native/token_cache.cpp`) holding the ragged encoded corpus, keyed by a
fingerprint of everything that could change the tokens. Readers mmap the
file, so startup cost is O(pages touched) regardless of corpus size.

The C++ path (ctypes) and the Python fallback here read and write the SAME
byte format — caches are interchangeable across hosts with and without a
toolchain. Tests pin the interop both ways.
"""

from __future__ import annotations

import hashlib
import os
import struct

import numpy as np

from nanorlhf_tpu.native import (
    flatten_rows,
    token_cache_open_native,
    token_cache_write_native,
)

_MAGIC = 0x4E524C48544F4B31
_HEADER = struct.Struct("<QQQ")  # magic, n_rows, fingerprint


def corpus_fingerprint(**kwargs) -> int:
    """Stable 64-bit fingerprint of the tokenization inputs (source name,
    split, limit, seed, max len, tokenizer identity...)."""
    text = "\x1f".join(f"{k}={kwargs[k]}" for k in sorted(kwargs))
    return int.from_bytes(
        hashlib.blake2b(text.encode(), digest_size=8).digest(), "little"
    )


def tokenizer_identity(tokenizer) -> str:
    """Best-effort identity string: class, vocab size, name/path, AND a hash
    of the chat template — the pipeline templates before encoding, so a
    changed/custom `chat_template` under the same name_or_path must miss."""
    template = getattr(tokenizer, "chat_template", None)
    template_h = hashlib.blake2b(
        str(template).encode(), digest_size=8
    ).hexdigest() if template is not None else None
    return "/".join(
        str(x) for x in (
            type(tokenizer).__name__,
            getattr(tokenizer, "vocab_size", None),
            getattr(tokenizer, "name_or_path", None),
            template_h,
        )
    )


def _write_py(path: str, rows, fingerprint: int) -> bool:
    """Python fallback writer — byte-identical to token_cache_write (both
    flatten via the shared `native.flatten_rows`)."""
    offsets, flat = flatten_rows(rows)
    tmp = f"{path}.{os.getpid()}.tmp"
    try:
        with open(tmp, "wb") as f:
            f.write(_HEADER.pack(_MAGIC, len(rows), fingerprint & (2**64 - 1)))
            f.write(offsets.tobytes())
            f.write(flat.tobytes())
        os.replace(tmp, path)
        return True
    except OSError:
        try:
            os.remove(tmp)
        except OSError:
            pass
        return False


def _read_py(path: str, fingerprint: int):
    """Python fallback reader: validated np.memmap views (zero-copy)."""
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            head = f.read(_HEADER.size)
        magic, n, fp = _HEADER.unpack(head)
        if magic != _MAGIC or fp != (fingerprint & (2**64 - 1)):
            return None
        # bound the u64 n_rows BEFORE any offset arithmetic: a corrupt
        # header otherwise overflows the memmap length (OverflowError, not
        # the ValueError the old catch assumed) — mirror of the native
        # reader's check (ADVICE r3)
        if n >= (size - _HEADER.size) // 8:
            return None
        offsets = np.memmap(path, "<i8", "r", _HEADER.size, (n + 1,))
        total = int(offsets[n])
        if total < 0:
            return None
        expect = _HEADER.size + (n + 1) * 8 + total * 4
        if size != expect:
            return None
        flat = np.memmap(path, "<i4", "r", _HEADER.size + (n + 1) * 8,
                         (total,)) if total else np.empty(0, np.int32)
        return offsets, flat, int(n)
    except (OSError, ValueError, struct.error):
        return None


def save_token_cache(path: str, rows, fingerprint: int) -> bool:
    """Write the corpus cache (native writer, Python fallback)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    if token_cache_write_native(path, rows, fingerprint):
        return True
    return _write_py(path, rows, fingerprint)


def load_token_cache(path: str, fingerprint: int):
    """Return list-like of int32 row arrays, or None on miss/mismatch.

    Rows are zero-copy views into the mapping; the mapping lives as long as
    the returned list holds references (native views carry the TokenCacheView
    keeper; memmap rows keep the memmap alive)."""
    view = token_cache_open_native(path, fingerprint)
    if view is not None:
        # the list keeps the mmap alive; rows are zero-copy views into it
        return _KeptList([view.row(i) for i in range(view.n_rows)], view)
    got = _read_py(path, fingerprint)
    if got is None:
        return None
    offsets, flat, n = got
    return _KeptList([flat[offsets[i]:offsets[i + 1]] for i in range(n)],
                     (offsets, flat))


class _KeptList(list):
    """List that keeps the underlying mapping object alive."""

    def __init__(self, rows, keeper):
        super().__init__(rows)
        self._keeper = keeper
