"""Tokenizer protocol: HF fast tokenizers when available, a toy fallback.

Tokenization stays host-side/CPU, outside the compiled graph — same split as
the reference, which runs Rust HF tokenizers on the host
(`/root/reference/GRPO/grpo.py:209-216`, SURVEY.md §2.2). The toy tokenizer
exists because this build environment has zero egress: smoke tests and CPU
integration runs need a self-contained vocabulary.

Both implementations expose the slice of the HF interface the trainers use:
`pad_token_id`, `eos_token_id`, `eos_token`, `encode`, `batch_decode`,
`__call__(text, padding='max_length' | longest-style)`.
"""

from __future__ import annotations

import re
import zlib


class ToyTokenizer:
    """Whitespace/word-piece-free toy tokenizer with a stable hashed vocab.

    Deterministic, reversible for its own output (each id maps to one word),
    with the special tokens the trainers rely on: `[PAD]`=0 (the reference
    adds a `[PAD]` token, `GRPO/grpo.py:210-216`) and an EOS.
    """

    def __init__(self, vocab_size: int = 4096):
        self.vocab_size = vocab_size
        self.pad_token = "[PAD]"
        self.eos_token = "</s>"
        self.pad_token_id = 0
        self.eos_token_id = 1
        self.unk_token_id = 2
        self._id_to_word: dict[int, str] = {}
        # the decode cache fills during encode; encoding in a forked pool
        # would leave the PARENT's cache empty and decode to <unk:N> — keep
        # this tokenizer on the serial path (see data/datasets.encode_texts)
        self.parallel_safe = False

    def _word_id(self, word: str) -> int:
        if word == self.pad_token:
            return self.pad_token_id
        if word == self.eos_token:
            return self.eos_token_id
        # crc32, not hash(): Python's str hash is salted per process, which
        # would silently desync vocab across restarts/hosts
        h = 3 + (zlib.crc32(word.encode()) % (self.vocab_size - 3))
        self._id_to_word.setdefault(h, word)
        return h

    def encode(self, text: str) -> list[int]:
        words = re.findall(r"\S+", text)
        return [self._word_id(w) for w in words]

    def decode(self, ids, skip_special_tokens: bool = False) -> str:
        out = []
        for i in ids:
            i = int(i)
            if i == self.pad_token_id:
                if not skip_special_tokens:
                    out.append(self.pad_token)
            elif i == self.eos_token_id:
                if not skip_special_tokens:
                    out.append(self.eos_token)
            else:
                out.append(self._id_to_word.get(i, f"<unk:{i}>"))
        return " ".join(out)

    def batch_decode(self, batch, skip_special_tokens: bool = False) -> list[str]:
        return [self.decode(row, skip_special_tokens) for row in batch]

    def apply_chat_template(self, messages, tokenize=False, add_generation_prompt=True):
        text = " ".join(m["content"] for m in messages)
        return f"<user> {text} <assistant>"


def load_tokenizer(name_or_path: str):
    """HF AutoTokenizer with the reference's [PAD] handling; toy fallback.

    `toy:<vocab_size>` explicitly requests the toy tokenizer.
    """
    if name_or_path.startswith("toy"):
        _, _, size = name_or_path.partition(":")
        return ToyTokenizer(int(size) if size else 4096)
    from transformers import AutoTokenizer

    tok = AutoTokenizer.from_pretrained(name_or_path, padding_side="left")
    if tok.pad_token is None:
        # same move as the reference (`GRPO/grpo.py:210-216`)
        tok.add_special_tokens({"pad_token": "[PAD]"})
    return tok
