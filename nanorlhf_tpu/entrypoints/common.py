"""Shared launcher plumbing: model/tokenizer/dataset/reward resolution.

The reference keeps "ALL setting is on the file you run" (`README.md:34`) —
each launcher is a config literal plus loading code. These helpers keep the
launchers that thin while handling the environments a TPU build actually
meets: real HF checkpoints when present on disk, a fully offline demo mode
(random-init model + toy tokenizer + synthetic prompts) otherwise, so every
launcher runs end-to-end even with zero egress.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from nanorlhf_tpu.core import ModelConfig, init_params
from nanorlhf_tpu.core.params import load_hf_checkpoint
from nanorlhf_tpu.data import ToyTokenizer, load_prompt_dataset, load_tokenizer
from nanorlhf_tpu.rewards import make_rule_reward
from nanorlhf_tpu.rewards.builders import make_torch_rm_reward
from nanorlhf_tpu.trainer import RLConfig, RLTrainer


def resolve_model(sft_model_path: str, seed: int = 0, attention_impl: str = "auto"):
    """(ModelConfig, params, tokenizer): HF checkpoint dir → load it; else an
    offline demo model (1.5B-shaped unless path says 'tiny')."""
    import dataclasses

    if sft_model_path and os.path.isdir(sft_model_path):
        config, params = load_hf_checkpoint(sft_model_path)
        tokenizer = load_tokenizer(sft_model_path)
    else:
        print(f"[offline demo] '{sft_model_path}' not found locally — "
              "random-init model + toy tokenizer")
        path = (sft_model_path or "").lower()
        llama = "llama" in path  # Llama-family geometry (no attention biases)
        if "tiny" in path:
            config = ModelConfig.qwen2_tiny(vocab_size=4096)
            if llama:  # e.g. "TinyLlama-...": tiny shape, llama family
                import dataclasses

                config = dataclasses.replace(
                    config, attention_bias=False, rope_theta=500_000.0
                )
        elif llama:
            config = ModelConfig.llama3_2_1b()
        else:
            config = ModelConfig.qwen2_1_5b()
        tokenizer = ToyTokenizer(vocab_size=min(4096, config.vocab_size))
        params = init_params(config, jax.random.PRNGKey(seed), jnp.bfloat16)
    if attention_impl != config.attention_impl:
        config = dataclasses.replace(config, attention_impl=attention_impl)
    return config, params, tokenizer


def resolve_dataset(cfg: RLConfig, tokenizer, max_prompt_len: int = 256):
    """hh-rlhf when the datasets cache has it; synthetic corpus otherwise."""
    name = cfg.train_dataset_name
    cache = cfg.dataset_cache_dir
    try:
        return load_prompt_dataset(name, tokenizer, split=cfg.train_dataset_split,
                                   max_prompt_len=max_prompt_len,
                                   cache_dir=cache)
    except Exception as e:  # zero-egress / no local cache
        print(f"[offline demo] dataset '{name}' unavailable ({type(e).__name__}) — "
              "synthetic prompts")
        return load_prompt_dataset("synthetic:512", tokenizer,
                                   max_prompt_len=max_prompt_len)


def resolve_rm_reward(reward_model_path: str, batch_size: int = 16):
    """Torch host-side RM when its checkpoint exists (deberta path,
    `GRPO/grpo.py:159-198`); otherwise a rule-based stand-in so the loop
    still runs offline."""
    if reward_model_path and os.path.isdir(reward_model_path):
        return make_torch_rm_reward(reward_model_path, batch_size)
    print(f"[offline demo] reward model '{reward_model_path}' not found — "
          "rule-based stand-in reward")

    def fn(s: str, eos_token: str) -> float:
        has_eos = 1.0 if eos_token in s else 0.0
        words = s.split()
        return has_eos + 0.05 * min(len(set(words)) / max(len(words), 1), 1.0)

    return make_rule_reward(fn)


def init_multihost_logged() -> dict:
    """Multi-host bring-up FIRST (before anything touches the backend):
    no-op on a single host; on a pod it joins jax.distributed so
    jax.devices() is the global mesh (parallel/distributed.py). Logs the
    per-process device counts when running multi-process. Shared by
    common.run and the r1 launcher. Also the single place every launcher
    passes through before compiling anything, so the persistent compile
    cache is enabled here (compile time is the scarcest resource on a
    tunneled TPU)."""
    from nanorlhf_tpu.parallel import initialize_multihost
    from nanorlhf_tpu.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()

    dist = initialize_multihost()
    if dist["process_count"] > 1:
        print(f"[multihost] process {dist['process_index']}/"
              f"{dist['process_count']}: {dist['local_device_count']} local "
              f"/ {dist['global_device_count']} global devices")
    return dist


def run(cfg: RLConfig, value_params_fn=None, post_build=None):
    """Build everything and train — the tail of every launcher.

    `value_params_fn(mcfg, params) -> tree` builds the value model from the
    freshly resolved policy (PPO). `post_build(trainer, dataset, reward_func)`
    runs before training (PPO's value-initializer phase).
    """
    init_multihost_logged()
    mcfg, params, tokenizer = resolve_model(
        cfg.sft_model_path, cfg.seed, attention_impl=cfg.attention_impl
    )
    dataset = resolve_dataset(cfg, tokenizer)
    reward_func = resolve_rm_reward(cfg.reward_model_path)
    value_params = value_params_fn(mcfg, params) if value_params_fn else None
    trainer = RLTrainer(
        cfg, mcfg, tokenizer, params, dataset, reward_func,
        value_params=value_params,
    )
    if post_build is not None:
        post_build(trainer, dataset, reward_func)
    from nanorlhf_tpu.resilience import Preempted

    try:
        return trainer.train()
    except Preempted as e:
        # SIGTERM during training: the loop already flushed the in-flight
        # async save and committed an emergency checkpoint — exit cleanly
        # (resume_from_checkpoint picks the run back up) instead of dumping
        # a stack trace into the preemption logs
        print(f"[preemption] {e} — exiting cleanly; resume with "
              "resume_from_checkpoint()")
        return trainer.state
    finally:
        trainer.close()
        if cfg.telemetry:
            # close() just (re)wrote the span trace — point the operator at
            # it (docs/OBSERVABILITY.md)
            trace = os.path.join(cfg.telemetry_dir or cfg.output_dir,
                                 "trace.json")
            print(f"[telemetry] span trace: {trace} — load at "
                  "https://ui.perfetto.dev")
