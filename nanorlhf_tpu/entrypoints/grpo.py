"""GRPO launcher — config parity with `/root/reference/GRPO/grpo.py:86-155`.

All settings live in this file (reference convention, `README.md:34`).
Run: python -m nanorlhf_tpu.entrypoints.grpo
"""

from nanorlhf_tpu.entrypoints.common import run
from nanorlhf_tpu.trainer import AlgoName, RLConfig


def build_config(sequence_parallel: int = 1,
                 rollout_staleness: int | None = None,
                 rollout_devices: int = 0,
                 rollout_workers: int = 1,
                 rollout_spec_k: int = 0,
                 status_port: int = 0,
                 env_name: str = "",
                 env_max_turns: int = 1) -> RLConfig:
    """`sequence_parallel > 1` routes the chunked logprob pass and the jitted
    update through ring attention with the sequence dim sharded over an sp
    mesh axis (response_length must divide by it).

    `rollout_staleness` (not None) turns on the async rollout orchestrator
    (docs/ORCHESTRATOR.md) at that max_staleness, with sampler logprob
    capture so the truncated-IS off-policy correction has the behavior
    logprobs it needs; pair with `rollout_devices > 0` to give generation
    its own device group so it truly never waits on the train step.

    `rollout_workers > 1` generalizes the pipeline into the elastic rollout
    fleet (docs/FLEET.md): N independent, preemptible workers under leased
    work with reassignment/quarantine fault tolerance. Implies the
    orchestrator; staleness defaults to the worker count (the gate bounds
    in-flight leases, so fewer stale steps would idle workers). With
    `rollout_devices > 0` the reserved group is split into per-worker
    meshes (rollout_devices must divide by rollout_workers).

    `rollout_spec_k > 0` turns on draft-free speculative rollout decode
    (sampler/speculative.py, distribution-exact); composes with every knob
    above except rollout_compaction_segments.

    `status_port != 0` serves the live run-health endpoints /metrics ·
    /healthz · /statusz on that port (-1 = ephemeral; docs/OBSERVABILITY.md
    §5). Health scoring itself is on regardless — this only exposes it
    over HTTP.

    `env_name` runs rollouts through a vectorized environment
    (docs/ENVIRONMENTS.md): "single_turn" wraps the reward callable
    (bit-identical to the default pipeline); "python_tool" with
    `env_max_turns > 1` runs fenced ```python blocks as mid-episode tools
    over the paged scheduler — multi-turn forces the paged continuous-
    batching layout and turns off the knobs the episode driver replaces
    (orchestrator, spec decode, logprob capture)."""
    cfg = RLConfig(
        algo=AlgoName.GRPO,
        exp_name="grpo-v1",
        sft_model_path="Qwen/Qwen2.5-1.5B-Instruct",
        reward_model_path="OpenAssistant/reward-model-deberta-v3-large-v2",
        output_dir="output/grpo-v1",
        # reference defaults (`GRPO/grpo.py:108-155`)
        kl_coef=0.01,
        cliprange=0.2,
        temperature=0.9,
        learning_rate=6e-6,
        warmup_steps=0,
        min_lr_rate=0.1,
        response_length=1500,
        per_device_train_batch_size=4,
        gradient_accumulation_steps=8,
        num_mini_batches=16,
        num_ppo_epochs=1,
        total_episodes=250000,
        whiten_rewards=False,
        advantage_whiten=False,   # GRPO has its own group baseline
        sample_n=4,               # grpo_sample_N (`grpo.py:106`)
        use_lora=True,
        lora_r=64,
        lora_alpha=16,
        gradient_checkpointing=True,
        missing_eos_penalty=None,
        save_steps=1,
        save_total_limit=8,
        metric_for_best_model="eval_objective/rlhf_reward_old",
        greater_is_better=True,
        load_best_model_at_end=True,
        stop_token="eos",
    )
    if sequence_parallel > 1:
        from nanorlhf_tpu.parallel import MeshConfig

        cfg.mesh = MeshConfig(data=-1, sp=sequence_parallel)
    if rollout_staleness is not None:
        cfg.rollout_orchestrator = True
        cfg.max_staleness = rollout_staleness
        cfg.sampler_logprob_capture = True  # behavior logprobs for the IS fix
    if rollout_workers > 1:
        cfg.rollout_orchestrator = True
        cfg.rollout_workers = rollout_workers
        cfg.sampler_logprob_capture = True
        if rollout_staleness is None:
            # N workers need N leases in flight to all stay busy
            cfg.max_staleness = rollout_workers
    if rollout_devices > 0:
        cfg.rollout_devices = rollout_devices
    cfg.rollout_spec_k = rollout_spec_k
    cfg.status_port = status_port
    if env_name:
        cfg.env_name = env_name
        cfg.env_max_turns = env_max_turns
        if env_max_turns > 1:
            # the episode driver owns the rollout phase: paged continuous
            # batching on, and the knobs it replaces off
            if cfg.rollout_page_size <= 0:
                cfg.rollout_page_size = 128
            if cfg.rollout_decode_rows <= 0:
                cfg.rollout_decode_rows = cfg.batch_size * cfg.sample_n // 2
            cfg.rollout_orchestrator = False
            cfg.rollout_workers = 1
            cfg.sampler_logprob_capture = False
            cfg.rollout_spec_k = 0
            # half the budget per turn, the rest for the tool observations
            cfg.env_turn_tokens = cfg.response_length // (2 * env_max_turns)
            cfg.env_obs_budget = min(
                256,
                (cfg.response_length - cfg.env_turn_tokens * env_max_turns)
                // max(1, env_max_turns - 1))
    return cfg


if __name__ == "__main__":
    run(build_config())
