"""R1-Zero launcher — sparse GRPO on math reasoning, parity with
`/root/reference/examples/r1-v0/grpo_r1.py`.

Base (non-instruct) model, MetaMathQA training prompts / MATH-500 eval,
binary boxed-answer reward, response_length 8000 with kl_coef 0.0
(`grpo_r1.py:92,126-128,138,145`), greedy accuracy eval before training and
every `eval_steps` updates (`grpo_r1_trainer.py:471-475,824-825`). Offline
builds fall back to a synthetic arithmetic corpus so the full sparse-GRPO
path still runs.
"""

from __future__ import annotations

import os

import numpy as np

import jax
import jax.numpy as jnp

from nanorlhf_tpu.data.datasets import PromptDataset, _left_pad
from nanorlhf_tpu.entrypoints.common import resolve_model
from nanorlhf_tpu.rewards import get_boxed, is_correct
from nanorlhf_tpu.sampler import SamplingParams, generate
from nanorlhf_tpu.trainer import AlgoName, RLConfig
from nanorlhf_tpu.trainer.sparse_grpo import SparseGRPOTrainer

# the reference's math prompt template (`grpo_r1.py:228`)
TEMPLATE = (
    "# Question:\nQUESTION\nPlease reason step by step, and put your final "
    "answer within \\boxed{}.\n# Answer:\n"
)


def build_config(sequence_parallel: int = 1,
                 rollout_ahead: bool = False,
                 rollout_spec_k: int = 0) -> RLConfig:
    """`sequence_parallel > 1` shards the 8k-token scoring/update passes over
    an sp mesh axis (ring attention, `parallel/sp.py`) — context beyond one
    chip's HBM. Devices split as (data = n/sp, sp); response_length must be
    a multiple of sp. `rollout_ahead` overlaps the next update's generation
    with this update's sympy grading (one-update-stale rollouts, clip-
    corrected — trainer/config.py). `rollout_spec_k > 0` turns on draft-free
    speculative rollout decode (sampler/speculative.py) — THIS launcher is
    its natural home: R1-style math rollouts restate the problem and repeat
    `\\boxed{}` / step templates, exactly what the n-gram drafter feeds on;
    sampled rollouts stay distribution-exact. Try 4; watch
    rollout/draft_acceptance."""
    cfg = RLConfig(
        algo=AlgoName.GRPO,
        exp_name="grpo-r1-v0",
        sft_model_path="Qwen/Qwen2-1.5B",        # base model (`grpo_r1.py:92`)
        output_dir="output/grpo-r1-v0",
        response_length=8000,                     # (`grpo_r1.py:145`)
        kl_coef=0.0,                              # (`grpo_r1.py:138`)
        temperature=0.9,
        # exact full-vocab nucleus, matching the reference's untruncated
        # vLLM top_p (`grpo_r1.py:127` via vllm SamplingParams): a BASE
        # model at temp 0.9 is exactly the high-entropy regime where the
        # 0.95-nucleus can exceed a fixed top-k early in training, and a
        # k=64 pre-trim would silently narrow exploration (VERDICT r3 #6).
        # Sort-free: the top_k=0 path rides the bisection threshold filter
        # (reduction passes, `sampler.top_p_filter_bisect`), not a
        # full-vocab sort; instruction-tuned launchers keep the k=64
        # ApproxTopK fast path.
        rollout_top_k=0,
        sample_n=4,
        learning_rate=6e-6,
        per_device_train_batch_size=4,
        gradient_accumulation_steps=8,
        num_mini_batches=16,
        total_episodes=250000,
        use_lora=True,
        lora_r=64,
        lora_alpha=16,
        eval_steps=10,                            # accuracy every 10 steps
        save_steps=1,
        save_total_limit=8,
        # re-launches mmap the tokenized corpus instead of re-tokenizing
        # 250k prompts (data/token_cache.py)
        dataset_cache_dir="output/grpo-r1-v0/token_cache",
        # the run's deploy artifact: HF checkpoint, LoRA merged
        export_hf_dir="output/grpo-r1-v0/hf_export",
    )
    cfg.rollout_ahead = rollout_ahead
    cfg.rollout_spec_k = rollout_spec_k
    if sequence_parallel > 1:
        from nanorlhf_tpu.parallel import MeshConfig

        cfg.mesh = MeshConfig(data=-1, sp=sequence_parallel)
    return cfg


# ---------------------------------------------------------------------------
# datasets: MetaMathQA / MATH-500, synthetic arithmetic fallback
# ---------------------------------------------------------------------------


def synthetic_math_corpus(n: int, seed: int = 0):
    """Offline stand-in: single-step arithmetic with known boxed answers."""
    rng = np.random.default_rng(seed)
    qa = []
    for _ in range(n):
        a, b = int(rng.integers(2, 99)), int(rng.integers(2, 99))
        op = rng.choice(["+", "-", "*"])
        ans = {"+": a + b, "-": a - b, "*": a * b}[op]
        qa.append((f"What is {a} {op} {b}?", str(ans)))
    return qa


def load_math_datasets(train_name: str, eval_name: str, limit: int | None = None):
    """(train_qa, eval_qa) as lists of (question, boxed_answer)."""
    try:
        from nanorlhf_tpu.data.datasets import _load_hf_dataset

        train = _load_hf_dataset(train_name, "train")
        train_qa = []
        for row in train:
            resp = row["response"]
            marker = "The answer is: "
            i = resp.find(marker)
            if i != -1:
                train_qa.append((row["query"], resp[i + len(marker):].strip()))
        ev = _load_hf_dataset(eval_name, "test")
        eval_qa = [(row["problem"], get_boxed(row["solution"])) for row in ev]
        if limit:
            train_qa, eval_qa = train_qa[:limit], eval_qa[: min(limit, 500)]
        return train_qa, eval_qa
    except Exception as e:
        print(f"[offline demo] math datasets unavailable ({type(e).__name__}) — "
              "synthetic arithmetic corpus")
        return synthetic_math_corpus(512), synthetic_math_corpus(64, seed=1)


def build_prompt_dataset(train_qa, tokenizer, max_prompt_len: int = 512,
                         cache_dir: str | None = None):
    """Templated + tokenized prompt dataset. `cache_dir` enables the mmap
    token cache (data/token_cache.py) keyed on the corpus content hash —
    relaunches skip tokenizing the 250k-question corpus."""
    ids = None
    cache_path = fp = None
    if cache_dir is not None:
        import hashlib

        from nanorlhf_tpu.data.token_cache import (
            corpus_fingerprint, load_token_cache, save_token_cache,
            tokenizer_identity)

        corpus_h = hashlib.blake2b(
            "\x1e".join(q for q, _ in train_qa).encode(), digest_size=8
        ).hexdigest()
        fp = corpus_fingerprint(
            corpus=corpus_h, template=TEMPLATE, max_prompt_len=max_prompt_len,
            tok=tokenizer_identity(tokenizer),
        )
        cache_path = os.path.join(cache_dir, f"prompts-{fp:016x}.tok")
        ids = load_token_cache(cache_path, fp)
    if ids is None:
        texts = [TEMPLATE.replace("QUESTION", q) for q, _ in train_qa]
        ids = [tokenizer.encode(t)[:max_prompt_len] for t in texts]
        if cache_path is not None:
            save_token_cache(cache_path, ids, fp)
    return PromptDataset(_left_pad(ids, tokenizer.pad_token_id), tokenizer.pad_token_id)


# ---------------------------------------------------------------------------
# reward + accuracy (r1 protocol)
# ---------------------------------------------------------------------------


def make_r1_reward(train_index: dict, use_subprocess: bool = True):
    """Binary reward via the r1 signature
    `(pmt_and_responses, responses_ids, tokenizer)` (`grpo_r1.py:250-273`)."""

    def reward_func(pmt_and_responses, responses_ids, tokenizer):
        rewards = np.zeros(len(pmt_and_responses), np.float32)
        for i, s in enumerate(pmt_and_responses):
            q_start = len("# Question:\n")
            q_end = s.find("\nPlease reason step by step, and")
            if q_end == -1:
                continue
            question = s[q_start:q_end]
            a_idx = s.find("\n# Answer:\n", q_end)
            if a_idx == -1:
                continue
            solution = s[a_idx + len("\n# Answer:\n"):]
            end = solution.find(tokenizer.eos_token)
            if end != -1:
                solution = solution[:end]
            gt = train_index.get(question)
            if gt is None:
                continue
            if is_correct(get_boxed(solution), gt, use_subprocess=use_subprocess):
                rewards[i] = 1.0
        return rewards

    return reward_func


def make_accuracy_func(eval_qa, max_prompt_len: int = 512,
                       eval_response_length: int = 1024,
                       use_subprocess: bool = True, batch: int = 64):
    """Greedy-decode accuracy on the eval set (`grpo_r1.py:276-341`)."""

    def accuracy_func(trainer) -> float:
        tok = trainer.tokenizer
        texts = [TEMPLATE.replace("QUESTION", q) for q, _ in eval_qa]
        ids = _left_pad([tok.encode(t)[:max_prompt_len] for t in texts],
                        tok.pad_token_id)
        correct = 0
        for i in range(0, len(eval_qa), batch):
            chunk = jnp.asarray(ids[i : i + batch])
            out = generate(
                trainer.params, trainer.mcfg, chunk,
                chunk != tok.pad_token_id, jax.random.PRNGKey(0),
                SamplingParams(greedy=True, max_tokens=eval_response_length),
                eos_token_id=tok.eos_token_id, pad_token_id=tok.pad_token_id,
                lora_scale=trainer.lora_scale,
            )
            for row, (_, gt) in zip(np.asarray(out), eval_qa[i : i + batch]):
                text = tok.decode(row, skip_special_tokens=True)
                if is_correct(get_boxed(text), gt, use_subprocess=use_subprocess):
                    correct += 1
        return correct / max(len(eval_qa), 1)

    return accuracy_func


def main(cfg: RLConfig | None = None, limit: int | None = None,
         max_prompt_len: int = 512, eval_response_length: int = 1024):
    cfg = cfg or build_config()
    from nanorlhf_tpu.entrypoints.common import init_multihost_logged

    init_multihost_logged()  # no-op single-host; joins the pod otherwise
    mcfg, params, tokenizer = resolve_model(cfg.sft_model_path, cfg.seed)
    train_qa, eval_qa = load_math_datasets("meta-math/MetaMathQA", "HuggingFaceH4/MATH-500",
                                           limit=limit)
    train_index = dict(train_qa)
    dataset = build_prompt_dataset(train_qa, tokenizer,
                                   max_prompt_len=max_prompt_len,
                                   cache_dir=cfg.dataset_cache_dir)
    trainer = SparseGRPOTrainer(
        cfg, mcfg, tokenizer, params, dataset,
        make_r1_reward(train_index),
        accuracy_func=make_accuracy_func(
            eval_qa, max_prompt_len=max_prompt_len,
            eval_response_length=eval_response_length,
        ),
    )
    try:
        return trainer.train()
    finally:
        trainer.close()


if __name__ == "__main__":
    main()
