"""PPO launcher — parity with `/root/reference/PPO/ppo.py`: dual config
(PPO + value-finetune), a value model initialized from the SFT model with a
fresh score head, separate policy/value learning rates, and the one-off
value-initializer phase before PPO proper (`ppo.py:369-380`)."""

import jax
import jax.numpy as jnp
import numpy as np

from nanorlhf_tpu.core import init_score_head
from nanorlhf_tpu.entrypoints.common import run
from nanorlhf_tpu.entrypoints.grpo import build_config
from nanorlhf_tpu.trainer import AlgoName
from nanorlhf_tpu.trainer.value_init import ValueInitConfig, finetune_value_model


def build_ppo_config():
    cfg = build_config()
    cfg.algo = AlgoName.PPO
    cfg.exp_name = "ppo-v1"
    cfg.output_dir = "output/ppo-v1"
    cfg.sample_n = 1
    # separate value-model LR (`PPO/ppo.py:118-119`)
    cfg.value_learning_rate = 1e-5
    cfg.cliprange_value = 0.01
    cfg.vf_coef = 0.1
    cfg.gamma = 1.0
    cfg.lam = 0.95            # GAE(γ=1.0, λ=0.95) (`PPO/ppo.py:177-178`)
    return cfg


def make_value_params(mcfg, params):
    """Value model = SFT backbone + fresh score head
    (`AutoModelForSequenceClassification(num_labels=1)`, `PPO/ppo.py:280-287`)."""
    value_params = {k: v for k, v in params.items() if k not in ("lm_head", "lora")}
    value_params = jax.tree.map(jnp.copy, value_params)
    value_params["score"] = init_score_head(mcfg, jax.random.PRNGKey(1))
    return value_params


def main(run_value_init: bool = True, value_init_cfg: ValueInitConfig | None = None):
    cfg = build_ppo_config()

    def value_init_phase(trainer, dataset, reward_func):
        if not run_value_init:
            return
        vcfg = value_init_cfg or ValueInitConfig()
        prompts = np.asarray(dataset.input_ids[: vcfg.train_data_size])
        trainer.value_params = finetune_value_model(
            trainer.value_params, trainer.params,
            # None in ref-free mode (kl_coef 0): value_init then skips the
            # ref forward — its KL shaping is multiplied away anyway
            trainer.ref_params,
            reward_func, prompts, trainer.tokenizer, trainer.mcfg,
            response_length=cfg.response_length, temperature=cfg.temperature,
            kl_coef=cfg.kl_coef, gamma=cfg.gamma, vcfg=vcfg,
            whiten_rewards=cfg.whiten_rewards, lora_scale=trainer.lora_scale,
            # regress only the value tree's LoRA partition (`PPO/ppo.py:317-332`)
            value_lora_cfg=trainer.value_lora_cfg,
            key=jax.random.PRNGKey(cfg.seed + 2),
            # the fused-scoring escape hatch covers this pass too
            fused_logprob_scoring=cfg.fused_logprob,
        )

    return run(cfg, value_params_fn=make_value_params, post_build=value_init_phase)


if __name__ == "__main__":
    main()
