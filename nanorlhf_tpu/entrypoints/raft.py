"""RAFT launcher — parity with `/root/reference/RAFT/raft.py` (raft_sample_K,
no cliprange/whiten fields used; best-of-K + SFT loss, SURVEY.md §2.4)."""

from nanorlhf_tpu.entrypoints.common import run
from nanorlhf_tpu.entrypoints.grpo import build_config
from nanorlhf_tpu.trainer import AlgoName


def build_raft_config():
    cfg = build_config()
    cfg.algo = AlgoName.RAFT
    cfg.exp_name = "raft-v1"
    cfg.output_dir = "output/raft-v1"
    cfg.sample_n = 4          # raft_sample_K (`RAFT/raft.py:105`)
    # "best" = documented intent; set "random" for bit-parity with the
    # reference as shipped (`RAFT/raft_trainer.py:585-588`)
    cfg.raft_selection = "best"
    return cfg


if __name__ == "__main__":
    run(build_raft_config())
