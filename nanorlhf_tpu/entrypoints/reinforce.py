"""REINFORCE launcher — parity with `/root/reference/REINFORCE/reinforce.py`.

The only algorithm whose launcher defaults `advantage_whiten=True`
(`reinforce.py:103`) — whitening is its baseline."""

from nanorlhf_tpu.entrypoints.common import run
from nanorlhf_tpu.entrypoints.grpo import build_config
from nanorlhf_tpu.trainer import AlgoName


def build_reinforce_config():
    cfg = build_config()
    cfg.algo = AlgoName.REINFORCE
    cfg.exp_name = "reinforce-v1"
    cfg.output_dir = "output/reinforce-v1"
    cfg.sample_n = 1
    cfg.advantage_whiten = True   # (`REINFORCE/reinforce.py:103`)
    cfg.gamma = 1.0               # (`reinforce.py:113-114`)
    cfg.lam = 0.95
    return cfg


if __name__ == "__main__":
    run(build_reinforce_config())
