"""ReMax launcher — parity with `/root/reference/ReMax/remax.py` (n=1 plus a
greedy baseline rollout, SURVEY.md §2.1/§2.4)."""

from nanorlhf_tpu.entrypoints.common import run
from nanorlhf_tpu.entrypoints.grpo import build_config
from nanorlhf_tpu.trainer import AlgoName


def build_remax_config():
    cfg = build_config()
    cfg.algo = AlgoName.REMAX
    cfg.exp_name = "remax-v1"
    cfg.output_dir = "output/remax-v1"
    cfg.sample_n = 1          # single sampled rollout; baseline is greedy
    return cfg


if __name__ == "__main__":
    run(build_remax_config())
