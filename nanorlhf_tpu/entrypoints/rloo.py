"""RLOO launcher — parity with `/root/reference/RLOO/rloo.py` (= grpo.py
modulo rloo_sample_N and lam fields, SURVEY.md §2.1)."""

from nanorlhf_tpu.entrypoints.common import run
from nanorlhf_tpu.entrypoints.grpo import build_config
from nanorlhf_tpu.trainer import AlgoName


def build_rloo_config():
    cfg = build_config()
    cfg.algo = AlgoName.RLOO
    cfg.exp_name = "rloo-v1"
    cfg.output_dir = "output/rloo-v1"
    cfg.sample_n = 4          # rloo_sample_N (`RLOO/rloo.py:107`)
    cfg.lam = 0.95            # (`RLOO/rloo.py:115`)
    return cfg


if __name__ == "__main__":
    run(build_rloo_config())
