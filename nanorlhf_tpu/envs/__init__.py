"""Vectorized multi-turn environments (docs/ENVIRONMENTS.md).

`Environment.reset/step` is the episode contract; `SingleTurnEnv` lifts
any existing reward callable into it (the degenerate case IS the current
pipeline, parity-pinned); `PythonToolEnv` feeds pooled-executor stdout
back as mid-episode observations; `run_env_episodes` drives episodes over
the paged scheduler's admission/recycling machinery.
"""

from nanorlhf_tpu.envs.base import Environment, EnvState, SingleTurnEnv
from nanorlhf_tpu.envs.python_tool import PythonToolEnv, extract_python_block
from nanorlhf_tpu.envs.rollout import run_env_episodes

ENV_REGISTRY = ("single_turn", "python_tool")


def build_env(name: str, reward_func, *, max_turns: int = 1,
              tool_timeout: float = 5.0, eos_token: str = "",
              extractor=None) -> Environment:
    """Construct a named environment around an existing reward callable.

    ``single_turn`` wraps ``reward_func`` one-shot (must have
    ``max_turns == 1``); ``python_tool`` runs fenced ```python blocks as
    mid-episode tools and grades the full transcript with ``reward_func``
    at episode end. The trainer injects ``eos_token`` so reward callables
    keep their ``(pairs, eos_token)`` protocol.
    """
    if name == "single_turn":
        if max_turns != 1:
            raise ValueError(
                f"env 'single_turn' is single-turn by definition; "
                f"env_max_turns={max_turns}")
        env: Environment = SingleTurnEnv(reward_func)
    elif name == "python_tool":
        env = PythonToolEnv(reward_func, max_turns=max_turns,
                            timeout=tool_timeout, extractor=extractor)
    else:
        raise ValueError(f"unknown env {name!r}; known: {ENV_REGISTRY}")
    env.eos_token = eos_token
    return env


__all__ = [
    "Environment",
    "EnvState",
    "SingleTurnEnv",
    "PythonToolEnv",
    "extract_python_block",
    "run_env_episodes",
    "build_env",
    "ENV_REGISTRY",
]
