"""Vectorized environment interface for tool-augmented multi-turn rollouts.

The reference's reward layer is single-shot — ``reward_func(prompt +
response, eos_token)`` grades a finished completion and that is the entire
"environment". This module promotes that contract to a real environment
interface (ROADMAP item 4) without breaking it:

- ``Environment.reset(prompts) -> EnvState`` starts one episode per prompt.
- ``Environment.step(state, responses) -> (observations, rewards, done)``
  consumes the model's turn text and returns the environment's reply
  (observation text appended to the context for the next turn), this turn's
  scalar reward, and whether each episode ended.

Both calls are VECTORIZED over episodes; ``step`` additionally takes
``indices`` so the multi-turn driver (envs/rollout.py) can step a single
episode the moment its row hits EOS-of-turn instead of barriering the
batch on the slowest tool.

``SingleTurnEnv`` lifts any existing ``reward_func`` into this interface:
one turn, empty observation, the wrapped callable's score as the terminal
reward. The degenerate case IS the current pipeline — the trainer routes
a single-turn env through the exact same generate + reward-dispatch path
as a bare reward_func, and tests/test_envs.py pins the two bit-identical
(docs/ENVIRONMENTS.md).

Masking contract: tokens the ENVIRONMENT wrote (observations) are not the
policy's actions. The rollout driver records their spans and the trainer
threads a per-token ``loss_mask`` (False on observation tokens) through
``algos/losses.py``'s existing ``mask`` argument, so environment text is
conditioned on but never scored (docs/ENVIRONMENTS.md §masking).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

import numpy as np


@dataclass
class EnvState:
    """Per-episode host-side state. Arrays are indexed by episode.

    ``turn`` counts COMPLETED model turns; ``done`` episodes take no more
    steps; ``transcripts`` accumulates the episode text (model turns +
    observations) so terminal graders can score the whole interaction.
    """

    prompts: list[str]
    turn: np.ndarray
    done: np.ndarray
    transcripts: list[str]
    meta: dict = field(default_factory=dict)

    @classmethod
    def fresh(cls, prompts: Sequence[str]) -> "EnvState":
        n = len(prompts)
        return cls(
            prompts=list(prompts),
            turn=np.zeros(n, np.int32),
            done=np.zeros(n, bool),
            transcripts=[""] * n,
        )


class Environment:
    """Vectorized environment contract (docs/ENVIRONMENTS.md).

    Subclasses override ``reset``/``step``; ``max_turns`` bounds episode
    length (the driver also enforces its own budget). ``eos_token`` is the
    tokenizer's EOS string — injected by the trainer at construction so
    reward callables keep their existing ``(pairs, eos_token)`` protocol.
    """

    max_turns: int = 1
    eos_token: str = ""

    def reset(self, prompts: Sequence[str]) -> EnvState:
        return EnvState.fresh(prompts)

    def step(
        self,
        state: EnvState,
        responses: Sequence[str],
        indices: Optional[Sequence[int]] = None,
    ) -> tuple[list[str], np.ndarray, np.ndarray]:
        """Consume one model turn for the episodes in ``indices`` (None =
        all, in order) and return (observations, rewards, done) aligned
        with ``responses``. Implementations mutate ``state`` in place —
        per-episode slots are disjoint, so concurrent single-index steps
        from the driver's tool threads are safe."""
        raise NotImplementedError

    def as_reward_func(self) -> Callable:
        """A single-turn env back out as ``(pairs, eos_token) -> scores``
        via a real reset/step round trip. The trainer unwraps any
        ``max_turns == 1`` env through this so generation and reward
        dispatch stay on the exact non-env code path (the parity pin)
        while the env machinery is still exercised on every update."""
        if self.max_turns != 1:
            raise ValueError(
                f"as_reward_func() is the single-turn unwrap; "
                f"max_turns={self.max_turns}")

        def fn(pairs, eos_token):
            self.eos_token = eos_token
            st = self.reset([""] * len(pairs))
            _, scores, _ = self.step(st, list(pairs))
            return scores

        return fn


class SingleTurnEnv(Environment):
    """Any ``reward_func`` lifted into the environment interface.

    One turn: the response is graded by the wrapped callable and the
    episode ends — no observation, no continuation. This is the degenerate
    case the ISSUE pins bit-identical to the non-env pipeline: the trainer
    unwraps it back into a plain reward callable (``as_reward_func``) so
    generation, reward dispatch (retries, the ``reward.exec`` fault site),
    and every metric stay on the exact code path they were on before
    environments existed.
    """

    max_turns = 1

    def __init__(self, reward_func: Callable):
        self.reward_func = reward_func

    def step(
        self,
        state: EnvState,
        responses: Sequence[str],
        indices: Optional[Sequence[int]] = None,
    ) -> tuple[list[str], np.ndarray, np.ndarray]:
        idx = list(range(len(responses))) if indices is None else list(indices)
        texts = [state.prompts[i] + r for i, r in zip(idx, responses)]
        scores = np.asarray(
            self.reward_func(texts, self.eos_token), np.float32
        )
        for i, r in zip(idx, responses):
            state.transcripts[i] += r
            state.turn[i] += 1
            state.done[i] = True
        return [""] * len(responses), scores, np.ones(len(responses), bool)
