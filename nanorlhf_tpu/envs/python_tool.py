"""Python-tool environment: mid-episode code execution as observations.

The reference's r1 tooling only ever runs model-emitted Python as a
*grader* after the episode ends (`rewards/python_executor.py`). Here the
same executor becomes a mid-episode TOOL: a turn that ends with a fenced
```python block pauses generation, the snippet runs in the pooled
subprocess executor, and its stdout (or traceback) comes back as the next
turn's observation text — the model continues from a context that now
contains real execution results.

Executor pooling matters here: the spawn-context bootstrap fence from the
original ``PythonExecutor`` costs seconds PER SPAWN, which a grader pays
once per sample but a tool would pay once per TURN. ``PooledPythonExecutor``
keeps one warm worker process across turns (same terminate→kill escalation
on timeout), so steady-state tool calls cost milliseconds.
"""

from __future__ import annotations

import re
from typing import Callable, Optional, Sequence

import numpy as np

from nanorlhf_tpu.envs.base import Environment, EnvState
from nanorlhf_tpu.rewards.python_executor import PooledPythonExecutor

_CODE_RE = re.compile(r"```python\s(.*?)```", re.DOTALL)


def extract_python_block(text: str) -> Optional[str]:
    """Last fenced ```python block in ``text``, or None. The LAST block is
    the tool call the turn ends on — earlier blocks are quoted context."""
    blocks = _CODE_RE.findall(text)
    return blocks[-1].strip() if blocks else None


class PythonToolEnv(Environment):
    """Tool-augmented episodes over the pooled Python executor.

    A turn whose text contains a ```python block (and turns remain) gets
    the snippet's stdout back as a fenced ```output observation and the
    episode continues; otherwise the episode ends and ``reward_func``
    (the unchanged ``(pairs, eos_token)`` protocol) grades the FULL
    transcript — prompt, every model turn, every observation. Intermediate
    turns earn 0 reward; per-turn credit assignment happens in
    ``algos.advantages`` from the turn-end positions the driver records.

    ``extractor`` overrides the fenced-block regex for prompt formats with
    a different tool-call grammar (it returns the snippet string or None).
    A tool failure — nonzero-exit snippet, timeout, or an injected
    ``env.crash`` fault absorbed by the driver — still produces an
    observation (the error text), never a crashed rollout.
    """

    def __init__(
        self,
        reward_func: Optional[Callable] = None,
        max_turns: int = 2,
        timeout: float = 5.0,
        executor=None,
        extractor: Optional[Callable[[str], Optional[str]]] = None,
        obs_chars: int = 512,
    ):
        if max_turns < 1:
            raise ValueError(f"max_turns={max_turns}")
        self.reward_func = reward_func
        self.max_turns = max_turns
        self.extractor = extractor or extract_python_block
        self.obs_chars = obs_chars
        self.executor = (
            executor if executor is not None
            else PooledPythonExecutor(timeout=timeout)
        )

    def reset(self, prompts: Sequence[str]) -> EnvState:
        return EnvState.fresh(prompts)

    def _terminal_reward(self, state: EnvState, i: int) -> float:
        if self.reward_func is None:
            return 0.0
        score = self.reward_func(
            [state.prompts[i] + state.transcripts[i]], self.eos_token
        )
        return float(np.asarray(score).reshape(-1)[0])

    def step(
        self,
        state: EnvState,
        responses: Sequence[str],
        indices: Optional[Sequence[int]] = None,
    ) -> tuple[list[str], np.ndarray, np.ndarray]:
        idx = list(range(len(responses))) if indices is None else list(indices)
        obs_out: list[str] = []
        rewards = np.zeros(len(responses), np.float32)
        done = np.zeros(len(responses), bool)
        for k, (i, resp) in enumerate(zip(idx, responses)):
            state.transcripts[i] += resp
            turn = int(state.turn[i]) + 1
            code = self.extractor(resp)
            if code is not None and turn < self.max_turns:
                res = self.executor.run(code)
                text = (res.stdout if res.ok else (res.error or res.stdout))
                text = (text or "").strip()[: self.obs_chars]
                obs = f" ```output {text} ``` "
                state.transcripts[i] += obs
                obs_out.append(obs)
            else:
                obs_out.append("")
                done[k] = True
                state.done[i] = True
                rewards[k] = self._terminal_reward(state, i)
            state.turn[i] = turn
        return obs_out, rewards, done

    def close(self):
        self.executor.close()
