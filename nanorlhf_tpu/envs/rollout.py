"""Multi-turn rollout driver over the paged continuous-batching scheduler.

One episode interleaves model turns and environment observations in a
single token stream:

    prompt | turn-1 tokens .. EOS | obs tokens | turn-2 tokens .. EOS | ...

Turn 1 is the EXISTING ``generate()`` call, bit-for-bit — the whole batch
prefills and decodes exactly as the non-env pipeline does, so a
single-turn environment never enters this module's continuation loop and
the degenerate-case parity pin holds by construction.

Continuation turns reuse the queued paged scheduler's admission path (PR
10) nearly verbatim: when a row hits EOS-of-turn its pages are released
back to the pool IMMEDIATELY (``release_row``) and the turn text goes to
the environment on a tool thread; when the observation arrives, the
extended context — real prompt + prior turns + observation tokens,
left-padded to the fixed episode width — is admitted into a recycled row
through the same single-row bucketed prefill (``_admit_one``) and
carry re-init (``_install_row``) mid-loop admissions use, writing KV
through the row's freshly allocated block table. A slow tool therefore
never holds pages: the rows it would have occupied decode OTHER episodes'
turns, and ``env/stalled_rows`` counts the scheduler waits where decode
sat fully idle on tool results.

Loss masking: observation tokens are environment actions, not policy
actions. The driver records every span and returns a per-token
``loss_mask`` (False on observation tokens) plus per-turn reward/end
positions; the trainer threads the mask through ``algos/losses.py``'s
existing ``mask`` argument and attributes advantages per turn
(``algos.advantages.per_turn_terminal_rewards``). docs/ENVIRONMENTS.md
walks the full lifecycle.

Fault sites: ``env.hang`` (default ``action=delay`` — the tool call
stalls ``delay=S`` seconds first, driving the page-release-while-stalled
path) and ``env.crash`` (default raise — absorbed here as an error-text
observation, never a dead rollout) fire per tool dispatch with
``worker=<episode index>`` scoping (docs/RESILIENCE.md).
"""

from __future__ import annotations

import time
from collections import deque
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait

import jax
import jax.numpy as jnp
import numpy as np

from nanorlhf_tpu.core.model import init_paged_kv_cache
from nanorlhf_tpu.envs.base import Environment
from nanorlhf_tpu.sampler import generate
from nanorlhf_tpu.sampler.paged.pages import blocks_per_row, init_page_state
from nanorlhf_tpu.sampler.paged.scheduler import _finalize_segments
from nanorlhf_tpu.sampler.paged.session import (
    _ADMIT_BASE,
    _admit_one,
    _alloc_jit,
    _decode_chunk,
    _install_row,
    _release_jit,
)


def _trim_turn(tok_row: np.ndarray, eos_token_id: int,
               pad_token_id: int) -> np.ndarray:
    """Real tokens of one generated turn: through the first EOS inclusive,
    else through the last non-pad token (budget exhausted without EOS)."""
    eos = np.nonzero(tok_row == eos_token_id)[0]
    if eos.size:
        return tok_row[: int(eos[0]) + 1]
    real = np.nonzero(tok_row != pad_token_id)[0]
    return tok_row[: int(real[-1]) + 1] if real.size else tok_row[:0]


def run_env_episodes(
    params: dict,
    config,
    prompt_ids: jnp.ndarray,   # [B, Tp] left-padded prompts
    prompt_mask: jnp.ndarray,  # [B, Tp]
    key: jax.Array,
    sampling,                  # SamplingParams with max_tokens == turn_tokens
    env: Environment,
    *,
    eos_token_id: int,
    pad_token_id: int,
    tokenizer,
    max_turns: int,
    turn_tokens: int,
    obs_budget: int,
    response_length: int,
    page_size: int,
    decode_rows: int,
    lora_scale: float = 1.0,
    sync_every: int = 8,
    faults=None,
    tool_threads: int = 4,
    weight_refresh=None,
) -> dict:
    """Run one vectorized batch of multi-turn episodes; returns a payload:

    - ``tokens``      [B*n, response_length] int32 — packed episode streams
    - ``loss_mask``   [B*n, response_length] bool — False on observation tokens
    - ``scores``      [B*n] float32 — per-episode total reward (Σ turns)
    - ``turn_rewards``/``turn_ends`` [B*n, max_turns] — per-turn credit inputs
      (``turn_ends`` = final model-token position of each turn, −1 absent)
    - ``turns``       per-turn lineage records (row, turn, tool_wall_s,
      obs_range, reward, tok_range)
    - ``stats``       the ``env/*`` metric rows (docs/METRICS.md)
    - ``pages_recycled``/``admissions`` — continuation-loop paged evidence

    ``weight_refresh`` (optional ``() -> (version, tree|None)``): in-flight
    mid-sequence weight swaps (docs/ORCHESTRATOR.md §in-flight swaps). The
    callback is polled once per main-loop iteration — the driver's host
    sync point, which also covers every multi-turn re-admission — and a
    newer tree replaces ``params`` for all subsequent prefills and decode
    chunks. The payload then ALSO carries ``segments`` (per-episode
    ``[{policy_version, tok_range}]`` in packed response-token coordinates,
    the same space as ``turns``' tok_range), ``swap_installs`` and
    ``swap_wait_s``. With no mid-rollout publish the poll returns
    ``(version, None)`` every time and the episode streams are bit-identical
    to ``weight_refresh=None``.
    """
    if sampling.max_tokens != turn_tokens:
        raise ValueError(
            f"sampling.max_tokens={sampling.max_tokens} != "
            f"turn_tokens={turn_tokens}: the per-turn generation budget and "
            "the first-turn sampling params must agree")
    if sampling.capture_logprobs:
        raise ValueError(
            "multi-turn episodes recompute logprobs in the scoring pass "
            "(observation tokens have no sampler logprob) — capture off")
    B, Tp = prompt_ids.shape
    n = sampling.n
    rows_total = B * n
    P = int(page_size)

    # ---- in-flight weight swaps (docs/ORCHESTRATOR.md §in-flight swaps) -
    swaps = weight_refresh is not None
    swap_installs = 0
    swap_wait_s = 0.0
    cur_version = None
    seg_bounds: list[list] = [[] for _ in range(rows_total)]
    if swaps:
        # base install: the serial refresh's first call (have_version=None)
        # returns the store's latest outright — installed before turn 1 and
        # NOT counted as a swap
        t0_sw = time.perf_counter()
        cur_version, fresh = weight_refresh()
        swap_wait_s += time.perf_counter() - t0_sw
        if fresh is not None:
            params = fresh
        seg_bounds = [[(cur_version, 0)] for _ in range(rows_total)]

    # ---- turn 1: the existing pipeline, bit-for-bit --------------------
    first = generate(
        params, config, prompt_ids, prompt_mask, key, sampling,
        eos_token_id=eos_token_id, pad_token_id=pad_token_id,
        lora_scale=lora_scale,
    )
    toks1 = np.asarray(first)

    prompt_np = np.asarray(prompt_ids)
    pmask_np = np.asarray(prompt_mask).astype(bool)
    prompt_rows = np.repeat(prompt_np, n, axis=0)
    pmask_rows = np.repeat(pmask_np, n, axis=0)
    pad_tok = getattr(tokenizer, "pad_token", "")
    prompt_texts = [
        t.replace(pad_tok, "") if pad_tok else t
        for t in tokenizer.batch_decode(prompt_np)
    ]
    prompt_texts = [t for t in prompt_texts for _ in range(n)]

    state = env.reset(prompt_texts)

    # per-episode records
    spans: list[list[tuple[str, np.ndarray]]] = [[] for _ in range(rows_total)]
    turn_walls: list[list[float]] = [[] for _ in range(rows_total)]
    turn_rewards = np.zeros((rows_total, max_turns), np.float32)
    cur_turn = [0] * rows_total
    completed = 0
    tool_wall_total = 0.0
    obs_tokens_total = 0
    stall_events = 0
    decode_chunks = 0
    overlap_chunks = 0
    pages_recycled = 0
    admissions = 0
    tool_errors = 0

    pool = ThreadPoolExecutor(max_workers=max(1, tool_threads))
    futures: dict = {}
    pending: deque = deque()

    def tool_step(ep: int, text: str):
        """One env.step on a tool thread; injected faults are absorbed —
        env.crash becomes an error observation, env.hang a pre-step stall."""
        t0 = time.perf_counter()
        try:
            if faults is not None:
                act = faults.fire("env.hang", worker=ep)
                if act and act.startswith("delay:"):
                    time.sleep(float(act.split(":", 1)[1]))
                faults.fire("env.crash", worker=ep)
            obs, rew, done = env.step(state, [text], indices=[ep])
            return obs[0], float(rew[0]), bool(done[0]), \
                time.perf_counter() - t0, False
        except Exception as e:  # noqa: BLE001 — a crashed tool, injected or
            # organic, must not kill the rollout: the error text IS the
            # observation and the episode keeps its remaining turns
            state.transcripts[ep] += text
            state.turn[ep] += 1
            obs = f" ```output {type(e).__name__}: {e} ``` "
            state.transcripts[ep] += obs
            return obs, 0.0, False, time.perf_counter() - t0, True

    def finish_turn(ep: int, toks: np.ndarray):
        """EOS-of-turn: record the model span and hand the turn text to the
        environment on a tool thread (the row's pages are already released
        by the caller — a slow tool holds no pool capacity)."""
        spans[ep].append(("model", toks))
        cur_turn[ep] += 1
        fut = pool.submit(tool_step, ep, tokenizer.decode(toks))
        futures[fut] = ep

    # ---- continuation machinery (lazy: only when a turn-2 exists) ------
    Tp_ep = Tp + (max_turns - 1) * (turn_tokens + obs_budget)
    T_max = Tp_ep + turn_tokens
    R = max(1, min(int(decode_rows) if decode_rows > 0 else rows_total,
                   rows_total))
    nb = blocks_per_row(T_max, P)
    N = R * nb
    carry = None
    pstate = None
    owner = [-1] * R
    statics = dict(
        Tp=Tp_ep, max_tokens=turn_tokens, page_size=P,
        sync_every=int(sync_every), eos_token_id=eos_token_id,
        pad_token_id=pad_token_id, temperature=sampling.temperature,
        top_p=sampling.top_p, greedy=sampling.greedy,
        lora_scale=lora_scale, top_k=sampling.top_k,
        capture_logprobs=False, approx_top_k=sampling.approx_top_k,
    )

    def ensure_carry():
        nonlocal carry, pstate
        if carry is not None:
            return
        caches0 = init_paged_kv_cache(config, N, P,
                                      params["embed_tokens"].dtype)
        # radix-pattern empty carry: every row starts done; admissions
        # install episodes through the same path mid-loop recycling uses
        carry = (jnp.int32(1),
                 jnp.full((R, turn_tokens), pad_token_id, jnp.int32),
                 jnp.zeros((R, turn_tokens), jnp.float32),
                 caches0,
                 jnp.zeros((R, T_max), bool),
                 jnp.ones((R,), bool),
                 jnp.zeros((R,), jnp.int32),
                 jnp.ones((R,), jnp.int32),
                 jnp.zeros((R,), jnp.int32),
                 key)
        pstate = init_page_state(N, R, nb)

    def harvest(fut):
        """A tool result landed: either the episode ended (terminal reward)
        or its extended context joins the admission queue."""
        nonlocal completed, tool_wall_total, obs_tokens_total, tool_errors
        ep = futures.pop(fut)
        obs_text, reward, done, wall, err = fut.result()
        tool_errors += int(err)
        t = cur_turn[ep]
        turn_walls[ep].append(wall)
        tool_wall_total += wall
        turn_rewards[ep, t - 1] = reward
        if done or t >= max_turns:
            completed += 1
            return
        obs_toks = np.asarray(tokenizer.encode(obs_text),
                              np.int32)[:obs_budget]
        spans[ep].append(("obs", obs_toks))
        obs_tokens_total += int(obs_toks.size)
        ctx = np.concatenate(
            [prompt_rows[ep][pmask_rows[ep]]]
            + [s for _, s in spans[ep]]
        ).astype(np.int32)
        assert ctx.size <= Tp_ep, (ctx.size, Tp_ep)
        ids = np.full(Tp_ep, pad_token_id, np.int32)
        ids[Tp_ep - ctx.size:] = ctx
        mask = np.zeros(Tp_ep, bool)
        mask[Tp_ep - ctx.size:] = True
        pending.append((ep, ids, mask))

    # turn 1 goes through the same EOS-of-turn path as every later turn
    for ep in range(rows_total):
        finish_turn(ep, _trim_turn(toks1[ep], eos_token_id, pad_token_id))

    while completed < rows_total:
        for fut in [f for f in list(futures) if f.done()]:
            harvest(fut)
        if swaps:
            # host sync point: one non-blocking poll per loop iteration —
            # BEFORE admissions, so a re-admitted turn prefills under the
            # freshly installed params and its tokens sit past the boundary
            t0_sw = time.perf_counter()
            version, fresh = weight_refresh()
            if fresh is not None:
                # swap boundary in packed response coordinates: committed
                # span tokens + the live row's generated-so-far count. The
                # EOS trim at finish_turn can only shorten a live span, so
                # finalize clamps bounds monotonically into [0, total].
                n_gen_h = (np.asarray(carry[7])
                           if carry is not None else None)
                committed = [sum(int(t.size) for _, t in spans[ep])
                             for ep in range(rows_total)]
                if n_gen_h is not None:
                    for r in range(R):
                        if owner[r] >= 0:
                            committed[owner[r]] += int(n_gen_h[r])
                for ep in range(rows_total):
                    seg_bounds[ep].append((version, committed[ep]))
                params = fresh
                cur_version = version
                swap_installs += 1
            swap_wait_s += time.perf_counter() - t0_sw
        while pending and any(o < 0 for o in owner):
            r = next(i for i, o in enumerate(owner) if o < 0)
            ep, ids, mask = pending.popleft()
            ensure_carry()
            pstate, ok = _alloc_jit(pstate, r, nb)
            assert bool(ok), "env pool underflow: uniform page budget rows"
            # deterministic per-(episode, turn) admission key — completion
            # ORDER must not steer the PRNG stream
            admit_key = jax.random.fold_in(
                key, _ADMIT_BASE + ep * max_turns + cur_turn[ep])
            caches, t0, l0, pl = _admit_one(
                params, config, jnp.asarray(ids)[None, :],
                jnp.asarray(mask)[None, :], carry[3], pstate.table[r],
                admit_key, page_size=P, T_max=T_max,
                temperature=sampling.temperature, top_p=sampling.top_p,
                greedy=sampling.greedy, top_k=sampling.top_k,
                approx_top_k=sampling.approx_top_k, lora_scale=lora_scale,
            )
            carry = _install_row(
                carry, caches, r, t0, l0, jnp.asarray(mask), pl,
                Tp=Tp_ep, max_tokens=turn_tokens,
                eos_token_id=eos_token_id, pad_token_id=pad_token_id,
                spec=False,
            )
            owner[r] = ep
            admissions += 1
        if any(o >= 0 for o in owner):
            decode_chunks += 1
            if futures:
                overlap_chunks += 1
            carry = _decode_chunk(params, config, carry, pstate.table,
                                  **statics)
            done_h = np.asarray(carry[5])
            for r in range(R):
                if owner[r] >= 0 and done_h[r]:
                    ep = owner[r]
                    n_gen = int(np.asarray(carry[7])[r])
                    toks = np.asarray(carry[1])[r][:n_gen]
                    owner[r] = -1
                    # pages back to the pool BEFORE the tool runs: a
                    # stalled episode holds zero KV capacity
                    pstate, m = _release_jit(pstate, r)
                    pages_recycled += int(m)
                    finish_turn(
                        ep, _trim_turn(toks, eos_token_id, pad_token_id))
        elif futures:
            # decode fully idle on tool results — the stalled-rows signal
            stall_events += 1
            wait(list(futures), timeout=0.2, return_when=FIRST_COMPLETED)
        elif not pending:
            break
    pool.shutdown(wait=False)

    # ---- pack episodes + per-token loss mask ---------------------------
    out = np.full((rows_total, response_length), pad_token_id, np.int32)
    loss_mask = np.ones((rows_total, response_length), bool)
    turn_ends = np.full((rows_total, max_turns), -1, np.int64)
    turns_records: list[dict] = []
    totals = [0] * rows_total
    for ep in range(rows_total):
        cur, t_idx = 0, 0
        rec_by_turn: list[dict] = []
        for kind, toks in spans[ep]:
            L = min(int(toks.size), response_length - cur)
            out[ep, cur:cur + L] = toks[:L]
            if kind == "model":
                turn_ends[ep, t_idx] = cur + L - 1
                rec_by_turn.append({
                    "row": ep, "turn": t_idx + 1,
                    "tok_range": [cur, cur + L],
                    "reward": round(float(turn_rewards[ep, t_idx]), 6),
                    "tool_wall_s": round(
                        turn_walls[ep][t_idx], 6
                    ) if t_idx < len(turn_walls[ep]) else None,
                    "obs_range": None, "obs_tokens": 0,
                })
                t_idx += 1
            else:
                loss_mask[ep, cur:cur + L] = False
                rec_by_turn[-1]["obs_range"] = [cur, cur + L]
                rec_by_turn[-1]["obs_tokens"] = L
            cur += L
        turns_records.extend(rec_by_turn)
        totals[ep] = cur

    segments_out = None
    if swaps:
        segments_out = []
        for ep in range(rows_total):
            total = totals[ep]
            bounds, hi = [], 0
            for v, pos in seg_bounds[ep]:
                # running max + clip: the EOS trim and the response_length
                # clip can only shorten spans, so bounds stay a monotone
                # tiling of [0, total]; empty trailing segments (a swap
                # after this episode finished) are dropped by finalize
                hi = max(hi, min(int(pos), total))
                bounds.append((v, hi))
            segments_out.append(_finalize_segments(bounds, total))

    turns_count = np.asarray(cur_turn, np.float32)
    stats = {
        "env/turns_per_episode": float(turns_count.mean()),
        "env/tool_wall_s": round(tool_wall_total, 6),
        "env/obs_tokens": float(obs_tokens_total),
        "env/stalled_rows": float(stall_events),
        "env/tool_stall_overlap": (
            overlap_chunks / decode_chunks if decode_chunks else 0.0),
        "env/tool_errors": float(tool_errors),
    }
    payload = {
        "tokens": out,
        "loss_mask": loss_mask,
        "scores": turn_rewards.sum(axis=1).astype(np.float32),
        "turn_rewards": turn_rewards,
        "turn_ends": turn_ends,
        "turns": turns_records,
        "stats": stats,
        "pages_recycled": pages_recycled,
        "admissions": admissions,
    }
    if swaps:
        # conditional keys (the loss_mask pattern): present only when the
        # in-flight swap path is live, so swaps-off payloads are unchanged
        payload["segments"] = segments_out
        payload["swap_installs"] = swap_installs
        payload["swap_wait_s"] = round(swap_wait_s, 6)
    return payload
