"""Traffic harness: deterministic open-loop load generation, SLO-driven
autoscaling, and goodput sweeps (docs/TRAFFIC.md).

- workload.py   — replayable WorkloadSpec → request sequence (jax-free,
                  zero wall-clock; seed + spec replays bit-identically)
- driver.py     — open-loop multi-threaded driver (in-process ServingEngine
                  or HTTP gateway target); records client TTFT + outcomes
- autoscaler.py — hysteresis controller: SLO verdicts → add/remove_worker
- report.py     — offered-load sweep → goodput/shed/TTFT curve
"""

from nanorlhf_tpu.loadgen.workload import (  # noqa: F401
    GenRequest, WorkloadSpec, requests_digest, sample_requests, spec_digest,
)
from nanorlhf_tpu.loadgen.driver import (  # noqa: F401
    RequestRecord, TrafficDriver, TrafficSummary,
)
from nanorlhf_tpu.loadgen.autoscaler import (  # noqa: F401
    Autoscaler, AutoscalerConfig, slo_level_from_monitor,
)
from nanorlhf_tpu.loadgen.report import (  # noqa: F401
    SweepPoint, format_table, points_as_detail, run_sweep,
)
