"""SLO-driven autoscaling control loop (docs/TRAFFIC.md §4).

Closes the loop PR 13 left open: the health plane's SLO rules
(`slo_ttft_p95`, `slo_queue_wait_p99`) produce verdicts that until now
terminated in a blackbox dump. The Autoscaler reads those verdicts plus
the engine's live queue depth each `evaluate()` tick and actuates the
fleet's elastic hooks (`FleetOrchestrator.add_worker` /
`remove_worker(..., drain=True)`), under the same hysteresis discipline
health.py applies to level transitions:

- scale UP only after `breach_evals` CONSECUTIVE breached ticks (a
  single bursty tick is not a capacity problem);
- scale DOWN only after `recovery_evals` consecutive healthy ticks
  (mirror of health.py's `recovery_rows` step-down damping — recovery
  must be *sustained* before capacity is taken away);
- a shared `cooldown_s` after ANY action, so the controller observes the
  effect of its last decision before making another (workers take time
  to warm up; removing the wait is how flapping happens);
- hard `min_workers`/`max_workers` bounds, and scale-in picks the
  NEWEST worker (highest id — worker ids are monotonic) and drains it,
  so the longest-warmed workers survive and no in-flight lease is
  stranded.

The controller is deliberately clock-injectable (`clock=`) and does not
own a thread: callers decide the tick cadence (a loop, a test with a
fake clock, the e2e harness). Every decision — including deliberate
holds — is visible: actions become `autoscale` lineage events and trace
instants; holds due to cooldown are counted.

Lock order: `loadgen.autoscaler` is rank 0 in LOCK_ORDER — below
`fleet.coordinator` and `telemetry.lineage` — so actuating the fleet and
recording lineage while holding the controller lock is legal. The lock
exists because `evaluate()` may be called from a driver thread while a
test inspects counters.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional

from nanorlhf_tpu.analysis.lockorder import make_lock

# health.py's levels, re-declared as an ordering (OK < WARN < CRIT) so
# this module stays importable without the health plane
_LEVEL_RANK = {"ok": 0, "warn": 1, "crit": 2}

# the SLO rules an autoscaler watches by default (health.py SLO_RULES)
DEFAULT_SLO_RULES = ("slo_ttft_p95", "slo_queue_wait_p99")


def slo_level_from_monitor(monitor, rules=DEFAULT_SLO_RULES) -> str:
    """Worst level among `rules` in a HealthMonitor snapshot — the glue
    between health.py's verdict surface and the controller's input."""
    levels = monitor.snapshot().get("rules", {})
    worst = "ok"
    for name in rules:
        lvl = levels.get(name, "ok")
        if _LEVEL_RANK.get(lvl, 0) > _LEVEL_RANK[worst]:
            worst = lvl
    return worst


@dataclasses.dataclass(frozen=True)
class AutoscalerConfig:
    min_workers: int = 1
    max_workers: int = 4
    # consecutive breached evaluate() ticks before scaling up
    breach_evals: int = 2
    # consecutive healthy ticks before scaling down (health.py
    # recovery_rows idiom: sustained recovery, not one good sample)
    recovery_evals: int = 8
    # seconds after any action during which both directions hold
    cooldown_s: float = 5.0
    # SLO level that counts as a breach ("warn" scales earlier)
    breach_level: str = "crit"
    # queue depth that counts as a breach even while SLOs still read OK
    # (leading indicator — the queue fills before p95 TTFT degrades);
    # None disables the depth trigger
    queue_high: Optional[int] = None

    def validate(self) -> None:
        if not (1 <= self.min_workers <= self.max_workers):
            raise ValueError(
                f"need 1 <= min_workers <= max_workers, got "
                f"{self.min_workers}..{self.max_workers}")
        if self.breach_evals < 1 or self.recovery_evals < 1:
            raise ValueError("breach_evals and recovery_evals must be >= 1")
        if self.breach_level not in _LEVEL_RANK:
            raise ValueError(f"unknown breach_level {self.breach_level!r}")


class Autoscaler:
    """Hysteresis controller from SLO verdicts to fleet size.

    Pure actuator wiring: `add_worker()` returns a worker id,
    `remove_worker(worker_id)` drains and removes (the caller binds
    `drain=True` — see FleetOrchestrator.remove_worker), `worker_ids()`
    returns the live ids, `slo_level()` returns "ok"/"warn"/"crit", and
    optional `queue_depth()` returns the engine's pending count.
    """

    def __init__(self, *, add_worker: Callable[[], int],
                 remove_worker: Callable[[int], object],
                 worker_ids: Callable[[], list],
                 slo_level: Callable[[], str],
                 queue_depth: Optional[Callable[[], int]] = None,
                 config: Optional[AutoscalerConfig] = None,
                 clock: Callable[[], float] = time.monotonic,
                 lineage=None, tracer=None):
        self.cfg = config or AutoscalerConfig()
        self.cfg.validate()
        self._add_worker = add_worker
        self._remove_worker = remove_worker
        self._worker_ids = worker_ids
        self._slo_level = slo_level
        self._queue_depth = queue_depth
        self._clock = clock
        self._lineage = lineage
        self._tracer = tracer
        self._lock = make_lock("loadgen.autoscaler")
        self._breach_streak = 0
        self._ok_streak = 0
        self._last_action_t: Optional[float] = None
        self._evals = 0
        self._counters = {"scale_ups": 0, "scale_downs": 0,
                          "holds_cooldown": 0}

    # ------------------------------------------------------------- #
    # control step
    # ------------------------------------------------------------- #

    def evaluate(self) -> str:
        """One control tick. Returns the decision:
        "scale_up" | "scale_down" | "hold" | "hold_cooldown"."""
        with self._lock:
            self._evals += 1
            step = self._evals
            level = self._slo_level()
            depth = self._queue_depth() if self._queue_depth else None
            breach = (_LEVEL_RANK.get(level, 0)
                      >= _LEVEL_RANK[self.cfg.breach_level])
            if (not breach and self.cfg.queue_high is not None
                    and depth is not None
                    and depth >= self.cfg.queue_high):
                breach = True
            if breach:
                self._breach_streak += 1
                self._ok_streak = 0
            else:
                self._ok_streak += 1
                self._breach_streak = 0

            ids = sorted(self._worker_ids())
            n = len(ids)
            now = self._clock()
            cooling = (self._last_action_t is not None
                       and now - self._last_action_t < self.cfg.cooldown_s)

            action = "hold"
            worker_id = None
            if breach and self._breach_streak >= self.cfg.breach_evals:
                if n < self.cfg.max_workers:
                    if cooling:
                        action = "hold_cooldown"
                        self._counters["holds_cooldown"] += 1
                    else:
                        action = "scale_up"
            elif (not breach and self._ok_streak >= self.cfg.recovery_evals
                    and n > self.cfg.min_workers):
                if cooling:
                    action = "hold_cooldown"
                    self._counters["holds_cooldown"] += 1
                else:
                    action = "scale_down"
                    # newest worker drains out: ids are monotonic, so the
                    # longest-warmed workers keep serving
                    worker_id = ids[-1]

            if action == "scale_up":
                worker_id = self._add_worker()
                self._counters["scale_ups"] += 1
                self._breach_streak = 0
                self._last_action_t = now
            elif action == "scale_down":
                self._remove_worker(worker_id)
                self._counters["scale_downs"] += 1
                self._ok_streak = 0
                self._last_action_t = now

            if action in ("scale_up", "scale_down"):
                n_after = len(self._worker_ids())
                if self._lineage is not None and self._lineage.enabled:
                    self._lineage.event(
                        "autoscale", action=action, worker_id=worker_id,
                        workers_before=n, workers_after=n_after,
                        level=level, queue_depth=depth, eval=step)
                if self._tracer is not None and self._tracer.enabled:
                    self._tracer.instant(
                        f"autoscale.{action}", worker_id=worker_id,
                        workers=n_after, level=level)
            return action

    # ------------------------------------------------------------- #
    # observability
    # ------------------------------------------------------------- #

    def metrics(self) -> dict:
        with self._lock:
            return {
                "loadgen/scale_ups": self._counters["scale_ups"],
                "loadgen/scale_downs": self._counters["scale_downs"],
                "loadgen/holds_cooldown": self._counters["holds_cooldown"],
            }

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "evals": self._evals,
                "breach_streak": self._breach_streak,
                "ok_streak": self._ok_streak,
                "workers": sorted(self._worker_ids()),
                "counters": dict(self._counters),
                "config": dataclasses.asdict(self.cfg),
            }
