"""Open-loop traffic driver (docs/TRAFFIC.md §3).

Fires a materialized workload (workload.py) at a serving target on the
spec's arrival schedule, OPEN LOOP: the scheduler sleeps to each
request's `t_offset` and fires regardless of how many earlier requests
are still in flight — completion never gates arrival, so offered load is
exactly what the spec says and saturation shows up as shedding and TTFT
degradation instead of being silently absorbed by a closing loop (the
measurement honesty arxiv 2605.25645's goodput curves depend on).

Two targets, same records:

- in-process (`engine=`): `ServingEngine.submit()`/`stream()` directly —
  the CPU-CI mode the `traffic-smoke` tier-1 step and bench
  `detail.traffic` use (no sockets, deterministic shed reasons).
- HTTP (`base_url=`): `POST /generate` with `"stream": true` against a
  ServingGateway; a 429 is recorded as a shed with the gateway's JSON
  reason and its `Retry-After` header — which the driver deliberately
  IGNORES (an open-loop client never retries or backs off; the header
  exists for well-behaved closed-loop clients and dashboards).

Per-request outcomes land in three places: the shared LatencyHub
(`latency/client_ttft_s` / `latency/client_total_s` — CLIENT-side, so
queue wait inside the engine is included, unlike the engine's own
`latency/ttft_s` which starts at submit), the driver's `loadgen/*`
counters (METRICS.md), and one `traffic` lineage event per request plus
a `traffic_run` header event — enough for `tools/inspect_run.py
--traffic` to rebuild the offered/goodput/shed timeline jax-free from
the ledger alone.

Lock order: `loadgen.driver` is ranked BELOW every lock the firing path
takes (serving.engine, telemetry.hist, telemetry.lineage) in LOCK_ORDER;
the driver still never calls out while holding its lock — the lock only
guards the counters and the per-run record list.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
import urllib.error
import urllib.request
from typing import Optional

from nanorlhf_tpu.analysis.lockorder import make_lock
from nanorlhf_tpu.resilience.faults import InjectedFault
from nanorlhf_tpu.loadgen.workload import (
    KEY_PATH, WorkloadSpec, sample_requests, spec_digest,
)

_COUNTER_KEYS = ("offered", "completed", "shed", "errors")


@dataclasses.dataclass
class RequestRecord:
    """One fired request's observed outcome (client side)."""

    index: int
    t_offset: float
    outcome: str                  # "completed" | "shed" | "error"
    reason: Optional[str] = None  # shed reason / error class
    ttft_s: Optional[float] = None
    total_s: Optional[float] = None
    tokens: int = 0               # generated tokens observed
    retry_after_s: Optional[float] = None  # HTTP 429 header (recorded,
                                           # never obeyed — open loop)


@dataclasses.dataclass
class TrafficSummary:
    """One run's aggregate — the row a sweep point (report.py) keeps."""

    offered: int
    completed: int
    shed: int
    errors: int
    duration_s: float
    offered_rps: float
    goodput_rps: float
    shed_frac: float
    shed_reasons: dict
    p50_ttft_s: Optional[float]
    p95_ttft_s: Optional[float]
    records: list


class TrafficDriver:
    """Open-loop load generator over one target. Reusable across runs;
    counters are cumulative, rates are per-run. `time_scale` compresses
    the spec's arrival timeline (0.1 = 10× faster) without changing the
    sequence — CI runs the same replayable workload, just denser."""

    def __init__(self, *, engine=None, base_url: Optional[str] = None,
                 latency=None, lineage=None, tracer=None, faults=None,
                 stream_timeout_s: float = 120.0, time_scale: float = 1.0):
        if (engine is None) == (base_url is None):
            raise ValueError(
                "exactly one of engine= (in-process) or base_url= (HTTP) "
                "selects the target")
        self._engine = engine
        # gw.disconnect for the in-process target: the driver IS the
        # client, so a fire makes THIS client vanish mid-stream and call
        # engine.cancel — the same page-release path the gateway drives
        # for HTTP clients (where the site is armed on the gateway side)
        self._faults = faults
        self._base_url = base_url.rstrip("/") if base_url else None
        self._hub = latency if (latency is not None
                                and latency.enabled) else None
        self._lineage = lineage
        self._tracer = tracer
        self.stream_timeout_s = float(stream_timeout_s)
        self.time_scale = float(time_scale)
        self._lock = make_lock("loadgen.driver")
        self._counters = {k: 0 for k in _COUNTER_KEYS}
        self._shed_reasons: dict = {}
        self._records: list = []
        self._last_duration_s = 0.0
        self._last_offered = 0
        self._last_completed = 0

    # ------------------------------------------------------------- #
    # run
    # ------------------------------------------------------------- #

    def run(self, spec) -> TrafficSummary:
        """Fire one workload to completion (all request threads joined or
        timed out). `spec` is a WorkloadSpec or a pre-materialized
        request sequence."""
        if isinstance(spec, WorkloadSpec):
            reqs = sample_requests(spec)
            digest = spec_digest(spec)
            meta = {"n_requests": spec.n_requests,
                    "rate_rps": spec.rate_rps, "arrival": spec.arrival,
                    "seed": spec.seed}
        else:
            reqs = tuple(spec)
            digest = None
            meta = {"n_requests": len(reqs)}
        with self._lock:
            self._records = []
        if self._lineage is not None and self._lineage.enabled:
            self._lineage.event(
                "traffic_run", spec_digest=digest, key_path=KEY_PATH,
                time_scale=self.time_scale,
                mode="inprocess" if self._engine is not None else "http",
                **meta)
        if self._tracer is not None and self._tracer.enabled:
            self._tracer.instant("traffic.run_start", n=len(reqs))

        t0 = time.perf_counter()
        threads = []
        for rq in reqs:
            # open loop: sleep to the arrival offset, fire, move on —
            # in-flight count never gates the schedule
            delay = t0 + rq.t_offset * self.time_scale - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            th = threading.Thread(
                target=self._fire, args=(rq,), daemon=True,
                name=f"loadgen-{rq.index}")
            th.start()
            threads.append(th)
        deadline = time.perf_counter() + self.stream_timeout_s
        for th in threads:
            th.join(timeout=max(0.1, deadline - time.perf_counter()))
        duration = time.perf_counter() - t0

        with self._lock:
            records = sorted(self._records, key=lambda r: r.index)
            self._last_duration_s = duration
            self._last_offered = len(reqs)
            self._last_completed = sum(
                1 for r in records if r.outcome == "completed")
        summary = self._summarize(records, duration)
        if self._tracer is not None and self._tracer.enabled:
            self._tracer.instant(
                "traffic.run_end", completed=summary.completed,
                shed=summary.shed)
        return summary

    def _summarize(self, records, duration: float) -> TrafficSummary:
        completed = [r for r in records if r.outcome == "completed"]
        shed = [r for r in records if r.outcome == "shed"]
        errors = [r for r in records if r.outcome == "error"]
        reasons: dict = {}
        for r in shed:
            reasons[r.reason or "unknown"] = (
                reasons.get(r.reason or "unknown", 0) + 1)
        ttfts = sorted(r.ttft_s for r in completed if r.ttft_s is not None)

        def pct(q: float):
            if not ttfts:
                return None
            pos = q * (len(ttfts) - 1)
            lo = int(pos)
            hi = min(lo + 1, len(ttfts) - 1)
            return ttfts[lo] + (ttfts[hi] - ttfts[lo]) * (pos - lo)

        n = len(records)
        return TrafficSummary(
            offered=n, completed=len(completed), shed=len(shed),
            errors=len(errors), duration_s=duration,
            offered_rps=n / duration if duration > 0 else 0.0,
            goodput_rps=len(completed) / duration if duration > 0 else 0.0,
            shed_frac=len(shed) / n if n else 0.0,
            shed_reasons=reasons, p50_ttft_s=pct(0.50),
            p95_ttft_s=pct(0.95), records=records,
        )

    # ------------------------------------------------------------- #
    # firing paths (one thread per request)
    # ------------------------------------------------------------- #

    def _fire(self, rq) -> None:
        t_send = time.perf_counter()
        try:
            if self._engine is not None:
                rec = self._fire_inprocess(rq, t_send)
            else:
                rec = self._fire_http(rq, t_send)
        except Exception as e:  # a client bug must not kill the run
            rec = RequestRecord(index=rq.index, t_offset=rq.t_offset,
                                outcome="error",
                                reason=type(e).__name__)
        if self._hub is not None:
            if rec.ttft_s is not None:
                self._hub.record("latency/client_ttft_s", rec.ttft_s)
            if rec.total_s is not None:
                self._hub.record("latency/client_total_s", rec.total_s)
        if self._lineage is not None and self._lineage.enabled:
            self._lineage.event(
                "traffic", request_index=rq.index,
                t_offset=round(rq.t_offset, 6), outcome=rec.outcome,
                reason=rec.reason,
                ttft_s=(round(rec.ttft_s, 6)
                        if rec.ttft_s is not None else None),
                total_s=(round(rec.total_s, 6)
                         if rec.total_s is not None else None),
                tokens=rec.tokens,
                prefix_group=(rq.prefix_group
                              if rq.prefix_group >= 0 else None))
        with self._lock:
            self._records.append(rec)
            self._counters["offered"] += 1
            self._counters[rec.outcome if rec.outcome in _COUNTER_KEYS
                           else "errors"] += 1
            if rec.outcome == "shed":
                key = rec.reason or "unknown"
                self._shed_reasons[key] = self._shed_reasons.get(key, 0) + 1

    def _fire_inprocess(self, rq, t_send: float) -> RequestRecord:
        req, reason = self._engine.submit(
            list(rq.tokens), temperature=rq.temperature, top_p=rq.top_p,
            greedy=rq.greedy, max_tokens=rq.max_tokens)
        if req is None:
            return RequestRecord(index=rq.index, t_offset=rq.t_offset,
                                 outcome="shed", reason=reason)
        ttft = None
        n = 0
        for _tok in self._engine.stream(req, timeout=self.stream_timeout_s):
            if n == 0:
                ttft = time.perf_counter() - t_send
            n += 1
            if self._disconnect_fires():
                # this client vanishes mid-stream: tell the engine so the
                # row stops decoding and its KV pages are released
                self._engine.cancel(req)
                return RequestRecord(
                    index=rq.index, t_offset=rq.t_offset, outcome="error",
                    reason="disconnect", ttft_s=ttft,
                    total_s=time.perf_counter() - t_send, tokens=n)
        if n == 0:
            # an admitted request whose stream ended with zero tokens:
            # the engine aborted it (pool shed) or the stream timed out
            return RequestRecord(index=rq.index, t_offset=rq.t_offset,
                                 outcome="shed", reason="engine_abort")
        return RequestRecord(
            index=rq.index, t_offset=rq.t_offset, outcome="completed",
            ttft_s=ttft, total_s=time.perf_counter() - t_send, tokens=n)

    def _fire_http(self, rq, t_send: float) -> RequestRecord:
        body = json.dumps({
            "tokens": list(rq.tokens), "temperature": rq.temperature,
            "top_p": rq.top_p, "greedy": rq.greedy,
            "max_tokens": rq.max_tokens, "stream": True,
        }).encode()
        http_req = urllib.request.Request(
            self._base_url + "/generate", data=body,
            headers={"Content-Type": "application/json"})
        try:
            resp = urllib.request.urlopen(
                http_req, timeout=self.stream_timeout_s)
        except urllib.error.HTTPError as e:
            if e.code == 429:
                try:
                    reason = json.loads(e.read()).get("reason", "unknown")
                except (ValueError, OSError):
                    reason = "unknown"
                ra = e.headers.get("Retry-After")
                return RequestRecord(
                    index=rq.index, t_offset=rq.t_offset, outcome="shed",
                    reason=reason,
                    retry_after_s=float(ra) if ra else None)
            return RequestRecord(index=rq.index, t_offset=rq.t_offset,
                                 outcome="error", reason=f"http_{e.code}")
        ttft = None
        n = 0
        saw_done = False
        with resp:
            for line in resp:
                line = line.strip()
                if not line:
                    continue
                obj = json.loads(line)
                if "token" in obj:
                    if n == 0:
                        ttft = time.perf_counter() - t_send
                    n += 1
                if obj.get("done"):
                    saw_done = True
                    break
        if n == 0:
            return RequestRecord(index=rq.index, t_offset=rq.t_offset,
                                 outcome="shed", reason="engine_abort")
        if not saw_done:
            # the stream ended without the final done line — the gateway
            # aborted it (its gw.disconnect site, or a server-side write
            # failure); an unfinished stream must not count as goodput
            return RequestRecord(
                index=rq.index, t_offset=rq.t_offset, outcome="error",
                reason="disconnect", ttft_s=ttft,
                total_s=time.perf_counter() - t_send, tokens=n)
        return RequestRecord(
            index=rq.index, t_offset=rq.t_offset, outcome="completed",
            ttft_s=ttft, total_s=time.perf_counter() - t_send, tokens=n)

    def _disconnect_fires(self) -> bool:
        """True when the gw.disconnect site fires for this client (any
        action — a raising schedule is the same vanished client)."""
        if self._faults is None:
            return False
        try:
            return self._faults.fire("gw.disconnect") is not None
        except InjectedFault:
            return True

    # ------------------------------------------------------------- #
    # observability
    # ------------------------------------------------------------- #

    def metrics(self) -> dict:
        """Flat `loadgen/*` rows (docs/METRICS.md): cumulative counters
        plus the LAST run's offered/goodput rates."""
        with self._lock:
            c = dict(self._counters)
            dur = self._last_duration_s
            offered = self._last_offered
            done = self._last_completed
            reasons = dict(self._shed_reasons)
        out = {
            "loadgen/offered": c["offered"],
            "loadgen/completed": c["completed"],
            "loadgen/shed": c["shed"],
            "loadgen/errors": c["errors"],
            "loadgen/offered_rps": round(offered / dur, 4) if dur else 0.0,
            "loadgen/goodput_rps": round(done / dur, 4) if dur else 0.0,
            "loadgen/shed_frac": round(c["shed"] / c["offered"], 4)
                                 if c["offered"] else 0.0,
        }
        for reason, count in sorted(reasons.items()):
            out[f'loadgen/shed_total{{reason="{reason}"}}'] = count
        return out
