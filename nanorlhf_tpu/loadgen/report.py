"""Offered-load sweep → goodput curve (docs/TRAFFIC.md §5).

A single episodes/s number hides the part of the serving story that
matters under load: where goodput stops tracking offered load, how much
traffic is shed past that knee, and how far p95 TTFT degrades before
admission control kicks in. `run_sweep` replays the SAME workload spec
at a grid of offered rates (only `rate_rps` varies; the seed and every
distribution stay fixed, so the curve is deterministic and
regression-testable — the arxiv 2605.25645 goodput-vs-offered-load
framing) and tabulates one SweepPoint per rate.

The sweep owns no engine: the caller passes `run_point(spec)` which must
build a FRESH target per point (bench.py's `detail.traffic` does this so
shed state and hub histograms never bleed across rates), run a
TrafficDriver over it, and return the TrafficSummary. jax-free.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Iterable

from nanorlhf_tpu.loadgen.workload import WorkloadSpec


@dataclasses.dataclass(frozen=True)
class SweepPoint:
    """One offered-load grid point's aggregate row."""

    offered_rps: float      # what the spec asked for (nominal rate)
    achieved_rps: float     # what the open-loop driver actually offered
    goodput_rps: float      # completed requests per second
    shed_frac: float
    completed: int
    shed: int
    errors: int
    p50_ttft_s: float | None
    p95_ttft_s: float | None


def run_sweep(run_point: Callable, spec: WorkloadSpec,
              rates: Iterable[float]) -> list[SweepPoint]:
    """Replay `spec` at each rate in `rates`; one SweepPoint per rate."""
    points: list[SweepPoint] = []
    for rate in rates:
        point_spec = dataclasses.replace(spec, rate_rps=float(rate))
        summary = run_point(point_spec)
        points.append(SweepPoint(
            offered_rps=float(rate),
            achieved_rps=round(summary.offered_rps, 4),
            goodput_rps=round(summary.goodput_rps, 4),
            shed_frac=round(summary.shed_frac, 4),
            completed=summary.completed,
            shed=summary.shed,
            errors=summary.errors,
            p50_ttft_s=(round(summary.p50_ttft_s, 6)
                        if summary.p50_ttft_s is not None else None),
            p95_ttft_s=(round(summary.p95_ttft_s, 6)
                        if summary.p95_ttft_s is not None else None),
        ))
    return points


def points_as_detail(points: list[SweepPoint]) -> dict:
    """Column-oriented dict for bench.py's `detail.traffic` JSON."""
    return {
        "offered_rps": [p.offered_rps for p in points],
        "goodput_rps": [p.goodput_rps for p in points],
        "shed_frac": [p.shed_frac for p in points],
        "p95_ttft_s": [p.p95_ttft_s for p in points],
        "completed": [p.completed for p in points],
        "shed": [p.shed for p in points],
        "errors": [p.errors for p in points],
    }


def format_table(points: list[SweepPoint]) -> str:
    """Human-readable curve (inspect_run / bench stderr)."""
    header = (f"{'offered':>9} {'goodput':>9} {'shed%':>7} "
              f"{'p50_ttft':>10} {'p95_ttft':>10} {'done':>6} {'shed':>6}")
    lines = [header]
    for p in points:
        p50 = f"{p.p50_ttft_s:.4f}" if p.p50_ttft_s is not None else "-"
        p95 = f"{p.p95_ttft_s:.4f}" if p.p95_ttft_s is not None else "-"
        lines.append(
            f"{p.offered_rps:>9.2f} {p.goodput_rps:>9.2f} "
            f"{100.0 * p.shed_frac:>6.1f}% {p50:>10} {p95:>10} "
            f"{p.completed:>6d} {p.shed:>6d}")
    return "\n".join(lines)
