"""Deterministic open-loop workload specs (docs/TRAFFIC.md §1-2).

A `WorkloadSpec` + a seed IS the traffic: `sample_requests(spec)` expands
it into a fully materialized request sequence — arrival offsets, prompt
tokens, per-request sampling params, token budgets — with ZERO wall-clock
reads and zero global RNG state, so the same spec replays the bit-
identical sequence on any host (the acceptance pin in
tests/test_loadgen.py). This is what makes offered load a *spec property*
rather than a measurement: the driver (driver.py) fires the sequence
open-loop and never applies back-pressure, so saturation and shedding
become observable instead of being absorbed by a closing loop.

PRNG discipline mirrors the project's lineage convention
(docs/OBSERVABILITY.md §6): every request's entropy derives from
``fold_in(fold_in(seed, _ROOT), request_index)`` and per-field
sub-streams fold a named constant into the request key — no key is ever
consumed twice, and the derivation path is recorded (`KEY_PATH`) so a
ledger reader can re-derive any request from the seed alone. The
generator is jax-free on purpose (splitmix64, Vigna 2015): the traffic
harness must run in the same jax-less contexts as the telemetry readers
(tools/inspect_run.py, CPU CI collection), and a 64-bit mix gives the
replay guarantee without importing an accelerator runtime.

Arrival processes:

- ``"poisson"``: memoryless inter-arrivals at `rate_rps` — the classic
  open-system model (the serving-comparison framing of
  arxiv 2605.25645's offered-load sweeps).
- ``"bursty"``: a 2-state Markov-modulated Poisson process. The chain
  alternates calm/burst states with exponential holding times; the burst
  state multiplies the calm rate by `burst_factor`, and `burst_frac`
  fixes the stationary fraction of time spent bursting, so the MEAN rate
  stays exactly `rate_rps` — curves at the same offered load are
  comparable across arrival shapes. Sampling is exact (memorylessness
  lets an inter-arrival that crosses a state boundary restart at the
  boundary under the new rate, with a fresh sub-key per attempt).

Prefix overlap: `prefix_groups` tenants each own a fixed shared prefix
(`prefix_len` real tokens, derived from the seed); a request joins a
group with probability `prefix_frac` and prepends that group's prefix to
its unique suffix. This exercises the radix prefix cache
(serving/radix.py) the way multi-tenant traffic does — repeat admissions
within a group install refcount-shared pages instead of prefilling.
"""

from __future__ import annotations

import dataclasses
import hashlib
import math

_MASK64 = (1 << 64) - 1
_GAMMA = 0x9E3779B97F4A7C15  # splitmix64 weyl increment

# root stream id: request keys are fold_in(fold_in(seed, _ROOT), index)
_ROOT = 0x7F1C
# per-request sub-streams (folded into the request key)
_SUB_ARRIVAL, _SUB_LEN, _SUB_TOKENS, _SUB_PARAMS, _SUB_PREFIX = 1, 2, 3, 4, 5
# spec-level streams (folded into the root key)
_STREAM_STATE = 0x51A7E   # bursty-chain holding times
_STREAM_GROUPS = 0x6709   # shared-prefix token material

#: the documented derivation path for request `i`'s key — recorded in the
#: driver's `traffic_run` lineage event so a ledger reader can re-derive
#: the full sequence from the seed alone
KEY_PATH = "fold_in(fold_in(seed, 0x7F1C), request_index)"


def _mix(x: int) -> int:
    """splitmix64 finalizer: bijective 64-bit avalanche."""
    x &= _MASK64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4B5B9) & _MASK64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _MASK64
    return x ^ (x >> 31)


def fold_in(key: int, data: int) -> int:
    """Derive a child key — the jax.random.fold_in analogue of the
    lineage PRNG discipline, jax-free. Pure function of (key, data);
    +1 keeps fold_in(k, 0) distinct from k's own draw stream."""
    return _mix((key + _GAMMA * ((int(data) & _MASK64) + 1)) & _MASK64)


def uniform(key: int) -> float:
    """One double in [0, 1) with 53 random bits, from the key alone.
    Keys are never reused: derive a fresh sub-key per draw."""
    return (_mix(key ^ _GAMMA) >> 11) / float(1 << 53)


def randint(key: int, lo: int, hi: int) -> int:
    """One int in [lo, hi) from the key alone (hi exclusive, hi > lo)."""
    return lo + int(uniform(key) * (hi - lo))


def _exponential(key: int, rate: float) -> float:
    """One Exp(rate) draw; uniform() < 1 keeps log() finite."""
    return -math.log(1.0 - uniform(key)) / rate


@dataclasses.dataclass(frozen=True)
class GenRequest:
    """One materialized request of a workload. Immutable and fully
    value-typed (token tuple, plain floats) so two samplings of the same
    spec compare ==, field for field — the replay contract."""

    index: int
    t_offset: float               # arrival offset from run start, seconds
    tokens: tuple                 # prompt token ids (real, un-padded)
    temperature: float
    top_p: float
    greedy: bool
    max_tokens: int
    prefix_group: int             # shared-prefix tenant, -1 = cold prompt
    key: int                      # fold_in-derived request key (KEY_PATH)


@dataclasses.dataclass(frozen=True)
class WorkloadSpec:
    """Replayable traffic description — the grammar in docs/TRAFFIC.md.

    `rate_rps` is the MEAN offered rate for both arrival shapes; the
    sweep surface (report.py) varies only this field across a grid, so
    every other distribution is held fixed along a goodput curve."""

    seed: int = 0
    n_requests: int = 64
    rate_rps: float = 8.0
    arrival: str = "poisson"      # "poisson" | "bursty"
    burst_factor: float = 4.0     # bursty: burst rate = calm rate × this
    burst_frac: float = 0.25      # bursty: stationary fraction bursting
    mean_burst_s: float = 1.0     # bursty: mean burst holding time
    prompt_len_min: int = 4       # real prompt tokens, inclusive
    prompt_len_max: int = 12      # inclusive
    token_lo: int = 4             # prompt token id range [lo, hi)
    token_hi: int = 60
    prefix_groups: int = 4        # shared-prefix tenants (0 = all cold)
    prefix_frac: float = 0.5      # P(request joins a tenant)
    prefix_len: int = 4           # shared real tokens per tenant
    greedy_frac: float = 0.5      # P(greedy decode)
    temp_min: float = 0.7         # sampled requests: temperature range
    temp_max: float = 1.3
    top_p_min: float = 0.8
    top_p_max: float = 1.0
    max_tokens_min: int = 4       # per-request token budget, inclusive
    max_tokens_max: int = 16      # inclusive

    def validate(self) -> None:
        if self.n_requests < 1:
            raise ValueError(f"n_requests={self.n_requests} must be >= 1")
        if self.rate_rps <= 0.0:
            raise ValueError(f"rate_rps={self.rate_rps} must be > 0")
        if self.arrival not in ("poisson", "bursty"):
            raise ValueError(
                f"arrival={self.arrival!r}: 'poisson' | 'bursty'")
        if not 0 < self.prompt_len_min <= self.prompt_len_max:
            raise ValueError(
                f"prompt length range [{self.prompt_len_min}, "
                f"{self.prompt_len_max}] invalid")
        if self.token_hi <= self.token_lo:
            raise ValueError("token_hi must exceed token_lo")
        if self.prefix_groups and not (
                0 < self.prefix_len <= self.prompt_len_max):
            raise ValueError(
                f"prefix_len={self.prefix_len} outside "
                f"(0, prompt_len_max={self.prompt_len_max}]")
        if not 0 < self.burst_frac < 1:
            raise ValueError(f"burst_frac={self.burst_frac} outside (0, 1)")
        if self.burst_factor <= 1.0:
            raise ValueError(
                f"burst_factor={self.burst_factor} must be > 1")
        if not 1 <= self.max_tokens_min <= self.max_tokens_max:
            raise ValueError("max_tokens range invalid")


def spec_digest(spec: WorkloadSpec) -> str:
    """Stable short digest of a spec (seed included) — stamped into the
    `traffic_run` lineage event so offline readers can tell two sweeps'
    ledgers apart and pin replay identity across hosts."""
    payload = repr(dataclasses.astuple(spec)).encode()
    return hashlib.sha256(payload).hexdigest()[:16]


def _arrival_offsets(spec: WorkloadSpec, root: int) -> list:
    """Cumulative arrival offsets for every request, exact under both
    arrival shapes. Bursty: state intervals are drawn lazily from their
    own stream; an inter-arrival crossing a boundary restarts AT the
    boundary under the new rate (exact by memorylessness), each attempt
    on a fresh sub-key."""
    if spec.arrival == "poisson":
        out, t = [], 0.0
        for i in range(spec.n_requests):
            akey = fold_in(fold_in(root, i), _SUB_ARRIVAL)
            t += _exponential(akey, spec.rate_rps)
            out.append(t)
        return out

    # bursty: calm rate chosen so the stationary mean is exactly rate_rps
    calm = spec.rate_rps / (
        (1.0 - spec.burst_frac) + spec.burst_frac * spec.burst_factor)
    burst = calm * spec.burst_factor
    mean_calm_s = spec.mean_burst_s * (1.0 - spec.burst_frac) / spec.burst_frac
    skey = fold_in(root, _STREAM_STATE)

    def holding(j: int) -> float:
        mean = spec.mean_burst_s if j % 2 else mean_calm_s  # even = calm
        return _exponential(fold_in(skey, j), 1.0 / mean)

    out, t = [], 0.0
    j = 0                       # state interval index (even = calm)
    end = holding(0)            # current interval's end time
    for i in range(spec.n_requests):
        attempt = 0
        while True:
            rate = burst if j % 2 else calm
            akey = fold_in(fold_in(root, i), _SUB_ARRIVAL)
            d = _exponential(fold_in(akey, attempt), rate)
            if t + d <= end or attempt >= 64:
                t += min(d, max(end - t, 0.0)) if attempt >= 64 else d
                break
            t = end
            j += 1
            end += holding(j)
            attempt += 1
        out.append(t)
        while t > end:          # skip intervals an arrival overshot
            j += 1
            end += holding(j)
    return out


def _group_prefixes(spec: WorkloadSpec, root: int) -> list:
    gkey = fold_in(root, _STREAM_GROUPS)
    return [
        tuple(
            randint(fold_in(fold_in(gkey, g), k),
                    spec.token_lo, spec.token_hi)
            for k in range(spec.prefix_len)
        )
        for g in range(spec.prefix_groups)
    ]


def sample_requests(spec: WorkloadSpec) -> tuple:
    """Expand a spec into its full request sequence — pure function of
    the spec (wall-clock-free), bit-identical across calls and hosts."""
    spec.validate()
    root = fold_in(spec.seed, _ROOT)
    offsets = _arrival_offsets(spec, root)
    prefixes = _group_prefixes(spec, root)

    reqs = []
    for i in range(spec.n_requests):
        rkey = fold_in(root, i)
        pkey = fold_in(rkey, _SUB_PREFIX)
        group = -1
        if prefixes and uniform(fold_in(pkey, 0)) < spec.prefix_frac:
            group = randint(fold_in(pkey, 1), 0, len(prefixes))

        lkey = fold_in(rkey, _SUB_LEN)
        n = randint(lkey, spec.prompt_len_min, spec.prompt_len_max + 1)
        prefix = prefixes[group] if group >= 0 else ()
        if group >= 0:
            # a tenant request always carries its full prefix plus at
            # least one unique token (a pure-prefix prompt would make
            # two requests literally identical, hiding COW splits)
            n = max(n, len(prefix) + 1)
            n = min(n, spec.prompt_len_max) if (
                spec.prompt_len_max > len(prefix)) else len(prefix) + 1
        tkey = fold_in(rkey, _SUB_TOKENS)
        suffix = tuple(
            randint(fold_in(tkey, k), spec.token_lo, spec.token_hi)
            for k in range(n - len(prefix))
        )

        skey = fold_in(rkey, _SUB_PARAMS)
        greedy = uniform(fold_in(skey, 0)) < spec.greedy_frac
        temp = (spec.temp_min
                + uniform(fold_in(skey, 1))
                * (spec.temp_max - spec.temp_min))
        top_p = (spec.top_p_min
                 + uniform(fold_in(skey, 2))
                 * (spec.top_p_max - spec.top_p_min))
        budget = randint(fold_in(skey, 3), spec.max_tokens_min,
                         spec.max_tokens_max + 1)

        reqs.append(GenRequest(
            index=i, t_offset=offsets[i], tokens=prefix + suffix,
            temperature=round(temp, 6), top_p=round(top_p, 6),
            greedy=greedy, max_tokens=budget, prefix_group=group,
            key=rkey,
        ))
    return tuple(reqs)


def requests_digest(reqs) -> str:
    """Stable digest over a materialized sequence — the replay-identity
    check two hosts (or two CI runs) compare."""
    h = hashlib.sha256()
    for r in reqs:
        h.update(repr((r.index, round(r.t_offset, 12), r.tokens,
                       r.temperature, r.top_p, r.greedy, r.max_tokens,
                       r.prefix_group)).encode())
    return h.hexdigest()[:16]
