"""Native (C++) host-runtime components, loaded via ctypes.

Compiled on first import with the baked-in g++ (no pip installs available;
pybind11 absent — a plain C ABI + ctypes keeps the binding surface zero-
dependency). Every entry point has a pure-Python fallback, so the framework
degrades gracefully on hosts without a toolchain; tests pin native ==
Python semantics.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile

import numpy as np

_LIB = None
_TRIED = False


def _build_and_load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    here = os.path.dirname(__file__)
    srcs = [os.path.join(here, f) for f in ("bucketing.cpp", "token_cache.cpp")]
    cache_dir = os.path.join(
        os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
        "nanorlhf_tpu",
    )
    os.makedirs(cache_dir, exist_ok=True)
    so_path = os.path.join(cache_dir, "libnanorlhf_native.so")
    try:
        if (not os.path.exists(so_path)
                or os.path.getmtime(so_path) < max(map(os.path.getmtime, srcs))):
            # pid-unique tmp: concurrent processes (pytest workers, multi-host
            # launchers sharing $HOME) must not clobber each other mid-write
            tmp_path = f"{so_path}.{os.getpid()}.tmp"
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", *srcs, "-o",
                 tmp_path],
                check=True, capture_output=True, timeout=120,
            )
            os.replace(tmp_path, so_path)
        lib = ctypes.CDLL(so_path)
        lib.create_batches.restype = ctypes.c_int
        lib.create_batches.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ]
        for fn in (lib.pack_left_pad, lib.pack_right_pad):
            fn.restype = None
            fn.argtypes = [
                ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int, ctypes.c_int, ctypes.c_int32,
                ctypes.POINTER(ctypes.c_int32),
            ]
        lib.token_cache_write.restype = ctypes.c_int
        lib.token_cache_write.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, ctypes.c_uint64,
        ]
        lib.token_cache_stat.restype = ctypes.c_int
        lib.token_cache_stat.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ]
        lib.token_cache_open.restype = ctypes.c_int
        lib.token_cache_open.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.token_cache_close.restype = None
        lib.token_cache_close.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        _LIB = lib
    except Exception as e:  # missing toolchain etc. → Python fallback
        detail = ""
        stderr = getattr(e, "stderr", None)
        if stderr:
            detail = ": " + stderr.decode(errors="replace")[-500:]
        print(f"[native] build/load failed ({type(e).__name__}{detail}), "
              "using Python fallbacks")
        _LIB = None
    return _LIB


def available() -> bool:
    return _build_and_load() is not None


def create_batches_native(lengths, budget: int):
    """Native bucket packing; returns list[list[int]] (or None w/o lib)."""
    lib = _build_and_load()
    if lib is None:
        return None
    lengths = np.ascontiguousarray(np.asarray(lengths, np.int64))
    n = len(lengths)
    out_indices = np.empty(n, np.int32)
    out_offsets = np.empty(n + 1, np.int32)
    n_buckets = lib.create_batches(
        lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n, int(budget),
        out_indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        out_offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
    )
    return [
        out_indices[out_offsets[b]:out_offsets[b + 1]].tolist()
        for b in range(n_buckets)
    ]


def _pack(rows, max_len: int, pad_id: int, left: bool):
    lib = _build_and_load()
    if lib is None:
        return None
    lens = np.asarray([len(r) for r in rows], np.int64)
    flat = np.ascontiguousarray(
        np.concatenate([np.asarray(r, np.int32) for r in rows])
        if len(rows) else np.empty(0, np.int32)
    )
    out = np.empty((len(rows), max_len), np.int32)
    fn = lib.pack_left_pad if left else lib.pack_right_pad
    fn(
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(rows), max_len, pad_id,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return out


def pack_left_pad_native(rows, max_len: int, pad_id: int):
    return _pack(rows, max_len, pad_id, left=True)


def pack_right_pad_native(rows, max_len: int, pad_id: int):
    return _pack(rows, max_len, pad_id, left=False)


# --------------------------------------------------------------------------
# Token-cache file (token_cache.cpp): mmap-backed tokenized-corpus cache
# --------------------------------------------------------------------------


class TokenCacheView:
    """Zero-copy view over an open native token cache. `offsets` and `flat`
    are numpy arrays aliasing the mmap — valid until `close()`."""

    def __init__(self, base, length, offsets, flat, n_rows):
        self._base, self._len = base, length
        self.offsets, self.flat, self.n_rows = offsets, flat, n_rows

    def row(self, i: int) -> np.ndarray:
        return self.flat[self.offsets[i]:self.offsets[i + 1]]

    def close(self):
        lib = _build_and_load()
        if lib is not None and self._base:
            lib.token_cache_close(self._base, self._len)
            self._base = None

    def __del__(self):  # cache-hit loads must not leak the mapping
        try:
            self.close()
        except Exception:
            pass  # interpreter teardown: module globals may be gone

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def flatten_rows(rows) -> tuple[np.ndarray, np.ndarray]:
    """(offsets int64 [n+1], flat int32) for a ragged corpus — the ONE
    flattening both cache writers share (the C++/Python interop guarantee
    rests on the two writers producing identical bytes)."""
    lens = np.asarray([len(r) for r in rows], np.int64)
    offsets = np.concatenate([[0], np.cumsum(lens)]).astype(np.int64)
    flat = np.ascontiguousarray(
        np.concatenate([np.asarray(r, np.int32) for r in rows])
        if len(rows) and offsets[-1] else np.empty(0, np.int32)
    )
    return offsets, flat


def token_cache_write_native(path: str, rows, fingerprint: int) -> bool:
    """Write a ragged int32 corpus to the cache file (atomic). False w/o lib."""
    lib = _build_and_load()
    if lib is None:
        return False
    offsets, flat = flatten_rows(rows)
    rc = lib.token_cache_write(
        path.encode(), flat.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(rows), ctypes.c_uint64(fingerprint & (2**64 - 1)),
    )
    return rc == 0


def token_cache_open_native(path: str, fingerprint: int) -> TokenCacheView | None:
    """mmap an existing cache; None on missing/corrupt/fingerprint mismatch."""
    lib = _build_and_load()
    if lib is None or not os.path.exists(path):
        return None
    base = ctypes.c_void_p()
    length = ctypes.c_int64()
    off_p = ctypes.POINTER(ctypes.c_int64)()
    flat_p = ctypes.POINTER(ctypes.c_int32)()
    n_rows = ctypes.c_int64()
    rc = lib.token_cache_open(
        path.encode(), ctypes.c_uint64(fingerprint & (2**64 - 1)),
        ctypes.byref(base), ctypes.byref(length), ctypes.byref(off_p),
        ctypes.byref(flat_p), ctypes.byref(n_rows),
    )
    if rc != 0:
        return None
    n = n_rows.value
    offsets = np.ctypeslib.as_array(off_p, shape=(n + 1,))
    flat = np.ctypeslib.as_array(flat_p, shape=(int(offsets[n]),)) \
        if offsets[n] else np.empty(0, np.int32)
    return TokenCacheView(base, length.value, offsets, flat, n)
