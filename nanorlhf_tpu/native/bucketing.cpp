// Host-side data-path kernels: bucket packing + batch padding.
//
// The reference's data path leans on native code it doesn't own (Rust HF
// tokenizers, vLLM's C++ scheduler — SURVEY.md §2.2). This library is the
// framework's own native runtime piece: the per-update host work that sits
// between tokenization and device transfer, where Python loops become the
// bottleneck at large batch×length (the r1 trainer re-packs every minibatch,
// `/root/reference/examples/r1-v0/grpo_r1_trainer.py:700-788`).
//
// Exposed via a C ABI, loaded with ctypes (no pybind11 in this image).
// Semantics are pinned by tests against the Python implementations.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <numeric>
#include <vector>

extern "C" {

// Greedy length-sorted packing under max(cur_len, len) * (count+1) <= budget.
// lengths: n int64s. out_indices: n ints (bucket-grouped sample indices).
// out_offsets: (n+1) ints (bucket b = out_indices[out_offsets[b]..out_offsets[b+1]]).
// Returns the number of buckets.
int create_batches(const int64_t* lengths, int n, int64_t budget,
                   int* out_indices, int* out_offsets) {
  std::vector<int> order(n);
  std::iota(order.begin(), order.end(), 0);
  std::stable_sort(order.begin(), order.end(),
                   [&](int a, int b) { return lengths[a] < lengths[b]; });

  int n_buckets = 0;
  int out_pos = 0;
  int64_t cur_len = 0;
  int cur_count = 0;
  out_offsets[0] = 0;
  for (int oi = 0; oi < n; ++oi) {
    int idx = order[oi];
    int64_t sample_len = lengths[idx];
    int64_t future = std::max(cur_len, sample_len) * (cur_count + 1);
    if (future > budget && cur_count > 0) {
      out_offsets[++n_buckets] = out_pos;
      cur_len = 0;
      cur_count = 0;
    }
    out_indices[out_pos++] = idx;
    cur_len = std::max(cur_len, sample_len);
    cur_count += 1;
  }
  if (cur_count > 0) {
    out_offsets[++n_buckets] = out_pos;
  }
  return n_buckets;
}

// Left-pad ragged token rows into a [n, max_len] int32 matrix.
// tokens_flat: concatenated rows; lens: per-row lengths (each <= max_len
// after caller-side truncation; rows longer than max_len keep their TAIL).
void pack_left_pad(const int32_t* tokens_flat, const int64_t* lens, int n,
                   int max_len, int32_t pad_id, int32_t* out) {
  int64_t offset = 0;
  for (int i = 0; i < n; ++i) {
    int64_t len = lens[i];
    const int32_t* row = tokens_flat + offset;
    offset += len;
    if (len > max_len) {  // keep tail
      row += len - max_len;
      len = max_len;
    }
    int32_t* dst = out + (int64_t)i * max_len;
    std::fill(dst, dst + (max_len - len), pad_id);
    std::memcpy(dst + (max_len - len), row, len * sizeof(int32_t));
  }
}

// Right-pad variant (RM scoring batches, response tensors).
void pack_right_pad(const int32_t* tokens_flat, const int64_t* lens, int n,
                    int max_len, int32_t pad_id, int32_t* out) {
  int64_t offset = 0;
  for (int i = 0; i < n; ++i) {
    int64_t len = lens[i];
    const int32_t* row = tokens_flat + offset;
    offset += len;
    if (len > max_len) len = max_len;  // keep head
    int32_t* dst = out + (int64_t)i * max_len;
    std::memcpy(dst, row, len * sizeof(int32_t));
    std::fill(dst + len, dst + max_len, pad_id);
  }
}

}  // extern "C"
