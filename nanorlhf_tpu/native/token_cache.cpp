// Native token-cache file: the TPU-host analogue of the Arrow cache HF
// datasets keeps behind `dataset.map` (`/root/reference/GRPO/grpo.py:266-268`
// relies on it so re-runs skip tokenization). A single binary file holds the
// ragged tokenized corpus; readers mmap it and pack batches straight from
// the flat buffer (pack_left_pad in bucketing.cpp), so a 250k-prompt corpus
// loads in O(pages touched), not O(re-tokenize).
//
// Layout (little-endian, 8-byte aligned):
//   [0]  u64 magic   0x4e524c48'544f4b31  ("NRLH" "TOK1")
//   [8]  u64 n_rows
//   [16] u64 fingerprint  (caller-supplied hash of tokenizer/source/params)
//   [24] i64 offsets[n_rows+1]            (offsets[0] == 0)
//   [..] i32 tokens[offsets[n_rows]]
//
// C ABI + ctypes (no pybind11 in the image); every entry point returns an
// error code instead of throwing. Python fallback lives in data/token_cache.py.

#include <cstdint>
#include <cstdio>
#include <cstring>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {
constexpr uint64_t kMagic = 0x4e524c48544f4b31ull;

struct Header {
  uint64_t magic;
  uint64_t n_rows;
  uint64_t fingerprint;
};
}  // namespace

extern "C" {

// Write the cache atomically (tmp file + rename). Returns 0 on success.
int token_cache_write(const char* path, const int32_t* flat,
                      const int64_t* offsets, int64_t n_rows,
                      uint64_t fingerprint) {
  if (n_rows < 0 || offsets[0] != 0) return -1;
  char tmp[4096];
  if (snprintf(tmp, sizeof(tmp), "%s.%d.tmp", path, getpid()) >=
      static_cast<int>(sizeof(tmp)))
    return -2;
  FILE* f = fopen(tmp, "wb");
  if (!f) return -3;
  Header h{kMagic, static_cast<uint64_t>(n_rows), fingerprint};
  int64_t total = offsets[n_rows];
  bool ok = fwrite(&h, sizeof(h), 1, f) == 1 &&
            fwrite(offsets, sizeof(int64_t), n_rows + 1, f) ==
                static_cast<size_t>(n_rows + 1) &&
            (total == 0 ||
             fwrite(flat, sizeof(int32_t), total, f) ==
                 static_cast<size_t>(total));
  ok = (fclose(f) == 0) && ok;
  if (!ok || rename(tmp, path) != 0) {
    remove(tmp);
    return -4;
  }
  return 0;
}

// Validate the header; returns 0 and fills n_rows/total_tokens on success,
// <0 on missing/corrupt/fingerprint-mismatch (callers then re-tokenize).
int token_cache_stat(const char* path, uint64_t fingerprint, int64_t* n_rows,
                     int64_t* total_tokens) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  Header h;
  int64_t first_last[1];
  int rc = -2;
  struct stat st;
  if (fread(&h, sizeof(h), 1, f) == 1 && h.magic == kMagic &&
      h.fingerprint == fingerprint && fstat(fileno(f), &st) == 0 &&
      // bound n_rows BEFORE any offset arithmetic: a corrupt header's u64
      // n_rows can overflow the signed fseek offset (UB) and the expected
      // size computation (ADVICE r3). The offsets table alone needs
      // (n_rows+1)*8 bytes inside the file.
      st.st_size >= static_cast<int64_t>(sizeof(Header) + sizeof(int64_t)) &&
      h.n_rows < (static_cast<uint64_t>(st.st_size) - sizeof(Header)) /
                     sizeof(int64_t)) {
    // last offset sits right before the token payload; bound it against
    // the space actually left for the payload BEFORE the *4 multiply — a
    // corrupt value near 2^62 would otherwise wrap the uint64 product back
    // onto the true file size and hand the caller a view spanning ~2^64
    // bytes past the mapping
    int64_t payload_cap = (st.st_size - static_cast<int64_t>(sizeof(Header)) -
                           static_cast<int64_t>((h.n_rows + 1) *
                                                sizeof(int64_t))) /
                          static_cast<int64_t>(sizeof(int32_t));
    if (payload_cap >= 0 &&
        fseek(f, sizeof(Header) + h.n_rows * sizeof(int64_t), SEEK_SET) == 0 &&
        fread(first_last, sizeof(int64_t), 1, f) == 1 &&
        first_last[0] >= 0 && first_last[0] <= payload_cap) {
      int64_t expect = sizeof(Header) +
                       (h.n_rows + 1) * sizeof(int64_t) +
                       first_last[0] * sizeof(int32_t);
      if (st.st_size == expect) {
        *n_rows = static_cast<int64_t>(h.n_rows);
        *total_tokens = first_last[0];
        rc = 0;
      }
    }
  }
  fclose(f);
  return rc;
}

// mmap the cache read-only. Fills pointers into the mapping; the caller owns
// the mapping via token_cache_close(map_base, map_len). Returns 0 on success.
int token_cache_open(const char* path, uint64_t fingerprint,
                     void** map_base, int64_t* map_len,
                     const int64_t** offsets, const int32_t** flat,
                     int64_t* n_rows) {
  int64_t rows = 0, total = 0;
  int rc = token_cache_stat(path, fingerprint, &rows, &total);
  if (rc != 0) return rc;
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -5;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return -6;
  }
  void* base = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  close(fd);  // mapping persists past close
  if (base == MAP_FAILED) return -7;
  *map_base = base;
  *map_len = st.st_size;
  auto* p = static_cast<const char*>(base);
  *offsets = reinterpret_cast<const int64_t*>(p + sizeof(Header));
  *flat = reinterpret_cast<const int32_t*>(p + sizeof(Header) +
                                           (rows + 1) * sizeof(int64_t));
  *n_rows = rows;
  return 0;
}

void token_cache_close(void* map_base, int64_t map_len) {
  if (map_base) munmap(map_base, map_len);
}

}  // extern "C"
