from nanorlhf_tpu.ops.masking import (
    INVALID_LOGPROB,
    exact_div,
    first_true_indices,
    truncate_response,
    masked_mean,
    masked_var,
    masked_whiten,
    response_padding_masks,
    logprobs_from_logits,
    entropy_from_logits,
)

__all__ = [
    "INVALID_LOGPROB",
    "exact_div",
    "first_true_indices",
    "truncate_response",
    "masked_mean",
    "masked_var",
    "masked_whiten",
    "response_padding_masks",
    "logprobs_from_logits",
    "entropy_from_logits",
]
