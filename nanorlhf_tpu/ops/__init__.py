from nanorlhf_tpu.ops.masking import (
    INVALID_LOGPROB,
    MIN_TEMPERATURE,
    exact_div,
    first_true_indices,
    guard_temperature,
    truncate_response,
    masked_mean,
    masked_var,
    masked_whiten,
    response_padding_masks,
    logprobs_from_logits,
    entropy_from_logits,
)
from nanorlhf_tpu.ops.fused_logprob import (
    chunked_entropy,
    fused_chunk_rows,
    fused_logprob,
    fused_logprob_reference,
)

__all__ = [
    "INVALID_LOGPROB",
    "MIN_TEMPERATURE",
    "exact_div",
    "first_true_indices",
    "guard_temperature",
    "truncate_response",
    "masked_mean",
    "masked_var",
    "masked_whiten",
    "response_padding_masks",
    "logprobs_from_logits",
    "entropy_from_logits",
    "chunked_entropy",
    "fused_chunk_rows",
    "fused_logprob",
    "fused_logprob_reference",
]
