"""Causal flash attention as a Pallas TPU kernel (+ XLA reference path).

TPU-native replacement for the reference's FlashAttention-2 dependency
(`attn_implementation="flash_attention_2"`, `/root/reference/GRPO/
grpo.py:219,223` — CUDA, SURVEY.md §2.2). Design:

- **Forward**: online-softmax blockwise kernel. Grid (B, H, q_blocks,
  kv_blocks); the kv axis iterates fastest, carrying running max / sum /
  accumulator in VMEM scratch across grid steps. Never materializes the
  [T, T] score matrix, streams K/V HBM→VMEM block by block. GQA is free: the
  K/V BlockSpec index maps query head h to kv head h // group, so grouped
  heads re-read the same KV block instead of materializing repeats.
- **Causal skip**: kv blocks entirely above the diagonal skip their compute
  under `pl.when` (half the FLOPs at long T).
- **Backward**: fused Pallas kernels (FlashAttention-2 style). The forward
  emits per-row LSE as a residual; `_dq_kernel` accumulates dQ over kv
  blocks, `_dkv_kernel` accumulates dK/dV over (group, q-block) — the GQA
  group sum happens in-scratch, so gradients come out already reduced to
  [B, KV, T, d]. No [T, T] probability matrix is ever materialized in either
  direction. `NANORLHF_FLASH_BWD=xla` switches the backward to an XLA
  reference recompute for hardware triage (values validated; anything else
  than pallas/xla raises).

Padding contract matches the model's mask recipe: `key_valid` is the [B, T]
attention mask; query rows that are padding produce garbage rows which the
caller's downstream masking discards (identical to the XLA path).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU too; guarded for safety
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

NEG_INF = -1e30
# TPU VREG tile: small per-row operands (mask, lse, delta) are carried
# sublane-/lane-expanded so every BlockSpec satisfies Mosaic's (8, 128)
# last-two-dims tiling rule on real hardware (interpret mode never checks).
_SUBLANES = 8
_LANES = 128


def _interpret_default() -> bool:
    """Interpret mode: forced via env, or automatic off-TPU (tests/CPU)."""
    env = os.environ.get("NANORLHF_PALLAS_INTERPRET")
    if env is not None:
        return env == "1"
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# XLA reference (also the backward path)
# ---------------------------------------------------------------------------


def reference_attention(q, k, v, key_valid, causal: bool = True):
    """Plain-jnp GQA attention. q: [B, H, T, d]; k/v: [B, KV, T, d];
    key_valid: [B, T] bool. Returns [B, H, T, d]."""
    B, H, T, d = q.shape
    KV = k.shape[1]
    G = H // KV
    qg = q.reshape(B, KV, G, T, d)
    s = jnp.einsum("bkgqh,bkth->bkgqt", qg, k).astype(jnp.float32)
    s = s / jnp.sqrt(jnp.float32(d))
    mask = key_valid[:, None, None, None, :]
    if causal:
        causal_m = jnp.tril(jnp.ones((T, T), bool))[None, None, None, :, :]
        mask = mask & causal_m
    s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgqt,bkth->bkgqh", p, v)
    return out.reshape(B, H, T, d)


# ---------------------------------------------------------------------------
# Pallas forward kernel
# ---------------------------------------------------------------------------


def _flash_kernel(q_ref, k_ref, v_ref, mask_ref, out_ref, lse_ref,
                  acc_ref, m_ref, l_ref,
                  *, scale: float, block_q: int, block_k: int, causal: bool):
    kv_idx = pl.program_id(3)
    q_idx = pl.program_id(2)
    n_kv = pl.num_programs(3)

    @pl.when(kv_idx == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start = q_idx * block_q
    kv_start = kv_idx * block_k

    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)            # [Bq, d]
        k = k_ref[0, 0].astype(jnp.float32)            # [Bk, d]
        v = v_ref[0, 0].astype(jnp.float32)            # [Bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                       # [Bq, Bk]
        key_ok = mask_ref[0, :1, :] > 0                 # [1, Bk]
        s = jnp.where(key_ok, s, NEG_INF)
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = kv_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)

        m_prev = m_ref[:, :1]                           # [Bq, 1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                          # [Bq, Bk]
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    if causal:
        # skip kv blocks entirely above the diagonal (pure future): half the
        # FLOPs at long T
        pl.when(kv_start <= q_start + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(kv_idx == n_kv - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)            # fully-masked rows → 0/1
        out_ref[0, 0] = (acc_ref[:] / l).astype(out_ref.dtype)
        # lse = m + log(l): the backward residual (P = exp(S − lse)).
        # Stored lane-expanded [Bq, LANES] — Mosaic tiling requires the last
        # two block dims be (8k, 128k)-aligned or span the array dim, so a
        # [Bq]-vector output is not liftable on real TPU hardware.
        lse_ref[0, 0] = jnp.broadcast_to(
            m_ref[:, :1] + jnp.log(l), lse_ref.shape[2:]
        )


def _flash_forward(q, k, v, key_valid, causal: bool, block_q: int, block_k: int,
                   interpret: bool):
    B, H, T, d = q.shape
    KV = k.shape[1]
    G = H // KV
    scale = 1.0 / (d ** 0.5)
    n_q = pl.cdiv(T, block_q)
    n_kv = pl.cdiv(T, block_k)

    kernel = functools.partial(
        _flash_kernel, scale=scale, block_q=block_q, block_k=block_k,
        causal=causal,
    )
    # Mosaic tiling: the last two block dims must be (8, 128)-multiples or
    # span the array dim. A [B, T] mask with (1, block_k) blocks violates the
    # sublane rule, so the mask rides sublane-broadcast as [B, 8, T] (the
    # same recipe as jax's reference TPU flash kernel's segment ids), and lse
    # rides lane-expanded as [B, H, T, LANES].
    mask8 = jnp.broadcast_to(
        key_valid.astype(jnp.int32)[:, None, :], (B, _SUBLANES, T)
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(B, H, n_q, n_kv),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, i, j: (b, h, i, 0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, 1, block_k, d), lambda b, h, i, j: (b, h // G, j, 0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, 1, block_k, d), lambda b, h, i, j: (b, h // G, j, 0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, _SUBLANES, block_k), lambda b, h, i, j: (b, 0, j),
                         memory_space=_VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, i, j: (b, h, i, 0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, 1, block_q, _LANES),
                         lambda b, h, i, j: (b, h, i, 0),
                         memory_space=_VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((B, H, T, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, mask8)
    # lse stays lane-expanded [B, H, T, LANES]: it is only ever a backward
    # residual, and the backward kernels read it in this layout — slicing to
    # [B, H, T] here would just force a re-broadcast (a 128x HBM round trip)
    # before the bwd pallas_calls.
    return out, lse


# ---------------------------------------------------------------------------
# Pallas backward kernels (FlashAttention-2 style)
#
# With P = exp(S − lse), D_i = Σ_j dO_ij · O_ij:
#   dV = Pᵀ @ dO        dP = dO @ Vᵀ        dS = P ⊙ (dP − D)
#   dQ = dS @ K · scale          dK = dSᵀ @ Q · scale
# Two kernels: dq iterates kv blocks per q block; dk/dv iterate q blocks per
# kv block (emitted per query head, summed over GQA groups outside).
# ---------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, out_ref,
               dq_out_ref, dq_acc_ref,
               *, scale: float, block_q: int, block_k: int, causal: bool):
    kv_idx = pl.program_id(3)
    q_idx = pl.program_id(2)
    n_kv = pl.num_programs(3)

    @pl.when(kv_idx == 0)
    def _init():
        dq_acc_ref[:] = jnp.zeros_like(dq_acc_ref)

    q_start = q_idx * block_q
    kv_start = kv_idx * block_k

    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, :1]                       # [Bq, 1]
        # D_i = Σ_d dO·O, recomputed per block ([Bq, d] elementwise+reduce) —
        # cheaper than streaming a lane-expanded [B, H, T, 128] HBM array
        delta = jnp.sum(
            do * out_ref[0, 0].astype(jnp.float32), axis=-1, keepdims=True
        )                                                # [Bq, 1]

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        key_ok = mask_ref[0, :1, :] > 0                  # [1, Bk]
        s = jnp.where(key_ok, s, NEG_INF)
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = kv_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)                             # [Bq, Bk]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dq_acc_ref[:] = dq_acc_ref[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale

    if causal:
        pl.when(kv_start <= q_start + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(kv_idx == n_kv - 1)
    def _finalize():
        dq_out_ref[0, 0] = dq_acc_ref[:].astype(dq_out_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, out_ref,
                dk_out_ref, dv_out_ref, dk_acc_ref, dv_acc_ref,
                *, scale: float, block_q: int, block_k: int, causal: bool):
    # grid (B, KV, n_kv, G, n_q): q blocks fastest, then the GQA group — the
    # group sum accumulates in scratch, emitting dk/dv already [B, KV, T, d]
    q_idx = pl.program_id(4)
    g_idx = pl.program_id(3)
    kv_idx = pl.program_id(2)
    n_q = pl.num_programs(4)
    n_g = pl.num_programs(3)

    @pl.when((q_idx == 0) & (g_idx == 0))
    def _init():
        dk_acc_ref[:] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[:] = jnp.zeros_like(dv_acc_ref)

    q_start = q_idx * block_q
    kv_start = kv_idx * block_k

    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, :1]
        delta = jnp.sum(                                 # see _dq_kernel
            do * out_ref[0, 0].astype(jnp.float32), axis=-1, keepdims=True
        )

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        key_ok = mask_ref[0, :1, :] > 0
        s = jnp.where(key_ok, s, NEG_INF)
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = kv_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)
        # dV += Pᵀ @ dO
        dv_acc_ref[:] = dv_acc_ref[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        # dK += dSᵀ @ Q · scale
        dk_acc_ref[:] = dk_acc_ref[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale

    if causal:
        pl.when(kv_start <= q_start + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when((q_idx == n_q - 1) & (g_idx == n_g - 1))
    def _finalize():
        dk_out_ref[0, 0] = dk_acc_ref[:].astype(dk_out_ref.dtype)
        dv_out_ref[0, 0] = dv_acc_ref[:].astype(dv_out_ref.dtype)


def _flash_backward(q, k, v, key_valid, out, lse, g, causal, block_q, block_k,
                    interpret):
    B, H, T, d = q.shape
    KV = k.shape[1]
    G = H // KV
    scale = 1.0 / (d ** 0.5)
    n_q = pl.cdiv(T, block_q)
    n_kv = pl.cdiv(T, block_k)
    # sublane-broadcast mask / lane-expanded lse: see _flash_forward (lse
    # arrives already lane-expanded; delta is recomputed per block in-kernel
    # from `out`, so no lane-expanded delta array exists)
    mask8 = jnp.broadcast_to(
        key_valid.astype(jnp.int32)[:, None, :], (B, _SUBLANES, T)
    )

    common_q_specs = dict(
        q=pl.BlockSpec((1, 1, block_q, d), lambda b, h, i, j: (b, h, i, 0),
                       memory_space=_VMEM),
        k=pl.BlockSpec((1, 1, block_k, d), lambda b, h, i, j: (b, h // G, j, 0),
                       memory_space=_VMEM),
        v=pl.BlockSpec((1, 1, block_k, d), lambda b, h, i, j: (b, h // G, j, 0),
                       memory_space=_VMEM),
        mask=pl.BlockSpec((1, _SUBLANES, block_k), lambda b, h, i, j: (b, 0, j),
                          memory_space=_VMEM),
        do=pl.BlockSpec((1, 1, block_q, d), lambda b, h, i, j: (b, h, i, 0),
                        memory_space=_VMEM),
        lse=pl.BlockSpec((1, 1, block_q, _LANES),
                         lambda b, h, i, j: (b, h, i, 0),
                         memory_space=_VMEM),
    )

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, block_q=block_q,
                          block_k=block_k, causal=causal),
        grid=(B, H, n_q, n_kv),
        in_specs=[common_q_specs["q"], common_q_specs["k"], common_q_specs["v"],
                  common_q_specs["mask"], common_q_specs["do"],
                  common_q_specs["lse"], common_q_specs["do"]],
        out_specs=common_q_specs["q"],
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, mask8, g, lse, out)

    # dk/dv: kv head and block outer; (group, q block) inner with q fastest.
    # Scratch accumulates across BOTH inner axes, so the GQA group sum happens
    # in-kernel and the outputs are already reduced to [B, KV, T, d] — no
    # G x-sized per-query-head gradient buffers in HBM.
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, block_q=block_q,
                          block_k=block_k, causal=causal),
        grid=(B, KV, n_kv, G, n_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b, kv, j, gq, i: (b, kv * G + gq, i, 0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b, kv, j, gq, i: (b, kv, j, 0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b, kv, j, gq, i: (b, kv, j, 0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, _SUBLANES, block_k),
                         lambda b, kv, j, gq, i: (b, 0, j),
                         memory_space=_VMEM),
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b, kv, j, gq, i: (b, kv * G + gq, i, 0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, 1, block_q, _LANES),
                         lambda b, kv, j, gq, i: (b, kv * G + gq, i, 0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b, kv, j, gq, i: (b, kv * G + gq, i, 0),
                         memory_space=_VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d), lambda b, kv, j, gq, i: (b, kv, j, 0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, 1, block_k, d), lambda b, kv, j, gq, i: (b, kv, j, 0),
                         memory_space=_VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, mask8, g, lse, out)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public entry: custom_vjp + shape handling
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash_attention_core(q, k, v, key_valid, causal, block_q, block_k):
    out, _ = _flash_forward(q, k, v, key_valid, causal, block_q, block_k,
                            interpret=_interpret_default())
    return out


def _core_fwd(q, k, v, key_valid, causal, block_q, block_k):
    out, lse = _flash_forward(q, k, v, key_valid, causal, block_q, block_k,
                              interpret=_interpret_default())
    return out, (q, k, v, key_valid, out, lse)


def _core_bwd(causal, block_q, block_k, residuals, g):
    q, k, v, key_valid, out, lse = residuals
    bwd_impl = os.environ.get("NANORLHF_FLASH_BWD", "pallas")
    if bwd_impl not in ("pallas", "xla"):
        raise ValueError(
            f"NANORLHF_FLASH_BWD={bwd_impl!r}: must be 'pallas' or 'xla'"
        )
    if bwd_impl == "xla":
        # triage escape hatch: recompute through the XLA reference
        _, vjp = jax.vjp(
            lambda q_, k_, v_: reference_attention(q_, k_, v_, key_valid, causal),
            q, k, v,
        )
        dq, dk, dv = vjp(g)
    else:
        dq, dk, dv = _flash_backward(
            q, k, v, key_valid, out, lse, g, causal, block_q, block_k,
            interpret=_interpret_default(),
        )
    return dq, dk, dv, None


_flash_attention_core.defvjp(_core_fwd, _core_bwd)


def block_and_pad(block_q: int, block_k: int, T: int) -> tuple[int, int]:
    """The shared pad-up recipe (used here and by the flash ring): blocks
    must be 128-lane multiples, never larger than the padded sequence, and
    T pads UP to a block multiple — a non-aligned T is rejected by Mosaic,
    and an unpadded partial last block would read out-of-bounds keys that
    key_valid does not neutralize (silent wrong logprobs on silicon;
    interpret mode zero-fills and cannot catch it)."""
    block = max(block_q, block_k)
    block = max(128, (block // 128) * 128)
    block = min(block, 128 * int(pl.cdiv(T, 128)))
    T_pad = int(pl.cdiv(T, block) * block)
    return block, T_pad


def flash_attention(
    q: jnp.ndarray,          # [B, H, T, d]
    k: jnp.ndarray,          # [B, KV, T, d]
    v: jnp.ndarray,          # [B, KV, T, d]
    key_valid: jnp.ndarray,  # [B, T] bool
    causal: bool = True,
    block_q: int = 512,
    block_k: int = 512,
) -> jnp.ndarray:
    """Blockwise flash attention; pads T up to a block multiple internally.

    Blocks are always multiples of 128 (lane width): a non-aligned T (e.g.
    100) pads UP to 128 rather than shrinking the block to a lane-unaligned
    size that Mosaic tiling may reject on real hardware (ADVICE r1). The
    key_valid padding neutralizes the extra columns; extra query rows are
    garbage the caller's masking discards.
    """
    B, H, T, d = q.shape
    block, T_pad = block_and_pad(block_q, block_k, T)
    block_q = block_k = block
    if T_pad != T:
        pad = [(0, 0), (0, 0), (0, T_pad - T), (0, 0)]
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
        key_valid = jnp.pad(key_valid, [(0, 0), (0, T_pad - T)])
    out = _flash_attention_core(q, k, v, key_valid, causal, block_q, block_k)
    return out[:, :, :T, :]
