"""Pallas decode-attention kernel: read only the FILLED cache prefix.

The round-1 decode step attended over the whole [B, KV, T_max, hd] cache
with masking every token (`core/model.py` decode path) — at 8k-token
responses that reads the full cache square-wise over the rollout while the
valid region grows linearly. This kernel is the TPU-native analogue of
vLLM's paged/decode attention (SURVEY.md §2.2 row 1, replacing the CUDA
kernels behind `/root/reference/GRPO/grpo_trainer.py:122-166`):

- **Scalar-prefetched bounds**: per-row `start` (left-pad offset) and
  `filled` (one past the last written slot) arrive as scalar-prefetch
  operands, so the KV BlockSpec index_map can CLAMP the block index to the
  valid range. Grid steps past the last valid block re-map to the same
  block; Pallas's revisiting optimization skips the re-fetch, so HBM traffic
  is proportional to the filled prefix, not T_max.
- **Online softmax** across kv blocks (same recipe as `ops/attention.py`),
  carried in VMEM scratch.
- **GQA layout**: queries are grouped [B, KV, G, hd] and each (batch, kv
  head) grid cell contracts its G query heads against one un-repeated KV
  block — no KV repeat materialization, identical to the train-time kernel.

Decode attention is HBM-bandwidth-bound (the MXU sees [G, block] matmuls);
the win is skipped traffic, not FLOPs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from nanorlhf_tpu.ops.attention import _interpret_default

try:  # pragma: no cover - pltpu import guarded like ops/attention.py
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30


def reference_decode_attention(q, k_cache, v_cache, start, filled):
    """XLA oracle: masked softmax over the cache. q: [B, H, hd];
    k/v: [B, KV, T, hd]; start/filled: [B] int32. Returns [B, H, hd]."""
    B, H, hd = q.shape
    KV, T = k_cache.shape[1], k_cache.shape[2]
    G = H // KV
    qg = q.reshape(B, KV, G, hd)
    s = jnp.einsum("bkgh,bkth->bkgt", qg, k_cache).astype(jnp.float32)
    s = s / jnp.sqrt(jnp.float32(hd))
    pos = jnp.arange(T)[None, :]
    valid = (pos >= start[:, None]) & (pos < filled[:, None])  # [B, T]
    s = jnp.where(valid[:, None, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v_cache.dtype)
    out = jnp.einsum("bkgt,bkth->bkgh", p, v_cache)
    return out.reshape(B, H, hd)


def _decode_kernel(start_ref, filled_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, *, scale: float, block_k: int):
    b = pl.program_id(0)
    j = pl.program_id(2)
    n_blk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    start = start_ref[b]
    filled = filled_ref[b]
    first_blk = start // block_k
    last_blk = (filled - 1) // block_k
    actual_j = jnp.minimum(first_blk + j, last_blk)

    # grid steps beyond the valid range re-visit last_blk with compute skipped
    @pl.when(first_blk + j <= last_blk)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)              # [Gp, hd]
        k = k_ref[0, 0].astype(jnp.float32)              # [block_k, hd]
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                        # [Gp, block_k]
        pos = actual_j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1
        )
        s = jnp.where((pos >= start) & (pos < filled), s, NEG_INF)

        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == n_blk - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[:] / l).astype(o_ref.dtype)


def reference_decode_attention_q8(q, k_q, k_s, v_q, v_s, start, filled):
    """XLA oracle for the int8-cache kernel: dequantize, then the exact
    reference. k_q/v_q: [B, KV, T, hd] int8; k_s/v_s: [B, KV, 8, T] bf16
    (sublane-expanded scales, core/model.init_kv_cache)."""
    dt = q.dtype
    k = (k_q.astype(jnp.float32) * k_s[:, :, 0, :, None]).astype(dt)
    v = (v_q.astype(jnp.float32) * v_s[:, :, 0, :, None]).astype(dt)
    return reference_decode_attention(q, k, v, start, filled)


def _decode_q8_kernel(start_ref, filled_ref, q_ref, kq_ref, ks_ref, vq_ref,
                      vs_ref, o_ref, acc_ref, m_ref, l_ref,
                      *, scale: float, block_k: int):
    b = pl.program_id(0)
    j = pl.program_id(2)
    n_blk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    start = start_ref[b]
    filled = filled_ref[b]
    first_blk = start // block_k
    last_blk = (filled - 1) // block_k
    actual_j = jnp.minimum(first_blk + j, last_blk)

    @pl.when(first_blk + j <= last_blk)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)              # [Gp, hd]
        k = kq_ref[0, 0].astype(jnp.float32)             # [block_k, hd] int8→f32
        v = vq_ref[0, 0].astype(jnp.float32)
        ks = ks_ref[0, 0][:1, :]                         # [1, block_k]
        vs = vs_ref[0, 0][:1, :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale * ks                                   # fold k scales into the score row
        pos = actual_j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1
        )
        s = jnp.where((pos >= start) & (pos < filled), s, NEG_INF)

        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        # fold v scales into the probability row: Σ p·(v_q·vs) = (p·vs)@v_q
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p * vs, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == n_blk - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[:] / l).astype(o_ref.dtype)


def decode_attention_q8(
    q: jnp.ndarray,      # [B, H, hd] — single decode position
    k_q: jnp.ndarray,    # [B, KV, T_max, hd] int8
    k_s: jnp.ndarray,    # [B, KV, 8, T_max] bf16 sublane-expanded scales
    v_q: jnp.ndarray,    # [B, KV, T_max, hd] int8
    v_s: jnp.ndarray,    # [B, KV, 8, T_max] bf16
    start: jnp.ndarray,  # [B] int32
    filled: jnp.ndarray, # [B] int32
    block_k: int = 512,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Prefix-bounded decode attention over the int8 KV cache. int8 value
    blocks + bf16 scale rows stream HBM→VMEM at 144/256 of the exact cache's
    bytes (hd=128); dequantization is two row-broadcast multiplies folded
    into the existing online-softmax math. Returns [B, H, hd]."""
    B, H, hd = q.shape
    KV, T = k_q.shape[1], k_q.shape[2]
    G = H // KV
    Gp = max(8, G)
    block_k = min(block_k, max(128, 128 * pl.cdiv(T, 128)))

    qg = q.reshape(B, KV, G, hd)
    if Gp != G:
        qg = jnp.pad(qg, [(0, 0), (0, 0), (0, Gp - G), (0, 0)])

    if T % block_k != 0:
        pad_t = block_k * pl.cdiv(T, block_k) - T
        k_q = jnp.pad(k_q, [(0, 0), (0, 0), (0, pad_t), (0, 0)])
        v_q = jnp.pad(v_q, [(0, 0), (0, 0), (0, pad_t), (0, 0)])
        k_s = jnp.pad(k_s, [(0, 0), (0, 0), (0, 0), (0, pad_t)])
        v_s = jnp.pad(v_s, [(0, 0), (0, 0), (0, 0), (0, pad_t)])
        T = T + pad_t
    n_blk = T // block_k

    kernel = functools.partial(
        _decode_q8_kernel, scale=1.0 / (hd ** 0.5), block_k=block_k
    )

    def kv_index_map(b, kv, j, start_ref, filled_ref):
        first = start_ref[b] // block_k
        # max(last, 0): filled==0 (no valid slots) would map to block -1 —
        # the @pl.when guard already skips compute, but the prefetch index
        # must still be in range
        last = jnp.maximum((filled_ref[b] - 1) // block_k, 0)
        return (b, kv, jnp.minimum(first + j, last), 0)

    def scale_index_map(b, kv, j, start_ref, filled_ref):
        first = start_ref[b] // block_k
        last = jnp.maximum((filled_ref[b] - 1) // block_k, 0)
        return (b, kv, 0, jnp.minimum(first + j, last))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, KV, n_blk),
        in_specs=[
            pl.BlockSpec((1, 1, Gp, hd), lambda b, kv, j, s, f: (b, kv, 0, 0)),
            pl.BlockSpec((1, 1, block_k, hd), kv_index_map),
            pl.BlockSpec((1, 1, 8, block_k), scale_index_map),
            pl.BlockSpec((1, 1, block_k, hd), kv_index_map),
            pl.BlockSpec((1, 1, 8, block_k), scale_index_map),
        ],
        out_specs=pl.BlockSpec((1, 1, Gp, hd), lambda b, kv, j, s, f: (b, kv, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((Gp, hd), jnp.float32),
            pltpu.VMEM((Gp, 128), jnp.float32),
            pltpu.VMEM((Gp, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KV, Gp, hd), q.dtype),
        interpret=_interpret_default() if interpret is None else interpret,
    )(start.astype(jnp.int32), filled.astype(jnp.int32), qg, k_q, k_s, v_q, v_s)
    return out[:, :, :G, :].reshape(B, H, hd)


def reference_decode_verify_attention(q, k_cache, v_cache, start, fill):
    """XLA oracle for the k-query (speculative verify) variant: query i of a
    row attends over cache slots [start, fill + i + 1) — the valid prefix
    plus the candidate tokens up to and including itself (their KV is
    already written at slots [fill, fill + Tq)). q: [B, H, Tq, hd];
    k/v: [B, KV, T, hd]; start/fill: [B] int32. Returns [B, H, Tq, hd]."""
    B, H, Tq, hd = q.shape
    KV, T = k_cache.shape[1], k_cache.shape[2]
    G = H // KV
    qg = q.reshape(B, KV, G, Tq, hd)
    s = jnp.einsum("bkgqh,bkth->bkgqt", qg, k_cache).astype(jnp.float32)
    s = s / jnp.sqrt(jnp.float32(hd))
    pos = jnp.arange(T)[None, None, :]                       # [1, 1, T]
    qi = jnp.arange(Tq)[None, :, None]                       # [1, Tq, 1]
    valid = (pos >= start[:, None, None]) & (
        pos < fill[:, None, None] + qi + 1
    )                                                        # [B, Tq, T]
    s = jnp.where(valid[:, None, None, :, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v_cache.dtype)
    out = jnp.einsum("bkgqt,bkth->bkgqh", p, v_cache)
    return out.reshape(B, H, Tq, hd)


def _verify_kernel(start_ref, fill_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, *, scale: float, block_k: int,
                   Tq: int):
    """k-query generalization of `_decode_kernel`: the query block carries
    G*Tq rows (row r = g*Tq + qi) and the per-row key bound becomes
    fill + qi + 1 — the causal-within-candidates rule. Same prefix-clamped
    grid + online softmax as the single-query kernel."""
    b = pl.program_id(0)
    j = pl.program_id(2)
    n_blk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    start = start_ref[b]
    fill = fill_ref[b]
    first_blk = start // block_k
    last_blk = (fill + Tq - 1) // block_k
    actual_j = jnp.minimum(first_blk + j, last_blk)

    @pl.when(first_blk + j <= last_blk)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)              # [Rp, hd]
        k = k_ref[0, 0].astype(jnp.float32)              # [block_k, hd]
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                        # [Rp, block_k]
        pos = actual_j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1
        )
        qi = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) % Tq
        s = jnp.where((pos >= start) & (pos < fill + qi + 1), s, NEG_INF)

        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == n_blk - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[:] / l).astype(o_ref.dtype)


def decode_verify_attention(
    q: jnp.ndarray,        # [B, H, Tq, hd] — k+1 candidate positions
    k_cache: jnp.ndarray,  # [B, KV, T_max, hd] (candidate KV already written)
    v_cache: jnp.ndarray,  # [B, KV, T_max, hd]
    start: jnp.ndarray,    # [B] int32: first valid cache slot
    fill: jnp.ndarray,     # [B] int32: slot of candidate 0 (query i owns
                           # slot fill + i; it attends to [start, fill+i+1))
    block_k: int = 512,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Prefix-bounded decode attention for a BLOCK of Tq candidate queries —
    the speculative-verify variant of `decode_attention` (interpret fallback
    off-TPU, like every kernel here). One kernel pass scores all k+1
    candidates against the cache, so the dominant weight/cache HBM stream is
    paid once per verify step instead of once per token. Returns
    [B, H, Tq, hd]."""
    B, H, Tq, hd = q.shape
    KV, T = k_cache.shape[1], k_cache.shape[2]
    G = H // KV
    R = G * Tq
    Rp = 8 * pl.cdiv(R, 8)  # sublane-pad the flattened (group, query) rows
    block_k = min(block_k, max(128, 128 * pl.cdiv(T, 128)))

    # [B, KV, G, Tq, hd] -> [B, KV, G*Tq, hd]; row r = g*Tq + qi, so the
    # kernel recovers the query index as r % Tq (padded rows compute a
    # garbage qi and are sliced off after the call)
    qg = q.reshape(B, KV, G, Tq, hd).reshape(B, KV, R, hd)
    if Rp != R:
        qg = jnp.pad(qg, [(0, 0), (0, 0), (0, Rp - R), (0, 0)])

    if T % block_k != 0:
        pad_t = block_k * pl.cdiv(T, block_k) - T
        padz = [(0, 0), (0, 0), (0, pad_t), (0, 0)]
        k_cache = jnp.pad(k_cache, padz)
        v_cache = jnp.pad(v_cache, padz)
        T = T + pad_t
    n_blk = T // block_k

    kernel = functools.partial(
        _verify_kernel, scale=1.0 / (hd ** 0.5), block_k=block_k, Tq=Tq
    )

    def kv_index_map(b, kv, j, start_ref, fill_ref):
        first = start_ref[b] // block_k
        last = jnp.maximum((fill_ref[b] + Tq - 1) // block_k, 0)
        return (b, kv, jnp.minimum(first + j, last), 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, KV, n_blk),
        in_specs=[
            pl.BlockSpec((1, 1, Rp, hd), lambda b, kv, j, s, f: (b, kv, 0, 0)),
            pl.BlockSpec((1, 1, block_k, hd), kv_index_map),
            pl.BlockSpec((1, 1, block_k, hd), kv_index_map),
        ],
        out_specs=pl.BlockSpec((1, 1, Rp, hd), lambda b, kv, j, s, f: (b, kv, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((Rp, hd), jnp.float32),
            pltpu.VMEM((Rp, 128), jnp.float32),
            pltpu.VMEM((Rp, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KV, Rp, hd), q.dtype),
        interpret=_interpret_default() if interpret is None else interpret,
    )(start.astype(jnp.int32), fill.astype(jnp.int32), qg, k_cache, v_cache)
    return out[:, :, :R, :].reshape(B, KV, G, Tq, hd).reshape(B, H, Tq, hd)


def decode_attention(
    q: jnp.ndarray,        # [B, H, hd] — single decode position
    k_cache: jnp.ndarray,  # [B, KV, T_max, hd]
    v_cache: jnp.ndarray,  # [B, KV, T_max, hd]
    start: jnp.ndarray,    # [B] int32: first valid cache slot (left-pad offset)
    filled: jnp.ndarray,   # [B] int32: one past the last valid slot
    block_k: int = 512,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Prefix-bounded decode attention. Returns [B, H, hd]."""
    B, H, hd = q.shape
    KV, T = k_cache.shape[1], k_cache.shape[2]
    G = H // KV
    Gp = max(8, G)  # sublane-pad the tiny query-head dim
    block_k = min(block_k, max(128, 128 * pl.cdiv(T, 128)))

    qg = q.reshape(B, KV, G, hd)
    if Gp != G:
        qg = jnp.pad(qg, [(0, 0), (0, 0), (0, Gp - G), (0, 0)])

    if T % block_k != 0:
        pad_t = block_k * pl.cdiv(T, block_k) - T
        padz = [(0, 0), (0, 0), (0, pad_t), (0, 0)]
        k_cache = jnp.pad(k_cache, padz)
        v_cache = jnp.pad(v_cache, padz)
        T = T + pad_t
    n_blk = T // block_k

    scale = 1.0 / (hd ** 0.5)
    kernel = functools.partial(_decode_kernel, scale=scale, block_k=block_k)

    def kv_index_map(b, kv, j, start_ref, filled_ref):
        first = start_ref[b] // block_k
        # max(last, 0): filled==0 (no valid slots) would map to block -1 —
        # the @pl.when guard already skips compute, but the prefetch index
        # must still be in range
        last = jnp.maximum((filled_ref[b] - 1) // block_k, 0)
        return (b, kv, jnp.minimum(first + j, last), 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, KV, n_blk),
        in_specs=[
            pl.BlockSpec((1, 1, Gp, hd), lambda b, kv, j, s, f: (b, kv, 0, 0)),
            pl.BlockSpec((1, 1, block_k, hd), kv_index_map),
            pl.BlockSpec((1, 1, block_k, hd), kv_index_map),
        ],
        out_specs=pl.BlockSpec((1, 1, Gp, hd), lambda b, kv, j, s, f: (b, kv, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((Gp, hd), jnp.float32),
            pltpu.VMEM((Gp, 128), jnp.float32),
            pltpu.VMEM((Gp, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KV, Gp, hd), q.dtype),
        interpret=_interpret_default() if interpret is None else interpret,
    )(start.astype(jnp.int32), filled.astype(jnp.int32), qg, k_cache, v_cache)
    return out[:, :, :G, :].reshape(B, H, hd)


# --------------------------------------------------------------------------- #
# paged variants (ISSUE 10): K/V live in a global page pool and are gathered
# through a per-row block table instead of sitting in a per-row slab
# --------------------------------------------------------------------------- #
#
# Pool layout (core/model.py:init_paged_kv_cache, per layer): [num_pages, KV,
# page_size, hd]; block table: [B, n_blocks] int32 mapping logical block j of
# row b to a physical page (sentinel num_pages = unallocated, clamped here).
# The kernel bodies are UNCHANGED — positions are logical (`actual_j * block_k
# + iota` with block_k = page_size), only the BlockSpec index maps change: the
# table rides along as a third scalar-prefetch operand and the kv index map
# resolves logical block → physical page before the DMA is issued. The same
# clamp-to-last-valid-block trick applies, so revisited blocks still skip the
# re-fetch and HBM traffic stays proportional to the filled prefix.
#
# NOTE on tiling: block_k here is the page size, so the pool's (page_size, hd)
# trailing dims must satisfy the dtype's min tile — page_size ≥ 8 for f32,
# ≥ 16 for bf16, and the int8 scale block (1, 1, 8, page_size) wants
# page_size ≥ 128 lanes on real hardware. CPU tests run in interpret mode
# where any page size works; pick page_size ≥ 128 for compiled TPU runs.


def _gather_pool(pool, table):
    """[N, KV, P, hd] pool + [B, nb] table → contiguous [B, KV, nb*P, hd]
    view (sentinel entries clamp to page N-1; callers mask those slots)."""
    N = pool.shape[0]
    g = pool[jnp.minimum(table, N - 1)]          # [B, nb, KV, P, hd]
    B, nb, KV, P, hd = g.shape
    return g.transpose(0, 2, 1, 3, 4).reshape(B, KV, nb * P, hd)


def _gather_scale_pool(spool, table):
    """[N, KV, 8, P] scale pool + [B, nb] table → [B, KV, 8, nb*P] view."""
    N = spool.shape[0]
    g = spool[jnp.minimum(table, N - 1)]         # [B, nb, KV, 8, P]
    B, nb, KV, e, P = g.shape
    return g.transpose(0, 2, 3, 1, 4).reshape(B, KV, e, nb * P)


def reference_paged_decode_attention(q, k_pool, v_pool, table, start, filled):
    """XLA oracle for `paged_decode_attention`: gather pages to a contiguous
    per-row view, then the exact reference. q: [B, H, hd]; pools:
    [N, KV, P, hd]; table: [B, nb] int32."""
    return reference_decode_attention(
        q, _gather_pool(k_pool, table), _gather_pool(v_pool, table),
        start, filled)


def reference_paged_decode_attention_q8(q, kq_pool, ks_pool, vq_pool, vs_pool,
                                        table, start, filled):
    """int8 oracle: gather quant + scale pools, dequantize, exact reference."""
    return reference_decode_attention_q8(
        q, _gather_pool(kq_pool, table), _gather_scale_pool(ks_pool, table),
        _gather_pool(vq_pool, table), _gather_scale_pool(vs_pool, table),
        start, filled)


def reference_paged_decode_verify_attention(q, k_pool, v_pool, table, start,
                                            fill):
    """k-query (speculative verify) oracle over pages."""
    return reference_decode_verify_attention(
        q, _gather_pool(k_pool, table), _gather_pool(v_pool, table),
        start, fill)


def _paged_decode_kernel(start_ref, filled_ref, table_ref, q_ref, k_ref,
                         v_ref, o_ref, acc_ref, m_ref, l_ref,
                         *, scale: float, block_k: int):
    # the table is consumed by the index maps only — the body is identical
    del table_ref
    _decode_kernel(start_ref, filled_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, scale=scale, block_k=block_k)


def _paged_decode_q8_kernel(start_ref, filled_ref, table_ref, q_ref, kq_ref,
                            ks_ref, vq_ref, vs_ref, o_ref, acc_ref, m_ref,
                            l_ref, *, scale: float, block_k: int):
    del table_ref
    _decode_q8_kernel(start_ref, filled_ref, q_ref, kq_ref, ks_ref, vq_ref,
                      vs_ref, o_ref, acc_ref, m_ref, l_ref, scale=scale,
                      block_k=block_k)


def _paged_verify_kernel(start_ref, fill_ref, table_ref, q_ref, k_ref, v_ref,
                         o_ref, acc_ref, m_ref, l_ref, *, scale: float,
                         block_k: int, Tq: int):
    del table_ref
    _verify_kernel(start_ref, fill_ref, q_ref, k_ref, v_ref, o_ref, acc_ref,
                   m_ref, l_ref, scale=scale, block_k=block_k, Tq=Tq)


def _paged_kv_index_map(num_pages, page_size, last_offset=-1):
    """Logical block → physical page index map for pool operands. The clamp
    chain: logical block clamps to the last valid block (revisit
    optimization, same as the contiguous kernels), then the table lookup
    clamps the sentinel `num_pages` to a real page (rows with released pages
    produce garbage that the caller discards — their writes were dropped and
    their outputs are masked).

    `last_offset`: the last readable slot relative to the prefetched bound —
    decode passes `filled` (one past the last slot, offset -1); verify
    passes `fill` (slot of candidate 0, offset Tq - 1)."""
    def kv_index_map(b, kv, j, start_ref, filled_ref, table_ref):
        first = start_ref[b] // page_size
        last = jnp.maximum((filled_ref[b] + last_offset) // page_size, 0)
        lb = jnp.minimum(first + j, last)
        page = jnp.minimum(table_ref[b, lb], num_pages - 1)
        return (page, kv, 0, 0)
    return kv_index_map


def paged_decode_attention(
    q: jnp.ndarray,       # [B, H, hd] — single decode position
    k_pool: jnp.ndarray,  # [N, KV, P, hd] global page pool
    v_pool: jnp.ndarray,  # [N, KV, P, hd]
    table: jnp.ndarray,   # [B, nb] int32 block table (sentinel = N)
    start: jnp.ndarray,   # [B] int32: first valid logical slot
    filled: jnp.ndarray,  # [B] int32: one past the last valid logical slot
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Prefix-bounded decode attention over the paged KV cache: the grid
    walks logical blocks [start//P, (filled-1)//P] and the index map routes
    each through the block table, so a row's pages may be scattered anywhere
    in the pool. Returns [B, H, hd]."""
    B, H, hd = q.shape
    N, KV, P, _ = k_pool.shape
    nb = table.shape[1]
    G = H // KV
    Gp = max(8, G)

    qg = q.reshape(B, KV, G, hd)
    if Gp != G:
        qg = jnp.pad(qg, [(0, 0), (0, 0), (0, Gp - G), (0, 0)])

    kernel = functools.partial(
        _paged_decode_kernel, scale=1.0 / (hd ** 0.5), block_k=P
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, KV, nb),
        in_specs=[
            pl.BlockSpec((1, 1, Gp, hd),
                         lambda b, kv, j, s, f, t: (b, kv, 0, 0)),
            pl.BlockSpec((1, 1, P, hd), _paged_kv_index_map(N, P)),
            pl.BlockSpec((1, 1, P, hd), _paged_kv_index_map(N, P)),
        ],
        out_specs=pl.BlockSpec((1, 1, Gp, hd),
                               lambda b, kv, j, s, f, t: (b, kv, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((Gp, hd), jnp.float32),
            pltpu.VMEM((Gp, 128), jnp.float32),
            pltpu.VMEM((Gp, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KV, Gp, hd), q.dtype),
        interpret=_interpret_default() if interpret is None else interpret,
    )(start.astype(jnp.int32), filled.astype(jnp.int32),
      table.astype(jnp.int32), qg, k_pool, v_pool)
    return out[:, :, :G, :].reshape(B, H, hd)


def paged_decode_attention_q8(
    q: jnp.ndarray,        # [B, H, hd]
    kq_pool: jnp.ndarray,  # [N, KV, P, hd] int8
    ks_pool: jnp.ndarray,  # [N, KV, 8, P] bf16 sublane-expanded scales
    vq_pool: jnp.ndarray,  # [N, KV, P, hd] int8
    vs_pool: jnp.ndarray,  # [N, KV, 8, P] bf16
    table: jnp.ndarray,    # [B, nb] int32
    start: jnp.ndarray,    # [B] int32
    filled: jnp.ndarray,   # [B] int32
    interpret: bool | None = None,
) -> jnp.ndarray:
    """int8-pool variant of `paged_decode_attention` (same folded-scale math
    as `decode_attention_q8`). Returns [B, H, hd]."""
    B, H, hd = q.shape
    N, KV, P, _ = kq_pool.shape
    nb = table.shape[1]
    G = H // KV
    Gp = max(8, G)

    qg = q.reshape(B, KV, G, hd)
    if Gp != G:
        qg = jnp.pad(qg, [(0, 0), (0, 0), (0, Gp - G), (0, 0)])

    kernel = functools.partial(
        _paged_decode_q8_kernel, scale=1.0 / (hd ** 0.5), block_k=P
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, KV, nb),
        in_specs=[
            pl.BlockSpec((1, 1, Gp, hd),
                         lambda b, kv, j, s, f, t: (b, kv, 0, 0)),
            # the scale block (1, 1, 8, P) shares the kv index map — both
            # resolve to (page, kv, 0, 0)
            pl.BlockSpec((1, 1, P, hd), _paged_kv_index_map(N, P)),
            pl.BlockSpec((1, 1, 8, P), _paged_kv_index_map(N, P)),
            pl.BlockSpec((1, 1, P, hd), _paged_kv_index_map(N, P)),
            pl.BlockSpec((1, 1, 8, P), _paged_kv_index_map(N, P)),
        ],
        out_specs=pl.BlockSpec((1, 1, Gp, hd),
                               lambda b, kv, j, s, f, t: (b, kv, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((Gp, hd), jnp.float32),
            pltpu.VMEM((Gp, 128), jnp.float32),
            pltpu.VMEM((Gp, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KV, Gp, hd), q.dtype),
        interpret=_interpret_default() if interpret is None else interpret,
    )(start.astype(jnp.int32), filled.astype(jnp.int32),
      table.astype(jnp.int32), qg, kq_pool, ks_pool, vq_pool, vs_pool)
    return out[:, :, :G, :].reshape(B, H, hd)


def paged_decode_verify_attention(
    q: jnp.ndarray,       # [B, H, Tq, hd] — k+1 candidate positions
    k_pool: jnp.ndarray,  # [N, KV, P, hd] (candidate KV already written)
    v_pool: jnp.ndarray,  # [N, KV, P, hd]
    table: jnp.ndarray,   # [B, nb] int32
    start: jnp.ndarray,   # [B] int32
    fill: jnp.ndarray,    # [B] int32: slot of candidate 0
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Paged k-query verify attention — `decode_verify_attention` with the
    kv stream routed through the block table. The grid covers logical blocks
    up to (fill + Tq - 1)//P so candidate writes straddling a page boundary
    are both visited. Returns [B, H, Tq, hd]."""
    B, H, Tq, hd = q.shape
    N, KV, P, _ = k_pool.shape
    nb = table.shape[1]
    G = H // KV
    R = G * Tq
    Rp = 8 * pl.cdiv(R, 8)

    qg = q.reshape(B, KV, G, Tq, hd).reshape(B, KV, R, hd)
    if Rp != R:
        qg = jnp.pad(qg, [(0, 0), (0, 0), (0, Rp - R), (0, 0)])

    kernel = functools.partial(
        _paged_verify_kernel, scale=1.0 / (hd ** 0.5), block_k=P, Tq=Tq
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, KV, nb),
        in_specs=[
            pl.BlockSpec((1, 1, Rp, hd),
                         lambda b, kv, j, s, f, t: (b, kv, 0, 0)),
            pl.BlockSpec((1, 1, P, hd), _paged_kv_index_map(N, P, last_offset=Tq - 1)),
            pl.BlockSpec((1, 1, P, hd), _paged_kv_index_map(N, P, last_offset=Tq - 1)),
        ],
        out_specs=pl.BlockSpec((1, 1, Rp, hd),
                               lambda b, kv, j, s, f, t: (b, kv, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((Rp, hd), jnp.float32),
            pltpu.VMEM((Rp, 128), jnp.float32),
            pltpu.VMEM((Rp, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KV, Rp, hd), q.dtype),
        interpret=_interpret_default() if interpret is None else interpret,
    )(start.astype(jnp.int32), fill.astype(jnp.int32),
      table.astype(jnp.int32), qg, k_pool, v_pool)
    return out[:, :, :R, :].reshape(B, KV, G, Tq, hd).reshape(B, H, Tq, hd)
