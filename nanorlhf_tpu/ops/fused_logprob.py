"""Fused hidden→logprob scoring: chunked linear-cross-entropy.

Every non-sequence-parallel scoring and update pass used to materialize the
full `[B, T, V]` logits tensor (`padded_forward_logits` → `logprobs_from_
logits`), plus an extra f32 copy for the entropy stat. At Qwen2's 152k vocab
that buffer is the single largest HBM allocation in the train step — it caps
microbatch size, grad-accum shape, and reachable response length (RLAX and
LlamaRL both name trainer logits memory as the first-order bottleneck for
long-sequence RLHF).

This module fuses the unembedding matmul with the log-softmax gather (and the
entropy stat + optional top-k margin, in the same pass), chunked over the
flattened token rows so only one `[chunk, V]` logits block is ever live:

- **`fused_logprob_reference`** — the naive full-logits lax path (parity
  oracle, and the `fused_logprob=False` trainer fallback's math).
- **lax chunked path** (`impl="lax"`) — a `lax.scan` over row chunks; each
  chunk recomputes its logits block from `hidden @ W` and reduces it to
  per-token scalars. Chunk math goes through the SAME `logprobs_from_logits`
  / `entropy_from_logits` helpers as the naive path, so fused-vs-naive parity
  is exact up to matmul tiling noise.
- **Pallas kernel** (`impl="pallas"`, `interpret=True` CPU fallback) — a
  vocab-blocked online-logsumexp kernel (grid: row blocks × vocab blocks,
  vocab fastest) carrying running max / sumexp / Σp·z / label-logit in VMEM
  scratch, the same online-softmax recipe as ops/attention.py. The `[rows,
  V]` block never leaves VMEM.
- **`jax.custom_vjp`**: the backward RECOMPUTES each chunk's logits block
  from the saved `(hidden, W, labels)` instead of saving any logits — the
  flash-attention memory trade applied to the LM head. `dW` accumulates in
  f32 across chunks.

Gradient semantics: per-token logprobs are exact (the backward replays the
naive path's VJP chunk by chunk). The entropy and margin outputs carry
STOP-GRADIENT semantics — their cotangents are discarded, matching the
trainer's `stop_gradient(entropy)` stat (a differentiable entropy would have
to re-derive Σp·z in the backward; nothing in the repo wants that gradient).

`impl="auto"` resolves to the Pallas kernel on TPU and the lax chunk scan
elsewhere; `with_margin` forces the lax path (the kernel does not track
top-2). See docs/FUSED_LOGPROB.md for the chunk-size trade.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU too; guarded for safety
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

from nanorlhf_tpu.ops.masking import (
    entropy_from_logits,
    guard_temperature,
    logprobs_from_logits,
)

NEG_INF = -1e30
_LANES = 128
_SUBLANES = 8

# Default HBM budget for one recomputed logits chunk. Per row the forward
# holds the [1, V] logits strip in model dtype and the backward recompute
# additionally its f32 softmax (the vjp intermediate), so ~(itemsize + 4)
# bytes per vocab entry. 256 MB → 288 rows at a 152k bf16 vocab
# (256 MB // (151936·6 B), floored to a sublane multiple): two orders of
# magnitude under the multi-GB full-logits buffer, still far above the
# matmul-efficiency floor.
_FUSED_BYTES_BUDGET = 256 * 1024**2


def fused_chunk_rows(
    vocab_size: int,
    total_rows: int,
    dtype_bytes: int = 2,
    bytes_budget: int | None = None,
) -> int:
    """Rows (flattened B·T tokens) per recomputed logits chunk.

    Derived from a bytes budget the same way trainer.forward_token_budget
    bounds the scoring chunk — the knob that makes peak memory SUBLINEAR in
    V: as the vocabulary grows, the chunk shrinks so chunk×V stays ≈ budget.
    Rounded down to a sublane multiple (8) for TPU-friendly tiling; floored
    at 8 rows; capped at total_rows.
    """
    budget = _FUSED_BYTES_BUDGET if bytes_budget is None else bytes_budget
    per_row = max(1, vocab_size) * (dtype_bytes + 4)
    rows = max(8, int(budget) // per_row)
    rows = max(8, (rows // _SUBLANES) * _SUBLANES)
    return int(min(rows, max(1, total_rows)))


# ---------------------------------------------------------------------------
# lax reference (full logits — the parity oracle)
# ---------------------------------------------------------------------------


def _head_matmul(h: jnp.ndarray, w: jnp.ndarray,
                 transposed: bool) -> jnp.ndarray:
    """`h @ w` ([D, V] weight) or `h @ wᵀ` ([V, D] weight, `transposed`) as
    ONE dot_general — never a transposed weight copy. The transposed form
    is how tied embeddings reach the op: a `.Tᵀ` view feeding a Pallas
    custom call would make XLA materialize the full [D, V] transpose
    (custom-call operands are physical buffers; only XLA dots fold
    transposes), ~260 MB bf16 at Qwen2's 152k vocab, held live across the
    whole vocab sweep."""
    dims = (((1,), (1,)) if transposed else ((1,), (0,)), ((), ()))
    return jax.lax.dot_general(h, w, dims)


def fused_logprob_reference(
    hidden: jnp.ndarray,     # [..., D]
    unembed: jnp.ndarray,    # [D, V] ([V, D] when `transposed`)
    labels: jnp.ndarray,     # [...] int
    temperature: float = 1.0,
    with_entropy: bool = False,
    with_margin: bool = False,
    transposed: bool = False,
):
    """Naive full-logits path: `hidden @ unembed` → per-token logprobs
    (+ entropy, + top-1-vs-top-2 margin). Materializes [..., V] — the
    memory behavior the fused paths eliminate. Entropy/margin are emitted
    under stop_gradient, matching the fused op's semantics."""
    logits = hidden @ (unembed.T if transposed else unembed)
    t = guard_temperature(temperature)
    out = (logprobs_from_logits(logits, labels, temperature),)
    if with_entropy:
        out += (jax.lax.stop_gradient(
            entropy_from_logits(logits.astype(jnp.float32) / t)
        ),)
    if with_margin:
        top2 = jax.lax.top_k(logits.astype(jnp.float32) / t, 2)[0]
        out += (jax.lax.stop_gradient(top2[..., 0] - top2[..., 1]),)
    return out[0] if len(out) == 1 else out


def chunked_entropy(
    logits: jnp.ndarray, temperature: float = 1.0, chunk: int | None = None,
    bytes_budget: int | None = None,
) -> jnp.ndarray:
    """Per-position entropy of temperature-scaled logits WITHOUT the f32
    full-logits copy: blocks are cast f32 one slice at a time (the
    `fused_logprob=False` fallback's entropy stat — the fused path gets
    entropy from its own pass and never sees full logits at all).

    Chunks along the TIME axis (second-to-last), not flattened rows: time
    slices leave a batch-sharded tensor's sharding intact, whereas
    flattening batch×time into rows and re-chunking reshards the batch
    axis — GSPMD answered the ragged slice+concat form of that with a
    MISCOMPILED program (entropy exactly 2× on a 2-way-sharded batch;
    pinned by the sharded-mesh test in tests/test_fused_logprob.py), and
    the padded form with a second full-logits copy. The static python loop
    unrolls into one slice+reduce per block.
    """
    t = guard_temperature(temperature)
    T, V = logits.shape[-2], logits.shape[-1]
    rows = int(np.prod(logits.shape[:-1]))
    if chunk is None:
        # only the f32 copy + softmax intermediates count here — the source
        # logits already exist
        chunk = fused_chunk_rows(V, rows, dtype_bytes=4,
                                 bytes_budget=bytes_budget)
    # row budget → time-axis block width
    rows_per_t = max(1, rows // T)
    t_chunk = max(1, min(T, int(chunk) // rows_per_t))
    n_blocks = -(-T // t_chunk)
    if n_blocks == 1:
        return entropy_from_logits(logits.astype(jnp.float32) / t)

    # fori_loop keeps the traced graph O(1) in T (an unrolled python loop
    # is ~300 slice+reduce ops at 8k responses). A ragged final block is
    # handled by CLAMPING its start to T - t_chunk: dynamic_slice clamps
    # out-of-bounds starts the same way, and the overlapping positions are
    # recomputed to identical values, so the overlapping write is benign.
    def body(i, out):
        start = jnp.minimum(i * t_chunk, T - t_chunk)
        block = jax.lax.dynamic_slice_in_dim(logits, start, t_chunk, axis=-2)
        ent = entropy_from_logits(block.astype(jnp.float32) / t)
        return jax.lax.dynamic_update_slice_in_dim(out, ent, start, axis=-1)

    out0 = jnp.zeros(logits.shape[:-1], jnp.float32)
    return jax.lax.fori_loop(0, n_blocks, body, out0)


# ---------------------------------------------------------------------------
# lax chunked forward/backward (the default off-TPU fused path)
# ---------------------------------------------------------------------------


def _lax_forward(hidden, unembed, labels, temperature, chunk,
                 with_entropy, with_margin, transposed):
    """Scan over row chunks; each [chunk, V] logits block is a scan-local
    temporary. Chunk math reuses the exact naive helpers so fused == naive."""
    R, D = hidden.shape
    n = R // chunk
    t = guard_temperature(temperature)
    hs = hidden.reshape(n, chunk, D)
    ls = labels.reshape(n, chunk)

    def body(_, xs):
        h_c, l_c = xs
        z = _head_matmul(h_c, unembed, transposed)
        out = (logprobs_from_logits(z, l_c, temperature),)
        if with_entropy:
            out += (entropy_from_logits(z.astype(jnp.float32) / t),)
        if with_margin:
            top2 = jax.lax.top_k(z.astype(jnp.float32) / t, 2)[0]
            out += (top2[..., 0] - top2[..., 1],)
        return None, out

    _, outs = jax.lax.scan(body, None, (hs, ls))
    return tuple(o.reshape(R) for o in outs)


# ---------------------------------------------------------------------------
# Pallas kernel: vocab-blocked online logsumexp + label gather + Σp·z
# ---------------------------------------------------------------------------


def _fused_kernel(h_ref, w_ref, lab_ref, lp_ref, *refs,
                  inv_temp: float, block_v: int, vocab_size: int,
                  w_transposed: bool, with_entropy: bool):
    # the entropy accumulator (Σ exp(z−m)·z) costs ~2 VPU ops per logit
    # element across the whole vocab sweep — the entropy output, its u
    # scratch, and that work exist only when the caller asked (the hot
    # scoring path never does; only the update-pass entropy stat does)
    if with_entropy:
        ent_ref, m_ref, l_ref, u_ref, g_ref = refs
    else:
        ent_ref = u_ref = None
        m_ref, l_ref, g_ref = refs
    v_idx = pl.program_id(1)
    n_v = pl.num_programs(1)

    @pl.when(v_idx == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        if with_entropy:
            u_ref[:] = jnp.zeros_like(u_ref)
        g_ref[:] = jnp.zeros_like(g_ref)

    h = h_ref[...].astype(jnp.float32)                  # [Br, D]
    w = w_ref[...].astype(jnp.float32)                  # [D, Bv] / [Bv, D]
    s = jax.lax.dot_general(
        h, w,
        (((1,), (1,) if w_transposed else (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * inv_temp                                        # [Br, Bv]
    block_r = s.shape[0]
    col = v_idx * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (block_r, block_v), 1
    )
    # vocab tail mask: V need not divide block_v — out-of-range columns are
    # neutralized here instead of padding a copy of the (huge) weight
    s = jnp.where(col < vocab_size, s, NEG_INF)

    lab = lab_ref[:, :1]                                # [Br, 1] int32
    # label gather: exactly one column matches across the whole vocab sweep
    g_new = g_ref[:, :1] + jnp.sum(
        jnp.where(col == lab, s, 0.0), axis=1, keepdims=True
    )

    m_prev = m_ref[:, :1]
    l_prev = l_ref[:, :1]
    m_cur = jnp.max(s, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)                              # masked cols → exp(-inf)=0
    l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
    if with_entropy:
        # Σ softmax·z carried unnormalized as Σ exp(z−m)·z (entropy
        # residual); 0 · NEG_INF = -0.0 for masked columns, never NaN
        # (NEG_INF is finite)
        u_new = alpha * u_ref[:, :1] + jnp.sum(p * s, axis=1, keepdims=True)
        u_ref[:] = jnp.broadcast_to(u_new, u_ref.shape)

    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)
    g_ref[:] = jnp.broadcast_to(g_new, g_ref.shape)

    @pl.when(v_idx == n_v - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        lse = m_ref[:, :1] + jnp.log(l)
        lp_ref[...] = jnp.broadcast_to(g_ref[:, :1] - lse, lp_ref.shape)
        if with_entropy:
            ent_ref[...] = jnp.broadcast_to(
                lse - u_ref[:, :1] / l, ent_ref.shape
            )


def _interpret_default() -> bool:
    from nanorlhf_tpu.ops.attention import _interpret_default as _att

    return _att()


def _pallas_forward(hidden, unembed, labels, temperature,
                    block_r: int = 256, block_v: int = 512,
                    interpret: bool | None = None, transposed: bool = False,
                    with_entropy: bool = False):
    """`(logprobs, entropy | None)` per row, [R] f32 — the [R, V] logits
    exist only as per-(row-block, vocab-block) VMEM tiles. With
    `transposed` the weight arrives [V, D] (tied embeddings) and the grid
    reads vocab-ROW blocks — the contraction flips inside the kernel, so no
    [D, V] transposed copy is staged for the custom call."""
    if pltpu is None:  # scratch_shapes needs pltpu.VMEM — no guarded fallback
        raise RuntimeError(
            "fused_logprob impl='pallas' unavailable: "
            "jax.experimental.pallas.tpu failed to import — use impl='lax'"
        )
    R, D = hidden.shape
    V = unembed.shape[0] if transposed else unembed.shape[1]
    inv_temp = 1.0 / guard_temperature(temperature)
    if interpret is None:
        interpret = _interpret_default()

    block_r = max(_SUBLANES, min(block_r, -(-R // _SUBLANES) * _SUBLANES))
    R_pad = -(-R // block_r) * block_r
    if R_pad != R:
        hidden = jnp.pad(hidden, ((0, R_pad - R), (0, 0)))
        labels = jnp.pad(labels, (0, R_pad - R))
    n_r = R_pad // block_r
    n_v = int(pl.cdiv(V, block_v))
    # labels ride lane-expanded [R, LANES] — a 1-D int vector is not a
    # Mosaic-liftable operand (same recipe as the attention kernels' mask)
    lab2 = jnp.broadcast_to(
        labels.astype(jnp.int32)[:, None], (R_pad, _LANES)
    )

    kernel = functools.partial(
        _fused_kernel, inv_temp=float(inv_temp), block_v=block_v,
        vocab_size=V, w_transposed=transposed, with_entropy=with_entropy,
    )
    w_spec = (
        pl.BlockSpec((block_v, D), lambda i, j: (j, 0), memory_space=_VMEM)
        if transposed else
        pl.BlockSpec((D, block_v), lambda i, j: (0, j), memory_space=_VMEM)
    )
    row_spec = pl.BlockSpec((block_r, _LANES), lambda i, j: (i, 0),
                            memory_space=_VMEM)
    row_shape = jax.ShapeDtypeStruct((R_pad, _LANES), jnp.float32)
    row_scratch = pltpu.VMEM((block_r, _LANES), jnp.float32)
    n_out = 2 if with_entropy else 1          # lp [, ent]
    n_scratch = 4 if with_entropy else 3      # m, l [, u], g
    outs = pl.pallas_call(
        kernel,
        grid=(n_r, n_v),
        in_specs=[
            pl.BlockSpec((block_r, D), lambda i, j: (i, 0),
                         memory_space=_VMEM),
            w_spec,
            row_spec,
        ],
        out_specs=[row_spec] * n_out,
        out_shape=[row_shape] * n_out,
        scratch_shapes=[row_scratch] * n_scratch,
        interpret=interpret,
    )(hidden, unembed, lab2)
    lp = outs[0][:R, 0]
    return lp, (outs[1][:R, 0] if with_entropy else None)


# ---------------------------------------------------------------------------
# custom_vjp core (2-D rows) + public entry
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _fused_core(hidden, unembed, labels, temperature, chunk, impl,
                with_entropy, with_margin, transposed):
    if impl == "pallas":
        lp, ent = _pallas_forward(hidden, unembed, labels, temperature,
                                  transposed=transposed,
                                  with_entropy=with_entropy)
        out = (lp,)
        if with_entropy:
            out += (ent,)
        return out
    return _lax_forward(
        hidden, unembed, labels, temperature, chunk, with_entropy,
        with_margin, transposed,
    )


def _core_fwd(hidden, unembed, labels, temperature, chunk, impl,
              with_entropy, with_margin, transposed):
    out = _fused_core(hidden, unembed, labels, temperature, chunk, impl,
                      with_entropy, with_margin, transposed)
    return out, (hidden, unembed, labels)


def _core_bwd(temperature, chunk, impl, with_entropy, with_margin,
              transposed, residuals, g):
    """Recompute each chunk's logits block and replay the naive VJP on it —
    no logits were saved in the forward. Entropy/margin cotangents (g[1:])
    are discarded: stop-gradient semantics, see module docstring. With
    `transposed` the vjp runs through `_head_matmul`'s flipped contraction,
    so dW lands in the weight's own [V, D] orientation — it accumulates
    straight into the tied embedding leaf, no transpose copy."""
    hidden, unembed, labels = residuals
    g_lp = g[0]
    R, D = hidden.shape
    n = R // chunk
    hs = hidden.reshape(n, chunk, D)
    ls = labels.reshape(n, chunk)
    gs = g_lp.reshape(n, chunk)

    def body(dw_acc, xs):
        h_c, l_c, g_c = xs

        def f(h_, w_):
            return logprobs_from_logits(
                _head_matmul(h_, w_, transposed), l_c, temperature
            )

        _, vjp = jax.vjp(f, h_c, unembed)
        dh_c, dw_c = vjp(g_c)
        return dw_acc + dw_c.astype(jnp.float32), dh_c

    dw, dh = jax.lax.scan(
        body, jnp.zeros(unembed.shape, jnp.float32), (hs, ls, gs)
    )
    # integer primal → float0 cotangent (jax's tangent type for int arrays)
    dlabels = np.zeros(labels.shape, jax.dtypes.float0)
    return dh.reshape(R, D), dw.astype(unembed.dtype), dlabels


_fused_core.defvjp(_core_fwd, _core_bwd)


def _resolve_impl(impl: str, with_margin: bool) -> str:
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "lax"
    if impl not in ("lax", "pallas"):
        raise ValueError(f"fused_logprob impl={impl!r}: auto | lax | pallas")
    if with_margin and impl == "pallas":
        return "lax"  # the kernel does not track top-2; lax path does
    return impl


def fused_logprob(
    hidden: jnp.ndarray,     # [..., D] final-normed hidden states
    unembed: jnp.ndarray,    # [D, V] weight ([V, D] when `transposed`)
    labels: jnp.ndarray,     # [...] int token ids to gather
    temperature: float = 1.0,
    *,
    chunk: int | None = None,
    impl: str = "auto",
    with_entropy: bool = False,
    with_margin: bool = False,
    bytes_budget: int | None = None,
    transposed: bool = False,
):
    """Per-token `log softmax(hidden @ unembed / T)[labels]` without ever
    materializing the [..., V] logits tensor.

    Returns `logprobs` (f32, shaped like `labels`), or a tuple
    `(logprobs[, entropy][, margin])` when the extra outputs are requested
    — entropy is the per-token logsumexp entropy of the temperature-scaled
    distribution, margin the top-1-vs-top-2 scaled-logit gap (both
    stop-gradient). `chunk=None` derives the rows-per-block from
    `bytes_budget` (`fused_chunk_rows`): peak memory then stays ≈ budget
    regardless of vocabulary size. Differentiable wrt `hidden` and
    `unembed`; the custom-VJP backward recomputes chunk logits instead of
    saving them.

    `transposed=True` takes the weight vocab-major ([V, D] — i.e. the tied
    `embed_tokens` leaf directly, see `core.model.unembedding`): every path
    contracts on the shared D axis (`_head_matmul`), dW comes back [V, D],
    and the Pallas grid reads vocab-row blocks — passing `embed.T` instead
    would stage a full [D, V] transposed copy for the custom call.
    """
    lead = hidden.shape[:-1]
    D = hidden.shape[-1]
    V = unembed.shape[0] if transposed else unembed.shape[-1]
    if labels.shape != lead:
        raise ValueError(f"labels shape {labels.shape} != hidden[:-1] {lead}")
    R = int(np.prod(lead)) if lead else 1
    impl = _resolve_impl(impl, with_margin)
    if chunk is None:
        chunk = fused_chunk_rows(
            V, R, dtype_bytes=jnp.dtype(hidden.dtype).itemsize,
            bytes_budget=bytes_budget,
        )
    chunk = max(1, min(int(chunk), R))
    h2 = hidden.reshape(R, D)
    l2 = labels.reshape(R).astype(jnp.int32)
    R_pad = -(-R // chunk) * chunk
    if R_pad != R:
        # pad rows so the scan sees equal chunks; the slice below zeroes the
        # pad rows' cotangents, so dW never sees them
        h2 = jnp.pad(h2, ((0, R_pad - R), (0, 0)))
        l2 = jnp.pad(l2, (0, R_pad - R))
    outs = _fused_core(h2, unembed, l2, float(temperature), int(chunk), impl,
                       bool(with_entropy), bool(with_margin),
                       bool(transposed))
    outs = tuple(o[:R].reshape(lead) for o in outs)
    if not with_entropy and not with_margin:
        return outs[0]
    return outs
