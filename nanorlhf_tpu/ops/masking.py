"""Pure sequence-masking / whitening numerics shared by every algorithm.

These are the TPU-native equivalents of the TRL helpers the reference trainers
import (`/root/reference/GRPO/grpo_trainer.py:54` — `first_true_indices`,
`truncate_response`, `masked_mean`, `masked_whiten`, `exact_div`) plus the
padding-mask construction inlined in every `train()` body
(`/root/reference/GRPO/grpo_trainer.py:588-594`).

All functions are pure jnp so they can live inside a jit/pjit-compiled step.
Semantics are pinned by unit tests in tests/test_masking.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Sentinel written into logprob tensors at padded positions
# (`/root/reference/GRPO/grpo_trainer.py:81,591-592`). A *positive* logprob is
# impossible, so downstream masked reductions can never confuse it with data —
# but it must be masked out before any mean/sum.
INVALID_LOGPROB = 1.0


def exact_div(a: int, b: int, custom_error_message: str = "") -> int:
    """Integer division that refuses to lose a remainder.

    Batch-size hierarchy guard (`/root/reference/GRPO/grpo_trainer.py:226-231`).
    """
    q = a // b
    if a != q * b:
        raise ValueError(f"{custom_error_message}, inexact division: {a} / {b} = {a / b}")
    return q


def first_true_indices(bools: jnp.ndarray, dtype=jnp.int32) -> jnp.ndarray:
    """Index of the first True along the last axis; row length if no True.

    Matches TRL `first_true_indices` used for sequence-length discovery
    (`/root/reference/GRPO/grpo_trainer.py:565`).
    """
    row_len = bools.shape[-1]
    idxs = jnp.arange(row_len, dtype=dtype)
    # Where False, pretend the index is row_len so min() skips it.
    masked = jnp.where(bools, idxs, row_len)
    return jnp.min(masked, axis=-1).astype(dtype)


def truncate_response(
    stop_token_id: int, pad_token_id: int, responses: jnp.ndarray
) -> jnp.ndarray:
    """Replace everything *after* the first stop token with pad.

    The stop token itself is kept — identical contract to TRL
    `truncate_response` (used at `/root/reference/GRPO/grpo_trainer.py:559-562`).
    """
    trunc_idxs = first_true_indices(responses == stop_token_id)[..., None]
    idxs = jnp.arange(responses.shape[-1])
    idxs = jnp.broadcast_to(idxs, responses.shape)
    return jnp.where(idxs > trunc_idxs, pad_token_id, responses)


def masked_mean(values: jnp.ndarray, mask: jnp.ndarray, axis=None) -> jnp.ndarray:
    """Mean of `values` over positions where `mask` is True."""
    mask = mask.astype(values.dtype)
    return jnp.sum(values * mask, axis=axis) / jnp.maximum(jnp.sum(mask, axis=axis), 1e-8)


def masked_var(
    values: jnp.ndarray, mask: jnp.ndarray, unbiased: bool = True
) -> jnp.ndarray:
    """Variance over masked positions, with Bessel correction by default.

    Mirrors TRL `masked_var` semantics (global reduction, used inside
    `masked_whiten` at e.g. `/root/reference/GRPO/grpo_trainer.py:608`).
    """
    mean = masked_mean(values, mask)
    centered = values - mean
    var = masked_mean(centered * centered, mask)
    if unbiased:
        n = jnp.sum(mask.astype(values.dtype))
        bessel = n / jnp.maximum(n - 1, 1.0)
        var = var * bessel
    return var


def masked_whiten(
    values: jnp.ndarray, mask: jnp.ndarray, shift_mean: bool = True
) -> jnp.ndarray:
    """Whiten to zero mean / unit variance over masked positions.

    `shift_mean=False` keeps the original mean (reward whitening path,
    `/root/reference/GRPO/grpo_trainer.py:606-608`).
    """
    mean = masked_mean(values, mask)
    var = masked_var(values, mask)
    whitened = (values - mean) * jax.lax.rsqrt(var + 1e-8)
    if not shift_mean:
        whitened = whitened + mean
    return whitened


def response_padding_masks(responses: jnp.ndarray, sequence_lengths: jnp.ndarray):
    """Build the (padding_mask, padding_mask_p1) pair every trainer uses.

    `sequence_lengths` is the index of the last real generated token.
    `padding_mask` is True strictly after it (logprobs/advantages);
    `padding_mask_p1` is True strictly after the one-past position
    (values/rewards). (`/root/reference/GRPO/grpo_trainer.py:588-594`.)
    """
    response_idxs = jnp.broadcast_to(
        jnp.arange(responses.shape[-1]), responses.shape
    )
    padding_mask = response_idxs > sequence_lengths[..., None]
    padding_mask_p1 = response_idxs > (sequence_lengths[..., None] + 1)
    return padding_mask, padding_mask_p1


# Floor for every temperature division in the repo. ONE constant, ONE guard:
# the sampler's decode-time logprob capture, the scoring-pass
# `logprobs_from_logits`, and the update-pass entropy stat previously used
# three different guards (max(t, 1e-6) / raw t / t + 1e-7), so captured
# behavior logprobs and scoring logprobs disagreed bit-for-bit at small
# temperatures — exactly where the IS-ratio math is most sensitive.
MIN_TEMPERATURE = 1e-6


def guard_temperature(temperature):
    """`max(temperature, MIN_TEMPERATURE)` — the shared division guard.

    Accepts a static python float (sampler/scoring pass the config value,
    returning a float that folds into the jitted graph as a constant) or a
    traced array.
    """
    if isinstance(temperature, (int, float)):
        return max(float(temperature), MIN_TEMPERATURE)
    return jnp.maximum(temperature, MIN_TEMPERATURE)


def logprobs_from_logits(
    logits: jnp.ndarray, labels: jnp.ndarray, temperature: float = 1.0
) -> jnp.ndarray:
    """log softmax(logits / temperature) gathered at `labels`.

    Temperature divides the logits *before* log-softmax, exactly as in the
    reference logprob pass (`/root/reference/GRPO/grpo_trainer.py:547-549`),
    through the shared `guard_temperature` floor (so sampler-captured and
    scoring logprobs agree bit-for-bit at any temperature).

    Memory-shaped for big vocabularies: computed as
    `logit[label]/T − logsumexp(logits/T)` so no [B, T, V] log-softmax (or
    f32 copy of the logits) is ever materialized — the f32 convert fuses
    into the logsumexp reduction. At Qwen2's 152k vocab this halves the
    peak HBM of the scoring/update passes. f32 math throughout. (The
    fully-fused path that never sees [B, T, V] logits at all lives in
    ops/fused_logprob.py.)
    """
    temperature = guard_temperature(temperature)
    label_logits = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    lse = jax.scipy.special.logsumexp(
        logits.astype(jnp.float32) / temperature, axis=-1
    )
    return label_logits.astype(jnp.float32) / temperature - lse


def entropy_from_logits(logits: jnp.ndarray) -> jnp.ndarray:
    """Per-position entropy: logsumexp(z) - sum softmax(z) * z.

    Matches the stats computation at
    `/root/reference/GRPO/grpo_trainer.py:679-680`.
    """
    logits = logits.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    return jax.scipy.special.logsumexp(logits, axis=-1) - jnp.sum(
        probs * logits, axis=-1
    )
