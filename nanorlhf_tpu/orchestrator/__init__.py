"""Async rollout orchestration: version-tagged weights, bounded-staleness
sample queue, producer-thread rollout pipeline (docs/ORCHESTRATOR.md), and
the N-worker elastic rollout fleet (docs/FLEET.md)."""

from nanorlhf_tpu.orchestrator.weight_store import VersionedWeightStore
from nanorlhf_tpu.orchestrator.sample_queue import (
    BoundedStalenessQueue,
    ProducerFailed,
    QueuedSample,
)
from nanorlhf_tpu.orchestrator.orchestrator import (
    OverlapMeter,
    RolloutOrchestrator,
    note_ready_async,
)
from nanorlhf_tpu.orchestrator.fleet import (
    FleetConfig,
    FleetCoordinator,
    FleetExhausted,
    FleetOrchestrator,
    FleetTransport,
    InProcessTransport,
    Lease,
    RolloutWorker,
)
from nanorlhf_tpu.orchestrator.rpc import (
    FleetRpcServer,
    RemoteCoordinator,
    RpcClient,
    RpcConfig,
    RpcTransport,
    TransportError,
)

__all__ = [
    "BoundedStalenessQueue",
    "FleetConfig",
    "FleetCoordinator",
    "FleetExhausted",
    "FleetOrchestrator",
    "FleetRpcServer",
    "FleetTransport",
    "InProcessTransport",
    "Lease",
    "OverlapMeter",
    "ProducerFailed",
    "QueuedSample",
    "RemoteCoordinator",
    "RolloutOrchestrator",
    "RolloutWorker",
    "RpcClient",
    "RpcConfig",
    "RpcTransport",
    "TransportError",
    "VersionedWeightStore",
    "note_ready_async",
]
