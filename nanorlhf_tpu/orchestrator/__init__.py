"""Async rollout orchestration: version-tagged weights, bounded-staleness
sample queue, producer-thread rollout pipeline (docs/ORCHESTRATOR.md)."""

from nanorlhf_tpu.orchestrator.weight_store import VersionedWeightStore
from nanorlhf_tpu.orchestrator.sample_queue import (
    BoundedStalenessQueue,
    ProducerFailed,
    QueuedSample,
)
from nanorlhf_tpu.orchestrator.orchestrator import (
    OverlapMeter,
    RolloutOrchestrator,
    note_ready_async,
)

__all__ = [
    "BoundedStalenessQueue",
    "OverlapMeter",
    "ProducerFailed",
    "QueuedSample",
    "RolloutOrchestrator",
    "VersionedWeightStore",
    "note_ready_async",
]
