"""Elastic rollout fleet: leased work, worker-level fault tolerance, live
reassignment (docs/FLEET.md).

Generalizes the single producer thread of `orchestrator.py` into N
independent, *preemptible* rollout workers — the trainer-pod + rollout-pod
shape of RLAX (arxiv 2512.06392) and LlamaRL (arxiv 2505.24034), where
losing a rollout worker under multi-tenant traffic is routine, not
exceptional. Three layers:

- **FleetCoordinator** — owns the prompt-index cursor and the determinism
  contract. It hands out *leases*: contiguous rollout-index ranges whose
  prompt batches are drawn from the data iterator AT GRANT TIME (under the
  coordinator lock, in strict index order) and cached on the lease. A
  revoked lease is reassigned to a healthy worker **with the same cached
  batches and the same index-keyed PRNG stream**, so a lost worker changes
  which silicon generates a sample but never what is generated (at
  staleness 0 the token stream is bit-identical, test-pinned; at
  staleness > 0 the re-dispatch may read fresher weights — the same resume
  semantics the single-producer restart has). Completed samples pass
  through an in-order reorder buffer before entering the bounded-staleness
  queue, so the consumer sees exactly the single-producer index order.

- **RolloutWorker** — an in-process thread (the whole machinery runs on the
  tier-1 CPU mesh) looping acquire-lease → heartbeat → fetch weights →
  dispatch → report. It talks to the world only through a small
  **FleetTransport** (`dispatch` / `heartbeat` / `fetch_weights`), the seam
  where a future multi-host backend (gRPC to a rollout pod, weights via
  device-to-device broadcast) plugs in without touching the coordinator.

- **FleetOrchestrator** — the consumer-facing shell with the SAME surface
  as `RolloutOrchestrator` (get / publish / stats / journal / close /
  consumed_without_update), so the trainer's watchdog, sentinel, and
  checkpoint machinery drive both interchangeably.

Fault tolerance (every mode deterministically reproducible via the
worker-scoped fault sites in resilience/faults.py):

- *crash* — a dead worker (in-band fatal report, or thread death noticed
  by the liveness check) has its lease revoked and reassigned; membership
  shrinks. `fleet/reassigned_leases` counts these.
- *hang / straggle* — lease deadlines derive from an EWMA of sample
  latency (`straggler_factor × ewma × lease_len`); an expired lease is
  revoked and re-dispatched speculatively (first completion per index
  wins; late duplicates are dropped, `fleet/duplicate_samples`).
- *partition / split-brain* — every lease carries a monotonically
  increasing **epoch** (fencing token, = the coordinator's lease
  sequence at grant). A completion whose epoch is lower than the highest
  epoch granted for that index is FENCED: the revoked holder — maybe a
  partitioned worker racing its replacement over a healed link — cannot
  commit, regardless of arrival order. Fenced completions count as
  `fleet/fenced_completions` (and duplicates) and emit the
  `fleet_late_duplicate` lineage drop with `{"fenced": true, "epoch"}`.
- *flaky* — consecutive in-band failures past `failure_budget` quarantine
  the worker with exponential backoff + jitter (resilience/retry.py — the
  jitter prevents N workers from stampeding the weight store in lockstep);
  a completed sample resets the streak.
- *elastic membership* — workers join/leave mid-run (`add_worker` /
  `remove_worker`); losing the LAST worker fails the queue with
  `FleetExhausted` (a ProducerFailed), which the trainer's existing
  watchdog answers with restart-with-backoff and, past budget, the
  synchronous degraded mode — never a deadlock.
"""

from __future__ import annotations

import collections
import dataclasses
import random
import threading

from nanorlhf_tpu.analysis.lockorder import make_condition
import time
from typing import Callable, Optional

from nanorlhf_tpu.orchestrator.sample_queue import (
    BoundedStalenessQueue,
    ProducerFailed,
    QueuedSample,
)
from nanorlhf_tpu.orchestrator.weight_store import (
    VersionedWeightStore,
    make_swap_refresh,
)
from nanorlhf_tpu.resilience.retry import backoff_delay
from nanorlhf_tpu.telemetry.lineage import segments_summary, spec_summary


class FleetExhausted(ProducerFailed):
    """Every fleet worker is lost. A ProducerFailed subclass so the
    trainer's producer watchdog supervises fleet death exactly like a
    single-producer death: restart (a fresh fleet) with backoff, then the
    synchronous degraded mode."""


@dataclasses.dataclass
class FleetConfig:
    """Coordinator policy knobs (mirrored by RLConfig.fleet_*)."""

    lease_size: int = 1           # rollout indices per lease
    failure_budget: int = 2       # consecutive failures before quarantine
    quarantine_base: float = 0.5  # re-admission backoff: base · 2^k seconds
    quarantine_max: float = 30.0
    backoff_jitter: float = 0.25  # ±fraction spread (anti-stampede)
    straggler_factor: float = 4.0  # lease deadline = factor · ewma · length
    initial_deadline_s: float = 600.0  # pre-EWMA deadline (cold compile)
    worker_timeout_s: float = 600.0    # heartbeat staleness → lost (only
                                       # for transports without a liveness
                                       # probe; in-process uses the thread)
    ewma_alpha: float = 0.3
    poll_interval: float = 0.25   # acquire-wait / consumer-poll cadence
    seed: int = 0                 # quarantine-jitter PRNG


@dataclasses.dataclass
class Lease:
    """A contiguous rollout-index range granted to one worker, with the
    prompt batches drawn (in index order) at grant time. Reassignment hands
    the SAME batches to the next worker — the data cursor is never redrawn
    for a lease that already burned it."""

    lease_id: int
    worker_id: int
    start: int                 # first rollout index
    batches: list              # prompt batch per index (host arrays)
    issued_at: float           # coordinator clock
    deadline: float
    revoked: bool = False
    reassigned_from: Optional[int] = None  # worker that lost it (if any)
    epoch: int = 0             # fencing token (coordinator lease sequence
                               # at grant; higher = granted later)

    def __len__(self) -> int:
        return len(self.batches)


@dataclasses.dataclass
class _WorkerRecord:
    worker_id: int
    alive_fn: Optional[Callable[[], bool]] = None
    last_heartbeat: float = 0.0
    quarantined_until: float = 0.0
    consecutive_failures: int = 0
    quarantines: int = 0
    samples: int = 0
    ewma_s: float = 0.0
    lost: bool = False
    # drain-then-remove (elastic scale-in): a draining worker is still
    # alive and may finish its in-flight leases, but the grant path
    # skips it — no new work, then deregister once its leases complete
    draining: bool = False

    def alive(self, now: float, timeout: float) -> bool:
        if self.lost:
            return False
        if self.alive_fn is not None:
            return bool(self.alive_fn())
        return (now - self.last_heartbeat) < timeout


_COUNTERS = (
    "leases_granted", "reassigned_leases", "expired_leases",
    "speculative_dispatches", "worker_failures", "quarantines",
    "worker_joins", "worker_losses", "duplicate_samples",
    "fenced_completions",
)

# transport counters merged into stats() when a network transport has
# registered its provider (FleetRpcServer.transport_info); always present
# (0.0) so the fleet/rpc_* metric rows exist for every transport
_TRANSPORT_COUNTERS = (
    "rpc_retries", "rpc_reconnects", "rpc_rtt_ewma_s",
    "rpc_bytes_tx", "rpc_bytes_rx", "rpc_errors", "heartbeat_misses",
)


class FleetCoordinator:
    """Owns the prompt-index cursor, the lease table, worker membership /
    liveness, and the in-order reorder buffer feeding the bounded-staleness
    queue. jax-free: unit-testable with fake workers and plain payloads.

    Grant fairness: workers waiting in `acquire` form a FIFO; only the
    first ELIGIBLE (not lost, not quarantined) waiter is granted, then
    rejoins the tail. Round-robin grants make fleet behavior reproducible
    enough for the fault-matrix tests without a global scheduler.

    Lock order: the coordinator lock may be held while taking the queue's
    lock (`may_produce`, `put`, `fail`), never the reverse — the queue
    calls nothing back.
    """

    def __init__(
        self,
        queue: BoundedStalenessQueue,
        batch_fn: Optional[Callable[[], object]],
        start_index: int = 0,
        config: Optional[FleetConfig] = None,
        clock: Callable[[], float] = time.monotonic,
        faults=None,
        tracer=None,
        meter=None,
        lineage=None,
    ):
        self.cfg = config or FleetConfig()
        self._queue = queue
        self._batch_fn = batch_fn
        self._clock = clock
        self._faults = faults
        self._tracer = tracer
        self._meter = meter  # OverlapMeter: retire a lost worker's track
        # telemetry.LineageLedger: lease-grant provenance (lease/worker ids,
        # reassigned_from on a re-grant) + late-duplicate drop attribution
        self._lineage = lineage
        self._cond = make_condition("fleet.coordinator")
        self._workers: dict[int, _WorkerRecord] = {}
        self._waiters: list[int] = []
        self._leases: dict[int, Lease] = {}
        self._reassign: collections.deque[Lease] = collections.deque()
        self._cursor = start_index     # next index to draw/grant
        self._next_emit = start_index  # next index to enter the queue
        self._ready: dict[int, QueuedSample] = {}
        self._done: set[int] = set()   # completed but not yet emitted
        self._lease_seq = 0
        # fencing: highest lease epoch granted per rollout index (pruned a
        # fixed window behind the emit cursor, so late-landing completions
        # of recently emitted indices still get fenced attribution)
        self._index_epoch: dict[int, int] = {}
        self._transport_name = "inprocess"
        self._transport_info: Optional[Callable[[], dict]] = None
        self._ewma_s = 0.0             # fleet-wide sample latency
        self._rng = random.Random(self.cfg.seed)
        self._closed = False
        self.exhausted = False
        self.last_error: Optional[BaseException] = None
        self.gate_wait_s = 0.0         # cumulative worker wait in acquire
        self.counters = {k: 0 for k in _COUNTERS}

    # ---------------------------------------------------------------- #
    # membership
    # ---------------------------------------------------------------- #

    def register_worker(self, worker_id: int,
                        alive_fn: Optional[Callable[[], bool]] = None):
        with self._cond:
            self._workers[worker_id] = _WorkerRecord(
                worker_id, alive_fn=alive_fn, last_heartbeat=self._clock()
            )
            self.counters["worker_joins"] += 1
            if self._tracer is not None and self._tracer.enabled:
                self._tracer.instant("fleet.join", worker=worker_id)
            self._cond.notify_all()

    def deregister_worker(self, worker_id: int):
        """Graceful leave (elastic scale-down): revoke + reassign the
        worker's leases; not counted as a loss, but the exhaustion check
        still fires if this was the last member."""
        with self._cond:
            rec = self._workers.get(worker_id)
            if rec is None or rec.lost:
                return
            rec.lost = True
            self._revoke_worker_leases_locked(worker_id)
            if self._meter is not None:
                self._meter.retire_gen_track(worker_id)
            self._check_exhausted_locked()
            self._cond.notify_all()

    def drain_worker(self, worker_id: int) -> bool:
        """Stop granting this worker new leases; its in-flight leases keep
        running to completion (the first half of drain-then-remove —
        wait_drained + deregister_worker finish the job). Returns False
        for an unknown/lost worker."""
        with self._cond:
            rec = self._workers.get(worker_id)
            if rec is None or rec.lost:
                return False
            rec.draining = True
            if self._tracer is not None and self._tracer.enabled:
                self._tracer.instant("fleet.drain", worker=worker_id)
            self._cond.notify_all()
            return True

    def wait_drained(self, worker_id: int, timeout: float = 30.0) -> bool:
        """Block until the worker holds no live lease (all completed or
        revoked) or `timeout` real seconds pass. `complete()` prunes done
        leases and notifies, so this wakes promptly."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while any(l.worker_id == worker_id and not l.revoked
                      for l in self._leases.values()):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(timeout=min(remaining,
                                            self.cfg.poll_interval))
                self._poll_locked()
            return True

    def live_worker_ids(self) -> list:
        """Ids of members that have not left/been lost (draining workers
        still count — they hold capacity until deregistered)."""
        with self._cond:
            return sorted(wid for wid, rec in self._workers.items()
                          if not rec.lost)

    def heartbeat(self, worker_id: int):
        with self._cond:
            rec = self._workers.get(worker_id)
            if rec is not None:
                rec.last_heartbeat = self._clock()

    def set_transport(self, name: str,
                      info_fn: Optional[Callable[[], dict]] = None):
        """Register the transport's identity + stats provider. `info_fn`
        (e.g. FleetRpcServer.transport_info) is called under the
        coordinator lock from stats()/snapshot(); it must only take the
        transport's own lock and never call back into the coordinator."""
        with self._cond:
            self._transport_name = name
            self._transport_info = info_fn

    @property
    def current_epoch(self) -> int:
        """Highest lease epoch granted so far (the fencing high-water
        mark a reconnecting worker learns in the hello handshake)."""
        with self._cond:
            return self._lease_seq

    def kick(self):
        """Wake acquire-waiters (a publish or skip-credit may have opened
        the staleness gate)."""
        with self._cond:
            self._cond.notify_all()

    # ---------------------------------------------------------------- #
    # lease lifecycle (worker side)
    # ---------------------------------------------------------------- #

    def acquire(self, worker_id: int, stop: threading.Event
                ) -> Optional[Lease]:
        """Block until this worker is granted a lease; None on stop/close/
        deregistration. Wait time accumulates into `gate_wait_s` — the
        fleet's analogue of the producer staleness-gate wait."""
        with self._cond:
            if worker_id not in self._waiters:
                self._waiters.append(worker_id)
            try:
                while not stop.is_set() and not self._closed:
                    self._poll_locked()
                    rec = self._workers.get(worker_id)
                    if rec is None or rec.lost:
                        return None
                    now = self._clock()
                    if (rec.quarantined_until <= now
                            and self._head_waiter_locked(now) == worker_id):
                        lease = self._next_work_locked(worker_id, now)
                        if lease is not None:
                            self._waiters.remove(worker_id)
                            self._cond.notify_all()
                            return lease
                    t0 = time.perf_counter()
                    self._cond.wait(timeout=self.cfg.poll_interval)
                    self.gate_wait_s += time.perf_counter() - t0
                return None
            finally:
                if worker_id in self._waiters and (
                        stop.is_set() or self._closed
                        or worker_id not in self._workers
                        or self._workers[worker_id].lost):
                    self._waiters.remove(worker_id)

    def acquire_nowait(self, worker_id: int
                       ) -> tuple[Optional[Lease], bool]:
        """One non-blocking grant attempt for a REMOTE worker (the RPC
        server answers `acquire` ops with this; the client polls). Returns
        (lease, stopped): lease is None when nothing is grantable right
        now, stopped=True tells the worker to exit its loop. FIFO fairness
        is preserved — a polling remote worker holds its waiter slot
        between attempts exactly like a blocked in-process one."""
        with self._cond:
            self._poll_locked()
            rec = self._workers.get(worker_id)
            if self._closed or rec is None or rec.lost:
                if worker_id in self._waiters:
                    self._waiters.remove(worker_id)
                return None, True
            if worker_id not in self._waiters:
                self._waiters.append(worker_id)
            now = self._clock()
            if (rec.quarantined_until <= now
                    and self._head_waiter_locked(now) == worker_id):
                lease = self._next_work_locked(worker_id, now)
                if lease is not None:
                    self._waiters.remove(worker_id)
                    self._cond.notify_all()
                    return lease, False
            # the remote worker sleeps its poll interval client-side; that
            # wait is this fleet's staleness-gate wait
            self.gate_wait_s += self.cfg.poll_interval
            return None, self._closed

    def _head_waiter_locked(self, now: float) -> Optional[int]:
        for wid in self._waiters:
            rec = self._workers.get(wid)
            if rec is None or rec.lost or rec.draining:
                continue
            if rec.quarantined_until > now:
                continue
            return wid
        return None

    def _next_work_locked(self, worker_id: int, now: float
                          ) -> Optional[Lease]:
        # 1) reassignment pool first (oldest revoked work carries the
        #    lowest indices — the consumer is blocked on exactly those)
        while self._reassign:
            old = self._reassign.popleft()
            offsets = [o for o in range(len(old))
                       if not self._index_done_locked(old.start + o)]
            if not offsets:
                continue  # fully completed by a speculative peer meanwhile
            lease = self._grant_locked(
                worker_id, old.start, old.batches, now,
                reassigned_from=old.worker_id,
            )
            self.counters["reassigned_leases"] += 1
            if self._tracer is not None and self._tracer.enabled:
                self._tracer.instant(
                    "fleet.reassign", worker=worker_id,
                    from_worker=old.worker_id, start=old.start,
                    length=len(old),
                )
            return lease
        # 2) new indices from the cursor, as many as the staleness gate
        #    admits up to lease_size
        if self._batch_fn is None:
            return None
        n = 0
        while (n < self.cfg.lease_size
               and self._queue.may_produce(self._cursor + n)):
            n += 1
        if n == 0:
            return None
        try:
            if self._faults is not None:
                # generic producer fault site — BEFORE the data iterator is
                # touched, same contract as the single-producer loop
                self._faults.fire("rollout.produce")
            batches = [self._batch_fn() for _ in range(n)]
        except BaseException as e:
            # the data source (or an injected produce fault) failed: this is
            # a COORDINATOR death, not a worker death — surface it to the
            # consumer through the queue so the watchdog restarts the fleet
            self.last_error = e
            self._closed = True
            self._queue.fail(e)
            self._cond.notify_all()
            return None
        lease = self._grant_locked(worker_id, self._cursor, batches, now)
        self._cursor += n
        return lease

    def _grant_locked(self, worker_id: int, start: int, batches: list,
                      now: float, reassigned_from: Optional[int] = None
                      ) -> Lease:
        self._lease_seq += 1
        deadline = now + self._deadline_s(len(batches))
        lease = Lease(
            lease_id=self._lease_seq, worker_id=worker_id, start=start,
            batches=batches, issued_at=now, deadline=deadline,
            reassigned_from=reassigned_from, epoch=self._lease_seq,
        )
        self._leases[lease.lease_id] = lease
        self.counters["leases_granted"] += 1
        for o in range(len(batches)):
            # fencing high-water mark: a re-grant raises the bar, and any
            # completion still carrying the old epoch is rejected
            idx = start + o
            if lease.epoch > self._index_epoch.get(idx, 0):
                self._index_epoch[idx] = lease.epoch
        if self._lineage is not None and self._lineage.enabled:
            # one lease event per covered index: the chain for a rollout
            # index joins on rollout_index, and a reassigned lease's second
            # event carries BOTH worker ids (worker_id + reassigned_from)
            for o in range(len(batches)):
                self._lineage.lease(
                    start + o, lease_id=lease.lease_id, worker_id=worker_id,
                    reassigned_from=reassigned_from, cursor=start + o,
                    length=len(batches), transport=self._transport_name,
                    epoch=lease.epoch,
                )
        return lease

    def _deadline_s(self, length: int) -> float:
        if self._ewma_s <= 0.0:
            return self.cfg.initial_deadline_s
        return self.cfg.straggler_factor * self._ewma_s * max(1, length)

    # ---------------------------------------------------------------- #
    # completion / failure (worker side)
    # ---------------------------------------------------------------- #

    def _index_done_locked(self, index: int) -> bool:
        return index < self._next_emit or index in self._done

    def index_done(self, index: int) -> bool:
        with self._cond:
            return self._index_done_locked(index)

    def lease_revoked(self, lease: Lease) -> bool:
        with self._cond:
            return lease.revoked

    def lease_by_id(self, lease_id: int) -> Optional[Lease]:
        """The live lease with this id, or None if completed/revoked and
        pruned (the RPC server resolves completion/failure reports that
        arrive carrying only the id)."""
        with self._cond:
            return self._leases.get(lease_id)

    def lease_active(self, lease_id: int) -> bool:
        with self._cond:
            return lease_id in self._leases

    def complete(self, worker_id: int, lease: Lease, index: int,
                 sample: QueuedSample) -> bool:
        """Record a device-ready sample. A completion commits only when it
        is the first for its index AND carries the highest epoch granted
        for that index (the fencing token): a revoked holder — straggler
        or partitioned worker — cannot commit after its re-dispatch was
        granted, regardless of arrival order. Rejected completions return
        False; accepted samples enter the queue strictly in index order
        via the reorder buffer."""
        with self._cond:
            now = self._clock()
            rec = self._workers.get(worker_id)
            latency = max(0.0, sample.ready_time - sample.dispatch_time)
            if rec is not None:
                rec.last_heartbeat = now
                rec.samples += 1
                rec.consecutive_failures = 0
                rec.ewma_s = latency if rec.samples == 1 else (
                    self.cfg.ewma_alpha * latency
                    + (1 - self.cfg.ewma_alpha) * rec.ewma_s
                )
            self._ewma_s = latency if self._ewma_s <= 0.0 else (
                self.cfg.ewma_alpha * latency
                + (1 - self.cfg.ewma_alpha) * self._ewma_s
            )
            epoch = getattr(lease, "epoch", 0)
            granted = self._index_epoch.get(index)
            fenced = granted is not None and 0 < epoch < granted
            if fenced or self._index_done_locked(index):
                self.counters["duplicate_samples"] += 1
                if fenced:
                    self.counters["fenced_completions"] += 1
                if self._lineage is not None:
                    # a revoked/straggling holder's result losing to its
                    # replacement: the SAMPLES are not lost (the winner's
                    # are trained on) — the duplicate batch is what hits
                    # the floor. `fenced` marks epoch rejections (the
                    # partition case) vs plain arrival-order losses.
                    self._lineage.drop(
                        index, "fleet_late_duplicate", worker_id=worker_id,
                        lease_id=lease.lease_id, fenced=fenced, epoch=epoch,
                    )
                self._cond.notify_all()
                return False
            self._done.add(index)
            self._ready[index] = sample
            while self._next_emit in self._ready:
                self._queue.put(self._ready.pop(self._next_emit))
                self._done.discard(self._next_emit)
                # keep a trailing window of epochs so late completions of
                # just-emitted indices still get fenced attribution
                self._index_epoch.pop(self._next_emit - 1024, None)
                self._next_emit += 1
            # sweep EVERY fully-completed lease, not just the one this
            # completion belongs to: after a speculative re-dispatch the
            # same indices live on two leases, and the one whose worker
            # skipped all already-done offsets never calls complete() — a
            # survivor would later "expire" and charge a phantom failure
            # to the innocent replacement worker
            self._prune_done_leases_locked()
            if self._tracer is not None and self._tracer.enabled:
                self._tracer.counter(
                    "orchestrator/queue_depth", self._queue.depth()
                )
            self._cond.notify_all()
            return True

    def worker_failed(self, worker_id: int, lease: Optional[Lease],
                      exc: BaseException, fatal: bool = False):
        """In-band failure report. Recoverable failures charge the
        consecutive-failure budget (quarantine past it); fatal ones remove
        the worker from membership. Either way the lease's incomplete
        indices go back to the reassignment pool."""
        with self._cond:
            self.last_error = exc
            self.counters["worker_failures"] += 1
            rec = self._workers.get(worker_id)
            if lease is not None:
                self._revoke_locked(lease)
            if rec is not None and not rec.lost:
                if fatal:
                    self._mark_lost_locked(rec)
                else:
                    self._charge_failure_locked(rec)
            self._check_exhausted_locked()
            self._cond.notify_all()

    def _charge_failure_locked(self, rec: _WorkerRecord):
        rec.consecutive_failures += 1
        if rec.consecutive_failures > self.cfg.failure_budget:
            rec.quarantines += 1
            rec.consecutive_failures = 0  # fresh budget after re-admission
            delay = backoff_delay(
                rec.quarantines - 1, self.cfg.quarantine_base,
                self.cfg.quarantine_max, jitter=self.cfg.backoff_jitter,
                rng=self._rng,
            )
            rec.quarantined_until = self._clock() + delay
            self.counters["quarantines"] += 1
            if self._tracer is not None and self._tracer.enabled:
                self._tracer.instant(
                    "fleet.quarantine", worker=rec.worker_id,
                    backoff_s=round(delay, 3),
                )

    def _mark_lost_locked(self, rec: _WorkerRecord):
        rec.lost = True
        self.counters["worker_losses"] += 1
        self._revoke_worker_leases_locked(rec.worker_id)
        if self._meter is not None:
            self._meter.retire_gen_track(rec.worker_id)
        if self._tracer is not None and self._tracer.enabled:
            self._tracer.instant("fleet.lost", worker=rec.worker_id)

    def _revoke_worker_leases_locked(self, worker_id: int):
        for lease in [l for l in self._leases.values()
                      if l.worker_id == worker_id]:
            self._revoke_locked(lease)

    def _revoke_locked(self, lease: Lease):
        lease.revoked = True
        self._leases.pop(lease.lease_id, None)
        if any(not self._index_done_locked(lease.start + o)
               for o in range(len(lease))):
            self._reassign.append(lease)

    def _check_exhausted_locked(self):
        live = [r for r in self._workers.values() if not r.lost]
        if self._workers and not live and not self.exhausted:
            self.exhausted = True
            self._queue.fail(FleetExhausted(
                f"all {len(self._workers)} rollout workers lost"
            ))

    # ---------------------------------------------------------------- #
    # liveness / straggler sweep
    # ---------------------------------------------------------------- #

    def poll(self):
        with self._cond:
            self._poll_locked()

    def _prune_done_leases_locked(self):
        for lease in list(self._leases.values()):
            if all(self._index_done_locked(lease.start + o)
                   for o in range(len(lease))):
                self._leases.pop(lease.lease_id, None)

    def _poll_locked(self):
        now = self._clock()
        self._prune_done_leases_locked()
        for lease in list(self._leases.values()):
            if now <= lease.deadline:
                continue
            rec = self._workers.get(lease.worker_id)
            alive = rec is not None and rec.alive(
                now, self.cfg.worker_timeout_s
            )
            self.counters["expired_leases"] += 1
            if alive:
                # straggler (or hang): revoke + re-dispatch speculatively.
                # The original worker's in-flight result is still accepted
                # if it lands before the replacement's (dedupe in complete);
                # chronic expiry WITHOUT completions walks the worker into
                # quarantine — a completed sample resets the streak.
                self.counters["speculative_dispatches"] += 1
                self._revoke_locked(lease)
                if rec is not None:
                    self._charge_failure_locked(rec)
                if self._tracer is not None and self._tracer.enabled:
                    self._tracer.instant(
                        "fleet.lease_expired", worker=lease.worker_id,
                        start=lease.start, speculative=True,
                    )
            else:
                self._revoke_locked(lease)
                if rec is not None and not rec.lost:
                    self._mark_lost_locked(rec)
        self._check_exhausted_locked()

    # ---------------------------------------------------------------- #
    # consumer-side introspection / persistence
    # ---------------------------------------------------------------- #

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed

    def close(self):
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def stats(self) -> dict:
        """Flat numeric snapshot for the `fleet/*` metric rows
        (docs/METRICS.md). Transport counters (rpc_retries, heartbeat
        misses, ...) are always present — zero under InProcessTransport,
        live values once a network transport registers its provider."""
        with self._cond:
            now = self._clock()
            live = [r for r in self._workers.values() if not r.lost]
            transport = {k: 0.0 for k in _TRANSPORT_COUNTERS}
            if self._transport_info is not None:
                info = self._transport_info()
                for k, v in (info.get("counters") or {}).items():
                    if k in transport:
                        transport[k] = float(v)
            return {
                "workers": float(len(live)),
                "workers_quarantined": float(sum(
                    1 for r in live if r.quarantined_until > now
                )),
                "leases_active": float(len(self._leases)),
                **{k: float(v) for k, v in self.counters.items()},
                **transport,
            }

    def snapshot(self) -> dict:
        """Structured membership + lease table for the status exporter's
        /statusz (telemetry/exporter.py) — the human-readable companion to
        the flat `stats()` gauges: who is in the fleet, who is quarantined
        or lost, and which leases are in flight against what deadline."""
        with self._cond:
            now = self._clock()
            per_worker: dict = {}
            if self._transport_info is not None:
                per_worker = self._transport_info().get("per_worker") or {}
            return {
                "transport": self._transport_name,
                "workers": [
                    {
                        "worker_id": r.worker_id,
                        "lost": r.lost,
                        "quarantined": r.quarantined_until > now,
                        "quarantined_for_s": max(
                            0.0, round(r.quarantined_until - now, 3)
                        ),
                        "consecutive_failures": r.consecutive_failures,
                        "quarantines": r.quarantines,
                        "samples": r.samples,
                        "ewma_s": round(r.ewma_s, 4),
                        "heartbeat_age_s": round(now - r.last_heartbeat, 3),
                        # per-worker transport state: connection phase, RTT,
                        # retries, last fencing epoch seen (rpc); in-process
                        # workers are trivially "connected"
                        "transport": per_worker.get(
                            r.worker_id, {"state": "connected"}
                        ),
                    }
                    for r in self._workers.values()
                ],
                "leases": [
                    {
                        "lease_id": l.lease_id,
                        "worker_id": l.worker_id,
                        "start": l.start,
                        "batches": len(l),
                        "age_s": round(now - l.issued_at, 3),
                        "deadline_in_s": round(l.deadline - now, 3),
                        "reassigned_from": l.reassigned_from,
                        "epoch": l.epoch,
                    }
                    for l in self._leases.values()
                ],
                "counters": dict(self.counters),
            }

    def journal(self) -> dict:
        """JSON-able coordinator state for trainer_state.json. Granted-but-
        unemitted indices are informational (resume re-draws them from the
        consumed-rollout cursor, exactly like the queue's pending list);
        the counters seed a rebuilt fleet so the fleet/* metric series
        stays continuous across restart/degrade/resume."""
        with self._cond:
            pending = sorted(
                set(range(self._next_emit, self._cursor)) - set(self._ready)
            )
            return {
                "cursor": self._cursor,
                "next_emit": self._next_emit,
                "pending": pending,
                "quarantined_workers": [
                    r.worker_id for r in self._workers.values()
                    if not r.lost and r.quarantined_until > self._clock()
                ],
                "counters": dict(self.counters),
            }

    def restore_counters(self, journal: dict):
        """Seed cumulative counters from a saved journal (fresh fleets —
        rebuilt after watchdog restart or checkpoint resume — must not zero
        the fleet/* series). Cursor/membership are NOT restored: a new
        fleet re-draws from the consumed-rollout cursor with fresh
        workers."""
        with self._cond:
            for k, v in (journal.get("counters") or {}).items():
                if k in self.counters:
                    self.counters[k] = int(v)


# --------------------------------------------------------------------- #
# transport seam + in-process worker
# --------------------------------------------------------------------- #


class FleetTransport:
    """What a rollout worker needs from the outside world. The in-process
    implementation below closes over host objects; a multi-host backend
    implements the same three calls over the network (dispatch on the
    remote pod's mesh, heartbeat/completions over RPC, weights via
    device-to-device broadcast from the store) without the coordinator or
    the worker loop changing."""

    def fetch_weights(self, worker_id: int, stop=None):
        """-> (version, param_tree) of the newest published policy."""
        raise NotImplementedError

    def poll_weights(self, worker_id: int, have_version: int, stop=None):
        """Non-blocking in-flight swap check (docs/ORCHESTRATOR.md
        §in-flight swaps): -> (version, tree|None), tree None when nothing
        newer than `have_version` is published. Unlike `fetch_weights`
        this NEVER waits and never fires the worker.fetch_weights fault —
        it runs inside the decode loop's host sync window, where a stall
        is generator idle time. Base implementation: swaps unsupported,
        always (have_version, None)."""
        return have_version, None

    def heartbeat(self, worker_id: int) -> None:
        raise NotImplementedError

    def dispatch(self, worker_id: int, index: int, queries, tree,
                 weight_refresh=None):
        """Run generation for rollout `index`; returns a DEVICE-READY
        payload (the transport owns the block_until_ready).
        `weight_refresh` (optional `() -> (version, tree|None)`) is the
        in-flight swap callback threaded down to the decode driver; the
        transport forwards it to the dispatch closure only when set, so
        4-arg dispatch_fn signatures keep working with swaps off."""
        raise NotImplementedError


class InProcessTransport(FleetTransport):
    """Thread-worker transport: direct calls into the trainer's dispatch
    closure and the shared weight store."""

    def __init__(self, store: VersionedWeightStore,
                 coordinator: FleetCoordinator,
                 dispatch_fn: Callable[[int, object, dict, int], dict],
                 faults=None, weight_timeout: Optional[float] = None):
        self._store = store
        self._coord = coordinator
        self._dispatch_fn = dispatch_fn
        self._faults = faults
        self._weight_timeout = weight_timeout

    def fetch_weights(self, worker_id: int, stop=None):
        if self._faults is not None:
            self._faults.fire("worker.fetch_weights", worker=worker_id)
        # wait_for_version: a worker that joins before publish-0 blocks here
        # instead of crash-looping latest()'s RuntimeError into quarantine
        return self._store.wait_for_version(
            0, timeout=self._weight_timeout, stop=stop
        )

    def poll_weights(self, worker_id: int, have_version: int, stop=None):
        # direct non-blocking store read; deliberately NOT the
        # worker.fetch_weights fault site (that models the per-lease
        # blocking fetch) — the in-flight path has its own swap.stale site
        # fired by make_swap_refresh at install time
        v = self._store.version
        if v < 0 or v <= have_version:
            return max(v, have_version), None
        return self._store.latest()

    def heartbeat(self, worker_id: int) -> None:
        self._coord.heartbeat(worker_id)

    def dispatch(self, worker_id: int, index: int, queries, tree,
                 weight_refresh=None):
        if weight_refresh is not None:
            payload = self._dispatch_fn(
                index, queries, tree, worker_id, weight_refresh
            )
        else:
            payload = self._dispatch_fn(index, queries, tree, worker_id)
        import jax  # lazy: keeps fleet.py importable jax-free for units

        jax.block_until_ready(payload)
        return payload


class RolloutWorker:
    """One in-process fleet worker thread."""

    def __init__(self, worker_id: int, coordinator: FleetCoordinator,
                 transport: FleetTransport, meter=None, faults=None,
                 tracer=None, lineage=None, latency=None,
                 inflight_swaps: bool = False):
        self.worker_id = worker_id
        self._coord = coordinator
        self._transport = transport
        self._meter = meter
        self._faults = faults
        self._tracer = tracer
        self._lineage = lineage
        # in-flight mid-sequence weight swaps (docs/ORCHESTRATOR.md
        # §in-flight swaps): each dispatch gets a refresh callback that
        # polls the transport for newer weights at the decode loop's host
        # sync points, seeded with the dispatch version so the first poll
        # is a no-op unless a publish landed after fetch_weights
        self._inflight_swaps = bool(inflight_swaps)
        # telemetry.LatencyHub: dispatch→device-ready per generation —
        # the fleet's generation-wall + TTFT-upper-bound sketches. All
        # workers share ONE hub: its histograms are mergeable, but
        # in-process threads can simply record centrally.
        self._latency = latency
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"fleet-worker-{worker_id}",
        )

    def start(self):
        self._thread.start()

    def stop(self):
        self._stop.set()

    def join(self, timeout: Optional[float] = None):
        self._thread.join(timeout=timeout)

    def alive(self) -> bool:
        # registered before start(): not-yet-started counts as alive
        return self._thread.ident is None or self._thread.is_alive()

    # ---------------------------------------------------------------- #

    def _run(self):
        lease: Optional[Lease] = None
        try:
            while not self._stop.is_set():
                lease = self._coord.acquire(self.worker_id, self._stop)
                if lease is None:
                    return  # stopped / closed / deregistered / lost
                self._run_lease(lease)
                lease = None
        except BaseException as e:
            # worker.crash lands here: the thread dies like a preempted
            # host would, after one in-band report so the fault matrix is
            # deterministic (a silent thread death is ALSO handled — the
            # liveness probe marks the worker lost at the next poll)
            self._coord.worker_failed(self.worker_id, lease, e, fatal=True)

    def _run_lease(self, lease: Lease):
        from nanorlhf_tpu.resilience.faults import InjectedFault

        for offset in range(len(lease)):
            index = lease.start + offset
            if self._stop.is_set() or self._coord.lease_revoked(lease):
                return
            if self._coord.index_done(index):
                continue  # a speculative peer already delivered this index
            self._transport.heartbeat(self.worker_id)
            try:
                if self._faults is not None:
                    self._faults.fire("worker.crash", worker=self.worker_id)
                    act = self._faults.fire(
                        "worker.hang", worker=self.worker_id
                    )
                    if act == "hang":
                        # stall holding the lease until its deadline revokes
                        # it (or shutdown) — the straggler/hang fault shape
                        while not (self._stop.is_set()
                                   or self._coord.lease_revoked(lease)):
                            time.sleep(0.01)
                        return
                    act = self._faults.fire(
                        "worker.slow", worker=self.worker_id
                    )
                    if act is not None and act.startswith("delay:"):
                        self._sleep_interruptible(
                            float(act.split(":", 1)[1]), lease
                        )
                version, tree = self._transport.fetch_weights(
                    self.worker_id, stop=self._stop
                )
                tr = self._tracer
                span = (
                    tr.span("rollout.generate", rollout_index=index,
                            policy_version=version, worker=self.worker_id,
                            lease=lease.lease_id)
                    if tr is not None and tr.enabled else _null_ctx()
                )
                # monotonic: [t0, t1] feeds the straggler-deadline latency
                # EWMA (via QueuedSample dispatch/ready stamps) and the
                # overlap meter — an NTP step across a wall-clock window
                # would corrupt both. Same clock as the consumer's busy
                # windows and the queue's transit stamps. (Cross-host
                # transports must measure latency on ONE host's clock —
                # these stamps are taken coordinator-side, so that holds.)
                refresh = None
                if self._inflight_swaps:
                    refresh = make_swap_refresh(
                        lambda have: self._transport.poll_weights(
                            self.worker_id, have, stop=self._stop
                        ),
                        have_version=version, faults=self._faults,
                        worker=self.worker_id,
                    )
                t0 = time.perf_counter()
                with span:
                    payload = self._transport.dispatch(
                        self.worker_id, index, lease.batches[offset], tree,
                        weight_refresh=refresh,
                    )
                t1 = time.perf_counter()
                if self._meter is not None:
                    self._meter.note_gen(t0, t1, track=self.worker_id)
                if self._latency is not None and self._latency.enabled:
                    # one pair per generation event: keeps the TTFT
                    # sketch's _count equal to the ledger's generation-
                    # event count (the monolithic sampler is one jit, so
                    # dispatch→ready is the TTFT upper bound here)
                    self._latency.record("latency/generation_s", t1 - t0)
                    self._latency.record("latency/ttft_s", t1 - t0)
                if self._lineage is not None and self._lineage.enabled:
                    self._lineage.generation(
                        index, policy_version=version,
                        worker_id=self.worker_id, lease_id=lease.lease_id,
                        gen_s=round(t1 - t0, 6), spec=spec_summary(payload),
                        segments=segments_summary(payload),
                        swap_wait_s=payload.get("swap_wait_s"),
                    )
                self._coord.complete(
                    self.worker_id, lease, index,
                    QueuedSample(index, version, payload, t0, t1),
                )
            except InjectedFault as e:
                if e.point == "worker.crash":
                    raise  # fatal: the outer handler reports + thread dies
                self._coord.worker_failed(self.worker_id, lease, e)
                return
            except Exception as e:
                # organic dispatch/weight failure: recoverable — charge the
                # quarantine budget, surrender the lease, take the next one
                self._coord.worker_failed(self.worker_id, lease, e)
                return

    def _sleep_interruptible(self, seconds: float, lease: Lease):
        deadline = time.monotonic() + seconds
        while time.monotonic() < deadline:
            if self._stop.is_set():
                return
            time.sleep(min(0.01, max(0.0, deadline - time.monotonic())))


def _null_ctx():
    import contextlib

    return contextlib.nullcontext()


# --------------------------------------------------------------------- #
# consumer-facing shell (RolloutOrchestrator-compatible surface)
# --------------------------------------------------------------------- #


class FleetOrchestrator:
    """N-worker drop-in for RolloutOrchestrator.

    `dispatch_fn(index, queries, params_tree, worker_id) -> payload`
    async-dispatches generation (the transport blocks until device-ready);
    `batch_fn()` draws the next prompt batch — called ONLY by the
    coordinator, under its lock, in strict index order, so the data cursor
    semantics (and the checkpoint/resume journal) are exactly the
    single-producer ones. `initial_params` becomes weight version 0.

    `transport` selects the worker↔coordinator seam: "inprocess" (direct
    calls, the default) or "rpc" (loopback FleetRpcServer + one RpcClient
    per worker — the same wire path a cross-host deployment uses, so the
    fault matrix and bit-parity tests cover the network code on CPU CI).
    `rpc` (an orchestrator.rpc.RpcConfig) carries address/timeout/retry
    knobs; None = loopback on an ephemeral port.

    `inflight_swaps=True` hands every dispatch an in-flight weight-swap
    refresh callback (weight_store.make_swap_refresh over the transport's
    non-blocking `poll_weights`): the decode driver installs mid-rollout
    publishes at its host sync points and the payload/ledger carry
    per-segment {policy_version, tok_range} provenance
    (docs/ORCHESTRATOR.md §in-flight swaps). With no mid-rollout publish
    the callback returns (version, None) every poll and the token stream
    is bit-identical to swaps off — test-pinned over both transports.
    """

    def __init__(
        self,
        dispatch_fn: Callable[[int, object, dict, int], dict],
        batch_fn: Callable[[], object],
        initial_params: dict,
        n_workers: int = 2,
        start_index: int = 0,
        max_staleness: int = 1,
        policy: str = "wait",
        meter=None,
        restore: Optional[dict] = None,
        heartbeat: float = 30.0,
        faults=None,
        tracer=None,
        fleet: Optional[FleetConfig] = None,
        lineage=None,
        transport: str = "inprocess",
        rpc=None,
        latency=None,
        inflight_swaps: bool = False,
    ):
        if n_workers < 1:
            raise ValueError(f"n_workers={n_workers} must be >= 1")
        from nanorlhf_tpu.orchestrator.orchestrator import OverlapMeter

        self.store = VersionedWeightStore()
        self.store.publish(initial_params)  # version 0
        self.queue = BoundedStalenessQueue(
            max_staleness, policy, start_index=start_index, lineage=lineage,
            latency=latency,
        )
        self.meter = meter if meter is not None else OverlapMeter()
        self.max_staleness = max_staleness
        self._heartbeat = heartbeat
        self._faults = faults
        self._tracer = tracer
        self._lineage = lineage
        self._latency = latency
        self._inflight_swaps = bool(inflight_swaps)
        self.coordinator = FleetCoordinator(
            queue=self.queue, batch_fn=batch_fn, start_index=start_index,
            config=fleet, faults=faults, tracer=tracer, meter=self.meter,
            lineage=lineage,
        )
        if restore:
            self.queue.restore_counters(restore)
            self.coordinator.restore_counters(restore.get("fleet", {}))
        if transport not in ("inprocess", "rpc"):
            raise ValueError(
                f"transport={transport!r}: 'inprocess' | 'rpc'"
            )
        self._dispatch_fn = dispatch_fn
        self._rpc_server = None
        self._rpc_clients: list = []
        self._rpc_cfg = None
        if transport == "rpc":
            from nanorlhf_tpu.orchestrator import rpc as _rpc

            self._rpc_mod = _rpc
            self._rpc_cfg = rpc if rpc is not None else _rpc.RpcConfig(
                poll_interval=self.coordinator.cfg.poll_interval
            )
            # the server registers itself as the coordinator's transport
            # stats provider (set_transport) at construction
            self._rpc_server = _rpc.FleetRpcServer(
                self.coordinator, self.store, config=self._rpc_cfg,
                faults=faults,
            )
            self.transport = None  # per-worker RpcTransport instead
        else:
            self.transport = InProcessTransport(
                self.store, self.coordinator, dispatch_fn, faults=faults
            )
        self._poll = min(heartbeat, self.coordinator.cfg.poll_interval)
        self._workers: list[RolloutWorker] = []
        self._next_worker_id = 0
        # register the WHOLE initial cohort before starting any thread: a
        # first worker fast enough to acquire + crash before the second is
        # registered would otherwise trip the all-workers-lost exhaustion
        # check against a 1-member fleet
        initial = [self._make_worker() for _ in range(n_workers)]
        for w in initial:
            w.start()

    # ---------------------------------------------------------------- #
    # elastic membership
    # ---------------------------------------------------------------- #

    def _make_worker(self) -> RolloutWorker:
        wid = self._next_worker_id
        self._next_worker_id += 1
        if self._rpc_server is not None:
            # worker side of the wire: its own client connection, a proxy
            # with the coordinator surface, and the 3-call transport —
            # the worker loop itself is identical to the in-process one
            client = self._rpc_mod.RpcClient(
                self._rpc_server.address, wid, config=self._rpc_cfg,
                faults=self._faults, latency=self._latency,
            )
            self._rpc_clients.append(client)
            coord = self._rpc_mod.RemoteCoordinator(
                client, poll_interval=self._rpc_cfg.poll_interval
            )
            transport = self._rpc_mod.RpcTransport(
                client, self._dispatch_fn
            )
        else:
            coord, transport = self.coordinator, self.transport
        w = RolloutWorker(
            wid, coord, transport, meter=self.meter,
            faults=self._faults, tracer=self._tracer, lineage=self._lineage,
            latency=self._latency, inflight_swaps=self._inflight_swaps,
        )
        # register BEFORE start: the worker's first acquire must find its
        # membership record (alive() treats not-yet-started as alive)
        self.coordinator.register_worker(wid, alive_fn=w.alive)
        self._workers.append(w)
        return w

    def add_worker(self) -> int:
        """Join a worker mid-run; returns its worker id."""
        w = self._make_worker()
        w.start()
        return w.worker_id

    def remove_worker(self, worker_id: int, drain: bool = False,
                      drain_timeout_s: float = 30.0) -> bool:
        """Leave mid-run (elastic scale-down).

        `drain=True` (what autoscaler scale-in uses): stop granting the
        worker new leases, wait for its in-flight leases to complete,
        THEN deregister — nothing is stranded and nothing needs the
        lease-expiry reassignment sweep. Falls through to the abrupt
        path if the drain times out (the reassignment machinery then
        recovers whatever was left, same as a crash).

        `drain=False` (default, kept for fault tests): immediate
        deregister — in-flight leases are revoked and reassigned.

        Returns True when the worker left cleanly drained (vacuously
        True for the abrupt path)."""
        drained = True
        if drain:
            if self.coordinator.drain_worker(worker_id):
                drained = self.coordinator.wait_drained(
                    worker_id, timeout=drain_timeout_s)
        self.coordinator.deregister_worker(worker_id)
        for w in self._workers:
            if w.worker_id == worker_id:
                w.stop()
        return drained

    # ---------------------------------------------------------------- #
    # consumer API (RolloutOrchestrator-compatible)
    # ---------------------------------------------------------------- #

    @property
    def version(self) -> int:
        return self.store.version

    def get(self) -> QueuedSample:
        """Next sample in index order. Short poll slices keep the liveness/
        deadline sweep running while the consumer waits; like the single
        producer there is NO hard deadline on a healthy slow generation
        (cold-cache compiles run minutes) — only actual fleet death raises
        (FleetExhausted via the queue, or every thread gone)."""
        while True:
            try:
                return self.queue.get(timeout=self._poll)
            except TimeoutError:
                self.coordinator.poll()
                if (not self.coordinator.exhausted
                        and not any(w.alive() for w in self._workers)):
                    raise ProducerFailed(
                        "every fleet worker thread died without reporting "
                        "an error through the queue"
                    ) from self.coordinator.last_error

    def producer_alive(self) -> bool:
        return any(w.alive() for w in self._workers)

    def consumed_without_update(self) -> None:
        self.queue.credit_skip()
        self.coordinator.kick()

    def publish(self, tree: dict) -> int:
        v = self.store.publish(tree)
        self.queue.advance_version(v)
        self.coordinator.kick()  # the staleness gate may have opened
        return v

    def stats(self) -> dict:
        return {
            "queue_depth": self.queue.depth(),
            "dropped": self.queue.dropped,
            "staleness_counts": dict(self.queue.staleness_counts),
            "consumer_wait_s": self.queue.consumer_wait_s,
            # fleet workers wait in coordinator.acquire, not the queue gate
            "producer_gate_wait_s": self.coordinator.gate_wait_s,
        }

    def fleet_stats(self) -> dict:
        """fleet/* metric rows (docs/METRICS.md)."""
        return self.coordinator.stats()

    def status_snapshot(self) -> dict:
        """/statusz seam (telemetry/exporter.py): queue counters + the
        fleet membership/lease table, JSON-able and safe from any thread."""
        return {
            "queue": {**self.stats(), "version": self.version},
            "fleet": self.coordinator.snapshot(),
        }

    def journal(self) -> dict:
        return {**self.queue.journal(), "fleet": self.coordinator.journal()}

    def close(self, join_timeout: float = 30.0) -> None:
        for w in self._workers:
            w.stop()
        self.coordinator.close()
        deadline = time.monotonic() + join_timeout
        for w in self._workers:
            w.join(timeout=max(0.1, deadline - time.monotonic()))
        for c in self._rpc_clients:
            c.close()
        if self._rpc_server is not None:
            self._rpc_server.close()
