"""RolloutOrchestrator — producer-thread rollout pipeline over the
version-tagged weight store and the bounded-staleness queue.

Generalizes the trainer's one-step `rollout_ahead` prefetch into a
configurable pipelined depth (PipelineRL / LlamaRL): a daemon producer
thread pulls prompt batches, grabs the LATEST published policy snapshot,
dispatches generation, blocks until the sample is device-ready, and
enqueues it version-tagged; the trainer consumes via `get()` and publishes
a new version after every optimizer update. With disaggregated rollout
devices the producer's generation executes on its own mesh WHILE the
consumer's scoring/update runs on the train mesh — and, unlike
rollout_ahead (whose prefetch lives inside one `train()` call), the
pipeline stays warm across `train(num_updates=1)` invocations.

Determinism contract: the producer is the ONLY consumer of the trainer's
data iterator, and generation PRNG keys come from the trainer's stateless
index-keyed stream (`fold_in(base, index)`), so the data and PRNG streams
are exactly the ones the synchronous trainer would see — the basis of the
checkpoint/resume journal (docs/ORCHESTRATOR.md).
"""

from __future__ import annotations

import contextlib
import threading

from nanorlhf_tpu.analysis.lockorder import make_lock
import time
from typing import Callable, Optional

import jax

from nanorlhf_tpu.orchestrator.sample_queue import (
    BoundedStalenessQueue,
    ProducerFailed,
    QueuedSample,
)
from nanorlhf_tpu.orchestrator.weight_store import VersionedWeightStore
from nanorlhf_tpu.telemetry.lineage import (
    segments_summary as _segments_summary,
    spec_summary as _spec_summary,
)


def _merge_intervals(ivs: list[tuple[float, float]]) -> list[tuple[float, float]]:
    out: list[tuple[float, float]] = []
    for t0, t1 in sorted(ivs):
        if out and t0 <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], t1))
        else:
            out.append((t0, t1))
    return out


def _sweep_overlap(gen, busy) -> float:
    """Σ |gen_i ∩ busy_j| over two merged, sorted interval lists."""
    overlap, j = 0.0, 0
    for g0, g1 in gen:
        while j < len(busy) and busy[j][1] <= g0:
            j += 1
        k = j
        while k < len(busy) and busy[k][0] < g1:
            overlap += min(g1, busy[k][1]) - max(g0, busy[k][0])
            k += 1
    return overlap


class OverlapMeter:
    """Rollout/train overlap accounting from measured wall-clock intervals.

    Producers record generation busy windows [dispatch, device-ready];
    the consumer records its own busy windows (everything between fetching
    a sample and asking for the next one — reward, scoring, update).
    `overlap_fraction()` = |union(gen) ∩ union(busy)| / |union(gen)|: the
    fraction of generation wall-clock that ran CONCURRENTLY with useful
    trainer work. 0 for the serial trainer (generation only runs while the
    consumer waits); → 1 when the pipeline fully hides generation.

    With N producers (the rollout fleet) the generation windows of
    different workers legitimately OVERLAP each other; the union in the
    numerator/denominator counts concurrently-generating wall-clock once,
    which is the honest "fraction of generation time hidden by training"
    reading. Producers tag their intervals with a per-producer `track`
    (fleet workers use their worker id); the default track 0 reproduces
    the single-producer behavior exactly.

    The metric is cumulative over the trainer's lifetime but the interval
    history is NOT: past `_COMPACT_AT` stored intervals the prefix below a
    watermark is folded into scalar accumulators (overlap seconds + gen
    seconds), so a long run pays O(_COMPACT_AT) per reading instead of an
    ever-growing sweep. The watermark is the minimum over every track (gen
    and busy) of that track's latest recorded end-time: each TRACK records
    chronologically non-overlapping windows (a worker's next dispatch
    starts after its previous sample is device-ready), so every FUTURE
    interval starts at or after its own track's last end ≥ the watermark —
    clipping both histories there makes the folded / retained
    decomposition exact, not an approximation. (Taking the min over the
    raw append order instead would be wrong with N producers: arrivals
    interleave, so the last-appended interval's end is not a lower bound
    on future starts.) A producer that leaves for good must be retired
    (`retire_gen_track`) or its stale watermark pins compaction forever.
    """

    _COMPACT_AT = 4096

    def __init__(self):
        self._lock = make_lock("orchestrator.meter")
        self._gen: list[tuple[float, float]] = []
        self._busy: list[tuple[float, float]] = []
        self._gen_ends: dict[int, float] = {}    # track -> latest end time
        self._busy_ends: dict[int, float] = {}
        self._overlap_acc = 0.0   # folded prefix: overlap seconds
        self._gen_acc = 0.0       # folded prefix: generation seconds

    def note_gen(self, t0: float, t1: float, track: int = 0) -> None:
        with self._lock:
            self._gen.append((t0, t1))
            self._gen_ends[track] = max(self._gen_ends.get(track, t1), t1)
            self._maybe_compact()

    def note_busy(self, t0: float, t1: float, track: int = 0) -> None:
        with self._lock:
            self._busy.append((t0, t1))
            self._busy_ends[track] = max(self._busy_ends.get(track, t1), t1)
            self._maybe_compact()

    def retire_gen_track(self, track: int) -> None:
        """A producer left the fleet for good: stop holding the compaction
        watermark down at its last recorded window."""
        with self._lock:
            self._gen_ends.pop(track, None)

    def _maybe_compact(self) -> None:
        # caller holds the lock
        if len(self._gen) + len(self._busy) < self._COMPACT_AT \
                or not self._gen or not self._busy:
            return
        if not self._gen_ends or not self._busy_ends:
            # every producing track on one side was retired while its
            # intervals are still retained (e.g. all fleet workers lost
            # before the degraded fallback records again): no watermark
            # exists, so skip — the next note_gen/note_busy re-adds a
            # track (whose windows start later in wall-clock) and
            # compaction resumes
            return
        cutoff = min(
            min(self._gen_ends.values()), min(self._busy_ends.values())
        )

        def clip(ivs):
            below, above = [], []
            for t0, t1 in ivs:
                if t1 <= cutoff:
                    below.append((t0, t1))
                elif t0 >= cutoff:
                    above.append((t0, t1))
                else:  # straddler: split exactly at the watermark
                    below.append((t0, cutoff))
                    above.append((cutoff, t1))
            return below, above

        gen_lo, gen_hi = clip(_merge_intervals(self._gen))
        busy_lo, busy_hi = clip(_merge_intervals(self._busy))
        self._overlap_acc += _sweep_overlap(gen_lo, busy_lo)
        self._gen_acc += sum(t1 - t0 for t0, t1 in gen_lo)
        self._gen, self._busy = gen_hi, busy_hi

    def overlap_fraction(self) -> float:
        with self._lock:
            gen = _merge_intervals(self._gen)
            busy = _merge_intervals(self._busy)
            overlap = self._overlap_acc + _sweep_overlap(gen, busy)
            total = self._gen_acc + sum(t1 - t0 for t0, t1 in gen)
        if total <= 0.0:
            return 0.0
        return min(1.0, max(0.0, overlap / total))


def note_ready_async(meter: OverlapMeter, payload, t0: float,
                     tracer=None, span_args: Optional[dict] = None) -> None:
    """Record [t0, device-ready] into `meter` without blocking the caller —
    a daemon waiter thread block_until_ready's the (async-dispatched)
    payload. Lets the synchronous RolloutStream report honest generation
    busy windows for the same overlap metric the orchestrator emits.

    With a telemetry.SpanTracer the same window is also recorded as a
    `rollout.generate` ASYNC trace event on the "rollout" track (explicit
    start/duration; async because rollout_ahead's prefetch makes
    consecutive windows overlap, which complete "X" spans on one track
    cannot express) — so serial / rollout_ahead runs show their generation
    lane in trace.json just like orchestrated runs do."""
    tp0 = tracer.now_us() if tracer is not None and tracer.enabled else None

    def _wait():
        try:
            jax.block_until_ready(payload)
        except Exception:
            return  # the consumer surfaces dispatch errors; meter stays silent
        meter.note_gen(t0, time.perf_counter())
        if tp0 is not None:
            args = span_args or {}
            tracer.add_async(
                "rollout.generate", tp0, tracer.now_us() - tp0,
                aid=args.get("rollout_index", id(payload)), track="rollout",
                **args,
            )

    threading.Thread(target=_wait, daemon=True,
                     name="rollout-ready-watch").start()


class RolloutOrchestrator:
    """Producer thread + version store + bounded-staleness queue.

    `dispatch_fn(index, params_tree) -> payload` pulls the next prompt
    batch, folds the generation key for `index`, and async-dispatches
    generation from `params_tree` (a published snapshot — never the live
    donated training tree). `initial_params` becomes version 0.
    """

    def __init__(
        self,
        dispatch_fn: Callable[[int, dict], dict],
        initial_params: dict,
        start_index: int = 0,
        max_staleness: int = 1,
        policy: str = "wait",
        meter: Optional[OverlapMeter] = None,
        restore: Optional[dict] = None,
        heartbeat: float = 30.0,
        faults=None,
        tracer=None,
        lineage=None,
        latency=None,
    ):
        self.store = VersionedWeightStore()
        self.store.publish(initial_params)  # version 0
        self.queue = BoundedStalenessQueue(
            max_staleness, policy, start_index=start_index, lineage=lineage,
            latency=latency,
        )
        if restore:
            self.queue.restore_counters(restore)
        self.meter = meter if meter is not None else OverlapMeter()
        self.max_staleness = max_staleness
        self._dispatch_fn = dispatch_fn
        self._next_index = start_index
        self._heartbeat = heartbeat
        self._faults = faults  # resilience.FaultInjector ("rollout.produce")
        # telemetry.SpanTracer: generation spans land on the producer
        # thread's own track — the trainer-vs-producer overlap picture
        self._tracer = tracer
        # telemetry.LineageLedger: per-index lease + generation provenance
        # (the single producer is "worker 0" with an implicit lease)
        self._lineage = lineage
        # telemetry.LatencyHub: generation-wall + TTFT histograms. The
        # monolithic sampler is one jit (prefill + while_loop), so the
        # first token is not separately observable without splitting the
        # compiled graph; dispatch→device-ready is recorded as the TTFT
        # UPPER BOUND (exact per-request TTFT comes from the paged
        # scheduler's admission stamps — docs/OBSERVABILITY.md §7).
        self._latency = latency
        self.producer_error: Optional[BaseException] = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._produce, daemon=True, name="rollout-producer"
        )
        self._thread.start()

    # ---------------------------------------------------------------- #
    # producer loop
    # ---------------------------------------------------------------- #

    def _produce(self):
        try:
            while not self._stop.is_set():
                idx = self._next_index
                if not self.queue.wait_to_produce(idx, self._stop):
                    break
                if self._faults is not None:
                    # resilience injection point — BEFORE the dispatch touches
                    # the data iterator, so a supervised restart redraws from
                    # an unburned cursor (docs/RESILIENCE.md)
                    self._faults.fire("rollout.produce")
                version, tree = self.store.latest()
                lin = self._lineage
                if lin is not None and lin.enabled:
                    # the single producer IS the lease grant: the dispatch
                    # below burns the data cursor + PRNG stream for `idx`
                    lin.lease(idx, worker_id=0, cursor=idx, length=1)
                tr = self._tracer
                span = (
                    # the producer is one long-lived thread, so the span
                    # lands on its own trace.json track — the generation
                    # lane of the producer/trainer overlap picture
                    tr.span("rollout.generate", rollout_index=idx,
                            policy_version=version)
                    if tr is not None and tr.enabled
                    else contextlib.nullcontext()
                )
                # monotonic: gen windows must share the consumer's busy-
                # window clock (perf_counter) or the overlap meter's
                # interval intersection silently goes to zero; wall clock
                # would also expose gen_s to NTP steps
                t0 = time.perf_counter()
                with span:
                    payload = self._dispatch_fn(idx, tree)
                    # block HERE (producer thread): the consumer receives
                    # device-ready samples, and [t0, t1] is the true
                    # generation busy window for the overlap meter
                    jax.block_until_ready(payload)
                t1 = time.perf_counter()
                self.meter.note_gen(t0, t1)
                if self._latency is not None and self._latency.enabled:
                    # one observation per generation event, so the TTFT
                    # sketch's _count stays joinable against the lineage
                    # ledger's generation-event count
                    self._latency.record("latency/generation_s", t1 - t0)
                    self._latency.record("latency/ttft_s", t1 - t0)
                if lin is not None and lin.enabled:
                    lin.generation(
                        idx, policy_version=version, worker_id=0,
                        gen_s=round(t1 - t0, 6),
                        spec=_spec_summary(payload),
                        segments=_segments_summary(payload),
                        swap_wait_s=payload.get("swap_wait_s"),
                    )
                self.queue.put(QueuedSample(idx, version, payload, t0, t1))
                if tr is not None and tr.enabled:
                    tr.counter("orchestrator/queue_depth", self.queue.depth())
                self._next_index += 1
        except BaseException as e:  # surfaces in the consumer's get()
            self.producer_error = e
            self.queue.fail(e)

    # ---------------------------------------------------------------- #
    # consumer API (the trainer)
    # ---------------------------------------------------------------- #

    @property
    def version(self) -> int:
        return self.store.version

    def get(self) -> QueuedSample:
        """Next sample — waits as long as the producer is making progress.

        No hard deadline: a cold-cache first generation can legitimately
        compile for many minutes (the bench's 1.5B config budgets whole
        attempts at 2100 s), so the wait only aborts when the producer
        thread is actually DEAD without having reported an error through
        `queue.fail()` (which covers every exception path in `_produce`).
        The heartbeat interval just bounds how often liveness is checked.
        A dead producer raises ProducerFailed (never a silent spin): the
        queue surfaces the stored terminal exception when one was reported,
        and a thread that died without reporting (e.g. killed at interpreter
        teardown before its except clause ran) raises it with whatever
        `producer_error` holds."""
        while True:
            try:
                return self.queue.get(timeout=self._heartbeat)
            except TimeoutError:
                if not self._thread.is_alive():
                    raise ProducerFailed(
                        "rollout producer thread died without reporting an "
                        "error through the queue"
                    ) from self.producer_error

    def producer_alive(self) -> bool:
        return self._thread.is_alive()

    def consumed_without_update(self) -> None:
        """A fetched sample was discarded without an optimizer update (a
        sentinel-quarantined batch): credit the producer gate so the
        pipeline doesn't deadlock waiting for a publish that never comes."""
        self.queue.credit_skip()

    def publish(self, tree: dict) -> int:
        """Publish a post-update policy snapshot; wakes the producer gate."""
        v = self.store.publish(tree)
        self.queue.advance_version(v)
        return v

    def stats(self) -> dict:
        # overlap lives on the meter (trainer reads meter.overlap_fraction()
        # directly) — recomputing the sweep here per update would be waste
        return {
            "queue_depth": self.queue.depth(),
            "dropped": self.queue.dropped,
            "staleness_counts": dict(self.queue.staleness_counts),
            # who-waits-on-whom (sample_queue.py): trainer starved vs
            # producer gated — the two numbers that say which side of the
            # pipeline is the bottleneck (docs/OBSERVABILITY.md)
            "consumer_wait_s": self.queue.consumer_wait_s,
            "producer_gate_wait_s": self.queue.producer_gate_wait_s,
        }

    def status_snapshot(self) -> dict:
        """/statusz seam (telemetry/exporter.py): queue counters + policy
        version, JSON-able and safe from any thread (single-producer
        pipelines have no fleet table)."""
        return {"queue": {**self.stats(), "version": self.version}}

    def journal(self) -> dict:
        """Checkpoint payload (trainer_state.json "orchestrator" key)."""
        return self.queue.journal()

    def close(self, join_timeout: float = 30.0) -> None:
        self._stop.set()
        self.queue.advance_version(self.queue.version)  # wake any waiter
        self._thread.join(timeout=join_timeout)
