"""Network FleetTransport: length-prefixed binary RPC over loopback/LAN
(docs/FLEET.md §multi-host).

PR 6 built the whole fleet control plane behind the 3-call FleetTransport
seam but shipped only `InProcessTransport`; this module fills in the
network half with nothing but the stdlib (socket/struct/threading — the
exporter's no-dependency discipline):

- **framing** — every message is one frame: a 13-byte header
  (magic ``nRPC``, kind byte, payload length, CRC32) followed by the
  payload. A torn/half-written frame is detected by length+checksum and
  surfaces as `TornFrame`, a RECOVERABLE transport error: the connection
  drops, the caller retries with jittered backoff
  (`resilience/retry.py`), and persistent failure charges the worker's
  `fleet_failure_budget` — neither side crashes.
- **codec** — a small tagged binary encoding for the JSON-ish + ndarray
  payloads that cross the wire (leases, completions, param trees). No
  pickle: the decoder can only produce data, never code. Arrays travel
  as dtype/shape + raw C-order buffers and round-trip bit-identically.
- **server** (`FleetRpcServer`) — coordinator-side, thread-per-connection.
  Wraps the real `FleetCoordinator` + `VersionedWeightStore` and speaks
  the op set: hello / acquire / complete / heartbeat / fetch_weights /
  worker_failed / lease_revoked / index_done. It is also the transport
  stats provider behind `FleetCoordinator.snapshot()` — the /statusz
  fleet table grows per-worker connection state, RTT, retries, epochs.
- **client** (`RpcClient` + `RemoteCoordinator` + `RpcTransport`) —
  worker-side. Every call gets a per-attempt socket timeout and
  `retry_with_backoff`; a dead connection reconnects and re-handshakes
  (worker id, last lease epoch, last weight version) before the retry
  goes out. `RemoteCoordinator` mirrors the coordinator surface the
  worker loop uses (acquire/complete/worker_failed/...), so
  `RolloutWorker` runs unchanged over the network.

**Fencing.** Leases carry a monotonically increasing *epoch* (fencing
token, stamped by the coordinator at grant time). A partitioned worker
whose lease expired and was re-dispatched can still deliver its late
completion after the link heals — the coordinator compares the
completion's epoch against the highest epoch granted for that index and
rejects stale ones, emitting the existing `fleet_late_duplicate` lineage
drop with ``{"fenced": true, "epoch": ...}``. First-completion-wins
dedup (PR 6) handles races between live workers; the epoch handles the
split-brain case dedup cannot: a revoked holder racing its replacement.

**Weight streaming.** `fetch_weights` streams the versioned store's
param tree with zero disk writes: one header frame (version tag, tree
structure with leaf placeholders, per-leaf dtype/shape/nbytes), then the
leaf buffers as chunked raw frames tagged ``(leaf, offset)`` — chunk
writes are idempotent, so a net.duplicate'd frame is absorbed by
construction. The client caches the last tree by version and sends
``have_version`` so an unchanged policy costs one tiny round trip.

**Fault injection.** The `net.{drop,delay,partition,duplicate,tear}`
sites (resilience/faults.py) fire inside `send_frame` on both the client
request path and the server response path; `net.partition` is client
link state (every call fails fast until the window passes). All
deterministic under the existing `worker=I`/`at=N`/`every=K` grammar.

Loopback is the tested deployment (CPU CI: workers in threads, one
process); the same wire format runs cross-host — see docs/FLEET.md for
the deployment sketch and the native-endianness caveat on arrays.
"""

from __future__ import annotations

import dataclasses
import socket
import struct
import threading

from nanorlhf_tpu.analysis.lockorder import make_lock, make_rlock
import time
import zlib
from typing import Callable, Optional

import numpy as np

from nanorlhf_tpu.orchestrator.fleet import FleetTransport, Lease
from nanorlhf_tpu.orchestrator.sample_queue import QueuedSample
from nanorlhf_tpu.resilience.retry import retry_with_backoff

_MAGIC = b"nRPC"
_HEADER = struct.Struct("!4sBII")  # magic, kind, payload length, crc32
_MAX_FRAME = 1 << 31
KIND_OBJ = 1    # payload is a codec-encoded object (request/response)
KIND_CHUNK = 2  # payload is !II (leaf, offset) + raw weight bytes
_NET_DEAD = (OSError, EOFError)


class TransportError(RuntimeError):
    """Recoverable transport-level failure (reset, timeout, torn frame,
    injected net fault). The client retries with backoff; retries that
    exhaust surface to the worker loop as an ordinary recoverable failure
    charging the fleet failure budget."""


class TornFrame(TransportError):
    """A frame failed the length/checksum check (half-written frame, torn
    connection, corrupted payload). Both sides treat it as recoverable:
    drop the connection, reconnect, retry."""


class ConnectionClosed(TransportError):
    """Clean EOF at a frame boundary — the peer hung up between frames."""


class RemoteCallError(RuntimeError):
    """The server executed the request and the HANDLER raised — an
    application error, not a transport error; never retried blindly."""


# --------------------------------------------------------------------- #
# framing
# --------------------------------------------------------------------- #


def _recv_exact(sock: socket.socket, n: int, *, at_boundary: bool = False
                ) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            if at_boundary and not buf:
                raise ConnectionClosed("peer closed the connection")
            raise TornFrame(
                f"connection closed mid-frame ({len(buf)}/{n} bytes)"
            )
        buf += chunk
    return bytes(buf)


def recv_frame(sock: socket.socket) -> tuple[int, bytes]:
    """Read one frame -> (kind, payload). Raises ConnectionClosed on clean
    EOF between frames, TornFrame on a truncated/corrupt frame."""
    hdr = _recv_exact(sock, _HEADER.size, at_boundary=True)
    magic, kind, length, crc = _HEADER.unpack(hdr)
    if magic != _MAGIC:
        raise TornFrame(f"bad frame magic {magic!r}")
    if length > _MAX_FRAME:
        raise TornFrame(f"oversized frame ({length} bytes)")
    payload = _recv_exact(sock, length)
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise TornFrame("frame checksum mismatch")
    return kind, payload


def send_frame(sock: socket.socket, payload: bytes, kind: int = KIND_OBJ,
               faults=None, worker: Optional[int] = None) -> int:
    """Write one frame; returns bytes put on the wire. The net.* fault
    sites live here — one `fire()` sweep per frame, on whichever side is
    sending, so both directions are coverable (net.partition is handled
    by the client's link state, not per-frame)."""
    frame = _HEADER.pack(
        _MAGIC, kind, len(payload), zlib.crc32(payload) & 0xFFFFFFFF
    ) + payload
    if faults is not None and faults.armed:
        act = faults.fire("net.delay", worker=worker)
        if act is not None and act.startswith("delay:"):
            time.sleep(float(act.split(":", 1)[1]))
        if faults.fire("net.drop", worker=worker) is not None:
            _hard_close(sock)
            raise TransportError("injected net.drop: frame lost")
        if faults.fire("net.tear", worker=worker) is not None:
            # half-write the payload then kill the connection: the peer
            # reads a full header promising more bytes than arrive
            try:
                sock.sendall(frame[: _HEADER.size + max(0, len(payload) // 2)])
            except _NET_DEAD:
                pass
            _hard_close(sock)
            raise TransportError("injected net.tear: frame truncated")
        if faults.fire("net.duplicate", worker=worker) is not None:
            sock.sendall(frame)
            sock.sendall(frame)
            return 2 * len(frame)
    sock.sendall(frame)
    return len(frame)


def _hard_close(sock: socket.socket) -> None:
    try:
        sock.close()
    except OSError:
        pass


# --------------------------------------------------------------------- #
# codec: tagged binary encoding (no pickle — data in, data out)
# --------------------------------------------------------------------- #

_U32 = struct.Struct("!I")
_I64 = struct.Struct("!q")
_F64 = struct.Struct("!d")


def dumps(obj) -> bytes:
    out = bytearray()
    _enc(obj, out)
    return bytes(out)


def loads(buf: bytes):
    obj, off = _dec(buf, 0)
    if off != len(buf):
        raise TornFrame(f"trailing garbage after object ({len(buf) - off}B)")
    return obj


def _enc(obj, out: bytearray) -> None:
    if isinstance(obj, np.generic):  # numpy scalar -> python scalar
        obj = obj.item()
    if obj is None:
        out += b"N"
    elif obj is True:
        out += b"T"
    elif obj is False:
        out += b"F"
    elif isinstance(obj, int):
        if -(2 ** 63) <= obj < 2 ** 63:
            out += b"i"
            out += _I64.pack(obj)
        else:
            b = obj.to_bytes((obj.bit_length() + 8) // 8, "big", signed=True)
            out += b"I" + _U32.pack(len(b)) + b
    elif isinstance(obj, float):
        out += b"d"
        out += _F64.pack(obj)
    elif isinstance(obj, str):
        b = obj.encode("utf-8")
        out += b"s" + _U32.pack(len(b)) + b
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        b = bytes(obj)
        out += b"b" + _U32.pack(len(b)) + b
    elif isinstance(obj, list):
        out += b"l" + _U32.pack(len(obj))
        for v in obj:
            _enc(v, out)
    elif isinstance(obj, tuple):
        out += b"t" + _U32.pack(len(obj))
        for v in obj:
            _enc(v, out)
    elif isinstance(obj, dict):
        out += b"m" + _U32.pack(len(obj))
        for k, v in obj.items():
            _enc(k, out)
            _enc(v, out)
    elif _is_arraylike(obj):
        a = np.ascontiguousarray(np.asarray(obj))
        # dtype by NAME (native endianness — loopback/LAN of like hosts;
        # covers extension dtypes like bfloat16 once their package is
        # imported, which importing jax does)
        ds = a.dtype.name.encode("ascii")
        out += b"a" + struct.pack("!B", len(ds)) + ds
        out += struct.pack("!B", a.ndim)
        out += struct.pack(f"!{a.ndim}q", *a.shape)
        out += struct.pack("!Q", a.nbytes) + a.tobytes()
    else:
        raise TypeError(f"rpc codec cannot encode {type(obj).__name__}")


def _is_arraylike(obj) -> bool:
    # ndarray, jax.Array, anything array-protocol'd that isn't a builtin
    return isinstance(obj, np.ndarray) or hasattr(obj, "__array__")


def _dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # extension dtype (bfloat16, float8_*): the names resolve only once
        # ml_dtypes has registered them — which importing jax does, but a
        # jax-free decoder process may not have yet
        import ml_dtypes  # noqa: F401

        return np.dtype(name)


def _dec(buf: bytes, off: int):
    tag = buf[off:off + 1]
    off += 1
    if tag == b"N":
        return None, off
    if tag == b"T":
        return True, off
    if tag == b"F":
        return False, off
    if tag == b"i":
        return _I64.unpack_from(buf, off)[0], off + 8
    if tag == b"I":
        n = _U32.unpack_from(buf, off)[0]
        off += 4
        return int.from_bytes(buf[off:off + n], "big", signed=True), off + n
    if tag == b"d":
        return _F64.unpack_from(buf, off)[0], off + 8
    if tag == b"s":
        n = _U32.unpack_from(buf, off)[0]
        off += 4
        return buf[off:off + n].decode("utf-8"), off + n
    if tag == b"b":
        n = _U32.unpack_from(buf, off)[0]
        off += 4
        return buf[off:off + n], off + n
    if tag in (b"l", b"t"):
        n = _U32.unpack_from(buf, off)[0]
        off += 4
        items = []
        for _ in range(n):
            v, off = _dec(buf, off)
            items.append(v)
        return (items if tag == b"l" else tuple(items)), off
    if tag == b"m":
        n = _U32.unpack_from(buf, off)[0]
        off += 4
        d = {}
        for _ in range(n):
            k, off = _dec(buf, off)
            v, off = _dec(buf, off)
            d[k] = v
        return d, off
    if tag == b"a":
        dlen = buf[off]
        off += 1
        dtype = _dtype(buf[off:off + dlen].decode("ascii"))
        off += dlen
        ndim = buf[off]
        off += 1
        shape = struct.unpack_from(f"!{ndim}q", buf, off)
        off += 8 * ndim
        nbytes = struct.unpack_from("!Q", buf, off)[0]
        off += 8
        a = np.frombuffer(buf, dtype=dtype, count=int(np.prod(shape, dtype=np.int64)) if ndim else 1,
                          offset=off).reshape(shape)
        return a.copy(), off + nbytes  # copy: writable, detached from buf
    raise TornFrame(f"unknown codec tag {tag!r} at offset {off - 1}")


# --------------------------------------------------------------------- #
# lease / tree (de)serialization
# --------------------------------------------------------------------- #


def encode_lease(lease: Lease) -> dict:
    return {
        "lease_id": lease.lease_id,
        "worker_id": lease.worker_id,
        "start": lease.start,
        "epoch": lease.epoch,
        "issued_at": lease.issued_at,
        "deadline": lease.deadline,
        "reassigned_from": lease.reassigned_from,
        "batches": list(lease.batches),
    }


def decode_lease(d: dict) -> Lease:
    return Lease(
        lease_id=d["lease_id"], worker_id=d["worker_id"], start=d["start"],
        batches=list(d["batches"]), issued_at=d["issued_at"],
        deadline=d["deadline"], reassigned_from=d.get("reassigned_from"),
        epoch=d.get("epoch", 0),
    )


_LEAF = "__nrpc_leaf__"


def split_leaves(tree):
    """(structure, leaves): the tree with every array leaf replaced by a
    (_LEAF, i) placeholder, plus the host arrays in placeholder order."""
    leaves: list = []

    def rec(x):
        if isinstance(x, dict):
            return {k: rec(v) for k, v in x.items()}
        if isinstance(x, list):
            return [rec(v) for v in x]
        if isinstance(x, tuple):
            return tuple(rec(v) for v in x)
        if _is_arraylike(x) and not isinstance(x, (str, bytes)):
            leaves.append(np.ascontiguousarray(np.asarray(x)))
            return (_LEAF, len(leaves) - 1)
        return x

    return rec(tree), leaves


def join_leaves(structure, leaves):
    def rec(x):
        if isinstance(x, dict):
            return {k: rec(v) for k, v in x.items()}
        if isinstance(x, list):
            return [rec(v) for v in x]
        if isinstance(x, tuple):
            if len(x) == 2 and x[0] == _LEAF:
                return leaves[x[1]]
            return tuple(rec(v) for v in x)
        return x

    return rec(structure)


# --------------------------------------------------------------------- #
# config
# --------------------------------------------------------------------- #


@dataclasses.dataclass
class RpcConfig:
    """Transport knobs (mirrored by RLConfig.fleet_rpc_*)."""

    host: str = "127.0.0.1"
    port: int = 0                 # 0 = ephemeral (the test/CI default)
    call_timeout: float = 10.0    # per-attempt socket timeout, seconds
    attempts: int = 4             # retry_with_backoff attempts per call
    backoff_base: float = 0.05
    backoff_max: float = 1.0
    poll_interval: float = 0.05   # client acquire-poll cadence
    chunk_bytes: int = 1 << 18    # weight-stream chunk size
    weight_timeout: float = 600.0  # server-side wait for a first publish
    rtt_alpha: float = 0.3


# --------------------------------------------------------------------- #
# coordinator-side server
# --------------------------------------------------------------------- #


class FleetRpcServer:
    """Thread-per-connection RPC server wrapping the live FleetCoordinator
    and VersionedWeightStore. Binds at construction (ephemeral port by
    default — `address` is the (host, port) workers dial) and registers
    itself as the coordinator's transport stats provider, which is how the
    /statusz fleet table grows per-worker connection state."""

    def __init__(self, coordinator, store, config: Optional[RpcConfig] = None,
                 faults=None):
        self.cfg = config or RpcConfig()
        self._coord = coordinator
        self._store = store
        self._faults = faults
        self._lock = make_lock("rpc.server")
        self._closed = threading.Event()
        self._peers: dict[int, dict] = {}  # worker_id -> transport record
        self._bytes_tx = 0
        self._bytes_rx = 0
        self._errors = 0  # torn frames / undecodable payloads / send faults
        self._sock = socket.create_server((self.cfg.host, self.cfg.port))
        self._sock.settimeout(0.2)
        self.address: tuple[str, int] = self._sock.getsockname()[:2]
        coordinator.set_transport("rpc", self.transport_info)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="fleet-rpc-accept"
        )
        self._accept_thread.start()

    # ------------------------------------------------------------ #

    def close(self) -> None:
        self._closed.set()
        _hard_close(self._sock)
        self._accept_thread.join(timeout=5.0)

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            conn.settimeout(0.5)  # short recv slices: poll closed between
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True,
                name="fleet-rpc-conn",
            ).start()

    def _serve(self, conn: socket.socket) -> None:
        wid: Optional[int] = None
        try:
            while not self._closed.is_set():
                try:
                    kind, payload = recv_frame(conn)
                except socket.timeout:
                    continue
                except ConnectionClosed:
                    break
                except TornFrame:
                    self._note_error(wid)
                    break
                except _NET_DEAD:
                    break
                self._note_rx(wid, _HEADER.size + len(payload))
                if kind != KIND_OBJ:
                    continue  # stray duplicated chunk frame: ignore
                try:
                    req = loads(payload)
                    assert isinstance(req, dict)
                except Exception:
                    self._note_error(wid)
                    break
                if req.get("worker_id") is not None:
                    wid = int(req["worker_id"])
                try:
                    self._handle(conn, req, wid)
                except TransportError:
                    break  # injected send fault: connection is gone
                except _NET_DEAD:
                    break
        finally:
            _hard_close(conn)
            if wid is not None:
                with self._lock:
                    peer = self._peers.get(wid)
                    if peer is not None and peer["state"] == "connected":
                        peer["state"] = "reconnecting"

    # ------------------------------------------------------------ #

    def _handle(self, conn, req: dict, wid: Optional[int]) -> None:
        op = req.get("op")
        seq = req.get("seq", 0)
        try:
            if op == "fetch_weights":
                self._handle_fetch_weights(conn, req, wid)
                return
            resp = self._dispatch_op(op, req, wid)
        except (TransportError,) + _NET_DEAD:
            raise
        except Exception as e:  # application error -> error response
            resp = {"error": f"{type(e).__name__}: {e}"}
        resp["seq"] = seq
        self._send_obj(conn, resp, wid)

    def _dispatch_op(self, op, req: dict, wid: Optional[int]) -> dict:
        coord = self._coord
        if op == "hello":
            with self._lock:
                peer = self._peers.setdefault(wid, _new_peer())
                peer["hellos"] += 1
                peer["state"] = "connected"
                peer["last_epoch"] = int(req.get("last_epoch", 0))
                peer["last_weight_version"] = int(
                    req.get("last_weight_version", -1)
                )
                _merge_client_stats(peer, req.get("stats"))
            return {"ok": True, "version": self._store.version,
                    "epoch": coord.current_epoch}
        if op == "heartbeat":
            coord.heartbeat(wid)
            with self._lock:
                peer = self._peers.setdefault(wid, _new_peer())
                _merge_client_stats(peer, req.get("stats"))
            return {"ok": True}
        if op == "acquire":
            lease, stopped = coord.acquire_nowait(wid)
            return {
                "lease": encode_lease(lease) if lease is not None else None,
                "stop": stopped,
            }
        if op == "complete":
            sample = QueuedSample(
                index=int(req["index"]), version=int(req["version"]),
                payload=req["payload"],
                dispatch_time=float(req["dispatch_time"]),
                ready_time=float(req["ready_time"]),
            )
            lease = coord.lease_by_id(int(req["lease_id"]))
            if lease is None:
                # revoked + pruned already: a stub carries the id/epoch the
                # fencing check and drop attribution need
                lease = Lease(
                    lease_id=int(req["lease_id"]), worker_id=wid,
                    start=sample.index, batches=[None], issued_at=0.0,
                    deadline=0.0, epoch=int(req.get("epoch", 0)),
                )
            accepted = coord.complete(wid, lease, sample.index, sample)
            with self._lock:
                peer = self._peers.setdefault(wid, _new_peer())
                peer["last_epoch"] = max(
                    peer["last_epoch"], int(req.get("epoch", 0))
                )
            return {"accepted": accepted}
        if op == "worker_failed":
            lease = None
            if req.get("lease_id") is not None:
                lease = coord.lease_by_id(int(req["lease_id"]))
            coord.worker_failed(
                wid, lease,
                RemoteCallError(str(req.get("message", "remote failure"))),
                fatal=bool(req.get("fatal", False)),
            )
            return {"ok": True}
        if op == "lease_revoked":
            return {"revoked": not coord.lease_active(int(req["lease_id"]))}
        if op == "index_done":
            return {"done": coord.index_done(int(req["index"]))}
        raise ValueError(f"unknown rpc op {op!r}")

    def _handle_fetch_weights(self, conn, req: dict, wid) -> None:
        seq = req.get("seq", 0)
        have = int(req.get("have_version", -1))
        if have >= 0 and self._store.version == have:
            self._send_obj(conn, {"seq": seq, "unchanged": True,
                                  "version": have}, wid)
            return
        try:
            version, tree = self._store.wait_for_version(
                0, timeout=self.cfg.weight_timeout
            )
        except TimeoutError as e:
            self._send_obj(conn, {"seq": seq,
                                  "error": f"TimeoutError: {e}"}, wid)
            return
        structure, leaves = split_leaves(tree)
        header = {
            "seq": seq, "version": version, "structure": structure,
            "leaves": [
                {"dtype": a.dtype.name, "shape": list(a.shape),
                 "nbytes": a.nbytes}
                for a in leaves
            ],
        }
        self._send_obj(conn, header, wid)
        # leaf buffers as chunked raw frames tagged (leaf, offset): chunk
        # placement is idempotent, so a net.duplicate'd frame is harmless
        chunk = self.cfg.chunk_bytes
        for i, a in enumerate(leaves):
            raw = a.tobytes()
            for off in range(0, max(1, len(raw)), chunk):
                body = struct.pack("!II", i, off) + raw[off:off + chunk]
                n = send_frame(conn, body, kind=KIND_CHUNK,
                               faults=self._faults, worker=wid)
                self._note_tx(wid, n)

    # ------------------------------------------------------------ #

    def _send_obj(self, conn, obj: dict, wid) -> None:
        n = send_frame(conn, dumps(obj), kind=KIND_OBJ,
                       faults=self._faults, worker=wid)
        self._note_tx(wid, n)

    def _note_tx(self, wid, n: int) -> None:
        with self._lock:
            self._bytes_tx += n
            if wid is not None:
                self._peers.setdefault(wid, _new_peer())["bytes_tx"] += n

    def _note_rx(self, wid, n: int) -> None:
        with self._lock:
            self._bytes_rx += n
            if wid is not None:
                self._peers.setdefault(wid, _new_peer())["bytes_rx"] += n

    def _note_error(self, wid) -> None:
        with self._lock:
            self._errors += 1
            if wid is not None:
                self._peers.setdefault(wid, _new_peer())["errors"] += 1

    def transport_info(self) -> dict:
        """Stats provider for FleetCoordinator.stats()/snapshot(): flat
        counters for the fleet/rpc_* metric rows plus the per-worker
        connection table for /statusz."""
        with self._lock:
            peers = {w: dict(p) for w, p in self._peers.items()}
        rtts = [p["rtt_ewma_s"] for p in peers.values()
                if p["rtt_ewma_s"] > 0.0]
        return {
            "name": "rpc",
            "counters": {
                "rpc_retries": float(sum(p["retries"]
                                         for p in peers.values())),
                "rpc_reconnects": float(sum(max(0, p["hellos"] - 1)
                                            for p in peers.values())),
                "rpc_rtt_ewma_s": float(np.mean(rtts)) if rtts else 0.0,
                "rpc_bytes_tx": float(self._bytes_tx),
                "rpc_bytes_rx": float(self._bytes_rx),
                "rpc_errors": float(self._errors + sum(
                    p["errors"] for p in peers.values()
                )),
                "heartbeat_misses": float(sum(p["heartbeat_misses"]
                                              for p in peers.values())),
            },
            "per_worker": {
                w: {
                    "state": ("partitioned" if p["partitioned"]
                              else p["state"]),
                    "rtt_ewma_s": round(p["rtt_ewma_s"], 6),
                    "retries": p["retries"],
                    "reconnects": max(0, p["hellos"] - 1),
                    "heartbeat_misses": p["heartbeat_misses"],
                    "bytes_tx": p["bytes_tx"],
                    "bytes_rx": p["bytes_rx"],
                    "last_epoch": p["last_epoch"],
                    "last_weight_version": p["last_weight_version"],
                }
                for w, p in peers.items()
            },
        }


def _new_peer() -> dict:
    return {
        "state": "reconnecting", "hellos": 0, "retries": 0,
        "heartbeat_misses": 0, "rtt_ewma_s": 0.0, "bytes_tx": 0,
        "bytes_rx": 0, "errors": 0, "last_epoch": 0,
        "last_weight_version": -1, "partitioned": False,
    }


def _merge_client_stats(peer: dict, stats) -> None:
    if not isinstance(stats, dict):
        return
    for k in ("retries", "heartbeat_misses"):
        if k in stats:
            peer[k] = int(stats[k])
    if "rtt_ewma_s" in stats:
        peer["rtt_ewma_s"] = float(stats["rtt_ewma_s"])
    peer["partitioned"] = bool(stats.get("partitioned", False))


# --------------------------------------------------------------------- #
# worker-side client
# --------------------------------------------------------------------- #


class RpcClient:
    """One worker's connection to the coordinator server: request/response
    with sequence numbers (stale duplicated replies are discarded by seq),
    per-attempt socket timeout, retry-with-backoff, and reconnect +
    re-handshake (worker id, last epoch, last weight version) on any
    connection loss. Thread-compatible: a lock serializes wire use."""

    def __init__(self, address: tuple[str, int], worker_id: int,
                 config: Optional[RpcConfig] = None, faults=None,
                 latency=None):
        self.address = (address[0], int(address[1]))
        self.worker_id = int(worker_id)
        self.cfg = config or RpcConfig()
        self._faults = faults
        # telemetry.LatencyHub: per-call-kind RTT histograms — one
        # `latency/rpc_<op>_s` family per op, recorded at the same site
        # as the rtt_ewma_s fold (telemetry.hist ranks above rpc.client
        # in LOCK_ORDER, so recording under self._lock is order-legal)
        self._latency = latency
        self._lock = make_rlock("rpc.client")
        self._sock: Optional[socket.socket] = None
        self._seq = 0
        self._partitioned_until = 0.0
        # client-side transport counters (reported to the server with every
        # hello/heartbeat so the coordinator's /statusz sees them)
        self.connects = 0
        self.retries = 0
        self.heartbeat_misses = 0
        self.rtt_ewma_s = 0.0
        self.last_epoch = 0
        self._cache_version = -1
        self._cache_tree = None

    @property
    def reconnects(self) -> int:
        return max(0, self.connects - 1)

    def stats_payload(self) -> dict:
        return {
            "retries": self.retries,
            "heartbeat_misses": self.heartbeat_misses,
            "rtt_ewma_s": self.rtt_ewma_s,
            "partitioned": time.monotonic() < self._partitioned_until,
        }

    def close(self) -> None:
        with self._lock:
            self._drop()

    # ------------------------------------------------------------ #

    def _drop(self) -> None:
        if self._sock is not None:
            _hard_close(self._sock)
            self._sock = None

    def _check_link(self) -> None:
        """net.partition state: every call fails fast while the link is
        down — the fault fires at most once per call attempt."""
        if time.monotonic() < self._partitioned_until:
            raise TransportError("link partitioned (injected)")
        if self._faults is not None and self._faults.armed:
            act = self._faults.fire("net.partition", worker=self.worker_id)
            if act is not None and act.startswith("partition:"):
                self._partitioned_until = (
                    time.monotonic() + float(act.split(":", 1)[1])
                )
                self._drop()
                raise TransportError("injected net.partition: link down")

    def _ensure_connected(self) -> None:
        if self._sock is not None:
            return
        try:
            sock = socket.create_connection(
                self.address, timeout=self.cfg.call_timeout
            )
        except _NET_DEAD as e:
            raise TransportError(f"connect to {self.address} failed: {e}")
        sock.settimeout(self.cfg.call_timeout)
        self._sock = sock
        self.connects += 1
        # re-handshake: who we are, the last lease epoch we held, and the
        # weight version we already have (resume without a full re-stream)
        resp = self._roundtrip({
            "op": "hello", "worker_id": self.worker_id,
            "last_epoch": self.last_epoch,
            "last_weight_version": self._cache_version,
            "stats": self.stats_payload(),
        })
        if "error" in resp:
            self._drop()
            raise TransportError(f"handshake rejected: {resp['error']}")

    def _roundtrip(self, req: dict) -> dict:
        """One request/response on the live socket; transport faults map to
        TransportError and drop the connection."""
        self._seq += 1
        seq = req["seq"] = self._seq
        sock = self._sock
        try:
            send_frame(sock, dumps(req), kind=KIND_OBJ,
                       faults=self._faults, worker=self.worker_id)
            while True:
                kind, payload = recv_frame(sock)
                if kind != KIND_OBJ:
                    continue  # stray weight chunk from an aborted stream
                resp = loads(payload)
                if isinstance(resp, dict) and resp.get("seq") == seq:
                    return resp
                # stale (duplicated) reply from an earlier seq: discard
        except TransportError:
            self._drop()
            raise
        except socket.timeout as e:
            self._drop()
            raise TransportError(f"call timed out: {e}")
        except _NET_DEAD as e:
            self._drop()
            raise TransportError(f"connection lost: {e}")

    def call(self, op: str, *, attempts: Optional[int] = None,
             stop: Optional[threading.Event] = None, **fields) -> dict:
        """`op` with retry/backoff. Raises TransportError when every
        attempt failed, RemoteCallError when the server's handler raised."""

        def attempt():
            with self._lock:
                self._check_link()
                self._ensure_connected()
                t0 = time.perf_counter()
                resp = self._roundtrip(
                    {"op": op, "worker_id": self.worker_id, **fields}
                )
                rtt = time.perf_counter() - t0
                a = self.cfg.rtt_alpha
                self.rtt_ewma_s = rtt if self.rtt_ewma_s <= 0.0 else (
                    a * rtt + (1 - a) * self.rtt_ewma_s
                )
                if self._latency is not None and self._latency.enabled:
                    self._latency.record(f"latency/rpc_{op}_s", rtt)
            if "error" in resp:
                raise RemoteCallError(resp["error"])
            return resp

        def on_retry(_i, _e):
            self.retries += 1

        sleep = time.sleep if stop is None else (lambda s: stop.wait(s))
        return retry_with_backoff(
            attempt, attempts=attempts or self.cfg.attempts,
            backoff_base=self.cfg.backoff_base,
            backoff_max=self.cfg.backoff_max, jitter=0.25,
            retry_on=(TransportError,), on_retry=on_retry, sleep=sleep,
        )

    # ------------------------------------------------------------ #

    def fetch_weights(self, stop: Optional[threading.Event] = None
                      ) -> tuple[int, object]:
        """(version, tree) streamed from the server's versioned store —
        header frame + chunked raw leaf buffers, zero disk writes. Cached
        by version: an unchanged policy costs one small round trip."""

        def attempt():
            with self._lock:
                self._check_link()
                self._ensure_connected()
                t0 = time.perf_counter()
                resp = self._roundtrip({
                    "op": "fetch_weights", "worker_id": self.worker_id,
                    "have_version": self._cache_version,
                })
                if "error" in resp:
                    raise RemoteCallError(resp["error"])
                if resp.get("unchanged"):
                    return self._cache_version, self._cache_tree
                try:
                    leaves = self._recv_leaves(resp["leaves"])
                except socket.timeout as e:
                    self._drop()
                    raise TransportError(f"weight stream stalled: {e}")
                except _NET_DEAD as e:
                    self._drop()
                    raise TransportError(f"weight stream lost: {e}")
                tree = join_leaves(resp["structure"], leaves)
                self._cache_version = int(resp["version"])
                self._cache_tree = tree
                a = self.cfg.rtt_alpha
                rtt = time.perf_counter() - t0
                self.rtt_ewma_s = rtt if self.rtt_ewma_s <= 0.0 else (
                    a * rtt + (1 - a) * self.rtt_ewma_s
                )
                if self._latency is not None and self._latency.enabled:
                    self._latency.record("latency/rpc_fetch_weights_s", rtt)
                return self._cache_version, tree

        def on_retry(_i, _e):
            self.retries += 1

        sleep = time.sleep if stop is None else (lambda s: stop.wait(s))
        return retry_with_backoff(
            attempt, attempts=self.cfg.attempts,
            backoff_base=self.cfg.backoff_base,
            backoff_max=self.cfg.backoff_max, jitter=0.25,
            retry_on=(TransportError,), on_retry=on_retry, sleep=sleep,
        )

    def _recv_leaves(self, metas: list[dict]) -> list[np.ndarray]:
        bufs = [bytearray(int(m["nbytes"])) for m in metas]
        need = sum(len(b) for b in bufs)
        got = 0
        seen: set[tuple[int, int]] = set()
        while got < need:
            kind, payload = recv_frame(self._sock)
            if kind != KIND_OBJ and kind != KIND_CHUNK:
                raise TornFrame(f"unexpected frame kind {kind}")
            if kind == KIND_OBJ:
                continue  # stale duplicated reply straggling in the stream
            leaf, off = struct.unpack_from("!II", payload)
            data = payload[8:]
            if leaf >= len(bufs) or off + len(data) > len(bufs[leaf]):
                raise TornFrame("weight chunk outside leaf bounds")
            bufs[leaf][off:off + len(data)] = data
            if (leaf, off) not in seen:  # duplicates are idempotent
                seen.add((leaf, off))
                got += len(data)
        return [
            np.frombuffer(bytes(b), dtype=_dtype(m["dtype"]))
            .reshape(m["shape"]).copy()
            for b, m in zip(bufs, metas)
        ]


class RemoteCoordinator:
    """Client-side proxy with the coordinator surface RolloutWorker uses
    (acquire / complete / worker_failed / lease_revoked / index_done), so
    the PR 6 worker loop runs unchanged over the network."""

    def __init__(self, client: RpcClient, poll_interval: float = 0.05):
        self._client = client
        self._poll = poll_interval

    def acquire(self, worker_id: int, stop: threading.Event
                ) -> Optional[Lease]:
        while not stop.is_set():
            try:
                resp = self._client.call("acquire", stop=stop)
            except (TransportError, RemoteCallError):
                resp = None  # server unreachable: keep polling until stop
            if resp is not None:
                if resp.get("stop"):
                    return None
                if resp.get("lease") is not None:
                    lease = decode_lease(resp["lease"])
                    self._client.last_epoch = max(
                        self._client.last_epoch, lease.epoch
                    )
                    return lease
            if stop.wait(self._poll):
                return None
        return None

    def complete(self, worker_id: int, lease: Lease, index: int,
                 sample: QueuedSample) -> bool:
        resp = self._client.call(
            "complete", lease_id=lease.lease_id, epoch=lease.epoch,
            index=index, version=sample.version, payload=sample.payload,
            dispatch_time=sample.dispatch_time,
            ready_time=sample.ready_time,
        )
        return bool(resp.get("accepted"))

    def worker_failed(self, worker_id: int, lease: Optional[Lease],
                      exc: BaseException, fatal: bool = False) -> None:
        try:
            self._client.call(
                "worker_failed",
                lease_id=None if lease is None else lease.lease_id,
                fatal=fatal, message=f"{type(exc).__name__}: {exc}",
                attempts=2,
            )
        except (TransportError, RemoteCallError):
            pass  # unreachable: the lease deadline sweep handles it

    def lease_revoked(self, lease: Lease) -> bool:
        try:
            resp = self._client.call("lease_revoked",
                                     lease_id=lease.lease_id, attempts=1)
            return bool(resp.get("revoked"))
        except (TransportError, RemoteCallError):
            return False  # can't tell: keep working, fencing protects us

    def index_done(self, index: int) -> bool:
        try:
            resp = self._client.call("index_done", index=index, attempts=1)
            return bool(resp.get("done"))
        except (TransportError, RemoteCallError):
            return False


class RpcTransport(FleetTransport):
    """The 3-call FleetTransport over RpcClient. Generation itself runs
    locally on the worker (the rollout pod owns the model); the wire
    carries weights in and heartbeats/completions out — the direct
    in-memory stream that replaces the reference's disk round-trip."""

    def __init__(self, client: RpcClient,
                 dispatch_fn: Callable[[int, object, dict, int], dict]):
        self._client = client
        self._dispatch_fn = dispatch_fn

    def fetch_weights(self, worker_id: int, stop=None):
        return self._client.fetch_weights(stop=stop)

    def poll_weights(self, worker_id: int, have_version: int, stop=None):
        # in-flight swap poll (docs/ORCHESTRATOR.md §in-flight swaps): the
        # client's by-version cache makes the no-newer-weights case one tiny
        # have_version round trip (the server answers "unchanged" and no
        # leaf bytes move). Transport failures are swallowed — a missed
        # poll is a missed swap opportunity inside the decode loop, not a
        # worker failure; the next sync point retries.
        try:
            version, tree = self._client.fetch_weights(stop=stop)
        except (TransportError, RemoteCallError):
            return have_version, None
        if version <= have_version:
            return version, None
        return version, tree

    def heartbeat(self, worker_id: int) -> None:
        # best-effort: a missed heartbeat is COUNTED, never fatal — the
        # coordinator notices real silence through the lease deadline
        try:
            self._client.call("heartbeat", attempts=1,
                              stats=self._client.stats_payload())
        except (TransportError, RemoteCallError):
            self._client.heartbeat_misses += 1

    def dispatch(self, worker_id: int, index: int, queries, tree,
                 weight_refresh=None):
        if weight_refresh is not None:
            payload = self._dispatch_fn(
                index, queries, tree, worker_id, weight_refresh
            )
        else:
            payload = self._dispatch_fn(index, queries, tree, worker_id)
        import jax  # lazy: keeps rpc.py importable jax-free for units

        jax.block_until_ready(payload)
        return payload
