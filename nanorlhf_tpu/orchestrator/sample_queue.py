"""Bounded-staleness rollout sample queue (host thread + deque; device
arrays inside payloads stay sharded — the queue never copies them).

Staleness model (docs/ORCHESTRATOR.md): the policy advances one VERSION per
optimizer update (`VersionedWeightStore.publish`); a sample generated from
version v consumed while the policy is at version V has staleness V − v.
With one publish per consumed sample (the dense trainer), gating the
producer so rollout i (relative to the queue's start index) waits until
`version >= i - max_staleness` bounds every consumed sample's staleness at
`max_staleness` — the PipelineRL/LlamaRL bounded-lag queue.

Overflow policy — production is gated IDENTICALLY in both modes (a sample
whose dispatch could already exceed the bound would only burn the data/PRNG
cursor and rollout compute on a result destined for the floor); they differ
in what happens to a queued sample that goes over-stale anyway, which under
the normal one-publish-per-consume cadence cannot happen and therefore
signals an abnormal cadence (the consumer published without consuming —
e.g. an external weight sync or multi-update schedule):
- "wait" (default): over-stale samples are still DELIVERED (recorded in the
  staleness histogram above the bound) — nothing is ever discarded, and the
  truncated-IS correction absorbs the extra staleness.
- "drop": `get()` DISCARDS over-stale samples and returns the next fresh
  one; `dropped` counts the discards.

jax-free on purpose: unit-testable with plain dict payloads.
"""

from __future__ import annotations

import collections
import dataclasses
import threading

from nanorlhf_tpu.analysis.lockorder import make_condition
import time
from typing import Any, Optional


class ProducerFailed(RuntimeError):
    """The producer thread died (its terminal exception is `__cause__`, when
    it reported one). A dedicated type so the trainer's watchdog can tell a
    supervisable producer death from an organic consumer-side error —
    subclassing RuntimeError keeps pre-watchdog callers working."""


@dataclasses.dataclass
class QueuedSample:
    index: int           # rollout index — the data/PRNG cursor position
    version: int         # policy version the sample was generated from
    payload: Any         # the rollout dict (sharded device arrays + host data)
    dispatch_time: float = 0.0
    ready_time: float = 0.0


class BoundedStalenessQueue:
    def __init__(self, max_staleness: int, policy: str = "wait",
                 start_index: int = 0, lineage=None, latency=None):
        if max_staleness < 0:
            raise ValueError(f"max_staleness={max_staleness} must be >= 0")
        if policy not in ("wait", "drop"):
            raise ValueError(f"staleness policy {policy!r}: wait | drop")
        self.max_staleness = max_staleness
        self.policy = policy
        # lineage ledger (telemetry/lineage.py): queue-transit events —
        # enqueue/dequeue monotonic times + staleness at consumption — and
        # stale-drop attribution. None/disabled = no-op.
        self._lineage = lineage
        # LatencyHub (telemetry/hist.py): per-sample queue-wait histogram,
        # recorded at dequeue. telemetry.hist ranks above
        # orchestrator.queue in LOCK_ORDER, so recording under _cond is
        # order-legal. None/disabled = no-op.
        self._latency = latency
        self.maxsize = max_staleness + 1
        self._base = start_index     # gate arithmetic is RELATIVE to this
        self._q: collections.deque[QueuedSample] = collections.deque()
        self._cond = make_condition("orchestrator.queue")
        self._version = 0            # latest published policy version
        self._error: Optional[BaseException] = None
        # ---- metrics (cumulative; resume seeds them from the journal) ----
        self.dropped = 0
        self.staleness_counts: dict[int, int] = {}
        # who-waits-on-whom diagnostics (cumulative seconds, perf_counter;
        # per-process — not journaled): consumer_wait_s = trainer starved
        # for samples (pipeline too shallow / generation too slow),
        # producer_gate_wait_s = producer blocked on the staleness gate or
        # queue capacity (training is the bottleneck — the healthy state).
        # Surfaced via orchestrator.stats() and the telemetry counters.
        self.consumer_wait_s = 0.0
        self.producer_gate_wait_s = 0.0

    # ---------------------------------------------------------------- #
    # producer side
    # ---------------------------------------------------------------- #

    def wait_to_produce(self, index: int, stop) -> bool:
        """Block until rollout `index` may be dispatched; False on stop.

        Gate (both policies): the staleness bound — the version must have
        reached `index - base - max_staleness` — plus queue capacity. With
        one publish per consume, a sample admitted here can never exceed
        the bound at consumption.
        """
        with self._cond:
            while not stop.is_set():
                gate_open = (
                    (index - self._base) - self._version <= self.max_staleness
                )
                if gate_open and len(self._q) < self.maxsize:
                    return True
                t0 = time.perf_counter()
                self._cond.wait(timeout=0.1)
                self.producer_gate_wait_s += time.perf_counter() - t0
            return False

    def may_produce(self, index: int) -> bool:
        """Non-blocking `wait_to_produce` gate check — the fleet coordinator
        holds its own lock while sizing leases and cannot block in here; it
        re-polls on its own wait cadence instead. Capacity is NOT checked:
        the coordinator's in-order reorder buffer means granted-but-unqueued
        indices already bound queue depth via this same staleness gate."""
        with self._cond:
            return (index - self._base) - self._version <= self.max_staleness

    def put(self, sample: QueuedSample) -> None:
        with self._cond:
            self._q.append(sample)
            self._cond.notify_all()

    def fail(self, exc: BaseException) -> None:
        """Producer died: wake the consumer with the exception."""
        with self._cond:
            self._error = exc
            self._cond.notify_all()

    # ---------------------------------------------------------------- #
    # consumer side
    # ---------------------------------------------------------------- #

    def advance_version(self, version: int) -> None:
        """The trainer published a new policy version (one per update)."""
        with self._cond:
            self._version = version
            self._cond.notify_all()

    def credit_skip(self) -> None:
        """The consumer took a sample WITHOUT training on it (a sentinel-
        quarantined batch): shift the gate's base so the producer may run
        one more index ahead without a version publish — publishing instead
        would mislabel every queued sample one version staler than its
        weights really are (and the "drop" policy would evict them)."""
        with self._cond:
            self._base += 1
            self._cond.notify_all()

    def get(self, timeout: Optional[float] = None) -> QueuedSample:
        """Next sample, oldest first; records its staleness in the
        histogram. Under "drop", over-stale samples are discarded here.

        Buffered samples are drained BEFORE a producer failure is raised:
        samples already in the deque are complete device-ready rollouts
        produced under the same version arithmetic the consumer is using —
        discarding them would make every watchdog restart regenerate up to
        max_staleness+1 rollouts that were never lost."""
        with self._cond:
            while True:
                if self._q:
                    s = self._q.popleft()
                    staleness = self._version - s.version
                    if (self.policy == "drop"
                            and staleness > self.max_staleness):
                        self.dropped += 1
                        if self._lineage is not None:
                            self._lineage.drop(
                                s.index, "stale_drop", staleness=staleness,
                                policy_version=s.version,
                            )
                        self._cond.notify_all()
                        continue
                    self.staleness_counts[staleness] = (
                        self.staleness_counts.get(staleness, 0) + 1
                    )
                    if (self._latency is not None and self._latency.enabled
                            and s.ready_time > 0.0):
                        # dequeue − device-ready, both on the producer's
                        # monotonic clock: the sample's true queue wait
                        # (unstamped samples — plain-dict unit-test
                        # payloads — carry ready_time 0.0 and are skipped)
                        self._latency.record(
                            "latency/queue_wait_s",
                            time.perf_counter() - s.ready_time,
                        )
                    if (self._lineage is not None
                            and self._lineage.enabled):
                        # dispatch/ready stamps share the producer's clock
                        # (perf_counter), so queue wait = dequeue_t -
                        # enqueue_t is NTP-step-safe; the wall-clock
                        # dequeue stamp survives as provenance only (the
                        # ledger's own record envelope carries it too)
                        self._lineage.queue(
                            s.index, enqueue_t=s.ready_time,
                            dequeue_t=time.perf_counter(),
                            staleness=staleness, policy_version=s.version,
                            # nanolint: allow[determinism.wall-clock] dequeue_wall is a provenance stamp, not a duration input
                            dequeue_wall=time.time(),
                        )
                    self._cond.notify_all()
                    return s
                if self._error is not None:  # buffer drained: surface it
                    raise ProducerFailed(
                        "rollout producer failed"
                    ) from self._error
                t0 = time.perf_counter()
                ok = self._cond.wait(timeout=timeout)
                self.consumer_wait_s += time.perf_counter() - t0
                if not ok:
                    raise TimeoutError(
                        f"no rollout sample after {timeout}s (producer "
                        "stalled?)"
                    )

    # ---------------------------------------------------------------- #
    # introspection / persistence
    # ---------------------------------------------------------------- #

    @property
    def version(self) -> int:
        with self._cond:
            return self._version

    def depth(self) -> int:
        with self._cond:
            return len(self._q)

    def journal(self) -> dict:
        """JSON-able queue state for the checkpoint's trainer_state: the
        pending (dispatched, unconsumed) indices plus cumulative counters.
        Pending samples are NOT persisted — on resume they are re-drawn
        from the consumed-rollout cursor (the index-keyed generation PRNG
        and deterministic loader reproduce their token streams)."""
        with self._cond:
            return {
                "pending": [s.index for s in self._q],
                "version": self._version,
                "dropped": self.dropped,
                "staleness_counts": {
                    str(k): v for k, v in self.staleness_counts.items()
                },
            }

    def restore_counters(self, journal: dict) -> None:
        """Seed the cumulative metric counters from a saved journal so
        dropped/staleness series stay continuous across resume. Version and
        pending entries are NOT restored — a fresh orchestrator restarts
        version-relative arithmetic at 0 and re-draws pending samples."""
        with self._cond:
            self.dropped = int(journal.get("dropped", 0))
            self.staleness_counts = {
                int(k): int(v)
                for k, v in journal.get("staleness_counts", {}).items()
            }
