"""Version-tagged policy weight store for async rollouts.

The synchronous trainer hands the live `self.params` tree to generation.
With a producer thread generating WHILE the consumer updates, that tree is
a moving target — worse, the jitted update DONATES its trainable input
buffers (`trainer._make_update_fn`, donate_argnums), so a generation
dispatched off the live tree mid-update can read deleted/aliased arrays.

The store decouples the two: the trainer PUBLISHES an immutable snapshot
after every optimizer update (the caller copies exactly the donation-hazard
leaves — trainable ones; frozen base weights are safely aliased, see
`RLTrainer._policy_snapshot`), and rollout workers PULL the latest published
version without ever blocking the train step. Versions are monotonically
increasing, starting at 0 for the tree published at construction/creation —
all staleness arithmetic (`sample_queue`, metrics) is relative to these
version tags.

Device placement is untouched: published leaves stay sharded jax.Arrays;
the store is plain host-side bookkeeping (no jax import).
"""

from __future__ import annotations

import threading
import time

from nanorlhf_tpu.analysis.lockorder import make_condition
from typing import Any, Callable, Optional


def store_poll(store: "VersionedWeightStore") -> Callable:
    """Non-blocking `poll(have) -> (version, tree|None)` reading `store`
    directly — the serial/in-process side of `make_swap_refresh`. Never
    waits: an unpublished store (version < 0) reports `(have, None)`."""
    def poll(have: int):
        v = store.version
        if v < 0 or v <= have:
            return max(v, have), None
        return store.latest()
    return poll


def make_swap_refresh(poll: Callable, *, have_version: Optional[int] = None,
                      faults=None, worker: Optional[int] = None) -> Callable:
    """Build the in-flight weight-swap callback (docs/ORCHESTRATOR.md
    §in-flight swaps) handed down to the decode drivers.

    `poll(have) -> (version, tree|None)` is the transport-specific
    non-blocking check (`store_poll` for direct store readers, the fleet
    transports' `poll_weights` otherwise — the RPC client's version cache
    makes an unchanged-policy poll one tiny have_version round trip).

    The returned `refresh() -> (version, tree|None)` is what the queued
    scheduler / env episode driver calls at each host sync point: `tree`
    is None when the held version is still the newest (install nothing),
    otherwise the fresh param tree to install before the next decode
    chunk. `have_version=None` (the serial path, where the dispatch
    closure does not know which version it was handed) makes the FIRST
    call return the store's latest outright — the caller installs it
    pre-loop without counting a swap.

    When a newer tree is about to be handed over, the `swap.stale` fault
    site fires (docs/RESILIENCE.md): the default `delay` action sleeps
    first and installs anyway — deliberately landing a version that may
    already be superseded; the next sync point then installs the newer
    one, so the ledger's per-segment versions stay strictly increasing.
    """
    state = {"v": have_version}

    def refresh():
        have = state["v"]
        version, tree = poll(-1 if have is None else have)
        if tree is None or (have is not None and version <= have):
            return (version if have is None else max(version, have)), None
        if faults is not None:
            act = faults.fire("swap.stale", worker=worker)
            if act and str(act).startswith("delay:"):
                time.sleep(float(str(act).split(":", 1)[1]))
        state["v"] = version
        return version, tree

    return refresh


class VersionedWeightStore:
    """Thread-safe {version -> param tree} holder keeping only the latest.

    `publish(tree)` tags `tree` with the next version and makes it the one
    `latest()` returns; the previous snapshot is dropped (rollout dispatch
    always wants the freshest policy — a sample's version tag, not the
    store, remembers which weights generated it).
    """

    def __init__(self):
        self._cond = make_condition("orchestrator.weights")
        self._version = -1
        self._tree: Any = None

    @property
    def version(self) -> int:
        with self._cond:
            return self._version

    def publish(self, tree: Any) -> int:
        """Store `tree` as the new latest snapshot; returns its version."""
        with self._cond:
            self._version += 1
            self._tree = tree
            self._cond.notify_all()
            return self._version

    def latest(self) -> tuple[int, Any]:
        """(version, tree) of the newest published snapshot."""
        with self._cond:
            if self._version < 0:
                raise RuntimeError("no weights published yet")
            return self._version, self._tree

    def wait_for_version(self, min_version: int = 0,
                         timeout: Optional[float] = None,
                         stop: Optional[threading.Event] = None,
                         ) -> tuple[int, Any]:
        """Block until a version >= `min_version` is published, then return
        `latest()`. A rollout worker that joins the fleet BEFORE the trainer
        publishes snapshot 0 (multi-host workers boot concurrently with the
        trainer; in-process ones can race a slow `_policy_snapshot` copy)
        must wait here instead of crash-looping `latest()`'s RuntimeError
        through its consecutive-failure budget into quarantine.

        `stop` (optional Event) aborts the wait with a TimeoutError when
        set — a worker being shut down must not ride out a long timeout.
        Raises TimeoutError after `timeout` seconds (None = wait forever).
        """
        deadline = (
            None if timeout is None else threading.TIMEOUT_MAX
            if timeout < 0 else timeout
        )
        with self._cond:
            waited = 0.0
            while self._version < min_version:
                if stop is not None and stop.is_set():
                    raise TimeoutError(
                        f"stopped while waiting for weight version "
                        f">= {min_version}"
                    )
                # slice the wait so `stop` is polled even with timeout=None
                slice_s = 0.1 if deadline is None \
                    else min(0.1, max(0.0, deadline - waited))
                self._cond.wait(timeout=slice_s)
                waited += slice_s
                if deadline is not None and waited >= deadline \
                        and self._version < min_version:
                    raise TimeoutError(
                        f"no weight version >= {min_version} published "
                        f"after {timeout}s (latest: {self._version})"
                    )
            return self._version, self._tree
