"""Version-tagged policy weight store for async rollouts.

The synchronous trainer hands the live `self.params` tree to generation.
With a producer thread generating WHILE the consumer updates, that tree is
a moving target — worse, the jitted update DONATES its trainable input
buffers (`trainer._make_update_fn`, donate_argnums), so a generation
dispatched off the live tree mid-update can read deleted/aliased arrays.

The store decouples the two: the trainer PUBLISHES an immutable snapshot
after every optimizer update (the caller copies exactly the donation-hazard
leaves — trainable ones; frozen base weights are safely aliased, see
`RLTrainer._policy_snapshot`), and rollout workers PULL the latest published
version without ever blocking the train step. Versions are monotonically
increasing, starting at 0 for the tree published at construction/creation —
all staleness arithmetic (`sample_queue`, metrics) is relative to these
version tags.

Device placement is untouched: published leaves stay sharded jax.Arrays;
the store is plain host-side bookkeeping (no jax import).
"""

from __future__ import annotations

import threading

from nanorlhf_tpu.analysis.lockorder import make_condition
from typing import Any, Optional


class VersionedWeightStore:
    """Thread-safe {version -> param tree} holder keeping only the latest.

    `publish(tree)` tags `tree` with the next version and makes it the one
    `latest()` returns; the previous snapshot is dropped (rollout dispatch
    always wants the freshest policy — a sample's version tag, not the
    store, remembers which weights generated it).
    """

    def __init__(self):
        self._cond = make_condition("orchestrator.weights")
        self._version = -1
        self._tree: Any = None

    @property
    def version(self) -> int:
        with self._cond:
            return self._version

    def publish(self, tree: Any) -> int:
        """Store `tree` as the new latest snapshot; returns its version."""
        with self._cond:
            self._version += 1
            self._tree = tree
            self._cond.notify_all()
            return self._version

    def latest(self) -> tuple[int, Any]:
        """(version, tree) of the newest published snapshot."""
        with self._cond:
            if self._version < 0:
                raise RuntimeError("no weights published yet")
            return self._version, self._tree

    def wait_for_version(self, min_version: int = 0,
                         timeout: Optional[float] = None,
                         stop: Optional[threading.Event] = None,
                         ) -> tuple[int, Any]:
        """Block until a version >= `min_version` is published, then return
        `latest()`. A rollout worker that joins the fleet BEFORE the trainer
        publishes snapshot 0 (multi-host workers boot concurrently with the
        trainer; in-process ones can race a slow `_policy_snapshot` copy)
        must wait here instead of crash-looping `latest()`'s RuntimeError
        through its consecutive-failure budget into quarantine.

        `stop` (optional Event) aborts the wait with a TimeoutError when
        set — a worker being shut down must not ride out a long timeout.
        Raises TimeoutError after `timeout` seconds (None = wait forever).
        """
        deadline = (
            None if timeout is None else threading.TIMEOUT_MAX
            if timeout < 0 else timeout
        )
        with self._cond:
            waited = 0.0
            while self._version < min_version:
                if stop is not None and stop.is_set():
                    raise TimeoutError(
                        f"stopped while waiting for weight version "
                        f">= {min_version}"
                    )
                # slice the wait so `stop` is polled even with timeout=None
                slice_s = 0.1 if deadline is None \
                    else min(0.1, max(0.0, deadline - waited))
                self._cond.wait(timeout=slice_s)
                waited += slice_s
                if deadline is not None and waited >= deadline \
                        and self._version < min_version:
                    raise TimeoutError(
                        f"no weight version >= {min_version} published "
                        f"after {timeout}s (latest: {self._version})"
                    )
            return self._version, self._tree
