"""Version-tagged policy weight store for async rollouts.

The synchronous trainer hands the live `self.params` tree to generation.
With a producer thread generating WHILE the consumer updates, that tree is
a moving target — worse, the jitted update DONATES its trainable input
buffers (`trainer._make_update_fn`, donate_argnums), so a generation
dispatched off the live tree mid-update can read deleted/aliased arrays.

The store decouples the two: the trainer PUBLISHES an immutable snapshot
after every optimizer update (the caller copies exactly the donation-hazard
leaves — trainable ones; frozen base weights are safely aliased, see
`RLTrainer._policy_snapshot`), and rollout workers PULL the latest published
version without ever blocking the train step. Versions are monotonically
increasing, starting at 0 for the tree published at construction/creation —
all staleness arithmetic (`sample_queue`, metrics) is relative to these
version tags.

Device placement is untouched: published leaves stay sharded jax.Arrays;
the store is plain host-side bookkeeping (no jax import).
"""

from __future__ import annotations

import threading
from typing import Any


class VersionedWeightStore:
    """Thread-safe {version -> param tree} holder keeping only the latest.

    `publish(tree)` tags `tree` with the next version and makes it the one
    `latest()` returns; the previous snapshot is dropped (rollout dispatch
    always wants the freshest policy — a sample's version tag, not the
    store, remembers which weights generated it).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._version = -1
        self._tree: Any = None

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def publish(self, tree: Any) -> int:
        """Store `tree` as the new latest snapshot; returns its version."""
        with self._lock:
            self._version += 1
            self._tree = tree
            return self._version

    def latest(self) -> tuple[int, Any]:
        """(version, tree) of the newest published snapshot."""
        with self._lock:
            if self._version < 0:
                raise RuntimeError("no weights published yet")
            return self._version, self._tree
