from nanorlhf_tpu.parallel.mesh import MeshConfig, make_mesh, param_sharding_rules, shard_params, batch_sharding

__all__ = [
    "MeshConfig",
    "make_mesh",
    "param_sharding_rules",
    "shard_params",
    "batch_sharding",
]
