from nanorlhf_tpu.parallel.mesh import MeshConfig, make_mesh, param_sharding_rules, shard_params, batch_sharding
from nanorlhf_tpu.parallel.ring_attention import ring_attention, ring_attention_flash
from nanorlhf_tpu.parallel.sp import (
    sp_forward_logits,
    sp_fsdp_forward_logits,
    sp_score_logprobs,
    sp_score_values,
)
from nanorlhf_tpu.parallel.distributed import initialize_multihost, broadcast_host_value

__all__ = [
    "MeshConfig",
    "make_mesh",
    "param_sharding_rules",
    "shard_params",
    "batch_sharding",
    "ring_attention",
    "ring_attention_flash",
    "sp_forward_logits",
    "sp_fsdp_forward_logits",
    "sp_score_logprobs",
    "sp_score_values",
    "initialize_multihost",
    "broadcast_host_value",
]
