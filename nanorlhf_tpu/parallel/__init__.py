from nanorlhf_tpu.parallel.mesh import MeshConfig, make_mesh, param_sharding_rules, shard_params, batch_sharding
from nanorlhf_tpu.parallel.ring_attention import ring_attention
from nanorlhf_tpu.parallel.sp import (
    sp_forward_logits,
    sp_fsdp_forward_logits,
    sp_score_logprobs,
)
from nanorlhf_tpu.parallel.distributed import initialize_multihost, broadcast_host_value

__all__ = [
    "MeshConfig",
    "make_mesh",
    "param_sharding_rules",
    "shard_params",
    "batch_sharding",
    "ring_attention",
    "sp_forward_logits",
    "sp_fsdp_forward_logits",
    "sp_score_logprobs",
    "initialize_multihost",
    "broadcast_host_value",
]
