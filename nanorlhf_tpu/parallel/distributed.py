"""Multi-host initialization — the TPU-native distributed backend.

The reference's distributed story is accelerate → torch.distributed → NCCL,
exercised at world_size 1 (SURVEY.md §5.8; `/root/reference/GRPO/
grpo_trainer.py:218,242`). Its used collective surface — one broadcast of a
run timestamp, metric gathers, and gradient sync — all become XLA
collectives inside the compiled step here. What remains host-side is
process-group bring-up, which this module wraps:

- on a TPU pod slice, `jax.distributed.initialize()` discovers coordinator
  and process ids from the TPU environment automatically;
- across slices (DCN), the standard env vars / explicit args apply;
- mesh axes should map (data → DCN × ICI, fsdp/tensor → ICI only) so
  parameter collectives never cross the slow DCN links.
"""

from __future__ import annotations

import os

import jax


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> dict:
    """Bring up jax.distributed for multi-host runs; no-op for single host.

    Returns a summary dict (process_index, process_count, device counts).
    Safe to call when already initialized or on a single host.
    """
    should_init = (
        coordinator_address is not None
        or os.environ.get("COORDINATOR_ADDRESS")
        or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS")
        or os.environ.get("TPU_WORKER_HOSTNAMES", "").count(",") > 0
    )
    already = getattr(jax.distributed, "is_initialized", lambda: False)()
    if should_init and not already:
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
        except RuntimeError as e:
            # jax raises "distributed.initialize should only be called once"
            # on re-entry (wording varies by version) — treat as no-op
            msg = str(e).lower()
            if "once" not in msg and "already" not in msg:
                raise
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": jax.device_count(),
    }


def broadcast_host_value(value: int) -> int:
    """Agree on process 0's value across all hosts (run-timestamp parity with
    `broadcast(time_tensor, 0)`, `grpo_trainer.py:241-242`)."""
    if jax.process_count() == 1:
        return int(value)
    from jax.experimental import multihost_utils

    import numpy as np

    return int(multihost_utils.broadcast_one_to_all(np.int32(value)))
