"""Device mesh + sharding rules — the TPU replacement for the reference's
entire memory/distribution story.

The reference scales by CPU↔GPU offload choreography and an unused
accelerate/NCCL scaffold (SURVEY.md §2.3, `/root/reference/GRPO/
grpo_trainer.py:168-172,475-476,622-626`). Here the same capability is a
`jax.sharding.Mesh` with axes:

- `data`  — batch/data parallel (primary scaling axis; DCN axis multi-slice)
- `fsdp`  — parameter/optimizer-state sharding (ZeRO-equivalent; replaces the
            optimizer-state CPU paging entirely)
- `tensor`— megatron-style tensor parallel for >8B models
- `sp`    — sequence/context parallel (ring attention over ICI;
            `parallel/sp.py`). Params and batch are replicated over sp; the
            sequence dim of the scoring/update passes shards over it.

All rules are GSPMD PartitionSpecs over the *stacked* param tree of
core/model.py; XLA inserts the collectives (psum/all-gather over ICI).
Batch axes shard over (data, fsdp) jointly — fsdp acts as a second data axis
for activations, param all-gathers ride the fsdp axis.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    data: int = -1      # -1 = all remaining devices
    fsdp: int = 1
    tensor: int = 1
    sp: int = 1         # sequence-parallel extent (ring attention)
    # number of TPU slices the DATA axis spans (multi-slice / DCN scaling).
    # The data axis becomes (dcn_data × per-slice data) with slices
    # slowest-varying, so fsdp/tensor/sp collectives stay inside a slice
    # (ICI) and only the once-per-update gradient psum crosses DCN — the
    # layout §5.8 calls for. 1 = single slice (no DCN traffic at all).
    dcn_data: int = 1

    def resolve(self, n_devices: int) -> tuple[int, int, int, int]:
        d, f, t, s = self.data, self.fsdp, self.tensor, self.sp
        dcn = max(self.dcn_data, 1)
        known = (f if f > 0 else 1) * (t if t > 0 else 1) * (s if s > 0 else 1)
        if d == -1:
            d = n_devices // (known * dcn) * dcn
        if d * f * t * s != n_devices:
            raise ValueError(
                f"mesh {d}x{f}x{t}x{s} != {n_devices} devices"
            )
        if d % dcn != 0:
            raise ValueError(f"data axis {d} not divisible by dcn_data {dcn}")
        return d, f, t, s


def _slice_ordered(devices, dcn: int):
    """Order devices slice-major so reshaping puts whole slices on the
    leading (DCN) part of the data axis. TPU runtimes expose `slice_index`
    on each device — when present, the physical layout must actually match
    `dcn` (distinct slices == dcn, equal sizes), else fsdp/tensor/sp
    collectives would silently straddle slice boundaries and cross DCN
    every layer. Hosts without `slice_index` (CPU test meshes) fall back to
    id order, which partitions the virtual devices into `dcn` contiguous
    groups — same axis semantics, no physical slices to respect."""
    if all(hasattr(d, "slice_index") for d in devices):
        slices = sorted({d.slice_index for d in devices})
        if len(slices) != dcn:
            raise ValueError(
                f"dcn_data={dcn} but devices span {len(slices)} slices"
            )
        per = [sum(d.slice_index == s for d in devices) for s in slices]
        if len(set(per)) != 1:
            raise ValueError(f"uneven devices per slice: {per}")
        return sorted(devices, key=lambda dev: (dev.slice_index, dev.id))
    return sorted(devices, key=lambda dev: dev.id)


def make_mesh(config: MeshConfig = MeshConfig(), devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    d, f, t, s = config.resolve(len(devices))
    dcn = max(config.dcn_data, 1)
    if dcn > 1:
        devices = _slice_ordered(devices, dcn)
    arr = np.asarray(devices).reshape(d, f, t, s)
    return Mesh(arr, ("data", "fsdp", "tensor", "sp"))


# ---------------------------------------------------------------------------
# Sharding rules for the stacked Qwen2 tree (+ optional LoRA subtree)
# ---------------------------------------------------------------------------

# leaf-name path suffix -> PartitionSpec (leading None = stacked layer axis)
_RULES = {
    ("embed_tokens",): P("tensor", "fsdp"),
    ("norm",): P(None),
    ("lm_head",): P("fsdp", "tensor"),
    # attention: out-features sharded by tensor, in-features by fsdp
    ("layers", "q_proj", "kernel"): P(None, "fsdp", "tensor"),
    ("layers", "k_proj", "kernel"): P(None, "fsdp", "tensor"),
    ("layers", "v_proj", "kernel"): P(None, "fsdp", "tensor"),
    ("layers", "q_proj", "bias"): P(None, "tensor"),
    ("layers", "k_proj", "bias"): P(None, "tensor"),
    ("layers", "v_proj", "bias"): P(None, "tensor"),
    ("layers", "o_proj", "kernel"): P(None, "tensor", "fsdp"),
    # mlp: intermediate dim by tensor
    ("layers", "gate_proj", "kernel"): P(None, "fsdp", "tensor"),
    ("layers", "up_proj", "kernel"): P(None, "fsdp", "tensor"),
    ("layers", "down_proj", "kernel"): P(None, "tensor", "fsdp"),
    ("layers", "input_layernorm"): P(None, None),
    ("layers", "post_attention_layernorm"): P(None, None),
    # LoRA: A shards like the input dim, B like the output dim
    ("a",): P(None, "fsdp", None),
    ("b",): P(None, None, "tensor"),
}

_RULES_BY_LEN = sorted(_RULES.items(), key=lambda kv: -len(kv[0]))


def _spec_for_path(path: tuple[str, ...]) -> P:
    # int8 rollout kernels (core/quant.py): kernel_q shards exactly like the
    # kernel it replaces; its per-output-channel scale [L, 1, out] keeps the
    # kernel's out-axis sharding with the contracted axis unsharded
    if path and path[-1] == "kernel_q":
        path = path[:-1] + ("kernel",)
    elif path and path[-1] == "kernel_scale":
        kspec = _spec_for_path(path[:-1] + ("kernel",))
        return P(*(list(kspec)[:-2] + [None, list(kspec)[-1]])) \
            if len(kspec) >= 2 else kspec
    for suffix, spec in _RULES_BY_LEN:
        if path[-len(suffix):] == suffix:
            return spec
    return P()  # replicate anything unmatched


def param_sharding_rules(params) -> dict:
    """PartitionSpec pytree matching `params` (works for LoRA subtrees too)."""

    def spec(path, leaf):
        keys = tuple(
            p.key if hasattr(p, "key") else str(p) for p in path
        )
        # strip a leading "lora" namespace so LoRA trees reuse layer rules
        if keys and keys[0] == "lora":
            keys = keys[1:]
        return _spec_for_path(keys)

    return jax.tree_util.tree_map_with_path(spec, params)


def shard_params(params, mesh: Mesh, rules=None):
    """Place a param tree on the mesh according to the rules (host → device)."""
    rules = rules if rules is not None else param_sharding_rules(params)
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        params,
        rules,
    )


def batch_sharding(mesh: Mesh, ndim: int = 2) -> NamedSharding:
    """Shard the batch dim over (data, fsdp); replicate other dims."""
    return NamedSharding(mesh, P(("data", "fsdp"), *([None] * (ndim - 1))))


def split_worker_groups(devices, n_workers: int):
    """Partition a rollout device group into `n_workers` equal per-worker
    sub-groups (the fleet's per-worker generation meshes,
    `RLConfig.rollout_workers > 1` + `rollout_devices > 0`).

    Groups are contiguous in id order. Device ids are slice-major on TPU
    pods (slice 0's chips number before slice 1's), so when the per-worker
    size divides the slice size each worker's collectives stay inside a
    slice (ICI); a per-worker group that straddles a slice boundary is
    warned — its own collectives would ride DCN every decode step. Workers
    joining beyond the initial cohort reuse the groups round-robin (worker
    id mod n_groups), so elastic membership never re-partitions silicon
    mid-run."""
    if n_workers < 1:
        raise ValueError(f"n_workers={n_workers} must be >= 1")
    if len(devices) % n_workers != 0:
        raise ValueError(
            f"rollout_devices={len(devices)} not divisible by "
            f"rollout_workers={n_workers} — every worker needs an "
            "identically-shaped generation mesh (one compiled executable "
            "serves the whole fleet)"
        )
    per = len(devices) // n_workers
    ordered = sorted(devices, key=lambda d: d.id)
    groups = [ordered[i * per:(i + 1) * per] for i in range(n_workers)]
    if all(hasattr(d, "slice_index") for d in devices):
        import warnings

        for i, g in enumerate(groups):
            slices = {d.slice_index for d in g}
            if len(slices) > 1:
                warnings.warn(
                    f"split_worker_groups: worker {i}'s device group spans "
                    f"slices {sorted(slices)} — its generation collectives "
                    "ride DCN every decode step. Pick rollout_workers so "
                    "the per-worker size divides the slice size.",
                    RuntimeWarning,
                    stacklevel=2,
                )
    return groups


def split_rollout_devices(devices, k: int):
    """(train_devices, rollout_devices): reserve `k` devices for generation.

    The disaggregated-rollout layout (trainer `rollout_devices`): training
    runs on one device group, generation on another, with one param sync
    per update crossing between them. On a multi-slice pod the reservation
    prefers WHOLE slices (highest slice_index first) so the rollout mesh's
    own collectives stay on ICI and only the param sync rides DCN; when no
    suffix of whole slices sums to `k` (or on hosts without slice_index,
    e.g. CPU test meshes) it falls back to the id-ordered tail — which on a
    MULTI-slice pod either spreads the rollout mesh over several slices
    (rollout collectives then ride DCN every decode step) or carves the
    rollout group out of one slice shared with training (train-mesh
    collectives straddle the cut); both are warned (ADVICE r5).
    Single-slice hosts warn about nothing — every link is ICI. The
    whole-slice reservation assumes HOMOGENEOUS slices (equal device
    counts per slice, the normal TPU pod shape); pick `k` as a multiple of
    the slice size to stay on the whole-slice path."""
    if not 0 < k < len(devices):
        raise ValueError(
            f"rollout_devices={k} must leave >=1 of {len(devices)} devices "
            "for training"
        )
    if all(hasattr(d, "slice_index") for d in devices):
        by_slice = {}
        for d in devices:
            by_slice.setdefault(d.slice_index, []).append(d)
        picked = []
        for s in sorted(by_slice, reverse=True):
            if len(picked) + len(by_slice[s]) > k:
                break
            picked.extend(by_slice[s])
        if len(picked) == k:
            picked_ids = {d.id for d in picked}
            train = [d for d in devices if d.id not in picked_ids]
            return (sorted(train, key=lambda d: d.id),
                    sorted(picked, key=lambda d: d.id))
    ordered = sorted(devices, key=lambda d: d.id)
    train, roll = ordered[:-k], ordered[-k:]
    if all(hasattr(d, "slice_index") for d in devices) \
            and len({d.slice_index for d in devices}) > 1:
        # multi-slice pod and the whole-slice reservation failed. Two
        # distinct fallout modes (single-slice hosts are skipped entirely —
        # every link there is ICI and there is nothing to warn about):
        import warnings

        roll_slices = {d.slice_index for d in roll}
        if len(roll_slices) > 1:
            # rollout mesh spans slices: its OWN collectives (and they run
            # every decode step) now cross DCN — the expensive case
            warnings.warn(
                f"split_rollout_devices: no suffix of whole slices sums to "
                f"k={k}; the id-ordered fallback spreads the rollout mesh "
                f"over slices {sorted(roll_slices)}, so rollout-mesh "
                "collectives ride DCN every decode step. Pick "
                "rollout_devices as a multiple of the slice size (the "
                "whole-slice reservation assumes homogeneous slices).",
                RuntimeWarning,
                stacklevel=2,
            )
        else:
            # rollout fits inside one slice (its collectives stay on ICI)
            # but that slice is split with training — the train mesh now
            # has a partial slice, skewing ITS collective topology
            warnings.warn(
                f"split_rollout_devices: no suffix of whole slices sums to "
                f"k={k}; the id-ordered fallback carves the rollout group "
                f"out of slice {sorted(roll_slices)}, leaving the TRAIN "
                "mesh a partial slice (its collectives straddle the cut). "
                "Rollout-internal collectives stay on ICI. Pick "
                "rollout_devices as a multiple of the slice size (the "
                "whole-slice reservation assumes homogeneous slices).",
                RuntimeWarning,
                stacklevel=2,
            )
    return train, roll
