"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

The reference has no sequence/context parallelism at all — it reaches 8k
tokens on one GPU purely by shape economy (SURVEY.md §2.3, §5.7). For a TPU
pod, long context is a first-class axis: the sequence dim is sharded over a
mesh axis and K/V shards rotate around the ring via `lax.ppermute` (one hop
per step, riding ICI), while each device keeps its Q shard and folds every
incoming K/V block into an online-softmax accumulator. Communication
overlaps compute; memory per device is O(T/n · T/n) per block instead of
O(T²).

`ring_attention` runs *inside* `shard_map` over the sequence axis. Causal
structure across shards follows global positions: a K/V chunk entirely in
the future contributes nothing (masked), the diagonal chunk applies the
in-chunk causal mask, past chunks attend fully.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def ring_attention(
    q: jnp.ndarray,           # [B, H, T_local, d]   (this device's Q shard)
    k: jnp.ndarray,           # [B, KV, T_local, d]  (this device's K shard)
    v: jnp.ndarray,           # [B, KV, T_local, d]
    key_valid: jnp.ndarray,   # [B, T_local] bool    (this device's mask shard)
    axis_name: str,
    causal: bool = True,
) -> jnp.ndarray:
    """Exact attention over the full (sharded) sequence. Returns [B,H,T_local,d]."""
    my_idx = jax.lax.axis_index(axis_name)
    n = jax.lax.psum(1, axis_name)
    B, H, T, d = q.shape
    KV = k.shape[1]
    G = H // KV
    scale = 1.0 / jnp.sqrt(jnp.float32(d))
    qg = q.astype(jnp.float32).reshape(B, KV, G, T, d)

    local_pos = jnp.arange(T)
    q_pos = my_idx * T + local_pos                       # global q positions

    # derive the accumulators from qg so they carry the same varying-axis
    # type as the rotated K/V (shard_map check_vma compatibility)
    m0 = jnp.zeros_like(qg[..., :1]) + NEG_INF
    l0 = jnp.zeros_like(qg[..., :1])
    acc0 = jnp.zeros_like(qg)

    def step(s, carry):
        m, l, acc, k_cur, v_cur, valid_cur = carry
        src = (my_idx - s) % n                           # owner of current K/V
        k_pos = src * T + local_pos                      # global k positions

        scores = jnp.einsum(
            "bkgqd,bktd->bkgqt", qg, k_cur.astype(jnp.float32)
        ) * scale                                        # [B,KV,G,T,T]
        mask = valid_cur[:, None, None, None, :]
        if causal:
            mask = mask & (q_pos[:, None] >= k_pos[None, :])[None, None, None, :, :]
        scores = jnp.where(mask, scores, NEG_INF)

        m_cur = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "bkgqt,bktd->bkgqd", p, v_cur.astype(jnp.float32)
        )

        # rotate K/V/mask one hop around the ring (device i -> i+1)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        valid_nxt = jax.lax.ppermute(valid_cur, axis_name, perm)
        return m_new, l_new, acc_new, k_nxt, v_nxt, valid_nxt

    m, l, acc, *_ = jax.lax.fori_loop(0, n, step, (m0, l0, acc0, k, v, key_valid))
    out = acc / jnp.maximum(l, 1e-30)
    return out.reshape(B, H, T, d).astype(q.dtype)
