"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

The reference has no sequence/context parallelism at all — it reaches 8k
tokens on one GPU purely by shape economy (SURVEY.md §2.3, §5.7). For a TPU
pod, long context is a first-class axis: the sequence dim is sharded over a
mesh axis and K/V shards rotate around the ring via `lax.ppermute` (one hop
per step, riding ICI), while each device keeps its Q shard and folds every
incoming K/V block into an online-softmax accumulator. Communication
overlaps compute; memory per device is O(T/n · T/n) per block instead of
O(T²).

`ring_attention` runs *inside* `shard_map` over the sequence axis. Causal
structure across shards follows global positions: a K/V chunk entirely in
the future contributes nothing (masked), the diagonal chunk applies the
in-chunk causal mask, past chunks attend fully.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def ring_attention(
    q: jnp.ndarray,           # [B, H, T_local, d]   (this device's Q shard)
    k: jnp.ndarray,           # [B, KV, T_local, d]  (this device's K shard)
    v: jnp.ndarray,           # [B, KV, T_local, d]
    key_valid: jnp.ndarray,   # [B, T_local] bool    (this device's mask shard)
    axis_name: str,
    causal: bool = True,
) -> jnp.ndarray:
    """Exact attention over the full (sharded) sequence. Returns [B,H,T_local,d]."""
    my_idx = jax.lax.axis_index(axis_name)
    n = jax.lax.psum(1, axis_name)
    B, H, T, d = q.shape
    KV = k.shape[1]
    G = H // KV
    scale = 1.0 / jnp.sqrt(jnp.float32(d))
    qg = q.astype(jnp.float32).reshape(B, KV, G, T, d)

    local_pos = jnp.arange(T)
    q_pos = my_idx * T + local_pos                       # global q positions

    # derive the accumulators from qg so they carry the same varying-axis
    # type as the rotated K/V (shard_map check_vma compatibility)
    m0 = jnp.zeros_like(qg[..., :1]) + NEG_INF
    l0 = jnp.zeros_like(qg[..., :1])
    acc0 = jnp.zeros_like(qg)

    def step(s, carry):
        m, l, acc, k_cur, v_cur, valid_cur = carry
        src = (my_idx - s) % n                           # owner of current K/V
        k_pos = src * T + local_pos                      # global k positions

        scores = jnp.einsum(
            "bkgqd,bktd->bkgqt", qg, k_cur.astype(jnp.float32)
        ) * scale                                        # [B,KV,G,T,T]
        mask = valid_cur[:, None, None, None, :]
        if causal:
            mask = mask & (q_pos[:, None] >= k_pos[None, :])[None, None, None, :, :]
        scores = jnp.where(mask, scores, NEG_INF)

        m_cur = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "bkgqt,bktd->bkgqd", p, v_cur.astype(jnp.float32)
        )

        # rotate K/V/mask one hop around the ring (device i -> i+1)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        valid_nxt = jax.lax.ppermute(valid_cur, axis_name, perm)
        return m_new, l_new, acc_new, k_nxt, v_nxt, valid_nxt

    m, l, acc, *_ = jax.lax.fori_loop(0, n, step, (m0, l0, acc0, k, v, key_valid))
    out = acc / jnp.maximum(l, 1e-30)
    return out.reshape(B, H, T, d).astype(q.dtype)


_LSE_FLOOR = -1e30  # stands in for log(0): keeps exp(l - max) finite


def _merge_partials(o1, l1, o2, l2):
    """Combine two flash partials over the SAME queries, flash-decoding
    style: each is (normalized out, logsumexp); the merged pair reweights by
    exp(lse − max)."""
    m = jnp.maximum(l1, l2)
    w1 = jnp.exp(l1 - m)
    w2 = jnp.exp(l2 - m)
    den = w1 + w2                                     # ≥ 1 (max term is 1)
    o = (o1 * w1[..., None] + o2 * w2[..., None]) / den[..., None]
    return o, m + jnp.log(den)


def _ring_flash_fwd_loop(q, k, v, key_valid, axis_name, causal, block_q,
                         block_k):
    """The flash ring forward: per-chunk Pallas kernel + lse merge. Returns
    (out_f32 [B,H,T,d], lse [B,H,T] f32) — lse is the GLOBAL logsumexp over
    the full (sharded) sequence, the backward residual."""
    from nanorlhf_tpu.ops.attention import (
        _flash_forward,
        _interpret_default,
        block_and_pad,
    )

    my_idx = jax.lax.axis_index(axis_name)
    n = jax.lax.psum(1, axis_name)
    B, H, T, d = q.shape
    interpret = _interpret_default()
    block, T_pad = block_and_pad(block_q, block_k, T)
    q_pad = q
    if T_pad != T:
        q_pad = jnp.pad(q, [(0, 0), (0, 0), (0, T_pad - T), (0, 0)])

    def chunk(causal_chunk, k_cur, v_cur, valid_cur):
        if T_pad != T:
            pad = [(0, 0), (0, 0), (0, T_pad - T), (0, 0)]
            k_cur = jnp.pad(k_cur, pad)
            v_cur = jnp.pad(v_cur, pad)
            valid_cur = jnp.pad(valid_cur, [(0, 0), (0, T_pad - T)])
        out, lse = _flash_forward(q_pad, k_cur, v_cur, valid_cur,
                                  causal=causal_chunk, block_q=block,
                                  block_k=block, interpret=interpret)
        out = out[:, :, :T, :]
        lse = jnp.maximum(lse[..., 0][:, :, :T], _LSE_FLOOR)  # de-lane, floor
        return out.astype(jnp.float32), lse

    def skip(k_cur, v_cur, valid_cur):
        return (jnp.zeros(q.shape, jnp.float32),
                jnp.full((B, H, T), _LSE_FLOOR, jnp.float32))

    def step(s, carry):
        o_acc, l_acc, k_cur, v_cur, valid_cur = carry
        src = (my_idx - s) % n                        # owner of current K/V
        # 0 = future (skip), 1 = past (full attention), 2 = diagonal (causal)
        branch = jnp.where(src == my_idx, 2,
                           jnp.where(src < my_idx, 1, 0)) if causal else \
            jnp.int32(1)
        o_i, l_i = jax.lax.switch(
            branch,
            [skip,
             lambda k_, v_, m_: chunk(False, k_, v_, m_),
             lambda k_, v_, m_: chunk(True, k_, v_, m_)],
            k_cur, v_cur, valid_cur,
        )
        o_acc, l_acc = _merge_partials(o_acc, l_acc, o_i, l_i)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        valid_nxt = jax.lax.ppermute(valid_cur, axis_name, perm)
        return o_acc, l_acc, k_nxt, v_nxt, valid_nxt

    o0 = jnp.zeros(q.shape, jnp.float32)
    l0 = jnp.full((B, H, T), _LSE_FLOOR, jnp.float32)
    o, lse, *_ = jax.lax.fori_loop(0, n, step, (o0, l0, k, v, key_valid))
    return o, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _ring_flash_core(q, k, v, key_valid, axis_name, causal, block_q, block_k):
    o, _ = _ring_flash_fwd_loop(q, k, v, key_valid, axis_name, causal,
                                block_q, block_k)
    return o.astype(q.dtype)


def _ring_core_fwd(q, k, v, key_valid, axis_name, causal, block_q, block_k):
    o, lse = _ring_flash_fwd_loop(q, k, v, key_valid, axis_name, causal,
                                  block_q, block_k)
    out = o.astype(q.dtype)
    # `out` is saved in the RETURNED dtype so the backward's delta
    # (Σ dO·O) uses the same values downstream gradients were computed from
    return out, (q, k, v, key_valid, out, lse)


def _ring_core_bwd(axis_name, causal, block_q, block_k, residuals, g):
    """Ring backward with the fused Pallas flash-bwd kernels per chunk.

    FlashAttention-2's backward identity with the GLOBAL lse:
    p_chunk = exp(s_chunk − lse_global) is the true attention probability of
    this chunk's keys, so each ring step runs `ops.attention._flash_backward`
    on (my Q shard, visiting K/V chunk) with the global (out, lse, dO) and
    yields exact dq contributions (summed locally) and the chunk's dk/dv
    (accumulated in f32 carried around the ring WITH the chunk — after n
    hops both land back on the chunk's owner). The O(T_local²) f32 score
    tensor of the einsum ring never materializes in either direction.
    """
    from nanorlhf_tpu.ops.attention import (
        _LANES,
        _flash_backward,
        _interpret_default,
        block_and_pad,
    )

    q, k, v, key_valid, out, lse = residuals
    my_idx = jax.lax.axis_index(axis_name)
    n = jax.lax.psum(1, axis_name)
    B, H, T, d = q.shape
    KV = k.shape[1]
    interpret = _interpret_default()
    block, T_pad = block_and_pad(block_q, block_k, T)

    pad4 = [(0, 0), (0, 0), (0, T_pad - T), (0, 0)]
    q_pad, out_pad, g_pad, lse_pad = q, out, g, lse
    if T_pad != T:
        q_pad = jnp.pad(q, pad4)
        out_pad = jnp.pad(out, pad4)
        # g stays f32 (pad only): the bwd kernels cast operands internally,
        # and the single-device path feeds them the f32 cotangent — casting
        # here made ring gradients differ at bf16-rounding level
        g_pad = jnp.pad(g, pad4)
        lse_pad = jnp.pad(lse, [(0, 0), (0, 0), (0, T_pad - T)])
    # the bwd kernels read lse lane-expanded (ops/attention.py layout)
    lse_lanes = jnp.broadcast_to(
        lse_pad[..., None], (B, H, T_pad, _LANES)
    ).astype(jnp.float32)

    def chunk_bwd(causal_chunk, k_cur, v_cur, valid_cur):
        if T_pad != T:
            k_cur = jnp.pad(k_cur, pad4)
            v_cur = jnp.pad(v_cur, pad4)
            valid_cur = jnp.pad(valid_cur, [(0, 0), (0, T_pad - T)])
        dq_c, dk_c, dv_c = _flash_backward(
            q_pad, k_cur, v_cur, valid_cur, out_pad, lse_lanes, g_pad,
            causal_chunk, block, block, interpret,
        )
        return (dq_c[:, :, :T].astype(jnp.float32),
                dk_c[:, :, :T].astype(jnp.float32),
                dv_c[:, :, :T].astype(jnp.float32))

    def skip_bwd(k_cur, v_cur, valid_cur):
        return (jnp.zeros((B, H, T, d), jnp.float32),
                jnp.zeros((B, KV, T, d), jnp.float32),
                jnp.zeros((B, KV, T, d), jnp.float32))

    def step(s, carry):
        dq_acc, dk_rot, dv_rot, k_cur, v_cur, valid_cur = carry
        src = (my_idx - s) % n
        branch = jnp.where(src == my_idx, 2,
                           jnp.where(src < my_idx, 1, 0)) if causal else \
            jnp.int32(1)
        dq_i, dk_i, dv_i = jax.lax.switch(
            branch,
            [skip_bwd,
             lambda k_, v_, m_: chunk_bwd(False, k_, v_, m_),
             lambda k_, v_, m_: chunk_bwd(True, k_, v_, m_)],
            k_cur, v_cur, valid_cur,
        )
        dq_acc = dq_acc + dq_i
        dk_rot = dk_rot + dk_i
        dv_rot = dv_rot + dv_i
        # rotate the chunk AND its gradient accumulators together: after n
        # hops the (k, v, dk, dv) quadruple is back at the chunk's owner
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        valid_nxt = jax.lax.ppermute(valid_cur, axis_name, perm)
        dk_nxt = jax.lax.ppermute(dk_rot, axis_name, perm)
        dv_nxt = jax.lax.ppermute(dv_rot, axis_name, perm)
        return dq_acc, dk_nxt, dv_nxt, k_nxt, v_nxt, valid_nxt

    dq0 = jnp.zeros((B, H, T, d), jnp.float32)
    dkv0 = jnp.zeros((B, KV, T, d), jnp.float32)
    dq, dk, dv, *_ = jax.lax.fori_loop(
        0, n, step, (dq0, dkv0, dkv0, k, v, key_valid)
    )
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype), None)


_ring_flash_core.defvjp(_ring_core_fwd, _ring_core_bwd)


def ring_attention_flash(
    q: jnp.ndarray,           # [B, H, T_local, d]
    k: jnp.ndarray,           # [B, KV, T_local, d]
    v: jnp.ndarray,           # [B, KV, T_local, d]
    key_valid: jnp.ndarray,   # [B, T_local] bool
    axis_name: str,
    causal: bool = True,
    block_q: int = 512,
    block_k: int = 512,
) -> jnp.ndarray:
    """Ring attention with the Pallas flash kernel per chunk — differentiable.

    Each ring step runs the flash kernel on (my Q shard, incoming K/V chunk)
    and merges the per-chunk (out, lse) partials flash-decoding style — the
    O(T_local²) f32 score tensor of the einsum ring never materializes, and
    the chunk attention itself rides the MXU-tuned kernel (21× the XLA
    einsum at 8k on v5e). Chunk causality follows global positions: the
    diagonal chunk is in-kernel causal, past chunks attend fully, future
    chunks are skipped outright (three lax.switch branches).

    The backward (`_ring_core_bwd`) re-runs the ring through the fused
    Pallas flash-bwd kernels with the global lse, so both the SP scoring
    pass and the SP update pass can use the same kernels — no
    scoring/update kernel-mismatch bias in exp(new−old) ratios (ADVICE r3).
    `NANORLHF_FLASH_BWD=xla` is not consulted here (chunk backwards need
    the global-lse form only the Pallas kernels expose); use
    `attn_impl="xla"` to route the whole ring to the einsum path instead.
    """
    return _ring_flash_core(q, k, v, key_valid, axis_name, causal,
                            block_q, block_k)
