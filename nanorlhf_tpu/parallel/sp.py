"""Sequence-parallel model forward: the whole decoder under shard_map.

Long-context as a first-class axis (the reference has none — SURVEY.md §5.7):
the sequence dimension is sharded over a mesh axis; attention runs as ring
attention (K/V rotating over ICI, `parallel/ring_attention.py`) while RMSNorm,
RoPE, projections and the MLP are position-local and need no communication.
Per-device memory for activations and attention state scales with T/n instead
of T, so contexts beyond a single device's HBM become trainable/scoreable.

Caveats (v1):
- `position_ids` must be precomputed globally and passed in sharded (the
  left-pad `cumsum` is a cross-shard scan, so it stays outside);
- the logit head runs locally per shard (vocab projection is position-local);
- sampling still uses the single-shard KV-cache path; SP targets the
  training/scoring passes where the O(T) activations live;
- **params are closure-captured and therefore replicated over the sp mesh** —
  use a dedicated sequence-parallel mesh. Composing SP with fsdp-sharded
  params (so an fsdp×sp mesh never gathers the full tree per device) is a
  planned follow-up (docs/ROADMAP.md).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from nanorlhf_tpu.core.config import ModelConfig
from nanorlhf_tpu.core.model import _layer_body, _logits, rope_tables
from nanorlhf_tpu.parallel.ring_attention import ring_attention


def _sp_layer_body(config: ModelConfig, x, layer_params, cos, sin, key_valid,
                   axis_name, lora_layer=None, lora_scale=1.0):
    """One decoder layer on a sequence shard — the shared `_layer_body` with
    its attention contraction routed around the ring."""

    def ring_attn(q, k, v):
        return ring_attention(q, k, v, key_valid, axis_name=axis_name, causal=True)

    y, _ = _layer_body(config, x, layer_params, cos, sin, mask=None,
                       kv_cache=None, cache_index=0, lora_layer=lora_layer,
                       lora_scale=lora_scale, attn_fn=ring_attn)
    return y


def _sp_forward_local(params, config: ModelConfig, input_ids, attention_mask,
                      position_ids, axis_name, lora_scale, remat):
    """Runs inside shard_map: all [B, T_local] shards of the global batch."""
    attention_mask = attention_mask.astype(bool)
    x = params["embed_tokens"][jnp.where(attention_mask, input_ids, 0)].astype(
        params["embed_tokens"].dtype
    )
    cos, sin = rope_tables(position_ids, config.actual_head_dim, config.rope_theta)
    lora_layers = params.get("lora", {}).get("layers")

    def body(carry, inp):
        layer_params, lora_layer = inp
        y = _sp_layer_body(config, carry, layer_params, cos, sin, attention_mask,
                           axis_name, lora_layer, lora_scale)
        return y, None

    if remat:
        body = jax.checkpoint(body)
    x, _ = jax.lax.scan(body, x, (params["layers"], lora_layers))
    return _logits(config, params, x)


def sp_forward_logits(
    params: dict,
    config: ModelConfig,
    input_ids: jnp.ndarray,       # [B, T] global (T divisible by the sp axis)
    attention_mask: jnp.ndarray,  # [B, T]
    position_ids: jnp.ndarray,    # [B, T] global positions
    mesh: Mesh,
    axis_name: str = "sp",
    lora_scale: float = 1.0,
    remat: bool = False,
) -> jnp.ndarray:
    """Full-model forward with the sequence dim sharded over `axis_name`.

    Returns global logits [B, T, V] (sharded over T on the mesh).
    """
    fn = shard_map(
        partial(
            _sp_forward_local, params, config,
            axis_name=axis_name, lora_scale=lora_scale, remat=remat,
        ),
        mesh=mesh,
        in_specs=(P(None, axis_name), P(None, axis_name), P(None, axis_name)),
        out_specs=P(None, axis_name, None),
    )
    return fn(input_ids, attention_mask, position_ids)
