"""Sequence-parallel model forward: the whole decoder under shard_map.

Long-context as a first-class axis (the reference has none — SURVEY.md §5.7):
the sequence dimension is sharded over a mesh axis; attention runs as ring
attention (K/V rotating over ICI, `parallel/ring_attention.py`) while RMSNorm,
RoPE, projections and the MLP are position-local and need no communication.
Per-device memory for activations and attention state scales with T/n instead
of T, so contexts beyond a single device's HBM become trainable/scoreable.

Caveats (v1):
- `position_ids` must be precomputed globally and passed in sharded (the
  left-pad `cumsum` is a cross-shard scan, so it stays outside);
- the logit head runs locally per shard (vocab projection is position-local);
- sampling still uses the single-shard KV-cache path; SP targets the
  training/scoring passes where the O(T) activations live;
- `sp_forward_logits` closure-captures params (replicated over the sp mesh):
  right for dedicated-SP meshes. For fsdp×sp meshes use
  `sp_fsdp_forward_logits` / `sp_score_logprobs(fsdp_axis=...)` below —
  params stay sharded at rest and gather one layer at a time.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from nanorlhf_tpu.utils.shardmap_compat import shard_map

from nanorlhf_tpu.core.config import ModelConfig
from nanorlhf_tpu.core.model import _hidden_from_inputs, _logits, use_flash
from nanorlhf_tpu.parallel.ring_attention import (
    ring_attention,
    ring_attention_flash,
)


def _ring_attn_fn(key_valid, axis_name, attn_impl: str, t_local: int):
    """Pick the ring implementation: the Pallas flash ring
    (`ring_attention_flash`, differentiable via its global-lse custom_vjp)
    when `attn_impl` resolves to flash at this local width, the einsum ring
    otherwise. Scoring and update callers should pass the SAME attn_impl so
    exp(new−old) ratios carry no kernel-mismatch offset (ADVICE r3)."""
    if use_flash(attn_impl, t_local):
        return lambda q, k, v: ring_attention_flash(
            q, k, v, key_valid, axis_name=axis_name, causal=True
        )
    return lambda q, k, v: ring_attention(
        q, k, v, key_valid, axis_name=axis_name, causal=True
    )


def _sp_hidden_local(params, config: ModelConfig, input_ids, attention_mask,
                     position_ids, axis_name, lora_scale, remat,
                     attn_impl: str = "xla"):
    """Runs inside shard_map: the shared forward recipe up to the final
    hidden states, attention routed around the ring."""
    key_valid = attention_mask.astype(bool)
    ring_attn = _ring_attn_fn(key_valid, axis_name, attn_impl,
                              input_ids.shape[1])
    return _hidden_from_inputs(
        params, config, jnp.where(key_valid, input_ids, 0), attention_mask,
        position_ids, lora_scale, remat, attn_fn=ring_attn,
    )


def _sp_forward_local(params, config: ModelConfig, input_ids, attention_mask,
                      position_ids, axis_name, lora_scale, remat,
                      attn_impl: str = "xla"):
    """Hidden states → vocab logits (no duplicated embed/scan logic)."""
    x = _sp_hidden_local(params, config, input_ids, attention_mask,
                         position_ids, axis_name, lora_scale, remat, attn_impl)
    return _logits(config, params, x)


def sp_forward_logits(
    params: dict,
    config: ModelConfig,
    input_ids: jnp.ndarray,       # [B, T] global (T divisible by the sp axis)
    attention_mask: jnp.ndarray,  # [B, T]
    position_ids: jnp.ndarray,    # [B, T] global positions
    mesh: Mesh,
    axis_name: str = "sp",
    lora_scale: float = 1.0,
    remat: bool = False,
) -> jnp.ndarray:
    """Full-model forward with the sequence dim sharded over `axis_name`.

    Returns global logits [B, T, V] (sharded over T on the mesh).
    """
    fn = shard_map(
        partial(
            _sp_forward_local, params, config,
            axis_name=axis_name, lora_scale=lora_scale, remat=remat,
        ),
        mesh=mesh,
        in_specs=(P(None, axis_name), P(None, axis_name), P(None, axis_name)),
        out_specs=P(None, axis_name, None),
    )
    return fn(input_ids, attention_mask, position_ids)


# ---------------------------------------------------------------------------
# SP × FSDP: params sharded at rest, gathered per layer inside the scan
# ---------------------------------------------------------------------------


def _fsdp_specs(params, fsdp_axis: str):
    """Per-leaf PartitionSpecs for this mesh: keep the fsdp placements from
    the framework's sharding rules, drop the (absent) tensor axis."""
    from nanorlhf_tpu.parallel.mesh import param_sharding_rules

    rules = param_sharding_rules(params)

    def remap(spec):
        return P(*[fsdp_axis if a == "fsdp" else None for a in spec])

    return jax.tree.map(remap, rules, is_leaf=lambda x: isinstance(x, P))


def _gather_by_spec(tree, specs, axis_name: str, skip_leading_dim: bool = False):
    """all_gather each leaf along the dims its spec marks as fsdp-sharded.

    `skip_leading_dim=True` for per-layer slices inside the scan: their spec
    still names the stacked [L, ...] layout, whose leading dim the scan has
    already consumed.
    """

    def gather(leaf, spec):
        dims = list(spec)
        if skip_leading_dim:
            dims = dims[1:]
        for dim, ax in enumerate(dims):
            if ax == axis_name:
                leaf = jax.lax.all_gather(leaf, axis_name, axis=dim, tiled=True)
        return leaf

    return jax.tree.map(gather, tree, specs)


def _sp_fsdp_forward_local(config, specs, sp_axis, fsdp_axis, lora_scale, remat,
                           params_local, input_ids, attention_mask, position_ids,
                           attn_impl: str = "xla", head: str = "lm"):
    """Inside shard_map over (fsdp, sp): sequence shard local, params shards
    gathered — embeddings up front (the lookup needs them), layer leaves one
    scan step at a time via the shared recipe's `layer_transform` hook, the
    lm_head lazily after the scan (ZeRO-3 execution model). Gradients flow
    back through all_gather's transpose (reduce-scatter), so grads come out
    sharded exactly like the params."""
    key_valid = attention_mask.astype(bool)
    ring_attn = _ring_attn_fn(key_valid, sp_axis, attn_impl,
                              input_ids.shape[1])

    lora_specs = specs.get("lora", {}).get("layers")

    def gather_layer(layer_local, lora_local):
        layer_full = _gather_by_spec(
            layer_local, specs["layers"], fsdp_axis, skip_leading_dim=True
        )
        lora_full = (
            _gather_by_spec(lora_local, lora_specs, fsdp_axis, skip_leading_dim=True)
            if lora_local is not None else None
        )
        return layer_full, lora_full

    embed_full = _gather_by_spec(
        params_local["embed_tokens"], specs["embed_tokens"], fsdp_axis
    )
    params_mixed = {**params_local, "embed_tokens": embed_full}
    x = _hidden_from_inputs(
        params_mixed, config, jnp.where(key_valid, input_ids, 0), attention_mask,
        position_ids, lora_scale, remat, attn_fn=ring_attn,
        layer_transform=gather_layer,
    )
    norm_full = _gather_by_spec(params_local["norm"], specs["norm"], fsdp_axis)
    if head == "score":
        # value/RM head: final-normed hidden @ score — position-local, no
        # cross-shard traffic (matches core.model.score_forward)
        from nanorlhf_tpu.core.model import rms_norm

        x = rms_norm(x, norm_full, config.rms_norm_eps)
        score = _gather_by_spec(
            params_local["score"], specs["score"], fsdp_axis
        )
        return x.astype(jnp.float32) @ score.astype(jnp.float32)
    # lm_head / final norm gathered only now (tied models reuse embed_full)
    head_tree = {"embed_tokens": embed_full, "norm": norm_full}
    if not config.tie_word_embeddings:
        head_tree["lm_head"] = _gather_by_spec(
            params_local["lm_head"], specs["lm_head"], fsdp_axis
        )
    return _logits(config, head_tree, x)


def sp_score_logprobs(
    params: dict,
    config: ModelConfig,
    query_responses: jnp.ndarray,   # [B, T] global, T divisible by sp axis
    pad_token_id: int,
    temperature: float,
    mesh: Mesh,
    sp_axis: str = "sp",
    fsdp_axis: str | None = None,
    lora_scale: float = 1.0,
    remat: bool = False,
    with_entropy: bool = False,
    entropy_from_position: int = 0,
    attn_impl: str = "xla",
) -> jnp.ndarray:
    """Per-position next-token logprobs [B, T] under sequence parallelism —
    the scoring primitive for beyond-one-device contexts (the RL logprob
    pass, `/root/reference/GRPO/grpo_trainer.py:534-556`, at ring scale).

    Entry t holds log p(token_{t+1} | tokens_{<=t}); the final position is 0
    (no next token). Labels cross shard boundaries, so each shard fetches its
    right neighbor's first token via ppermute. Callers slice
    `[:, ctx-1:T-1]` for response logprobs exactly as in the single-device
    path. `fsdp_axis` switches the underlying forward to the
    params-sharded-at-rest variant. `remat` checkpoints per-layer activations
    — pass the trainer's gradient_checkpointing when differentiating through
    this (scoring-only callers can leave it off).

    `attn_impl` routes the ring: "auto"/"pallas" engage the flash ring
    (`ring_attention_flash`) per `use_flash` resolution. Both rings are
    differentiable (the flash ring's backward re-runs the ring through the
    Pallas flash-bwd kernels with the global lse) — scoring and update
    passes should use the SAME impl so the ratio/KL estimates carry no
    kernel-mismatch offset.

    `with_entropy=True` additionally returns the unmasked-mean entropy of
    the temperature-scaled logits (the reference's `policy/entropy_avg_new`
    stat, `GRPO/grpo_trainer.py:679-687`): each shard's logits are
    full-vocab, so per-position entropy is local and the global mean is one
    psum over the sp axis — the global [B, T, V] logits never materialize.
    The mean spans global positions [entropy_from_position, T-1) — callers
    pass `context_length - 1` so the scope matches the dense path, whose
    logits cover only the response region (`padded_forward_logits`'s
    `response_context_length` slice); prompt positions have systematically
    lower entropy on a trained model and must not dilute the stat.

    Unaffected by `cfg.fused_logprob` (ops/fused_logprob.py, the dense
    paths' chunked linear-cross-entropy): the per-shard logits block here is
    already [B, T/sp, V]-local, reduced to per-token scalars inside the
    shard_map body before anything global assembles — sequence parallelism
    IS this path's logits-memory mitigation, scaling with the ring width.
    Row-chunking the local head would compose with it but only pays off once
    T/sp alone exceeds the fused chunk budget.
    """
    from nanorlhf_tpu.core.model import padding_inputs
    from nanorlhf_tpu.ops.masking import (
        entropy_from_logits,
        guard_temperature,
        logprobs_from_logits,
    )

    _, attention_mask, position_ids = padding_inputs(query_responses, pad_token_id)
    attention_mask = attention_mask.astype(jnp.int32)

    n_sp = mesh.shape[sp_axis]

    T_global = query_responses.shape[1]

    def local_score(logits_local, ids_local):
        # label for local position t = ids[t+1]; last local label comes from
        # the right neighbor's first token (left rotation around the ring)
        perm = [(i, (i - 1) % n_sp) for i in range(n_sp)]
        from_right = jax.lax.ppermute(ids_local[:, :1], sp_axis, perm)
        labels = jnp.concatenate([ids_local[:, 1:], from_right], axis=1)
        lp = logprobs_from_logits(logits_local, labels, temperature)
        if not with_entropy:
            return lp
        # response-region scope: global positions [from, T-1) — same span
        # the dense path's response_context_length slice covers
        t_local = logits_local.shape[1]
        gpos = jax.lax.axis_index(sp_axis) * t_local + jnp.arange(t_local)
        in_span = (gpos >= entropy_from_position) & (gpos < T_global - 1)
        ent_pos = jax.lax.stop_gradient(entropy_from_logits(
            logits_local.astype(jnp.float32) / guard_temperature(temperature)
        ))                                             # [B, T_local]
        s = jax.lax.psum((ent_pos * in_span[None, :]).sum(), sp_axis)
        c = jax.lax.psum(
            (in_span.sum() * ent_pos.shape[0]).astype(jnp.float32), sp_axis
        )
        return lp, s / jnp.maximum(c, 1.0)

    out_specs = (P(None, sp_axis), P()) if with_entropy else P(None, sp_axis)

    if fsdp_axis is not None:
        specs = _fsdp_specs(params, fsdp_axis)

        def fn(params_local, ids, mask, pos):
            logits = _sp_fsdp_forward_local(
                config, specs, sp_axis, fsdp_axis, lora_scale, remat,
                params_local, ids, mask, pos, attn_impl=attn_impl,
            )
            return local_score(logits, ids)

        out = shard_map(
            fn, mesh=mesh,
            in_specs=(specs, P(None, sp_axis), P(None, sp_axis), P(None, sp_axis)),
            out_specs=out_specs,
            check_vma=False,
        )(params, query_responses, attention_mask, position_ids)
    else:
        def fn(ids, mask, pos):
            logits = _sp_forward_local(
                params, config, ids, mask, pos,
                axis_name=sp_axis, lora_scale=lora_scale, remat=remat,
                attn_impl=attn_impl,
            )
            return local_score(logits, ids)

        out = shard_map(
            fn, mesh=mesh,
            in_specs=(P(None, sp_axis), P(None, sp_axis), P(None, sp_axis)),
            out_specs=out_specs,
            check_vma=False,
        )(query_responses, attention_mask, position_ids)
    lp, ent = out if with_entropy else (out, None)
    # final global position has no next token
    lp = lp.at[:, -1].set(0.0)
    return (lp, ent) if with_entropy else lp


def sp_score_values(
    params: dict,
    config: ModelConfig,
    query_responses: jnp.ndarray,   # [B, T] global, T divisible by sp axis
    pad_token_id: int,
    mesh: Mesh,
    sp_axis: str = "sp",
    fsdp_axis: str | None = None,
    lora_scale: float = 1.0,
    remat: bool = False,
    attn_impl: str = "xla",
) -> jnp.ndarray:
    """Per-position value/RM scores [B, T, num_labels] under sequence
    parallelism — `core.model.score_forward` at ring scale (the PPO value
    pass, `PPO/ppo_trainer.py:630-634,732`, for beyond-one-device contexts).
    The score head is position-local, so unlike logprob scoring nothing
    crosses shard boundaries after the ring. Differentiable with either
    ring impl; the PPO update should score and differentiate with the same
    `attn_impl` as the value-scoring pass."""
    from nanorlhf_tpu.core.model import padding_inputs, rms_norm

    _, attention_mask, position_ids = padding_inputs(query_responses, pad_token_id)
    attention_mask = attention_mask.astype(jnp.int32)

    if fsdp_axis is not None:
        specs = _fsdp_specs(params, fsdp_axis)
        fn = partial(_sp_fsdp_forward_local, config, specs, sp_axis,
                     fsdp_axis, lora_scale, remat, attn_impl=attn_impl,
                     head="score")
        return shard_map(
            fn, mesh=mesh,
            in_specs=(specs, P(None, sp_axis), P(None, sp_axis), P(None, sp_axis)),
            out_specs=P(None, sp_axis, None),
            check_vma=False,
        )(params, query_responses, attention_mask, position_ids)

    def fn(ids, mask, pos):
        x = _sp_hidden_local(params, config, ids, mask, pos,
                             axis_name=sp_axis, lora_scale=lora_scale,
                             remat=remat, attn_impl=attn_impl)
        x = rms_norm(x, params["norm"], config.rms_norm_eps)
        return x.astype(jnp.float32) @ params["score"].astype(jnp.float32)

    return shard_map(
        fn, mesh=mesh,
        in_specs=(P(None, sp_axis), P(None, sp_axis), P(None, sp_axis)),
        out_specs=P(None, sp_axis, None),
        check_vma=False,
    )(query_responses, attention_mask, position_ids)


def sp_fsdp_forward_logits(
    params: dict,
    config: ModelConfig,
    input_ids: jnp.ndarray,
    attention_mask: jnp.ndarray,
    position_ids: jnp.ndarray,
    mesh: Mesh,
    sp_axis: str = "sp",
    fsdp_axis: str = "fsdp",
    lora_scale: float = 1.0,
    remat: bool = False,
) -> jnp.ndarray:
    """Sequence-parallel forward with fsdp-sharded parameters (roadmap #7).

    Params enter through shard_map in_specs with the framework's fsdp
    placements — sharded at rest, all-gathered one layer at a time inside the
    scan — while the sequence dim shards over `sp_axis`. Peak param memory
    per device ≈ params/n_fsdp + one full layer + the full embedding table
    (and, for untied models, the lm_head while computing logits) — the
    embedding must be whole for the lookup and the head for the projection.
    """
    specs = _fsdp_specs(params, fsdp_axis)
    fn = shard_map(
        partial(_sp_fsdp_forward_local, config, specs, sp_axis, fsdp_axis,
                lora_scale, remat),
        mesh=mesh,
        in_specs=(specs, P(None, sp_axis), P(None, sp_axis), P(None, sp_axis)),
        out_specs=P(None, sp_axis, None),
        # logits are fsdp-replicated by construction (every member gathered
        # identical weights), which vma inference can't prove through
        # all_gather — the parity tests assert it instead
        check_vma=False,
    )
    return fn(params, input_ids, attention_mask, position_ids)
