"""Sequence-parallel model forward: the whole decoder under shard_map.

Long-context as a first-class axis (the reference has none — SURVEY.md §5.7):
the sequence dimension is sharded over a mesh axis; attention runs as ring
attention (K/V rotating over ICI, `parallel/ring_attention.py`) while RMSNorm,
RoPE, projections and the MLP are position-local and need no communication.
Per-device memory for activations and attention state scales with T/n instead
of T, so contexts beyond a single device's HBM become trainable/scoreable.

Caveats (v1):
- `position_ids` must be precomputed globally and passed in sharded (the
  left-pad `cumsum` is a cross-shard scan, so it stays outside);
- the logit head runs locally per shard (vocab projection is position-local);
- sampling still uses the single-shard KV-cache path; SP targets the
  training/scoring passes where the O(T) activations live;
- **params are closure-captured and therefore replicated over the sp mesh** —
  use a dedicated sequence-parallel mesh. Composing SP with fsdp-sharded
  params (so an fsdp×sp mesh never gathers the full tree per device) is a
  planned follow-up (docs/ROADMAP.md).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from nanorlhf_tpu.core.config import ModelConfig
from nanorlhf_tpu.core.model import _hidden_from_inputs, _logits
from nanorlhf_tpu.parallel.ring_attention import ring_attention


def _sp_forward_local(params, config: ModelConfig, input_ids, attention_mask,
                      position_ids, axis_name, lora_scale, remat):
    """Runs inside shard_map: the shared forward recipe with the attention
    contraction routed around the ring (no duplicated embed/scan logic)."""
    key_valid = attention_mask.astype(bool)

    def ring_attn(q, k, v):
        return ring_attention(q, k, v, key_valid, axis_name=axis_name, causal=True)

    x = _hidden_from_inputs(
        params, config, jnp.where(key_valid, input_ids, 0), attention_mask,
        position_ids, lora_scale, remat, attn_fn=ring_attn,
    )
    return _logits(config, params, x)


def sp_forward_logits(
    params: dict,
    config: ModelConfig,
    input_ids: jnp.ndarray,       # [B, T] global (T divisible by the sp axis)
    attention_mask: jnp.ndarray,  # [B, T]
    position_ids: jnp.ndarray,    # [B, T] global positions
    mesh: Mesh,
    axis_name: str = "sp",
    lora_scale: float = 1.0,
    remat: bool = False,
) -> jnp.ndarray:
    """Full-model forward with the sequence dim sharded over `axis_name`.

    Returns global logits [B, T, V] (sharded over T on the mesh).
    """
    fn = shard_map(
        partial(
            _sp_forward_local, params, config,
            axis_name=axis_name, lora_scale=lora_scale, remat=remat,
        ),
        mesh=mesh,
        in_specs=(P(None, axis_name), P(None, axis_name), P(None, axis_name)),
        out_specs=P(None, axis_name, None),
    )
    return fn(input_ids, attention_mask, position_ids)
