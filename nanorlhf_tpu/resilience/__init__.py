"""Resilience layer: fault injection, producer watchdog with sync fallback,
training sentinel with checkpoint rollback, and graceful preemption
(docs/RESILIENCE.md). jax-free on purpose — every module unit-tests with
plain Python objects."""

from nanorlhf_tpu.resilience.faults import (
    ENV_VAR,
    INJECTION_POINTS,
    FaultInjector,
    FaultSchedule,
    InjectedFault,
    parse_fault_spec,
)
from nanorlhf_tpu.resilience.preemption import Preempted, PreemptionGuard, null_guard
from nanorlhf_tpu.resilience.procs import reap_process
from nanorlhf_tpu.resilience.retry import backoff_delay, retry_with_backoff
from nanorlhf_tpu.resilience.sentinel import (
    SentinelBudgetExceeded,
    SentinelConfig,
    TrainingSentinel,
)
from nanorlhf_tpu.resilience.watchdog import ProducerWatchdog, WatchdogConfig

__all__ = [
    "ENV_VAR",
    "INJECTION_POINTS",
    "FaultInjector",
    "FaultSchedule",
    "InjectedFault",
    "Preempted",
    "PreemptionGuard",
    "ProducerWatchdog",
    "SentinelBudgetExceeded",
    "SentinelConfig",
    "TrainingSentinel",
    "WatchdogConfig",
    "backoff_delay",
    "null_guard",
    "parse_fault_spec",
    "reap_process",
    "retry_with_backoff",
]
