"""Deterministic fault injection for the resilience stack.

Every failure mode the supervision machinery handles (producer crash, NaN
step, checkpoint-write failure, preemption) must be reproducible in a unit
test — "it recovered once in prod" is not a test. The registry arms named
injection points with deterministic schedules; the hosting code calls
`injector.fire(point)` at the point and the schedule decides whether this
call fails.

Injection points (wired in trainer/checkpoint/orchestrator dispatch):

    ckpt.save        inside CheckpointManager.save's write attempt
    ckpt.restore     inside CheckpointManager.restore's read attempt
    rollout.produce  top of the orchestrator producer's dispatch closure
                     (before the data iterator is touched, so a restart
                     redraws from an unburned cursor)
    reward.exec      inside the trainer's reward-dispatch attempt
    update.step      after the jitted update's host stats land — `action=nan`
                     poisons the observed loss/grad-norm instead of raising,
                     exercising the sentinel exactly like a real NaN step

Worker-scoped points (wired in the rollout fleet's worker loop,
orchestrator/fleet.py — each selectable by worker id via `worker=I`):

    worker.crash         worker thread dies (fatal — the lease is revoked
                         and reassigned; heartbeat/membership notice it)
    worker.hang          worker stalls holding its lease until the lease
                         deadline revokes it (default action "hang")
    worker.slow          worker sleeps `delay` seconds before dispatching —
                         the straggler/speculative-re-dispatch path
                         (default action "delay")
    worker.fetch_weights worker's weight-store fetch raises (recoverable —
                         counts toward the consecutive-failure quarantine)

Network-layer points (wired inside the RpcTransport framing,
orchestrator/rpc.py — fired once per frame send on BOTH directions: the
client's request path and the server's response path, so either direction
is coverable deterministically with a `worker=I` selector):

    net.drop       the frame is not sent and the connection is closed
                   (packet loss → reset; the caller's retry/backoff path)
    net.delay      sleep `delay` seconds before the frame goes out
                   (latency spike; default action "delay")
    net.partition  the worker's link goes dead for `delay` seconds — every
                   call fails fast until it heals (lease expiry + fencing
                   path; client-side state, default action "partition")
    net.duplicate  the frame is sent twice (at-least-once delivery; the
                   receiver's seq/offset dedup must absorb it)
    net.tear       the frame is truncated mid-payload and the connection
                   closed — the receiver detects it by length+checksum
                   (recoverable, counts against the failure budget)

Environment tool points (wired around the env.step tool dispatch in
envs/rollout.py — `worker=I` selects the episode index):

    env.hang       the tool call stalls `delay` seconds before running
                   (default action "delay") — the stalled row's pages are
                   already released, so this drives the
                   release-while-stalled / re-admit path
    env.crash      the tool call raises (default "raise") — the driver
                   absorbs it into an error-text observation; the episode
                   continues, never a dead rollout

Serving-path points (wired in serving/gateway.py's streaming response
loop and loadgen/driver.py's in-process client):

    gw.disconnect  the client vanishes mid-stream (default action "drop") —
                   the gateway/driver must cancel the request so its KV
                   pages are released and in-flight counters decremented

Storage-integrity points (wired in trainer/checkpoint.py):

    ckpt.corrupt   the checkpoint selected for restore reads back
                   corrupt/torn (default action "tear") — restore falls
                   back to the newest EARLIER intact checkpoint instead of
                   failing the run, counting `resilience/ckpt_fallbacks`

In-flight weight-swap point (wired in orchestrator/weight_store.py's
make_swap_refresh, the poll callback the decode driver calls at its host
sync points — `worker=I` selects the polling worker):

    swap.stale     the swap install stalls `delay` seconds before the
                   fresh tree is handed over (default action "delay") —
                   long enough, the next publish lands during the stall
                   and the tree being installed is already superseded; the
                   NEXT sync point's poll installs the newer one, so the
                   versions recorded in the segment ledger stay strictly
                   increasing

Spec grammar (config `fault_spec` or env `NANORLHF_FAULT`; entries separated
by ";" or whitespace):

    point:key=val[,key=val...]

    at=N       fire on the N-th call to this point (1-based; fires once)
    every=K    fire on every K-th call
    prob=P     fire each call with probability P under a seeded PRNG
    seed=S     PRNG seed for prob (default 0 — always deterministic)
    count=C    cap total fires (default: 1 for `at`, unbounded otherwise)
    action=A   "raise" (default) raises InjectedFault; "nan" returns "nan"
               from fire() for the caller to poison its observed value;
               "hang"/"delay" return themselves for the fleet worker loop
               to stall on; "drop"/"partition"/"duplicate"/"tear" return
               themselves for the RPC framing to act on (worker.* and
               net.* points default to the matching action);
               "delay"/"partition" return with their duration attached
               ("delay:<s>" / "partition:<s>")
    worker=I   only fire for calls tagged with this worker id
               (`fire(point, worker=I)`); the call counter then counts
               THAT worker's calls — `at=1,worker=0` is worker 0's first
               dispatch, deterministic even though fleet workers race.
               Without `worker=`, calls from all workers share one counter
               in arrival order (nondeterministic across threads — fine
               for `every=1`, not for `at=N` assertions).
    delay=S    seconds for actions "delay" and "partition" (default 1.0)

Examples:

    NANORLHF_FAULT="ckpt.save:at=1"                 first save write fails once
    NANORLHF_FAULT="rollout.produce:every=1"        every produce attempt dies
    NANORLHF_FAULT="update.step:at=2,action=nan"    2nd update observes NaN
    NANORLHF_FAULT="worker.crash:at=1,worker=0"     worker 0 dies on 1st lease
    NANORLHF_FAULT="worker.slow:every=2,worker=1,delay=0.5"
"""

from __future__ import annotations

import dataclasses
import os
import threading

from nanorlhf_tpu.analysis.lockorder import make_lock
from typing import Callable, Optional

import numpy as np

ENV_VAR = "NANORLHF_FAULT"

INJECTION_POINTS = frozenset({
    "ckpt.save",
    "ckpt.restore",
    "rollout.produce",
    "reward.exec",
    "update.step",
    # worker-scoped fleet sites (orchestrator/fleet.py worker loop)
    "worker.crash",
    "worker.hang",
    "worker.slow",
    "worker.fetch_weights",
    # network-layer sites (orchestrator/rpc.py framing)
    "net.drop",
    "net.delay",
    "net.partition",
    "net.duplicate",
    "net.tear",
    # environment tool sites (envs/rollout.py tool dispatch): env.hang
    # stalls the tool call (default action=delay — drives the
    # page-release-while-stalled path), env.crash raises inside it (the
    # driver absorbs it as an error-text observation)
    "env.hang",
    "env.crash",
    # serving-path site (serving/gateway.py response loop + loadgen/driver.py
    # in-process client): the client vanishes mid-stream
    "gw.disconnect",
    # storage-integrity site (trainer/checkpoint.py): the restored
    # checkpoint reads back corrupt/torn
    "ckpt.corrupt",
    # in-flight weight-swap site (orchestrator/weight_store.py
    # make_swap_refresh): the mid-rollout install stalls past the next
    # publish — the stalled tree lands already superseded and the next
    # sync point installs the newer one (ledger versions stay strictly
    # increasing)
    "swap.stale",
})

ACTIONS = ("raise", "nan", "hang", "delay",
           "drop", "partition", "duplicate", "tear")

# a bare `worker.hang:at=1` should hang, not raise — the point name IS the
# intended behavior; an explicit action= still overrides
_DEFAULT_ACTIONS = {
    "worker.hang": "hang",
    "worker.slow": "delay",
    "net.drop": "drop",
    "net.delay": "delay",
    "net.partition": "partition",
    "net.duplicate": "duplicate",
    "net.tear": "tear",
    "env.hang": "delay",
    "gw.disconnect": "drop",
    "ckpt.corrupt": "tear",
    "swap.stale": "delay",
}


class InjectedFault(RuntimeError):
    """Raised by an armed injection point. Carries the point name so
    supervision code (and test assertions) can tell injected failures from
    organic ones."""

    def __init__(self, point: str, detail: str = ""):
        self.point = point
        super().__init__(f"injected fault at {point!r}" + (f" ({detail})" if detail else ""))


@dataclasses.dataclass
class FaultSchedule:
    point: str
    at: Optional[int] = None
    every: Optional[int] = None
    prob: Optional[float] = None
    seed: int = 0
    count: Optional[int] = None   # max fires; None = unbounded
    action: Optional[str] = None  # None -> point default ("raise" mostly)
    worker: Optional[int] = None  # only match calls tagged with this worker
    delay: float = 1.0            # seconds, action="delay"
    # runtime state
    calls: int = 0
    fires: int = 0

    def __post_init__(self):
        if self.point not in INJECTION_POINTS:
            raise ValueError(
                f"unknown injection point {self.point!r}; known: "
                f"{sorted(INJECTION_POINTS)}"
            )
        if self.action is None:
            self.action = _DEFAULT_ACTIONS.get(self.point, "raise")
        if self.action not in ACTIONS:
            raise ValueError(f"action={self.action!r}: {' | '.join(ACTIONS)}")
        if sum(x is not None for x in (self.at, self.every, self.prob)) != 1:
            raise ValueError(
                f"{self.point}: exactly one of at=/every=/prob= required"
            )
        if self.count is None and self.at is not None:
            self.count = 1  # "fire at step N" means once
        self._rng = np.random.default_rng(self.seed)

    def should_fire(self) -> bool:
        """Advance this schedule's call counter; True if this call fails."""
        self.calls += 1
        if self.count is not None and self.fires >= self.count:
            return False
        if self.at is not None:
            hit = self.calls == self.at
        elif self.every is not None:
            hit = self.calls % self.every == 0
        else:
            hit = bool(self._rng.random() < self.prob)
        if hit:
            self.fires += 1
        return hit


def parse_fault_spec(spec: str) -> list[FaultSchedule]:
    schedules = []
    for entry in spec.replace(";", " ").split():
        if ":" not in entry:
            raise ValueError(f"fault entry {entry!r}: expected point:key=val,...")
        point, _, kvs = entry.partition(":")
        kwargs: dict = {}
        for kv in kvs.split(","):
            if "=" not in kv:
                raise ValueError(f"fault entry {entry!r}: bad clause {kv!r}")
            k, _, v = kv.partition("=")
            if k in ("at", "every", "seed", "count", "worker"):
                kwargs[k] = int(v)
            elif k in ("prob", "delay"):
                kwargs[k] = float(v)
            elif k == "action":
                kwargs[k] = v
            else:
                raise ValueError(f"fault entry {entry!r}: unknown key {k!r}")
        schedules.append(FaultSchedule(point=point, **kwargs))
    return schedules


class FaultInjector:
    """Thread-safe registry of armed fault schedules.

    `fire(point, worker=...)` advances every schedule armed on `point`
    (schedules carrying a `worker` selector only when the tag matches);
    when one triggers with action "raise" it raises InjectedFault, with
    action "nan" it returns "nan" for the caller to poison its observation,
    with "hang" it returns "hang", and with "delay" it returns
    "delay:<seconds>" for the fleet worker loop to stall on. Returns None
    when nothing fires — the disarmed fast path is one dict lookup, so
    production code leaves the calls in unconditionally.

    `on_fire(point, worker, outcome)` — when set — observes every fire
    (outcome is the action string, or "raise" for raising fires) AFTER the
    registry lock is released, so the hook may take other declared locks
    (the chaos harness journals fires into the lineage ledger here)."""

    def __init__(self, schedules: Optional[list[FaultSchedule]] = None):
        self._lock = make_lock("resilience.faults")
        self._by_point: dict[str, list[FaultSchedule]] = {}
        self.on_fire: Optional[Callable[[str, Optional[int], str], None]] = None
        for s in schedules or []:
            self._by_point.setdefault(s.point, []).append(s)

    @classmethod
    def from_spec(cls, spec: Optional[str] = None) -> "FaultInjector":
        """Build from an explicit spec string, falling back to the
        NANORLHF_FAULT env var; empty/None spec arms nothing."""
        spec = spec if spec is not None else os.environ.get(ENV_VAR)
        return cls(parse_fault_spec(spec) if spec else None)

    @property
    def armed(self) -> bool:
        return bool(self._by_point)

    def fire(self, point: str, worker: Optional[int] = None) -> Optional[str]:
        schedules = self._by_point.get(point)
        if not schedules:
            return None
        fired: Optional[tuple[str, str]] = None  # (outcome tag, detail)
        with self._lock:
            for s in schedules:
                if s.worker is not None and s.worker != worker:
                    continue  # call not tagged for this schedule's worker
                if s.should_fire():
                    if s.action == "raise":
                        detail = f"call {s.calls}" + (
                            f" worker {worker}" if worker is not None else ""
                        )
                        fired = ("raise", detail)
                    elif s.action in ("delay", "partition"):
                        # these carry their duration parameter through
                        fired = (f"{s.action}:{s.delay}", "")
                    else:
                        fired = (s.action, "")
                    break
        if fired is None:
            return None
        outcome, detail = fired
        hook = self.on_fire
        if hook is not None:
            try:
                hook(point, worker, outcome)
            except Exception:
                pass  # observation must never change fault semantics
        if outcome == "raise":
            raise InjectedFault(point, detail=detail)
        return outcome

    def stats(self) -> dict:
        """{point: {"calls": n, "fires": m}} — test/debug introspection."""
        with self._lock:
            out: dict = {}
            for point, schedules in self._by_point.items():
                out[point] = {
                    "calls": sum(s.calls for s in schedules),
                    "fires": sum(s.fires for s in schedules),
                }
            return out
