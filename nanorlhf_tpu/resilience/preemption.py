"""Graceful SIGTERM/preemption handling.

TPU pods get preempted with a SIGTERM and a grace window. The default
Python behavior (immediate KeyboardInterrupt-style death) abandons the
in-flight async checkpoint write — a corrupt directory the next resume has
to clamp away — and loses every update since the last `save_steps`
boundary. The guard converts SIGTERM into a flag the training loop polls
at update boundaries: the trainer flushes the in-flight async save, writes
an emergency checkpoint at the current step, and raises `Preempted` so
launchers unwind through their normal `finally: trainer.close()` path.

Signal handlers can only be installed from the main thread; elsewhere the
guard degrades to a manual `trigger()`-only object (tests use this too).
While installed the guard does NOT chain to the previous handler — a
harness-installed handler that exits would defeat the grace window; the
previous handler is restored on `uninstall()`, so stacking guards
(multiple trainers in one process) stays well-behaved.
"""

from __future__ import annotations

import signal
import threading


class Preempted(RuntimeError):
    """Raised by the training loop after the emergency checkpoint commits."""


class PreemptionGuard:
    def __init__(self, signum: int = signal.SIGTERM, install: bool = True):
        self.signum = signum
        self._event = threading.Event()
        self._prev = None
        self._installed = False
        if install:
            try:
                self._prev = signal.signal(signum, self._on_signal)
                self._installed = True
            except ValueError:  # not the main thread: manual trigger only
                pass

    def _on_signal(self, signum, frame):
        # flag only — deliberately NOT chaining to the previous handler
        # while the guard is installed: the whole point of the grace window
        # is that nothing exits before the emergency checkpoint commits
        # (harness-installed SIGTERM handlers typically sys.exit). The
        # previous handler comes back on uninstall().
        self._event.set()

    @property
    def installed(self) -> bool:
        return self._installed

    @property
    def triggered(self) -> bool:
        return self._event.is_set()

    def trigger(self) -> None:
        """Manual preemption signal (tests; cooperative shutdown)."""
        self._event.set()

    def clear(self) -> None:
        self._event.clear()

    def uninstall(self) -> None:
        if self._installed:
            try:
                if signal.getsignal(self.signum) == self._on_signal:
                    signal.signal(self.signum, self._prev or signal.SIG_DFL)
            except ValueError:
                pass
            self._installed = False


def null_guard() -> PreemptionGuard:
    """A fresh never-installed guard for `graceful_preemption=False` paths —
    callers poll `.triggered` unconditionally. Fresh per call: a shared
    instance would let one trainer's manual trigger() poison every later
    trainer in the process with a spurious Preempted."""
    return PreemptionGuard(install=False)
