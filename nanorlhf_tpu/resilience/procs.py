"""Subprocess reaping shared by the grader/executor hosts."""

from __future__ import annotations


def reap_process(p, grace: float = 2.0) -> None:
    """terminate → join(grace) → kill → join: SIGTERM first, SIGKILL for a
    child that ignores/blocks it (signal-handler games, D-state I/O). A
    grader host must never leave an immortal child pinning its scratch
    dir. One implementation so every timeout path escalates identically."""
    p.terminate()
    p.join(grace)
    if p.is_alive():
        p.kill()
        p.join()
