"""Bounded retry-with-backoff for host-side I/O (checkpoint writes/reads,
reward dispatch). Deliberately dumb: synchronous sleep, exponential backoff,
exception allowlist — supervision layers above decide what failure means."""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Type

# Default jitter stream for callers that don't pass their own rng. A
# module-level seeded instance (not the global `random` module): the jitter
# draw must never depend on whatever unrelated code did to global random
# state, and a fresh process replays the same delay sequence.
_JITTER_RNG = random.Random(0x6A177E12)


def retry_with_backoff(
    fn: Callable,
    attempts: int = 3,
    backoff_base: float = 0.25,
    backoff_max: float = 30.0,
    retry_on: tuple[Type[BaseException], ...] = (Exception,),
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
    jitter: float = 0.0,
    rng: Optional[random.Random] = None,
):
    """Call `fn()` up to `attempts` times; sleep base·2^k (capped) between
    tries, spread by ±`jitter` fraction (see `backoff_delay` for why).
    `on_retry(attempt_index, exc)` observes each failure that will be
    retried — the hook where callers count retries into metrics. The final
    failure propagates unchanged."""
    if attempts < 1:
        raise ValueError(f"attempts={attempts} must be >= 1")
    for attempt in range(attempts):
        try:
            return fn()
        except retry_on as e:
            if attempt == attempts - 1:
                raise
            if on_retry is not None:
                on_retry(attempt, e)
            sleep(backoff_delay(attempt, backoff_base, backoff_max,
                                jitter=jitter, rng=rng))


def backoff_delay(attempt: int, base: float, cap: float,
                  jitter: float = 0.0,
                  rng: Optional[random.Random] = None) -> float:
    """Exponential backoff schedule shared by the producer watchdog and the
    fleet's worker-quarantine re-admission.

    `jitter` spreads the delay uniformly over ±jitter·delay: N workers (or N
    restarted producers) that failed on the same cause at the same moment
    would otherwise all sleep EXACTLY base·2^k and stampede the weight
    store / checkpoint filesystem in lockstep on every retry wave. Callers
    that need per-caller determinism pass a seeded `random.Random`; the
    default draws from a module-level SEEDED stream (never the global
    `random` module, whose state any unrelated code may have perturbed), so
    the default delay sequence is identical in every fresh process."""
    delay = min(cap, base * (2 ** max(0, attempt)))
    if jitter > 0.0 and delay > 0.0:
        draw = (rng if rng is not None else _JITTER_RNG).random()
        delay *= 1.0 + jitter * (2.0 * draw - 1.0)
    return min(cap, delay)
