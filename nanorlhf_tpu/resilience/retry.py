"""Bounded retry-with-backoff for host-side I/O (checkpoint writes/reads,
reward dispatch). Deliberately dumb: synchronous sleep, exponential backoff,
exception allowlist — supervision layers above decide what failure means."""

from __future__ import annotations

import time
from typing import Callable, Optional, Type


def retry_with_backoff(
    fn: Callable,
    attempts: int = 3,
    backoff_base: float = 0.25,
    backoff_max: float = 30.0,
    retry_on: tuple[Type[BaseException], ...] = (Exception,),
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
):
    """Call `fn()` up to `attempts` times; sleep base·2^k (capped) between
    tries. `on_retry(attempt_index, exc)` observes each failure that will be
    retried — the hook where callers count retries into metrics. The final
    failure propagates unchanged."""
    if attempts < 1:
        raise ValueError(f"attempts={attempts} must be >= 1")
    for attempt in range(attempts):
        try:
            return fn()
        except retry_on as e:
            if attempt == attempts - 1:
                raise
            if on_retry is not None:
                on_retry(attempt, e)
            sleep(min(backoff_max, backoff_base * (2 ** attempt)))


def backoff_delay(attempt: int, base: float, cap: float) -> float:
    """Exponential backoff schedule shared by the producer watchdog."""
    return min(cap, base * (2 ** max(0, attempt)))
