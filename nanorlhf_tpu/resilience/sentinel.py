"""Training sentinel: per-step finite checks + EWMA loss-spike detection,
with a bounded rollback budget and a quarantine set for offending batches.

The sentinel only OBSERVES host-side scalars the trainer already computes
(mean policy loss, global grad norm) — a no-fault run with the sentinel
enabled is numerically identical to one without it; the guard costs one
float comparison per update. On a trip the trainer restores the last
committed checkpoint (the PR-1 queue journal + index-keyed PRNG make the
replayed data/token streams bit-identical), quarantines the offending
rollout index so the replay skips it instead of re-deriving the same NaN,
and charges the bounded rollback budget.

Sentinel state is journaled into every checkpoint (`trainer_state.json`
under "resilience") so recovery behavior itself resumes: a run restored on
a fresh host remembers its rollback spend and quarantined batches.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional


class SentinelBudgetExceeded(RuntimeError):
    """The run tripped more times than `rollback_budget` allows — repeated
    divergence is a config/data problem rollback cannot fix."""


@dataclasses.dataclass
class SentinelConfig:
    enabled: bool = True
    # EWMA spike detector: trip when (loss − ewma) / √var > spike_zscore,
    # after `warmup_steps` observations have seeded the statistics. The
    # default is deliberately loose — RL losses are noisy and a false trip
    # costs a rollback.
    spike_zscore: float = 6.0
    ewma_alpha: float = 0.1
    warmup_steps: int = 20
    # variance floor, as a fraction of |ewma|: early in a run the EWMA
    # variance badly underestimates the true spread (it has only folded a
    # few deviations), so a raw z-test trips on ordinary noise around a
    # near-constant loss. The floor demands a spike ALSO clear
    # zscore · var_floor_frac · |ewma| — a relative-magnitude gate.
    var_floor_frac: float = 0.05
    rollback_budget: int = 2


class TrainingSentinel:
    """`observe(loss, grad_norm)` → None | "nonfinite" | "spike"."""

    def __init__(self, config: Optional[SentinelConfig] = None):
        self.cfg = config or SentinelConfig()
        self.steps = 0          # healthy observations folded into the EWMA
        self.ewma = 0.0
        self.var = 0.0
        self.rollbacks = 0
        self.quarantined: set[int] = set()  # rollout indices to skip on replay
        self.trips: list[dict] = []

    # ------------------------------------------------------------------ #
    # observation
    # ------------------------------------------------------------------ #

    def observe(self, loss: float, grad_norm: Optional[float] = None) -> Optional[str]:
        """Check one update's host-side stats. Tripped observations are NOT
        folded into the EWMA (a spike must not normalize itself)."""
        if not self.cfg.enabled:
            return None
        loss = float(loss)
        if not math.isfinite(loss) or (
            grad_norm is not None and not math.isfinite(float(grad_norm))
        ):
            return "nonfinite"
        if self.steps >= self.cfg.warmup_steps and self.var > 0.0:
            floor = (self.cfg.var_floor_frac * abs(self.ewma)) ** 2
            z = (loss - self.ewma) / math.sqrt(max(self.var, floor))
            if z > self.cfg.spike_zscore:
                return "spike"
        a = self.cfg.ewma_alpha
        if self.steps == 0:
            self.ewma = loss
        else:
            delta = loss - self.ewma
            self.ewma += a * delta
            # West's EWMA variance: decays old spread, folds in new deviation
            self.var = (1.0 - a) * (self.var + a * delta * delta)
        self.steps += 1
        return None

    # ------------------------------------------------------------------ #
    # rollback accounting
    # ------------------------------------------------------------------ #

    def note_rollback(self, step: int, rollout_index: int, verdict: str) -> None:
        """Charge one rollback and quarantine the offending rollout index.
        Raises SentinelBudgetExceeded when the budget is spent."""
        self.rollbacks += 1
        self.quarantined.add(int(rollout_index))
        self.trips.append(
            {"step": int(step), "rollout_index": int(rollout_index),
             "verdict": verdict}
        )
        if self.rollbacks > self.cfg.rollback_budget:
            raise SentinelBudgetExceeded(
                f"sentinel tripped {self.rollbacks} times "
                f"(budget {self.cfg.rollback_budget}); last verdict "
                f"{verdict!r} at step {step}"
            )

    # ------------------------------------------------------------------ #
    # checkpoint journal
    # ------------------------------------------------------------------ #

    def journal(self) -> dict:
        return {
            "steps": self.steps,
            "ewma": self.ewma,
            "var": self.var,
            "rollbacks": self.rollbacks,
            "quarantined": sorted(self.quarantined),
            "trips": list(self.trips),
        }

    def restore(self, journal: dict) -> None:
        self.steps = int(journal.get("steps", 0))
        self.ewma = float(journal.get("ewma", 0.0))
        self.var = float(journal.get("var", 0.0))
        self.restore_accounting(journal)

    def restore_accounting(self, journal: dict) -> None:
        """Restore only the rollback-accounting half (budget spend,
        quarantine set, trip log), leaving the EWMA statistics alone. The
        trainer's rollback path uses this: the statistical state must
        REWIND with the restored checkpoint (the replayed steps get folded
        into checkpoint-era statistics exactly once — re-applying the
        pre-trip EWMA would double-count every replayed loss and decay the
        variance toward a spurious second trip), while the accounting must
        SURVIVE the restore (the checkpoint predates the trip)."""
        self.rollbacks = int(journal.get("rollbacks", 0))
        self.quarantined = {int(i) for i in journal.get("quarantined", [])}
        self.trips = list(journal.get("trips", []))
