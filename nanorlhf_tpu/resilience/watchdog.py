"""Producer watchdog policy: restart budget, exponential backoff, and the
degrade-to-synchronous decision.

The mechanism (tearing down / rebuilding the orchestrator, resetting the
data iterator to the consumed cursor) lives in the trainer — it owns the
iterator and the weight snapshots. This module owns the POLICY so it is
unit-testable without a trainer: given a sequence of producer failures,
when do we restart, how long do we back off, and when do we stop trying
and fall back to synchronous rollouts (staleness 0) instead of killing
the run.

Budget semantics: `restart_budget` bounds CONSECUTIVE failed recoveries —
a successful sample consumption resets the streak (a producer that dies
once a day should not exhaust a long run's budget), while a producer that
dies every time it is restarted exhausts the budget quickly and triggers
degradation. `restarts_total` counts every restart for the
`resilience/producer_restarts` metric series.
"""

from __future__ import annotations

import dataclasses
import random

from nanorlhf_tpu.resilience.retry import backoff_delay


@dataclasses.dataclass
class WatchdogConfig:
    restart_budget: int = 2       # consecutive restarts before degrading
    backoff_base: float = 0.5     # seconds; doubles per consecutive failure
    backoff_max: float = 30.0
    # ±fraction spread on each restart delay (resilience/retry.backoff_delay):
    # several supervised producers/fleets restarted off the same failure
    # would otherwise retry against the weight store in lockstep. 0 keeps
    # the schedule exact (policy unit tests pin the 2× doubling).
    backoff_jitter: float = 0.0
    degrade_to_sync: bool = True  # past budget: sync fallback vs re-raise
    # (the producer liveness poll interval lives on the orchestrator —
    # RLConfig.producer_heartbeat — not here: the watchdog only decides
    # what to do once a death has already been detected)


class ProducerWatchdog:
    """Decision state machine for producer-thread supervision."""

    RESTART = "restart"
    DEGRADE = "degrade"
    RAISE = "raise"

    def __init__(self, config: WatchdogConfig | None = None,
                 seed: int = 0):
        self.cfg = config or WatchdogConfig()
        self._rng = random.Random(seed)  # deterministic jitter draws
        self.consecutive_failures = 0
        self.restarts_total = 0
        self.degraded = False

    def on_failure(self) -> tuple[str, float]:
        """The producer died (or heartbeat-silenced past its liveness
        check). Returns (decision, backoff_seconds)."""
        self.consecutive_failures += 1
        if self.consecutive_failures > self.cfg.restart_budget:
            if self.cfg.degrade_to_sync:
                self.degraded = True
                return self.DEGRADE, 0.0
            return self.RAISE, 0.0
        self.restarts_total += 1
        return self.RESTART, backoff_delay(
            self.consecutive_failures - 1,
            self.cfg.backoff_base, self.cfg.backoff_max,
            jitter=self.cfg.backoff_jitter, rng=self._rng,
        )

    def on_success(self) -> None:
        """A sample was consumed: the pipeline is healthy again."""
        self.consecutive_failures = 0

    # ------------------------------------------------------------------ #
    # checkpoint journal (recovery behavior itself resumes)
    # ------------------------------------------------------------------ #

    def journal(self) -> dict:
        return {
            "restarts_total": self.restarts_total,
            "degraded": self.degraded,
        }

    def restore(self, journal: dict) -> None:
        self.restarts_total = int(journal.get("restarts_total", 0))
        self.degraded = bool(journal.get("degraded", False))
