from nanorlhf_tpu.rewards.math_grader import (
    get_boxed,
    normalize_math_answer,
    math_answers_equal,
    is_correct,
    call_with_timeout,
)
from nanorlhf_tpu.rewards.builders import (
    make_binary_math_reward,
    make_rm_reward,
    make_rule_reward,
)

__all__ = [
    "get_boxed",
    "normalize_math_answer",
    "math_answers_equal",
    "is_correct",
    "call_with_timeout",
    "make_binary_math_reward",
    "make_rm_reward",
    "make_rule_reward",
]
