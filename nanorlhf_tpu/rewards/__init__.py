from nanorlhf_tpu.rewards.math_grader import (
    get_boxed,
    normalize_math_answer,
    math_answers_equal,
    is_correct,
    call_with_timeout,
)
from nanorlhf_tpu.rewards.eval_dispatch import is_correct_item
from nanorlhf_tpu.rewards.answer_extraction import (
    extract_answer,
    extract_math_answer,
    get_all_boxed,
    get_extractor,
)
from nanorlhf_tpu.rewards.builders import (
    make_binary_math_reward,
    make_rm_reward,
    make_rule_reward,
)

__all__ = [
    "get_boxed",
    "get_all_boxed",
    "normalize_math_answer",
    "math_answers_equal",
    "is_correct",
    "is_correct_item",
    "call_with_timeout",
    "extract_answer",
    "extract_math_answer",
    "get_extractor",
    "make_binary_math_reward",
    "make_rm_reward",
    "make_rule_reward",
]
