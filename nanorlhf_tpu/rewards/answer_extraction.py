"""Benchmark-style answer extraction from model completions.

Capability parity with the vendored Qwen data-processing toolkit
(`/root/reference/examples/r1-v0/utils/data_processing/
answer_extraction.py:245-330`): per-format extractors that recover a final
answer string from free-form reasoning text. Compact fresh implementation
covering the formats the training/eval paths use.
"""

from __future__ import annotations

import re

from nanorlhf_tpu.rewards.math_grader import get_boxed

_ANSWER_MARKERS = (
    "the answer is:",
    "the answer is",
    "the final answer is",
    "final answer:",
    "answer:",
)

_NUMBER_RE = re.compile(r"-?\d[\d,]*(?:\.\d+)?(?:/\d+)?")


def extract_after_marker(text: str) -> str:
    """Text after the last 'The answer is'-style marker (MetaMathQA format,
    `grpo_r1.py:231-234`)."""
    low = text.lower()
    best = -1
    best_len = 0
    for marker in _ANSWER_MARKERS:
        i = low.rfind(marker)
        if i > best:
            best, best_len = i, len(marker)
    if best == -1:
        return ""
    ans = text[best + best_len:].strip()
    # stop at sentence/line end
    for stop in ("\n", ". ", ".\n"):
        j = ans.find(stop)
        if j != -1:
            ans = ans[:j]
    return ans.strip().rstrip(".")


def extract_last_number(text: str) -> str:
    """Last number in the text (GSM8K-style fallback)."""
    matches = _NUMBER_RE.findall(text)
    return matches[-1].replace(",", "") if matches else ""


def extract_answer(text: str, fmt: str = "auto") -> str:
    """Dispatcher: 'boxed' | 'marker' | 'last_number' | 'auto'
    (boxed → marker → last number)."""
    if fmt == "boxed":
        return get_boxed(text)
    if fmt == "marker":
        return extract_after_marker(text)
    if fmt == "last_number":
        return extract_last_number(text)
    return get_boxed(text) or extract_after_marker(text) or extract_last_number(text)


# ---------------------------------------------------------------------------
# per-benchmark extractors — the reference's dispatch surface
# (`answer_extraction.py:207-338`): each takes (question, reasoning, task)
# ---------------------------------------------------------------------------


def get_all_boxed(text: str) -> list[str]:
    """Every \\boxed{...} in order, brace-matched (exhaust variant of
    get_boxed; `extract_boxed_answers` parity)."""
    out = []
    pos = 0
    while True:
        i = text.find("boxed{", pos)
        if i == -1:
            return out
        body = text[i + len("boxed{"):]
        depth = 1
        for j, ch in enumerate(body):
            if ch == "{":
                depth += 1
            elif ch == "}":
                depth -= 1
                if depth == 0:
                    out.append(body[:j].strip())
                    pos = i + len("boxed{") + j
                    break
        else:
            return out  # unbalanced


def _extract_all(reasoning: str) -> list[str]:
    """General answer extraction, exhaust mode (`extract_answer:207-243`):
    'final answer is $X$. I hope' → all boxed → 'he answer is' marker →
    last number; results line-clipped and stripped."""
    preds: list[str] = []
    if "final answer is $" in reasoning and "$. I hope" in reasoning:
        tmp = reasoning.split("final answer is $", 1)[1]
        preds = [tmp.split("$. I hope", 1)[0].strip()]
    elif "boxed" in reasoning:
        preds = get_all_boxed(reasoning)
    elif "he answer is" in reasoning:
        preds = [reasoning.split("he answer is")[-1].strip()]
    else:
        n = extract_last_number(reasoning)
        preds = [n] if n else []
    out = []
    for ans in preds:
        ans = ans.strip().split("\n")[0]
        ans = ans.lstrip(":").rstrip(".").rstrip("/").strip()
        out.append(ans)
    return out


def extract_math_answer(question: str, reasoning: str, task: str) -> list[str]:
    """MATH-style multi-answer extraction (`answer_extraction.py:245-254`):
    'separated by commas' questions split bare comma lists; \\text{and}
    separators split too."""
    answer: list[str] = []
    for ans in _extract_all(reasoning):
        if "separated by commas" in question and all(
            ch not in ans for ch in "()[]"
        ):
            answer.extend(a.strip() for a in ans.split(","))
        elif re.search(r"\\text\{\s*and\s*\}", ans):
            answer.extend(
                a.strip()
                for a in re.sub(r"\\text\{\s*and\s*\}", "[SEP]", ans).split("[SEP]")
            )
        else:
            answer.append(ans.strip())
    return answer


def extract_math_few_shot_cot_answer(question, reasoning, task):
    if "Problem:" in reasoning:
        reasoning = reasoning.split("Problem:", 1)[0]
    return extract_math_answer(question, reasoning, task)


def extract_last_single_answer(question, reasoning, task):
    preds = _extract_all(reasoning)
    return preds[-1] if preds else ""


def extract_gsm_few_shot_cot_answer(question, reasoning, task):
    """Last plain number (`answer_extraction.py:264-271`)."""
    if "Q: " in reasoning:
        reasoning = reasoning.split("Q: ", 1)[0]
    pred = re.findall(r"-?\d+\.?\d*", reasoning)
    return pred[-1] if pred else "[invalid]"


def extract_sat_few_shot_answer(question, reasoning, task):
    """Multiple-choice letter (`answer_extraction.py:294-300`)."""
    if "Problem:" in reasoning:
        reasoning = reasoning.split("Problem:", 1)[0]
    m = re.search(r"the final answer is \(?(?P<ans>[abcd])\)?", reasoning.lower())
    return m.group("ans").upper() if m else "placeholder"


def extract_mmlu_stem(question, reasoning, task):
    if "Problem:" in reasoning:
        reasoning = reasoning.split("Problem:", 1)[0]
    return extract_sat_few_shot_answer(question, reasoning, task)


def extract_ocwcourses_few_shot_answer(question, reasoning, task):
    """'final answer is X. I hope it is correct.' (`:302-311`)."""
    if "Problem:" in reasoning:
        reasoning = reasoning.split("Problem:", 1)[0]
    m = re.search(r"final answer is (?P<ans>.*)\. I hope it is correct\.", reasoning)
    return m.group("ans") if m else "[invalid]"


def extract_agieval_gaokao_mathcloze_few_shot_cot_test(question, reasoning, task):
    if "问题 " in reasoning:
        reasoning = reasoning.split("问题 ", 1)[0]
    if "答案是" in reasoning:
        ans = reasoning.split("答案是", 1)[1].strip()
        ans = ans.split("\n")[0].strip()
        return [ans.strip("$").strip("。").strip()]
    return ["placeholder"]


def extract_agieval_gaokao_mathqa_few_shot_cot_test(question, reasoning, task):
    if "问题 " in reasoning:
        reasoning = reasoning.split("问题 ", 1)[0]
    if "答案是" in reasoning:
        ans = reasoning.split("答案是", 1)[1].strip()
        return ans.split("\n")[0].strip()
    return "placeholder"


def extract_cmath_few_shot_test(question, reasoning, task):
    if "问题：" in reasoning:
        reasoning = reasoning.split("问题：", 1)[0]
    if "答案是" in reasoning:
        ans = reasoning.split("答案是", 1)[1].strip()
        ans = ans.split("\n")[0].strip("：").strip("。")
        nums = re.findall(r"-?\d+\.?\d*", ans)
        return nums[-1] if nums else "[invalid]"
    return extract_last_single_answer(question, reasoning, task)


def extract_minif2f_isabelle(question, reasoning, task):
    if "Informal:" in reasoning:
        reasoning = reasoning.split("Informal:", 1)[0]
    return reasoning.strip()


# task-name → extractor registry; unknown tasks fall back to the general
# last-answer extraction (same shape as the reference's eval dispatch)
_EXTRACTORS = {
    "math": extract_math_answer,
    "math-500": extract_math_answer,
    "math_few_shot": extract_math_few_shot_cot_answer,
    "gsm8k": extract_gsm_few_shot_cot_answer,
    "sat-math": extract_sat_few_shot_answer,
    "sat": extract_sat_few_shot_answer,
    "mmlu-stem": extract_mmlu_stem,
    "mmlu_stem": extract_mmlu_stem,
    "ocwcourses": extract_ocwcourses_few_shot_answer,
    "ocw": extract_ocwcourses_few_shot_answer,
    "agieval-gaokao-mathcloze": extract_agieval_gaokao_mathcloze_few_shot_cot_test,
    "agieval-gaokao-mathqa": extract_agieval_gaokao_mathqa_few_shot_cot_test,
    "cmath": extract_cmath_few_shot_test,
    "minif2f_isabelle": extract_minif2f_isabelle,
}


_EXTRACTOR_PREFIXES = (
    ("math", extract_math_answer),
    ("gsm", extract_gsm_few_shot_cot_answer),
    ("sat", extract_sat_few_shot_answer),
    ("mmlu", extract_mmlu_stem),
    ("ocw", extract_ocwcourses_few_shot_answer),
    ("cmath", extract_cmath_few_shot_test),
    ("minif2f", extract_minif2f_isabelle),
)


def get_extractor(task: str):
    """Benchmark name → extractor, tolerant of spelling variants ('MATH500',
    'gsm8k_test', ...): exact key, then normalized key, then name-prefix
    rules; the general last-answer fallback is LOGGED so a silent dispatch
    miss (graded with the wrong answer shape) is observable."""
    if task in _EXTRACTORS:
        return _EXTRACTORS[task]
    norm = task.strip().lower().replace("_", "-")
    if norm in _EXTRACTORS:
        return _EXTRACTORS[norm]
    compact = norm.replace("-", "")
    for prefix, fn in _EXTRACTOR_PREFIXES:
        if compact.startswith(prefix):
            return fn
    import logging

    from nanorlhf_tpu.utils.logging import warn_once

    warn_once(
        "nanorlhf_tpu.rewards",
        "no benchmark extractor for task %r; using last-answer fallback",
        task, level=logging.INFO,
    )
    return extract_last_single_answer
