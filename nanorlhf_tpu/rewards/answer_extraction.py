"""Benchmark-style answer extraction from model completions.

Capability parity with the vendored Qwen data-processing toolkit
(`/root/reference/examples/r1-v0/utils/data_processing/
answer_extraction.py:245-330`): per-format extractors that recover a final
answer string from free-form reasoning text. Compact fresh implementation
covering the formats the training/eval paths use.
"""

from __future__ import annotations

import re

from nanorlhf_tpu.rewards.math_grader import get_boxed

_ANSWER_MARKERS = (
    "the answer is:",
    "the answer is",
    "the final answer is",
    "final answer:",
    "answer:",
)

_NUMBER_RE = re.compile(r"-?\d[\d,]*(?:\.\d+)?(?:/\d+)?")


def extract_after_marker(text: str) -> str:
    """Text after the last 'The answer is'-style marker (MetaMathQA format,
    `grpo_r1.py:231-234`)."""
    low = text.lower()
    best = -1
    best_len = 0
    for marker in _ANSWER_MARKERS:
        i = low.rfind(marker)
        if i > best:
            best, best_len = i, len(marker)
    if best == -1:
        return ""
    ans = text[best + best_len:].strip()
    # stop at sentence/line end
    for stop in ("\n", ". ", ".\n"):
        j = ans.find(stop)
        if j != -1:
            ans = ans[:j]
    return ans.strip().rstrip(".")


def extract_last_number(text: str) -> str:
    """Last number in the text (GSM8K-style fallback)."""
    matches = _NUMBER_RE.findall(text)
    return matches[-1].replace(",", "") if matches else ""


def extract_answer(text: str, fmt: str = "auto") -> str:
    """Dispatcher: 'boxed' | 'marker' | 'last_number' | 'auto'
    (boxed → marker → last number)."""
    if fmt == "boxed":
        return get_boxed(text)
    if fmt == "marker":
        return extract_after_marker(text)
    if fmt == "last_number":
        return extract_last_number(text)
    return get_boxed(text) or extract_after_marker(text) or extract_last_number(text)
