"""Reward-function builders implementing the reference's reward protocol.

Protocol (`/root/reference/GRPO/grpo.py:162`): a callable
`reward_func(pmt_and_responses: list[str], eos_token: str) -> array[B]`.
The reward model is *user-pluggable by design* (`README.md:12`); these
builders cover the three families the reference ships:

- rule-based closures (r1's binary correctness, `grpo_r1.py:250-273`)
- RM-based scoring with a JAX sequence-classifier running on the TPU mesh
- RM-based scoring with a host-side torch model (the deberta path,
  `GRPO/grpo.py:159-198`) when torch weights are available locally
"""

from __future__ import annotations

from typing import Callable

import numpy as np


def make_rule_reward(fn: Callable[[str, str], float]):
    """Lift a per-string scoring fn into the reward protocol."""

    def reward_func(pmt_and_responses, eos_token):
        return np.asarray([fn(s, eos_token) for s in pmt_and_responses], np.float32)

    return reward_func


def make_binary_math_reward(
    question_to_answer: dict,
    extract_question: Callable[[str], str],
    extract_solution: Callable[[str, str], str],
    timeout: float = 0.05,
    use_subprocess: bool = True,
):
    """r1-style binary reward: 1 if the boxed answer grades correct, else 0.

    `question_to_answer` is the train-set hash map (`grpo_r1.py:237-240`);
    the extractors recover the question and the model's boxed solution from
    the concatenated prompt+response string (`grpo_r1.py:250-273`).
    """
    from nanorlhf_tpu.rewards.math_grader import get_boxed, is_correct

    def reward_func(pmt_and_responses, eos_token):
        rewards = np.zeros(len(pmt_and_responses), np.float32)
        for i, s in enumerate(pmt_and_responses):
            question = extract_question(s)
            gt = question_to_answer.get(question)
            if gt is None:
                continue
            solution = get_boxed(extract_solution(s, eos_token))
            if is_correct(solution, gt, timeout=timeout, use_subprocess=use_subprocess):
                rewards[i] = 1.0
        return rewards

    return reward_func


def make_rm_reward(
    rm_params: dict,
    model_config,
    tokenizer,
    batch_size: int = 16,
    max_len: int = 2048,
):
    """TPU-native RM reward: a JAX decoder + score head rates each string.

    Scores at the last real token (TRL `get_reward` semantics, used at
    `PPO/ppo_trainer.py:630-634`). Batched at `reward_batch_size` parity
    (`GRPO/grpo.py:97,189-192`). Unlike the reference there is no CPU↔GPU
    RM migration (`grpo.py:164,195`) — the RM tree lives in HBM alongside
    the policy.
    """
    import jax
    import jax.numpy as jnp

    from nanorlhf_tpu.core.model import score_forward

    pad_id = tokenizer.pad_token_id

    @jax.jit
    def score_batch(params, ids):
        scores = score_forward(params, model_config, ids, pad_id)[:, :, 0]
        mask = ids != pad_id
        last = jnp.maximum(jnp.sum(mask, axis=1) - 1, 0)
        return scores[jnp.arange(ids.shape[0]), last]

    def reward_func(pmt_and_responses, eos_token):
        out = []
        for i in range(0, len(pmt_and_responses), batch_size):
            chunk = pmt_and_responses[i : i + batch_size]
            enc = [tokenizer.encode(s)[:max_len] for s in chunk]
            width = max(len(e) for e in enc)
            ids = np.full((len(enc), width), pad_id, np.int32)
            for j, e in enumerate(enc):
                ids[j, : len(e)] = e  # right-pad; scorer finds last real token
            out.append(np.asarray(score_batch(rm_params, jnp.asarray(ids))))
        return np.concatenate(out).astype(np.float32)

    return reward_func


def make_torch_rm_reward(model_path: str, batch_size: int = 16, device: str = "cpu"):
    """Host-side torch RM (the deberta-v3 path, `GRPO/grpo.py:159-198`).

    Runs on CPU next to the TPU loop; use when the RM checkpoint is a torch
    encoder with its own tokenizer. Requires local weights (zero-egress).
    """
    import torch
    from transformers import AutoModelForSequenceClassification, AutoTokenizer

    model = AutoModelForSequenceClassification.from_pretrained(model_path).eval().to(device)
    rm_tok = AutoTokenizer.from_pretrained(model_path)

    def reward_func(pmt_and_responses, eos_token):
        out = []
        with torch.no_grad():
            for i in range(0, len(pmt_and_responses), batch_size):
                chunk = [s.replace(eos_token, "") for s in
                         pmt_and_responses[i : i + batch_size]]
                enc = rm_tok(chunk, return_tensors="pt", padding=True,
                             truncation=True, max_length=2048).to(device)
                logits = model(**enc).logits[:, 0]
                out.append(logits.float().cpu().numpy())
        return np.concatenate(out).astype(np.float32)

    return reward_func
