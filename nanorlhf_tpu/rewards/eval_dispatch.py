"""Multi-benchmark correctness dispatch — `is_correct` over single answers,
answer lists, and set unions, plus per-benchmark evaluators.

Capability parity with the vendored Qwen eval script
(`/root/reference/examples/r1-v0/utils/eval/eval_script.py`):

- `is_correct_item` (`eval_script.py:6-44`): list-vs-list predictions use
  bipartite coverage — every predicted answer must match some ground-truth
  answer AND every ground-truth answer must be matched (multi-part answers
  in any order); strings containing ``\\cup`` split into their union pieces
  and recurse as lists; scalar strings grade by numeric closeness
  (comma-stripped, ``prec`` tolerance), exact match, then the full
  `math_answers_equal` ladder.
- per-benchmark evaluators (`eval_script.py:46-172`): MATH multi-answer
  dedup/truncate, gaokao cloze bracket-aware splitting, gaokao mathqa
  latest-choice-letter, SAT/MMLU case-insensitive letters, OCW
  numeric/equation/expression grading, minif2f passthrough — looked up via
  `get_evaluator(dataset_name)`.
"""

from __future__ import annotations

import re

from nanorlhf_tpu.rewards.math_grader import (
    _numeric_equal,
    _parse_sympy,
    _sympy_equal,
    _try_float,
    math_answers_equal,
    normalize_math_answer,
)


def is_correct_item(pred, answer, prec: float = 1e-3) -> bool:
    if isinstance(pred, list) and isinstance(answer, list):
        pred_matched: set[int] = set()
        ans_matched: set[int] = set()
        for i, p in enumerate(pred):
            for j, a in enumerate(answer):
                if is_correct_item(p, a, prec=prec):
                    pred_matched.add(i)
                    ans_matched.add(j)
        return len(pred_matched) == len(pred) and len(ans_matched) == len(answer)
    if isinstance(pred, str) and isinstance(answer, str):
        if "\\cup" in pred and "\\cup" in answer:
            return is_correct_item(
                pred.split("\\cup"), answer.split("\\cup"), prec=prec
            )
        try:
            if abs(
                float(re.sub(r",", "", pred)) - float(re.sub(r",", "", answer))
            ) < prec:
                return True
        except (ValueError, TypeError):
            pass
        # offline-eval leniency: accept x100/÷100 numeric variants like the
        # reference eval toolkit (`eval_utils.math_equal:195-214`); the live
        # training reward calls math_answers_equal directly and stays strict
        return bool(answer and pred == answer) or math_answers_equal(
            pred, answer, percent_variants=True
        )
    # mixed scalar/list: wrap the scalar (the reference raises; grading a
    # reward must not crash the training loop)
    if isinstance(pred, str):
        return is_correct_item([pred], answer, prec=prec)
    if isinstance(answer, str):
        return is_correct_item(pred, [answer], prec=prec)
    return False


def _dedup_keep_order(xs: list) -> list:
    out = []
    for x in xs:
        if x not in out:
            out.append(x)
    return out


def eval_math(pred, answer, prec: float = 1e-3) -> bool:
    """MATH: dedup repeated answers on both sides, keep only the LAST
    len(answer) predictions (models sometimes box non-answer strings early),
    then bipartite-match (`eval_script.py:46-70`)."""
    if isinstance(pred, str):
        pred = [pred]
    if isinstance(answer, str):
        answer = [answer]  # gold often stored scalar; truncation must run
    if isinstance(pred, list) and isinstance(answer, list):
        answer = _dedup_keep_order(answer)
        pred = _dedup_keep_order(pred)[-len(answer):]
    return is_correct_item(pred, answer, prec=prec)


def _last_str(x) -> str:
    """Coerce a maybe-list to its last string element. The reference asserts
    str and crashes (`eval_script.py:73-74`); a mislabeled dataset row must
    score 0, not abort the eval/reward loop (module no-crash rule)."""
    if isinstance(x, list):
        x = x[-1] if x else ""
    return x if isinstance(x, str) else str(x)


def eval_last_single_answer(pred, answer, prec: float = 1e-3) -> bool:
    """Scalar benchmarks (GSM8K etc., `eval_script.py:72-75`); list inputs
    coerce to their last element (extractors return lists)."""
    return is_correct_item(_last_str(pred), _last_str(answer), prec=prec)


def _split_top_level(piece: str) -> list[str]:
    """Split on ';' anywhere and on ',' outside brackets
    (`eval_script.py:81-99` bracket-counting loop)."""
    out: list[str] = []
    depth = 0
    cur: list[str] = []
    for ch in piece:
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        if ch == ";" or (ch == "," and depth == 0):
            part = "".join(cur).strip()
            if part:
                out.append(part)
            cur = []
        else:
            cur.append(ch)
    part = "".join(cur).strip()
    if part:
        out.append(part)
    return out


def eval_agieval_gaokao_math_cloze(pred, answer, prec: float = 1e-3) -> bool:
    """Gaokao cloze: split predictions bracket-aware, dedup, keep the last
    len(answer), require IN-ORDER pairwise match (`eval_script.py:77-110`).
    A scalar answer wraps to a one-element list (the reference asserts list
    and would crash; len() on the raw string would count characters)."""
    if isinstance(pred, str):
        pred = [pred]
    if isinstance(answer, str):
        answer = [answer]
    parts: list[str] = []
    for p in pred:
        for part in _split_top_level(p):
            if part not in parts:
                parts.append(part)
    parts = parts[-len(answer):]
    if len(parts) != len(answer):
        return False
    return all(
        is_correct_item(p, a, prec=prec) for p, a in zip(parts, answer)
    )


def eval_agieval_gaokao_mathqa(pred, answer, prec: float = 1e-3) -> bool:
    """Gaokao mathqa: the chosen letter is the one whose FIRST occurrence in
    the joined prediction text is LATEST (`eval_script.py:112-124`)."""
    if isinstance(pred, str):
        pred = [pred]
    pred_str = " ".join(pred)
    tag, idx = None, -1
    for t in "ABCD":
        if t in pred_str and pred_str.index(t) > idx:
            tag, idx = t, pred_str.index(t)
    return tag == _last_str(answer)


def eval_math_sat(pred, answer, prec: float = 1e-3) -> bool:
    """Case-insensitive choice-letter match (`eval_script.py:126-129`)."""
    return _last_str(pred).lower() == _last_str(answer).lower()


eval_mmlu_stem = eval_math_sat  # `eval_script.py:131-132`


_OCW_INVALID = "[invalidanswer]"

# dimension words the OCW answers carry; stripped before numeric parsing
# (`ocwcourses_eval_utils.normalize_numeric:26-57` unit list)
_OCW_UNITS = (
    "eV",
    " \\mathrm{~kg} \\cdot \\mathrm{m} / \\mathrm{s}",
    " kg m/s", "kg*m/s", "kg",
    "m/s", "m / s", "m s^{-1}", "\\text{ m/s}", " \\mathrm{m/s}",
    " \\text{ m/s}",
    "g/mole", "g/mol", "\\mathrm{~g}", "\\mathrm{~g} / \\mathrm{mol}",
    "W", "erg/s", "years", "year", "cm",
)


def _ocw_normalize_numeric(s: str):
    """Strip units, then evaluate to a float; sympy handles latex-ish
    scalars like ``3 \\times 10^{4}``. Returns float or _OCW_INVALID."""
    for unit in _OCW_UNITS:
        s = s.replace(unit, "").strip()
    for maybe_unit in ("m", "s", "cm"):
        s = s.replace("\\mathrm{" + maybe_unit + "}", "")
        s = s.replace("\\mathrm{~" + maybe_unit + "}", "")
        s = s.strip()
    s = s.strip("$").strip()
    v = _try_float(s)
    if v is not None:
        return v
    try:
        expr = _parse_sympy(s.replace("\\times", "*"))
        if expr.is_number:
            return float(expr)
    except Exception:
        pass
    return _OCW_INVALID


def _ocw_numeric_equality(n1: float, n2: float, threshold: float = 0.01) -> bool:
    """Reference parity (`ocwcourses_eval_utils.numeric_equality:69-75`):
    rel_tol 1e-5 closeness on the main path; `threshold` (1% of the mean)
    applies only in the near-zero carve-out. Unlike the reference, exact
    equality always passes (its carve-out grades 0 == 0 and negative pairs
    False — `abs(n1-n2) < threshold*(n1+n2)/2` is never true for a zero or
    negative mean)."""
    import math

    if n1 == n2:
        return True
    if math.isclose(n1, 0.0, abs_tol=1e-8) or math.isclose(
        n2, 0.0, abs_tol=1e-8
    ) or math.isclose(n1 - n2, 0.0, abs_tol=1e-8):
        return abs(n1 - n2) < threshold * abs(n1 + n2) / 2
    return math.isclose(n1, n2, rel_tol=1e-5)


def _ocw_normalize_equation(s: str):
    """Parse an equation string to a canonical sympy Equality, or invalid
    (`ocwcourses_eval_utils.normalize_symbolic_equation:77-97`)."""
    if not isinstance(s, str) or "=" not in s:
        return _OCW_INVALID
    s = s.strip()
    if s.startswith("\\["):
        s = s[2:]
    if s.endswith("\\]"):
        s = s[:-2]
    s = s.replace("\\left(", "(").replace("\\right)", ")")
    s = s.replace("\\\\", "\\").strip("$")
    lhs, _, rhs = s.partition("=")
    try:
        import sympy

        eq = sympy.Eq(_parse_sympy(lhs), _parse_sympy(rhs))
        return sympy.simplify(eq)
    except Exception:
        return _OCW_INVALID


def eval_ocwcourses(pred, answer, prec: float = 1e-3) -> bool:
    """OCW: answer type decides the grader — numeric (unit-stripped, rel_tol
    1e-5 with a 1%-of-mean near-zero carve-out), equation (canonical sympy
    Equality), or tex expression (normalize + symbolic equivalence)
    (`eval_script.py:134-170`)."""
    pred, answer = _last_str(pred), _last_str(answer)
    if not pred:
        return False
    if _try_float(answer) is not None:
        gold = _ocw_normalize_numeric(answer)
        got = _ocw_normalize_numeric(pred)
        if gold == _OCW_INVALID or got == _OCW_INVALID:
            return False
        return _ocw_numeric_equality(got, gold)
    if "=" in answer:
        gold = _ocw_normalize_equation(answer)
        got = _ocw_normalize_equation(pred)
        return gold != _OCW_INVALID and got != _OCW_INVALID and gold == got
    a = normalize_math_answer(pred)
    b = normalize_math_answer(answer)
    return a == b or _numeric_equal(a, b, tol=prec) or _sympy_equal(a, b)


def eval_minif2f_isabelle(pred, answer, prec: float = 1e-3) -> bool:
    """Formal-proof benchmark: correctness is decided by the proof checker
    downstream, not string grading (`eval_script.py:171-172`)."""
    return True


_EVALUATORS = {
    "math-cot": eval_math,
    "math": eval_math,
    "gsm8k-cot": eval_last_single_answer,
    "gsm8k": eval_last_single_answer,
    "cmath": eval_last_single_answer,
    "mgsm-zh": eval_last_single_answer,
    "mgsm_zh": eval_last_single_answer,
    "agieval-gaokao-math-cloze": eval_agieval_gaokao_math_cloze,
    "agieval-gaokao-mathqa": eval_agieval_gaokao_mathqa,
    "math_sat": eval_math_sat,
    "sat": eval_math_sat,
    "mmlu-stem": eval_mmlu_stem,
    "mmlu_stem": eval_mmlu_stem,
    "ocwcourses": eval_ocwcourses,
    "ocw": eval_ocwcourses,
    "minif2f-isabelle": eval_minif2f_isabelle,
}


def get_evaluator(dataset: str):
    """Per-benchmark evaluator lookup; unknown names fall back to the
    generic `is_correct_item` (logged once per name)."""
    key = dataset.strip().lower()
    if key in _EVALUATORS:
        return _EVALUATORS[key]
    from nanorlhf_tpu.utils.logging import warn_once

    warn_once(
        "nanorlhf_tpu.rewards",
        "no per-benchmark evaluator for %r; using generic is_correct_item",
        dataset,
    )
    return is_correct_item
