"""Multi-benchmark correctness dispatch — `is_correct` over single answers,
answer lists, and set unions.

Capability parity with the vendored Qwen eval script
(`/root/reference/examples/r1-v0/utils/eval/eval_script.py:6-44`):

- list-vs-list predictions use bipartite coverage — every predicted answer
  must match some ground-truth answer AND every ground-truth answer must be
  matched (multi-part answers in any order);
- strings containing ``\\cup`` split into their union pieces and recurse as
  lists;
- scalar strings grade by numeric closeness (comma-stripped, ``prec``
  tolerance), exact match, then the full `math_answers_equal` ladder.
"""

from __future__ import annotations

import re

from nanorlhf_tpu.rewards.math_grader import math_answers_equal


def is_correct_item(pred, answer, prec: float = 1e-3) -> bool:
    if isinstance(pred, list) and isinstance(answer, list):
        pred_matched: set[int] = set()
        ans_matched: set[int] = set()
        for i, p in enumerate(pred):
            for j, a in enumerate(answer):
                if is_correct_item(p, a, prec=prec):
                    pred_matched.add(i)
                    ans_matched.add(j)
        return len(pred_matched) == len(pred) and len(ans_matched) == len(answer)
    if isinstance(pred, str) and isinstance(answer, str):
        if "\\cup" in pred and "\\cup" in answer:
            return is_correct_item(
                pred.split("\\cup"), answer.split("\\cup"), prec=prec
            )
        try:
            if abs(
                float(re.sub(r",", "", pred)) - float(re.sub(r",", "", answer))
            ) < prec:
                return True
        except (ValueError, TypeError):
            pass
        return bool(answer and pred == answer) or math_answers_equal(pred, answer)
    # mixed scalar/list: wrap the scalar (the reference raises; grading a
    # reward must not crash the training loop)
    if isinstance(pred, str):
        return is_correct_item([pred], answer, prec=prec)
    if isinstance(answer, str):
        return is_correct_item(pred, [answer], prec=prec)
    return False
