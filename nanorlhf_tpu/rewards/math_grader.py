"""Math-answer grading: boxed extraction, normalization, equivalence.

Fresh implementation of the capability the reference gets from its vendored
DeepSeek/Qwen toolkits (`/root/reference/examples/r1-v0/utils/
{toolkit_for_MATH,eval}/**`) and the r1 launcher's graders
(`examples/r1-v0/grpo_r1.py:179-224`):

- `get_boxed`: brace-matched \\boxed{...} extraction;
- `normalize_math_answer`: MATH-style latex normalization;
- `math_answers_equal`: string → numeric → sympy-symbolic equivalence ladder;
- `call_with_timeout`: run a grader in a killable subprocess so adversarial
  expressions (e.g. 2^(2^100000)) cannot stall training — the reference's
  timeout-subprocess pattern, host-side next to the TPU loop.

Everything here is pure Python/sympy on the host; nothing enters the
compiled graph.
"""

from __future__ import annotations

import logging
import multiprocessing
import os
import re

# ---------------------------------------------------------------------------
# extraction
# ---------------------------------------------------------------------------


def get_boxed(text: str) -> str:
    """Contents of the first \\boxed{...}, with nested braces matched.

    Returns "" when absent — callers treat that as wrong
    (`grpo_r1.py:194-213,216-218`). Whitespace stripped like the reference.
    """
    pos = text.find("boxed{")
    if pos == -1:
        return ""
    body = text[pos + len("boxed{"):]
    depth = 1
    for i, ch in enumerate(body):
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            if depth == 0:
                return body[:i].replace(" ", "")
    return ""  # unbalanced braces


# ---------------------------------------------------------------------------
# normalization (MATH-style latex surface cleanup)
# ---------------------------------------------------------------------------

_TEXT_CMDS = ("\\text", "\\mbox", "\\textbf", "\\mathrm", "\\mathbf")


def _strip_cmd_wrapper(s: str, cmd: str) -> str:
    """Replace cmd{X} with X (single level, repeatedly)."""
    while True:
        pos = s.find(cmd + "{")
        if pos == -1:
            return s
        depth, start = 1, pos + len(cmd) + 1
        for i in range(start, len(s)):
            if s[i] == "{":
                depth += 1
            elif s[i] == "}":
                depth -= 1
                if depth == 0:
                    s = s[:pos] + s[start:i] + s[i + 1:]
                    break
        else:
            return s


def normalize_math_answer(ans: str) -> str:
    """Canonicalize a latex answer string for surface comparison."""
    s = ans.strip()
    # outer $ ... $ / \( ... \)
    s = s.strip("$")
    s = s.replace("\\(", "").replace("\\)", "").replace("\\[", "").replace("\\]", "")
    s = s.replace("\\left", "").replace("\\right", "")
    s = s.replace("\\!", "").replace("\\,", "").replace("\\;", "").replace("\\:", "")
    s = s.replace("\\$", "").replace("\\%", "").replace("%", "")
    # trailing units: "5\text{ cm}" / "12 \text{ cm}^2" -> "5" / "12" (the
    # MATH-toolkit remove-right-units behavior). A PURE text answer
    # ("\text{east}") has nothing before the block and is left for the
    # wrapper stripping below.
    m = re.match(r"^(.*\S)\s*\\text\{[^{}]*\}(\s*\^\{?\d+\}?)?\s*$", s)
    if m and m.group(1).strip():
        s = m.group(1)
    for cmd in _TEXT_CMDS:
        s = _strip_cmd_wrapper(s, cmd)
    s = s.replace("^{\\circ}", "").replace("^\\circ", "")
    s = s.replace("\\cdot", "*").replace("\\times", "*")
    # \tfrac/\dfrac -> \frac
    s = s.replace("\\tfrac", "\\frac").replace("\\dfrac", "\\frac")
    # \frac ab / \frac{a}b / \frac a{b} -> \frac{a}{b}
    s = re.sub(r"\\frac\s*([0-9a-zA-Z])\s*([0-9a-zA-Z])", r"\\frac{\1}{\2}", s)
    s = re.sub(r"\\frac\{([^{}]*)\}\s*([0-9a-zA-Z])", r"\\frac{\1}{\2}", s)
    s = re.sub(r"\\frac\s*([0-9a-zA-Z])\s*\{", r"\\frac{\1}{", s)
    # \sqrt x -> \sqrt{x}
    s = re.sub(r"\\sqrt\s*([0-9a-zA-Z])", r"\\sqrt{\1}", s)
    # drop trailing units-ish words after a number, thousands separators
    s = s.replace(",\\!", "").replace("{,}", "")
    s = re.sub(r"(?<=\d),(?=\d{3}\b)", "", s)
    # leading "x=" / "x \in" style assignment prefixes
    s = re.sub(r"^[a-zA-Z]\s*=\s*", "", s)
    s = re.sub(r"^[a-zA-Z]\s*\\in\s*", "", s)
    # 0.5 -> .5 canonicalization (match MATH convention: strip leading 0)
    s = re.sub(r"(?<![\d.])0\.(\d)", r".\1", s)
    s = s.replace(" ", "")
    # trailing period
    s = s.rstrip(".")
    return s


# ---------------------------------------------------------------------------
# equivalence ladder
# ---------------------------------------------------------------------------


def _latex_to_sympy_str(s: str) -> str:
    """Light latex → sympy-parsable conversion for common answer shapes."""
    out = s
    # mixed numbers first: [-]N\frac{a}{b} means ±(N + a/b) — the sign
    # applies to the whole mixed number, so -1\frac{1}{2} = -1.5, not -0.5
    mixed = re.compile(r"(-?)(\d+)\\frac\{([^{}]*)\}\{([^{}]*)\}")
    frac = re.compile(r"\\frac\{([^{}]*)\}\{([^{}]*)\}")
    sqrt = re.compile(r"\\sqrt\{([^{}]*)\}")
    # one FIXPOINT over all three: each pattern only matches brace-free
    # arguments, so nesting (\frac{\sqrt{3}}{3}, \sqrt{\frac{1}{2}}) must
    # convert innermost-first across patterns — separate per-pattern loops
    # left nested forms half-converted into sympy garbage
    while True:
        prev = out
        out = mixed.sub(r"\1((\2)+((\3)/(\4)))", out)
        out = frac.sub(r"((\1)/(\2))", out)
        out = sqrt.sub(r"sqrt(\1)", out)
        if out == prev:
            break
    out = out.replace("\\pi", "pi").replace("\\infty", "oo")
    out = out.replace("^", "**")
    out = out.replace("{", "(").replace("}", ")")
    out = out.replace("\\", "")
    return out


def _try_float(s: str):
    try:
        return float(s)
    except (ValueError, TypeError):
        return None


def _numeric_equal(a: str, b: str, tol: float = 1e-6) -> bool | None:
    fa, fb = _try_float(a), _try_float(b)
    if fa is None or fb is None:
        return None
    return abs(fa - fb) <= tol * max(1.0, abs(fa), abs(fb))


def _parse_sympy(s: str):
    from sympy.parsing.sympy_parser import (
        implicit_multiplication_application,
        parse_expr,
        standard_transformations,
    )

    transforms = standard_transformations + (implicit_multiplication_application,)
    return parse_expr(_latex_to_sympy_str(s), transformations=transforms)


def _sympy_equal(a: str, b: str) -> bool:
    """Symbolic equality via sympy; exceptions mean 'not provably equal'.

    Falls back to numeric evaluation with the reference's closeness
    (`latex_answer_check.symbolic_equal:70-74` uses rel_tol 1e-3;
    `eval_utils.math_equal` abs_tol 1e-3) so `3.1416 == \\pi` grades True.
    """
    try:
        import sympy

        ea = _parse_sympy(a)
        eb = _parse_sympy(b)
        if sympy.simplify(ea - eb) == 0:
            return True
        try:
            import math

            return math.isclose(float(sympy.N(ea)), float(sympy.N(eb)),
                                rel_tol=1e-3, abs_tol=1e-3)
        except Exception:
            return False
    except Exception:
        return False


def _expand_pm(s: str) -> list[str]:
    """a\\pm b → [a+b, a-b] (first \\pm only; recursion covers multiples)."""
    if "\\pm" not in s:
        return [s]
    plus = s.replace("\\pm", "+", 1)
    minus = s.replace("\\pm", "-", 1)
    return _expand_pm(plus) + _expand_pm(minus)


def _branch_set(s: str) -> list[str]:
    """Branches of a \\pm expression, or the comma-separated members of an
    explicit pair/set written as {a,b} / (a,b) / a,b."""
    if "\\pm" in s:
        return _expand_pm(s)
    body = s
    if len(body) >= 2 and (body[0], body[-1]) in {("{", "}"), ("(", ")")}:
        body = body[1:-1]
    if "," in body:
        return [p for p in body.split(",") if p]
    return [s]


def _light_clean(s: str) -> str:
    """Structural cleanup only: strip $, spaces, \\left/\\right — keep
    brackets/commas/relations intact for the structured comparisons."""
    s = s.strip().strip("$")
    s = s.replace("\\left", "").replace("\\right", "")
    s = s.replace("\\!", "").replace("\\,", "").replace("\\;", "")
    return s.replace(" ", "")


def _digit_value(raw: str):
    """float value of a plain-number string; '%'-suffixed values divide by
    100 (`eval_utils.parse_digits` behavior). None when not a number."""
    s = _light_clean(raw)
    s = re.sub(r"(?<=\d),(?=\d{3}\b)", "", s)
    pct = False
    for suffix in ("\\%", "%"):
        if s.endswith(suffix):
            s, pct = s[: -len(suffix)], True
            break
    v = _try_float(s)
    if v is None:
        return None
    return v / 100.0 if pct else v


def _digits_equal(
    pred_raw: str, gt_raw: str, percent_variants: bool = False
) -> bool | None:
    """Numeric comparison with abs_tol 1e-3. With ``percent_variants``, pred
    is also compared against {gt/100, gt*100} (`eval_utils.math_equal:195-214`
    — the reference applies this leniency in its OFFLINE EVAL toolkit only).
    Without it, the x100 variants are accepted only when either side carries
    an explicit '%': a LIVE TRAINING reward that accepted '0.5' for '50'
    unconditionally would be a reward-hacking surface the reference's
    training-path grader (`grpo_r1.py:216-224`) does not have."""
    import math

    pv, gv = _digit_value(pred_raw), _digit_value(gt_raw)
    if pv is None or gv is None:
        return None
    lenient = percent_variants or any(
        "%" in s or "\\%" in s or "percent" in s.lower()
        for s in (pred_raw, gt_raw)
    )
    variants = (gv / 100.0, gv, gv * 100.0) if lenient else (gv,)
    return any(
        math.isclose(pv, g, rel_tol=1e-9, abs_tol=1e-3) for g in variants
    )


_MAT_ENVS = ("pmatrix", "bmatrix")


def _matrix_rows(s: str):
    for env in _MAT_ENVS:
        pre, post = f"\\begin{{{env}}}", f"\\end{{{env}}}"
        for env2 in _MAT_ENVS:  # mixed pmatrix/bmatrix graded alike
            post2 = f"\\end{{{env2}}}"
            if s.startswith(pre) and s.endswith(post2):
                body = s[len(pre): -len(post2)]
                return [
                    row.split("&") for row in body.split("\\\\") if row.strip()
                ]
    return None


_REL_CANON = (("\\leq", "<="), ("\\le", "<="), ("\\geq", ">="), ("\\ge", ">="),
              ("\\lt", "<"), ("\\gt", ">"), ("\\neq", "!="), ("\\ne", "!="))


def _canon_rel(s: str) -> str:
    for latex, op in _REL_CANON:
        s = s.replace(latex, op)
    return s


def _has_rel_op(s: str) -> bool:
    return any(op in s for op in ("<=", ">=", "<", ">"))


def _relational_equal(a: str, b: str) -> bool:
    """x <= 5 vs 5 >= x etc: canonicalize the sympy Relational (variable on
    the left) then require the same operator and a zero lhs-rhs difference."""
    try:
        import sympy

        ea, eb = _parse_sympy(a), _parse_sympy(b)
        if not (isinstance(ea, sympy.core.relational.Relational)
                and isinstance(eb, sympy.core.relational.Relational)):
            return False
        ca, cb = ea.canonical, eb.canonical
        if ca.rel_op != cb.rel_op:
            return False
        return sympy.simplify((ca.lhs - ca.rhs) - (cb.lhs - cb.rhs)) == 0
    except Exception:
        return False


def _inequation_equal(a: str, b: str) -> bool:
    """x != 5 vs 5 != x: the lhs-rhs differences must match up to sign."""
    if a.count("!=") != 1 or b.count("!=") != 1:
        return False
    try:
        import sympy

        al, ar = a.split("!=")
        bl, br = b.split("!=")
        da = _parse_sympy(al) - _parse_sympy(ar)
        db = _parse_sympy(bl) - _parse_sympy(br)
        return bool(
            sympy.simplify(da - db) == 0 or sympy.simplify(da + db) == 0
        )
    except Exception:
        return False


def _equation_equal(a: str, b: str) -> bool | None:
    """Both sides single '=' (`eval_utils.math_equal:255-266`): lhs-rhs must
    match up to global sign; 'x=5' vs '5' (lhs <= 2 chars) compares the rhs."""
    ca, cb = a.count("="), b.count("=")
    if ca == 1 and cb == 1:
        try:
            import sympy

            al, ar = a.split("=")
            bl, br = b.split("=")
            da = _parse_sympy(al) - _parse_sympy(ar)
            db = _parse_sympy(bl) - _parse_sympy(br)
            return bool(
                sympy.simplify(da - db) == 0 or sympy.simplify(da + db) == 0
            )
        except Exception:
            return False
    if ca == 1 and cb == 0:
        lhs, rhs = a.split("=")
        if len(lhs) <= 2:
            return math_answers_equal(rhs, b)
    if cb == 1 and ca == 0:
        lhs, rhs = b.split("=")
        if len(lhs) <= 2:
            return math_answers_equal(a, rhs)
    return None


def _split_top_level(s: str, sep: str = ",") -> list[str]:
    """Split on `sep` only at bracket depth 0 (over (), [], {})."""
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        if ch == sep and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    return parts


def math_answers_equal(
    pred: str, gt: str, percent_variants: bool = False
) -> bool:
    """Equivalence ladder, reference-toolkit breadth (VERDICT r1 #4):
    string → numeric (percent-aware; x100 variants only when a '%' marker
    appears or ``percent_variants`` is set — eval paths pass True for
    `eval_utils.math_equal` parity, training rewards stay strict) →
    \\cup unions → matrices → intervals/tuples → relations/equations →
    normalized → \\pm branches → numeric → sympy symbolic (with
    numeric-closeness fallback).
    No subprocess here — wrap in call_with_timeout for that.
    """
    if pred is None or gt is None:
        return False
    if pred.strip() == gt.strip():
        return True

    # numeric first, on the RAW strings (normalization strips '%', which
    # must influence the value first)
    num = _digits_equal(pred, gt, percent_variants=percent_variants)
    if num is not None:
        return num

    a_s, b_s = _light_clean(pred), _light_clean(gt)
    # set unions FIRST: order-free bipartite coverage of the pieces,
    # matching `eval_script.is_correct:28-33` (which recurses into the list
    # path). Must run before the brace-set branch — "\{1\}\cup\{2\}" both
    # starts with \{ and ends with \}, and treating the whole union as one
    # set mangles its elements.
    if "\\cup" in a_s or "\\cup" in b_s:
        pa, pb = a_s.split("\\cup"), b_s.split("\\cup")
        if len(pa) != len(pb):
            return False
        return all(
            any(math_answers_equal(x, y) for y in pb) for x in pa
        ) and all(
            any(math_answers_equal(x, y) for x in pa) for y in pb
        )
    # finite brace sets \{...\}: order-free symmetric coverage of the
    # TOP-LEVEL elements ({1,2} == {2,1} — FiniteSet semantics; elements
    # may themselves be tuples/intervals, so the comma split is depth-aware)
    if (a_s.startswith("\\{") and a_s.endswith("\\}")
            and b_s.startswith("\\{") and b_s.endswith("\\}")):
        ea = [x for x in _split_top_level(a_s[2:-2]) if x.strip()]
        eb = [x for x in _split_top_level(b_s[2:-2]) if x.strip()]
        return (
            all(any(math_answers_equal(x, y) for y in eb) for x in ea)
            and all(any(math_answers_equal(x, y) for x in ea) for y in eb)
        )
    # matrices: rows by \\\\, columns by &, env type ignored
    # (`eval_utils.math_equal:233-253`)
    ma, mb = _matrix_rows(a_s), _matrix_rows(b_s)
    if ma is not None and mb is not None:
        return len(ma) == len(mb) and all(
            len(ra) == len(rb)
            and all(math_answers_equal(x, y) for x, y in zip(ra, rb))
            for ra, rb in zip(ma, mb)
        )
    # intervals/tuples: elementwise; bracket TYPES are not compared — the
    # reference's regex accepts any ([ ... )] pairing (`eval_utils:225-231`)
    if (
        len(a_s) >= 2 and len(b_s) >= 2
        and a_s[0] in "([" and a_s[-1] in ")]"
        and b_s[0] in "([" and b_s[-1] in ")]"
        and "," in a_s and "," in b_s
    ):
        pa, pb = a_s[1:-1].split(","), b_s[1:-1].split(",")
        if len(pa) == len(pb) and all(
            math_answers_equal(x, y) for x, y in zip(pa, pb)
        ):
            return True
    # relations (<=, <, ...) and single-'=' equations, BEFORE normalization
    # strips assignment prefixes
    ra, rb = _canon_rel(a_s), _canon_rel(b_s)
    # != first: its '=' would otherwise route into the equation branch,
    # where splitting at '=' turns 'x!' into factorial(x)
    if "!=" in ra or "!=" in rb:
        if ("!=" in ra) != ("!=" in rb):
            return False
        return _inequation_equal(ra, rb)
    if _has_rel_op(ra) or _has_rel_op(rb):
        if _has_rel_op(ra) != _has_rel_op(rb):
            return False
        return _relational_equal(ra, rb)
    if "=" in ra or "=" in rb:
        eq = _equation_equal(ra, rb)
        if eq is not None:
            return eq

    a, b = normalize_math_answer(pred), normalize_math_answer(gt)
    if a == b:
        return True
    if not a or not b:
        return False
    # \pm answers: the branch SETS must match symmetrically, and an explicit
    # pair/set on the other side counts as its branches (2\pm 1 == {1, 3})
    if "\\pm" in a or "\\pm" in b:
        ea, eb = _branch_set(a), _branch_set(b)
        return (
            all(any(math_answers_equal(x, y) for y in eb) for x in ea)
            and all(any(math_answers_equal(x, y) for x in ea) for y in eb)
        )
    num = _numeric_equal(a, b)
    if num is not None:
        return num
    return _sympy_equal(a, b)


# ---------------------------------------------------------------------------
# timeout guard
# ---------------------------------------------------------------------------


def _grade_worker(pred, gt, q):
    try:
        q.put(math_answers_equal(pred, gt))
    except Exception:
        q.put(False)


def _ensure_sympy_loaded():
    """Import sympy in the parent once, so forked grader children inherit the
    loaded module instead of paying a multi-second import inside their tiny
    timeout budget (the reference's 0.015 s only works because its parent
    imported the toolkit at module load)."""
    import sympy  # noqa: F401
    import sympy.parsing.sympy_parser  # noqa: F401


_logger = logging.getLogger("nanorlhf_tpu.rewards")
_GRADER_CTX = None


def _grader_context():
    """Grader subprocess context, created once.

    Default: `fork` with the parent's preloaded sympy — fast (<1 ms spawn)
    but forks the (threaded) JAX parent; a wedged child is bounded by
    join+terminate and LOGGED (see call_with_timeout), so silent reward
    corruption is observable (ADVICE r1).

    `NANORLHF_GRADER_START_METHOD=forkserver` opts into forking from a
    single-threaded server instead — eliminates the fork-under-threads
    deadlock class entirely, at the price of spawn start-method semantics:
    children re-import `__main__` (grading funcs defined in a REPL/stdin
    fail, and launcher modules must be import-safe) and each child pays the
    server round-trip.
    """
    global _GRADER_CTX
    if _GRADER_CTX is None:
        method = os.environ.get("NANORLHF_GRADER_START_METHOD", "fork")
        if method == "forkserver":
            ctx = multiprocessing.get_context("forkserver")
            ctx.set_forkserver_preload(
                ["sympy", "sympy.parsing.sympy_parser",
                 "nanorlhf_tpu.rewards.math_grader"]
            )
            _GRADER_CTX = ctx
        else:
            _ensure_sympy_loaded()
            _GRADER_CTX = multiprocessing.get_context("fork")
    return _GRADER_CTX


def call_with_timeout(func, *args, timeout: float = 0.5):
    """Run func(*args, queue) in a subprocess; False on timeout or exception.

    Same contract as the reference's guard (`grpo_r1.py:179-192`): the child
    receives an extra Queue argument and must put its result there. join +
    terminate bounds the wait even if the child wedges. Every
    timeout/terminate/no-result path is LOGGED — a graded-False caused by
    infrastructure rather than a wrong answer must be observable, since it
    corrupts the reward signal silently otherwise.
    """
    global _GRADER_CTX
    ctx = _grader_context()
    q = ctx.Queue()
    try:
        p = ctx.Process(target=func, args=args + (q,))
        p.start()
    except Exception as e:
        # e.g. unpicklable func under forkserver: fall back to plain fork
        # PERSISTENTLY — re-attempting a doomed forkserver spawn on every one
        # of thousands of per-rollout grades would pay the failure each time
        _logger.warning("grader forkserver spawn failed (%s); using fork", e)
        _ensure_sympy_loaded()
        ctx = multiprocessing.get_context("fork")
        _GRADER_CTX = ctx
        q = ctx.Queue()
        p = ctx.Process(target=func, args=args + (q,))
        p.start()
    p.join(timeout)
    if p.is_alive():
        from nanorlhf_tpu.resilience import reap_process

        reap_process(p)
        _logger.warning(
            "grader timed out after %.3fs — graded False (func=%s)",
            timeout, getattr(func, "__name__", func),
        )
        return False
    try:
        return q.get(timeout=0.1)
    except Exception:
        _logger.warning(
            "grader child exited without a result (rc=%s) — graded False",
            p.exitcode,
        )
        return False


def is_correct(pred: str, gt: str, timeout: float = 0.5, use_subprocess: bool = True) -> bool:
    """Full grader: exact match fast path, then timeout-guarded equivalence.

    `iscorrect` parity (`grpo_r1.py:216-224`). `use_subprocess=False` runs
    in-process (tests / trusted inputs; much faster on 1-core hosts).
    """
    if not pred:
        return False
    if pred.strip() == gt.strip():
        return True
    if use_subprocess:
        return bool(call_with_timeout(_grade_worker, pred, gt, timeout=timeout))
    return math_answers_equal(pred, gt)
