"""Sandboxed execution of model-emitted Python — program-of-thought grading.

Capability parity with the vendored Qwen eval toolkit's `PythonExecutor`
(`/root/reference/examples/r1-v0/utils/eval/python_executor.py:42`): run a
code snippet in a killable subprocess with a wall-clock timeout, capture the
value of an `answer` variable (or stdout), never let model code touch the
training process. Host-side only.
"""

from __future__ import annotations

import multiprocessing
import sys
import traceback
from dataclasses import dataclass
from io import StringIO


@dataclass
class ExecutionResult:
    ok: bool
    answer: str = ""
    stdout: str = ""
    error: str = ""


def _exec_worker(code: str, answer_expr: str | None, q):
    buf = StringIO()
    old_stdout = sys.stdout
    sys.stdout = buf
    try:
        glb: dict = {"__name__": "__main__"}
        exec(code, glb)  # noqa: S102 — sandboxed by subprocess + timeout
        answer = ""
        if answer_expr:
            try:
                answer = repr(eval(answer_expr, glb))  # noqa: S307
            except Exception:
                answer = ""
        elif "answer" in glb:
            answer = repr(glb["answer"])
        q.put(("ok", answer, buf.getvalue()))
    except Exception:
        q.put(("err", "", buf.getvalue() + "\n" + traceback.format_exc()))
    finally:
        sys.stdout = old_stdout


class PythonExecutor:
    """`run(code)` → ExecutionResult; `timeout` seconds per snippet."""

    def __init__(self, timeout: float = 5.0, answer_expr: str | None = None):
        self.timeout = timeout
        self.answer_expr = answer_expr

    def run(self, code: str) -> ExecutionResult:
        ctx = multiprocessing.get_context("fork")
        q = ctx.Queue()
        p = ctx.Process(target=_exec_worker, args=(code, self.answer_expr, q))
        p.start()
        p.join(self.timeout)
        if p.is_alive():
            p.terminate()
            p.join()
            return ExecutionResult(ok=False, error=f"timeout after {self.timeout}s")
        try:
            status, answer, stdout = q.get(timeout=0.5)
        except Exception:
            return ExecutionResult(ok=False, error="no result (crashed?)")
        if status == "ok":
            return ExecutionResult(ok=True, answer=answer, stdout=stdout)
        return ExecutionResult(ok=False, stdout=stdout, error=stdout)
