"""Isolated-subprocess execution of model-emitted Python — program-of-thought
grading.

Capability parity with the vendored Qwen eval toolkit's `PythonExecutor`
(`/root/reference/examples/r1-v0/utils/eval/python_executor.py:42`): run a
code snippet in a killable subprocess with a wall-clock timeout, capture the
value of an `answer` variable (or stdout), never let model code crash the
training process. Host-side only.

Containment = process isolation + wall-clock timeout + child resource limits
(CPU seconds, address space, file size) + a scratch working directory. This
is NOT a security sandbox: the child still has host filesystem/network
access with the parent's credentials (same as the reference toolkit) — run
untrusted-model graders inside a containerized host if that matters.
"""

from __future__ import annotations

import multiprocessing
import os
import sys
import tempfile
import time
import traceback
from dataclasses import dataclass
from io import StringIO


@dataclass
class ExecutionResult:
    ok: bool
    answer: str = ""
    stdout: str = ""
    error: str = ""


def _apply_child_limits(cpu_seconds: int, mem_bytes: int | None):
    """Best-effort rlimits in the exec child: bound CPU burn and accidental
    giant file writes. Failures are ignored — limits are hardening, not the
    containment boundary.

    RLIMIT_AS is OPT-IN (`mem_bytes`): under the (non-default) fork context
    the child inherits the training process's mapped virtual address space
    (JAX/TPU runtime), which routinely exceeds any sane fixed cap — a
    default AS limit below the inherited mappings would fail every snippet
    with MemoryError. Spawn children are clean, but the default stays
    opt-in so both contexts behave identically.
    """
    try:
        import resource

        resource.setrlimit(resource.RLIMIT_CPU, (cpu_seconds, cpu_seconds + 1))
        if mem_bytes is not None:
            resource.setrlimit(resource.RLIMIT_AS, (mem_bytes, mem_bytes))
        resource.setrlimit(resource.RLIMIT_FSIZE, (64 * 1024**2, 64 * 1024**2))
    except Exception:
        pass
    try:
        scratch = tempfile.mkdtemp(prefix="nanorlhf_exec_")
        os.chdir(scratch)
    except Exception:
        pass


def _exec_worker(code: str, answer_expr: str | None, q,
                 cpu_seconds: int = 10, mem_bytes: int | None = None,
                 ready=None):
    if ready is not None:
        # bootstrap fence: under the spawn context the child re-imports the
        # parent's __main__ before this line runs (seconds, if the launcher
        # module pulls jax) — the parent starts the snippet's wall-clock
        # timeout only once this fires, so bootstrap cost is never charged
        # against the snippet's budget
        ready.set()
    _apply_child_limits(cpu_seconds, mem_bytes)
    buf = StringIO()
    old_stdout = sys.stdout
    sys.stdout = buf
    try:
        glb: dict = {"__name__": "__main__"}
        exec(code, glb)  # noqa: S102 — isolated subprocess + timeout + rlimits
        answer = ""
        if answer_expr:
            try:
                answer = repr(eval(answer_expr, glb))  # noqa: S307
            except Exception:
                answer = ""
        elif "answer" in glb:
            answer = repr(glb["answer"])
        q.put(("ok", answer, buf.getvalue()))
    except Exception:
        q.put(("err", "", buf.getvalue() + "\n" + traceback.format_exc()))
    finally:
        sys.stdout = old_stdout


def _pool_worker(jobs, results, answer_expr: str | None,
                 cpu_seconds: int, mem_bytes: int | None, ready):
    """Persistent exec loop: one spawn bootstrap, many snippets.

    Same body as `_exec_worker` per job, but the ready handshake and the
    rlimit/scratch-dir setup are paid ONCE; after that each job is a
    (job_id, code) → (job_id, status, answer, stdout) round trip. A None
    job is the shutdown sentinel. NOTE: RLIMIT_CPU is cumulative across
    every snippet this worker ever runs — the parent's per-job wall-clock
    timeout plus terminate→kill reaping is the real per-job bound, and a
    worker killed mid-job is simply respawned on the next call.
    """
    ready.set()
    _apply_child_limits(cpu_seconds, mem_bytes)
    while True:
        job = jobs.get()
        if job is None:
            return
        job_id, code = job
        buf = StringIO()
        old_stdout = sys.stdout
        sys.stdout = buf
        try:
            glb: dict = {"__name__": "__main__"}
            exec(code, glb)  # noqa: S102 — isolated subprocess + timeout + rlimits
            answer = ""
            if answer_expr:
                try:
                    answer = repr(eval(answer_expr, glb))  # noqa: S307
                except Exception:
                    answer = ""
            elif "answer" in glb:
                answer = repr(glb["answer"])
            results.put((job_id, "ok", answer, buf.getvalue()))
        except Exception:
            results.put((job_id, "err", "",
                         buf.getvalue() + "\n" + traceback.format_exc()))
        finally:
            sys.stdout = old_stdout


class PythonExecutor:
    """`run(code)` → ExecutionResult; `timeout` seconds per snippet.

    Children come from the `spawn` multiprocessing context: grader workers
    run inside the training process, and a fork would duplicate the mapped
    JAX/TPU runtime state (device handles, the libtpu lock, orbax's async
    machinery) into a child that then exec's arbitrary model code — the
    classic fork-after-threads hazard. `spawn` starts from a clean
    interpreter, BUT its bootstrap re-imports the parent's __main__ module
    — seconds when training launched via `python -m nanorlhf_tpu.
    entrypoints.*` (the `__main__` guard stops re-training, not the
    module-level jax imports). The snippet timeout therefore only starts
    at the child's ready handshake; `bootstrap_timeout` bounds the respawn
    itself. Pass mp_context="fork" only in jax-free host tools that need
    the lower startup latency."""

    def __init__(self, timeout: float = 5.0, answer_expr: str | None = None,
                 cpu_seconds: int = 10, mem_bytes: int | None = None,
                 mp_context: str = "spawn", term_grace: float = 2.0,
                 bootstrap_timeout: float = 60.0):
        self.timeout = timeout
        self.answer_expr = answer_expr
        self.cpu_seconds = cpu_seconds
        self.mem_bytes = mem_bytes
        self.mp_context = mp_context
        self.term_grace = term_grace
        self.bootstrap_timeout = bootstrap_timeout

    def run(self, code: str) -> ExecutionResult:
        ctx = multiprocessing.get_context(self.mp_context)
        q = ctx.Queue()
        ready = ctx.Event()
        p = ctx.Process(
            target=_exec_worker,
            args=(code, self.answer_expr, q, self.cpu_seconds, self.mem_bytes,
                  ready),
        )
        p.start()
        # bootstrap is metered separately from the snippet (spawn re-import
        # cost must not eat the grading budget). Poll in short slices with a
        # liveness check: a child that dies during bootstrap never sets
        # `ready`, and a blind wait would stall the full bootstrap budget
        # per snippet; a dead child falls straight through to the result
        # read below ("no result").
        deadline = time.monotonic() + self.bootstrap_timeout
        while (not ready.is_set() and p.is_alive()
               and time.monotonic() < deadline):
            ready.wait(0.05)
        p.join(self.timeout)
        if p.is_alive():
            from nanorlhf_tpu.resilience import reap_process

            reap_process(p, self.term_grace)
            return ExecutionResult(ok=False, error=f"timeout after {self.timeout}s")
        try:
            status, answer, stdout = q.get(timeout=0.5)
        except Exception:
            return ExecutionResult(ok=False, error="no result (crashed?)")
        if status == "ok":
            return ExecutionResult(ok=True, answer=answer, stdout=stdout)
        return ExecutionResult(ok=False, stdout=stdout, error=stdout)


class PooledPythonExecutor:
    """`run(code)` against ONE warm worker process reused across calls.

    The spawn-context bootstrap fence in `PythonExecutor` costs seconds per
    child (the re-import of the parent's __main__ pulls jax); a terminal
    grader pays that once per sample, but a mid-episode tool
    (envs/python_tool.py) would pay it once per TURN. Here the fence is
    paid once at (re)spawn: steady-state calls are a queue round trip into
    the warm worker. The containment story is unchanged — same rlimits,
    same per-call wall-clock `timeout`, same terminate→kill escalation
    (`reap_process`) on overrun; a reaped worker is respawned lazily on
    the next call, and monotonically increasing job ids let the parent
    discard any stale result a killed worker managed to flush.

    `run` is serialized under `make_lock("rewards.executor")` (declared in
    analysis/lockorder.py) so the multi-turn driver's tool threads share
    one warm worker safely; it never acquires other project locks.
    """

    def __init__(self, timeout: float = 5.0, answer_expr: str | None = None,
                 cpu_seconds: int = 60, mem_bytes: int | None = None,
                 mp_context: str = "spawn", term_grace: float = 2.0,
                 bootstrap_timeout: float = 60.0):
        from nanorlhf_tpu.analysis.lockorder import make_lock

        self.timeout = timeout
        self.answer_expr = answer_expr
        # default cpu_seconds is higher than PythonExecutor's: RLIMIT_CPU
        # accumulates over the worker's whole life, not per snippet
        self.cpu_seconds = cpu_seconds
        self.mem_bytes = mem_bytes
        self.mp_context = mp_context
        self.term_grace = term_grace
        self.bootstrap_timeout = bootstrap_timeout
        self._lock = make_lock("rewards.executor")
        self._proc = None
        self._jobs = None
        self._results = None
        self._next_job = 0

    @property
    def worker_pid(self) -> int | None:
        """Pid of the live worker (None before first run / after reap) —
        the pooling regression test pins this constant across calls."""
        p = self._proc
        return p.pid if p is not None and p.is_alive() else None

    def _ensure_worker(self) -> bool:
        if self._proc is not None and self._proc.is_alive():
            return True
        ctx = multiprocessing.get_context(self.mp_context)
        self._jobs = ctx.Queue()
        self._results = ctx.Queue()
        ready = ctx.Event()
        self._proc = ctx.Process(
            target=_pool_worker,
            args=(self._jobs, self._results, self.answer_expr,
                  self.cpu_seconds, self.mem_bytes, ready),
            daemon=True,
        )
        self._proc.start()
        deadline = time.monotonic() + self.bootstrap_timeout
        while (not ready.is_set() and self._proc.is_alive()
               and time.monotonic() < deadline):
            ready.wait(0.05)
        return ready.is_set()

    def _reap(self):
        from nanorlhf_tpu.resilience import reap_process

        if self._proc is not None:
            reap_process(self._proc, self.term_grace)
        self._proc = None

    def run(self, code: str) -> ExecutionResult:
        with self._lock:
            if not self._ensure_worker():
                self._reap()
                return ExecutionResult(ok=False, error="worker bootstrap failed")
            job_id = self._next_job
            self._next_job += 1
            self._jobs.put((job_id, code))
            deadline = time.monotonic() + self.timeout
            while True:
                try:
                    rid, status, answer, stdout = self._results.get(
                        timeout=max(0.0, deadline - time.monotonic()) + 0.05)
                except Exception:
                    rid = None
                if rid == job_id:
                    if status == "ok":
                        return ExecutionResult(ok=True, answer=answer,
                                               stdout=stdout)
                    return ExecutionResult(ok=False, stdout=stdout,
                                           error=stdout)
                if rid is not None and rid < job_id:
                    continue  # stale flush from a previously killed job
                if not self._proc.is_alive():
                    self._reap()
                    return ExecutionResult(ok=False,
                                           error="no result (crashed?)")
                # timed out: kill the wedged worker; next call respawns
                self._reap()
                return ExecutionResult(
                    ok=False, error=f"timeout after {self.timeout}s")

    def close(self):
        with self._lock:
            if self._proc is not None and self._proc.is_alive():
                try:
                    self._jobs.put(None)
                    self._proc.join(self.term_grace)
                except Exception:
                    pass
                if self._proc.is_alive():
                    self._reap()
            self._proc = None
