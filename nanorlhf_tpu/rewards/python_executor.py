"""Isolated-subprocess execution of model-emitted Python — program-of-thought
grading.

Capability parity with the vendored Qwen eval toolkit's `PythonExecutor`
(`/root/reference/examples/r1-v0/utils/eval/python_executor.py:42`): run a
code snippet in a killable subprocess with a wall-clock timeout, capture the
value of an `answer` variable (or stdout), never let model code crash the
training process. Host-side only.

Containment = process isolation + wall-clock timeout + child resource limits
(CPU seconds, address space, file size) + a scratch working directory. This
is NOT a security sandbox: the child still has host filesystem/network
access with the parent's credentials (same as the reference toolkit) — run
untrusted-model graders inside a containerized host if that matters.
"""

from __future__ import annotations

import multiprocessing
import os
import sys
import tempfile
import traceback
from dataclasses import dataclass
from io import StringIO


@dataclass
class ExecutionResult:
    ok: bool
    answer: str = ""
    stdout: str = ""
    error: str = ""


def _apply_child_limits(cpu_seconds: int, mem_bytes: int | None):
    """Best-effort rlimits in the exec child: bound CPU burn and accidental
    giant file writes. Failures are ignored — limits are hardening, not the
    containment boundary.

    RLIMIT_AS is OPT-IN (`mem_bytes`): the child forks from the training
    process, whose mapped virtual address space (JAX/TPU runtime) routinely
    exceeds any sane fixed cap — a default AS limit below the inherited
    mappings would fail every snippet with MemoryError.
    """
    try:
        import resource

        resource.setrlimit(resource.RLIMIT_CPU, (cpu_seconds, cpu_seconds + 1))
        if mem_bytes is not None:
            resource.setrlimit(resource.RLIMIT_AS, (mem_bytes, mem_bytes))
        resource.setrlimit(resource.RLIMIT_FSIZE, (64 * 1024**2, 64 * 1024**2))
    except Exception:
        pass
    try:
        scratch = tempfile.mkdtemp(prefix="nanorlhf_exec_")
        os.chdir(scratch)
    except Exception:
        pass


def _exec_worker(code: str, answer_expr: str | None, q,
                 cpu_seconds: int = 10, mem_bytes: int | None = None):
    _apply_child_limits(cpu_seconds, mem_bytes)
    buf = StringIO()
    old_stdout = sys.stdout
    sys.stdout = buf
    try:
        glb: dict = {"__name__": "__main__"}
        exec(code, glb)  # noqa: S102 — isolated subprocess + timeout + rlimits
        answer = ""
        if answer_expr:
            try:
                answer = repr(eval(answer_expr, glb))  # noqa: S307
            except Exception:
                answer = ""
        elif "answer" in glb:
            answer = repr(glb["answer"])
        q.put(("ok", answer, buf.getvalue()))
    except Exception:
        q.put(("err", "", buf.getvalue() + "\n" + traceback.format_exc()))
    finally:
        sys.stdout = old_stdout


class PythonExecutor:
    """`run(code)` → ExecutionResult; `timeout` seconds per snippet."""

    def __init__(self, timeout: float = 5.0, answer_expr: str | None = None,
                 cpu_seconds: int = 10, mem_bytes: int | None = None):
        self.timeout = timeout
        self.answer_expr = answer_expr
        self.cpu_seconds = cpu_seconds
        self.mem_bytes = mem_bytes

    def run(self, code: str) -> ExecutionResult:
        ctx = multiprocessing.get_context("fork")
        q = ctx.Queue()
        p = ctx.Process(
            target=_exec_worker,
            args=(code, self.answer_expr, q, self.cpu_seconds, self.mem_bytes),
        )
        p.start()
        p.join(self.timeout)
        if p.is_alive():
            p.terminate()
            p.join()
            return ExecutionResult(ok=False, error=f"timeout after {self.timeout}s")
        try:
            status, answer, stdout = q.get(timeout=0.5)
        except Exception:
            return ExecutionResult(ok=False, error="no result (crashed?)")
        if status == "ok":
            return ExecutionResult(ok=True, answer=answer, stdout=stdout)
        return ExecutionResult(ok=False, stdout=stdout, error=stdout)
