from nanorlhf_tpu.sampler.sampler import SamplingParams, generate, generate_tokens

__all__ = ["SamplingParams", "generate", "generate_tokens"]
