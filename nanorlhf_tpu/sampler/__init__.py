from nanorlhf_tpu.sampler.sampler import (
    SamplingParams, compose_check, generate, generate_tokens,
)
from nanorlhf_tpu.sampler.speculative import generate_tokens_spec

__all__ = [
    "SamplingParams", "compose_check", "generate", "generate_tokens",
    "generate_tokens_spec", "generate_tokens_queued",
]


def __getattr__(name):
    # lazy: the paged scheduler imports back into sampler.py at call time
    if name == "generate_tokens_queued":
        from nanorlhf_tpu.sampler.paged.scheduler import generate_tokens_queued
        return generate_tokens_queued
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
