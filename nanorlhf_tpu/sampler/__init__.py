from nanorlhf_tpu.sampler.sampler import SamplingParams, generate, generate_tokens
from nanorlhf_tpu.sampler.speculative import generate_tokens_spec

__all__ = [
    "SamplingParams", "generate", "generate_tokens", "generate_tokens_spec",
]
