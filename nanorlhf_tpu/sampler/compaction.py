"""Compacting decode: the TPU-idiomatic analogue of vLLM's continuous batching.

The monolithic decode loop (`sampler.generate_tokens`) runs until EVERY row
has emitted EOS — each straggler drags the whole batch through full-batch
steps (the exact cost vLLM's continuous batching avoids with its CUDA
scheduler, `/root/reference/GRPO/grpo_trainer.py:122-166`). Dynamic batches
are impossible under XLA's static shapes, so this module gets the same
effect with a POWER-OF-TWO BATCH MENU:

  prefill [B] → decode a SEGMENT (max_tokens / segments steps) → host sync:
  flush finished rows to the output buffer; if the live rows fit in a
  half-or-smaller menu batch, GATHER them (KV caches move with their rows —
  slot layout is untouched because all rows share the same step alignment)
  → continue decoding at the smaller batch.

Each distinct batch size compiles once (a handful of sizes; cached across
updates). Sampling keys are fold_in(base, step) — identical streams across
segment boundaries — but a compacted row changes its ROW INDEX inside the
batch, so draws diverge from the monolithic path after the first
compaction: same distribution, different stream. Off by default
(`SamplingParams.compaction_segments=0`).

Interaction with `rollout_ahead`: this path blocks the host at every
segment boundary, so a prefetch-dispatched compacting rollout executes
eagerly inside dispatch() instead of overlapping — combine them only when
reward grading is the dominant host cost and segments are coarse.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from nanorlhf_tpu.core.config import ModelConfig
from nanorlhf_tpu.sampler.sampler import _decode_body, _prefill_state

_MIN_BATCH = 8

_prefill_state_jit = partial(
    jax.jit,
    static_argnames=("config", "max_tokens", "eos_token_id", "pad_token_id",
                     "temperature", "top_p", "greedy", "lora_scale", "top_k",
                     "capture_logprobs", "approx_top_k"),
)(_prefill_state)


@partial(
    jax.jit,
    static_argnames=("config", "Tp", "max_tokens", "eos_token_id",
                     "pad_token_id", "temperature", "top_p", "greedy",
                     "lora_scale", "top_k", "capture_logprobs",
                     "approx_top_k"),
)
def _decode_segment(params, config, state, seg_end, *, Tp, max_tokens,
                    eos_token_id, pad_token_id, temperature, top_p, greedy,
                    lora_scale, top_k, capture_logprobs, approx_top_k):
    """Run the decode loop until `seg_end` (dynamic) or all rows done."""

    def cond(state):
        return (state[0] < jnp.minimum(seg_end, max_tokens)) & ~jnp.all(state[5])

    def body(state):
        return _decode_body(
            params, config, state, Tp=Tp, max_tokens=max_tokens,
            eos_token_id=eos_token_id, pad_token_id=pad_token_id,
            temperature=temperature, top_p=top_p, greedy=greedy,
            lora_scale=lora_scale, top_k=top_k,
            capture_logprobs=capture_logprobs, approx_top_k=approx_top_k,
        )

    return jax.lax.while_loop(cond, body, state)


@jax.jit
def _gather_rows(state, idx):
    """Row-gather the whole carry state (caches gather on their batch axis)."""
    step, out, lp_out, caches, key_mask, done, cur_tok, prompt_len, key = state
    take = lambda x: jnp.take(x, idx, axis=0)
    caches = tuple(jnp.take(c, idx, axis=1) for c in caches)  # [L, B, ...]
    return (step, take(out), take(lp_out), caches, take(key_mask),
            take(done), take(cur_tok), take(prompt_len), key)


def generate_tokens_compact(
    params: dict,
    config: ModelConfig,
    prompt_ids: jnp.ndarray,
    prompt_mask: jnp.ndarray,
    key: jax.Array,
    *,
    max_tokens: int,
    eos_token_id: int,
    pad_token_id: int,
    segments: int,
    temperature: float = 1.0,
    top_p: float = 0.95,
    greedy: bool = False,
    lora_scale: float = 1.0,
    top_k: int = 64,
    capture_logprobs: bool = False,
    approx_top_k: bool = True,
):
    """Segmented decode with batch compaction. Same output contract as
    `generate_tokens`; host-orchestrated (syncs once per segment)."""
    B0, Tp = prompt_ids.shape
    kw = dict(
        max_tokens=max_tokens, eos_token_id=eos_token_id,
        pad_token_id=pad_token_id, temperature=temperature, top_p=top_p,
        greedy=greedy, lora_scale=lora_scale, top_k=top_k,
        capture_logprobs=capture_logprobs, approx_top_k=approx_top_k,
    )
    state = _prefill_state_jit(params, config, prompt_ids, prompt_mask, key,
                               **kw)

    final_out = np.full((B0, max_tokens), pad_token_id, np.int32)
    final_lp = np.zeros((B0, max_tokens), np.float32)
    # owner[j] = original row the j-th physical row writes to; padding
    # duplicates (menu round-up) carry owner -1 and never flush
    owner = np.arange(B0)
    seg = max(1, -(-max_tokens // max(segments, 1)))

    def flush(rows, out_np, lp_np):
        live_owner = owner[rows]
        keep = live_owner >= 0
        final_out[live_owner[keep]] = out_np[rows[keep]]
        if capture_logprobs:
            final_lp[live_owner[keep]] = lp_np[rows[keep]]

    step = 1
    while step < max_tokens:
        state = _decode_segment(params, config, state,
                                jnp.int32(min(step + seg, max_tokens)), Tp=Tp,
                                **kw)
        step = int(state[0])
        done = np.asarray(state[5])
        if done.all() or step >= max_tokens:
            break
        live = np.where(~done)[0]
        target = max(_MIN_BATCH, 1 << (len(live) - 1).bit_length())
        if target <= len(done) // 2:
            # flush finished rows, then gather the live ones (+ pad
            # duplicates of live[0], owner -1) into the smaller batch
            out_np, lp_np = np.asarray(state[1]), np.asarray(state[2])
            flush(np.where(done)[0], out_np, lp_np)
            idx = np.concatenate(
                [live, np.repeat(live[:1], target - len(live))]
            )
            new_owner = owner[idx]
            new_owner[len(live):] = -1
            state = _gather_rows(state, jnp.asarray(idx, jnp.int32))
            owner = new_owner
            if len(live) < target:
                # padding duplicates must read as DONE, or they keep sampling
                # independently and can hold the whole batch at max_tokens
                # after every real row finished
                state = list(state)
                state[5] = state[5].at[len(live):].set(True)
                state = tuple(state)

    out_np, lp_np = np.asarray(state[1]), np.asarray(state[2])
    flush(np.arange(len(owner)), out_np, lp_np)
    out = jnp.asarray(final_out)
    return (out, jnp.asarray(final_lp)) if capture_logprobs else out
