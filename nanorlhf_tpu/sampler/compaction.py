"""Compacting decode — LEGACY, contiguous-cache-layout only.

DEPRECATED in favor of the paged KV cache: `SamplingParams.page_size > 0`
with `decode_rows > 0` (sampler/paged/, docs/PAGED_CACHE.md) recycles
finished rows' cache pages to QUEUED prompts mid-loop — true continuous
batching rather than this module's batch-shrink approximation — and, unlike
this path, composes with speculative decode. This module stays for the
contiguous layout (it gathers per-row [T_max] cache slabs, which the paged
pool doesn't have; `generate` raises on page_size > 0 + compaction).

The monolithic decode loop (`sampler.generate_tokens`) runs until EVERY row
has emitted EOS — each straggler drags the whole batch through full-batch
steps (the exact cost vLLM's continuous batching avoids with its CUDA
scheduler, `/root/reference/GRPO/grpo_trainer.py:122-166`). Dynamic batches
are impossible under XLA's static shapes, so this module gets the same
effect with a POWER-OF-TWO BATCH MENU:

  prefill [B] → decode a SEGMENT (max_tokens / segments steps) → host sync:
  flush finished rows to the output buffer; if the live rows fit in a
  half-or-smaller menu batch, GATHER them (KV caches move with their rows —
  slot layout is untouched because all rows share the same step alignment)
  → continue decoding at the smaller batch.

Each distinct batch size compiles once (a handful of sizes; cached across
updates). Sampling keys are fold_in(base, step) — identical streams across
segment boundaries — but a compacted row changes its ROW INDEX inside the
batch, so draws diverge from the monolithic path after the first
compaction: same distribution, different stream. Off by default
(`SamplingParams.compaction_segments=0`).

Interaction with `rollout_ahead`: this path blocks the host at every
segment boundary, so a prefetch-dispatched compacting rollout executes
eagerly inside dispatch() instead of overlapping — combine them only when
reward grading is the dominant host cost and segments are coarse.

Interaction with speculative decode (`SamplingParams.spec_k`,
sampler/speculative.py): MUTUALLY EXCLUSIVE — the row gather above moves
KV caches without touching slot layout precisely because all live rows
share the same step alignment (row r's token t always sits in slot Tp+t),
while speculative accept lengths advance rows at different rates and break
that invariant. `generate` raises on the combination. The paged scheduler
has no such restriction — per-row fill is native there — so
straggler-dominated AND self-repetitive corpora both route through
`page_size` + `decode_rows` (+ `spec_k`); reach for this module only when
the contiguous layout itself is required.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from nanorlhf_tpu.core.config import ModelConfig
from nanorlhf_tpu.sampler.sampler import _decode_body, _prefill_state

_MIN_BATCH = 8

_prefill_state_jit = partial(
    jax.jit,
    static_argnames=("config", "max_tokens", "eos_token_id", "pad_token_id",
                     "temperature", "top_p", "greedy", "lora_scale", "top_k",
                     "capture_logprobs", "approx_top_k", "prompt_fanout"),
)(_prefill_state)


@partial(
    jax.jit,
    static_argnames=("config", "Tp", "max_tokens", "eos_token_id",
                     "pad_token_id", "temperature", "top_p", "greedy",
                     "lora_scale", "top_k", "capture_logprobs",
                     "approx_top_k"),
    # donate the carry so XLA aliases the KV-cache buffers across segment
    # boundaries instead of holding two full copies of the cache live
    donate_argnums=(2,),
)
def _decode_segment(params, config, state, seg_end, *, Tp, max_tokens,
                    eos_token_id, pad_token_id, temperature, top_p, greedy,
                    lora_scale, top_k, capture_logprobs, approx_top_k):
    """Run the decode loop until `seg_end` (dynamic) or all rows done."""

    def cond(state):
        return (state[0] < jnp.minimum(seg_end, max_tokens)) & ~jnp.all(state[5])

    def body(state):
        return _decode_body(
            params, config, state, Tp=Tp, max_tokens=max_tokens,
            eos_token_id=eos_token_id, pad_token_id=pad_token_id,
            temperature=temperature, top_p=top_p, greedy=greedy,
            lora_scale=lora_scale, top_k=top_k,
            capture_logprobs=capture_logprobs, approx_top_k=approx_top_k,
        )

    return jax.lax.while_loop(cond, body, state)


# donation can't alias (the output batch is smaller) but frees the old
# cache as soon as the gather has consumed it, instead of holding both
# copies until the host drops its reference
@partial(jax.jit, donate_argnums=(0,))
def _gather_rows(state, idx):
    """Row-gather the whole carry state (caches gather on their batch axis)."""
    step, out, lp_out, caches, key_mask, done, cur_tok, prompt_len, key = state
    take = lambda x: jnp.take(x, idx, axis=0)
    caches = tuple(jnp.take(c, idx, axis=1) for c in caches)  # [L, B, ...]
    return (step, take(out), take(lp_out), caches, take(key_mask),
            take(done), take(cur_tok), take(prompt_len), key)


def _shard_state(state, batch_sharding):
    """Re-lay-out a gathered carry under the caller's batch sharding.

    `jnp.take` inside `_gather_rows` produces outputs under GSPMD's default
    layout choice, which for a gathered (smaller) batch is typically fully
    replicated — silently multiplying KV-cache HBM by the device count.
    Re-device_put each leaf with its batch axis sharded the way the caller
    shards rollout batches (caches carry batch on axis 1, the rest axis 0)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh, batch_axes = batch_sharding.mesh, batch_sharding.spec[0]

    def put(x, axis):
        spec = [None] * x.ndim
        spec[axis] = batch_axes
        return jax.device_put(x, NamedSharding(mesh, P(*spec)))

    step, out, lp_out, caches, key_mask, done, cur_tok, prompt_len, key = state
    caches = tuple(put(c, 1) for c in caches)
    return (step, put(out, 0), put(lp_out, 0), caches, put(key_mask, 0),
            put(done, 0), put(cur_tok, 0), put(prompt_len, 0), key)


def _batch_axis_size(batch_sharding) -> int:
    """Number of devices the batch axis spans (the gather target must stay a
    multiple of this or rows can't be laid out evenly)."""
    axes = batch_sharding.spec[0]
    if axes is None:
        return 1
    if not isinstance(axes, tuple):
        axes = (axes,)
    size = 1
    for a in axes:
        size *= batch_sharding.mesh.shape[a]
    return size


def generate_tokens_compact(
    params: dict,
    config: ModelConfig,
    prompt_ids: jnp.ndarray,
    prompt_mask: jnp.ndarray,
    key: jax.Array,
    *,
    max_tokens: int,
    eos_token_id: int,
    pad_token_id: int,
    segments: int,
    temperature: float = 1.0,
    top_p: float = 0.95,
    greedy: bool = False,
    lora_scale: float = 1.0,
    top_k: int = 64,
    capture_logprobs: bool = False,
    approx_top_k: bool = True,
    batch_sharding=None,
    prompt_fanout: int = 1,
):
    """Segmented decode with batch compaction. Same output contract as
    `generate_tokens`; host-orchestrated (syncs once per segment).

    `batch_sharding` (a NamedSharding with the batch axes in spec[0], as
    produced by `parallel.mesh.batch_sharding`) keeps compaction mesh-aware:
    the gather target is clamped to a multiple of the batch-axis device
    count and the gathered carry is re-laid-out under that sharding, so the
    compacted KV cache stays sharded instead of replicating."""
    B0, Tp = prompt_ids.shape
    B0 = B0 * prompt_fanout  # physical decode rows after shared-prefill fanout
    min_batch = _MIN_BATCH
    if batch_sharding is not None:
        min_batch = max(min_batch, _batch_axis_size(batch_sharding))
    kw = dict(
        max_tokens=max_tokens, eos_token_id=eos_token_id,
        pad_token_id=pad_token_id, temperature=temperature, top_p=top_p,
        greedy=greedy, lora_scale=lora_scale, top_k=top_k,
        capture_logprobs=capture_logprobs, approx_top_k=approx_top_k,
    )
    state = _prefill_state_jit(params, config, prompt_ids, prompt_mask, key,
                               prompt_fanout=prompt_fanout, **kw)

    final_out = np.full((B0, max_tokens), pad_token_id, np.int32)
    final_lp = np.zeros((B0, max_tokens), np.float32)
    # owner[j] = original row the j-th physical row writes to; padding
    # duplicates (menu round-up) carry owner -1 and never flush
    owner = np.arange(B0)
    seg = max(1, -(-max_tokens // max(segments, 1)))

    def flush(rows, out_np, lp_np):
        live_owner = owner[rows]
        keep = live_owner >= 0
        final_out[live_owner[keep]] = out_np[rows[keep]]
        if capture_logprobs:
            final_lp[live_owner[keep]] = lp_np[rows[keep]]

    step = 1
    while step < max_tokens:
        state = _decode_segment(params, config, state,
                                jnp.int32(min(step + seg, max_tokens)), Tp=Tp,
                                **kw)
        step = int(state[0])
        done = np.asarray(state[5])
        if done.all() or step >= max_tokens:
            break
        live = np.where(~done)[0]
        target = max(min_batch, 1 << (len(live) - 1).bit_length())
        # a non-power-of-two batch axis (e.g. data*fsdp=12): the pow2 menu
        # value may not be a multiple of it — round up so rows lay out evenly
        target = -(-target // min_batch) * min_batch
        if target <= len(done) // 2:
            # flush finished rows, then gather the live ones (+ pad
            # duplicates of live[0], owner -1) into the smaller batch
            out_np, lp_np = np.asarray(state[1]), np.asarray(state[2])
            flush(np.where(done)[0], out_np, lp_np)
            idx = np.concatenate(
                [live, np.repeat(live[:1], target - len(live))]
            )
            new_owner = owner[idx]
            new_owner[len(live):] = -1
            state = _gather_rows(state, jnp.asarray(idx, jnp.int32))
            if batch_sharding is not None:
                state = _shard_state(state, batch_sharding)
            owner = new_owner
            if len(live) < target:
                # padding duplicates must read as DONE, or they keep sampling
                # independently and can hold the whole batch at max_tokens
                # after every real row finished
                state = list(state)
                state[5] = state[5].at[len(live):].set(True)
                state = tuple(state)

    out_np, lp_np = np.asarray(state[1]), np.asarray(state[2])
    flush(np.arange(len(owner)), out_np, lp_np)
    out = jnp.asarray(final_out)
    return (out, jnp.asarray(final_lp)) if capture_logprobs else out
