from nanorlhf_tpu.sampler.paged.pages import (
    PageState, init_page_state, alloc_row, release_row, full_table,
    blocks_per_row,
)

__all__ = [
    "PageState", "init_page_state", "alloc_row", "release_row", "full_table",
    "blocks_per_row", "generate_tokens_queued",
]


def __getattr__(name):
    # lazy: scheduler imports sampler.sampler, which imports pages through
    # this package — an eager scheduler import here would close the cycle
    if name == "generate_tokens_queued":
        from nanorlhf_tpu.sampler.paged.scheduler import generate_tokens_queued
        return generate_tokens_queued
    raise AttributeError(name)
