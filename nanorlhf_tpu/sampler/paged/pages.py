"""Page allocator + per-row block tables for the paged KV cache.

The paged layout replaces the per-row contiguous `[T_max]` cache slab with a
global pool of fixed-size pages (`core/model.py:init_paged_kv_cache`,
`[L, num_pages, KV, page_size, hd]`) plus one int32 block table `[rows,
blocks_per_row]` shared by every layer: logical cache slot `t` of row `r`
lives at page `table[r, t // page_size]`, offset `t % page_size`.  Rows that
finish early hand their pages back to a free list so the continuous-batching
scheduler (`sampler/paged/scheduler.py`) can prefill the next queued prompt
into the freed pool mid-loop instead of draining the batch to its slowest row.

Everything here is pure, static-shape, and jittable:

  * `PageState` is a pytree of three arrays — a free-list stack `free` (the
    first `top` entries are free page ids), the scalar stack pointer `top`,
    and the block `table` itself.
  * `alloc_row` / `release_row` are functional updates returning a new
    `PageState`; `n_blocks` may be a traced value, so the scheduler can run
    them inside jit without retracing per allocation size.
  * Unallocated / released table entries hold the sentinel `num_pages`:
    writes through the table use `mode="drop"` scatters, reads clamp to
    `num_pages - 1`, so a sentinel entry can never corrupt a live page.

Allocation policy is full-budget-at-admission: a row claims
`blocks_per_row(prompt_len + max_tokens, page_size)` pages up front and
releases them all on EOS.  That keeps the allocator out of the jitted decode
carry entirely (no per-step allocation) at the cost of not reclaiming the
unreached tail of short rows until they finish — see docs/PAGED_CACHE.md for
the trade.
"""

from typing import NamedTuple, Tuple

import jax.numpy as jnp


class PageState(NamedTuple):
    """Free-list + block-table state.  `free[:top]` are free page ids (a
    stack: allocation pops from index `top - 1` downward); entries at or
    beyond `top` are dead storage.  `table[r, j]` is the physical page id of
    row `r`'s j-th logical block, or the sentinel `num_pages` when
    unallocated."""
    free: jnp.ndarray   # [num_pages] int32
    top: jnp.ndarray    # scalar int32 — number of free pages
    table: jnp.ndarray  # [rows, blocks_per_row] int32


def blocks_per_row(tokens: int, page_size: int) -> int:
    """Pages a row needs to hold `tokens` logical cache slots."""
    return -(-int(tokens) // int(page_size))


def full_table(rows: int, n_blocks: int) -> jnp.ndarray:
    """Dense identity table: row `r` owns pages `[r*n_blocks, (r+1)*n_blocks)`.

    Used by the monolithic (non-queued) paged path, where the pool is exactly
    `rows * n_blocks` pages and never recycles — this makes the paged cache a
    pure re-layout of the contiguous one, which is what the bit-parity test
    pins down."""
    return jnp.arange(rows * n_blocks, dtype=jnp.int32).reshape(rows, n_blocks)


def init_page_state(num_pages: int, rows: int, n_blocks: int) -> PageState:
    """All pages free, all table entries sentinel."""
    return PageState(
        free=jnp.arange(num_pages, dtype=jnp.int32),
        top=jnp.asarray(num_pages, jnp.int32),
        table=jnp.full((rows, n_blocks), num_pages, jnp.int32),
    )


def alloc_row(state: PageState, row, n_blocks) -> Tuple[PageState, jnp.ndarray]:
    """Pop `n_blocks` pages off the free stack into `table[row]`.

    Returns `(new_state, ok)`; on `ok == False` (free list too short) the
    state is returned unchanged — admission control in the scheduler gates on
    this flag.  `row` and `n_blocks` may be traced."""
    nb = state.table.shape[1]
    num_pages = state.free.shape[0]
    k = jnp.minimum(jnp.asarray(n_blocks, jnp.int32), nb)
    ok = k <= state.top
    idx = state.top - 1 - jnp.arange(nb, dtype=jnp.int32)
    take = jnp.arange(nb, dtype=jnp.int32) < k
    pages = jnp.where(take, state.free[jnp.clip(idx, 0, num_pages - 1)],
                      num_pages)
    new_row = jnp.where(ok, pages, state.table[row])
    return PageState(
        free=state.free,
        top=jnp.where(ok, state.top - k, state.top),
        table=state.table.at[row].set(new_row),
    ), ok


def release_row(state: PageState, row) -> Tuple[PageState, jnp.ndarray]:
    """Push `table[row]`'s live pages back onto the free stack and reset the
    row to sentinel.  Returns `(new_state, n_released)`.  Releasing an
    already-sentinel row is a no-op (returns 0), so the scheduler may release
    idempotently at every sync.

    Semantics under refcounting: a release decrements the row's hold AT MOST
    ONCE — the sentinel reset is what makes the second release of the same
    row a no-op rather than a double-free that would push the same page onto
    the free stack twice.  The host-side refcounted pool
    (`serving.radix.RefPagePool`) mirrors this contract at the row level:
    `RadixCache.release` skips sentinel entries, so releasing a row's table
    twice frees its refs exactly once, while a raw `RefPagePool.unref` past
    zero is a hard error (the invariant tests in tests/test_serving.py pin
    both)."""
    nb = state.table.shape[1]
    num_pages = state.free.shape[0]
    pages = state.table[row]
    valid = pages < num_pages
    m = jnp.sum(valid.astype(jnp.int32))
    rank = jnp.cumsum(valid.astype(jnp.int32)) - 1
    dest = jnp.where(valid, state.top + rank, num_pages)  # num_pages → drop
    return PageState(
        free=state.free.at[dest].set(pages, mode="drop"),
        top=state.top + m,
        table=state.table.at[row].set(
            jnp.full((nb,), num_pages, jnp.int32)),
    ), m
