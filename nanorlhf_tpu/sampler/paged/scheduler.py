"""Continuous batching over the paged KV cache: queued prompts, resident rows.

The monolithic rollout loop sizes its batch to the WHOLE prompt set and runs
until the slowest row finishes — a long-tail length distribution leaves most
rows idle (emitting pads) for most of the loop. Compaction
(sampler/compaction.py) approximated the fix by shrinking the batch between
segments; this module does the real thing, the way continuous-batching
servers (vLLM-style) do, but host-driven and offline-batch shaped.

Since the decode-session refactor the mechanism lives in
`sampler/paged/session.py` (`DecodeSession` owns the carry, the page
table, admission/step/release, the speculative draft seeds, and the
chunked-prefill backlog); this module is the QUEUE-POLICY driver: it maps
queue indices onto resident rows, collects finished rows' outputs in
queue order, and assembles the paged/spec stats surfaces. The serving
engine (serving/engine.py) drives the same session with open-loop
traffic — one scheduler code path for rollout and gateway streams,
test-pinned.

Scheduling shape (unchanged by the refactor):

  * `decode_rows` rows are RESIDENT in a fixed-shape jitted decode loop over
    a page pool sized for exactly those rows
    (`decode_rows * ceil((Tp + max_tokens)/page_size)` pages).
  * The loop runs in chunks of `sync_every` iterations. At each host sync,
    rows that emitted EOS are flushed to the output buffer, their pages
    handed back (free list or radix refcount), and the next queued prompt
    is admitted mid-loop. Batch shape, pool shape, and compiled code never
    change.
  * Decode iterations are counted (the carry's global counter only advances
    while at least one row is live), which is what the long-tail test and
    bench's `detail.paged` compare against the fixed-batch schedule.

Feature composition (the session's reason to exist — see
`sampler.compose_check` for the full matrix):

  * `spec_k > 0` runs draft+verify chunks over the speculative carry.
  * `prefix_cache` routes admissions through the radix tree; COMPOSES
    with spec decode — the drafter seeds its lookup window from the
    cached continuation of the matched prefix, so overlapping corpora
    accept drafts from the first generated token.
  * `prefill_chunk > 0` splits long cold admissions into KV-only chunk
    forwards interleaved with decode chunks (resident rows keep
    emitting while a long prompt prefills). Chunked-on/off streams are
    bit-identical; the initial non-radix batch stays batched-unchunked
    (there are no resident rows to protect yet).

Determinism: row streams are NOT bit-identical to the monolithic loop. The
per-iteration sampling key is `fold_in(key, it)` over the GLOBAL iteration
counter (rows admitted later see different folds than a monolithic run
would), and admitted rows draw their first token from
`fold_in(key, _ADMIT_BASE + queue_index)`. Greedy streams differ only
through chunk boundaries being invisible (they are: the carry is exact), so
greedy queued output EQUALS greedy monolithic output row-for-row — pinned by
tests/test_paged_cache.py — while sampled streams are merely equal in
distribution.

Safety of the recycled pool: a released row's table resets to the sentinel,
so a still-resident-but-done row's writes DROP at the table-routed scatter
(`core/model._paged_pages`) and its reads clamp to an arbitrary live page —
finite garbage feeding a discarded logit. An admitted row's prefill
overwrites every logical slot it will ever read, so stale page contents from
the previous owner never leak through the masked attention.
"""

from __future__ import annotations

import time

import jax.numpy as jnp
import numpy as np

from nanorlhf_tpu.sampler.paged.pages import blocks_per_row
# the jitted primitives and the session live in session.py; the names are
# re-exported here because envs/rollout.py's episode driver and older
# callers import them from the scheduler module
from nanorlhf_tpu.sampler.paged.session import (  # noqa: F401
    _ADMIT_BASE,
    _admit_one,
    _admit_sample,
    _alloc_jit,
    _decode_chunk,
    _install_row,
    _prefill_state_jit,
    _release_jit,
    _spec_chunk,
    DecodeSession,
)


def _finalize_segments(bounds: list, total: int) -> list:
    """Close one request's `{policy_version, tok_range}` list.

    `bounds` is the chronological [(version, start_tok), ...] recorded at
    admission and at each swap; each segment ends where the next begins,
    the last at `total` generated tokens. Empty spans (a swap landing
    before the row's first token, or after it finished) are dropped, so
    the survivors exactly tile [0, total) with strictly increasing
    versions."""
    segs = [
        {"policy_version": bounds[i][0],
         "tok_range": [bounds[i][1],
                       bounds[i + 1][1] if i + 1 < len(bounds) else total]}
        for i in range(len(bounds))
        if (bounds[i + 1][1] if i + 1 < len(bounds) else total) > bounds[i][1]
    ]
    if not segs:
        segs = [{"policy_version": bounds[-1][0] if bounds else None,
                 "tok_range": [0, total]}]
    return segs


def generate_tokens_queued(
    params: dict,
    config,
    prompt_ids: jnp.ndarray,    # [Q, Tp] — ALL queued prompts, left-padded
    prompt_mask: jnp.ndarray,   # [Q, Tp]
    key,
    *,
    max_tokens: int,
    eos_token_id: int,
    pad_token_id: int,
    page_size: int,
    decode_rows: int,
    spec_k: int = 0,
    spec_ngram: int = 3,
    temperature: float = 1.0,
    top_p: float = 0.95,
    greedy: bool = False,
    lora_scale: float = 1.0,
    top_k: int = 64,
    capture_logprobs: bool = False,
    approx_top_k: bool = True,
    sync_every: int = 8,
    prefill_chunk: int = 0,
    spec_stats_out: list | None = None,
    paged_stats_out: list | None = None,
    latency=None,
    prefix_cache=None,
    weight_refresh=None,
):
    """Host-driven continuous-batching generation: `generate_tokens`
    contract over the whole queue ([Q, max_tokens] int32 in queue order, or
    (tokens, logprobs) with capture), with only `decode_rows` rows resident
    at a time and finished rows' pages recycled to the next queued prompt
    mid-loop. See the module docstring for scheduling/determinism notes.

    `latency` (telemetry.LatencyHub, optional): records TRUE per-request
    TTFT — admission-start → first-token-ready, blocking on the admission
    prefill's sampled token — for the initial batch and every mid-loop
    admission, plus the mean inter-token gap per sync chunk (chunk wall /
    iterations advanced). The extra device syncs happen ONLY when a hub is
    attached; the default path's async chunk pipeline is untouched.

    `prefix_cache` (serving.radix.RadixCache, optional): admissions route
    through the cross-request radix prefix cache instead of the device
    free-stack allocator — a request whose padded prompt prefix is already
    cached installs the matched full pages by refcount inc alone (zero
    prefill FLOPs), COW-splits a mid-page straddler, and prefills only the
    suffix through `suffix_logits`. The cache RESETS at the start of every
    call (cached KV is tied to the params that wrote it — docs/SERVING.md),
    so the win here is intra-call: the n>1 queued fanout and dataset-level
    prompt repeats. Greedy streams stay bit-identical to the uncached path
    (test-pinned); sampled streams are equal in distribution only (cold
    initial rows draw tok0 from the per-queue-index admission fold instead
    of the batched fold_in(key, 0)). COMPOSES with `spec_k > 0`: finished
    rows' generated text extends the radix tree, seeding the drafter of
    later overlapping admissions.

    `prefill_chunk > 0` splits every per-row admission whose real suffix
    exceeds the chunk width into KV-only forwards, one per sync chunk —
    greedy/sampled streams are bit-identical to `prefill_chunk=0` (the
    final chunk samples from the same admission fold).

    `weight_refresh` (optional `() -> (version, tree|None)`, built by
    `orchestrator.weight_store.make_swap_refresh`): in-flight mid-sequence
    weight swaps (docs/ORCHESTRATOR.md §in-flight swaps). Polled once
    pre-loop (a returned tree is the BASE install — not counted as a swap)
    and once per host sync chunk; a newer tree is installed as
    `sess.params` before the next decode chunk — params is a traced
    argument of the jitted chunk fns, so the install never recompiles —
    and every live row gets a segment boundary at its current generated
    length. The paged-stats entry then carries `segments` (queue-order
    per-request `{policy_version, tok_range}` lists that exactly tile
    `[0, n_generated)` with strictly increasing versions),
    `swap_installs`, and `swap_wait_s`. With no mid-rollout publish the
    poll returns None every chunk and the token stream is bit-identical
    to `weight_refresh=None` (the PRNG stream never sees the callback)."""
    Q, Tp = prompt_ids.shape
    R = min(int(decode_rows), Q)
    P = int(page_size)
    T_max = Tp + max_tokens
    nb = blocks_per_row(T_max, P)
    spec = spec_k > 0

    radix = prefix_cache if (prefix_cache is not None
                             and getattr(prefix_cache, "enabled", False)) \
        else None

    sess = DecodeSession(
        params, config, rows=R, prompt_len=Tp, max_tokens=max_tokens,
        page_size=P, eos_token_id=eos_token_id, pad_token_id=pad_token_id,
        key=key, temperature=temperature, top_p=top_p, greedy=greedy,
        top_k=top_k, approx_top_k=approx_top_k,
        capture_logprobs=capture_logprobs, lora_scale=lora_scale,
        spec_k=spec_k, spec_ngram=spec_ngram, prefix_cache=radix,
        prefill_chunk=int(prefill_chunk), sync_every=int(sync_every),
        latency=latency)
    N = sess.num_pages
    stats0 = dict(radix.stats) if radix is not None else None

    prompt_np = np.asarray(prompt_ids)
    pmask_np = np.asarray(prompt_mask)

    # host bookkeeping
    out_all = np.full((Q, max_tokens), pad_token_id, np.int32)
    lp_all = np.zeros((Q, max_tokens), np.float32)
    acc_all = np.zeros((Q,), np.int64)            # spec: accepted drafts/row
    owner = [-1] * R                              # resident row → queue index
    next_q = 0
    recycled = 0
    admissions: list[dict] = []
    util_samples: list[float] = []
    shared_peak = 0

    # in-flight weight swaps: per-queue-index (version, start_tok) bounds
    swaps = weight_refresh is not None
    cur_version = None
    swap_installs = 0
    swap_wait_s = 0.0
    seg_bounds: dict[int, list] = {}
    seg_final: dict[int, list] = {}
    if swaps:
        t0 = time.perf_counter()
        cur_version, fresh = weight_refresh()
        if fresh is not None:
            # base install: a publish raced the dispatch — start the whole
            # stream on the newer tree (single segment, newer version)
            sess.params = fresh
            swap_wait_s += time.perf_counter() - t0

    if radix is not None:
        # initial batch admits row-by-row through the radix path (the
        # same path mid-loop admissions use)
        for r in range(R):
            sess.admit(r, prompt_np[next_q], pmask_np[next_q], next_q)
            owner[r] = next_q
            if swaps:
                seg_bounds[next_q] = [(cur_version, 0)]
            next_q += 1
    else:
        sess.bootstrap(prompt_ids, prompt_mask)
        owner = list(range(R))
        next_q = R
        if swaps:
            for q in range(R):
                seg_bounds[q] = [(cur_version, 0)]

    while True:
        done_h, installed = sess.step()
        it_now = sess.iterations()
        if installed is not None:
            admissions.append({"row": installed[0],
                               "queue_index": owner[installed[0]],
                               "iteration": it_now, "chunked": True})
        if spec:
            row_acc_h = np.asarray(sess.state[14])
            n_gen_h = np.asarray(sess.state[7])

        pending = sess.pending_rows()
        finished = [r for r in range(R)
                    if done_h[r] and owner[r] >= 0 and r not in pending]
        if swaps and finished and not spec:
            # generated-length sync only when a row actually flushes — the
            # no-publish steady state stays free of extra device syncs
            n_gen_h = np.asarray(sess.state[7])
        for r in finished:
            q = owner[r]
            out_all[q] = np.asarray(sess.state[1][r])
            if capture_logprobs:
                lp_all[q] = np.asarray(sess.state[2][r])
            gen = None
            if spec:
                acc_all[q] = int(row_acc_h[r])
                gen = out_all[q][:int(n_gen_h[r])]
            if swaps:
                seg_final[q] = _finalize_segments(
                    seg_bounds.pop(q), int(n_gen_h[r]))
            owner[r] = -1
            # radix: drop the REQUEST's refs; pages the tree still holds
            # survive as cached prefix KV (and, with spec, the generated
            # text extends the tree for the drafter seed)
            recycled += sess.release(r, gen_tokens=gen)
        for r in finished:
            if next_q >= Q:
                continue
            q = next_q
            next_q += 1
            sess.admit(r, prompt_np[q], pmask_np[q], q)
            owner[r] = q
            if swaps:
                seg_bounds[q] = [(cur_version, 0)]
            if not sess.is_pending(r):
                admissions.append({"row": r, "queue_index": q,
                                   "iteration": it_now})
        if swaps:
            # THE host sync point (ISSUE 20): poll the store once per
            # chunk; a newer tree is installed before the next decode
            # chunk and every live row's segment list gets a boundary at
            # its current generated length
            t0 = time.perf_counter()
            version, fresh = weight_refresh()
            if fresh is not None:
                # post-churn snapshot: rows admitted THIS sync read 0 here,
                # so their boundary collapses to a dropped empty segment
                n_gen_now = np.asarray(sess.state[7])
                for r in range(R):
                    if owner[r] >= 0:
                        seg_bounds[owner[r]].append(
                            (version, int(n_gen_now[r])))
                sess.params = fresh
                cur_version = version
                swap_installs += 1
                swap_wait_s += time.perf_counter() - t0
        # pool occupancy AFTER this sync's churn: allocated / total pages
        util_samples.append(sess.utilization())
        shared_peak = max(shared_peak, sess.shared_pages())
        if next_q >= Q and all(o < 0 for o in owner) \
                and not sess.has_pending():
            break

    n_iter = sess.iterations()
    if paged_stats_out is not None:
        entry = {
            "page_utilization": float(np.mean(util_samples)),
            "pages_recycled": recycled,
            "admitted_midloop": len(admissions),
            "decode_iterations": n_iter,
            "rows": R,
            "num_pages": N,
            "page_size": P,
            "admissions": admissions,
            "prefill_token_dispatch": sess.dispatch_tokens,
            "dispatch_events": sess.dispatch_events(),
            "chunked_admissions": sess.chunked_admissions,
            "prefill_backlog_peak": sess.backlog_peak,
            # end-of-call session snapshot for /statusz "session" (row
            # feature flags, pending-prefill backlog, dispatch counters)
            "session": sess.status(),
        }
        if swaps:
            entry.update({
                "segments": [seg_final[q] for q in range(Q)],
                "swap_installs": swap_installs,
                "swap_wait_s": swap_wait_s,
            })
        if radix is not None:
            lookup_tok = radix.stats["lookup_tokens"] - stats0["lookup_tokens"]
            entry.update({
                "prefix_hit_tokens": sess.hit_tokens,
                "prefix_hit_frac": (sess.hit_tokens / lookup_tok
                                    if lookup_tok else 0.0),
                "cow_splits": radix.stats["cow_splits"] - stats0["cow_splits"],
                "evicted_pages": (radix.stats["evicted_pages"]
                                  - stats0["evicted_pages"]),
                "shared_pages": shared_peak,
            })
        paged_stats_out.append(entry)
    if spec and spec_stats_out is not None:
        state = sess.state
        spec_stats_out.append({
            "verify_steps": n_iter,
            "drafted": state[10], "accepted": state[11],
            "emitted": state[12], "row_steps": state[13],
            "accepted_rows": jnp.asarray(acc_all.astype(np.int32)),
        })
    toks = jnp.asarray(out_all)
    if capture_logprobs:
        return toks, jnp.asarray(lp_all)
    return toks
