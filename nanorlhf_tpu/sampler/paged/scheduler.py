"""Continuous batching over the paged KV cache: queued prompts, resident rows.

The monolithic rollout loop sizes its batch to the WHOLE prompt set and runs
until the slowest row finishes — a long-tail length distribution leaves most
rows idle (emitting pads) for most of the loop. Compaction
(sampler/compaction.py) approximated the fix by shrinking the batch between
segments; this module does the real thing, the way continuous-batching
servers (vLLM-style) do, but host-driven and offline-batch shaped:

  * `decode_rows` rows are RESIDENT in a fixed-shape jitted decode loop over
    a page pool sized for exactly those rows
    (`decode_rows * ceil((Tp + max_tokens)/page_size)` pages).
  * The loop runs in chunks of `sync_every` iterations. At each host sync,
    rows that emitted EOS are flushed to the output buffer, their pages
    handed back to the free list (`pages.release_row`), and the next queued
    prompt is admitted mid-loop: `pages.alloc_row` claims the freed pages, a
    single-row prefill writes the prompt KV through the row's new block
    table into the shared pool, and the row's carry slots are re-installed.
    Batch shape, pool shape, and compiled code never change.
  * Decode iterations are counted (the carry's global counter only advances
    while at least one row is live), which is what the long-tail test and
    bench's `detail.paged` compare against the fixed-batch schedule.

Speculative decode composes: `spec_k > 0` runs the SAME chunk structure over
the speculative carry, reusing `speculative._draft_fn`/`_verify_fn` with the
live block table — per-row accept lengths are already per-row bookkeeping,
so admission just resets one row's slots.

Determinism: row streams are NOT bit-identical to the monolithic loop. The
per-iteration sampling key is `fold_in(key, it)` over the GLOBAL iteration
counter (rows admitted later see different folds than a monolithic run
would), and admitted rows draw their first token from
`fold_in(key, _ADMIT_BASE + queue_index)`. Greedy streams differ only
through chunk boundaries being invisible (they are: the carry is exact), so
greedy queued output EQUALS greedy monolithic output row-for-row — pinned by
tests/test_paged_cache.py — while sampled streams are merely equal in
distribution.

Safety of the recycled pool: a released row's table resets to the sentinel,
so a still-resident-but-done row's writes DROP at the table-routed scatter
(`core/model._paged_pages`) and its reads clamp to an arbitrary live page —
finite garbage feeding a discarded logit. An admitted row's prefill
overwrites every logical slot it will ever read, so stale page contents from
the previous owner never leak through the masked attention.
"""

from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from nanorlhf_tpu.core.model import decode_step, prefill
from nanorlhf_tpu.sampler.paged.pages import (
    PageState, alloc_row, blocks_per_row, full_table, release_row,
)
from nanorlhf_tpu.sampler.sampler import (
    _prefill_state,
    _sample_token,
    _token_logprob,
)

# admitted rows re-key the PRNG far away from the per-iteration fold_in
# stream (iteration counters are bounded by max_tokens << this)
_ADMIT_BASE = 10_000_000

# the scheduler drives _prefill_state from the host (sampler.py's callers
# run it inside their own jits), so it needs its own jit wrapper or the
# initial batch prefill executes op-by-op
_prefill_state_jit = partial(
    jax.jit,
    static_argnames=("config", "max_tokens", "eos_token_id", "pad_token_id",
                     "temperature", "top_p", "greedy", "lora_scale", "top_k",
                     "capture_logprobs", "approx_top_k", "page_size"),
)(_prefill_state)

_CHUNK_STATIC = (
    "config", "Tp", "max_tokens", "page_size", "sync_every", "eos_token_id",
    "pad_token_id", "temperature", "top_p", "greedy", "lora_scale", "top_k",
    "capture_logprobs", "approx_top_k",
)


def _queued_decode_body(params, config, s, table, *, Tp, max_tokens,
                        page_size, eos_token_id, pad_token_id, temperature,
                        top_p, greedy, lora_scale, top_k, capture_logprobs,
                        approx_top_k):
    """One decode step over the queued carry — `sampler._decode_body`
    generalized to PER-ROW generation counts (resident rows sit at
    different depths) and table-routed cache writes."""
    (it, out, lp_out, caches, key_mask, done, cur_tok, n_gen, prompt_len,
     key) = s
    R = cur_tok.shape[0]
    rows = jnp.arange(R)
    slot = Tp + n_gen - 1                      # [R] cache slot of cur_tok
    key_mask = key_mask.at[rows, slot].set(True)
    position = prompt_len + n_gen - 1
    logits, caches = decode_step(
        params, config, cur_tok, position, slot, key_mask, caches,
        lora_scale=lora_scale, page_table=table, page_size=page_size,
    )
    tok = _sample_token(jax.random.fold_in(key, it), logits, temperature,
                        top_p, greedy, top_k, approx_top_k)
    tok = jnp.where(done, pad_token_id, tok)
    live = ~done
    wpos = jnp.where(live, n_gen, max_tokens)  # done rows drop their write
    out = out.at[rows, wpos].set(tok, mode="drop")
    if capture_logprobs:
        lp = _token_logprob(logits, tok, temperature)
        lp_out = lp_out.at[rows, wpos].set(lp, mode="drop")
    cur_tok = jnp.where(live, tok, cur_tok)
    n_gen = n_gen + live.astype(jnp.int32)
    done = done | (tok == eos_token_id) | (n_gen >= max_tokens)
    return (it + 1, out, lp_out, caches, key_mask, done, cur_tok, n_gen,
            prompt_len, key)


@partial(jax.jit, static_argnames=_CHUNK_STATIC)
def _decode_chunk(params, config, state, table, **statics):
    """Up to `sync_every` decode iterations; exits early once every resident
    row is done (the iteration counter then stops, so it counts true decode
    dispatches)."""
    sync_every = statics.pop("sync_every")

    def cond(cs):
        c, s = cs
        return (c < sync_every) & ~jnp.all(s[5])

    def body(cs):
        c, s = cs
        return c + 1, _queued_decode_body(params, config, s, table, **statics)

    _, state = jax.lax.while_loop(cond, body, (jnp.int32(0), state))
    return state


_SPEC_CHUNK_STATIC = _CHUNK_STATIC + ("spec_k", "spec_ngram")


@partial(jax.jit, static_argnames=_SPEC_CHUNK_STATIC)
def _spec_chunk(params, config, state, table, prompt_rep, **statics):
    """Speculative twin of `_decode_chunk`: draft + verify per iteration
    over the 15-slot speculative carry, with the live block table routed
    into the verify forward. `prompt_rep` is the RESIDENT prompts [R, Tp]
    (it changes at admission, hence a traced argument)."""
    from nanorlhf_tpu.sampler.speculative import _draft_fn, _verify_fn

    sync_every = statics.pop("sync_every")
    spec_ngram = statics.pop("spec_ngram")
    ver_kw = dict(statics)
    ver_kw.pop("pad_token_id")
    spec_k = statics["spec_k"]
    Tp, pad = statics["Tp"], statics["pad_token_id"]

    def cond(cs):
        c, s = cs
        return (c < sync_every) & ~jnp.all(s[5])

    def body(cs):
        c, s = cs
        drafts = _draft_fn(prompt_rep, s, Tp=Tp, spec_k=spec_k,
                           spec_ngram=spec_ngram, pad_token_id=pad)
        return c + 1, _verify_fn(params, config, s, drafts, page_table=table,
                                 pad_token_id=pad, **ver_kw)

    _, state = jax.lax.while_loop(cond, body, (jnp.int32(0), state))
    return state


@partial(jax.jit, static_argnames=("config", "page_size", "T_max",
                                   "temperature", "top_p", "greedy", "top_k",
                                   "approx_top_k", "lora_scale"))
def _admit_one(params, config, pids, pmask, caches, row_table, key, *,
               page_size, T_max, temperature, top_p, greedy, top_k,
               approx_top_k, lora_scale):
    """Single-row admission prefill: write the prompt KV through the row's
    freshly allocated block table into the SHARED pool, sample the first
    token. pids/pmask: [1, Tp]; row_table: [nb]. Returns
    (caches, tok0, lp0, prompt_len) with row-0 scalars."""
    logits, caches = prefill(
        params, config, pids, pmask.astype(bool), caches,
        lora_scale=lora_scale, page_table=row_table[None, :],
        page_size=page_size, logical_len=T_max,
    )
    tok0 = _sample_token(key, logits, temperature, top_p, greedy, top_k,
                         approx_top_k)
    lp0 = _token_logprob(logits, tok0, temperature)
    plen = jnp.sum(pmask.astype(jnp.int32), axis=1)
    return caches, tok0[0], lp0[0], plen[0]


@partial(jax.jit, static_argnames=("Tp", "max_tokens", "eos_token_id",
                                   "pad_token_id", "spec"))
def _install_row(state, caches, r, tok0, lp0, pmask_row, plen, *, Tp,
                 max_tokens, eos_token_id, pad_token_id, spec):
    """Re-initialize resident row `r` of the carry for a freshly admitted
    prompt (out/lp rows cleared, key_mask reset to the prompt mask, counters
    to the post-prefill values). Works for both carry layouts — the first
    ten slots of the spec carry line up, and `spec` additionally resets the
    per-row accepted-draft counter."""
    s = list(state)
    T_mask = s[4].shape[1]
    s[3] = caches
    s[1] = s[1].at[r].set(
        jnp.full((max_tokens,), pad_token_id, jnp.int32).at[0].set(tok0))
    s[2] = s[2].at[r].set(jnp.zeros((max_tokens,), jnp.float32).at[0].set(lp0))
    s[4] = s[4].at[r].set(
        jnp.zeros((T_mask,), bool).at[:Tp].set(pmask_row.astype(bool)))
    s[5] = s[5].at[r].set(tok0 == eos_token_id)
    s[6] = s[6].at[r].set(tok0)
    s[7] = s[7].at[r].set(jnp.int32(1))
    s[8] = s[8].at[r].set(plen)
    if spec:
        s[14] = s[14].at[r].set(jnp.int32(0))
    return tuple(s)


_release_jit = jax.jit(release_row)
_alloc_jit = jax.jit(alloc_row)


@partial(jax.jit, static_argnames=("temperature", "top_p", "greedy", "top_k",
                                   "approx_top_k"))
def _admit_sample(logits, key, *, temperature, top_p, greedy, top_k,
                  approx_top_k):
    """First token + logprob from a single row's admission logits [V] —
    the sampling half of `_admit_one`, split out so the radix path can
    feed it suffix-prefill logits instead of full-prefill logits."""
    tok0 = _sample_token(key, logits[None, :], temperature, top_p, greedy,
                         top_k, approx_top_k)
    return tok0[0], _token_logprob(logits[None, :], tok0, temperature)[0]


def generate_tokens_queued(
    params: dict,
    config,
    prompt_ids: jnp.ndarray,    # [Q, Tp] — ALL queued prompts, left-padded
    prompt_mask: jnp.ndarray,   # [Q, Tp]
    key: jax.Array,
    *,
    max_tokens: int,
    eos_token_id: int,
    pad_token_id: int,
    page_size: int,
    decode_rows: int,
    spec_k: int = 0,
    spec_ngram: int = 3,
    temperature: float = 1.0,
    top_p: float = 0.95,
    greedy: bool = False,
    lora_scale: float = 1.0,
    top_k: int = 64,
    capture_logprobs: bool = False,
    approx_top_k: bool = True,
    sync_every: int = 8,
    spec_stats_out: list | None = None,
    paged_stats_out: list | None = None,
    latency=None,
    prefix_cache=None,
):
    """Host-driven continuous-batching generation: `generate_tokens`
    contract over the whole queue ([Q, max_tokens] int32 in queue order, or
    (tokens, logprobs) with capture), with only `decode_rows` rows resident
    at a time and finished rows' pages recycled to the next queued prompt
    mid-loop. See the module docstring for scheduling/determinism notes.

    `latency` (telemetry.LatencyHub, optional): records TRUE per-request
    TTFT — admission-start → first-token-ready, blocking on the admission
    prefill's sampled token — for the initial batch and every mid-loop
    admission, plus the mean inter-token gap per sync chunk (chunk wall /
    iterations advanced). The extra device syncs happen ONLY when a hub is
    attached; the default path's async chunk pipeline is untouched.

    `prefix_cache` (serving.radix.RadixCache, optional): admissions route
    through the cross-request radix prefix cache instead of the device
    free-stack allocator — a request whose padded prompt prefix is already
    cached installs the matched full pages by refcount inc alone (zero
    prefill FLOPs), COW-splits a mid-page straddler, and prefills only the
    suffix through `suffix_logits`. The cache RESETS at the start of every
    call (cached KV is tied to the params that wrote it — docs/SERVING.md),
    so the win here is intra-call: the n>1 queued fanout and dataset-level
    prompt repeats. Greedy streams stay bit-identical to the uncached path
    (test-pinned); sampled streams are equal in distribution only (cold
    initial rows draw tok0 from the per-queue-index admission fold instead
    of the batched fold_in(key, 0)). Incompatible with spec_k > 0."""
    Q, Tp = prompt_ids.shape
    R = min(int(decode_rows), Q)
    P = int(page_size)
    T_max = Tp + max_tokens
    nb = blocks_per_row(T_max, P)
    N = R * nb
    spec = spec_k > 0

    radix = prefix_cache if (prefix_cache is not None
                             and getattr(prefix_cache, "enabled", False)) \
        else None
    if radix is not None and spec:
        raise ValueError(
            "prefix_cache is incompatible with spec_k > 0: the radix "
            "admission path derives per-row cache fill from the matched "
            "prefix, which the speculative carry's per-row accept "
            "bookkeeping does not model — run one lever at a time.")

    hub = latency if (latency is not None and latency.enabled) else None
    sample_kw = dict(temperature=temperature, top_p=top_p, greedy=greedy,
                     top_k=top_k, approx_top_k=approx_top_k)

    prompt_np = np.asarray(prompt_ids)
    pmask_np = np.asarray(prompt_mask)
    dispatch_tokens = 0            # Σ Tq over prefill/suffix dispatches —
    hit_tokens = 0                 # the A/B's "prefill FLOPs" proxy
    shared_peak = 0                # max pages/shared over sync points

    if radix is not None:
        from nanorlhf_tpu.core.model import init_paged_kv_cache
        from nanorlhf_tpu.serving.radix import (
            bucket_len, copy_page, prompt_key, suffix_logits,
        )

        N = R * nb + radix.extra_pages(R, nb)
        radix.reset(num_pages=N, page_size=P)
        stats0 = dict(radix.stats)
        caches0 = init_paged_kv_cache(
            config, N, P, params["embed_tokens"].dtype)
        # empty carry: every row starts done; _radix_admit installs the
        # initial batch through the same path mid-loop admissions use
        state = (jnp.int32(1),
                 jnp.full((R, max_tokens), pad_token_id, jnp.int32),
                 jnp.zeros((R, max_tokens), jnp.float32),
                 caches0,
                 jnp.zeros((R, T_max), bool),
                 jnp.ones((R,), bool),
                 jnp.zeros((R,), jnp.int32),
                 jnp.ones((R,), jnp.int32),
                 jnp.zeros((R,), jnp.int32),
                 key)
        table_np = np.full((R, nb), N, np.int32)
        pstate = None

        def _radix_admit(q, r, state):
            """Admit queue index `q` into resident row `r` through the
            radix cache: refcount-share the matched full pages, COW-split
            a mid-page straddler, prefill only the suffix."""
            nonlocal dispatch_tokens, hit_tokens
            t_admit0 = time.perf_counter()
            toks, msk = prompt_np[q], pmask_np[q].astype(bool)
            kelems = prompt_key(toks, msk)
            pad_count = int(Tp - msk.sum())
            plan = radix.plan(kelems, pad_count=pad_count, n_blocks=nb,
                              prompt_len=Tp)
            table_np[r] = plan.row_pages
            admit_key = jax.random.fold_in(key, _ADMIT_BASE + q)
            caches = state[3]
            if plan.cow_src is not None:
                caches = copy_page(caches, plan.cow_src, plan.cow_dst)
            if plan.m == 0:
                # cold: the row's pages are all fresh, so the full
                # single-row prefill is IDENTICAL to the uncached path
                caches, t0, l0, pl = _admit_one(
                    params, config, prompt_ids[q:q + 1],
                    prompt_mask[q:q + 1], caches,
                    jnp.asarray(plan.row_pages), admit_key,
                    page_size=P, T_max=T_max, lora_scale=lora_scale,
                    **sample_kw)
                dispatch_tokens += Tp
            else:
                m = plan.m
                s_real = Tp - m
                Sb = bucket_len(s_real, T_max - m)
                suffix = np.zeros((1, Sb), np.int32)
                suffix[0, :s_real] = toks[m:]
                pos = (m - pad_count) + np.arange(Sb, dtype=np.int32)[None]
                km = np.zeros((1, T_max), bool)
                km[0, pad_count:m] = True
                logits, caches = suffix_logits(
                    params, config, jnp.asarray(suffix), jnp.asarray(pos),
                    jnp.asarray([m], jnp.int32), jnp.int32(s_real - 1),
                    jnp.asarray(km), caches, jnp.asarray(plan.row_pages),
                    page_size=P, lora_scale=lora_scale)
                t0, l0 = _admit_sample(logits, admit_key, **sample_kw)
                pl = jnp.int32(int(msk.sum()))
                dispatch_tokens += Sb
                hit_tokens += plan.hit_tokens
            radix.insert(kelems, plan.row_pages, Tp)
            if hub is not None:
                jax.block_until_ready(t0)
                hub.record("latency/ttft_s",
                           time.perf_counter() - t_admit0)
            return _install_row(
                state, caches, r, t0, l0, prompt_mask[q], pl, Tp=Tp,
                max_tokens=max_tokens, eos_token_id=eos_token_id,
                pad_token_id=pad_token_id, spec=False)

        for r in range(R):
            state = _radix_admit(r, r, state)
    else:
        # ---- initial admission: batch-prefill the first R prompts. The
        # fresh pool is fully claimed by the identity table (exactly what
        # _prefill_state builds), so the allocator starts with an EMPTY
        # free list; release/alloc churn begins at the first EOS.
        t_prefill0 = time.perf_counter()
        base = _prefill_state_jit(
            params, config, prompt_ids[:R], prompt_mask[:R], key,
            max_tokens=max_tokens, eos_token_id=eos_token_id,
            pad_token_id=pad_token_id, temperature=temperature, top_p=top_p,
            greedy=greedy, lora_scale=lora_scale, top_k=top_k,
            capture_logprobs=capture_logprobs, approx_top_k=approx_top_k,
            page_size=P,
        )
        (_one, out0, lp0, caches, key_mask0, done0, tok0, plen0, _key) = base
        dispatch_tokens += R * Tp
        if hub is not None:
            # every initial-batch row's first token exists once this
            # prefill lands: one TTFT observation per admitted request
            jax.block_until_ready(tok0)
            ttft0 = time.perf_counter() - t_prefill0
            for _ in range(R):
                hub.record("latency/ttft_s", ttft0)
        pstate = PageState(free=jnp.arange(N, dtype=jnp.int32),
                           top=jnp.asarray(0, jnp.int32),
                           table=full_table(R, nb))
        n_gen0 = jnp.ones((R,), jnp.int32)
        if spec:
            from nanorlhf_tpu.sampler.speculative import _spec_state
            state = _spec_state(base)
        else:
            state = (jnp.int32(1), out0, lp0, caches, key_mask0, done0,
                     tok0, n_gen0, plen0, key)

    statics = dict(
        Tp=Tp, max_tokens=max_tokens, page_size=P, sync_every=int(sync_every),
        eos_token_id=eos_token_id, pad_token_id=pad_token_id,
        temperature=temperature, top_p=top_p, greedy=greedy,
        lora_scale=lora_scale, top_k=top_k,
        capture_logprobs=capture_logprobs, approx_top_k=approx_top_k,
    )
    if spec:
        statics.update(spec_k=spec_k, spec_ngram=spec_ngram)

    # host bookkeeping
    out_all = np.full((Q, max_tokens), pad_token_id, np.int32)
    lp_all = np.zeros((Q, max_tokens), np.float32)
    acc_all = np.zeros((Q,), np.int64)            # spec: accepted drafts/row
    owner = list(range(R))                        # resident row → queue index
    prompt_res_np = np.array(prompt_np[:R])       # resident prompts (spec)
    prompt_rep = jnp.asarray(prompt_res_np)
    next_q = R
    recycled = 0
    admissions: list[dict] = []
    util_samples: list[float] = []

    it_prev = int(state[0]) - 1
    while True:
        t_chunk0 = time.perf_counter()
        table_dev = (jnp.asarray(table_np) if radix is not None
                     else pstate.table)
        if spec:
            state = _spec_chunk(params, config, state, table_dev,
                                prompt_rep, **statics)
        else:
            state = _decode_chunk(params, config, state, table_dev,
                                  **statics)
        done_h = np.asarray(state[5])
        it_now = int(state[0]) - 1
        if hub is not None:
            # done_h forced the device sync, so the chunk's wall time is
            # fully realised here; one mean inter-token gap per sync chunk
            hub.record("latency/intertoken_s",
                       (time.perf_counter() - t_chunk0)
                       / max(1, it_now - it_prev))
        it_prev = it_now
        if spec:
            row_acc_h = np.asarray(state[14])

        finished = [r for r in range(R) if done_h[r] and owner[r] >= 0]
        for r in finished:
            q = owner[r]
            out_all[q] = np.asarray(state[1][r])
            if capture_logprobs:
                lp_all[q] = np.asarray(state[2][r])
            if spec:
                acc_all[q] = int(row_acc_h[r])
            owner[r] = -1
            if radix is not None:
                # drop the REQUEST's refs; pages the tree still holds
                # survive as cached prefix KV for later admissions
                recycled += radix.release(table_np[r])
                table_np[r] = N
            else:
                pstate, m = _release_jit(pstate, r)
                recycled += int(m)
        for r in finished:
            if next_q >= Q:
                continue
            q = next_q
            next_q += 1
            if radix is not None:
                state = _radix_admit(q, r, state)
            else:
                pstate, ok = _alloc_jit(pstate, r, nb)
                assert bool(ok), "allocator underflow: full-budget rows recycle uniformly"
                t_admit0 = time.perf_counter()
                caches, t0, l0, pl = _admit_one(
                    params, config, prompt_ids[q:q + 1], prompt_mask[q:q + 1],
                    state[3], pstate.table[r],
                    jax.random.fold_in(key, _ADMIT_BASE + q),
                    page_size=P, T_max=T_max, temperature=temperature,
                    top_p=top_p, greedy=greedy, top_k=top_k,
                    approx_top_k=approx_top_k, lora_scale=lora_scale,
                )
                dispatch_tokens += Tp
                if hub is not None:
                    # t0 is the admission prefill's sampled first token:
                    # blocking on it gives this request's true TTFT
                    jax.block_until_ready(t0)
                    hub.record("latency/ttft_s",
                               time.perf_counter() - t_admit0)
                state = _install_row(
                    state, caches, r, t0, l0, prompt_mask[q], pl, Tp=Tp,
                    max_tokens=max_tokens, eos_token_id=eos_token_id,
                    pad_token_id=pad_token_id, spec=spec,
                )
            owner[r] = q
            if spec:
                prompt_res_np[r] = prompt_np[q]
                prompt_rep = jnp.asarray(prompt_res_np)
            admissions.append({"row": r, "queue_index": q,
                               "iteration": it_now})
        # pool occupancy AFTER this sync's churn: allocated / total pages
        if radix is not None:
            util_samples.append(1.0 - radix.pool.free_count / N)
            shared_peak = max(shared_peak, radix.pool.shared_count())
        else:
            util_samples.append(1.0 - float(np.asarray(pstate.top)) / N)
        if next_q >= Q and all(o < 0 for o in owner):
            break

    n_iter = int(state[0]) - 1
    if paged_stats_out is not None:
        entry = {
            "page_utilization": float(np.mean(util_samples)),
            "pages_recycled": recycled,
            "admitted_midloop": len(admissions),
            "decode_iterations": n_iter,
            "rows": R,
            "num_pages": N,
            "page_size": P,
            "admissions": admissions,
            "prefill_token_dispatch": dispatch_tokens,
        }
        if radix is not None:
            lookup_tok = radix.stats["lookup_tokens"] - stats0["lookup_tokens"]
            entry.update({
                "prefix_hit_tokens": hit_tokens,
                "prefix_hit_frac": (hit_tokens / lookup_tok
                                    if lookup_tok else 0.0),
                "cow_splits": radix.stats["cow_splits"] - stats0["cow_splits"],
                "evicted_pages": (radix.stats["evicted_pages"]
                                  - stats0["evicted_pages"]),
                "shared_pages": shared_peak,
            })
        paged_stats_out.append(entry)
    if spec and spec_stats_out is not None:
        spec_stats_out.append({
            "verify_steps": n_iter,
            "drafted": state[10], "accepted": state[11],
            "emitted": state[12], "row_steps": state[13],
            "accepted_rows": jnp.asarray(acc_all.astype(np.int32)),
        })
    toks = jnp.asarray(out_all)
    if capture_logprobs:
        return toks, jnp.asarray(lp_all)
    return toks
