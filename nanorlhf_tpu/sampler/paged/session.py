"""The decode session: one composable continuous-batching loop.

Before this module the decode stack was three pairwise-exclusive forks
over the same machinery: the rollout scheduler's queued loop
(sampler/paged/scheduler.py), the serving engine's private fixed-shape
chunk loop (serving/engine.py), and the speculative carry — with the
radix prefix cache legal in exactly one of them at a time. `DecodeSession`
collapses the forks: every resident row carries ONE uniform state record —
page-table row, sampling params, output/logprob/mask slots, speculative
draft state, and (when admitted through the radix cache) its prefix-cache
plan — and admission, step, verify, and release are methods on the
session instead of per-mode code paths. The drivers that remain are thin
policy loops: the rollout scheduler owns queue order and output
collection, the serving engine owns threads/SLO/shed, and both submit
rows into the same jitted chunk functions defined here.

Compositions this buys (all test-pinned, bench-gated):

  * **spec decode under the radix prefix cache** — the n-gram drafter's
    lookup window is seeded from the radix tree's cached continuation of
    the matched prefix (`RadixCache.matched_continuation`), so
    prefix-heavy corpora draft usefully from the first generated token
    instead of waiting for the row's own buffer to self-repeat. Greedy
    output is bit-identical to each feature alone (greedy acceptance is
    draft-independent), with strictly fewer model dispatches on an
    overlapping corpus.
  * **chunked prefill** — a long cold prompt's admission is split into
    `prefill_chunk`-token KV-only forwards (`core/model.decode_verify`
    with `want_logits=False`) interleaved one-per-sync-chunk with decode
    steps, so resident rows' inter-token latency no longer absorbs the
    whole prefill wall. The final chunk runs through `suffix_logits` and
    samples the first token with the SAME admission PRNG fold as the
    unchunked path, so chunked-on/off GREEDY output is bit-identical
    (the suffix-equals-prefill equivalence, chained per chunk); sampled
    output is equal in distribution only, because a chunk-delayed row
    decodes at later global `fold_in(key, it)` iterations.
  * **serving as a session client** — the engine's per-request sampling
    params ([R] temperature/top_p/greedy/budget arrays) become traced
    arguments of the shared chunk body instead of a private carry layout;
    one compiled decode program serves rollout and gateway traffic.

Carry layout (identical to the pre-session scheduler, which is what keeps
every greedy stream bit-identical through the refactor):

  base  (10): it · out · lp_out · caches · key_mask · done · cur_tok ·
              n_gen · prompt_len · key
  spec  (15): base + n_drafted · n_accepted · n_emitted · n_rowsteps ·
              row_acc   (sampler/speculative.py)

Dispatch accounting: `launches` counts model-forward dispatches
(admission prefills, per-chunk prefill forwards, decode iterations,
verify steps — each one full weight stream); `dispatch_tokens` counts
prefill/suffix tokens only. Spec decode trades MORE tokens per verify
launch for FEWER launches, so the combined spec+radix A/B gates on
launches (`dispatch_events`) and on prefill tokens vs the spec-alone
baseline — docs/DECODE_ANALYSIS.md walks the arithmetic.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from nanorlhf_tpu.core.model import decode_step, decode_verify, prefill
from nanorlhf_tpu.ops.masking import guard_temperature
from nanorlhf_tpu.sampler.paged.pages import (
    PageState, alloc_row, blocks_per_row, full_table, release_row,
)
from nanorlhf_tpu.sampler.sampler import (
    _nucleus_candidates,
    _prefill_state,
    _sample_token,
    _token_logprob,
)

# admitted rows re-key the PRNG far away from the per-iteration fold_in
# stream (iteration counters are bounded by max_tokens << this)
_ADMIT_BASE = 10_000_000

# the session drives _prefill_state from the host (sampler.py's callers
# run it inside their own jits), so it needs its own jit wrapper or the
# initial batch prefill executes op-by-op
_prefill_state_jit = partial(
    jax.jit,
    static_argnames=("config", "max_tokens", "eos_token_id", "pad_token_id",
                     "temperature", "top_p", "greedy", "lora_scale", "top_k",
                     "capture_logprobs", "approx_top_k", "page_size"),
)(_prefill_state)

_CHUNK_STATIC = (
    "config", "Tp", "max_tokens", "page_size", "sync_every", "eos_token_id",
    "pad_token_id", "temperature", "top_p", "greedy", "lora_scale", "top_k",
    "capture_logprobs", "approx_top_k",
)


def _serving_sample(key, logits, temperature, top_p, greedy, *, top_k,
                    approx_top_k):
    """Per-ROW sampling: `sampler._sample_token` with `temperature` /
    `top_p` / `greedy` as traced `[R]` arrays so one compiled decode
    step serves heterogeneous requests. Both branches are computed and
    selected with `jnp.where(greedy, ...)`; the nucleus keep rule
    broadcasts `top_p[:, None]` against the `[R, K]` candidate set.
    Unlike the rollout sampler there is no exact full-vocab escape for
    `top_p >= 1` — serving always samples in top-k candidate space
    (`top_p = 1` keeps every candidate), which is the usual serving
    trade and keeps the row-mixed program shape fixed."""
    scaled = (logits.astype(jnp.float32)
              / guard_temperature(temperature)[:, None])
    top_logits, top_idx, keep = _nucleus_candidates(
        scaled, top_p[:, None], top_k, approx_top_k)
    kept = jnp.where(keep, top_logits, -jnp.inf)
    choice = jax.random.categorical(key, kept, axis=-1)
    sampled = jnp.take_along_axis(
        top_idx, choice[..., None], axis=-1)[..., 0]
    return jnp.where(greedy, jnp.argmax(logits, axis=-1),
                     sampled).astype(jnp.int32)


@partial(jax.jit, static_argnames=("top_k", "approx_top_k"))
def _first_token(logits, key, temperature, top_p, greedy, *, top_k,
                 approx_top_k):
    """Sample one admission's first token from its suffix logits [V]."""
    return _serving_sample(key, logits[None, :], temperature[None],
                           top_p[None], greedy[None], top_k=top_k,
                           approx_top_k=approx_top_k)[0]


def _session_decode_body(params, config, s, table, row_params, *, Tp,
                         max_tokens, page_size, eos_token_id, pad_token_id,
                         temperature, top_p, greedy, lora_scale, top_k,
                         capture_logprobs, approx_top_k):
    """One decode step over the session carry — `sampler._decode_body`
    generalized to PER-ROW generation counts (resident rows sit at
    different depths) and table-routed cache writes. `row_params` is None
    for the rollout mode (static sampling params, row budget =
    `max_tokens`) or the serving mode's traced `[R]`
    (temperature, top_p, greedy, budget) tuple — a trace-time branch, so
    each mode compiles to exactly the program its pre-session driver ran."""
    (it, out, lp_out, caches, key_mask, done, cur_tok, n_gen, prompt_len,
     key) = s
    R = cur_tok.shape[0]
    rows = jnp.arange(R)
    slot = Tp + n_gen - 1                      # [R] cache slot of cur_tok
    key_mask = key_mask.at[rows, slot].set(True)
    position = prompt_len + n_gen - 1
    logits, caches = decode_step(
        params, config, cur_tok, position, slot, key_mask, caches,
        lora_scale=lora_scale, page_table=table, page_size=page_size,
    )
    if row_params is None:
        tok = _sample_token(jax.random.fold_in(key, it), logits, temperature,
                            top_p, greedy, top_k, approx_top_k)
        limit = max_tokens
    else:
        r_temp, r_topp, r_greedy, r_budget = row_params
        tok = _serving_sample(jax.random.fold_in(key, it), logits, r_temp,
                              r_topp, r_greedy, top_k=top_k,
                              approx_top_k=approx_top_k)
        limit = r_budget
    tok = jnp.where(done, pad_token_id, tok)
    live = ~done
    wpos = jnp.where(live, n_gen, max_tokens)  # done rows drop their write
    out = out.at[rows, wpos].set(tok, mode="drop")
    if capture_logprobs:
        lp = _token_logprob(logits, tok, temperature)
        lp_out = lp_out.at[rows, wpos].set(lp, mode="drop")
    cur_tok = jnp.where(live, tok, cur_tok)
    n_gen = n_gen + live.astype(jnp.int32)
    done = done | (tok == eos_token_id) | (n_gen >= limit)
    return (it + 1, out, lp_out, caches, key_mask, done, cur_tok, n_gen,
            prompt_len, key)


def _chunk_loop(params, config, state, table, row_params, statics):
    """Up to `sync_every` decode iterations; exits early once every
    resident row is done (the iteration counter then stops, so it counts
    true decode dispatches)."""
    statics = dict(statics)
    sync_every = statics.pop("sync_every")

    def cond(cs):
        c, s = cs
        return (c < sync_every) & ~jnp.all(s[5])

    def body(cs):
        c, s = cs
        return c + 1, _session_decode_body(params, config, s, table,
                                           row_params, **statics)

    _, state = jax.lax.while_loop(cond, body, (jnp.int32(0), state))
    return state


@partial(jax.jit, static_argnames=_CHUNK_STATIC)
def _decode_chunk(params, config, state, table, **statics):
    """Rollout-mode chunk: static sampling params (the pre-session
    scheduler's `_decode_chunk`, bit-identical program)."""
    return _chunk_loop(params, config, state, table, None, statics)


@partial(jax.jit, static_argnames=_CHUNK_STATIC)
def _serving_chunk(params, config, state, table, r_temp, r_topp, r_greedy,
                   r_budget, **statics):
    """Serving-mode chunk: per-request sampling params and token budgets
    ride as traced [R] arrays (the pre-session engine's `_engine_chunk`,
    bit-identical program — the params moved from carry slots to
    arguments, the values are the same)."""
    return _chunk_loop(params, config, state, table,
                       (r_temp, r_topp, r_greedy, r_budget), statics)


_SPEC_CHUNK_STATIC = _CHUNK_STATIC + ("spec_k", "spec_ngram")


def _spec_loop(params, config, state, table, prompt_rep, seed_rep, seed_len,
               statics):
    """Speculative twin of `_chunk_loop`: draft + verify per iteration
    over the 15-slot speculative carry, with the live block table routed
    into the verify forward. `prompt_rep` is the RESIDENT prompts [R, Tp]
    (it changes at admission, hence a traced argument); `seed_rep` /
    `seed_len`, when present, prepend the radix-matched cached
    continuation to each row's n-gram lookup window
    (`speculative._draft_fn`)."""
    from nanorlhf_tpu.sampler.speculative import _draft_fn, _verify_fn

    statics = dict(statics)
    sync_every = statics.pop("sync_every")
    spec_ngram = statics.pop("spec_ngram")
    ver_kw = dict(statics)
    ver_kw.pop("pad_token_id")
    spec_k = statics["spec_k"]
    Tp, pad = statics["Tp"], statics["pad_token_id"]

    def cond(cs):
        c, s = cs
        return (c < sync_every) & ~jnp.all(s[5])

    def body(cs):
        c, s = cs
        drafts = _draft_fn(prompt_rep, s, Tp=Tp, spec_k=spec_k,
                           spec_ngram=spec_ngram, pad_token_id=pad,
                           seed_rep=seed_rep, seed_len=seed_len)
        return c + 1, _verify_fn(params, config, s, drafts, page_table=table,
                                 pad_token_id=pad, **ver_kw)

    _, state = jax.lax.while_loop(cond, body, (jnp.int32(0), state))
    return state


@partial(jax.jit, static_argnames=_SPEC_CHUNK_STATIC)
def _spec_chunk(params, config, state, table, prompt_rep, **statics):
    """Spec chunk, own-buffer drafting only (spec without the radix
    cache — the pre-session scheduler's `_spec_chunk`)."""
    return _spec_loop(params, config, state, table, prompt_rep, None, None,
                      statics)


@partial(jax.jit, static_argnames=_SPEC_CHUNK_STATIC)
def _spec_chunk_seeded(params, config, state, table, prompt_rep, seed_rep,
                       seed_len, **statics):
    """Spec chunk with the radix-seeded lookup window (spec × prefix
    cache). Greedy acceptance is draft-independent, so seeding changes
    dispatch counts, never greedy output."""
    return _spec_loop(params, config, state, table, prompt_rep, seed_rep,
                      seed_len, statics)


@partial(jax.jit, static_argnames=("config", "page_size", "T_max",
                                   "temperature", "top_p", "greedy", "top_k",
                                   "approx_top_k", "lora_scale"))
def _admit_one(params, config, pids, pmask, caches, row_table, key, *,
               page_size, T_max, temperature, top_p, greedy, top_k,
               approx_top_k, lora_scale):
    """Single-row admission prefill: write the prompt KV through the row's
    freshly allocated block table into the SHARED pool, sample the first
    token. pids/pmask: [1, Tp]; row_table: [nb]. Returns
    (caches, tok0, lp0, prompt_len) with row-0 scalars."""
    logits, caches = prefill(
        params, config, pids, pmask.astype(bool), caches,
        lora_scale=lora_scale, page_table=row_table[None, :],
        page_size=page_size, logical_len=T_max,
    )
    tok0 = _sample_token(key, logits, temperature, top_p, greedy, top_k,
                         approx_top_k)
    lp0 = _token_logprob(logits, tok0, temperature)
    plen = jnp.sum(pmask.astype(jnp.int32), axis=1)
    return caches, tok0[0], lp0[0], plen[0]


@partial(jax.jit, static_argnames=("Tp", "max_tokens", "eos_token_id",
                                   "pad_token_id", "spec", "per_row"))
def _install_row(state, caches, r, tok0, lp0, pmask_row, plen, budget=None,
                 *, Tp, max_tokens, eos_token_id, pad_token_id, spec,
                 per_row=False):
    """Re-initialize resident row `r` of the carry for a freshly admitted
    prompt (out/lp rows cleared, key_mask reset to the prompt mask, counters
    to the post-prefill values). Works for both carry layouts — the first
    ten slots of the spec carry line up, and `spec` additionally resets the
    per-row accepted-draft counter. `per_row` (serving) folds the traced
    token `budget` into the initial done flag (a budget-1 request is done
    at its first token)."""
    s = list(state)
    T_mask = s[4].shape[1]
    s[3] = caches
    s[1] = s[1].at[r].set(
        jnp.full((max_tokens,), pad_token_id, jnp.int32).at[0].set(tok0))
    s[2] = s[2].at[r].set(jnp.zeros((max_tokens,), jnp.float32).at[0].set(lp0))
    s[4] = s[4].at[r].set(
        jnp.zeros((T_mask,), bool).at[:Tp].set(pmask_row.astype(bool)))
    if per_row:
        s[5] = s[5].at[r].set((tok0 == eos_token_id) | (budget <= 1))
    else:
        s[5] = s[5].at[r].set(tok0 == eos_token_id)
    s[6] = s[6].at[r].set(tok0)
    s[7] = s[7].at[r].set(jnp.int32(1))
    s[8] = s[8].at[r].set(plen)
    if spec:
        s[14] = s[14].at[r].set(jnp.int32(0))
    return tuple(s)


_release_jit = jax.jit(release_row)
_alloc_jit = jax.jit(alloc_row)


@partial(jax.jit, static_argnames=("temperature", "top_p", "greedy", "top_k",
                                   "approx_top_k"))
def _admit_sample(logits, key, *, temperature, top_p, greedy, top_k,
                  approx_top_k):
    """First token + logprob from a single row's admission logits [V] —
    the sampling half of `_admit_one`, split out so the radix path can
    feed it suffix-prefill logits instead of full-prefill logits."""
    tok0 = _sample_token(key, logits[None, :], temperature, top_p, greedy,
                         top_k, approx_top_k)
    return tok0[0], _token_logprob(logits[None, :], tok0, temperature)[0]


@partial(jax.jit, static_argnames=("config", "page_size", "lora_scale"))
def _prefill_chunk_fwd(params, config, chunk_ids, positions, fill, key_mask,
                       caches, row_table, *, page_size, lora_scale):
    """One KV-only prefill chunk: a `decode_verify` forward over a
    fixed-width slice of a long cold prompt, writing its KV through the
    row's block table and skipping the lm_head matmul entirely
    (`want_logits=False`) — only the FINAL chunk needs logits, and it
    runs through `suffix_logits` instead."""
    _, caches = decode_verify(
        params, config, chunk_ids, positions, fill, key_mask, caches,
        lora_scale=lora_scale, page_table=row_table[None, :],
        page_size=page_size, want_logits=False,
    )
    return caches


@dataclass
class _PendingPrefill:
    """A chunked admission in flight: the row's pages are claimed and its
    carry row is parked done=True; `next_slot` advances one chunk per
    session step until the final chunk installs the row."""
    row: int
    toks: np.ndarray              # [Tp] left-padded
    mask: np.ndarray              # [Tp] bool
    pad_count: int
    next_slot: int                # next absolute cache slot to prefill
    admit_key: jax.Array
    t_start: float
    kelems: Optional[tuple] = None        # radix key (radix mode)
    plan_hit: int = 0
    seed: Optional[np.ndarray] = None     # drafter seed (spec × radix)
    budget: Optional[int] = None          # per-row mode request params
    temperature: float = 1.0
    top_p: float = 1.0
    greedy: bool = False
    row_table: Optional[np.ndarray] = None  # non-radix: device row snapshot
    meta: dict = field(default_factory=dict)


class DecodeSession:
    """One resident decode batch with uniform per-row state.

    Owns the carry, the page table (radix-refcounted or device
    free-stack), the speculative draft seeds, the chunked-prefill
    backlog, and the latency-hub recording; exposes
    `admit` / `bootstrap` / `step` / `release` / `cancel_row` to the two
    drivers (rollout scheduler, serving engine). Modes:

      * `per_row=False` (rollout): static sampling params, every row
        shares `max_tokens`; spec decode composes (`spec_k > 0`), with
        the drafter seeded from the radix tree when `prefix_cache` is
        also attached.
      * `per_row=True` (serving): traced per-row temperature / top_p /
        greedy / budget; `capture_logprobs` is illegal (the logprob
        write needs a static temperature) — `sampler.compose_check`
        documents the matrix.

    The session NEVER resets an attached `prefix_cache` implicitly at
    step time — it resets it exactly once at construction (the rollout
    driver builds a session per generate call, giving the per-call reset
    the staleness note in serving/radix.py requires; the engine builds
    one session for its lifetime, keeping its tree warm)."""

    def __init__(self, params, config, *, rows, prompt_len, max_tokens,
                 page_size, eos_token_id, pad_token_id, key,
                 temperature=1.0, top_p=0.95, greedy=False, top_k=64,
                 approx_top_k=True, capture_logprobs=False, lora_scale=1.0,
                 per_row=False, spec_k=0, spec_ngram=3, prefix_cache=None,
                 prefill_chunk=0, sync_every=8, latency=None,
                 admit_key=None):
        if per_row and capture_logprobs:
            raise ValueError(
                "capture_logprobs is incompatible with per-row sampling "
                "params: the logprob write shares the chunk body's static "
                "temperature — see sampler.compose_check")
        if per_row and spec_k > 0 and not greedy:
            raise ValueError(
                "per-row spec decode requires the session's static "
                "greedy=True: the verify/accept rule compiles against "
                "static sampling params, so a spec serving engine admits "
                "greedy requests only — see sampler.compose_check")
        self.params = params
        self.config = config
        self.rows = int(rows)
        self.Tp = int(prompt_len)
        self.max_tokens = int(max_tokens)
        self.page_size = int(page_size)
        self.eos_token_id = int(eos_token_id)
        self.pad_token_id = int(pad_token_id)
        self.per_row = bool(per_row)
        self.spec = int(spec_k) > 0
        self.spec_k = int(spec_k)
        self.spec_ngram = int(spec_ngram)
        self.prefill_chunk = int(prefill_chunk)
        self.lora_scale = lora_scale
        self.capture_logprobs = bool(capture_logprobs)
        self._key = key
        self._admit_key = key if admit_key is None else admit_key
        self._hub = latency if (latency is not None
                                and getattr(latency, "enabled", False)) \
            else None

        self.T_max = self.Tp + self.max_tokens
        self.nb = blocks_per_row(self.T_max, self.page_size)

        self._radix = prefix_cache if (
            prefix_cache is not None
            and getattr(prefix_cache, "enabled", False)) else None
        if self._radix is not None:
            self.num_pages = (self.rows * self.nb
                              + self._radix.extra_pages(self.rows, self.nb))
            self._radix.reset(num_pages=self.num_pages,
                              page_size=self.page_size)
            self.table_np = np.full((self.rows, self.nb), self.num_pages,
                                    np.int32)
            self._pstate = None
        else:
            self.num_pages = self.rows * self.nb
            self.table_np = None
            # the free-stack allocator starts EMPTY: bootstrap() claims
            # the whole pool through the identity table, and churn begins
            # at the first release
            self._pstate = PageState(
                free=jnp.arange(self.num_pages, dtype=jnp.int32),
                top=jnp.asarray(0, jnp.int32),
                table=full_table(self.rows, self.nb))

        from nanorlhf_tpu.core.model import init_paged_kv_cache
        caches0 = init_paged_kv_cache(
            config, self.num_pages, self.page_size,
            params["embed_tokens"].dtype)
        R = self.rows
        # empty carry: every row starts done; admit() installs rows
        # through the same path mid-loop admissions use
        base = (jnp.int32(1),
                jnp.full((R, self.max_tokens), self.pad_token_id, jnp.int32),
                jnp.zeros((R, self.max_tokens), jnp.float32),
                caches0,
                jnp.zeros((R, self.T_max), bool),
                jnp.ones((R,), bool),
                jnp.zeros((R,), jnp.int32),
                jnp.ones((R,), jnp.int32),
                jnp.zeros((R,), jnp.int32),
                key)
        if self.spec:
            zero = jnp.int32(0)
            base = base + (zero, zero, zero, zero,
                           jnp.zeros((R,), jnp.int32))
        self.state = base

        self._sample_kw = dict(temperature=temperature, top_p=top_p,
                               greedy=greedy, top_k=top_k,
                               approx_top_k=approx_top_k)
        self._statics = dict(
            Tp=self.Tp, max_tokens=self.max_tokens, page_size=self.page_size,
            sync_every=int(sync_every), eos_token_id=self.eos_token_id,
            pad_token_id=self.pad_token_id, temperature=temperature,
            top_p=top_p, greedy=greedy, lora_scale=lora_scale, top_k=top_k,
            capture_logprobs=self.capture_logprobs,
            approx_top_k=approx_top_k,
        )
        if self.spec:
            self._statics.update(spec_k=self.spec_k,
                                 spec_ngram=self.spec_ngram)

        # per-row sampling params (serving mode): host-of-record arrays,
        # uploaded as traced chunk arguments — the values the pre-session
        # engine kept in carry slots 8–11
        self._temp_np = np.ones((R,), np.float32)
        self._topp_np = np.ones((R,), np.float32)
        self._greedy_np = np.zeros((R,), bool)
        self._budget_np = np.ones((R,), np.int32)

        # speculative draft state: resident prompts + radix-seeded windows
        self._prompt_res_np = np.full((R, self.Tp), self.pad_token_id,
                                      np.int32)
        self._prompt_rep = jnp.asarray(self._prompt_res_np)
        self.seed_window = (self.max_tokens + self.spec_ngram
                            if (self.spec and self._radix is not None) else 0)
        if self.seed_window:
            self._seed_np = np.full((R, self.seed_window), self.pad_token_id,
                                    np.int32)
            self._seed_len_np = np.zeros((R,), np.int32)
            self._seed_rep = jnp.asarray(self._seed_np)
            self._seed_len = jnp.asarray(self._seed_len_np)

        self._kelems: list = [None] * R       # radix keys of resident rows
        self._pending: list[_PendingPrefill] = []

        # dispatch accounting (module docstring): launches = model
        # forwards outside the decode/verify loop; decode iterations come
        # from the carry's own counter
        self.launches = 0
        self.dispatch_tokens = 0
        self.hit_tokens = 0
        self.chunked_admissions = 0
        self.backlog_peak = 0
        self._it_prev = 0

    # ------------------------------------------------------------- #
    # admission
    # ------------------------------------------------------------- #

    def bootstrap(self, prompt_ids, prompt_mask):
        """Batched initial admission for the non-radix rollout mode: one
        `_prefill_state` over the first `rows` prompts, pool fully
        claimed by the identity table — exactly the pre-session
        scheduler's initial batch, which is what keeps its greedy streams
        (and TTFT semantics) bit-identical. Never chunked: chunked
        prefill protects RESIDENT rows' latency, and there are none yet."""
        assert self._radix is None, "radix mode admits rows individually"
        R = self.rows
        t0 = time.perf_counter()
        base = _prefill_state_jit(
            self.params, self.config, prompt_ids[:R], prompt_mask[:R],
            self._key, max_tokens=self.max_tokens,
            eos_token_id=self.eos_token_id, pad_token_id=self.pad_token_id,
            lora_scale=self.lora_scale,
            capture_logprobs=self.capture_logprobs,
            page_size=self.page_size, **self._sample_kw)
        (_one, out0, lp0, caches, key_mask0, done0, tok0, plen0, _key) = base
        self.launches += 1
        self.dispatch_tokens += R * self.Tp
        if self._hub is not None:
            # every initial-batch row's first token exists once this
            # prefill lands: one TTFT observation per admitted request
            jax.block_until_ready(tok0)
            ttft0 = time.perf_counter() - t0
            for _ in range(R):
                self._hub.record("latency/ttft_s", ttft0)
        if self.spec:
            from nanorlhf_tpu.sampler.speculative import _spec_state
            self.state = _spec_state(base)
        else:
            self.state = (jnp.int32(1), out0, lp0, caches, key_mask0, done0,
                          tok0, jnp.ones((R,), jnp.int32), plen0, self._key)
        self._prompt_res_np[:] = np.asarray(prompt_ids[:R])
        self._prompt_rep = jnp.asarray(self._prompt_res_np)
        self._it_prev = int(self.state[0]) - 1

    def admit(self, r: int, toks_np, mask_np, admit_index: int, *,
              budget=None, temperature=None, top_p=None, greedy=None,
              t_start=None):
        """Admit one prompt into resident row `r`.

        `admit_index` keys the admission PRNG fold
        (`fold_in(admit_key, _ADMIT_BASE + admit_index)`) — the rollout
        driver passes the queue index, the engine the request id.
        Rollout mode ignores the per-request kwargs (sampling params are
        session statics); serving mode requires `budget`.

        Radix mode may raise RuntimeError (pool exhausted even after
        eviction) BEFORE any row state changes — the engine sheds on it.

        Returns the first token as a host int in per-row mode (the
        engine streams it immediately), None in rollout mode (no forced
        device sync), and None for a chunked admission in either mode
        (the first token lands when the final chunk installs the row —
        drivers must treat `is_pending(r)` rows as not-yet-done)."""
        toks_np = np.asarray(toks_np, np.int32)
        mask_np = np.asarray(mask_np, bool)
        t0 = time.perf_counter() if t_start is None else t_start
        pad_count = int(self.Tp - mask_np.sum())
        a_key = jax.random.fold_in(self._admit_key, _ADMIT_BASE
                                   + int(admit_index))

        kelems = plan = seed = None
        if self._radix is not None:
            from nanorlhf_tpu.serving.radix import copy_page, prompt_key
            kelems = prompt_key(toks_np, mask_np)
            # may raise RuntimeError — before any state mutation
            plan = self._radix.plan(kelems, pad_count=pad_count,
                                    n_blocks=self.nb, prompt_len=self.Tp)
            if self.seed_window:
                seed = self._radix.matched_continuation(
                    kelems, self.seed_window)
            self.table_np[r] = plan.row_pages
            if plan.cow_src is not None:
                s = list(self.state)
                s[3] = copy_page(s[3], plan.cow_src, plan.cow_dst)
                self.state = tuple(s)
            # per-row mode runs the unified suffix forward even on a cold
            # miss (start = pad_count, pad KV never written); rollout mode
            # keeps the cold full-row prefill so its streams stay
            # bit-identical to the uncached scheduler
            if plan.m > 0:
                start = plan.m
            elif self.per_row:
                start = pad_count
            else:
                start = None
        else:
            self._pstate, ok = _alloc_jit(self._pstate, r, self.nb)
            assert bool(ok), \
                "allocator underflow: full-budget rows recycle uniformly"
            start = None

        row_table_np = None
        if self._radix is None:
            row_table_np = self._pstate.table[r]

        pend = _PendingPrefill(
            row=r, toks=toks_np, mask=mask_np, pad_count=pad_count,
            next_slot=0, admit_key=a_key, t_start=t0, kelems=kelems,
            plan_hit=(plan.hit_tokens if plan is not None else 0),
            seed=seed, budget=budget,
            temperature=(1.0 if temperature is None else float(temperature)),
            top_p=(1.0 if top_p is None else float(top_p)),
            greedy=bool(greedy), row_table=row_table_np)

        if start is None:
            # cold full-row prefill (rollout mode): identical to the
            # uncached path, and — when chunking is on — chunked from the
            # first REAL token through the same KV-only forwards
            start_abs = pad_count
            full_cold = True
        else:
            start_abs = start
            full_cold = False
        s_real = self.Tp - start_abs
        C = self.prefill_chunk
        if C > 0 and s_real > C:
            pend.next_slot = start_abs
            pend.meta["full_cold"] = full_cold
            self._pending.append(pend)
            self.backlog_peak = max(self.backlog_peak,
                                    self._backlog_tokens())
            self.chunked_admissions += 1
            return None
        return self._admit_now(pend, full_cold=full_cold,
                               start_abs=start_abs)

    def _admit_now(self, pend: _PendingPrefill, *, full_cold: bool,
                   start_abs: int):
        """Unchunked (or final-chunk-only) admission forward + install."""
        from nanorlhf_tpu.serving.radix import bucket_len, suffix_logits
        p = pend
        caches = self.state[3]
        row_table = (jnp.asarray(self.table_np[p.row])
                     if self._radix is not None else p.row_table)
        if full_cold and not self.per_row and self.prefill_chunk == 0:
            # the pre-session cold path: one full-row prefill (pads
            # included) — kept verbatim so rollout parity pins hold
            caches, t0, l0, plen = _admit_one(
                self.params, self.config, jnp.asarray(p.toks[None, :]),
                jnp.asarray(p.mask[None, :]), caches, row_table,
                p.admit_key, page_size=self.page_size, T_max=self.T_max,
                lora_scale=self.lora_scale, **self._sample_kw)
            self.dispatch_tokens += self.Tp
        else:
            s_real = self.Tp - start_abs
            Sb = bucket_len(s_real, self.T_max - start_abs)
            suffix = np.zeros((1, Sb), np.int32)
            suffix[0, :s_real] = p.toks[start_abs:]
            pos = ((start_abs - p.pad_count)
                   + np.arange(Sb, dtype=np.int32)[None])
            km = np.zeros((1, self.T_max), bool)
            km[0, p.pad_count:start_abs] = True
            logits, caches = suffix_logits(
                self.params, self.config, jnp.asarray(suffix),
                jnp.asarray(pos), jnp.asarray([start_abs], jnp.int32),
                jnp.int32(s_real - 1), jnp.asarray(km), caches,
                row_table, page_size=self.page_size,
                lora_scale=self.lora_scale)
            self.dispatch_tokens += Sb
            self.hit_tokens += p.plan_hit
            if self.per_row:
                t0 = _first_token(
                    logits, p.admit_key, jnp.float32(p.temperature),
                    jnp.float32(p.top_p), jnp.asarray(p.greedy),
                    top_k=self._sample_kw["top_k"],
                    approx_top_k=self._sample_kw["approx_top_k"])
                l0 = jnp.float32(0.0)
            else:
                t0, l0 = _admit_sample(logits, p.admit_key,
                                       **self._sample_kw)
            plen = jnp.int32(int(p.mask.sum()))
        self.launches += 1
        return self._install(p, caches, t0, l0, plen)

    def _install(self, p: _PendingPrefill, caches, t0, l0, plen):
        r = p.row
        if self._radix is not None:
            self._radix.insert(p.kelems, self.table_np[r], self.Tp)
            self._kelems[r] = p.kelems
        if self.per_row:
            self._temp_np[r] = p.temperature
            self._topp_np[r] = p.top_p
            self._greedy_np[r] = p.greedy
            self._budget_np[r] = int(p.budget)
        if self.spec:
            self._prompt_res_np[r] = p.toks
            self._prompt_rep = jnp.asarray(self._prompt_res_np)
            if self.seed_window:
                W = self.seed_window
                self._seed_np[r] = self.pad_token_id
                n = 0 if p.seed is None else min(len(p.seed), W)
                if n:
                    self._seed_np[r, W - n:] = p.seed[:n]
                self._seed_len_np[r] = n
                self._seed_rep = jnp.asarray(self._seed_np)
                self._seed_len = jnp.asarray(self._seed_len_np)
        if self._hub is not None or self.per_row:
            # t0 is the admission forward's sampled first token: blocking
            # on it gives this request's true TTFT (and the engine needs
            # the host int to stream it)
            jax.block_until_ready(t0)
        if self._hub is not None:
            self._hub.record("latency/ttft_s",
                             time.perf_counter() - p.t_start)
        self.state = _install_row(
            self.state, caches, r, t0, l0, jnp.asarray(p.mask), plen,
            (jnp.int32(int(p.budget)) if self.per_row else None),
            Tp=self.Tp, max_tokens=self.max_tokens,
            eos_token_id=self.eos_token_id, pad_token_id=self.pad_token_id,
            spec=self.spec, per_row=self.per_row)
        return int(t0) if self.per_row else None

    # ------------------------------------------------------------- #
    # stepping
    # ------------------------------------------------------------- #

    def _prefill_tick(self):
        """Advance the OLDEST pending chunked admission by exactly one
        KV-only chunk forward; the final chunk (<= prefill_chunk real
        tokens) runs the normal suffix+install path, with the SAME
        admission PRNG fold as an unchunked admission — chunked-on/off
        greedy streams are bit-identical (sampled rows decode at later
        global folds, so they match in distribution only)."""
        p = self._pending[0]
        remaining = self.Tp - p.next_slot
        C = self.prefill_chunk
        if remaining <= C:
            self._pending.pop(0)
            tok0 = self._admit_now(p, full_cold=p.meta.get("full_cold",
                                                           False),
                                   start_abs=p.next_slot)
            return (p.row, tok0)
        chunk = p.toks[p.next_slot:p.next_slot + C][None, :]
        pos = ((p.next_slot - p.pad_count)
               + np.arange(C, dtype=np.int32)[None])
        km = np.zeros((1, self.T_max), bool)
        km[0, p.pad_count:p.next_slot] = True
        row_table = (jnp.asarray(self.table_np[p.row])
                     if self._radix is not None else p.row_table)
        s = list(self.state)
        s[3] = _prefill_chunk_fwd(
            self.params, self.config, jnp.asarray(chunk), jnp.asarray(pos),
            jnp.asarray([p.next_slot], jnp.int32), jnp.asarray(km), s[3],
            row_table, page_size=self.page_size, lora_scale=self.lora_scale)
        self.state = tuple(s)
        p.next_slot += C
        self.launches += 1
        self.dispatch_tokens += C
        return None

    def step(self):
        """One scheduler beat: at most one pending-prefill chunk, then
        one decode (or draft+verify) chunk of up to `sync_every`
        iterations. Returns (done_h, installed) — the host done flags
        and the (row, first_token_or_None) of an admission whose final
        chunk landed this beat, if any."""
        installed = None
        if self._pending:
            installed = self._prefill_tick()
        t0 = time.perf_counter()
        table_dev = (jnp.asarray(self.table_np) if self._radix is not None
                     else self._pstate.table)
        if self.spec:
            if self.seed_window:
                self.state = _spec_chunk_seeded(
                    self.params, self.config, self.state, table_dev,
                    self._prompt_rep, self._seed_rep, self._seed_len,
                    **self._statics)
            else:
                self.state = _spec_chunk(
                    self.params, self.config, self.state, table_dev,
                    self._prompt_rep, **self._statics)
        elif self.per_row:
            self.state = _serving_chunk(
                self.params, self.config, self.state, table_dev,
                jnp.asarray(self._temp_np), jnp.asarray(self._topp_np),
                jnp.asarray(self._greedy_np), jnp.asarray(self._budget_np),
                **self._statics)
        else:
            self.state = _decode_chunk(
                self.params, self.config, self.state, table_dev,
                **self._statics)
        done_h = np.asarray(self.state[5])
        it_now = int(self.state[0]) - 1
        if self._hub is not None:
            # done_h forced the device sync, so the chunk's wall time is
            # fully realised here; one mean inter-token gap per sync
            # chunk. The serving driver only records when the counter
            # advanced (its loop also spins on admission-only beats).
            if not self.per_row:
                self._hub.record("latency/intertoken_s",
                                 (time.perf_counter() - t0)
                                 / max(1, it_now - self._it_prev))
            elif it_now > self._it_prev:
                self._hub.record("latency/intertoken_s",
                                 (time.perf_counter() - t0)
                                 / (it_now - self._it_prev))
        self._it_prev = it_now
        return done_h, installed

    # ------------------------------------------------------------- #
    # release / introspection
    # ------------------------------------------------------------- #

    def iterations(self) -> int:
        """Decode/verify iterations so far (the carry's own counter)."""
        return int(self.state[0]) - 1

    def dispatch_events(self) -> int:
        """Total model-forward launches: admission/chunk forwards plus
        decode (or verify) iterations — the spec+radix A/B's unit."""
        return self.launches + self.iterations()

    def is_pending(self, r: int) -> bool:
        return any(p.row == r for p in self._pending)

    def pending_rows(self):
        return {p.row for p in self._pending}

    def has_pending(self) -> bool:
        return bool(self._pending)

    def _backlog_tokens(self) -> int:
        return int(sum(self.Tp - p.next_slot for p in self._pending))

    def release(self, r: int, gen_tokens=None) -> int:
        """Release row `r`'s pages (radix: drop the ROW's refs — tree
        refs survive as cached prefix KV; free-stack: push the row's
        pages). When the drafter seed is active and `gen_tokens` (the
        row's emitted tokens, EOS included) is given, the generated
        continuation is appended to the radix tree as TEXT-ONLY nodes
        (`RadixCache.extend_text`) so the next overlapping admission can
        seed its n-gram window from it. Returns pages freed."""
        if self._radix is not None:
            if (self.seed_window and gen_tokens is not None
                    and self._kelems[r] is not None):
                ext = self._kelems[r] + tuple(
                    int(t) * 2 + 1 for t in np.asarray(gen_tokens).ravel())
                self._radix.extend_text(ext)
            freed = self._radix.release(self.table_np[r])
            self.table_np[r] = self.num_pages
            self._kelems[r] = None
            return freed
        self._pstate, m = _release_jit(self._pstate, r)
        return int(m)

    def cancel_row(self, r: int) -> None:
        """Serving-side reap: drop any pending chunked admission for the
        row, force its done flag (the jitted chunk then skips it), and
        free its pages — mirrors the completion path exactly so a
        disconnect can never leak what a completion would have freed."""
        self._pending = [p for p in self._pending if p.row != r]
        s = list(self.state)
        s[5] = s[5].at[r].set(True)
        self.state = tuple(s)
        self.release(r)

    def utilization(self) -> float:
        """Allocated / total pages right now."""
        if self._radix is not None:
            return 1.0 - self._radix.pool.free_count / self.num_pages
        return 1.0 - float(np.asarray(self._pstate.top)) / self.num_pages

    def shared_pages(self) -> int:
        return (self._radix.pool.shared_count()
                if self._radix is not None else 0)

    def status(self) -> dict:
        """JSON-able /statusz `session` section: resident rows, the
        chunked-prefill backlog, and per-row feature flags."""
        done_h = np.asarray(self.state[5])
        pend = self.pending_rows()
        return {
            "rows": self.rows,
            "live_rows": int((~done_h).sum()),
            "mode": "serving" if self.per_row else "rollout",
            "features": {
                "spec_k": self.spec_k,
                "prefix_cache": self._radix is not None,
                "prefill_chunk": self.prefill_chunk,
                "per_row_sampling": self.per_row,
                "drafter_seed_window": self.seed_window,
            },
            "pending_prefill": {
                "rows": sorted(pend),
                "backlog_tokens": self._backlog_tokens(),
            },
            "row_flags": [
                {"live": bool(not done_h[r]),
                 "chunk_pending": r in pend,
                 "seeded_draft_len": (int(self._seed_len_np[r])
                                      if self.seed_window else 0)}
                for r in range(self.rows)
            ],
            "counters": {
                "launches": self.launches,
                "decode_iterations": self.iterations(),
                "dispatch_events": self.dispatch_events(),
                "dispatch_tokens": self.dispatch_tokens,
                "prefix_hit_tokens": self.hit_tokens,
                "chunked_admissions": self.chunked_admissions,
                "prefill_backlog_peak": self.backlog_peak,
            },
        }
